#!/usr/bin/env python
"""BASELINE config 2: Llama-3 70B TPxPP across a 64-way mesh.

Mesh is size-parametric (--tp x --pp x data fills the device count); on
fake devices this validates the GPipe schedule + TP compose at depth.
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))
from common import emit, parse_args, timed  # noqa: E402


def main():
    args = parse_args("Llama-3 70B TPxPP", tp=4, pp=2, microbatches=4,
                      virtual_stages=1)
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from butterfly_tpu.core.config import MeshConfig, llama3_70b, tiny
    from butterfly_tpu.core.mesh import make_mesh
    from butterfly_tpu.models.common import Model, init_cache
    from butterfly_tpu.parallel.partition import shard_cache, shard_params
    from butterfly_tpu.parallel.pipeline import pipeline_forward

    n = args.tp * args.pp
    V = args.virtual_stages
    # tiny depth fixed at 4*pp (divisible by pp*V for V in {1,2,4}) so
    # an A/B over --virtual-stages compares the SCHEDULE, not model depth
    cfg = tiny("llama", num_layers=4 * args.pp, dtype="float32",
               param_dtype="float32") if args.tiny else llama3_70b()
    mesh = make_mesh(MeshConfig(stage=args.pp, tensor=args.tp),
                     jax.devices()[:n])
    model = Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    if V > 1:
        # interleaved 1F1B-style schedule: one-time layer permutation,
        # donated so the full stack is never transiently duplicated
        from functools import partial
        from butterfly_tpu.parallel.pipeline import interleave_layers
        perm = jax.jit(partial(interleave_layers,
                               num_layers=cfg.num_layers, S=args.pp, V=V),
                       donate_argnums=(0,))
        params = dict(params)
        params["layers"] = perm(params["layers"])
    params = shard_params(params, cfg, mesh)
    cache = shard_cache(
        init_cache(cfg, args.batch, args.prompt_len + args.max_new),
        cfg, mesh)
    tokens = jax.device_put(
        jnp.asarray(np.random.RandomState(0).randint(
            0, cfg.vocab_size, (args.batch, args.prompt_len))),
        NamedSharding(mesh, P()))

    def step(params, tokens, cache):
        return pipeline_forward(params, cfg, tokens, cache, mesh,
                                num_microbatches=args.microbatches,
                                virtual_stages=V)

    with jax.set_mesh(mesh):
        (_, cache), dt_prefill = timed(jax.jit(step), params, tokens, cache)
        one = tokens[:, :1]
        (_, cache), dt_decode = timed(jax.jit(step), params, one, cache,
                                      warmup=2, iters=8)

    toks = args.batch / dt_decode
    emit("llama70b_tp_pp_decode_tokens_per_sec", toks, "tokens/sec",
         config="baseline_config_2", tp=args.tp, pp=args.pp,
         virtual_stages=V,
         tokens_per_sec_per_chip=round(toks / n, 2),
         ttft_s=round(dt_prefill, 4))


if __name__ == "__main__":
    main()
