#!/usr/bin/env python
"""BASELINE config 4: continuous batching + paged KV (serving throughput).

Submits a staggered stream of requests through the scheduler and reports
sustained tokens/sec plus TTFT percentiles — the serving metrics of
record (BASELINE.json north_star).
"""
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))
from common import emit, parse_args  # noqa: E402


def main():
    args = parse_args("continuous batching + paged KV", batch=8,
                      prompt_len=64, max_new=64, requests=32)
    import jax
    import numpy as np
    from butterfly_tpu.core.config import RuntimeConfig, llama3_8b, tiny
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.models.common import Model
    from butterfly_tpu.sched.scheduler import Scheduler

    cfg = tiny("llama", dtype="float32", param_dtype="float32") \
        if args.tiny or jax.default_backend() == "cpu" else llama3_8b()
    rt = RuntimeConfig(max_batch_size=args.batch,
                       max_seq_len=args.prompt_len + args.max_new,
                       page_size=16)
    model = Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    sched = Scheduler(ServingEngine(model, params, rt))

    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size, args.prompt_len).tolist()
               for _ in range(args.requests)]
    # warmup: compile prefill + decode programs
    sched.submit(prompts[0], max_new_tokens=2)
    sched.run_until_done()

    t0 = time.perf_counter()
    for p in prompts:
        sched.submit(p, max_new_tokens=args.max_new)
    sched.run_until_done(max_ticks=10 ** 6)
    dt = time.perf_counter() - t0

    m = sched.metrics()
    total = args.requests * args.max_new
    emit("serving_tokens_per_sec", total / dt, "tokens/sec",
         config="baseline_config_4", requests=args.requests,
         slots=args.batch,
         ttft_p50_s=round(m.get("ttft_p50", 0), 4),
         ttft_p95_s=round(m.get("ttft_p95", 0), 4),
         preemptions=int(m["preemptions_total"]))

    # Variant: shared system prompt + prefix caching (cache/prefix.py).
    # Every request reuses the same long prefix; prefill work collapses
    # to the per-request tail, which is where TTFT is won. Needs a
    # prefix spanning at least one full page to measure anything.
    if args.prompt_len - 8 < rt.page_size:
        return
    sched2 = Scheduler(ServingEngine(model, params,
                                     rt.replace(prefix_caching=True)))
    shared = rng.randint(1, cfg.vocab_size, args.prompt_len - 8).tolist()
    tails = [rng.randint(1, cfg.vocab_size, 8).tolist()
             for _ in range(args.requests)]
    sched2.submit(shared + tails[0], max_new_tokens=2)  # warm compile+cache
    sched2.run_until_done()
    t0 = time.perf_counter()
    for tail in tails:
        sched2.submit(shared + tail, max_new_tokens=args.max_new)
    sched2.run_until_done(max_ticks=10 ** 6)
    dt2 = time.perf_counter() - t0
    m2 = sched2.metrics()
    emit("serving_tokens_per_sec_prefix_cached", total / dt2, "tokens/sec",
         config="baseline_config_4_prefix_caching",
         ttft_p50_s=round(m2.get("ttft_p50", 0), 4),
         ttft_p95_s=round(m2.get("ttft_p95", 0), 4),
         prefix_hit_rate=round(m2["prefix_cache_hit_tokens"]
                               / max(1, m2["prefix_cache_lookup_tokens"]), 4))


if __name__ == "__main__":
    main()
