#!/usr/bin/env python
"""BASELINE config 1: Llama-3 8B tensor-parallel TP=8 on one host.

On fake devices this validates the TP mesh/schedule end-to-end (compile +
run + logit-parity-grade numerics); on a real v5e-8 it measures
tokens/sec/chip.
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))
from common import emit, parse_args, timed  # noqa: E402


def main():
    args = parse_args("Llama-3 8B TP=8", tp=8)
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from butterfly_tpu.core.config import MeshConfig, llama3_8b, tiny
    from butterfly_tpu.core.mesh import make_mesh
    from butterfly_tpu.models.common import Model, forward, init_cache
    from butterfly_tpu.parallel.partition import shard_cache, shard_params

    cfg = tiny("llama", dtype="float32", param_dtype="float32") if args.tiny \
        else llama3_8b()
    mesh = make_mesh(MeshConfig(tensor=args.tp),
                     jax.devices()[:args.tp])
    model = Model(cfg)
    params = shard_params(model.init(jax.random.PRNGKey(0)), cfg, mesh)
    cache = shard_cache(
        init_cache(cfg, args.batch, args.prompt_len + args.max_new),
        cfg, mesh)
    tokens = jax.device_put(
        jnp.asarray(np.random.RandomState(0).randint(
            0, cfg.vocab_size, (args.batch, args.prompt_len))),
        NamedSharding(mesh, P()))

    def step(params, tokens, cache):
        return forward(params, cfg, tokens, cache)

    with jax.set_mesh(mesh):
        jit_step = jax.jit(step)
        (_, cache), dt_prefill = timed(jit_step, params, tokens, cache)
        one = tokens[:, :1]
        (_, cache), dt_decode = timed(jax.jit(step), params, one, cache,
                                      warmup=2, iters=8)

    toks = args.batch / dt_decode
    emit("llama8b_tp_decode_tokens_per_sec", toks, "tokens/sec",
         config="baseline_config_1", tp=args.tp,
         tokens_per_sec_per_chip=round(toks / args.tp, 2),
         prefill_s=round(dt_prefill, 4),
         ttft_s=round(dt_prefill, 4))


if __name__ == "__main__":
    main()
