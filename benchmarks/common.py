"""Shared harness for the five BASELINE benchmark scripts."""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# runnable from anywhere: the package lives at the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(desc: str, **extra):
    p = argparse.ArgumentParser(description=desc)
    p.add_argument("--tiny", action="store_true",
                   help="shrink the model for CI / fake-device runs")
    p.add_argument("--fake-devices", type=int, default=0,
                   help="run on N fake CPU devices (mesh-shape validation)")
    p.add_argument("--batch", type=int, default=extra.pop("batch", 8))
    p.add_argument("--prompt-len", type=int,
                   default=extra.pop("prompt_len", 128))
    p.add_argument("--max-new", type=int, default=extra.pop("max_new", 128))
    for k, v in extra.items():
        p.add_argument(f"--{k.replace('_', '-')}", type=type(v), default=v)
    args = p.parse_args()
    if args.fake_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.fake_devices}"
        ).strip()
        import jax
        jax.config.update("jax_platforms", "cpu")
    return args


def emit(metric: str, value: float, unit: str, **kw) -> None:
    print(json.dumps({"metric": metric, "value": round(value, 2),
                      "unit": unit, **kw}))


def timed(fn, *args, warmup: int = 1, iters: int = 1):
    for _ in range(warmup):
        fn(*args)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    import jax
    jax.block_until_ready(out)
    return out, (time.perf_counter() - t0) / iters
