#!/usr/bin/env python
"""BASELINE config 3: Mixtral-8x7B MoE expert-parallel over ICI."""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))
from common import emit, parse_args, timed  # noqa: E402


def main():
    args = parse_args("Mixtral-8x7B EP", ep=4)
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from butterfly_tpu.core.config import MeshConfig, mixtral_8x7b, tiny
    from butterfly_tpu.core.mesh import make_mesh
    from butterfly_tpu.models.common import Model, forward, init_cache
    from butterfly_tpu.parallel.partition import shard_cache, shard_params

    cfg = (tiny("mixtral", dtype="float32", param_dtype="float32")
           if args.tiny else mixtral_8x7b()).replace(moe_impl="ep")
    mesh = make_mesh(MeshConfig(expert=args.ep), jax.devices()[:args.ep])
    model = Model(cfg)
    params = shard_params(model.init(jax.random.PRNGKey(0)), cfg, mesh)
    cache = shard_cache(
        init_cache(cfg, args.batch, args.prompt_len + args.max_new),
        cfg, mesh)
    tokens = jax.device_put(
        jnp.asarray(np.random.RandomState(0).randint(
            0, cfg.vocab_size, (args.batch, args.prompt_len))),
        NamedSharding(mesh, P()))

    def step(params, tokens, cache):
        return forward(params, cfg, tokens, cache)

    with jax.set_mesh(mesh):
        (_, cache), dt_prefill = timed(jax.jit(step), params, tokens, cache)
        one = tokens[:, :1]
        (_, cache), dt_decode = timed(jax.jit(step), params, one, cache,
                                      warmup=2, iters=8)

    toks = args.batch / dt_decode
    emit("mixtral_ep_decode_tokens_per_sec", toks, "tokens/sec",
         config="baseline_config_3", ep=args.ep,
         tokens_per_sec_per_chip=round(toks / args.ep, 2),
         ttft_s=round(dt_prefill, 4))


if __name__ == "__main__":
    main()
