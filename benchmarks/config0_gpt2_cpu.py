#!/usr/bin/env python
"""BASELINE config 0: GPT-2 124M single-host greedy decode (CPU reference)."""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))
from common import emit, parse_args  # noqa: E402


def main():
    args = parse_args("GPT-2 124M greedy decode", batch=4, prompt_len=64,
                      max_new=64)
    import jax
    from butterfly_tpu.core.config import gpt2_124m, tiny
    from butterfly_tpu.models.common import Model
    from butterfly_tpu.obs.benchmark import run_decode_benchmark

    cfg = tiny("gpt2") if args.tiny else gpt2_124m()
    if jax.default_backend() == "cpu":
        cfg = cfg.replace(dtype="float32")
    model = Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    stats = run_decode_benchmark(model, params, batch=args.batch,
                                 prompt_len=args.prompt_len,
                                 max_new=args.max_new)
    emit("gpt2_decode_tokens_per_sec", stats["tokens_per_sec"],
         "tokens/sec", config="baseline_config_0",
         tokens_per_sec_per_chip=round(stats["tokens_per_sec_per_chip"], 2))


if __name__ == "__main__":
    main()
