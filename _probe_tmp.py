import time, json
import jax, jax.numpy as jnp

B, D, F, L = 64, 2048, 19200, 16
key = jax.random.PRNGKey(1)
Wb = jax.random.normal(key, (L, D, F), jnp.bfloat16)
W8 = (jax.random.normal(key, (L, D, F)) * 50).astype(jnp.int8)
s  = jnp.ones((L, 1, F), jnp.bfloat16) * 0.02
xx = jax.random.normal(jax.random.PRNGKey(2), (B, D), jnp.bfloat16)

def timed(f, *a):
    f(*a); t0=time.perf_counter(); float(f(*a)); return time.perf_counter()-t0

def make(fn, n=16):
    @jax.jit
    def g(xx, *w):
        def outer(c, _):
            def body(c2, wi):
                return fn(c2, wi), None
            c, _ = jax.lax.scan(body, c, w if len(w)>1 else w[0])
            return c, None
        c, _ = jax.lax.scan(outer, xx, None, length=n)
        return c.astype(jnp.float32).sum()
    return g

bf = make(lambda c, wi: (c @ wi)[:, :D] + c)
q8 = make(lambda c, wi: ((c @ wi[0].astype(jnp.bfloat16)) * wi[1])[:, :D] + c)

t_bf = timed(bf, xx, Wb)
t_q8 = timed(q8, xx, (W8, s))
print(json.dumps({"bf16_s": round(t_bf,3), "int8_s": round(t_q8,3),
                  "marginal_speedup": round((t_bf-0.08)/(t_q8-0.08), 2)}), flush=True)
