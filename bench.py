#!/usr/bin/env python
"""Driver benchmark: one JSON line with the headline metric.

Metric: steady-state decode throughput (tokens/sec/chip) for a ~1B-class
Llama-3-style model in bfloat16 on the available chip(s) — the largest of
the BASELINE.json model family that fits a single v5e chip's HBM with
random weights. No published reference numbers exist (BASELINE.md: the
reference is an unimplemented scaffold), so `vs_baseline` is the ratio to
the first recorded run of this same benchmark (bench_baseline.json,
committed after round 1) — i.e. it tracks our own improvement.
"""
import json
import sys
from pathlib import Path

BASELINE_FILE = Path(__file__).parent / "bench_baseline.json"


def main() -> int:
    import jax
    from butterfly_tpu.core.config import ModelConfig
    from butterfly_tpu.models.common import Model
    from butterfly_tpu.obs.benchmark import run_decode_benchmark
    from butterfly_tpu.quant.int8 import quantize_int8

    on_tpu = jax.devices()[0].platform != "cpu"

    if on_tpu:
        # ~1.2B params: fits one v5e chip (16 GiB HBM) in bf16 with cache.
        cfg = ModelConfig(arch="llama", vocab_size=32000, hidden_size=2048,
                          num_layers=16, num_heads=16, num_kv_heads=8,
                          head_dim=128, intermediate_size=5632,
                          max_seq_len=2048)
        # batch 128 is the continuous-batching serving operating point
        # where the decode loop peaks on v5e (~73% HBM roofline with the
        # deferred-write decode path + int8 weights); 32 was ~0.27.
        batch, prompt_len, max_new = 128, 128, 128
    else:
        from butterfly_tpu.core.config import tiny
        cfg = tiny("llama", dtype="float32", param_dtype="float32")
        batch, prompt_len, max_new = 4, 32, 32

    model = Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    # int8 weight-only quant: the serving default for the bandwidth-bound
    # decode loop (CLI --quant int8); halves the weight bytes per step.
    params = quantize_int8(params, cfg)
    # int8 KV cache + write-combined decode window (CLI --kv-quant int8):
    # halves the cache bytes — the dominant decode-loop term at this
    # batch — and amortizes the whole-pool copy each in-loop cache
    # update costs on TPU (models/common.py window docs).
    kv_quant = "int8" if on_tpu else "none"
    stats = run_decode_benchmark(model, params, batch=batch,
                                 prompt_len=prompt_len, max_new=max_new,
                                 kv_quant=kv_quant)
    toks_per_sec_chip = stats["tokens_per_sec_per_chip"]

    vs = 1.0
    if BASELINE_FILE.exists():
        base = json.loads(BASELINE_FILE.read_text())
        key = "tpu" if on_tpu else "cpu"
        if base.get(key):
            vs = toks_per_sec_chip / base[key]

    print(json.dumps({
        "metric": "decode_tokens_per_sec_per_chip",
        "value": round(toks_per_sec_chip, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs, 4),
        "quant": "int8",
        "kv_quant": kv_quant,
        "decode_isolated_tokens_per_sec_per_chip":
            round(stats["decode_tokens_per_sec_per_chip"], 2),
        "hbm_util": round(stats["hbm_util"], 4),
        "mfu": round(stats["mfu"], 4),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
