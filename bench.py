#!/usr/bin/env python
"""Driver benchmark: one JSON line with the headline metric.

Headline: steady-state decode throughput (tokens/sec/chip) for the
BASELINE.json configs[1] model of record — Llama-3-8B geometry — in int8
(weights + KV cache) on the available chip(s). Rounds 1-4 benchmarked a
1.2B proxy; r5 moved to the 8B config of record, so `vs_baseline` is the
ratio to the first 8B run (bench_baseline.json key "tpu_8b" — the
reference is an unimplemented scaffold with no published numbers,
BASELINE.md).

NB (VERDICT r5 flaw 2): `vs_baseline` carries NO cross-round signal
across the r5 headline-model switch — r1-r4 ratios were against the
1.2B proxy, r5+ against the 8B run, so the series is discontinuous and
~1.0 by construction right after a re-baseline. The trend metrics of
record are the physical ones: `hbm_util` / `mfu` (roofline fractions,
model-switch-invariant) and the mixed-workload serving fields
(`mixed_serving_tokens_per_sec`, `mixed_ttft_*`, `mixed_itl_req_mean_*`,
`mixed_serving_preemptions`, the operating-point table) — see
docs/observability.md §benchmark-json.

The same line also carries the PRODUCT serving-path numbers (VERDICT r4
item 1): Scheduler + ServingEngine + paged Pallas kernel + int8 KV pools
under staggered arrivals — serving tokens/sec/chip and TTFT/ITL
percentiles, the BASELINE.md metrics of record.
"""
import json
import os
import sys
from pathlib import Path

# The long-context phase needs a seq-parallel mesh; on the CPU smoke
# that means 8 fake host devices (the tests/conftest.py arrangement).
# Harmless on TPU: the flag only shapes the host CPU platform, and the
# TPU backend's devices are what jax.devices() returns there.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

BASELINE_FILE = Path(__file__).parent / "bench_baseline.json"


def lint_preflight():
    """Run the project static analyzer (tools/staticcheck.py, ISSUE 11)
    over the default trees; returns the unsuppressed findings. A bench
    JSON published from a tree that violates the donation/lock/
    host-sync/determinism contracts would certify numbers the serving
    path can't be trusted to have produced — main() refuses."""
    tools = str(Path(__file__).parent / "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    import staticcheck
    return staticcheck.run_default()


def main() -> int:
    lint = lint_preflight()
    if lint:
        print("bench: refusing to run on a tree with unsuppressed "
              "staticcheck findings:", file=sys.stderr)
        for f in lint:
            print("  " + f.render(), file=sys.stderr)
        return 2
    import jax
    from butterfly_tpu.core.config import llama3_8b, tiny
    from butterfly_tpu.models.common import Model
    from butterfly_tpu.obs.benchmark import (run_autoscale_benchmark,
                                             run_chaos_benchmark,
                                             run_decode_benchmark,
                                             run_fleet_benchmark,
                                             run_longctx_benchmark,
                                             run_mixed_benchmark,
                                             run_serving_benchmark,
                                             run_spec_benchmark,
                                             run_warm_prefill_benchmark)
    from butterfly_tpu.quant.int8 import init_params_quantized

    on_tpu = jax.devices()[0].platform != "cpu"

    if on_tpu:
        # Llama-3-8B geometry (BASELINE configs[1]): int8 weights ~8.5 GB
        # fit one v5e chip's 16 GiB HBM with the int8 KV cache.
        cfg = llama3_8b().replace(max_seq_len=2048)
        batch, prompt_len, max_new = 128, 128, 128
        # decode_steps_per_tick=16: each tick runs 16 decode iterations
        # as ONE fused jitted scan (engine._decode_scan) — one dispatch
        # and one stacked token fetch per tick, so the per-token host
        # work (dispatch, operand conversion, RNG split) is paid once
        # per block; the dev tunnel's ~100 ms dispatch+fetch RTT would
        # otherwise dominate every per-token readback.
        # prefill_max_batch=16: a burst's prompts gang-prefill as
        # [B, 128] dispatches instead of one prompt per tick — the TTFT
        # lever this config's staggered-arrival phase measures
        serving_kw = dict(n_requests=64, prompt_len=128, max_new=128,
                          max_batch=32, decode_steps_per_tick=16,
                          prefill_max_batch=16)
        baseline_key = "tpu_8b"
    else:
        cfg = tiny("llama", dtype="float32", param_dtype="float32")
        batch, prompt_len, max_new = 4, 32, 32
        # max_new=32 (was 8): with k=4 fused blocks an 8-token request
        # lives ~2 blocks — all admission/finish barriers, no steady
        # state — so the smoke couldn't see decode-loop changes at all.
        # 32 gives ~8 blocks of steady decoding per request, enough for
        # the dispatch-ahead pipeline to show up in the sync-vs-
        # pipelined comparison below.
        serving_kw = dict(n_requests=8, prompt_len=16, max_new=32,
                          max_batch=4, decode_steps_per_tick=4,
                          prefill_max_batch=4)
        baseline_key = "cpu"

    model = Model(cfg)
    # int8 weight-only quant: the serving default for the bandwidth-bound
    # decode loop (CLI --quant int8); initialized pre-quantized so the 8B
    # float tree never materializes (init_params_quantized docs). Cast to
    # the compute dtype ONCE here: both benchmark engines share this tree,
    # and an engine-side cast would donate it out from under the other.
    from butterfly_tpu.engine.engine import cast_params
    params = cast_params(init_params_quantized(cfg, jax.random.PRNGKey(0)),
                         cfg)
    # int8 KV cache + write-combined decode window (CLI --kv-quant int8):
    # halves the cache bytes — the dominant decode-loop term at this
    # batch — and amortizes the whole-pool copy each in-loop cache
    # update costs on TPU (models/common.py window docs).
    kv_quant = "int8" if on_tpu else "none"
    stats = run_decode_benchmark(model, params, batch=batch,
                                 prompt_len=prompt_len, max_new=max_new,
                                 kv_quant=kv_quant)
    # Serving path at BOTH dispatch-ahead depths, same operating point:
    # inflight_blocks=1 is the synchronous drain-every-tick loop (the
    # "before"), the default depth keeps blocks in flight so host
    # scheduling overlaps device compute (the "after"). The headline
    # serving_* keys come from the pipelined run; the synchronous run's
    # throughput/gap ride along under a _sync suffix so the JSON line
    # carries the before/after comparison directly.
    serving_sync = run_serving_benchmark(
        model, params, kv_quant="int8" if on_tpu else "none",
        inflight_blocks=1,
        isolated_decode_tok_s_chip=stats["decode_tokens_per_sec_per_chip"],
        **serving_kw)
    # Write-combined KV window off (ISSUE 12): same operating point with
    # per-token pool scatters, so the JSON line carries the on/off pair
    # (`_nowin` suffix, serving_gap style) — the BENCH_r06 batch-128 TPU
    # comparison is then a --max-batch flag flip, not new plumbing.
    # Greedy outputs are byte-identical in both modes (parity grid).
    serving_nowin = run_serving_benchmark(
        model, params, kv_quant="int8" if on_tpu else "none",
        kv_write_combine=False,
        isolated_decode_tok_s_chip=stats["decode_tokens_per_sec_per_chip"],
        **serving_kw)
    serving = run_serving_benchmark(
        model, params, kv_quant="int8" if on_tpu else "none",
        # serving_gap (serving / isolated tok/s/chip) rides the serving
        # JSON so the trajectory tracks the gap this path is closing
        isolated_decode_tok_s_chip=stats["decode_tokens_per_sec_per_chip"],
        **serving_kw)
    for k in ("serving_tokens_per_sec_per_chip",
              "serving_capacity_tokens_per_sec", "serving_gap"):
        if k in serving_sync:
            serving[k + "_sync"] = serving_sync[k]
        if k in serving_nowin:
            serving[k + "_nowin"] = serving_nowin[k]
    # Speculation phase (ISSUE 9): spec-on vs spec-off tok/s at the
    # round's operating point plus the speculation instruments —
    # spec_tokens_per_forward (> 1 = drafts landing), the accept rate,
    # and drain barriers per verify round (~0 = the spec rounds really
    # pipeline instead of barriering like the old host accept loop).
    # Draft-friendly workload (prompts seeded with the model's own
    # greedy continuation) so prompt lookup has something to mine.
    # Warm-prefix flash prefill phase (ISSUE 13): long prompts (>= 512)
    # prefilled in chunks, so every chunk after the first runs the warm
    # path and admission rounds mix warm continuations with fresh
    # arrivals. On/off pair at the same operating point rides the JSON
    # under the `_dense` suffix (the `_nowin` pattern): off = the dense
    # O(T*S) warm fallback + the gang-freshness split this PR retires.
    # The prompt >= 512 grid point runs on BOTH platforms; on TPU the
    # on leg takes the kernel, on CPU (kernels are TPU-only) it
    # measures the gang-merge half and warm_prefill_kernelized: false
    # records that honestly.
    serving.update(run_warm_prefill_benchmark(
        model, params, kv_quant=kv_quant, prompt_len=640,
        prefill_chunk=256, n_requests=6, max_batch=4))
    # Long-context phase (ISSUE 20): one prompt spanning >= 8 prefill
    # chunks admitted through the scheduler's seq-parallel lane
    # (chunked SP prefill -> paged decode), beside short decoders. The
    # acceptance pair: longctx_mixed_itl_p95 vs the alone p95 + the
    # declared one-SP-chunk budget (longctx_itl_within_budget), plus
    # the ring-vs-jnp microbench pair with its CPU honesty key
    # (longctx_ring_kernelized: false — the Pallas leg is covered by
    # the interpret-mode parity grid, not by this wall clock).
    longctx_kw = (dict(prompt_len=4096, prefill_chunk=512, max_new=16,
                       decode_new=64, kv_quant="int8")
                  if on_tpu else dict())
    serving.update(run_longctx_benchmark(model, params, **longctx_kw))
    # The spec phase also drafts with BOTH sources (ngram vs the real
    # on-device draft model, ISSUE 14) on mixed_chat-shaped prompts at
    # the same operating point: spec_accept_rate_model >
    # spec_accept_rate_ngram is the ROADMAP item 3 evidence key.
    # draft_layers=1: the tiny CPU model is 2 layers deep, so 1 is the
    # only strict truncation; on the 8B a 1-layer shared-embed draft is
    # the cheapest resident draft (the TPU operating point can raise it
    # from the profile).
    spec_kw = dict(n_requests=serving_kw["n_requests"],
                   prompt_len=serving_kw["prompt_len"],
                   max_new=serving_kw["max_new"],
                   max_batch=serving_kw["max_batch"],
                   decode_steps_per_tick=serving_kw["decode_steps_per_tick"],
                   gamma=4, draft_layers=1)
    serving.update(run_spec_benchmark(
        model, params, kv_quant=kv_quant, **spec_kw))
    # Mixed-workload phase (ISSUE 10): the canned mixed_chat population
    # (heterogeneous prompts 32-1024 on TPU, shared-prefix cohorts,
    # priority/deadline mix) fired OPEN-LOOP in bursts against a page
    # pool sized below worst-case demand, so preemption, SLO-aware
    # shedding, deadline scrubbing, and the prefix cache are all
    # measured instead of idle (the uniform phase above reports
    # serving_preemptions: 0 by construction). Also emits the
    # decode_steps_per_tick x inflight_blocks operating-point table +
    # knee — the curve the round's operating point is chosen from.
    if on_tpu:
        # pool at 15% of worst-case demand: the cohort mix averages
        # ~18 pages/request, so 32 contested slots (~576 pages) overrun
        # the ~390-page pool while the largest single request (81
        # pages) still fits — preemption measured, not configured away
        # host KV tier (ISSUE 17): the contested pool above evicts
        # shared-prefix chains mid-run; a 64 MB host tier turns those
        # into demotions that revive on the cohorts' next admission —
        # kv_tier_hit_rate/restore latency measured under real pressure
        mixed_kw = dict(n_requests=64, max_batch=32,
                        prompt_lo=32, prompt_hi=1024,
                        max_new_lo=16, max_new_hi=256, page_size=16,
                        pool_fraction=0.15, host_kv_tier_mb=64.0,
                        decode_steps_per_tick=16, inflight_blocks=2,
                        prefill_max_batch=16, kv_quant="int8",
                        grid=[(4, 1), (4, 2), (16, 1), (16, 2)])
    else:
        # CPU smoke: decode budgets 16-48 keep slots alive across
        # many blocks (short budgets drain before pressure builds) and
        # the near-instant burst outruns the tiny model's service rate,
        # so the 0.35-provisioned pool is genuinely contested (verified:
        # every grid point preempts at this shape)
        mixed_kw = dict(n_requests=12, max_batch=4,
                        prompt_lo=8, prompt_hi=48,
                        max_new_lo=16, max_new_hi=48, page_size=8,
                        pool_fraction=0.35, host_kv_tier_mb=8.0,
                        arrival="burst:2000:0.5:0.1",
                        decode_steps_per_tick=4, inflight_blocks=2,
                        prefill_max_batch=4, kv_quant="none",
                        grid=[(1, 1), (1, 2), (4, 1), (4, 2)])
    serving.update(run_mixed_benchmark(model, params, **mixed_kw))
    # Unified mixed dispatch (ISSUE 18) acceptance pair as explicit
    # deltas: admission barrier count (fused ≈ 0 vs the alternating
    # reference's one per mid-flight arrival) and the ITL-p95 change
    # that buys at heavy prompt load (negative = fused improves the
    # tail). The raw `_alt` pairs ride along from the benchmark fns.
    for phase, itl, bar in (
            ("serving", "itl_req_mean_p95", "serving_admission_barriers"),
            ("mixed", "mixed_itl_req_mean_p95", "mixed_admission_barriers")):
        if itl in serving and itl + "_alt" in serving:
            serving[phase + "_itl_p95_delta"] = \
                serving[itl] - serving[itl + "_alt"]
        if bar in serving and bar + "_alt" in serving:
            serving[phase + "_admission_barriers_delta"] = \
                serving[bar] - serving[bar + "_alt"]
    toks_per_sec_chip = stats["tokens_per_sec_per_chip"]

    vs = 1.0
    if BASELINE_FILE.exists():
        base = json.loads(BASELINE_FILE.read_text())
        if base.get(baseline_key):
            vs = toks_per_sec_chip / base[baseline_key]

    out = {
        "metric": "decode_tokens_per_sec_per_chip",
        "value": round(toks_per_sec_chip, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs, 4),
        "model": "llama3-8b" if on_tpu else "tiny",
        "quant": "int8",
        "kv_quant": kv_quant,
        "decode_isolated_tokens_per_sec_per_chip":
            round(stats["decode_tokens_per_sec_per_chip"], 2),
        "hbm_util": round(stats["hbm_util"], 4),
        "mfu": round(stats["mfu"], 4),
        # the preflight refused above unless this is 0: the trajectory
        # records the tree staying contract-clean round over round
        "staticcheck_findings_total": len(lint),
    }
    for k, v in serving.items():
        out[k] = round(v, 4) if isinstance(v, float) else v
    # Fleet tier: a 2-prefill + 2-decode disaggregated topology
    # (in-process, tiny model on BOTH platforms — the fleet numbers
    # measure the control plane's handoff + rolling drain/restart, not
    # the model) driven through the loadgen soak. Carries the before/
    # after TTFT (direct vs disaggregated), the cross-replica KV
    # transfer volume/hit-rate, and the zero-drop soak property.
    fleet = run_fleet_benchmark("2p2d")
    for k, v in fleet.items():
        out[k] = round(v, 4) if isinstance(v, float) else v
    # Chaos tier: the same 2p2d topology under the seeded stock fault
    # plan (delays, 500s, a breaker-tripping wedge burst, drops,
    # truncations) plus a spent-deadline burst. Carries the overload-
    # protection counters (serving_shed_total, deadline_expired_total,
    # breaker_open_total) and the terminal-outcome property: every
    # request ends in tokens, 429, or 504 — zero hangs, zero silent
    # drops (chaos_unterminal/chaos_errors == 0 when healthy).
    chaos = run_chaos_benchmark("2p2d")
    for k, v in chaos.items():
        out[k] = round(v, 4) if isinstance(v, float) else v
    # Elastic tier (ISSUE 17): a ramp arrival against a 1-decode floor
    # with the closed-loop autoscaler governing the decode tier.
    # Carries SLO attainment, the replica-seconds integral vs the
    # static peak shape (the saving the loop exists to buy), and the
    # flight-recorder scale-event audit count.
    autoscale = run_autoscale_benchmark("1p1d")
    for k, v in autoscale.items():
        out[k] = round(v, 4) if isinstance(v, float) else v
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
