// Host-side page allocator for the paged KV cache — native runtime half.
//
// The reference scaffold planned a native (Rust) runtime around its
// engine (/root/reference/.gitignore:1-4 is a Cargo template; no code
// exists — SURVEY.md §0). This is the TPU-framework equivalent piece:
// the allocator sits on the scheduler's per-tick hot path (admission,
// just-in-time decode growth, preemption release) and owns no device
// state — the device only ever sees static pools and int32 block tables.
//
// Semantics are EXACTLY cache/allocator.py's PageAllocator (the Python
// fallback): LIFO free-list handing out low page ids first, per-slot
// ordered ownership lists, all-or-nothing grow, release returns pages
// in reverse so allocation order is stable across either backend.
// Parity is property-tested in tests/test_native.py.
//
// Build: make -C native   (or python -m butterfly_tpu.native.build)
// ABI: plain C (ctypes-friendly), one allocator handle per Scheduler.

#include <cstddef>
#include <cstdint>
#include <vector>

using std::size_t;

namespace {

struct Allocator {
  int32_t num_pages;          // usable pages (null page excluded)
  int32_t page_size;          // tokens per page
  int32_t max_pages_per_seq;  // block-table row width
  std::vector<int32_t> free_list;          // back = next page handed out
  std::vector<std::vector<int32_t>> owned; // slot -> page ids, in order
};

int32_t pages_needed(const Allocator& a, int32_t slot, int32_t new_length) {
  const int32_t have = static_cast<int32_t>(a.owned[slot].size());
  const int32_t want = (new_length + a.page_size - 1) / a.page_size;
  return want > have ? want - have : 0;
}

}  // namespace

extern "C" {

// Returns an opaque handle. num_slots bounds the slot index space (the
// scheduler's max_batch_size); slot ids outside [0, num_slots) are the
// caller's bug and are range-checked defensively.
void* bfa_create(int32_t num_pages, int32_t page_size,
                 int32_t max_pages_per_seq, int32_t num_slots) {
  if (num_pages < 0 || page_size <= 0 || max_pages_per_seq <= 0 ||
      num_slots <= 0) {
    return nullptr;
  }
  auto* a = new Allocator();
  a->num_pages = num_pages;
  a->page_size = page_size;
  a->max_pages_per_seq = max_pages_per_seq;
  a->free_list.reserve(num_pages);
  for (int32_t p = num_pages - 1; p >= 0; --p) a->free_list.push_back(p);
  a->owned.resize(num_slots);
  return a;
}

void bfa_destroy(void* h) { delete static_cast<Allocator*>(h); }

int32_t bfa_free_pages(void* h) {
  return static_cast<int32_t>(static_cast<Allocator*>(h)->free_list.size());
}

// Writes slot's page ids into out (caller sizes it max_pages_per_seq);
// returns the count.
int32_t bfa_pages_of(void* h, int32_t slot, int32_t* out) {
  auto* a = static_cast<Allocator*>(h);
  if (slot < 0 || slot >= static_cast<int32_t>(a->owned.size())) return 0;
  const auto& pages = a->owned[slot];
  for (size_t i = 0; i < pages.size(); ++i) out[i] = pages[i];
  return static_cast<int32_t>(pages.size());
}

int32_t bfa_can_grow(void* h, int32_t slot, int32_t new_length) {
  auto* a = static_cast<Allocator*>(h);
  if (slot < 0 || slot >= static_cast<int32_t>(a->owned.size())) return 0;
  if (new_length > a->max_pages_per_seq * a->page_size) return 0;
  return pages_needed(*a, slot, new_length) <=
                 static_cast<int32_t>(a->free_list.size())
             ? 1
             : 0;
}

// All-or-nothing grow. Returns the number of freshly allocated pages
// written to out (possibly 0), or -1 when the request cannot be
// satisfied (nothing is allocated).
int32_t bfa_grow(void* h, int32_t slot, int32_t new_length, int32_t* out) {
  auto* a = static_cast<Allocator*>(h);
  if (!bfa_can_grow(h, slot, new_length)) return -1;
  const int32_t n = pages_needed(*a, slot, new_length);
  auto& mine = a->owned[slot];
  for (int32_t i = 0; i < n; ++i) {
    const int32_t page = a->free_list.back();
    a->free_list.pop_back();
    mine.push_back(page);
    out[i] = page;
  }
  return n;
}

// Frees all of slot's pages (finish/preempt); returns how many.
int32_t bfa_release(void* h, int32_t slot) {
  auto* a = static_cast<Allocator*>(h);
  if (slot < 0 || slot >= static_cast<int32_t>(a->owned.size())) return 0;
  auto& pages = a->owned[slot];
  const int32_t n = static_cast<int32_t>(pages.size());
  for (auto it = pages.rbegin(); it != pages.rend(); ++it) {
    a->free_list.push_back(*it);
  }
  pages.clear();
  return n;
}

}  // extern "C"
