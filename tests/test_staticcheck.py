"""Tier-1 enforcement of the project-native static analyzer (ISSUE 11).

Three layers:

* the REPO ITSELF must lint clean — `staticcheck.run_default()` walks
  butterfly_tpu/, tools/, tests/ (minus the fixture snippets, which
  violate rules by design) and must return zero unsuppressed findings;
  every inline suppression must carry a reason;
* each rule must FIRE on its positive fixture and stay SILENT on its
  negative one (tests/staticcheck_fixtures/) — the contract
  tools/mutcheck.py's analyzer mutants verify stays sharp: weakening
  any one rule predicate makes its positive-count assertion fail;
* the driver surfaces behave: CLI exit codes, suppression mechanics,
  and the `butterfly lint` subcommand.

Stdlib-only (AST analysis): fast tier.
"""
from pathlib import Path
import subprocess
import sys

import pytest

REPO = Path(__file__).parent.parent
TOOLS = REPO / "tools"
FIXTURES = Path(__file__).parent / "staticcheck_fixtures"

sys.path.insert(0, str(TOOLS))
import staticcheck  # noqa: E402
import staticrules  # noqa: E402


def lint_fixture(name: str, rule_id: str):
    """Run exactly one rule over one fixture file (force=True: fixtures
    live outside the rule's deployment scope on purpose)."""
    rule = staticrules.RULES[rule_id]
    return staticrules.check_file(FIXTURES / name, rules=[rule],
                                  force=True)


# -- the rule catalog ---------------------------------------------------------

EXPECTED_RULES = {
    "BTF001": "outbound-http-timeout",
    "BTF002": "use-after-donation",
    "BTF003": "host-sync-in-hot-path",
    "BTF004": "lock-discipline",
    "BTF005": "workload-determinism",
    "BTF006": "prng-key-discipline",
}

#: rule -> expected finding count on its positive fixture. Pinned as
#: exact counts (not >= 1) so a weakened predicate that still catches
#: SOME sites — the mutcheck analyzer mutants — fails loudly.
POSITIVE_COUNTS = {
    "BTF001": 4,
    "BTF002": 8,
    "BTF003": 10,
    "BTF004": 7,
    "BTF005": 7,
    "BTF006": 3,
}


def test_all_rules_registered():
    assert set(EXPECTED_RULES) <= set(staticrules.RULES)
    for rid, name in EXPECTED_RULES.items():
        rule = staticrules.RULES[rid]
        assert rule.name == name
        assert rule.invariant, f"{rid} must state its invariant"
        assert rule.scope, f"{rid} must declare a scope"


@pytest.mark.parametrize("rid", sorted(EXPECTED_RULES))
def test_rule_fires_on_positive_fixture(rid):
    found = [f for f in lint_fixture(f"btf{rid[3:]}_pos.py", rid)
             if f.rule == rid]
    assert len(found) == POSITIVE_COUNTS[rid], \
        f"{rid} expected {POSITIVE_COUNTS[rid]} findings, got:\n" \
        + "\n".join(f.render() for f in found)
    assert all(not f.suppressed for f in found)


@pytest.mark.parametrize("rid", sorted(EXPECTED_RULES))
def test_rule_silent_on_negative_fixture(rid):
    found = [f for f in lint_fixture(f"btf{rid[3:]}_neg.py", rid)
             if f.rule == rid]
    assert not found, "false positives on the negative fixture:\n" \
        + "\n".join(f.render() for f in found)


# -- the repo itself ----------------------------------------------------------

def test_repo_tree_lints_clean():
    """THE acceptance gate: butterfly_tpu/ + tools/ + tests/ carry zero
    unsuppressed findings. A new violation anywhere in the walked trees
    fails tier-1 — the machine check the last ten PRs did by hand."""
    findings = staticcheck.run_default()
    assert not findings, "unsuppressed staticcheck findings:\n" \
        + "\n".join(f.render() for f in findings)


def test_no_bare_suppressions_in_repo():
    """Every `# btf: disable=` in the walked trees must carry a reason
    (a bare one would also surface as BTF000 in the clean-tree test;
    this pins the contract directly and readably)."""
    bare = []
    for f in staticcheck.iter_py_files(
            [REPO / t for t in staticcheck.DEFAULT_TREES]):
        for s in staticrules.parse_suppressions(f.read_text()):
            if not s.reason:
                bare.append(f"{f.relative_to(REPO)}:{s.line}")
    assert not bare, f"reason-less suppressions: {bare}"


def test_repo_suppressions_are_used_and_scarce():
    """Suppressions must point at real findings (a stale disable hides
    nothing and rots) and stay rare — the analyzer encodes contracts,
    not preferences."""
    findings = staticcheck.run_paths(
        [REPO / t for t in staticcheck.DEFAULT_TREES])
    suppressed = [f for f in findings if f.suppressed]
    assert suppressed, "expected the documented intentional exceptions"
    assert len(suppressed) < 20, \
        "suppression creep: fix the code or retune the rule"
    for f in suppressed:
        assert f.reason


# -- suppression mechanics ----------------------------------------------------

def test_suppression_mechanics():
    rule = staticrules.RULES["BTF001"]
    found = staticrules.check_file(FIXTURES / "suppression.py",
                                   rules=[rule], force=True)
    by_rule = {}
    for f in found:
        by_rule.setdefault(f.rule, []).append(f)
    btf1 = sorted(by_rule["BTF001"], key=lambda f: f.line)
    assert len(btf1) == 3
    reasoned, bare, multiline = btf1
    assert reasoned.suppressed and "reasoned suppression" in reasoned.reason
    assert not bare.suppressed, \
        "a reason-less disable must NOT suppress"
    assert multiline.suppressed, \
        "a standalone comment must cover the whole next statement"
    assert len(by_rule.get("BTF000", [])) == 1, \
        "the bare disable must itself be a BTF000 finding"


# -- driver surfaces ----------------------------------------------------------

def test_cli_clean_tree_exits_zero():
    r = subprocess.run([sys.executable, str(TOOLS / "staticcheck.py")],
                       cwd=REPO, capture_output=True, text=True,
                       timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_violation_exits_one():
    r = subprocess.run(
        [sys.executable, str(TOOLS / "staticcheck.py"), "--force",
         str(FIXTURES / "btf001_pos.py")],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "BTF001" in r.stdout


def test_cli_list_rules():
    r = subprocess.run(
        [sys.executable, str(TOOLS / "staticcheck.py"), "--list-rules"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0
    for rid in EXPECTED_RULES:
        assert rid in r.stdout


def test_butterfly_lint_subcommand():
    """`butterfly lint` goes through serve/cli.py and must agree with
    the direct driver on the clean tree."""
    from butterfly_tpu.serve.cli import main
    assert main(["lint"]) == 0


def test_bench_preflight_gate():
    """bench.py refuses to publish a JSON line from a dirty tree: its
    preflight is the same run_default() walk, so on the committed tree
    it must come back empty (and the bench JSON records the 0)."""
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.remove(str(REPO))
    findings = bench.lint_preflight()
    assert findings == []
