"""Multi-process + fault-injection tests (SURVEY.md §5 failure-detection
row; VERDICT r2 item 8).

* 2-process jax.distributed bringup on the CPU backend: real coordinator
  rendezvous, a global mesh spanning both processes, one cross-process
  psum (Gloo collectives) — exercised through core.mesh.init_distributed.
* Kill-a-host recovery: a subprocess scheduler is SIGKILLed with live
  requests (running, waiting AND mid-chunked-prefill); the parent
  restores its serving snapshot into a fresh scheduler and the recovered
  outputs must match an uninterrupted reference token-for-token.

Both spawn subprocesses with a clean 1-device CPU env (the parent's
8-fake-device XLA_FLAGS is stripped).
"""
import json
import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

import jax

REPO = Path(__file__).resolve().parent.parent
HERE = Path(__file__).resolve().parent


def _child_env():
    env = dict(os.environ)
    env["XLA_FLAGS"] = ""  # 1 local CPU device per process
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_psum():
    port = _free_port()
    procs = [subprocess.Popen(
        [sys.executable, str(HERE / "distributed_worker.py"),
         str(pid), "2", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        env=_child_env(), text=True) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
            assert p.returncode == 0, f"worker failed:\n{out}"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, out in enumerate(outs):
        assert f"proc{pid} psum_ok" in out, out


def test_kill_one_process_recovers_queued_work(tmp_path):
    """SIGKILL a serving process mid-flight; the snapshot alone must let a
    fresh scheduler finish every request with exactly the tokens an
    uninterrupted run produces (greedy recompute-from-prefix)."""
    from butterfly_tpu.ckpt.sharded import restore_serving_snapshot
    from butterfly_tpu.core.config import RuntimeConfig, tiny
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.models.common import Model
    from butterfly_tpu.sched.scheduler import Scheduler

    snap = tmp_path / "serving_snapshot.json"
    proc = subprocess.Popen(
        [sys.executable, str(HERE / "crash_worker.py"), str(snap), "4"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        env=_child_env(), text=True)
    try:
        deadline = time.monotonic() + 240
        while not snap.exists():
            assert proc.poll() is None, \
                f"worker died early:\n{proc.communicate()[0]}"
            assert time.monotonic() < deadline, "snapshot never appeared"
            time.sleep(0.1)
        proc.send_signal(signal.SIGKILL)  # the host "crash"
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()

    data = json.loads(snap.read_text())
    assert len(data["requests"]) == 3  # incl. the mid-chunked-prefill one
    partial = {tuple(r["prompt"]): r["output"] for r in data["requests"]}

    # same model/params as the worker (deterministic init from the seed)
    cfg = tiny("llama", dtype="float32", param_dtype="float32")
    model = Model(cfg)
    params = model.init(jax.random.PRNGKey(42))
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=64, page_size=8)

    sched = Scheduler(ServingEngine(model, params, rt))
    n = restore_serving_snapshot(snap, sched)
    assert n == 3
    recovered = {tuple(r.prompt): r for r in
                 list(sched.running) + list(sched.waiting)}
    sched.run_until_done()

    # uninterrupted reference
    ref = Scheduler(ServingEngine(model, params, rt))
    specs = [([5, 7, 11], 12), ([3, 1], 10), ([2, 4, 6, 8, 10, 12], 8)]
    ref_reqs = [ref.submit(p, max_new_tokens=m) for p, m in specs]
    ref.run_until_done()

    for (prompt, _), ref_req in zip(specs, ref_reqs):
        pre = partial[tuple(prompt)]
        # restore resubmits prompt+partial-output as the new prompt
        rec = recovered[tuple(prompt) + tuple(pre)]
        assert rec.state == "finished"
        assert pre + rec.output == ref_req.output, \
            f"recovered tokens diverge for prompt {prompt}"


# -- hybrid (multi-slice / DCN) mesh ----------------------------------------

class _FakeDev:
    """Minimal stand-in with the attrs slice grouping reads."""
    def __init__(self, i, slice_index):
        self.id = i
        self.slice_index = slice_index
        self.process_index = slice_index

    def __repr__(self):
        return f"dev{self.id}@slice{self.slice_index}"


def test_hybrid_mesh_single_slice_falls_back():
    from butterfly_tpu.core.config import MeshConfig
    from butterfly_tpu.core.mesh import make_hybrid_mesh, make_mesh
    import jax
    devs = jax.devices()[:4]  # fake CPUs: no slice_index -> one group
    cfg = MeshConfig(data=2, tensor=2)
    a = make_hybrid_mesh(cfg, devs)
    b = make_mesh(cfg, devs)
    assert a.shape == b.shape
    assert [d.id for d in a.devices.flat] == [d.id for d in b.devices.flat]


def test_hybrid_mesh_validations():
    from butterfly_tpu.core.config import MeshConfig
    from butterfly_tpu.core.mesh import make_hybrid_mesh
    import pytest
    devs = [_FakeDev(i, i // 4) for i in range(8)]  # 2 slices x 4
    with pytest.raises(ValueError, match="unknown mesh axes"):
        make_hybrid_mesh(MeshConfig(data=2, tensor=4), devs,
                         dcn_axes=("nope",))
    with pytest.raises(ValueError, match="spans 2 slices"):
        # data=4 over 2 slices
        make_hybrid_mesh(MeshConfig(data=4, tensor=2), devs)
    with pytest.raises(ValueError, match="must contribute"):
        make_hybrid_mesh(MeshConfig(data=2, tensor=4),
                         [_FakeDev(i, 0 if i < 5 else 1) for i in range(8)])


def test_hybrid_mesh_places_data_axis_across_slices(monkeypatch):
    """The device array handed to Mesh must vary slice only along the
    dcn axes — every per-layer collective then stays intra-slice."""
    from butterfly_tpu.core.config import MeshConfig
    from butterfly_tpu.core import mesh as M

    devs = [_FakeDev(i, i // 4) for i in range(8)]
    captured = {}

    def fake_create(ici_shape, dcn_shape, devices=None, **kw):
        captured["ici"] = tuple(ici_shape)
        captured["dcn"] = tuple(dcn_shape)
        import numpy as np
        # slice-major arrangement, as the real helper guarantees
        arr = np.asarray(devices).reshape(
            [i * d for i, d in zip(ici_shape, dcn_shape)])
        return arr

    import jax.experimental.mesh_utils as mu
    monkeypatch.setattr(mu, "create_hybrid_device_mesh", fake_create)
    mesh = M.make_hybrid_mesh(MeshConfig(data=2, tensor=4), devs)
    assert captured["dcn"] == (2, 1, 1, 1, 1)   # data across slices
    assert captured["ici"] == (1, 1, 1, 1, 4)   # tensor within a slice
    assert mesh.shape == {"data": 2, "stage": 1, "expert": 1, "seq": 1,
                          "tensor": 4}
