"""Worker for the 2-process jax.distributed test (run as a subprocess).

Exercises core.mesh.init_distributed — the multi-host control-plane
bringup (SURVEY.md §3 call stack 3) — on the CPU backend: DCN-style
rendezvous via the coordinator, a global mesh over both processes'
devices, and one cross-process psum through shard_map.

Usage: python distributed_worker.py <process_id> <num_processes> <port>
"""
import sys


def main() -> None:
    pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    import jax
    jax.config.update("jax_platforms", "cpu")

    from butterfly_tpu.core.config import MeshConfig
    from butterfly_tpu.core.mesh import init_distributed, make_mesh

    init_distributed(coordinator=f"127.0.0.1:{port}", num_processes=n,
                     process_id=pid)
    assert jax.process_count() == n, jax.process_count()
    assert jax.device_count() == n * jax.local_device_count()

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(MeshConfig(data=jax.device_count()))
    # each process contributes its local shard(s) of a data-sharded array
    sharding = NamedSharding(mesh, P("data"))
    local = [jnp.full((1,), float(pid * jax.local_device_count() + i + 1))
             for i in range(jax.local_device_count())]
    garr = jax.make_array_from_single_device_arrays(
        (jax.device_count(),), sharding, [
            jax.device_put(x, d) for x, d in
            zip(local, mesh.local_devices)])
    out = jax.jit(jax.shard_map(
        lambda x: jax.lax.psum(x, "data"), mesh=mesh,
        in_specs=P("data"), out_specs=P(), check_vma=False))(garr)
    total = float(np.asarray(out)[0])
    expect = sum(range(1, jax.device_count() + 1))
    assert total == expect, (total, expect)

    # all_hosts_probe is a collective — both processes reach this same
    # coordinated point, which is exactly its documented usage contract
    from butterfly_tpu.obs.health import all_hosts_probe
    assert all_hosts_probe()
    print(f"proc{pid} psum_ok {total} hosts_probe_ok", flush=True)


if __name__ == "__main__":
    main()
