"""Prompt-lookup speculative decoding (engine.generate_speculative).

The contract: at temperature 0 output tokens are IDENTICAL to plain
greedy decode; at temperature > 0 the rejection-sampling correction
makes the output DISTRIBUTION identical to plain sampling (pinned
statistically in tests/test_spec_sampling.py) — speculation changes
how many forwards a generation takes, never what it produces. Greedy
parity is pinned across prompts, gammas, stop tokens, and the int8 KV
cache; the acceptance machinery is additionally exercised on a looping
continuation where drafts actually hit.
"""
import jax
import numpy as np
import pytest

from butterfly_tpu.core.config import RuntimeConfig, tiny
from butterfly_tpu.engine import InferenceEngine, SamplingParams
from butterfly_tpu.engine.engine import _ngram_draft
from butterfly_tpu.models.common import Model

CFG = tiny("llama", dtype="float32", param_dtype="float32")


def make_engine(**rt):
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(3))
    return InferenceEngine(model, params, RuntimeConfig(**rt))


def ref_tokens(eng, prompt, sp):
    res = eng.generate([prompt], sp)
    return res.tokens[0, :int(res.lengths[0])].tolist()


def test_ngram_draft_lookup():
    #          0  1  2  3  4  5  6  7
    history = [5, 9, 2, 7, 1, 5, 9, 4]
    # tail [9,4] has no earlier occurrence -> zero padding
    assert _ngram_draft(history, 3, 2) == [0, 0, 0]
    # tail [5,9] in [5,9,2,7,1,5,9,5,9]: most recent earlier match is at
    # index 5 -> continuation [5,9], padded
    assert _ngram_draft(history[:-1] + [5, 9], 3, 2) == [5, 9, 0]
    # with only the index-0 occurrence, its continuation is drafted
    assert _ngram_draft([5, 9, 2, 7, 1, 5, 9], 3, 2) == [2, 7, 1]
    # short continuation pads
    assert _ngram_draft([1, 2, 1, 2], 4, 2)[:2] == [1, 2]


def test_parity_with_plain_greedy():
    eng = make_engine(max_seq_len=128)
    sp = SamplingParams(max_new_tokens=24)
    for prompt in ([5, 7, 11], [2], list(range(1, 17)), [3, 3, 3, 3, 3]):
        want = ref_tokens(eng, prompt, sp)
        for gamma in (1, 3, 5):
            got = eng.generate_speculative(prompt, sp, gamma=gamma)
            assert got.tokens.tolist() == want, (prompt, gamma)


def test_parity_with_stop_token():
    eng = make_engine(max_seq_len=128)
    base = ref_tokens(eng, [5, 7, 11], SamplingParams(max_new_tokens=24))
    stop = base[10]
    sp = SamplingParams(max_new_tokens=24, stop_token=stop)
    want = ref_tokens(eng, [5, 7, 11], sp)
    got = eng.generate_speculative([5, 7, 11], sp, gamma=4)
    assert got.tokens.tolist() == want
    assert got.tokens.tolist()[-1] == stop


def test_accepts_drafts_on_repetitive_continuation():
    """Greedy decode from a tiny random model settles into a loop (the
    prompt-lookup sweet spot); with the looping continuation seeded in
    the prompt, verifies must accept drafts and finish in far fewer
    forwards than tokens."""
    eng = make_engine(max_seq_len=256)
    sp0 = SamplingParams(max_new_tokens=32)
    cont = ref_tokens(eng, [5, 7, 11], sp0)
    # seed the prompt with the model's own continuation: drafts now hit
    prompt = [5, 7, 11] + cont
    sp = SamplingParams(max_new_tokens=32)
    want = ref_tokens(eng, prompt, sp)
    got = eng.generate_speculative(prompt, sp, gamma=4)
    assert got.tokens.tolist() == want
    assert got.accepted_drafts > 0
    assert got.forwards < 1 + len(want)  # beat one-forward-per-token


def test_parity_with_int8_kv_cache():
    eng = make_engine(max_seq_len=128, kv_quant="int8")
    sp = SamplingParams(max_new_tokens=16)
    want = ref_tokens(eng, [5, 7, 11, 2], sp)
    got = eng.generate_speculative([5, 7, 11, 2], sp, gamma=3)
    assert got.tokens.tolist() == want


def test_sampling_supported():
    """Temperature > 0 runs through the rejection-sampling correction:
    full budget generated, same-seed reproducible, different seeds
    actually sample (the guard that used to reject sampling is gone —
    exactness of the correction itself is pinned statistically in
    tests/test_spec_sampling.py)."""
    eng = make_engine(max_seq_len=128)
    sp = SamplingParams(temperature=0.9, top_k=16, max_new_tokens=20)
    a = eng.generate_speculative([5, 7, 11], sp, gamma=3, seed=1)
    b = eng.generate_speculative([5, 7, 11], sp, gamma=3, seed=1)
    c = eng.generate_speculative([5, 7, 11], sp, gamma=3, seed=2)
    assert len(a.tokens) == 20
    assert a.tokens.tolist() == b.tokens.tolist()
    assert a.forwards == b.forwards
    # a different seed draws a different trajectory (overwhelmingly
    # likely at 20 sampled tokens over a 258 vocab)
    assert a.tokens.tolist() != c.tokens.tolist()


def test_cli_speculate_flag():
    from butterfly_tpu.serve.cli import main
    assert main(["generate", "--model", "tiny", "--prompt", "hello",
                 "--max-new", "8", "--speculate", "4"]) == 0


def test_parity_on_tensor_mesh():
    from butterfly_tpu.core.config import MeshConfig
    from butterfly_tpu.core.mesh import make_mesh
    from butterfly_tpu.parallel.partition import shard_params

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 fake devices")
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(3))
    ref = InferenceEngine(model, params, RuntimeConfig(max_seq_len=128))
    sp = SamplingParams(max_new_tokens=12)
    want = ref_tokens(ref, [5, 7, 11], sp)

    mesh = make_mesh(MeshConfig(tensor=4), jax.devices()[:4])
    eng = InferenceEngine(model, shard_params(params, CFG, mesh),
                          RuntimeConfig(max_seq_len=128), mesh=mesh)
    got = eng.generate_speculative([5, 7, 11], sp, gamma=3)
    assert got.tokens.tolist() == want


def test_rejects_data_parallel_mesh():
    from butterfly_tpu.core.config import MeshConfig
    from butterfly_tpu.core.mesh import make_mesh
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 fake devices")
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(3))
    mesh = make_mesh(MeshConfig(data=2), jax.devices()[:2])
    eng = InferenceEngine(model, params, RuntimeConfig(max_seq_len=64),
                          mesh=mesh)
    with pytest.raises(NotImplementedError):
        eng.generate_speculative([1, 2], SamplingParams(max_new_tokens=4))


def test_cli_speculate_with_sampling():
    """--speculate now composes with --temperature (rejection-sampling
    correction): the CLI path must run, not reject."""
    from butterfly_tpu.serve.cli import main
    assert main(["generate", "--model", "tiny", "--prompt", "x",
                 "--max-new", "4", "--speculate", "2",
                 "--temperature", "0.5"]) == 0
