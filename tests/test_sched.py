"""Continuous-batching scheduler tests (SURVEY.md §7 stage 4).

Greedy parity: requests scheduled through slots + paged cache must produce
exactly the tokens InferenceEngine.generate produces on the contiguous
cache. Plus: staggered admission, preemption under page pressure, metrics.
"""
import jax
import numpy as np

from butterfly_tpu.core.config import RuntimeConfig, tiny
from butterfly_tpu.engine import InferenceEngine, SamplingParams
from butterfly_tpu.engine.serving import ServingEngine
from butterfly_tpu.models.common import Model
from butterfly_tpu.sched.scheduler import Scheduler

CFG = tiny("llama", dtype="float32", param_dtype="float32")


def make_sched(max_batch=2, max_seq=64, page=8, num_pages=0, seed=0, **rt_kw):
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(42))
    rt = RuntimeConfig(max_batch_size=max_batch, max_seq_len=max_seq,
                       page_size=page, num_pages=num_pages, **rt_kw)
    return Scheduler(ServingEngine(model, params, rt), seed=seed), params


def ref_tokens(params, prompt, max_new):
    eng = InferenceEngine(Model(CFG), params)
    res = eng.generate([prompt], SamplingParams(max_new_tokens=max_new))
    return res.tokens[0, :int(res.lengths[0])].tolist()


def test_single_request_greedy_parity():
    sched, params = make_sched()
    req = sched.submit([5, 7, 11], max_new_tokens=6)
    sched.run_until_done()
    assert req.state == "finished"
    assert req.output == ref_tokens(params, [5, 7, 11], 6)


def test_concurrent_requests_parity():
    """Two requests share the batch; each matches its solo reference."""
    sched, params = make_sched()
    r1 = sched.submit([5, 7, 11], max_new_tokens=6)
    r2 = sched.submit([3, 1], max_new_tokens=8)
    sched.run_until_done()
    assert r1.output == ref_tokens(params, [5, 7, 11], 6)
    assert r2.output == ref_tokens(params, [3, 1], 8)


def test_staggered_admission():
    """A request arriving mid-flight joins the running batch and still
    matches its solo reference (slot reuse after r1 finishes)."""
    sched, params = make_sched(max_batch=2)
    r1 = sched.submit([5, 7, 11], max_new_tokens=4)
    for _ in range(2):
        sched.tick()
    r2 = sched.submit([2, 4, 6, 8], max_new_tokens=5)
    r3 = sched.submit([9], max_new_tokens=3)  # waits for a slot
    sched.run_until_done()
    assert [r.state for r in (r1, r2, r3)] == ["finished"] * 3
    assert r1.output == ref_tokens(params, [5, 7, 11], 4)
    assert r2.output == ref_tokens(params, [2, 4, 6, 8], 5)
    assert r3.output == ref_tokens(params, [9], 3)


def test_queue_when_slots_full():
    sched, params = make_sched(max_batch=1)
    reqs = [sched.submit([i + 1], max_new_tokens=3) for i in range(3)]
    sched.run_until_done()
    for i, r in enumerate(reqs):
        assert r.output == ref_tokens(params, [i + 1], 3)


def test_preemption_under_page_pressure():
    """Tiny pool: two long generations can't both fit; the younger gets
    preempted+recomputed and still produces correct greedy output."""
    # 6 usable pages of 4 tokens; two requests growing to ~16 tokens each
    sched, params = make_sched(max_batch=2, max_seq=32, page=4, num_pages=6)
    r1 = sched.submit([5, 7, 11], max_new_tokens=10)
    r2 = sched.submit([3, 1], max_new_tokens=10)
    sched.run_until_done(max_ticks=300)
    assert r1.state == "finished" and r2.state == "finished"
    assert sched.metrics()["preemptions_total"] > 0
    assert r1.output == ref_tokens(params, [5, 7, 11], 10)
    assert r2.output == ref_tokens(params, [3, 1], 10)


def test_stop_token_frees_slot():
    sched, params = make_sched()
    ref = ref_tokens(params, [5, 7, 11], 8)
    stop = ref[2]  # force an early stop at the 3rd generated token
    req = sched.submit([5, 7, 11], max_new_tokens=8, stop_token=stop)
    sched.run_until_done()
    assert req.output == ref[:3]
    assert sched.alloc.free_pages == sched.alloc.num_pages


def test_metrics_surface():
    sched, _ = make_sched()
    sched.submit([1, 2], max_new_tokens=2)
    sched.run_until_done()
    m = sched.metrics()
    assert m["requests_finished"] == 1
    assert m["tokens_generated_total"] == 2
    assert m["ttft_p50"] >= 0
    # per-request mean inter-token gap: the burst-robust ITL stat
    assert m["itl_req_mean_p50"] >= 0
    assert m["kv_pages_free"] == m["kv_pages_total"]


def test_slo_attainment_counters_and_burn_rate():
    """Declared objectives turn latency into pass/fail counters: a
    generous SLO attains everything (burn 0), an impossible one
    violates everything (burn 1), and the trace finish event carries
    the per-request verdict."""
    from butterfly_tpu.obs.trace import Tracer
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(42))
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=64, page_size=8)
    engine = ServingEngine(model, params, rt)
    ok = Scheduler(engine, tracer=Tracer(), slo_ttft_s=1e6, slo_itl_s=1e6)
    r = ok.submit([5, 7, 11], max_new_tokens=4)
    ok.run_until_done()
    m = ok.metrics()
    assert m["slo_ttft_ok_total"] == 1 and m["slo_itl_ok_total"] == 1
    assert m["slo_violations_total"] == 0
    assert m["slo_burn_rate"] == 0.0 and m["slo_attainment"] == 1.0
    fin = [e for e in ok.trace.timeline(r.id)["events"]
           if e["name"] == "finish"][0]
    assert fin["slo_ok"] is True and fin["itl_mean_s"] >= 0
    # the typed registry renders the counters + burn gauge on /metrics
    text = ok.registry.render()
    assert "butterfly_slo_ttft_ok_total 1" in text
    assert "butterfly_slo_burn_rate 0" in text

    bad = Scheduler(engine, slo_ttft_s=1e-12, slo_itl_s=1e-12)
    bad.submit([5, 7, 11], max_new_tokens=4)
    bad.run_until_done()
    m = bad.metrics()
    assert m["slo_ttft_ok_total"] == 0
    assert m["slo_violations_total"] == 2  # ttft AND itl missed
    assert m["slo_burn_rate"] == 1.0 and m["slo_attainment"] == 0.0
    assert 'butterfly_slo_violations_total{kind="ttft"} 1' \
        in bad.registry.render()

    # no objective declared -> no accounting, no metrics keys
    off = Scheduler(engine)
    off.submit([5], max_new_tokens=2)
    off.run_until_done()
    assert "slo_burn_rate" not in off.metrics()


def test_streaming_callback_order():
    sched, _ = make_sched()
    seen = []
    req = sched.submit([4, 2], max_new_tokens=5,
                       on_token=lambda r, t: seen.append(t))
    sched.run_until_done()
    assert seen == req.output


def test_oversized_request_rejected_at_submit():
    """A request that could never fit the pool must be rejected up front
    (otherwise it livelocks admission / self-preempts forever)."""
    import pytest
    sched, _ = make_sched(max_batch=2, max_seq=32, page=4, num_pages=2)
    with pytest.raises(ValueError, match="KV pages"):
        sched.submit([1] * 20, max_new_tokens=20)
    # an over-max_seq request is likewise rejected (per-seq page limit)
    with pytest.raises(ValueError, match="KV pages"):
        sched.submit([1] * 30, max_new_tokens=30)
    assert not sched.has_work


def test_cancel_running_request_frees_resources():
    # mixed_dispatch=False: documents the ALTERNATING path's cadence
    # (prefill completes inside the admission tick); the fused-path
    # twins live in test_mixed_dispatch.py
    sched, _ = make_sched(mixed_dispatch=False)
    r1 = sched.submit([5, 7], max_new_tokens=50)
    r2 = sched.submit([3], max_new_tokens=4)
    sched.tick()
    assert r1.state == "running"
    sched.cancel(r1)
    assert r1.state == "cancelled" and r1.slot is None
    sched.run_until_done()
    assert r2.state == "finished"
    assert sched.alloc.free_pages == sched.alloc.num_pages
    assert sched.metrics()["requests_finished"] == 1


def test_chunked_prefill_parity():
    """A prompt far longer than prefill_chunk is prefilled in pieces that
    continue the warm cache — output must still match the whole-prompt
    reference exactly."""
    prompt = list(range(2, 32))  # 30 tokens, chunk=8 -> 4 chunks
    sched, params = make_sched(max_seq=64, prefill_chunk=8)
    req = sched.submit(prompt, max_new_tokens=6)
    sched.run_until_done()
    assert req.output == ref_tokens(params, prompt, 6)


def test_chunked_prefill_interleaves_decode():
    """VERDICT r2 item 3: a long admission must not head-of-line-block a
    decoding request — its inter-token gap stays at one tick per chunk."""
    sched, params = make_sched(max_batch=2, max_seq=64, prefill_chunk=4,
                               inflight_blocks=1)  # per-tick drain cadence
    r1 = sched.submit([5, 7, 11], max_new_tokens=20)
    sched.tick()
    sched.tick()  # second tick drains the first token + first decode step
    assert r1.state == "running" and len(r1.output) >= 1
    long_prompt = list(range(1, 17))  # 16 tokens = 4 chunks of 4
    r2 = sched.submit(long_prompt, max_new_tokens=4)
    gaps = []
    while r2.t_first_token is None:
        before = len(r1.output)
        sched.tick()
        gaps.append(len(r1.output) - before)
    # r2's prompt took multiple ticks to admit...
    assert len(gaps) >= 4
    # ...and r1 kept emitting exactly one token on EVERY one of them.
    assert all(g == 1 for g in gaps)
    sched.run_until_done()
    assert r1.output == ref_tokens(params, [5, 7, 11], 20)
    assert r2.output == ref_tokens(params, long_prompt, 4)


def test_cancel_mid_prefill_frees_resources():
    sched, _ = make_sched(max_batch=1, prefill_chunk=4)
    r1 = sched.submit(list(range(1, 17)), max_new_tokens=8)
    r2 = sched.submit([3], max_new_tokens=2)
    sched.tick()
    assert r1.state == "prefilling" and 0 < r1.prefilled < 16
    sched.cancel(r1)
    assert r1.state == "cancelled" and r1.slot is None
    sched.run_until_done()
    assert r2.state == "finished"
    assert sched.alloc.free_pages == sched.alloc.num_pages


def test_decode_steps_per_tick():
    # inflight_blocks=1: the synchronous drain-every-tick cadence this
    # test documents (the pipelined cadence has its own tests below)
    sched, params = make_sched(decode_steps_per_tick=3, inflight_blocks=1,
                               mixed_dispatch=False)
    req = sched.submit([5, 7, 11], max_new_tokens=10)
    # admission samples the first token on-device and the tick's 3
    # decode steps are dispatched chained on it; everything drains in
    # one stacked fetch at the NEXT tick's start (scheduler._inflight
    # docs), so the host sees 1+3 tokens one tick later
    sched.tick()
    assert len(req.output) == 0
    sched.tick()  # drains first + 3 in-flight steps, dispatches 3 more
    assert len(req.output) == 4
    sched.tick()
    assert len(req.output) == 7
    sched.run_until_done()
    assert req.output == ref_tokens(params, [5, 7, 11], 10)


def test_request_sized_to_page_cap_completes():
    """r5 regression: a request whose worst case exactly fills the
    per-seq page cap (accepted by submit) must finish — the pipelined
    page-growth target is clamped to the request's lifetime maximum,
    otherwise it self-preempts forever chasing in-flight slack pages."""
    sched, params = make_sched(max_batch=1, max_seq=32, page=8)
    prompt = list(range(1, 25))  # 24 + 8 = 32 = max_pages_per_seq * page
    req = sched.submit(prompt, max_new_tokens=8)
    sched.run_until_done(max_ticks=200)
    assert req.state == "finished"
    assert req.output == ref_tokens(params, prompt, 8)


def test_static_scheduler_drains_batches():
    """scheduler="static": a waiting request is only admitted once the
    in-flight batch has fully drained (no continuous admission)."""
    sched, params = make_sched(max_batch=2, scheduler="static")
    r1 = sched.submit([5, 7, 11], max_new_tokens=3)
    r2 = sched.submit([3, 1], max_new_tokens=6)
    sched.tick()
    r3 = sched.submit([9], max_new_tokens=2)
    while r3.state == "waiting":
        sched.tick()
    # r3 was only admitted after BOTH batch members finished
    assert r1.done and r2.done
    sched.run_until_done()
    assert r3.output == ref_tokens(params, [9], 2)


def test_cancel_waiting_request():
    sched, _ = make_sched(max_batch=1)
    r1 = sched.submit([5], max_new_tokens=30)
    r2 = sched.submit([6], max_new_tokens=3)
    sched.tick()
    sched.cancel(r2)  # still waiting
    assert r2.state == "cancelled"
    sched.run_until_done()
    assert r1.state == "finished" and len(r1.output) == 30


def test_inter_token_latency_metrics():
    """ITL percentiles appear once any request generates >= 2 tokens,
    and every non-first token contributes exactly one gap sample."""
    sched, _ = make_sched()
    r1 = sched.submit([5, 7, 11], max_new_tokens=6)
    r2 = sched.submit([3, 1], max_new_tokens=4)
    sched.run_until_done()
    m = sched.metrics()
    # raw-gap percentiles live ONLY under the _tick_burst suffix
    # (ISSUE 10: the bare itl_p50/itl_p95 keys published a degenerate
    # 0.0 median under pipelined dispatch and were dropped)
    assert {"itl_p50_tick_burst", "itl_p95_tick_burst",
            "itl_max_tick_burst"} <= set(m)
    assert not {"itl_p50", "itl_p95", "itl_max"} & set(m)
    assert m["itl_p50_tick_burst"] >= 0
    assert m["itl_max_tick_burst"] >= m["itl_p50_tick_burst"]
    # gaps = (6-1) + (4-1)
    assert len(sched._itls) == (len(r1.output) - 1) + (len(r2.output) - 1)


def test_speculative_scheduler_greedy_parity():
    """VERDICT r4 item 7: scheduler-level speculative decoding — per-slot
    ngram drafts + one batched verify — is token-for-token identical to
    the plain scheduler, across slots with different prompts/lengths."""
    sched, params = make_sched(max_batch=4, max_seq=64,
                               speculative_gamma=3)
    ref, _ = make_sched(max_batch=4, max_seq=64)
    prompts = [[5, 7, 11], [3, 3, 3, 3, 3], [2], list(range(1, 9))]
    want = [ref.submit(p, max_new_tokens=12) for p in prompts]
    ref.run_until_done()
    got = [sched.submit(p, max_new_tokens=12) for p in prompts]
    sched.run_until_done()
    assert [r.output for r in got] == [r.output for r in want]
    assert sched.metrics()["spec_forwards_total"] > 0


def test_speculative_scheduler_accepts_drafts():
    """On a looping continuation (prompt seeded with the model's own
    greedy output), drafts must hit: fewer verify forwards than tokens."""
    ref, params = make_sched(max_batch=2, max_seq=128)
    r0 = ref.submit([5, 7, 11], max_new_tokens=24)
    ref.run_until_done()
    prompt = [5, 7, 11] + r0.output

    ref2, _ = make_sched(max_batch=2, max_seq=128)
    want = ref2.submit(prompt, max_new_tokens=16)
    ref2.run_until_done()

    sched, _ = make_sched(max_batch=2, max_seq=128, speculative_gamma=4)
    got = sched.submit(prompt, max_new_tokens=16)
    sched.run_until_done()
    assert got.output == want.output
    m = sched.metrics()
    assert m["spec_drafts_accepted_total"] > 0
    # >1 tokens per verify forward on the repetitive continuation
    assert m["tokens_generated_total"] > m["spec_forwards_total"]


def test_speculative_scheduler_sampling_supported():
    """The greedy-only guard is gone: temperature > 0 requests ride the
    spec block through the rejection-sampling correction — full budget
    generated, same-seed reproducible (distribution exactness is
    pinned in tests/test_spec_sampling.py)."""
    outs = []
    for _ in range(2):
        sched, _ = make_sched(max_batch=2, max_seq=64,
                              speculative_gamma=2, seed=7)
        r1 = sched.submit([5, 7], max_new_tokens=8, temperature=0.8)
        r2 = sched.submit([3, 1, 4], max_new_tokens=6)  # greedy slotmate
        sched.run_until_done()
        assert len(r1.output) == 8 and len(r2.output) == 6
        outs.append((r1.output, r2.output))
    assert outs[0] == outs[1]  # same scheduler seed -> same draws


def test_speculative_parity_grid():
    """Acceptance criterion: greedy spec-on output is byte-identical to
    spec-off greedy serving at decode_steps_per_tick 1 and 8, at
    dispatch-ahead depth 1 and 2."""
    prompts = [[5, 7, 11], [3, 3, 3, 3, 3], [2], list(range(1, 9))]
    ref, _ = make_sched(max_batch=4, max_seq=64)
    want = [ref.submit(p, max_new_tokens=12) for p in prompts]
    ref.run_until_done()
    for k in (1, 8):
        for depth in (1, 2):
            sched, _ = make_sched(max_batch=4, max_seq=64,
                                  speculative_gamma=3,
                                  decode_steps_per_tick=k,
                                  inflight_blocks=depth)
            got = [sched.submit(p, max_new_tokens=12) for p in prompts]
            sched.run_until_done()
            assert [r.output for r in got] == \
                [r.output for r in want], (k, depth)


def test_speculative_pipelines_without_per_round_barriers():
    """The old implementation drained EVERY spec round to draft on the
    host; the block path must keep spec rounds in flight: at depth 2 a
    steady-state run reaches inflight depth 2 and pays far fewer full
    barriers than verify rounds."""
    sched, _ = make_sched(max_batch=2, max_seq=128, speculative_gamma=3,
                          inflight_blocks=2)
    reqs = [sched.submit([5, 7, 11], max_new_tokens=40),
            sched.submit([3, 1], max_new_tokens=40)]
    seen_depth = 0
    while sched.has_work:
        sched.tick()
        seen_depth = max(seen_depth, len(sched._inflight))
    assert all(r.state == "finished" for r in reqs)
    m = sched.metrics()
    assert seen_depth == 2  # spec blocks actually chained in flight
    assert m["spec_forwards_total"] > 0
    # membership changes (admission, finishes) barrier; steady-state
    # rounds must not — far fewer barriers than verify rounds
    assert m["drain_barriers_total"] < m["spec_forwards_total"] / 2
    assert m["spec_tokens_per_forward"] >= 1.0


def test_speculative_parity_under_preemption_pressure():
    """Spec mode + tiny page pool: preemption (drain, hist rebuild on
    readmission) must preserve exact greedy parity — the device-side
    history is reseeded from host truth at every (re)admission."""
    ref, params = make_sched(max_batch=2, max_seq=32, page=4, num_pages=6)
    w1 = ref.submit([5, 7, 11], max_new_tokens=10)
    w2 = ref.submit([2, 4], max_new_tokens=10)
    ref.run_until_done()
    sched, _ = make_sched(max_batch=2, max_seq=32, page=4, num_pages=6,
                          speculative_gamma=3)
    r1 = sched.submit([5, 7, 11], max_new_tokens=10)
    r2 = sched.submit([2, 4], max_new_tokens=10)
    sched.run_until_done()
    assert r1.output == w1.output
    assert r2.output == w2.output


def test_speculative_per_request_opt_out():
    """A request submitted with speculative=False rides the spec block
    but ignores drafts: its greedy output still matches the plain
    reference exactly (one exact sample per verify round)."""
    sched, params = make_sched(max_batch=2, max_seq=64,
                               speculative_gamma=3)
    r1 = sched.submit([5, 7, 11], max_new_tokens=10, speculative=False)
    r2 = sched.submit([3, 1], max_new_tokens=8)
    sched.run_until_done()
    assert r1.output == ref_tokens(params, [5, 7, 11], 10)
    assert r2.output == ref_tokens(params, [3, 1], 8)


def test_speculative_scheduler_stop_token():
    ref, _ = make_sched(max_batch=2, max_seq=64)
    base = ref.submit([5, 7, 11], max_new_tokens=12)
    ref.run_until_done()
    stop = base.output[6]
    ref2, _ = make_sched(max_batch=2, max_seq=64)
    want = ref2.submit([5, 7, 11], max_new_tokens=12, stop_token=stop)
    ref2.run_until_done()
    sched, _ = make_sched(max_batch=2, max_seq=64, speculative_gamma=3)
    got = sched.submit([5, 7, 11], max_new_tokens=12, stop_token=stop)
    sched.run_until_done()
    assert got.output == want.output


# -- fused decode block (engine._decode_scan, ISSUE 3) ----------------------


def test_fused_block_greedy_parity():
    """Tentpole contract: decode_steps_per_tick=8 — one jitted scan per
    tick with on-device RNG/EOS/budget masking — is token-for-token
    identical to single-step decode at temperature 0, across slots with
    different prompts and lengths (the sched/serving_mesh parity
    contract extended to the fused block)."""
    ref, params = make_sched(max_batch=4, max_seq=64)
    fused, _ = make_sched(max_batch=4, max_seq=64, decode_steps_per_tick=8)
    prompts = [[5, 7, 11], [3, 3, 3, 3, 3], [2], list(range(1, 9))]
    want = [ref.submit(p, max_new_tokens=12) for p in prompts]
    ref.run_until_done()
    got = [fused.submit(p, max_new_tokens=12) for p in prompts]
    fused.run_until_done()
    assert [r.output for r in got] == [r.output for r in want]
    # and the single-step path itself still matches the offline engine
    assert want[0].output == ref_tokens(params, prompts[0], 12)


def test_fused_block_seeded_sampling_reproducible():
    """temperature>0 through the fused block: per-step keys are derived
    on device (fold_in of one per-block key), so the same seed and
    config must reproduce the same tokens run-to-run."""
    outs = []
    for _ in range(2):
        sched, _ = make_sched(max_batch=2, max_seq=64, seed=7,
                              decode_steps_per_tick=4)
        r1 = sched.submit([5, 7, 11], max_new_tokens=10, temperature=0.8)
        r2 = sched.submit([3, 1], max_new_tokens=8, temperature=1.3)
        sched.run_until_done()
        outs.append((list(r1.output), list(r2.output)))
    assert outs[0] == outs[1]
    assert len(outs[0][0]) == 10 and len(outs[0][1]) == 8


def test_fused_block_eos_mid_block():
    """A stop token sampled mid-block kills the slot ON DEVICE: the host
    sees no post-EOS tokens, and the slot's device length froze at the
    written-token count (no post-EOS page growth) instead of advancing
    through the remaining scan steps."""
    ref, _ = make_sched(max_batch=2, max_seq=64)
    base = ref.submit([5, 7, 11], max_new_tokens=12)
    ref.run_until_done()
    stop = base.output[2]  # EOS lands at the 3rd generated token

    sched, _ = make_sched(max_batch=2, max_seq=64, decode_steps_per_tick=8)
    req = sched.submit([5, 7, 11], max_new_tokens=12, stop_token=stop)
    sched.tick()  # admit + prefill + first sample + one 8-step block
    slot = req.slot
    # The block has run past the EOS position on device. Written K/V:
    # 3 prompt tokens + generated tokens 1 and 2; the EOS (3rd) is
    # sampled but never consumed, and every later step was masked dead
    # — the device length count froze, writes landed on the null page
    # (window-off) or stayed unstaged (kv_write_combine: the flushed
    # pool length plus the staged window count is the same total).
    staged = 0
    if sched.engine._win_len is not None:
        staged = int(np.asarray(sched.engine._win_len)[slot])
    total = int(np.asarray(sched.engine.cache.lengths)[slot]) + staged
    assert total == 3 + 2
    sched.run_until_done()
    assert req.output == base.output[:3]
    assert req.state == "finished"
    assert sched.alloc.free_pages == sched.alloc.num_pages


# -- batched group prefill (engine.prefill_batch, ISSUE 4) ------------------


def test_batched_prefill_parity():
    """Tentpole contract: N requests gang-admitted and prefilled as ONE
    [B, Tbucket] dispatch produce token-for-token the same outputs as
    sequential single-slot prefill (prefill_max_batch=1) and as the
    offline reference, across members with different prompt lengths."""
    # alternating path: batched prefill dispatches only exist there
    # (mixed dispatch rides prompts inside the fused decode block)
    seq, params = make_sched(max_batch=4, max_seq=64, prefill_max_batch=1,
                             mixed_dispatch=False)
    gang, _ = make_sched(max_batch=4, max_seq=64, prefill_max_batch=4,
                         mixed_dispatch=False)
    prompts = [[5, 7, 11], [3, 3, 3, 3, 3], [2], list(range(1, 9))]
    want = [seq.submit(p, max_new_tokens=10) for p in prompts]
    seq.run_until_done()
    got = [gang.submit(p, max_new_tokens=10) for p in prompts]
    gang.run_until_done()
    assert [r.output for r in got] == [r.output for r in want]
    assert want[0].output == ref_tokens(params, prompts[0], 10)
    # the gang really was ONE dispatch of 4 (all chunks share the
    # 16-token bucket); the sequential control was 4 dispatches of 1
    h = gang.registry.get("prefill_batch_size")
    assert h.count == 1 and h.sum == 4
    h = seq.registry.get("prefill_batch_size")
    assert h.count == 4 and h.sum == 4


def test_gang_admission_single_tick():
    """A burst of waiting requests is admitted AND fully prefilled in
    one tick when budget and slots allow — the gang property that cuts
    burst TTFT (previously: one [1, T] dispatch per prompt)."""
    sched, _ = make_sched(max_batch=4, prefill_max_batch=4,
                          mixed_dispatch=False)
    reqs = [sched.submit([i + 1, i + 2], max_new_tokens=4)
            for i in range(4)]
    sched.tick()
    assert all(r.state == "running" for r in reqs)
    assert sched.registry.get("prefill_batch_size").count == 1
    sched.run_until_done()
    assert all(r.state == "finished" for r in reqs)


def test_batched_prefill_budget_and_carry():
    """A gang whose chunk demand exceeds prefill_chunk is budget-split:
    partially-prefilled members carry across ticks (mixing warm
    continuation chunks with fresh admissions in later rounds) and every
    member still matches the reference token-for-token."""
    gang, params = make_sched(max_batch=3, max_seq=64, prefill_max_batch=3,
                              prefill_chunk=8)
    prompts = [list(range(2, 14)), list(range(3, 9)), [4, 2]]
    got = [gang.submit(p, max_new_tokens=5) for p in prompts]
    gang.run_until_done()
    for p, r in zip(prompts, got):
        assert r.output == ref_tokens(params, p, 5)


def test_mixed_warm_cold_group_admission():
    """A gang containing a prefix-cache-warm member (start > 0) and a
    cold member (start == 0): with warm-prefix flash (the default) the
    mixed gang rides the warm program together — freshness no longer
    splits it (ISSUE 13) — and with prefill_flash_warm=False the seed
    behavior returns (separate freshness buckets, so a warm member
    never drags cold members off the flash path). Both members match
    their references either way."""
    for warm_flash in (True, False):
        sched, params = make_sched(max_batch=4, max_seq=64, page=8,
                                   prefix_caching=True, prefill_max_batch=4,
                                   prefill_flash_warm=warm_flash,
                                   mixed_dispatch=False)
        shared = list(range(1, 17))  # two full pages
        r0 = sched.submit(shared + [5], max_new_tokens=4)
        sched.run_until_done()
        n0 = sched.registry.get("prefill_batch_size").count
        rw = sched.submit(shared + [9], max_new_tokens=6)  # warm: prefix hit
        rc = sched.submit([7, 3, 2], max_new_tokens=6)     # cold
        sched.tick()
        assert rw.cached_at_admit == 16 and rc.cached_at_admit == 0
        # chunk lengths share the 16-token bucket, so the dispatch count
        # pins the gang-freshness rule directly: merged = ONE dispatch,
        # split (the seed rule) = one per freshness flavor
        n_disp = sched.registry.get("prefill_batch_size").count - n0
        assert n_disp == (1 if warm_flash else 2)
        sched.run_until_done()
        assert rw.output == ref_tokens(params, shared + [9], 6)
        assert rc.output == ref_tokens(params, [7, 3, 2], 6)


def test_preempt_partially_prefilled_group_member():
    """Page pressure can evict a gang member that is only partially
    prefilled: pages free, it requeues (prefilled reset), leaves the
    group, and still completes correctly after readmission."""
    sched, params = make_sched(max_batch=2, max_seq=64, prefill_chunk=4)
    r1 = sched.submit([5, 7, 11], max_new_tokens=8)
    sched.tick()
    sched.tick()
    long_prompt = list(range(1, 17))
    r2 = sched.submit(long_prompt, max_new_tokens=4)
    sched.tick()
    assert r2.state == "prefilling" and 0 < r2.prefilled < 16
    assert r2 in sched._prefill_group
    sched._preempt(r2)  # what _ensure_or_preempt does to the youngest
    assert r2.state == "waiting" and r2.slot is None and r2.prefilled == 0
    assert r2 not in sched._prefill_group
    sched.run_until_done()
    assert r1.output == ref_tokens(params, [5, 7, 11], 8)
    assert r2.output == ref_tokens(params, long_prompt, 4)
    assert sched.metrics()["preemptions_total"] == 1
    assert sched.alloc.free_pages == sched.alloc.num_pages


def test_prefill_group_member_is_preemption_victim():
    """_ensure_or_preempt's victim pool includes mid-prefill gang
    members: the youngest live request loses page pressure even if it
    is still prefilling (it cannot starve an older decoding request)."""
    sched, params = make_sched(max_batch=2, max_seq=32, page=4, num_pages=6,
                               prefill_chunk=4, mixed_dispatch=False)
    r1 = sched.submit([5, 7, 11], max_new_tokens=12)
    sched.tick()
    sched.tick()
    # r2's admission takes 4 of the 6 pages and holds them across
    # several prefill ticks; r1's decode growth must be able to evict it
    r2 = sched.submit(list(range(1, 13)), max_new_tokens=4)
    sched.run_until_done(max_ticks=300)
    assert r1.state == "finished" and r2.state == "finished"
    assert sched.metrics()["preemptions_total"] > 0
    assert r1.output == ref_tokens(params, [5, 7, 11], 12)
    assert r2.output == ref_tokens(params, list(range(1, 13)), 4)


def test_pending_first_set_tracks_drain():
    """The (id, preemptions)-keyed index over undrained first tokens is
    populated at admission and refreshed (cleared) at drain time — the
    budget computation reads it instead of scanning the pending list."""
    # alternating path: _pending_first only exists there (mixed
    # dispatch samples completion first tokens inside the fused block)
    sched, _ = make_sched(inflight_blocks=1, mixed_dispatch=False)
    req = sched.submit([5, 7, 11], max_new_tokens=4)
    sched.tick()
    assert (req.id, req.preemptions) in sched._pending_first_keys
    assert len(sched._pending_first) == 1
    sched.tick()  # stacked drain consumed the first token
    assert not sched._pending_first_keys
    assert not sched._pending_first
    sched.run_until_done()


# -- pipelined dispatch-ahead serving (ISSUE 5) -----------------------------


def test_pipelined_greedy_parity_vs_synchronous():
    """Tentpole contract: inflight_blocks=2 (dispatch-ahead — block t+1
    chained on block t's device carry before t is drained) is token-
    for-token identical to the synchronous inflight_blocks=1 loop at
    temperature 0, across slots with different prompts and lengths."""
    sync, params = make_sched(max_batch=4, max_seq=64, inflight_blocks=1)
    pipe, _ = make_sched(max_batch=4, max_seq=64, inflight_blocks=2)
    prompts = [[5, 7, 11], [3, 3, 3, 3, 3], [2], list(range(1, 9))]
    want = [sync.submit(p, max_new_tokens=12) for p in prompts]
    sync.run_until_done()
    got = [pipe.submit(p, max_new_tokens=12) for p in prompts]
    pipe.run_until_done()
    assert [r.output for r in got] == [r.output for r in want]
    # and the synchronous path itself still matches the offline engine
    assert want[0].output == ref_tokens(params, prompts[0], 12)


def test_pipelined_greedy_parity_fused_k8():
    """Dispatch-ahead composed with the fused block: two k=8 scans in
    flight produce exactly the synchronous path's tokens."""
    sync, _ = make_sched(max_batch=4, max_seq=64, inflight_blocks=1,
                         decode_steps_per_tick=8)
    pipe, _ = make_sched(max_batch=4, max_seq=64, inflight_blocks=2,
                         decode_steps_per_tick=8)
    prompts = [[5, 7, 11], [3, 3, 3, 3, 3], [2], list(range(1, 9))]
    want = [sync.submit(p, max_new_tokens=20) for p in prompts]
    sync.run_until_done()
    got = [pipe.submit(p, max_new_tokens=20) for p in prompts]
    pipe.run_until_done()
    assert [r.output for r in got] == [r.output for r in want]


def test_pipelined_lazy_drain_cadence():
    """Steady state at inflight_blocks=2: block t+1 is dispatched while
    block t is still undrained; the host fetches only once the queue is
    full (the dispatch-ahead overlap, made visible by token timing)."""
    sched, params = make_sched(decode_steps_per_tick=2, inflight_blocks=2,
                               mixed_dispatch=False)
    req = sched.submit([5, 7, 11], max_new_tokens=12)
    sched.tick()  # admit + first token (pending) + dispatch block 1
    assert len(req.output) == 0 and len(sched._inflight) == 1
    sched.tick()  # queue not full: block 2 chains, still nothing drained
    assert len(req.output) == 0 and len(sched._inflight) == 2
    sched.tick()  # queue full: drain first + block 1, dispatch block 3
    assert len(req.output) == 3
    assert sched.metrics()["inflight_depth"] == 2
    sched.run_until_done()
    assert req.output == ref_tokens(params, [5, 7, 11], 12)


def test_pipelined_admission_forces_drain_barrier():
    """A waiter with a free slot forces a FULL drain barrier before
    admission: every in-flight block reconciles, then the gang admits
    in the same tick."""
    # alternating path: the admission barrier class this documents is
    # exactly what mixed dispatch (the default) retires
    sched, params = make_sched(max_batch=2, inflight_blocks=2,
                               mixed_dispatch=False)
    r1 = sched.submit([5, 7, 11], max_new_tokens=16)
    sched.tick()
    sched.tick()
    assert len(sched._inflight) == 2 and len(r1.output) == 0
    r2 = sched.submit([3, 1], max_new_tokens=6)
    sched.tick()
    assert r2.state == "running"      # admitted this very tick
    assert len(r1.output) >= 3        # the barrier drained everything
    assert len(sched._inflight) == 1  # only the fresh block remains
    sched.run_until_done()
    assert r1.output == ref_tokens(params, [5, 7, 11], 16)
    assert r2.output == ref_tokens(params, [3, 1], 6)


def test_pipelined_cancel_discards_stale_blocks():
    """cancel() mid-pipeline: a full drain barrier runs first (pages
    with outstanding device writes are never reclaimed), the cancelled
    request gains no tokens afterwards, and the surviving request still
    matches its reference."""
    sched, params = make_sched(max_batch=2, inflight_blocks=2)
    r1 = sched.submit([5, 7, 11], max_new_tokens=30)
    r2 = sched.submit([3, 1], max_new_tokens=8)
    sched.tick()
    sched.tick()
    assert len(sched._inflight) == 2
    sched.cancel(r1)
    assert r1.state == "cancelled" and r1.slot is None
    assert not sched._inflight  # the barrier consumed every block
    n_after = len(r1.output)
    sched.run_until_done()
    assert len(r1.output) == n_after  # no tokens post-cancel
    assert r2.output == ref_tokens(params, [3, 1], 8)
    assert sched.alloc.free_pages == sched.alloc.num_pages


def test_page_pressure_drains_before_preempting():
    """_ensure_or_preempt under pressure with blocks in flight: the
    FULL drain barrier runs before any victim is chosen — preemption
    must never reclaim pages a dispatched block still writes to."""
    sched, _ = make_sched(max_batch=2, max_seq=32, page=4, num_pages=6,
                          inflight_blocks=2, mixed_dispatch=False)
    r1 = sched.submit([5, 7, 11], max_new_tokens=20)
    r2 = sched.submit([3, 1], max_new_tokens=20)
    sched.tick()
    sched.tick()
    assert sched._inflight
    # the whole pool for r1: cannot fit beside r2 -> barrier, then the
    # youngest (r2) is preempted
    sched._ensure_or_preempt(r1, 24)
    assert not sched._inflight
    assert r2.state == "waiting" and r2.preemptions == 1


def test_pipelined_parity_under_page_pressure():
    """Tiny pool at inflight_blocks=2: the widened (inflight+1)*k+1
    preallocation horizon falls back to drain barriers and recompute
    preemption under pressure, and both requests still match their
    references token-for-token."""
    sched, params = make_sched(max_batch=2, max_seq=32, page=4, num_pages=6,
                               inflight_blocks=2, decode_steps_per_tick=2)
    r1 = sched.submit([5, 7, 11], max_new_tokens=10)
    r2 = sched.submit([3, 1], max_new_tokens=10)
    sched.run_until_done(max_ticks=500)
    assert r1.state == "finished" and r2.state == "finished"
    assert sched.metrics()["preemptions_total"] > 0
    assert r1.output == ref_tokens(params, [5, 7, 11], 10)
    assert r2.output == ref_tokens(params, [3, 1], 10)


def test_pipelined_metrics_surface():
    """The dispatch-ahead observability contract: inflight_depth gauge
    and device_bubble_seconds histogram/percentiles populate once
    blocks pipeline."""
    sched, _ = make_sched(inflight_blocks=2)
    sched.submit([5, 7, 11], max_new_tokens=8)
    sched.run_until_done()
    m = sched.metrics()
    assert "inflight_depth" in m
    assert m.get("device_bubble_p50", 0.0) >= 0.0
    assert sched.registry.get("device_bubble_seconds").count >= 1
    assert sched.registry.get("inflight_depth") is not None


# -- tracing + instrument wiring (obs/trace.py, obs/registry.py) ------------

def test_scheduler_trace_timeline():
    """Every phase of a request's life shows up as span events with
    monotonic timestamps; disabled tracing (the default) records
    nothing and leaves sched.trace None."""
    from butterfly_tpu.obs.trace import Tracer
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(42))
    # alternating path: prefill_chunk trace events only exist there
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=64, page_size=8,
                       mixed_dispatch=False)
    tr = Tracer()
    sched = Scheduler(ServingEngine(model, params, rt), tracer=tr)
    req = sched.submit([5, 7, 11], max_new_tokens=4,
                       request_id="trace-me")
    sched.run_until_done()
    tl = tr.timeline(req.id)
    assert tl["request_id"] == "trace-me"
    names = [e["name"] for e in tl["events"]]
    for needed in ("submit", "admit", "prefill_chunk", "prefill_done",
                   "first_token", "finish"):
        assert needed in names
    ts = [e["t"] for e in tl["events"]]
    assert ts == sorted(ts)
    fin = tl["events"][-1]
    assert fin["name"] == "finish" and fin["tokens"] == 4
    # the global ring saw decode ticks and engine dispatches
    globs = [e["name"] for e in tr.global_events()]
    assert "decode_tick" in globs
    assert "engine.prefill_dispatch" in globs

    plain, _ = make_sched()
    assert plain.trace is None  # default: no tracer, bare None check


def test_scheduler_trace_preemption_events():
    from butterfly_tpu.obs.trace import Tracer
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(42))
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=32, page_size=4,
                       num_pages=6)
    tr = Tracer()
    sched = Scheduler(ServingEngine(model, params, rt), tracer=tr)
    r1 = sched.submit([5, 7, 11], max_new_tokens=10)
    r2 = sched.submit([3, 1], max_new_tokens=10)
    sched.run_until_done(max_ticks=400)
    assert sched.metrics()["preemptions_total"] > 0
    preempted = r1 if r1.preemptions else r2
    names = [e["name"] for e in tr.timeline(preempted.id)["events"]]
    assert "preempt" in names
    # readmission after the preempt is traced as a resumed admit
    i = names.index("preempt")
    admits = [e for e in tr.timeline(preempted.id)["events"][i:]
              if e["name"] == "admit"]
    assert admits and admits[0]["resumed"] is True


def test_registry_histograms_observe_through_scheduler():
    sched, _ = make_sched()
    sched.submit([1, 2, 3], max_new_tokens=3)
    sched.submit([4, 5], max_new_tokens=3)
    sched.run_until_done()
    reg = sched.registry
    assert reg.get("ttft_seconds").count == 2
    assert reg.get("queue_wait_seconds").count == 2
    assert reg.get("prefill_tokens").count == 2
    assert reg.get("itl_req_mean_seconds").count == 2
    assert reg.get("batch_size").count >= 1
    assert reg.get("requests_total").value == 2
    # legacy dict view still mirrors the registry counters
    m = sched.metrics()
    assert m["requests_total"] == 2 and m["requests_finished"] == 2


def test_written_counts_undrained_first_token():
    """ADVICE.md r5 off-by-one: after prefill sampled the first token
    on-device but before the stacked drain, every prompt token's K/V is
    written — _written must not subtract one (it loses a page of
    prefix-cache registration at page boundaries)."""
    sched, _ = make_sched(max_batch=2, max_seq=64, page=8,
                          inflight_blocks=1,  # per-tick drain cadence
                          mixed_dispatch=False)  # alternating cadence
    req = sched.submit([1] * 8, max_new_tokens=4)  # exactly one page
    sched.tick()  # admit + prefill + on-device first sample (undrained)
    assert req.state == "running" and req.output == []
    assert any(f[0] is req for f in sched._pending_first)
    assert sched._written(req) == 8  # the whole prompt, no -1
    sched.tick()  # drain: first token lands on the host
    assert len(req.output) >= 1
    # once drained, the last sampled token's K/V is indeed unwritten
    assert sched._written(req) == len(req.all_tokens) - 1
    sched.run_until_done()


# ---------------------------------------------------------------------------
# overload protection (ISSUE 8): deadlines, SLO-aware shedding, priorities
# ---------------------------------------------------------------------------

def test_deadline_expired_while_waiting():
    """An already-expired waiter is scrubbed from the queue at the next
    tick: no slot, no prefill, state 'expired', counted under
    where='waiting', and its on_finish waiter is answered."""
    import time
    sched, _ = make_sched()
    fired = []
    live = sched.submit([1, 2], max_new_tokens=2)
    dead = sched.submit([3, 4], max_new_tokens=2,
                        deadline_s=time.monotonic() - 0.01,
                        on_finish=lambda r: fired.append(r.id))
    sched.run_until_done()
    assert dead.state == "expired" and dead.expired_where == "waiting"
    assert dead.slot is None and dead.output == []
    assert fired == [dead.id]
    assert live.state == "finished" and len(live.output) == 2
    m = sched.metrics()
    assert m["deadline_expired_total"] == 1
    assert 'butterfly_deadline_expired_total{where="waiting"} 1' \
        in sched.registry.render()


def test_deadline_expired_while_running():
    """The acceptance hazard: a deadline firing mid-generation must
    cancel the request out of its decode slot at the next drain
    barrier — it never consumes a decode dispatch after expiry — while
    a co-running request decodes on unharmed."""
    import time
    sched, params = make_sched(max_batch=2, mixed_dispatch=False)
    doomed = sched.submit([5, 7, 11], max_new_tokens=50)
    ok = sched.submit([3, 1], max_new_tokens=8)
    sched.tick()
    assert doomed.state == "running"
    doomed.deadline_s = time.monotonic() - 1e-3  # fires before next tick
    sched.tick()
    assert doomed.state == "expired" and doomed.expired_where == "running"
    assert doomed.slot is None
    n_at_expiry = len(doomed.output)
    sched.run_until_done()
    assert len(doomed.output) == n_at_expiry  # zero decode steps after
    assert ok.state == "finished"
    assert ok.output == ref_tokens(params, [3, 1], 8)
    assert sched.metrics()["deadline_expired_total"] == 1
    # the freed slot + pages are fully reclaimed
    assert sched.alloc.free_pages == sched.alloc.num_pages


def test_shed_batch_before_interactive():
    """SLO-aware admission sheds by priority class: with a predicted
    TTFT between the objective and interactive_slack x it, batch is
    turned away (429 + computed Retry-After) while interactive still
    admits. Without evidence or without a declared objective, nothing
    sheds."""
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(42))
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=64, page_size=8)
    engine = ServingEngine(model, params, rt)
    sched = Scheduler(engine, slo_ttft_s=0.5)
    # no latency evidence yet: a cold server never sheds blind
    assert sched.shed_decision(32, "batch") is None
    # seed the rolling ITL window + a queue: predict_ttft becomes
    # rounds * mean_itl with rounds = ceil(backlog/prefill_chunk)
    # + len(waiting)  ->  0.1 * (1 + 6) = 0.7s for a 32-token prompt
    sched._itl_means.extend([0.1] * 8)
    for _ in range(6):
        sched.submit([1] * 30, max_new_tokens=2)
    pred = sched.predict_ttft(32)
    assert 0.5 < pred <= 1.0, pred  # between slo and 2x slo
    retry = sched.shed_decision(32, "batch")
    assert retry is not None and retry >= 1.0
    assert sched.shed_decision(32, "interactive") is None
    m = sched.metrics()
    assert m["shed_total"] == 1
    assert 'butterfly_shed_total{priority="batch"} 1' \
        in sched.registry.render()
    # no declared objective -> the same pressure never sheds
    off = Scheduler(engine)
    off._itl_means.extend([0.1] * 8)
    for _ in range(6):
        off.submit([1] * 30, max_new_tokens=2)
    assert off.shed_decision(32, "batch") is None
    sched.run_until_done()
    off.run_until_done()


def test_preempt_prefers_batch_victim():
    """Under page pressure the preemption victim is batch-first, then
    youngest: an OLDER batch request recomputes so a younger
    interactive one keeps its pages (both still finish correctly)."""
    sched, params = make_sched(max_batch=2, max_seq=32, page=4,
                               num_pages=6)
    batch = sched.submit([5, 7, 11], max_new_tokens=10, priority="batch")
    sched.tick()
    inter = sched.submit([3, 1], max_new_tokens=10)  # younger, interactive
    sched.run_until_done(max_ticks=300)
    assert batch.state == "finished" and inter.state == "finished"
    assert batch.preemptions > 0       # older but batch: the victim
    assert inter.preemptions == 0
    assert batch.output == ref_tokens(params, [5, 7, 11], 10)
    assert inter.output == ref_tokens(params, [3, 1], 10)


def test_submit_rejects_unknown_priority():
    import pytest
    sched, _ = make_sched()
    with pytest.raises(ValueError, match="priority"):
        sched.submit([1], max_new_tokens=2, priority="best-effort")


# -- write-combined KV decode window (ISSUE 12) -----------------------------


def test_kv_window_off_matches_on():
    """Core on/off contract: kv_write_combine stages K/V in the window
    and flushes once per drain, yet greedy outputs are byte-identical
    to the per-token write path — and only the window mode populates
    the flush instruments."""
    prompts = [[5, 7, 11], [3, 1]]
    # alternating path: the flushed-token arithmetic below assumes
    # prompts land via dedicated prefill scatters (under mixed dispatch
    # prompt K/V stages through the window too; parity twins in
    # test_mixed_dispatch.py)
    on, _ = make_sched(max_batch=2, mixed_dispatch=False)
    off, _ = make_sched(max_batch=2, kv_write_combine=False,
                        mixed_dispatch=False)
    a = [on.submit(p, max_new_tokens=10) for p in prompts]
    b = [off.submit(p, max_new_tokens=10) for p in prompts]
    on.run_until_done()
    off.run_until_done()
    assert [r.output for r in a] == [r.output for r in b]
    m_on, m_off = on.metrics(), off.metrics()
    assert m_on["kv_window_tokens_flushed_total"] > 0
    assert "kv_flush_p50" in m_on and "kv_flush_p95" in m_on
    assert "kv_window_tokens_flushed_total" not in m_off
    # every generated-and-consumed token was flushed exactly once; the
    # final sampled token of each request is never written (decode
    # contract), so flushed == generated - one per finished request
    assert m_on["kv_window_tokens_flushed_total"] == \
        m_on["tokens_generated_total"] - len(prompts)


def test_kv_window_greedy_parity_grid():
    """Acceptance grid: window on/off x decode_steps_per_tick 1/8 x
    dispatch-ahead depth 1/2, all byte-identical to the contiguous
    reference."""
    prompts = [[5, 7, 11], [3, 3, 3, 3, 3], [2]]
    ref, _ = make_sched(max_batch=4)
    want = [ref.submit(p, max_new_tokens=12) for p in prompts]
    ref.run_until_done()
    for wc in (True, False):
        for k in (1, 8):
            for depth in (1, 2):
                sched, _ = make_sched(max_batch=4, kv_write_combine=wc,
                                      decode_steps_per_tick=k,
                                      inflight_blocks=depth)
                got = [sched.submit(p, max_new_tokens=12) for p in prompts]
                sched.run_until_done()
                assert [r.output for r in got] == \
                    [r.output for r in want], (wc, k, depth)


def test_kv_window_seeded_sampling_parity():
    """temperature > 0 with a pinned scheduler seed: the windowed path
    derives the same per-step fold_in keys from the same block
    dispatches, so sampled streams match window-off exactly."""
    for k in (1, 8):
        outs = {}
        for wc in (True, False):
            sched, _ = make_sched(max_batch=2, seed=7, kv_write_combine=wc,
                                  decode_steps_per_tick=k)
            r1 = sched.submit([5, 7, 11], max_new_tokens=10,
                              temperature=0.8)
            r2 = sched.submit([3, 1], max_new_tokens=10, temperature=1.3)
            sched.run_until_done()
            outs[wc] = (r1.output, r2.output)
        assert outs[True] == outs[False], k


def test_kv_window_spec_parity_grid():
    """Speculative serving window on/off x rounds-per-tick 1/8: the
    window's accepted-count advance is the exact analogue of the spec
    scan's cache-length rollback, byte-identical greedy output."""
    prompts = [[5, 7, 11], [3, 1]]
    ref, _ = make_sched(max_batch=2)
    want = [ref.submit(p, max_new_tokens=12) for p in prompts]
    ref.run_until_done()
    for wc in (True, False):
        for k in (1, 8):
            sched, _ = make_sched(max_batch=2, speculative_gamma=3,
                                  kv_write_combine=wc,
                                  decode_steps_per_tick=k)
            got = [sched.submit(p, max_new_tokens=12) for p in prompts]
            sched.run_until_done()
            assert [r.output for r in got] == \
                [r.output for r in want], (wc, k)


def test_kv_window_preempt_mid_block_flush_before_reclaim():
    """Preemption under page pressure with staged window entries: the
    drain barrier's flush lands every staged K/V byte in the pool
    BEFORE any victim page is reclaimed, so recompute-preempted and
    surviving requests both stay byte-correct and the flush counter
    advances."""
    sched, params = make_sched(max_batch=2, max_seq=32, page=4,
                               num_pages=6, inflight_blocks=2,
                               decode_steps_per_tick=2)
    r1 = sched.submit([5, 7, 11], max_new_tokens=10)
    r2 = sched.submit([3, 1], max_new_tokens=10)
    sched.run_until_done(max_ticks=500)
    m = sched.metrics()
    assert m["preemptions_total"] > 0
    assert m["kv_window_tokens_flushed_total"] > 0
    assert not sched.engine._win_dirty
    assert r1.output == ref_tokens(params, [5, 7, 11], 10)
    assert r2.output == ref_tokens(params, [3, 1], 10)


def test_kv_window_cancel_mid_block_flush_before_reclaim():
    """cancel() with blocks in flight and staged-but-unflushed window
    entries: the drain barrier flushes before the cancelled request's
    pages are reclaimed, and a follow-up request that reuses the slot
    and pages still matches its reference (a dropped or stale flush
    would scatter old K/V into the readmitted pages)."""
    sched, params = make_sched(max_batch=2, inflight_blocks=2)
    r1 = sched.submit([5, 7, 11], max_new_tokens=30)
    r2 = sched.submit([3, 1], max_new_tokens=8)
    sched.tick()
    sched.tick()
    assert sched._inflight  # blocks (and staged K/V) in flight
    sched.cancel(r1)
    assert r1.state == "cancelled" and r1.slot is None
    assert not sched.engine._win_dirty  # the barrier flushed, not leaked
    r3 = sched.submit([2, 4, 6], max_new_tokens=8)
    sched.run_until_done()
    assert r2.output == ref_tokens(params, [3, 1], 8)
    assert r3.output == ref_tokens(params, [2, 4, 6], 8)
    assert sched.alloc.free_pages == sched.alloc.num_pages


def test_kv_window_spec_rejection_never_flushed():
    """The rollback-by-construction contract: a rejected draft's K/V
    sits past win_len and is NEVER flushed, so pool bytes beyond each
    slot's flushed length stay pristine (init zeros). Window-off writes
    all gamma+1 verify positions into the pool and relies on the
    rollback + write-then-attend rewrite argument — its pool DOES carry
    stale bytes past the written length, which is the discriminator
    this test pins."""
    import jax.numpy as jnp

    def stale_bytes(sched, slot):
        """Max |pool byte| past the slot's flushed length."""
        cache = sched.engine.cache
        kp = np.asarray(cache.k_pages)          # [L, P, Kv, page, H]
        page = kp.shape[3]
        length = int(np.asarray(cache.lengths)[slot])
        pids = sched.alloc.pages_of(slot)
        worst = 0.0
        for j, pid in enumerate(pids):
            lo = max(0, length - j * page)      # valid offsets in page j
            if lo < page:
                worst = max(worst,
                            float(np.abs(kp[:, pid, :, lo:, :]).max()))
        return worst

    runs = {}
    for wc in (True, False):
        sched, _ = make_sched(max_batch=1, max_seq=64,
                              speculative_gamma=3, kv_write_combine=wc)
        req = sched.submit([5, 7, 5, 7, 5], max_new_tokens=40)
        for _ in range(4):
            sched.tick()
        sched._drain_inflight()  # flush + surface everything dispatched
        assert not req.done      # still mid-generation: pages live
        assert sched.metrics()["spec_forwards_total"] > 0
        runs[wc] = stale_bytes(sched, req.slot)
    assert runs[True] == 0.0    # windowed pool: no stale spec bytes
    assert runs[False] > 0.0    # per-token path: rollback leaves them


# ---------------------------------------------------------------------------
# tick anatomy: per-phase attribution + barrier-cause accounting (ISSUE 15)
# ---------------------------------------------------------------------------

def test_tick_anatomy_ring_and_phase_reconciliation():
    """Every tick lands one record in the timeline ring: monotonic
    seq, the phase vocabulary, and phase sums reconciling with tick
    wall time (the 'other' residual makes the accounting explicit).
    The admission barrier-cause fires when a waiter admits while
    blocks are in flight."""
    from butterfly_tpu.obs.ticklog import TICK_PHASES

    # alternating path: the admission barrier-cause assertion below is
    # the behavior mixed dispatch (the default) retires
    sched, params = make_sched(max_batch=2, mixed_dispatch=False)
    r1 = sched.submit([5, 7, 11], max_new_tokens=12)
    for _ in range(3):
        sched.tick()  # fill the dispatch-ahead pipeline
    r2 = sched.submit([3, 1], max_new_tokens=4)  # free slot + inflight
    sched.run_until_done()
    assert r1.state == r2.state == "finished"

    dump = sched.ticklog.dump()
    ticks = dump["ticks"]
    assert ticks and dump["next_seq"] >= len(ticks)
    seqs = [t["seq"] for t in ticks]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    for t in ticks:
        assert set(t["phases"]) == set(TICK_PHASES)
        total = sum(t["phases"].values())
        # phase sums account for the tick wall (+-10%)
        assert abs(total - t["wall_s"]) <= 0.1 * t["wall_s"] + 1e-6
        assert 0.0 <= t["fetch_s"] <= t["wall_s"] + 1e-9
        assert t["pages_free"] >= 0 and t["inflight"] >= 0

    m = sched.metrics()
    for k in ("tick_phase_drain_p50", "tick_phase_drain_p95",
              "tick_phase_admit_p50", "tick_phase_dispatch_p95",
              "tick_phase_dominant_p95"):
        assert k in m, k
    assert m["tick_host_frac"] + m["tick_device_frac"] == \
        __import__("pytest").approx(1.0)
    assert 0.0 < m["tick_host_frac"] < 1.0
    assert m["tick_device_frac"] > 0.0  # the stacked fetch is real

    causes = sched.barrier_causes()
    assert causes.get("admission", 0) >= 1  # r2 admitted mid-pipeline
    assert causes.get("finish", 0) >= 1
    # compat: the unlabeled sum is preserved and equals the breakdown
    assert m["drain_barriers_total"] == sum(causes.values())
    # the per-tick records carry the same causes the family counted
    ring_causes = [c for t in ticks for c in t["barrier_causes"]]
    assert ring_causes.count("admission") == causes["admission"]


def test_barrier_causes_page_pressure_and_cancel():
    """The page_pressure cause fires when _ensure_or_preempt drains
    before preempting (tiny pool, the existing pressure scenario); the
    cancel cause when cancel() drains in-flight blocks."""
    sched, params = make_sched(max_batch=2, max_seq=32, page=4,
                               num_pages=6)
    r1 = sched.submit([5, 7, 11], max_new_tokens=10)
    r2 = sched.submit([2, 4], max_new_tokens=10)
    sched.run_until_done()
    m = sched.metrics()
    causes = sched.barrier_causes()
    assert m["preemptions_total"] >= 1
    assert causes.get("page_pressure", 0) >= 1

    r3 = sched.submit([9, 9, 9], max_new_tokens=12)
    for _ in range(2):
        sched.tick()
    assert sched._inflight  # blocks genuinely in flight
    sched.cancel(r3)
    assert r3.state == "cancelled"
    assert sched.barrier_causes().get("cancel", 0) >= 1


def test_flight_recorder_preempt_storm_dump_on_scheduler():
    """End-to-end anomaly path: a page-pressure preemption storm on a
    live scheduler trips the recorder and freezes a schema-valid
    post-mortem carrying the admission/preempt/barrier event trail."""
    from butterfly_tpu.obs.ticklog import FLIGHTREC_SCHEMA, FlightRecorder

    fr = FlightRecorder(preempt_storm=1)
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(42))
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=32, page_size=4,
                       num_pages=6)
    sched = Scheduler(ServingEngine(model, params, rt), flightrec=fr)
    r1 = sched.submit([5, 7, 11], max_new_tokens=10)
    r2 = sched.submit([2, 4], max_new_tokens=10)
    sched.run_until_done()
    assert sched.metrics()["preemptions_total"] >= 1
    dumps = list(fr.dumps)
    assert dumps, "preemption storm must have tripped the recorder"
    art = dumps[0]
    assert art["schema"] == FLIGHTREC_SCHEMA
    assert art["reason"] == "preempt_storm"
    kinds = {e["kind"] for e in art["events"]}
    assert "preempt" in kinds and "admit" in kinds and "barrier" in kinds
    assert art["signals"]["preemptions_total"] >= 1
    import json as _json
    _json.dumps(art)  # artifact must be JSON-serializable


# -- token-tree speculation (ISSUE 19) --------------------------------------


def test_tree_speculative_parity_grid():
    """Acceptance criterion: greedy TREE speculation (width-2, node
    budget gamma+1 — equal verify FLOPs vs the linear chain) is
    byte-identical to spec-off greedy serving across rounds-per-tick x
    dispatch-ahead depth x KV-window on/off. Every emitted token lies
    on the realized argmax path, the tree-attention mask keeps sibling
    branches invisible to each other, and the accepted-path KV
    compaction leaves the cache indistinguishable from plain decode —
    any cross-branch leak or mis-permuted K/V diverges within a few
    tokens (tools/mutcheck.py mutates exactly that mask against this
    grid). max_new=11 lands mid-round, covering budget-tail clamping."""
    prompts = [[5, 7, 11], [3, 3, 3, 3, 3], [2], list(range(1, 9))]
    ref, _ = make_sched(max_batch=4, max_seq=64)
    want = [ref.submit(p, max_new_tokens=11) for p in prompts]
    ref.run_until_done()
    for k in (1, 4):
        for depth in (1, 2):
            for wc in (False, True):
                sched, _ = make_sched(max_batch=4, max_seq=64,
                                      speculative_gamma=4,
                                      draft_model="model",
                                      draft_layers=1,
                                      spec_tree_width=2,
                                      kv_write_combine=wc,
                                      decode_steps_per_tick=k,
                                      inflight_blocks=depth)
                assert sched.engine.spec_tree_mode
                assert sched.engine.spec_tree_geometry == (2, 5)
                got = [sched.submit(p, max_new_tokens=11) for p in prompts]
                sched.run_until_done()
                assert [r.output for r in got] == \
                    [r.output for r in want], (k, depth, wc)


def test_tree_speculative_opt_out_and_stop_token():
    """Tree-mode slotmates: a speculative=False request rides the tree
    block but emits exact plain-decode tokens (one per round), and a
    stop token truncates a tree emission mid-path without leaking
    post-stop tokens."""
    ref, params = make_sched(max_batch=2, max_seq=64)
    base = ref.submit([5, 7, 11], max_new_tokens=12)
    ref.run_until_done()
    stop = base.output[6]
    ref2, _ = make_sched(max_batch=2, max_seq=64)
    want = ref2.submit([5, 7, 11], max_new_tokens=12, stop_token=stop)
    ref2.run_until_done()
    sched, _ = make_sched(max_batch=2, max_seq=64, speculative_gamma=4,
                          draft_model="model", draft_layers=1,
                          spec_tree_width=2)
    r1 = sched.submit([5, 7, 11], max_new_tokens=12, stop_token=stop)
    r2 = sched.submit([3, 1], max_new_tokens=8, speculative=False)
    sched.run_until_done()
    assert r1.output == want.output
    assert r2.output == ref_tokens(params, [3, 1], 8)


def test_tree_geometry_validation():
    """Bad tree geometry fails LOUDLY at engine construction: (N-1)
    not divisible by width, node budget below one full fan, and a
    draft source without tree_draft (ngram) are all rejected."""
    import pytest
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(42))

    def build(**kw):
        rt = RuntimeConfig(max_batch_size=2, max_seq_len=64, page_size=8,
                           **kw)
        return ServingEngine(model, params, rt)

    with pytest.raises(ValueError, match="divisible"):
        build(speculative_gamma=3, draft_model="model", draft_layers=1,
              spec_tree_width=2)  # N = 4 -> (N-1) % 2 != 0
    with pytest.raises(ValueError, match="invalid for width"):
        build(speculative_gamma=4, draft_model="model", draft_layers=1,
              spec_tree_width=2, spec_tree_nodes=2)
    with pytest.raises(ValueError, match="tree_draft"):
        build(speculative_gamma=4, spec_tree_width=2)  # ngram source


def test_mixed_fallback_counter_and_reason():
    """ISSUE 19 satellite: mixed_dispatch requested but gated (tree
    mode has no fused mixed program; stateful draft sources need the
    admission barrier) increments spec_mixed_fallback_total and
    surfaces the one-line reason in metrics(); an eligible config
    reports 0 and no reason."""
    sched, _ = make_sched(max_batch=2, speculative_gamma=4,
                          draft_model="model", draft_layers=1,
                          spec_tree_width=2, mixed_dispatch=True)
    m = sched.metrics()
    assert m["spec_mixed_fallback_total"] == 1.0
    assert "spec_mixed_fallback_reason" in m
    sched2, _ = make_sched(max_batch=2, speculative_gamma=3,
                           mixed_dispatch=True)
    m2 = sched2.metrics()
    assert m2["spec_mixed_fallback_total"] == 0.0
    assert "spec_mixed_fallback_reason" not in m2
