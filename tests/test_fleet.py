"""Fleet control plane (ISSUE 6): disaggregated prefill/decode with
cross-replica KV page transfer.

Layered like the subsystem:

* allocator units — the transfer surface on PrefixCachingAllocator
  (lookup / pin / unpin / import_page) with the full-accounting
  invariant checked after every mutation;
* kvtransfer units — export/import payload roundtrip between two real
  schedulers, geometry refusal, missing-hash reporting;
* HTTP endpoints — /kv/pages, /kv/import, the enriched /health;
* the control plane — classification, the disaggregated handoff with
  BYTE-IDENTICAL greedy parity vs single-replica serving (the
  acceptance contract), fallback when a tier dies mid-handoff;
* the fleet soak — 2 prefill + 2 decode replicas through a rolling
  drain/restart cycle with zero dropped un-started requests and a
  positive transfer hit rate;
* the observability plane (ISSUE 7) — /fleet/trace merged waterfalls
  (leg ordering, common clock, missing-replica degradation),
  /fleet/metrics rollup sums vs per-replica /metrics, and SLO
  attainment through the soak.

Everything runs in-process on the tiny model (the test_router.py
idiom); the multi-replica pieces are slow-marked in conftest.py.
"""
import json
import urllib.error
import urllib.request

import jax
import pytest

from butterfly_tpu.cache.prefix import (
    PrefixCachingAllocator, chain_block_hashes)
from butterfly_tpu.core.config import RuntimeConfig, tiny
from butterfly_tpu.engine.serving import ServingEngine
from butterfly_tpu.fleet.kvtransfer import export_payload, import_payload
from butterfly_tpu.models.common import Model
from butterfly_tpu.sched.scheduler import Scheduler

CFG = tiny("llama", dtype="float32", param_dtype="float32")
PAGE = 8


@pytest.fixture(scope="module")
def shared_model():
    model = Model(CFG)
    return model, model.init(jax.random.PRNGKey(0))


def make_sched(shared_model, max_batch=2, max_seq=128, num_pages=None):
    model, params = shared_model
    rt = RuntimeConfig(max_batch_size=max_batch, max_seq_len=max_seq,
                       page_size=PAGE, num_pages=num_pages,
                       prefix_caching=True)
    return Scheduler(ServingEngine(model, params, rt))


def post(url, path, obj, timeout=60):
    req = urllib.request.Request(
        url + path, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def get(url, path, timeout=30):
    with urllib.request.urlopen(url + path, timeout=timeout) as resp:
        return json.loads(resp.read())


# ---------------------------------------------------------------------------
# allocator units: the transfer surface
# ---------------------------------------------------------------------------

def test_lookup_and_import_page():
    a = PrefixCachingAllocator(num_pages=8, page_size=4, max_pages_per_seq=8)
    seq = list(range(9))  # 2 full pages
    a.admit(0, seq, len(seq) + 1)
    a.register(0, seq)
    h1, h2 = chain_block_hashes(seq, 4)
    assert a.lookup(h1) == a.pages_of(0)[0]
    assert a.lookup(h2) == a.pages_of(0)[1]
    assert a.lookup(b"\x00" * 32) is None
    # import of an already-registered digest is a no-op (idempotent)
    assert a.import_page(h1) is None
    # a fresh digest claims a page and registers it warm (evictable)
    h3 = chain_block_hashes(seq[:4] + [99] * 4, 4)[-1]
    pid = a.import_page(h3)
    assert pid is not None and a.lookup(h3) == pid
    assert pid in a._evictable
    a.check_invariants()
    a.release(0)
    a.check_invariants()


def test_imported_pages_attach_like_local_hits():
    """A chain imported (not computed locally) must satisfy a later
    admit exactly like a locally registered prefix."""
    a = PrefixCachingAllocator(num_pages=8, page_size=4, max_pages_per_seq=8)
    seq = list(range(10))  # 2 full pages + tail
    for h in chain_block_hashes(seq, 4):
        assert a.import_page(h) is not None
    a.check_invariants()
    assert a.admit(0, seq, len(seq) + 1) == 8  # both pages hit
    a.check_invariants()


def test_pin_blocks_eviction():
    """A pinned warm page must survive allocation pressure that would
    otherwise evict it (the export-in-progress guarantee)."""
    a = PrefixCachingAllocator(num_pages=2, page_size=4, max_pages_per_seq=2)
    seq = list(range(5))  # 1 full page
    a.admit(0, seq, len(seq) + 1)     # 2 pages: 1 registered + 1 private
    a.register(0, seq)
    (h,) = chain_block_hashes(seq, 4)
    pid = a.lookup(h)
    a.release(0)                       # registered page goes warm
    a.pin([pid])
    # both raw-free pages get consumed; the pinned page must NOT be
    # recycled even though the free list runs dry
    assert a.grow(1, 4) is not None
    assert a.grow(1, 8) is None        # only the pinned page "left"
    assert a.lookup(h) == pid          # still registered
    a.unpin([pid])
    assert a.grow(1, 8) is not None    # now evictable again
    assert a.lookup(h) is None         # eviction deregistered it
    a.check_invariants()


def test_import_page_exhaustion():
    a = PrefixCachingAllocator(num_pages=1, page_size=4, max_pages_per_seq=4)
    a.grow(0, 4)  # the only page is slot-held: not free, not evictable
    with pytest.raises(MemoryError):
        a.import_page(b"\x01" * 32)
    a.check_invariants()


# ---------------------------------------------------------------------------
# kvtransfer payloads between two real schedulers
# ---------------------------------------------------------------------------

def test_export_import_roundtrip_and_warm_hit(shared_model):
    """Pages exported from A and imported into B give B's admission a
    full prefix hit, and the decoded continuation is byte-identical to
    a single-replica run — K/V bytes moved, semantics did not."""
    prompt = list(range(1, 41))  # 5 full pages
    a = make_sched(shared_model)
    ra = a.submit(prompt, max_new_tokens=1, stop_token=-1)
    a.run_until_done()
    hashes = [h.hex() for h in chain_block_hashes(prompt, PAGE)]
    payload = export_payload(a, hashes)
    assert [p["hash"] for p in payload["pages"]] == hashes
    assert payload["missing"] == []
    assert payload["bytes"] > 0

    b = make_sched(shared_model)
    res = import_payload(b, payload)
    assert res["imported"] == len(hashes) and not res["no_space"]
    # B continues from A's first token with a full-prefix cache hit
    rb = b.submit(prompt + ra.output, max_new_tokens=7, stop_token=-1)
    b.run_until_done()
    assert b.alloc.hit_tokens == 40  # every full page came from import

    ref = make_sched(shared_model)
    rr = ref.submit(prompt, max_new_tokens=8, stop_token=-1)
    ref.run_until_done()
    assert ra.output + rb.output == rr.output


def test_export_reports_missing_tail(shared_model):
    a = make_sched(shared_model)
    prompt = list(range(1, 25))  # 3 full pages
    a.submit(prompt, max_new_tokens=1, stop_token=-1)
    a.run_until_done()
    other = chain_block_hashes(list(range(50, 90)), PAGE)
    hashes = [h.hex() for h in chain_block_hashes(prompt, PAGE)] \
        + [other[-1].hex()]
    payload = export_payload(a, hashes)
    assert len(payload["pages"]) == 3
    assert payload["missing"] == [other[-1].hex()]
    # a chain that misses at block 0 ships nothing (pages behind a gap
    # are unusable by admit)
    cold = export_payload(a, [other[0].hex()] + hashes)
    assert cold["pages"] == [] and len(cold["missing"]) == 5


def test_import_refuses_geometry_mismatch(shared_model):
    a = make_sched(shared_model)
    prompt = list(range(1, 17))
    a.submit(prompt, max_new_tokens=1, stop_token=-1)
    a.run_until_done()
    payload = export_payload(
        a, [h.hex() for h in chain_block_hashes(prompt, PAGE)])
    bad = dict(payload)
    bad["meta"] = {**payload["meta"], "page_size": 16}
    b = make_sched(shared_model)
    with pytest.raises(ValueError, match="geometry"):
        import_payload(b, bad)
    with pytest.raises(ValueError, match="version"):
        import_payload(b, {**payload, "version": 99})
    # nothing landed
    assert import_payload(b, payload)["imported"] == 2


def test_import_idempotent(shared_model):
    a = make_sched(shared_model)
    prompt = list(range(1, 17))
    a.submit(prompt, max_new_tokens=1, stop_token=-1)
    a.run_until_done()
    payload = export_payload(
        a, [h.hex() for h in chain_block_hashes(prompt, PAGE)])
    b = make_sched(shared_model)
    assert import_payload(b, payload)["imported"] == 2
    again = import_payload(b, payload)
    assert again["imported"] == 0 and again["skipped"] == 2


# ---------------------------------------------------------------------------
# HTTP surface: /health fields, /kv endpoints, /fleet/state
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fleet_1p1d(shared_model):
    from butterfly_tpu.fleet.harness import start_fleet
    model, params = shared_model
    # generous CPU-smoke objectives: the SLO layer records attainment
    # (fleet_slo_* counters, slo_ttft_ok response fields) without ever
    # turning a slow CI box into a flake
    fleet = start_fleet("1p1d", page_size=PAGE, max_batch=2, max_seq=128,
                        disagg_threshold=16, model=model, params=params,
                        slo_ttft_s=120.0, slo_itl_s=120.0)
    yield fleet
    fleet.stop()


def test_health_carries_fleet_signals(fleet_1p1d):
    pre = fleet_1p1d.replicas[0]
    body = get(pre.url, "/health")
    assert body["role"] == "prefill"
    assert body["free_pages"] > 0
    assert body["inflight_depth"] == 0
    assert "queue_depth" in body and "active" in body


def test_kv_endpoint_roundtrip_over_http(fleet_1p1d):
    pre, dec = fleet_1p1d.replicas
    prompt = list(range(1, 25))
    post(pre.url, "/generate", {"tokens": prompt, "max_tokens": 1,
                                "stop_token": -1})
    hashes = ",".join(h.hex() for h in chain_block_hashes(prompt, PAGE))
    payload = get(pre.url, f"/kv/pages?hashes={hashes}")
    assert len(payload["pages"]) == 3 and payload["bytes"] > 0
    res = post(dec.url, "/kv/import", payload)
    assert res["imported"] + res["skipped"] == 3


def test_kv_export_bad_requests(fleet_1p1d):
    pre = fleet_1p1d.replicas[0]
    with pytest.raises(urllib.error.HTTPError) as e:
        get(pre.url, "/kv/pages")
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        get(pre.url, "/kv/pages?hashes=nothex")
    assert e.value.code == 400


def test_kv_import_mismatch_is_409(fleet_1p1d):
    pre, dec = fleet_1p1d.replicas
    prompt = list(range(1, 17))
    post(pre.url, "/generate", {"tokens": prompt, "max_tokens": 1,
                                "stop_token": -1})
    hashes = ",".join(h.hex() for h in chain_block_hashes(prompt, PAGE))
    payload = get(pre.url, f"/kv/pages?hashes={hashes}")
    payload["meta"]["num_layers"] += 1
    with pytest.raises(urllib.error.HTTPError) as e:
        post(dec.url, "/kv/import", payload)
    assert e.value.code == 409


def test_fleet_state_table(fleet_1p1d):
    state = get(fleet_1p1d.url, "/fleet/state")
    assert len(state["replicas"]) == 2
    pre, dec = fleet_1p1d.replicas
    assert state["tiers"]["prefill"] == [pre.rid]
    assert state["tiers"]["decode"] == [dec.rid]
    by_rid = {s["replica"]: s for s in state["replicas"]}
    assert by_rid[pre.rid]["role"] == "prefill"
    assert by_rid[pre.rid]["free_pages"] is not None
    assert "kv_transfer_hit_rate" in state["metrics"]


# ---------------------------------------------------------------------------
# the disaggregated handoff (acceptance: byte-identical greedy parity)
# ---------------------------------------------------------------------------

def test_disaggregated_parity_with_single_replica(fleet_1p1d, shared_model):
    """A request prefilled on replica A and decoded on replica B
    produces byte-identical greedy tokens to single-replica serving,
    with the KV pages actually transferred (B prefix-hits every full
    prompt page instead of recomputing)."""
    pre, dec = fleet_1p1d.replicas
    prompt = list(range(3, 43))  # 5 full pages
    hits_before = dec.sched.alloc.hit_tokens
    r = post(fleet_1p1d.url, "/generate",
             {"tokens": prompt, "max_tokens": 8, "stop_token": -1})
    assert r["disaggregated"] is True
    assert r["prefill_replica"] == pre.rid
    assert r["decode_replica"] == dec.rid
    assert r["kv_pages_imported"] == 5
    assert r["ttft_s"] > 0
    assert dec.sched.alloc.hit_tokens - hits_before == 40

    ref = make_sched(shared_model)
    rr = ref.submit(prompt, max_new_tokens=8, stop_token=-1)
    ref.run_until_done()
    assert r["tokens"] == rr.output


def test_short_prompt_routes_direct(fleet_1p1d):
    before = fleet_1p1d.state.fleet_counters()["direct_requests"]
    r = post(fleet_1p1d.url, "/generate",
             {"tokens": [5, 6, 7], "max_tokens": 2, "stop_token": -1})
    assert "disaggregated" not in r
    after = fleet_1p1d.state.fleet_counters()["direct_requests"]
    assert after == before + 1


def test_string_prompt_routes_direct(fleet_1p1d):
    """String prompts cannot be chain-hashed by the control plane (no
    tokenizer there) — they must dispatch direct, never disaggregate."""
    r = post(fleet_1p1d.url, "/generate",
             {"prompt": "x" * 64, "max_tokens": 2})
    assert "disaggregated" not in r and len(r["tokens"]) == 2


def test_handoff_falls_back_when_prefill_tier_dies(shared_model):
    """Prefill replica dies before the handoff: the control plane falls
    back to a direct dispatch on the decode tier — correct tokens, no
    client-visible failure (the failure-matrix row docs/fleet.md
    documents)."""
    from butterfly_tpu.fleet.harness import start_fleet
    model, params = shared_model
    fleet = start_fleet("1p1d", page_size=PAGE, max_batch=2, max_seq=128,
                        disagg_threshold=16, model=model, params=params)
    try:
        # freeze the prober: the pool must still believe the prefill
        # replica is live, so the request takes the HANDOFF path and
        # exercises the mid-flight fallback (not the planner's
        # dead-replica exclusion)
        fleet.state.pool.stop()
        pre = fleet.replicas[0]
        pre.httpd.shutdown()
        pre.httpd.server_close()
        prompt = list(range(7, 47))
        r = post(fleet.url, "/generate",
                 {"tokens": prompt, "max_tokens": 4, "stop_token": -1,
                  "request_id": "fb-1"})
        assert "disaggregated" not in r and len(r["tokens"]) == 4
        assert fleet.state.fleet_counters()["disagg_fallbacks"] >= 1
        # the dead leg was CLASSIFIED (ISSUE 8 satellite): a refused
        # prefill leg lands in fleet_leg_failures_total{leg,kind},
        # not a bare except bucket
        assert fleet.state.fleet_counters()["leg_failures"] >= 1
        kinds = {k: c.value for k, c in
                 fleet.state._c_leg_fail._children.items()}
        assert kinds.get(("prefill_leg", "refused"), 0) >= 1, kinds
        # and the leg failure fed the replica's circuit breaker
        assert fleet.state.pool.get(pre.rid).breaker_fails >= 1
        ref = make_sched(shared_model)
        rr = ref.submit(prompt, max_new_tokens=4, stop_token=-1)
        ref.run_until_done()
        assert r["tokens"] == rr.output
        # the trace still assembles: the dead prefill replica's leg
        # degrades to control-plane spans only, the fallback event and
        # the direct leg that actually served are both recorded
        tr = get(fleet.url, "/fleet/trace?request_id=fb-1")
        names = [ev["name"] for ev in tr["merged"]
                 if ev["source"] == "control"]
        assert "fallback" in names and "direct_leg" in names
        assert tr["sources"][pre.rid].get("missing") is True
        dec_rid = fleet.replicas[1].rid
        assert tr["sources"][dec_rid]["events"] > 0
    finally:
        fleet.stop()


def test_fleet_deadline_spent_at_arrival_is_504(fleet_1p1d):
    """A request whose deadline budget is already spent 504s at the
    control plane — no classify, no handoff, no replica ever sees it —
    with where/elapsed detail and the fleet counter ticked."""
    before = fleet_1p1d.state.fleet_counters()["deadline_expired"]
    with pytest.raises(urllib.error.HTTPError) as e:
        post(fleet_1p1d.url, "/generate",
             {"tokens": list(range(1, 40)), "max_tokens": 4,
              "stop_token": -1, "deadline_ms": 0,
              "request_id": "dl-arrival-1"})
    assert e.value.code == 504
    body = json.loads(e.value.read())
    assert body["error"] == "deadline exceeded"
    assert body["where"] == "arrival"
    assert body["request_id"] == "dl-arrival-1"
    after = fleet_1p1d.state.fleet_counters()["deadline_expired"]
    assert after == before + 1
    # a generous budget rides the handoff end to end untouched
    r = post(fleet_1p1d.url, "/generate",
             {"tokens": list(range(1, 40)), "max_tokens": 4,
              "stop_token": -1, "deadline_ms": 120_000})
    assert len(r["tokens"]) == 4


def test_chaos_soak_terminal_outcomes():
    """The ISSUE 8 acceptance soak: a 2p2d fleet under the SEEDED stock
    fault plan (delays, 500s, a wedge burst, drops, truncations, a
    dropped control-plane leg) driven by loadgen, plus a spent-deadline
    burst. Every submitted request reaches a terminal outcome (tokens,
    429, or 504): zero un-started drops, zero client hangs, zero
    5xx-shaped errors — and the bench JSON carries the
    overload-protection counter fields."""
    from butterfly_tpu.obs.benchmark import run_chaos_benchmark
    out = run_chaos_benchmark("2p2d", clients=3, requests_per_client=4)
    assert out["chaos_requests"] == 15  # 12 chaos load + 3 expired burst
    assert out["chaos_terminal"] == out["chaos_requests"]
    assert out["chaos_unterminal"] == 0
    assert out["chaos_errors"] == 0
    # the faults actually fired (seeded plan, not a quiet pass) and the
    # handoff degraded through its real fallback paths
    assert out["chaos_injected"] > 0
    assert out["chaos_leg_failures"] > 0
    # the spent-budget burst died at the control plane as terminal 504s
    assert out["chaos_deadline_504"] == 3
    assert out["deadline_expired_total"] >= 3
    # the acceptance bench keys exist (values are workload-dependent)
    for key in ("serving_shed_total", "deadline_expired_total",
                "breaker_open_total"):
        assert key in out
    # flight recorder (ISSUE 15): the spent-deadline burst is a
    # deadline-expiry-burst anomaly at this scale — the control plane's
    # recorder must have produced a post-mortem artifact, and the
    # /fleet/flightrecorder rollup must have merged every source
    # (control plane + all four replicas)
    assert out["chaos_flightrec_dumps"] >= 1
    assert "expiry_burst" in out["chaos_flightrec_reasons"]
    assert out["chaos_flightrec_sources"] == 5  # control + 2p + 2d
    assert out["chaos_flightrec_events"] > 0


# ---------------------------------------------------------------------------
# fleet observability: merged traces, metrics rollup, SLO (ISSUE 7)
# ---------------------------------------------------------------------------

def test_fleet_trace_merged_waterfall(fleet_1p1d):
    """The acceptance trace: one disaggregated request yields ONE
    /fleet/trace timeline — control-plane legs (classify → prefill_leg
    → kv transfer → decode_leg) interleaved with BOTH replicas' span
    events on a common clock, leg durations summing to within 10% of
    the measured end-to-end latency, and SLO verdicts attached."""
    pre, dec = fleet_1p1d.replicas
    prompt = list(range(2, 42))  # 5 full pages
    r = post(fleet_1p1d.url, "/generate",
             {"tokens": prompt, "max_tokens": 8, "stop_token": -1,
              "request_id": "trace-e2e-1"})
    assert r["disaggregated"] and r["request_id"] == "trace-e2e-1"
    assert r["slo_ttft_ok"] is True and r["slo_itl_ok"] is True

    tr = get(fleet_1p1d.url, "/fleet/trace?request_id=trace-e2e-1")
    names = [leg["name"] for leg in tr["legs"]]
    assert names == ["classify", "prefill_leg", "kv_export",
                     "kv_import", "decode_leg"]
    # per-leg durations account for the end-to-end latency (10% slack)
    assert tr["total_s"] == pytest.approx(r["total_s"], rel=0.2)
    assert abs(tr["legs_total_s"] - tr["total_s"]) \
        < 0.1 * tr["total_s"]
    # control-plane leg spans are ordered and non-overlapping
    for a, b in zip(tr["legs"], tr["legs"][1:]):
        assert b["start_wall"] >= a["end_wall"] - 1e-4
    # all three processes contribute, merged on one clock
    srcs = {ev["source"] for ev in tr["merged"]}
    assert srcs == {"control", pre.rid, dec.rid}
    ts = [ev["t_wall"] for ev in tr["merged"]]
    assert ts == sorted(ts)
    # within each replica the span events stay in recorded order
    for rid in (pre.rid, dec.rid):
        mine = [ev for ev in tr["merged"] if ev["source"] == rid]
        assert mine and [ev["t_wall"] for ev in mine] == \
            sorted(ev["t_wall"] for ev in mine)
    # the prefill replica's own first_token lands inside the
    # prefill leg's wall-clock span (clock-offset sanity, loopback)
    leg = tr["legs"][1]
    ft = next(ev for ev in tr["merged"]
              if ev["source"] == pre.rid and ev["name"] == "first_token")
    assert leg["start_wall"] - 0.05 <= ft["t_wall"] \
        <= leg["end_wall"] + 0.05
    assert tr["slo"]["slo_ttft_ok"] is True


def test_fleet_trace_direct_request_and_unknown_id(fleet_1p1d):
    """Direct dispatches trace too (classify + direct_leg), and an
    unknown request id is a clean 404, not a 500."""
    post(fleet_1p1d.url, "/generate",
         {"tokens": [5, 6, 7], "max_tokens": 2, "stop_token": -1,
          "request_id": "trace-direct-1"})
    tr = get(fleet_1p1d.url, "/fleet/trace?request_id=trace-direct-1")
    names = [leg["name"] for leg in tr["legs"]]
    assert names[0] == "classify" and "direct_leg" in names
    direct = next(leg for leg in tr["legs"]
                  if leg["name"] == "direct_leg")
    assert direct["replica"] in {r.rid for r in fleet_1p1d.replicas}
    with pytest.raises(urllib.error.HTTPError) as e:
        get(fleet_1p1d.url, "/fleet/trace?request_id=never-seen")
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        get(fleet_1p1d.url, "/fleet/trace")
    assert e.value.code == 400


def test_fleet_metrics_rollup_sums_match_replicas(fleet_1p1d):
    """/fleet/metrics counter sums equal the per-replica sums, the
    re-bucketed histograms stay internally consistent (+Inf == _count),
    and the per-replica autoscale gauges are exposed labeled."""
    from butterfly_tpu.obs.registry import parse_exposition
    post(fleet_1p1d.url, "/generate",
         {"tokens": list(range(1, 30)), "max_tokens": 4,
          "stop_token": -1})
    fleet_1p1d.state.pool.probe_all()  # fresh synchronous scrape round
    with urllib.request.urlopen(fleet_1p1d.url + "/fleet/metrics",
                                timeout=30) as resp:
        text = resp.read().decode()
    fams = parse_exposition(text)
    # counters: fleet sum == sum over replicas' own /metrics
    per_replica = 0.0
    for rep in fleet_1p1d.replicas:
        with urllib.request.urlopen(rep.url + "/metrics",
                                    timeout=30) as resp:
            rf = parse_exposition(resp.read().decode())
        per_replica += rf["butterfly_requests_total"]["samples"][
            ("butterfly_requests_total", ())]
    agg = fams["butterfly_fleet_requests_total"]["samples"][
        ("butterfly_fleet_requests_total", ())]
    assert agg == per_replica > 0
    # histograms: re-bucketed exactly, +Inf bucket == _count
    h = fams["butterfly_fleet_ttft_seconds"]["samples"]
    inf = h[("butterfly_fleet_ttft_seconds_bucket", (("le", "+Inf"),))]
    assert inf == h[("butterfly_fleet_ttft_seconds_count", ())] > 0
    # per-replica autoscale gauges, one series per replica
    fp = fams["butterfly_fleet_replica_kv_pages_free"]["samples"]
    assert len(fp) == len(fleet_1p1d.replicas)
    assert fams["butterfly_fleet_replicas_scraped"]["samples"][
        ("butterfly_fleet_replicas_scraped", ())] == 2.0
    # clock offsets learned from the same probe loop (loopback: ~0)
    for snap in fleet_1p1d.state.pool.snapshot():
        assert snap["clock_offset_s"] is not None
        assert abs(snap["clock_offset_s"]) < 5.0


# ---------------------------------------------------------------------------
# load_score page pressure (satellite) — policy-level ordering
# ---------------------------------------------------------------------------

def test_load_score_prefers_page_headroom():
    from butterfly_tpu.router.pool import Replica
    rich = Replica("a:1", "a", 1)
    poor = Replica("b:1", "b", 1)
    rich.free_pages, poor.free_pages = 50, 2
    # equal outstanding/backlog: page headroom breaks the tie
    assert sorted([poor, rich], key=Replica.load_score)[0] is rich
    # outstanding still dominates (freshest signal)
    poor.outstanding, rich.outstanding = 0, 1
    assert sorted([poor, rich], key=Replica.load_score)[0] is poor
    # unknown headroom scores as zero pages (conservative)
    unknown = Replica("c:1", "c", 1)
    unknown.outstanding = 0
    assert sorted([poor, unknown], key=Replica.load_score)[0] is poor


def test_pool_candidates_filter_by_role():
    from butterfly_tpu.router.pool import ReplicaPool
    pool = ReplicaPool(["h:1", "h:2", "h:3"])
    pool.replicas["h:1"].role = "prefill"
    pool.replicas["h:2"].role = "decode"
    pool.replicas["h:3"].role = "both"
    assert {r.rid for r in pool.candidates("prefill")} == {"h:1", "h:3"}
    assert {r.rid for r in pool.candidates("decode")} == {"h:2", "h:3"}
    assert len(pool.candidates()) == 3


# ---------------------------------------------------------------------------
# the fleet soak: rolling drain/restart over 2 prefill + 2 decode
# ---------------------------------------------------------------------------

def test_fleet_soak_rolling_drain_restart(shared_model):
    """The acceptance soak: closed-loop load over a 2p2d topology while
    every replica is rolled through drain -> HTTP restart -> undrain.
    Zero dropped un-started requests, transfers actually happened."""
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))
    try:
        from loadgen import run_fleet_soak
    finally:
        sys.path.pop(0)
    from butterfly_tpu.fleet.harness import start_fleet
    model, params = shared_model
    fleet = start_fleet("2p2d", page_size=PAGE, max_batch=2, max_seq=128,
                        disagg_threshold=16, model=model, params=params)
    try:
        stats = run_fleet_soak(
            fleet.url, clients=3, requests_per_client=3,
            prefix_share=0.5, shared_len=4 * PAGE, tail_len=4,
            max_tokens=4, replicas=fleet.rids,
            restart_hook=lambda rid: fleet.by_rid[rid].restart(),
            slo_ttft_ms=120_000.0, slo_itl_ms=120_000.0)
        assert stats["failed"] == 0, stats["errors"]
        assert stats["ok"] == 9
        assert len(stats["rolling_cycles"]) == 4
        assert all(c["drained"] and c["restarted"]
                   for c in stats["rolling_cycles"])
        fm = stats["fleet_metrics"]
        assert fm["kv_transfer_hit_rate"] > 0
        assert fm["kv_transfer_bytes"] > 0
        assert stats["disaggregated"] > 0
        # client-side SLO attainment against the declared (generous)
        # objectives rides the soak summary
        assert stats["slo_attainment"] == 1.0
        assert stats["slo_ttft_ok"] == stats["ok"]
        # every replica answers again after its restart
        for r in fleet.replicas:
            assert get(r.url, "/health")["status"] == "ok"
        # trace assembly SURVIVED the rolling restarts: every loadgen
        # request id still yields at least its control-plane spans
        # (replica fronts bounced mid-soak; schedulers+tracers live on)
        tr = get(fleet.url, "/fleet/trace?request_id=loadgen-0-0")
        assert any(ev["source"] == "control" for ev in tr["merged"])
        assert [l["name"] for l in tr["legs"]][0] == "classify"
    finally:
        fleet.stop()
