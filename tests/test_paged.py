"""Paged KV cache tests: parity with the contiguous cache + allocator
bookkeeping (SURVEY.md §7 stage 4; BASELINE.json configs[4])."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from butterfly_tpu.cache.allocator import PageAllocator
from butterfly_tpu.cache.paged import (
    PagedKVCache, gather_paged_layer, init_paged_cache, paged_forward,
    write_paged_layer)
from butterfly_tpu.core.config import RuntimeConfig, tiny
from butterfly_tpu.models.common import Model, forward, init_cache


CFG = tiny("llama", dtype="float32", param_dtype="float32")
RT = RuntimeConfig(max_batch_size=2, max_seq_len=64, page_size=8)


def seq_table(cache, batch, pages_per_seq):
    """Identity block tables: slot b owns pages [b*p .. (b+1)*p)."""
    table = np.full(np.asarray(cache.page_table).shape, cache.null_page,
                    np.int32)
    for b in range(batch):
        table[b, :pages_per_seq] = np.arange(
            b * pages_per_seq, (b + 1) * pages_per_seq)
    return cache._replace(page_table=jnp.asarray(table))


def test_paged_forward_matches_contiguous():
    """Prefill + 4 decode steps: logits equal the contiguous-cache path."""
    params = Model(CFG).init(jax.random.PRNGKey(0))
    cache_c = init_cache(CFG, batch=2, max_seq=64)
    cache_p = seq_table(init_paged_cache(CFG, RT), 2, 64 // RT.page_size)

    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, CFG.vocab_size, (2, 9)))
    ref, cache_c = jax.jit(lambda p, t, c: forward(p, CFG, t, c))(
        params, tokens, cache_c)
    out, cache_p = jax.jit(lambda p, t, c: paged_forward(p, CFG, t, c))(
        params, tokens, cache_p)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    for step in range(4):
        nxt = jnp.argmax(ref[:, -1, :], axis=-1)[:, None]
        ref, cache_c = jax.jit(lambda p, t, c: forward(p, CFG, t, c))(
            params, nxt, cache_c)
        out, cache_p = jax.jit(
            lambda p, t, c: paged_forward(p, CFG, t, c))(params, nxt, cache_p)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_inactive_slots_frozen():
    """active=False slots keep their length and never corrupt others."""
    params = Model(CFG).init(jax.random.PRNGKey(0))
    cache = seq_table(init_paged_cache(CFG, RT), 2, 8)
    tokens = jnp.asarray(
        np.random.RandomState(1).randint(0, CFG.vocab_size, (2, 5)))
    _, cache = paged_forward(params, CFG, tokens, cache)

    active = jnp.asarray([True, False])
    tok = jnp.asarray([[7], [9]])
    out_a, cache2 = paged_forward(params, CFG, tok, cache, active=active)
    assert int(cache2.lengths[0]) == 6 and int(cache2.lengths[1]) == 5

    # slot 1's pages are untouched by slot 0's step
    p1 = np.asarray(cache.page_table)[1, :1]
    np.testing.assert_array_equal(np.asarray(cache2.k_pages[:, p1]),
                                  np.asarray(cache.k_pages[:, p1]))


def test_write_gather_roundtrip():
    k_pages = jnp.zeros((6, 2, 4, 3))  # [P, Kv, page, H]
    v_pages = jnp.zeros((6, 2, 4, 3))
    table = jnp.asarray([[0, 2], [3, 1]], jnp.int32)  # interleaved pages
    k = jnp.arange(2 * 5 * 2 * 3, dtype=jnp.float32).reshape(2, 5, 2, 3)
    start = jnp.asarray([0, 3], jnp.int32)
    # slot1 writing at start=3 spills onto its second page (page id 1)
    kp, vp, _, _ = write_paged_layer(k_pages, v_pages, table, k, k * 2, start)
    got = gather_paged_layer(kp, table)
    np.testing.assert_allclose(np.asarray(got[0, 0:5]), np.asarray(k[0]))
    np.testing.assert_allclose(np.asarray(got[1, 3:8]), np.asarray(k[1]))


def test_write_gather_roundtrip_int8():
    """Quantized write/gather: dequantized roundtrip within int8 error."""
    from butterfly_tpu.cache.paged import gather_paged_layer_q

    P, Kv, page, H = 6, 2, 4, 8
    k_pages = jnp.zeros((P, Kv, page, H), jnp.int8)
    v_pages = jnp.zeros((P, Kv, page, H), jnp.int8)
    ksp = jnp.zeros((P, Kv * page))
    vsp = jnp.zeros((P, Kv * page))
    table = jnp.asarray([[0, 2], [3, 1]], jnp.int32)
    k = jax.random.normal(jax.random.PRNGKey(0), (2, 5, Kv, H))
    start = jnp.asarray([0, 3], jnp.int32)
    kp, vp, ksp, vsp = write_paged_layer(k_pages, v_pages, table, k, k * 2,
                                         start, None, ksp, vsp)
    codes, scales = gather_paged_layer_q(kp, ksp, table)  # [B,Kv,S,*]
    got = (codes.astype(jnp.float32) *
           scales[..., None]).transpose(0, 2, 1, 3)       # [B,S,Kv,H]
    np.testing.assert_allclose(np.asarray(got[0, 0:5]), np.asarray(k[0]),
                               atol=2e-2)
    np.testing.assert_allclose(np.asarray(got[1, 3:8]), np.asarray(k[1]),
                               atol=2e-2)


def test_paged_forward_int8_close_to_fp():
    """int8 paged serving cache tracks the fp paged path closely and
    EXACTLY matches the contiguous int8 cache's numerics contract
    (scores scaled output-side, probs carry the V scale)."""
    params = Model(CFG).init(jax.random.PRNGKey(0))
    rt_q = RT.replace(kv_quant="int8")
    cache_f = seq_table(init_paged_cache(CFG, RT), 2, 64 // RT.page_size)
    cache_q = seq_table(init_paged_cache(CFG, rt_q), 2, 64 // RT.page_size)
    assert cache_q.quantized and cache_q.k_pages.dtype == jnp.int8

    tokens = jnp.asarray(
        np.random.RandomState(3).randint(0, CFG.vocab_size, (2, 9)))
    ref, cache_f = paged_forward(params, CFG, tokens, cache_f)
    out, cache_q = paged_forward(params, CFG, tokens, cache_q)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=0.1, atol=0.15)

    for _ in range(3):
        nxt = jnp.argmax(ref[:, -1, :], axis=-1)[:, None]
        ref, cache_f = paged_forward(params, CFG, nxt, cache_f)
        out, cache_q = paged_forward(params, CFG, nxt, cache_q)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=0.1, atol=0.15)


def test_allocator_grow_release():
    a = PageAllocator(num_pages=10, page_size=4, max_pages_per_seq=4)
    assert a.grow(0, 9) is not None       # 3 pages
    assert a.free_pages == 7
    assert a.grow(0, 12) == []            # fits in current pages
    assert a.pages_needed(0, 13) == 1
    assert a.grow(1, 16) is not None      # 4 pages
    assert a.free_pages == 3
    assert a.grow(0, 16) is not None      # 1 more page
    assert a.free_pages == 2
    assert a.grow(0, 17) is None          # over max_pages_per_seq
    assert a.grow(2, 9) is None           # needs 3 > 2 free, all-or-nothing
    assert a.free_pages == 2
    assert a.release(1) and a.free_pages == 6
    a.release(0)
    assert a.free_pages == 10


@pytest.mark.parametrize("lengths", [[1, 17, 8], [32, 1, 5]])
def test_allocator_property_accounting(lengths):
    """Σ owned + free == total, and no page owned twice."""
    a = PageAllocator(num_pages=32, page_size=4, max_pages_per_seq=16)
    for slot, ln in enumerate(lengths):
        assert a.grow(slot, ln) is not None
    owned = [p for s in range(len(lengths)) for p in a.pages_of(s)]
    assert len(owned) == len(set(owned))
    assert len(owned) + a.free_pages == 32
    for s in range(len(lengths)):
        a.release(s)
    assert a.free_pages == 32


def test_near_capacity_prompt_bucket_padding_no_corruption():
    """A prompt whose prefill bucket pads past the block-table capacity
    must not corrupt the slot's own pages.

    max_seq=96 (not a power of two), page=8 -> 12-entry rows. A 90-token
    prompt owns all 12 pages; its bucket pads to 128 positions, so the
    writer sees positions 96..127 with no table entry. write_paged_layer
    routes them to the null page explicitly; this pins greedy parity
    with the contiguous engine so that contract can never regress."""
    import numpy as np
    from butterfly_tpu.core.config import RuntimeConfig, tiny
    from butterfly_tpu.engine import InferenceEngine, SamplingParams
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.models.common import Model
    from butterfly_tpu.sched.scheduler import Scheduler

    cfg = tiny("llama", dtype="float32", param_dtype="float32")
    model = Model(cfg)
    params = model.init(jax.random.PRNGKey(7))
    prompt = [int(t) for t in
              np.random.RandomState(0).randint(0, cfg.vocab_size, 90)]

    rt = RuntimeConfig(max_batch_size=2, max_seq_len=96, page_size=8,
                       prefill_chunk=512)  # whole-prompt bucket: 128 > 96
    sched = Scheduler(ServingEngine(model, params, rt, use_kernels=False))
    req = sched.submit(prompt, max_new_tokens=5)
    sched.run_until_done()

    ref = InferenceEngine(model, params).generate(
        [prompt], SamplingParams(max_new_tokens=5))
    want = ref.tokens[0, :int(ref.lengths[0])].tolist()
    assert req.output == want
