"""Stage-5 expert-parallel MoE tests: GShard dispatch parity + sharding.

With capacity high enough that no token drops, moe_block_ep must equal the
dense reference moe_block exactly; under an expert=4 mesh the compiled HLO
must contain all-to-all (the dispatch einsum's lowering).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from butterfly_tpu.core.config import MeshConfig, tiny
from butterfly_tpu.core.mesh import make_mesh
from butterfly_tpu.models.common import Model, forward, init_cache, moe_block
from butterfly_tpu.parallel.expert import expert_capacity, moe_block_ep
from butterfly_tpu.parallel.partition import (
    compiled_hlo, count_collectives, shard_cache, shard_params)


def moe_cfg(**kw):
    return tiny("mixtral", vocab_size=256, hidden_size=64, num_heads=8,
                num_kv_heads=8, head_dim=8, intermediate_size=128,
                dtype="float32", param_dtype="float32", **kw)


def layer0_moe(params):
    return jax.tree.map(lambda a: a[0], params["layers"]["moe"])


def test_ep_matches_dense_no_drop():
    cfg = moe_cfg()
    params = Model(cfg).init(jax.random.PRNGKey(0))
    p = layer0_moe(params)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, cfg.hidden_size))

    dense = moe_block(x, p, cfg)
    # capacity = k*T -> nothing can drop
    ep = moe_block_ep(x, p, cfg, capacity=cfg.num_experts_per_tok * 8)
    np.testing.assert_allclose(np.asarray(ep), np.asarray(dense),
                               rtol=1e-5, atol=1e-5)


def test_ep_capacity_drops_overflow():
    """With capacity 1, experts process at most one token slot each; output
    differs from dense but stays finite (dropped tokens contribute 0)."""
    cfg = moe_cfg()
    params = Model(cfg).init(jax.random.PRNGKey(0))
    p = layer0_moe(params)
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 16, cfg.hidden_size))
    out = moe_block_ep(x, p, cfg, capacity=1)
    assert np.isfinite(np.asarray(out)).all()
    dense = moe_block(x, p, cfg)
    assert not np.allclose(np.asarray(out), np.asarray(dense))


def test_expert_capacity_formula():
    cfg = moe_cfg()  # E=4, k=2, cf=2.0
    assert expert_capacity(cfg, 16) == 16  # ceil(2*2*16/4)
    assert expert_capacity(cfg.replace(moe_capacity_factor=0.001), 16) == 1
    # clamped at k*T
    assert expert_capacity(cfg.replace(moe_capacity_factor=100.0), 4) == 8


def test_ep_forward_parity_on_mesh():
    """Full mixtral forward with moe_impl=ep on an expert=4 x data=2 mesh
    matches the dense single-device forward (no-drop capacity)."""
    cfg = moe_cfg(moe_impl="ep", moe_capacity_factor=float(
        tiny("mixtral").num_experts))  # cf=E => C=k*T, no drops
    dense_cfg = cfg.replace(moe_impl="dense")
    params = Model(cfg).init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (4, 8)))

    cache = init_cache(cfg, batch=4, max_seq=32)
    ref, _ = jax.jit(lambda p, t, c: forward(p, dense_cfg, t, c))(
        params, tokens, cache)

    mesh = make_mesh(MeshConfig(data=2, expert=4))
    sparams = shard_params(params, cfg, mesh)
    scache = shard_cache(init_cache(cfg, batch=4, max_seq=32), cfg, mesh)
    tokens_s = jax.device_put(tokens, NamedSharding(mesh, P("data", None)))
    with jax.set_mesh(mesh):
        logits, _ = jax.jit(lambda p, t, c: forward(p, cfg, t, c))(
            sparams, tokens_s, scache)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ep_hlo_has_real_all_to_all():
    """VERDICT r2 item 7: prefill dispatch must be an explicit
    lax.all_to_all (scatter + a2a path), not whatever GSPMD makes of a
    one-hot einsum — the compiled HLO must contain a real all-to-all."""
    cfg = moe_cfg(moe_impl="ep")
    mesh = make_mesh(MeshConfig(data=2, expert=4))
    params = shard_params(Model(cfg).init(jax.random.PRNGKey(0)), cfg, mesh)
    cache = shard_cache(init_cache(cfg, batch=4, max_seq=32), cfg, mesh)
    tokens = jax.device_put(jnp.zeros((4, 8), jnp.int32),
                            NamedSharding(mesh, P("data", None)))
    hlo = compiled_hlo(lambda p, t, c: forward(p, cfg, t, c),
                       params, tokens, cache, mesh=mesh)
    counts = count_collectives(hlo)
    assert counts["all-to-all"] >= 2, \
        f"EP prefill dispatch/combine not lowered to all-to-all: {counts}"


def test_ep_a2a_long_prefill_fits_memory():
    """VERDICT r2 item 7 'done' criterion: a Mixtral-shaped T=2048
    prefill block must fit fake-device memory. The old one-hot dispatch
    tensor would be [B,T,k,E,C] = 2048*2*8*2048 ~ 67M elements per
    einsum operand pair; the a2a path keeps O(B*T*k) indices + [E,C,D]
    buffers, and still matches the dense reference exactly."""
    cfg = moe_cfg(num_experts=8, moe_capacity_factor=8.0)  # no-drop
    mesh = make_mesh(MeshConfig(expert=8))
    params = Model(cfg).init(jax.random.PRNGKey(3))
    p = layer0_moe(params)
    x = jax.random.normal(jax.random.PRNGKey(4), (1, 2048, cfg.hidden_size))
    with jax.set_mesh(mesh):
        out = jax.jit(lambda x, p: moe_block_ep(x, p, cfg))(x, p)
        out.block_until_ready()
    dense = moe_block(x, p, cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               rtol=2e-4, atol=2e-4)


def test_ep_decode_step_falls_back_to_einsum_path():
    """T==1 (decode) can't sequence-shard over expert: the einsum path
    must engage and still match dense."""
    cfg = moe_cfg(num_experts=4, moe_capacity_factor=4.0)
    mesh = make_mesh(MeshConfig(expert=4, data=2))
    params = Model(cfg).init(jax.random.PRNGKey(5))
    p = layer0_moe(params)
    x = jax.random.normal(jax.random.PRNGKey(6), (2, 1, cfg.hidden_size))
    with jax.set_mesh(mesh):
        out = jax.jit(lambda x, p: moe_block_ep(x, p, cfg))(x, p)
    dense = moe_block(x, p, cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               rtol=1e-5, atol=1e-5)
