"""Native (C++) runtime component tests: allocator parity + integration.

The C++ allocator (native/allocator.cc via ctypes) must be behaviorally
IDENTICAL to the Python PageAllocator — same page ids in the same order
for any operation sequence — so either backend can serve the scheduler.
Property-tested with randomized grow/release workloads, then the whole
scheduler is run against the native backend for token parity.

Skips (rather than fails) when the lib hasn't been built:
`python -m butterfly_tpu.native.build`.
"""
import numpy as np
import pytest

from butterfly_tpu.cache.allocator import PageAllocator, make_page_allocator
from butterfly_tpu.native import native_available

pytestmark = pytest.mark.skipif(
    not native_available(),
    reason="native lib not built (python -m butterfly_tpu.native.build)")


def make_pair(num_pages=24, page=4, max_pages=8, slots=8):
    from butterfly_tpu.native import NativePageAllocator
    return (PageAllocator(num_pages, page, max_pages),
            NativePageAllocator(num_pages, page, max_pages, slots))


def test_native_allocator_basic_parity():
    py, cc = make_pair()
    assert cc.free_pages == py.free_pages == 24
    assert py.grow(0, 9) == cc.grow(0, 9)      # 3 pages, same ids
    assert py.grow(0, 9) == cc.grow(0, 9) == []  # idempotent
    assert py.pages_of(0) == cc.pages_of(0)
    assert py.grow(1, 100) is None and cc.grow(1, 100) is None  # > max/seq
    assert py.release(0) == cc.release(0)
    assert py.free_pages == cc.free_pages == 24


def test_native_allocator_property_parity():
    """Randomized workload: every operation must return identical results
    and leave identical observable state on both backends."""
    rng = np.random.RandomState(0)
    py, cc = make_pair(num_pages=16, page=4, max_pages=6, slots=4)
    lengths = {s: 0 for s in range(4)}
    for _ in range(2000):
        slot = int(rng.randint(4))
        if rng.rand() < 0.25:
            assert py.release(slot) == cc.release(slot)
            lengths[slot] = 0
        else:
            new_len = lengths[slot] + int(rng.randint(1, 9))
            assert py.can_grow(slot, new_len) == cc.can_grow(slot, new_len)
            got_py, got_cc = py.grow(slot, new_len), cc.grow(slot, new_len)
            assert got_py == got_cc
            if got_py is not None:
                lengths[slot] = new_len
        assert py.free_pages == cc.free_pages
        assert py.pages_of(slot) == cc.pages_of(slot)


def test_native_allocator_exhaustion_all_or_nothing():
    _, cc = make_pair(num_pages=4, page=4, max_pages=8, slots=2)
    assert cc.grow(0, 12) == [0, 1, 2]
    assert cc.grow(1, 8) is None          # needs 2, only 1 free
    assert cc.free_pages == 1             # nothing was taken
    assert cc.grow(1, 4) == [3]


def test_scheduler_runs_on_native_allocator():
    """End-to-end: the scheduler's admission/growth/preemption loop over
    the native backend produces the same tokens as the Python one."""
    import jax
    from butterfly_tpu.core.config import RuntimeConfig, tiny
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.models.common import Model
    from butterfly_tpu.sched.scheduler import Scheduler

    cfg = tiny("llama", dtype="float32", param_dtype="float32")
    model = Model(cfg)
    params = model.init(jax.random.PRNGKey(42))
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=32, page_size=4,
                       num_pages=6)  # tight pool => preemption path too

    def run(native: bool):
        import os
        old = os.environ.get("BUTTERFLY_NATIVE")
        os.environ["BUTTERFLY_NATIVE"] = "1" if native else "0"
        try:  # env gate is re-read on every load_native() call
            sched = Scheduler(ServingEngine(model, params, rt))
            assert type(sched.alloc).__name__ == (
                "NativePageAllocator" if native else "PageAllocator")
            reqs = [sched.submit([5, 7, 11], max_new_tokens=10),
                    sched.submit([3, 1], max_new_tokens=10)]
            sched.run_until_done(max_ticks=300)
            return [r.output for r in reqs]
        finally:
            if old is None:
                os.environ.pop("BUTTERFLY_NATIVE", None)
            else:
                os.environ["BUTTERFLY_NATIVE"] = old

    assert run(native=True) == run(native=False)
