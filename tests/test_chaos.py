"""Overload protection + chaos harness units (ISSUE 8).

Pure-host fast tier: the seeded fault plan's determinism and
validation, the replica-pool circuit breaker's open/half-open/close
cycle, and the shim pinning the outbound-HTTP-timeout hygiene check's
migration to the static analyzer (BTF001). The system-level
chaos soak (faulted 2p2d fleet under loadgen) lives in test_fleet.py
(slow tier); deadline/shed scheduler behavior in test_sched.py; the
HTTP 504/429 surfaces in test_server.py.
"""
from pathlib import Path

import pytest

from butterfly_tpu.fleet.chaos import (
    ChaosIdent, ChaosPlan, default_plan)
from butterfly_tpu.router.pool import ReplicaPool


# ---------------------------------------------------------------------------
# chaos plan: determinism, validation, scoping
# ---------------------------------------------------------------------------

PLAN_SPEC = {"seed": 42, "faults": [
    {"kind": "wedge", "target": "decode:0", "endpoint": "/generate",
     "p": 0.5, "count": 5},
    {"kind": "delay", "target": "*", "p": 0.25, "count": 10,
     "delay_s": 0.01},
]}


def _replay(n=60):
    """One deterministic call sequence against a fresh plan."""
    plan = ChaosPlan.from_json(PLAN_SPEC)
    idents = [ChaosIdent("h:1", "decode", 0), ChaosIdent("h:2", "prefill", 0)]
    out = []
    for i in range(n):
        inj = plan.decide(idents[i % 2], "/generate")
        out.append(None if inj is None else inj.kind)
    return out, plan.total_injected


def test_chaos_plan_deterministic():
    """The acceptance property: same plan JSON + seed + call sequence
    => byte-identical injection decisions (per-rule seeded streams)."""
    a, na = _replay()
    b, nb = _replay()
    assert a == b and na == nb
    assert na > 0 and any(k == "wedge" for k in a)
    # a different seed produces a different decision sequence
    other = ChaosPlan.from_json({**PLAN_SPEC, "seed": 43})
    idents = [ChaosIdent("h:1", "decode", 0), ChaosIdent("h:2", "prefill", 0)]
    c = [None if (inj := other.decide(idents[i % 2], "/generate")) is None
         else inj.kind for i in range(60)]
    assert c != a


def test_chaos_rule_budget_and_matching():
    plan = ChaosPlan([{"kind": "drop", "target": "decode", "p": 1.0,
                       "count": 2}])
    dec = ChaosIdent("h:1", "decode", 0)
    pre = ChaosIdent("h:2", "prefill", 0)
    assert plan.decide(pre, "/generate") is None      # role mismatch
    assert plan.decide(dec, "/generate").kind == "drop"
    assert plan.decide(dec, "/generate").kind == "drop"
    assert plan.decide(dec, "/generate") is None      # budget spent
    assert plan.total_injected == 2
    assert plan.summary()["rules"][0]["injected"] == 2


def test_chaos_star_never_matches_health():
    """'*' endpoints must not wedge liveness probing — /health is only
    chaos-able when a rule names it explicitly."""
    plan = ChaosPlan([{"kind": "drop", "target": "*", "p": 1.0}])
    ident = ChaosIdent("h:1", "decode", 0)
    assert plan.decide(ident, "/health") is None
    assert plan.decide(ident, "/generate") is not None
    named = ChaosPlan([{"kind": "wedge", "target": "*",
                        "endpoint": "/health", "p": 1.0}])
    assert named.decide(ident, "/health").kind == "wedge"


def test_chaos_ident_target_forms():
    ident = ChaosIdent("10.0.0.1:8000", "prefill", 1)
    for target in ("*", "prefill", "prefill:1", "10.0.0.1:8000"):
        assert ident.matches(target), target
    for target in ("decode", "prefill:0", "10.0.0.2:8000"):
        assert not ident.matches(target), target


def test_chaos_plan_validation():
    with pytest.raises(ValueError, match="kind"):
        ChaosPlan([{"kind": "explode"}])
    with pytest.raises(ValueError, match="probability"):
        ChaosPlan([{"kind": "drop", "p": 1.5}])
    with pytest.raises(ValueError, match="count"):
        ChaosPlan([{"kind": "drop", "count": 0}])
    with pytest.raises(ValueError, match="scope"):
        ChaosPlan([{"kind": "drop", "where": "everywhere"}])
    with pytest.raises(ValueError, match="plan"):
        ChaosPlan.from_json({"seed": 1})
    assert len(default_plan().rules) >= 5


# ---------------------------------------------------------------------------
# circuit breaker: open / half-open / close at the pool level
# ---------------------------------------------------------------------------

def make_pool(**kw):
    kw.setdefault("breaker_threshold", 3)
    kw.setdefault("breaker_cooldown", 60.0)  # manual clock control
    return ReplicaPool(["h:1", "h:2"], **kw)


def test_breaker_open_half_open_close_cycle():
    """The full wedged-replica cycle the docs/fleet.md failure matrix
    describes: threshold consecutive leg failures open the breaker
    (candidates skip the member while /health still answers), the
    cooldown admits ONE half-open probe, and a successful probe fully
    restores."""
    pool = make_pool()
    r = pool.replicas["h:1"]
    # two failures: still closed, still a candidate
    pool.note_leg_failure("h:1", "wedged")
    pool.note_leg_failure("h:1", "wedged")
    assert r.breaker == "closed"
    assert {c.rid for c in pool.candidates()} == {"h:1", "h:2"}
    # third consecutive failure: OPEN — skipped entirely
    pool.note_leg_failure("h:1", "wedged")
    assert r.breaker == "open" and r.breaker_opens == 1
    assert {c.rid for c in pool.candidates()} == {"h:2"}
    assert pool.breaker_opens_total() == 1
    # cooldown elapses: half-open, exactly one probe admitted
    r.breaker_next_probe_t = 0.0
    assert {c.rid for c in pool.candidates()} == {"h:1", "h:2"}
    assert r.breaker == "half_open"
    # with the probe in flight, the member is withheld again
    pool.note_dispatch("h:1")
    assert {c.rid for c in pool.candidates()} == {"h:2"}
    pool.note_done("h:1")
    # probe succeeded: fully closed, failure count reset
    pool.note_leg_ok("h:1")
    assert r.breaker == "closed" and r.breaker_fails == 0
    assert {c.rid for c in pool.candidates()} == {"h:1", "h:2"}
    assert r.breaker_opens == 1  # no second transition


def test_breaker_reopens_on_half_open_failure():
    pool = make_pool()
    r = pool.replicas["h:1"]
    for _ in range(3):
        pool.note_leg_failure("h:1")
    assert r.breaker == "open"
    r.breaker_next_probe_t = 0.0
    pool.candidates()                     # open -> half_open
    assert r.breaker == "half_open"
    pool.note_leg_failure("h:1")          # one bad probe re-opens
    assert r.breaker == "open" and r.breaker_opens == 2
    assert {c.rid for c in pool.candidates()} == {"h:2"}


def test_breaker_success_resets_consecutive_count():
    """Interleaved successes keep the breaker closed — it opens on
    CONSECUTIVE failures only."""
    pool = make_pool()
    for _ in range(10):
        pool.note_leg_failure("h:1")
        pool.note_leg_failure("h:1")
        pool.note_leg_ok("h:1")
    assert pool.replicas["h:1"].breaker == "closed"
    assert pool.breaker_opens_total() == 0


def test_breaker_open_tier_empties_candidates():
    """While every member of a tier has an open breaker, the tier's
    candidate list is empty — the control plane's _disagg_plan then
    finds no prefill candidate and degrades to direct dispatch (the
    planner requires both tiers routable)."""
    pool = make_pool()
    pool.replicas["h:1"].role = "prefill"
    pool.replicas["h:2"].role = "decode"
    for _ in range(3):
        pool.note_leg_failure("h:1")
    assert pool.candidates("prefill") == []
    assert {c.rid for c in pool.candidates("decode")} == {"h:2"}
    # breaker state is visible on the snapshot /fleet/state serves
    snap = {s["replica"]: s for s in pool.snapshot()}
    assert snap["h:1"]["breaker"] == "open"
    assert snap["h:1"]["breaker_opens"] == 1
    assert snap["h:2"]["breaker"] == "closed"


# ---------------------------------------------------------------------------
# hygiene: every outbound HTTP call carries an explicit timeout
# ---------------------------------------------------------------------------

def test_http_timeout_rule_replaces_string_span_check():
    """RETIRED (ISSUE 11): the balanced-paren string-span scan this
    file carried since PR 8 is replaced by the AST rule BTF001
    (tools/staticrules/http_timeout.py), enforced repo-wide by
    tests/test_staticcheck.py::test_repo_tree_lints_clean. This shim
    pins the replacement so coverage can never silently narrow: the
    rule must stay registered, walk AT LEAST the same trees the old
    grep walked (butterfly_tpu/ + tools/), and cover at least the same
    call names (urlopen/HTTPConnection — it added HTTPSConnection)."""
    import sys
    sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))
    try:
        import staticrules
        from staticrules.http_timeout import TIMEOUT_ARG_INDEX
    finally:
        sys.path.pop(0)
    rule = staticrules.RULES["BTF001"]
    assert rule.name == "outbound-http-timeout"
    for tree in ("butterfly_tpu", "tools"):  # the old grep's trees
        assert rule.applies(f"{tree}/anything/deep.py"), \
            f"BTF001 no longer walks {tree}/ — coverage narrowed"
    assert {"urlopen", "HTTPConnection"} <= set(TIMEOUT_ARG_INDEX), \
        "BTF001 dropped a call name the old string check covered"
