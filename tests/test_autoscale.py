"""Elastic fleet: the closed-loop autoscaler (fleet/autoscale.py) and
the runtime spawn/retire path it drives (fleet/harness.py).

Two layers, matching the two-tier suite:

* Control-loop units on a FAKE pool: a real ReplicaPool object whose
  scrape rings are hand-fed and whose spawn/retire are counters — every
  decision branch (band, bounds, shed floor, cooldowns, victim choice,
  replica-seconds integral) is pinned with injectable time. These are
  the tests that must kill the mutcheck mutant inverting the
  scale-down hysteresis guard.
* Live in-process fleets (slow-marked in conftest.py): spawn joins and
  serves, retire drains without dropping a request, and the full
  closed loop reshapes a real topology both directions.
"""
import threading
import urllib.request

import pytest

from butterfly_tpu.fleet.autoscale import Autoscaler, TierPolicy
from butterfly_tpu.obs.registry import MetricsRegistry
from butterfly_tpu.obs.ticklog import FlightRecorder
from butterfly_tpu.router.pool import ReplicaPool


# ---------------------------------------------------------------------------
# control-loop units (fake pool, injectable time)
# ---------------------------------------------------------------------------

class FakeState:
    """The slice of ControlPlaneState the autoscaler consumes."""

    def __init__(self, pool):
        self.pool = pool
        self.registry = MetricsRegistry()
        self.flightrec = FlightRecorder()


def make_pool(roles):
    """Pool of fake members (never started — no probes, no HTTP), one
    per role, ports counting up from 9001."""
    specs = [f"127.0.0.1:{9001 + i}" for i in range(len(roles))]
    pool = ReplicaPool(specs, probe_interval=999.0)
    for spec, role in zip(specs, roles):
        pool.replicas[spec].role = role
    return pool


def feed(pool, rid, signal, values):
    """Append fake scrape-ring samples for one replica."""
    for i, v in enumerate(values):
        pool.replicas[rid].series.append(
            {"t_wall": float(i), "signals": {signal: float(v)}})


class Fleet:
    """Fake spawn/retire: mutates pool membership and records calls."""

    def __init__(self, pool):
        self.pool = pool
        self.spawned = []
        self.retired = []
        self._next_port = 9500

    def spawn(self, role):
        rid = f"127.0.0.1:{self._next_port}"
        self._next_port += 1
        self.pool.add(rid)
        self.pool.replicas[rid].role = role
        self.spawned.append((role, rid))
        return rid

    def retire(self, rid):
        self.pool.remove(rid)
        self.retired.append(rid)
        return True


def make_scaler(roles, policies, **kw):
    pool = make_pool(roles)
    state = FakeState(pool)
    fleet = Fleet(pool)
    a = Autoscaler(state, fleet.spawn, fleet.retire, policies, **kw)
    return a, pool, fleet, state


def decision(step_out, role):
    (d,) = [d for d in step_out if d.tier == role]
    return d


def test_policy_validation():
    with pytest.raises(ValueError):
        TierPolicy("decode", min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        TierPolicy("decode", high=1.0, low=2.0)  # inverted band
    with pytest.raises(ValueError):
        Autoscaler(FakeState(make_pool(["decode"])), None, None,
                   [TierPolicy("decode"), TierPolicy("decode")])


def test_scale_up_on_sustained_high_signal():
    pol = TierPolicy("decode", min_replicas=1, max_replicas=3,
                     high=4.0, low=0.5, window=3, cooldown_up_s=0.0)
    a, pool, fleet, state = make_scaler(["decode"], [pol])
    feed(pool, "127.0.0.1:9001", "queue_depth", [6, 7, 8])
    d = decision(a.step(now=100.0), "decode")
    assert d.direction == "up" and d.reason == "signal_high"
    assert fleet.spawned == [("decode", d.rid)]
    assert len(pool.replicas) == 2
    # the decision is in the flight recorder with its evidence
    events = state.flightrec.dump().get("events", [])
    scales = [e for e in events if e.get("kind") == "scale"]
    assert scales and scales[-1]["tier"] == "decode"
    assert scales[-1]["direction"] == "up"
    assert scales[-1]["reason"] == "signal_high"


def test_in_band_signal_holds():
    pol = TierPolicy("decode", high=4.0, low=0.5, window=3)
    a, pool, fleet, _ = make_scaler(["decode", "decode"], [pol])
    for rid in list(pool.replicas):
        feed(pool, rid, "queue_depth", [1, 2, 2])
    d = decision(a.step(now=100.0), "decode")
    assert d.direction is None and d.reason == "in_band"
    assert not fleet.spawned and not fleet.retired


def test_no_ring_data_holds():
    pol = TierPolicy("decode", high=4.0, low=0.5)
    a, _, fleet, _ = make_scaler(["decode"], [pol])
    d = decision(a.step(now=100.0), "decode")
    assert d.direction is None and d.reason == "no_data"
    assert not fleet.spawned


def test_scale_down_hysteresis_cooldown():
    """The mutcheck anchor: a shrink is refused until a FULL
    cooldown_down_s has passed since the tier's last scale action, and
    allowed after. Both branches asserted, so inverting the guard
    (acting inside the window, holding outside it) fails either way."""
    pol = TierPolicy("decode", min_replicas=1, max_replicas=3,
                     high=4.0, low=0.5, window=2,
                     cooldown_up_s=0.0, cooldown_down_s=10.0)
    a, pool, fleet, _ = make_scaler(["decode"], [pol])
    feed(pool, "127.0.0.1:9001", "queue_depth", [9, 9])
    assert decision(a.step(now=100.0), "decode").direction == "up"

    # tier goes idle immediately after the grow
    for rid in list(pool.replicas):
        pool.replicas[rid].series.clear()
        feed(pool, rid, "queue_depth", [0, 0])

    # inside the window: wanted down, must HOLD
    d = decision(a.step(now=104.0), "decode")
    assert d.direction is None and d.reason == "cooldown_down"
    assert not fleet.retired and len(pool.replicas) == 2

    # outside the window: the shrink goes through
    d = decision(a.step(now=111.0), "decode")
    assert d.direction == "down" and d.reason == "signal_low"
    assert len(fleet.retired) == 1 and len(pool.replicas) == 1


def test_scale_up_cooldown_rate_limits_growth():
    pol = TierPolicy("decode", min_replicas=1, max_replicas=4,
                     high=4.0, low=0.5, window=2, cooldown_up_s=5.0)
    a, pool, fleet, _ = make_scaler(["decode"], [pol])
    feed(pool, "127.0.0.1:9001", "queue_depth", [9, 9])
    assert decision(a.step(now=100.0), "decode").direction == "up"
    # still saturated 1s later: held, not a spawn storm
    d = decision(a.step(now=101.0), "decode")
    assert d.direction is None and d.reason == "cooldown_up"
    assert len(fleet.spawned) == 1
    assert decision(a.step(now=106.0), "decode").direction == "up"


def test_bounds_cap_and_floor():
    pol = TierPolicy("decode", min_replicas=1, max_replicas=2,
                     high=4.0, low=0.5, window=2, cooldown_up_s=0.0,
                     cooldown_down_s=0.0)
    a, pool, fleet, _ = make_scaler(["decode", "decode"], [pol])
    for rid in list(pool.replicas):
        feed(pool, rid, "queue_depth", [9, 9])
    d = decision(a.step(now=100.0), "decode")
    assert d.direction is None and d.reason == "at_max"

    for rid in list(pool.replicas):
        pool.replicas[rid].series.clear()
        feed(pool, rid, "queue_depth", [0, 0])
    assert decision(a.step(now=101.0), "decode").direction == "down"
    # now at min: idle no longer shrinks
    d = decision(a.step(now=102.0), "decode")
    assert d.direction is None and d.reason == "at_min"
    assert len(pool.replicas) == 1


def test_below_min_spawns_ignoring_cooldown():
    """min_replicas is a bound, not a suggestion: an empty tier (the
    '0p4d' elastic starting shape, or after a crash) refills even
    inside the up-cooldown."""
    pol = TierPolicy("prefill", min_replicas=1, max_replicas=2,
                     cooldown_up_s=1e9)
    a, pool, fleet, _ = make_scaler(["decode"], [pol])
    d = decision(a.step(now=100.0), "prefill")
    assert d.direction == "up" and d.reason == "below_min"
    assert fleet.spawned[0][0] == "prefill"


def test_shed_floor_forces_scale_up():
    """PR 8's admission shedding is the backpressure floor: a tier
    whose replicas return 429s scales up even with the gauge in band."""
    pol = TierPolicy("decode", min_replicas=1, max_replicas=3,
                     high=4.0, low=0.5, window=2, cooldown_up_s=0.0)
    a, pool, fleet, _ = make_scaler(["decode"], [pol])
    rid = "127.0.0.1:9001"
    feed(pool, rid, "queue_depth", [1, 1])  # in band

    def shed_families(total):
        return {"butterfly_shed_total": {
            "type": "counter",
            "samples": {("butterfly_shed_total",
                         (("priority", "batch"),)): float(total)}}}

    pool.replicas[rid].metrics_families = shed_families(5)
    # first sight of the counter only establishes the baseline
    d = decision(a.step(now=100.0), "decode")
    assert d.direction is None and d.reason == "in_band"

    pool.replicas[rid].metrics_families = shed_families(9)  # 4 new sheds
    d = decision(a.step(now=101.0), "decode")
    assert d.direction == "up" and d.reason == "shed_floor"
    assert len(fleet.spawned) == 1


def test_tiers_scale_independently_same_step():
    pols = [TierPolicy("prefill", min_replicas=1, max_replicas=3,
                       high=4.0, low=0.5, window=2, cooldown_up_s=0.0),
            TierPolicy("decode", min_replicas=1, max_replicas=3,
                       high=4.0, low=0.5, window=2, cooldown_down_s=0.0)]
    a, pool, fleet, _ = make_scaler(["prefill", "decode", "decode"], pols)
    feed(pool, "127.0.0.1:9001", "queue_depth", [9, 9])     # prefill hot
    feed(pool, "127.0.0.1:9002", "queue_depth", [0, 0])     # decode idle
    feed(pool, "127.0.0.1:9003", "queue_depth", [0, 0])
    out = a.step(now=100.0)
    assert decision(out, "prefill").direction == "up"
    assert decision(out, "decode").direction == "down"
    assert fleet.spawned[0][0] == "prefill"
    roles = [r.role for r in pool.replicas.values()]
    assert roles.count("prefill") == 2 and roles.count("decode") == 1


def test_retire_victim_is_least_loaded():
    pol = TierPolicy("decode", min_replicas=1, max_replicas=3,
                     high=4.0, low=1.0, window=2, cooldown_down_s=0.0)
    a, pool, fleet, _ = make_scaler(["decode", "decode"], [pol])
    busy, idle = "127.0.0.1:9001", "127.0.0.1:9002"
    feed(pool, busy, "queue_depth", [0.5, 0.5])
    feed(pool, idle, "queue_depth", [0.0, 0.0])
    pool.replicas[busy].outstanding = 2
    assert decision(a.step(now=100.0), "decode").direction == "down"
    assert fleet.retired == [idle]


def test_failed_action_leaves_shape_and_loop_alive():
    pol = TierPolicy("decode", min_replicas=1, max_replicas=3,
                     high=4.0, low=0.5, window=2, cooldown_up_s=0.0)
    pool = make_pool(["decode"])
    state = FakeState(pool)

    def bad_spawn(role):
        raise RuntimeError("no capacity")

    a = Autoscaler(state, bad_spawn, lambda rid: True, [pol])
    feed(pool, "127.0.0.1:9001", "queue_depth", [9, 9])
    d = decision(a.step(now=100.0), "decode")
    assert d.direction is None and d.reason == "action_failed"
    assert len(pool.replicas) == 1
    kinds = [e.get("kind") for e in state.flightrec.dump()["events"]]
    assert "scale_error" in kinds
    # next step still evaluates (and would act if spawn recovered)
    assert decision(a.step(now=101.0), "decode").reason in (
        "action_failed", "signal_high")


def test_replica_seconds_integral_and_stats():
    pol = TierPolicy("decode", min_replicas=1, max_replicas=3)
    a, pool, fleet, _ = make_scaler(["decode", "decode"], [pol])
    a.step(now=100.0)
    a.step(now=110.0)   # 2 replicas x 10s
    fleet.spawn("decode")
    a.step(now=115.0)   # 3 replicas x 5s
    assert a.replica_seconds == pytest.approx(2 * 10 + 3 * 5)
    s = a.stats()
    assert s["replica_seconds"] == pytest.approx(35.0)
    assert s["steps"] == 3


def test_autoscale_metrics_exported():
    pol = TierPolicy("decode", min_replicas=1, max_replicas=3,
                     high=4.0, low=0.5, window=2, cooldown_up_s=0.0)
    a, pool, fleet, state = make_scaler(["decode"], [pol])
    feed(pool, "127.0.0.1:9001", "queue_depth", [9, 9])
    a.step(now=100.0)
    a.step(now=101.0)
    text = state.registry.render()
    assert 'butterfly_fleet_autoscale_decisions_total{' in text
    assert 'tier="decode"' in text and 'direction="up"' in text
    assert "butterfly_fleet_autoscale_replica_seconds_total" in text


# ---------------------------------------------------------------------------
# live fleets (slow tier): spawn joins, retire drains, loop closes
# ---------------------------------------------------------------------------

PAGE = 8


def post_completion(url, prompt_tokens, max_new=4, timeout=60):
    import json
    body = json.dumps({"tokens": prompt_tokens, "max_tokens": max_new,
                       "stop_token": -1}).encode()
    req = urllib.request.Request(
        url + "/generate", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def test_spawned_replica_joins_and_serves():
    from butterfly_tpu.fleet.harness import start_fleet
    fleet = start_fleet("1p1d", page_size=PAGE, max_batch=2, max_seq=128,
                        warm=True)
    try:
        h = fleet.spawn("decode")
        assert h.rid in fleet.state.pool.replicas
        assert fleet.state.pool.replicas[h.rid].role == "decode"
        assert h.rid in fleet.rids and len(fleet.replicas) == 3
        # the new member serves directly (it was warmed before joining)
        r = post_completion(h.url, [7] * 12)
        assert len(r["tokens"]) == 4
        # and the control plane routes across the grown pool
        r = post_completion(fleet.url, [7] * 12)
        assert len(r["tokens"]) == 4
    finally:
        fleet.stop()


def test_retire_drains_without_dropping_requests():
    """Shrink mid-traffic: every request issued before AND during the
    retire completes; the retired member leaves the pool."""
    from butterfly_tpu.fleet.harness import start_fleet
    fleet = start_fleet("3", page_size=PAGE, max_batch=2, max_seq=128,
                        warm=True)
    try:
        victim = fleet.rids[-1]
        results, errors = [], []

        def client(i):
            try:
                results.append(
                    post_completion(fleet.url, [3 + i % 5] * 10))
            except Exception as e:  # any drop fails the test
                errors.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        assert fleet.retire(victim, timeout=30.0)
        for t in threads:
            t.join(timeout=60.0)
        assert not errors
        assert len(results) == 8
        assert all(len(r["tokens"]) == 4 for r in results)
        assert victim not in fleet.state.pool.replicas
        assert victim not in fleet.rids
    finally:
        fleet.stop()


def test_autoscaler_closes_the_loop_on_a_live_fleet():
    """The full circuit: scraped rings -> policy -> spawn/retire on a
    real topology, both directions, decisions in the flight recorder."""
    import time as _time
    from butterfly_tpu.fleet.harness import start_fleet
    fleet = start_fleet("1p1d", page_size=PAGE, max_batch=2, max_seq=128,
                        warm=True, probe_interval=0.1)
    try:
        pol = TierPolicy("decode", min_replicas=1, max_replicas=2,
                         signal="queue_depth", high=0.5, low=0.1,
                         window=2, cooldown_up_s=0.0, cooldown_down_s=0.2)
        a = Autoscaler(fleet.state, fleet.spawn, fleet.retire, [pol])
        dec_rid = [r.rid for r in fleet.replicas if r.role == "decode"][0]
        # saturate the decode tier so scraped queue_depth rises
        stop = threading.Event()

        def pressure():
            while not stop.is_set():
                try:
                    post_completion(fleet.by_rid[dec_rid].url,
                                    [5] * 16, max_new=8)
                except Exception:
                    pass

        threads = [threading.Thread(target=pressure) for _ in range(4)]
        for t in threads:
            t.start()
        grew = False
        deadline = _time.monotonic() + 30.0
        while _time.monotonic() < deadline:
            if any(d.direction == "up" for d in a.step()):
                grew = True
                break
            _time.sleep(0.15)
        stop.set()
        for t in threads:
            t.join(timeout=30.0)
        assert grew, "autoscaler never grew the saturated decode tier"
        roles = [r.role for r in fleet.state.pool.replicas.values()]
        assert roles.count("decode") == 2

        # load gone: the tier shrinks back once rings show idle and the
        # hysteresis window passes
        shrank = False
        deadline = _time.monotonic() + 30.0
        while _time.monotonic() < deadline:
            if any(d.direction == "down" for d in a.step()):
                shrank = True
                break
            _time.sleep(0.15)
        assert shrank, "autoscaler never shrank the idle decode tier"
        roles = [r.role for r in fleet.state.pool.replicas.values()]
        assert roles.count("decode") == 1
        # both decisions are auditable in the control-plane recorder
        kinds = [(e.get("kind"), e.get("direction"))
                 for e in fleet.state.flightrec.dump()["events"]]
        assert ("scale", "up") in kinds and ("scale", "down") in kinds
    finally:
        fleet.stop()


def test_autoscale_benchmark_beats_static_peak():
    """ISSUE 17 acceptance: ramp-arrival soak where the autoscaler
    holds SLO attainment at the objective while spending fewer
    replica-seconds than a static fleet provisioned at the peak shape,
    with the decisions auditable via /debug/flightrecorder."""
    from butterfly_tpu.obs.benchmark import run_autoscale_benchmark
    out = run_autoscale_benchmark()
    assert out["autoscale_dropped"] == 0
    assert out["autoscale_slo_attainment"] == 1.0
    assert out["autoscale_scale_ups"] >= 1
    assert out["autoscale_replica_seconds"] \
        < out["autoscale_static_peak_replica_seconds"]
    assert out["autoscale_flightrec_scale_events"] >= 1


def test_parse_topology_arbitrary_shapes():
    from butterfly_tpu.fleet.harness import parse_topology
    assert parse_topology("2p2d") == ["prefill"] * 2 + ["decode"] * 2
    assert parse_topology("3p5d") == ["prefill"] * 3 + ["decode"] * 5
    assert parse_topology("0p4d") == ["decode"] * 4
    assert parse_topology("2p0d") == ["prefill"] * 2
    assert parse_topology(" 1P1D ") == ["prefill", "decode"]
    assert parse_topology("4") == ["both"] * 4
    for bad in ("0p0d", "0", "pd", "2p2", "x"):
        with pytest.raises(ValueError):
            parse_topology(bad)
