"""Unified mixed dispatch (ISSUE 18): prefill chunks and decode blocks
in ONE fused program per tick.

The contract under test:

* token parity — mixed dispatch (the default) must produce EXACTLY the
  greedy tokens the alternating prefill/decode path produces, across
  fresh/warm/ragged gangs x chunk width x kv dtype x spec x
  write-combined window (the alternating path is the parity reference
  the `mixed_dispatch=False` knob keeps reachable);
* the admission-cause drain barrier is retired as a class — a mixed run
  records ZERO `drain_barriers_total{cause="admission"}`;
* one device dispatch per tick in steady mixed state (the spy test):
  no separate prefill dispatch, no admission drain;
* `prefill_inline_budget` caps CONCURRENT prefill lanes (the ITL-tail
  knob) — the mutcheck drop-the-budget mutant must die here;
* mid-prefill preemption and cancel under the fused block keep the
  flush-before-reclaim invariant (exercised with kv_write_combine on).
"""
import jax
import numpy as np
import pytest

from butterfly_tpu.core.config import RuntimeConfig, tiny
from butterfly_tpu.engine.serving import ServingEngine
from butterfly_tpu.models.common import Model
from butterfly_tpu.sched.scheduler import Scheduler

CFG = tiny("llama", dtype="float32", param_dtype="float32")
_PARAMS = None


def params():
    global _PARAMS
    if _PARAMS is None:
        _PARAMS = Model(CFG).init(jax.random.PRNGKey(42))
    return _PARAMS


def make_sched(max_batch=3, max_seq=96, page=8, num_pages=0, seed=0,
               **rt_kw):
    rt = RuntimeConfig(max_batch_size=max_batch, max_seq_len=max_seq,
                       page_size=page, num_pages=num_pages, **rt_kw)
    return Scheduler(ServingEngine(Model(CFG), params(), rt), seed=seed)


# -- gang scenarios -----------------------------------------------------------
# Each scenario submits a staggered load whose admissions land while
# decode blocks are in flight — the exact state mixed dispatch fuses.

def _run_fresh(sched):
    """Fresh gang: cold prompts of equal-ish length admitted mid-flight."""
    r1 = sched.submit([5, 7, 11], max_new_tokens=8)
    for _ in range(2):
        sched.tick()
    r2 = sched.submit(list(range(1, 20)), max_new_tokens=6)
    r3 = sched.submit([9, 2, 4], max_new_tokens=5)
    sched.run_until_done()
    return [r1.output, r2.output, r3.output]


def _run_ragged(sched):
    """Ragged gang: wildly different prompt lengths admitted together,
    so prefill lanes complete on different scan steps of one block."""
    r1 = sched.submit([3], max_new_tokens=7)
    r2 = sched.submit(list(range(2, 35)), max_new_tokens=6)
    for _ in range(2):
        sched.tick()
    r3 = sched.submit(list(range(40, 49)), max_new_tokens=8)
    sched.run_until_done()
    return [r1.output, r2.output, r3.output]


def _run_warm(sched):
    """Warm gang (requires prefix_caching): the second wave shares the
    first wave's prompt prefix, so admission attaches cached pages and
    the chunk cursor starts past zero."""
    base = list(range(1, 17))
    r1 = sched.submit(base + [61], max_new_tokens=6)
    sched.run_until_done()
    r2 = sched.submit(base + [67, 3], max_new_tokens=7)
    for _ in range(1):
        sched.tick()
    r3 = sched.submit(base + [71], max_new_tokens=5)
    sched.run_until_done()
    return [r1.output, r2.output, r3.output]


SCENARIOS = {"fresh": _run_fresh, "ragged": _run_ragged, "warm": _run_warm}

#: the parity grid: every dimension value (scenario, chunk 8/16,
#: f32/int8, spec on/off, window on/off) appears at least twice,
#: without paying the full 48-point cross product on CPU.
GRID = [
    ("fresh", dict(prefill_chunk=8, prefill_inline_budget=8)),
    ("fresh", dict(prefill_chunk=16, prefill_inline_budget=16,
                   kv_quant="int8", speculative_gamma=3)),
    ("ragged", dict(prefill_chunk=16, prefill_inline_budget=16,
                    kv_quant="int8", kv_write_combine=True)),
    ("ragged", dict(prefill_chunk=8, prefill_inline_budget=8,
                    kv_quant="int8", speculative_gamma=3,
                    kv_write_combine=True)),
    ("warm", dict(prefill_chunk=8, prefill_inline_budget=8,
                  prefix_caching=True, kv_write_combine=True)),
    ("warm", dict(prefill_chunk=16, prefill_inline_budget=16,
                  prefix_caching=True, speculative_gamma=3)),
]


@pytest.mark.parametrize("scenario,rt_kw", GRID,
                         ids=[f"{s}-" + "-".join(sorted(k for k in kw))
                              for s, kw in GRID])
def test_mixed_vs_alternating_token_parity(scenario, rt_kw):
    run = SCENARIOS[scenario]
    alt = run(make_sched(mixed_dispatch=False, **rt_kw))
    sched = make_sched(mixed_dispatch=True, **rt_kw)
    mix = run(sched)
    assert mix == alt
    # the tentpole's headline: admission-cause barriers retired
    assert sched.barrier_causes().get("admission", 0) == 0


def test_alternating_path_unchanged_barriers():
    """The parity reference still barriers on admission — the knob
    really selects the old path."""
    sched = make_sched(mixed_dispatch=False)
    _run_fresh(sched)
    assert sched.barrier_causes().get("admission", 0) >= 1


def test_mixed_seeded_sampling_reproducible():
    """temperature > 0 under mixed dispatch diverges from the
    alternating RNG stream by design but must stay seed-deterministic."""
    def run(seed):
        sched = make_sched(seed=seed)
        r1 = sched.submit([5, 7, 11], max_new_tokens=8, temperature=0.8)
        sched.tick()
        r2 = sched.submit(list(range(1, 14)), max_new_tokens=6,
                          temperature=0.8)
        sched.run_until_done()
        return [r1.output, r2.output]
    assert run(0) == run(0)
    assert run(0) != run(7)  # and the seed actually matters


# -- one fused dispatch per tick ---------------------------------------------

def test_one_dispatch_per_tick_steady_mixed(monkeypatch):
    """Dispatch-count spy: in steady mixed state (decode in flight,
    prompts arriving) each tick issues EXACTLY ONE fused device
    dispatch — no separate prefill dispatch, no admission barrier."""
    sched = make_sched(max_batch=3)
    eng = sched.engine
    counts = {"mixed": 0, "prefill": 0, "decode": 0}
    orig_mixed = eng.mixed_block_async
    orig_prefill = eng.prefill_batch
    orig_decode = eng.decode_block_async
    monkeypatch.setattr(eng, "mixed_block_async",
                        lambda *a, **k: (counts.__setitem__(
                            "mixed", counts["mixed"] + 1)
                            or orig_mixed(*a, **k)))
    monkeypatch.setattr(eng, "prefill_batch",
                        lambda *a, **k: (counts.__setitem__(
                            "prefill", counts["prefill"] + 1)
                            or orig_prefill(*a, **k)))
    monkeypatch.setattr(eng, "decode_block_async",
                        lambda *a, **k: (counts.__setitem__(
                            "decode", counts["decode"] + 1)
                            or orig_decode(*a, **k)))
    sched.submit([5, 7, 11], max_new_tokens=20)
    sched.tick()
    sched.submit(list(range(1, 18)), max_new_tokens=20)
    sched.submit([9, 2], max_new_tokens=20)
    for _ in range(6):
        before = counts["mixed"]
        sched.tick()
        assert counts["mixed"] - before <= 1
    assert counts["prefill"] == 0  # prompts rode the fused blocks
    assert counts["decode"] == 0   # the alternating program never ran
    assert counts["mixed"] >= 5
    assert sched.barrier_causes().get("admission", 0) == 0


# -- the ITL-tail knob --------------------------------------------------------

def test_inline_budget_caps_concurrent_prefill():
    """prefill_inline_budget bounds CONCURRENT prefill lanes: with
    budget == chunk width, at most ONE slot may chew prompt chunks at a
    time no matter how many slots are free. Kills the mutcheck
    drop-the-budget mutant (cap -> num_slots)."""
    sched = make_sched(max_batch=4, max_seq=96,
                       prefill_chunk=8, prefill_inline_budget=8)
    assert sched._mixed_max_pf == 1
    reqs = [sched.submit(list(range(1 + 20 * i, 19 + 20 * i)),
                         max_new_tokens=4) for i in range(4)]
    seen_pf = 0
    for _ in range(60):
        if not sched.has_work:
            break
        sched.tick()
        pf = len(sched._prefill_group)
        seen_pf = max(seen_pf, pf)
        assert pf <= 1, "inline budget must cap concurrent prefill lanes"
    assert all(r.state == "finished" for r in reqs)
    assert seen_pf == 1
    # a wider budget admits wider gangs: the knob is live in BOTH
    # directions (budget 32 / chunk 8 -> 4 concurrent lanes allowed)
    wide = make_sched(max_batch=4, max_seq=96,
                      prefill_chunk=8, prefill_inline_budget=32)
    assert wide._mixed_max_pf == 4


def test_inline_budget_parity_not_affected():
    """A starved budget (one lane at a time) changes scheduling order,
    never tokens."""
    kw = dict(max_batch=4, max_seq=96, prefill_chunk=8)
    alt = make_sched(mixed_dispatch=False, **kw)
    a = _run_fresh(alt)
    mix = make_sched(mixed_dispatch=True, prefill_inline_budget=8, **kw)
    m = _run_fresh(mix)
    assert a == m


# -- preemption / cancel under the fused block --------------------------------

def test_mid_prefill_preemption_under_mixed():
    """Page pressure preempts a mid-prefill member while its chunks ride
    an in-flight fused block: the barrier-before-reclaim contract must
    hold (drain, then preempt), and the victim's eventual output must
    still be greedy-correct after readmission."""
    kw = dict(max_batch=2, max_seq=64, page=4, num_pages=9,
              prefill_chunk=8, prefill_inline_budget=8,
              kv_write_combine=True)
    alt = make_sched(mixed_dispatch=False, **kw)
    ra1 = alt.submit([5, 7, 11], max_new_tokens=10)
    ra2 = alt.submit(list(range(1, 14)), max_new_tokens=8)
    alt.run_until_done()

    sched = make_sched(mixed_dispatch=True, **kw)
    r1 = sched.submit([5, 7, 11], max_new_tokens=10)
    r2 = sched.submit(list(range(1, 14)), max_new_tokens=8)
    sched.run_until_done()
    assert r1.state == r2.state == "finished"
    assert [r1.output, r2.output] == [ra1.output, ra2.output]
    # the tiny pool really forced preemptions in the mixed run
    assert sched.metrics().get("preemptions_total", 0) >= 1


def test_cancel_mid_prefill_under_mixed():
    """Cancelling a request whose prefill chunks are riding an
    in-flight fused block must drain first (flush-before-reclaim), free
    the slot, and leave the survivors' tokens untouched."""
    kw = dict(max_batch=3, max_seq=96, prefill_chunk=8,
              prefill_inline_budget=8, kv_write_combine=True)
    alt = make_sched(mixed_dispatch=False, **kw)
    ka = alt.submit([5, 7, 11], max_new_tokens=10)
    alt.run_until_done()

    sched = make_sched(mixed_dispatch=True, **kw)
    keep = sched.submit([5, 7, 11], max_new_tokens=10)
    sched.tick()
    victim = sched.submit(list(range(1, 30)), max_new_tokens=8)
    # the inline budget (one lane) may defer admission a tick or two
    # while keep's own prefill drains out of the group
    for _ in range(6):
        if victim.state != "waiting":
            break
        sched.tick()
    assert victim.state in ("prefilling", "running")
    sched.cancel(victim)
    assert victim.state == "cancelled"
    assert victim.slot is None
    sched.run_until_done()
    assert keep.output == ka.output
    assert sched.barrier_causes().get("cancel", 0) >= 1
    assert sched.barrier_causes().get("admission", 0) == 0


def test_mixed_spec_mid_prefill_cancel():
    """Same cancel hazard under the speculative mixed twin (history
    doubles as the prompt buffer there)."""
    kw = dict(max_batch=3, max_seq=96, speculative_gamma=3,
              prefill_chunk=8, prefill_inline_budget=8)
    alt = make_sched(mixed_dispatch=False, **kw)
    ka = alt.submit([5, 7, 11], max_new_tokens=10)
    alt.run_until_done()

    sched = make_sched(mixed_dispatch=True, **kw)
    keep = sched.submit([5, 7, 11], max_new_tokens=10)
    sched.tick()
    victim = sched.submit(list(range(1, 30)), max_new_tokens=8)
    sched.tick()
    sched.cancel(victim)
    assert victim.state == "cancelled"
    sched.run_until_done()
    assert keep.output == ka.output


# -- carry hygiene ------------------------------------------------------------

def test_slot_reuse_reseeds_mixed_carries():
    """A freed slot re-admitted by a later request must reseed the
    cursor/plen/prompt-row carries: back-to-back waves through the same
    slots stay greedy-correct."""
    kw = dict(max_batch=1, max_seq=96, prefill_chunk=8,
              prefill_inline_budget=8)
    alt = make_sched(mixed_dispatch=False, **kw)
    outs_alt = []
    for p in ([5, 7, 11], list(range(1, 16)), [9, 2]):
        r = alt.submit(p, max_new_tokens=5)
        alt.run_until_done()
        outs_alt.append(r.output)

    sched = make_sched(mixed_dispatch=True, **kw)
    reqs = [sched.submit(p, max_new_tokens=5)
            for p in ([5, 7, 11], list(range(1, 16)), [9, 2])]
    sched.run_until_done()
    assert [r.output for r in reqs] == outs_alt


def test_stateful_draft_falls_back_to_alternating():
    """A stateful (model) draft source cannot reseed inside the fused
    block: mixed_dispatch stays requested but the engine reports not
    ready and the scheduler runs the alternating path (parity with an
    explicit mixed_dispatch=False run)."""
    kw = dict(max_batch=2, max_seq=96, speculative_gamma=3,
              draft_model="model")
    sched = make_sched(mixed_dispatch=True, **kw)
    assert not sched.engine.mixed_dispatch_ready
    assert not sched._mixed_mode
    r = sched.submit([5, 7, 11], max_new_tokens=6)
    sched.run_until_done()
    ref = make_sched(mixed_dispatch=False, **kw)
    rr = ref.submit([5, 7, 11], max_new_tokens=6)
    ref.run_until_done()
    assert r.output == rr.output


def test_mixed_tick_phase_recorded():
    """The fused dispatch attributes its host section to the 'mixed'
    tick phase (not 'dispatch'), and the metrics surface exports it."""
    sched = make_sched()
    sched.submit([5, 7, 11], max_new_tokens=6)
    sched.run_until_done()
    dump = sched.ticklog.dump()
    assert "mixed" in dump["phases"]
    assert any(t["phases"].get("mixed", 0.0) > 0.0 for t in dump["ticks"])
    assert all(t["phases"].get("dispatch", 0.0) == 0.0
               for t in dump["ticks"])
    m = sched.metrics()
    assert "tick_phase_mixed_p50" in m
