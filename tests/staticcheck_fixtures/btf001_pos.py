"""BTF001 positive fixture: outbound HTTP calls with no timeout.

Expected findings: 3 (urlopen, HTTPConnection, HTTPSConnection —
including a multi-line call the old string-span grep handled only via
a hand-rolled paren scan).
"""
import http.client
import urllib.request


def probe(url, host, port, headers):
    resp = urllib.request.urlopen(url)                       # 1
    conn = http.client.HTTPConnection(host, port)            # 2
    conn2 = http.client.HTTPSConnection(
        host,
        port,
    )                                                        # 3
    return resp, conn, conn2
