"""BTF001 positive fixture: outbound HTTP calls with no timeout.

Expected findings: 4 (urlopen, HTTPConnection, HTTPSConnection —
including a multi-line call the old string-span grep handled only via
a hand-rolled paren scan — and a Request-object urlopen in a control
loop, the shape the autoscaler uses to pull a replica's flight
recorder: a hung replica would wedge every subsequent scale decision).
"""
import http.client
import urllib.request


def probe(url, host, port, headers):
    resp = urllib.request.urlopen(url)                       # 1
    conn = http.client.HTTPConnection(host, port)            # 2
    conn2 = http.client.HTTPSConnection(
        host,
        port,
    )                                                        # 3
    return resp, conn, conn2


def pull_flightrecorder(base):
    req = urllib.request.Request(base + "/debug/flightrecorder")
    with urllib.request.urlopen(
            req) as resp:                                    # 4
        return resp.read()
