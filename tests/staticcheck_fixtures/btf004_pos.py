"""BTF004 positive fixture: lock-discipline violations.

Expected findings: 7 — an unbounded .acquire(), network I/O under a
lock, a raw `with state.lock:` in a handler class, two unlocked
instrument writes in a handler class, a host-tier pull that fetches
pages from a peer while holding the tier lock (every allocator waiting
on that lock inherits the peer's latency), and an unlocked histogram
observe in a handler class.
"""
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler


class State:
    def __init__(self):
        self.lock = threading.Lock()

    def bad_acquire(self):
        self.lock.acquire()                                  # 1

    def bad_io(self, url):
        with self.lock:
            urllib.request.urlopen(url, timeout=1.0)         # 2


def make_handler(state):
    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            with state.lock:                                 # 3
                n = len(state.waiting)
            state._c_requests.inc()                          # 4
            state._g_depth.set(n)                            # 5

    return Handler


class HostTier:
    def __init__(self):
        self._lock = threading.Lock()
        self._chains = {}

    def pull_from_peer(self, url, chain):
        with self._lock:
            body = urllib.request.urlopen(url, timeout=5.0)  # 6
            self._chains[chain] = body.read()


def make_kv_handler(state):
    class KvHandler(BaseHTTPRequestHandler):
        def do_POST(self):
            state._h_restore.observe(0.01)                   # 7

    return KvHandler
