"""BTF004 positive fixture: lock-discipline violations.

Expected findings: 5 — an unbounded .acquire(), network I/O under a
lock, a raw `with state.lock:` in a handler class, and two unlocked
instrument writes in a handler class.
"""
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler


class State:
    def __init__(self):
        self.lock = threading.Lock()

    def bad_acquire(self):
        self.lock.acquire()                                  # 1

    def bad_io(self, url):
        with self.lock:
            urllib.request.urlopen(url, timeout=1.0)         # 2


def make_handler(state):
    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            with state.lock:                                 # 3
                n = len(state.waiting)
            state._c_requests.inc()                          # 4
            state._g_depth.set(n)                            # 5

    return Handler
