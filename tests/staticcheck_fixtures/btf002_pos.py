"""BTF002 positive fixture: reads of donated references after dispatch.

Expected findings: 4 —
* a read of the donated cache in the statement after the dispatch,
* the same handle re-passed on the next loop iteration without rebind,
* a read of a tree donated to a locally-built donating jit,
* a window-carry dispatch (ISSUE 12: factory program donating the
  cache AND the staged-window buffers) that rebinds the cache but
  reads the donated window attribute afterwards.
"""
import jax


def _step(params, toks, cache):
    return toks, toks, cache


class Engine:
    def __init__(self):
        self._decode = jax.jit(_step, donate_argnums=(2,))

    def read_after_dispatch(self, params, toks, cache):
        nxt, logits, new_cache = self._decode(params, toks, cache)
        return nxt, cache.lengths                     # finding 1

    def stale_loop_operand(self, params, toks, cache):
        out = []
        for _ in range(4):
            # donates `cache` but rebinds `cache2`: iteration t+1
            # passes the freed buffer again
            nxt, logits, cache2 = self._decode(params, toks, cache)
            out.append(nxt)                           # finding 2 (cache)
        return out


def local_jit(tree):
    cast = jax.jit(lambda p: p, donate_argnums=(0,))
    out = cast(tree)
    return out, tree                                  # finding 3


def _step_win(params, toks, cache, window, wlen):
    return toks, toks, cache, window, wlen


class WindowEngine:
    """The write-combined-window carry: one program donates the cache
    AND the staged-window buffer + count (serving.py's
    _decode_block_win_prog shape)."""

    def __init__(self):
        self._win_progs = {}

    def _win_prog(self, k):
        prog = self._win_progs.get(k)
        if prog is None:
            prog = jax.jit(_step_win, donate_argnums=(2, 3, 4))
            self._win_progs[k] = prog
        return prog

    def stale_window_read(self, params, toks, k):
        blk, fin, cache, window, wlen = self._win_prog(k)(
            params, toks, self.cache, self._window, self._wlen)
        self.cache = cache          # cache rebound...
        return blk, self._window    # finding 4: window NOT rebound
