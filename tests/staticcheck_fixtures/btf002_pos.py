"""BTF002 positive fixture: reads of donated references after dispatch.

Expected findings: 8 —
* a read of the donated cache in the statement after the dispatch,
* the same handle re-passed on the next loop iteration without rebind,
* a read of a tree donated to a locally-built donating jit,
* a window-carry dispatch (ISSUE 12: factory program donating the
  cache AND the staged-window buffers) that rebinds the cache but
  reads the donated window attribute afterwards,
* a spec-block dispatch (ISSUE 14: factory program donating the
  history carry AND the draft-model KV cache) that rebinds the
  history but reads the donated draft cache afterwards,
* a mixed-dispatch block (ISSUE 18: factory program donating the
  per-slot prefill chunk-offset cursor alongside the cache) that
  rebinds the cache but reads the stale cursor afterwards,
* a tree-speculation dispatch (ISSUE 19: factory program donating the
  history carry, the draft KV state, AND the staged tree-KV window +
  count) that rebinds everything except the window and then reads the
  stale tree K/V,
* a seq-parallel chunk-prefill dispatch (ISSUE 20: factory program
  donating the paged KV pool AND the per-slot length vector) that
  rebinds the pool but reads the donated lengths afterwards.
"""
import jax


def _step(params, toks, cache):
    return toks, toks, cache


class Engine:
    def __init__(self):
        self._decode = jax.jit(_step, donate_argnums=(2,))

    def read_after_dispatch(self, params, toks, cache):
        nxt, logits, new_cache = self._decode(params, toks, cache)
        return nxt, cache.lengths                     # finding 1

    def stale_loop_operand(self, params, toks, cache):
        out = []
        for _ in range(4):
            # donates `cache` but rebinds `cache2`: iteration t+1
            # passes the freed buffer again
            nxt, logits, cache2 = self._decode(params, toks, cache)
            out.append(nxt)                           # finding 2 (cache)
        return out


def local_jit(tree):
    cast = jax.jit(lambda p: p, donate_argnums=(0,))
    out = cast(tree)
    return out, tree                                  # finding 3


def _step_win(params, toks, cache, window, wlen):
    return toks, toks, cache, window, wlen


class WindowEngine:
    """The write-combined-window carry: one program donates the cache
    AND the staged-window buffer + count (serving.py's
    _decode_block_win_prog shape)."""

    def __init__(self):
        self._win_progs = {}

    def _win_prog(self, k):
        prog = self._win_progs.get(k)
        if prog is None:
            prog = jax.jit(_step_win, donate_argnums=(2, 3, 4))
            self._win_progs[k] = prog
        return prog

    def stale_window_read(self, params, toks, k):
        blk, fin, cache, window, wlen = self._win_prog(k)(
            params, toks, self.cache, self._window, self._wlen)
        self.cache = cache          # cache rebound...
        return blk, self._window    # finding 4: window NOT rebound


def _step_spec(params, hist, cache, dstate):
    return hist, hist, cache, dstate


class DraftEngine:
    """The draft-model spec-block carry (ISSUE 14): one program donates
    the token-history carry AND the draft model's KV cache
    (serving.py's _spec_block_prog shape)."""

    def __init__(self):
        self._spec_progs = {}

    def _spec_prog(self, r):
        prog = self._spec_progs.get(r)
        if prog is None:
            prog = jax.jit(_step_spec, donate_argnums=(1, 3))
            self._spec_progs[r] = prog
        return prog

    def stale_draft_cache_read(self, params, r):
        toks, hist, cache, dstate = self._spec_prog(r)(
            params, self._hist, self.cache, self._draft_state)
        self._hist = hist               # history rebound...
        self.cache = cache
        return toks, self._draft_state  # finding 5: draft NOT rebound


def _step_mixed(params, toks, cursor, cache, pbuf):
    return toks, toks, cursor, cache


class MixedEngine:
    """The mixed-dispatch carry (ISSUE 18): one program donates the
    per-slot prefill chunk-offset cursor AND the cache (serving.py's
    _mixed_block_prog shape); the prompt buffer is not donated."""

    def __init__(self):
        self._mixed_progs = {}

    def _mixed_prog(self, k):
        prog = self._mixed_progs.get(k)
        if prog is None:
            prog = jax.jit(_step_mixed, donate_argnums=(2, 3))
            self._mixed_progs[k] = prog
        return prog

    def stale_cursor_read(self, params, toks, k):
        blk, fin, cursor, cache = self._mixed_prog(k)(
            params, toks, self._cursor, self.cache, self._pbuf)
        self.cache = cache          # cache rebound...
        return blk, self._cursor    # finding 6: cursor NOT rebound


def _step_tree(params, hist, cache, dstate, window, wlen):
    return hist, hist, cache, dstate, window, wlen


class TreeEngine:
    """The tree-speculation window carry (ISSUE 19): one program
    donates the history carry, the draft KV state, AND the staged
    tree-KV window + count (serving.py's _spec_tree_win_prog shape —
    rejected branches live only in the window, so a stale window read
    is a read of freed tree K/V)."""

    def __init__(self):
        self._tree_progs = {}

    def _tree_prog(self, r):
        prog = self._tree_progs.get(r)
        if prog is None:
            prog = jax.jit(_step_tree, donate_argnums=(1, 3, 4, 5))
            self._tree_progs[r] = prog
        return prog

    def stale_tree_window_read(self, params, r):
        toks, hist, cache, dstate, window, wlen = self._tree_prog(r)(
            params, self._hist, self.cache, self._draft_state,
            self._window, self._wlen)
        self._hist, self.cache = hist, cache
        self._draft_state, self._wlen = dstate, wlen
        return toks, self._window   # finding 7: tree window NOT rebound


def _step_sp(params, chunk, cache, lengths, table):
    return chunk, cache, lengths


class SeqParallelEngine:
    """The seq-parallel chunk-prefill carry (ISSUE 20): one program
    donates the paged KV pool AND the per-slot length vector
    (serving.py's _sp_chunk_prog shape); the chunk operand and the
    page table are not donated."""

    def __init__(self):
        self._sp_progs = {}

    def _sp_prog(self, c):
        prog = self._sp_progs.get(c)
        if prog is None:
            prog = jax.jit(_step_sp, donate_argnums=(2, 3))
            self._sp_progs[c] = prog
        return prog

    def stale_length_read(self, params, chunk, c):
        logits, cache, lengths = self._sp_prog(c)(
            params, chunk, self.cache, self._lengths, self._table)
        self.cache = cache            # pool rebound...
        return logits, self._lengths  # finding 8: lengths NOT rebound
