"""BTF002 positive fixture: reads of donated references after dispatch.

Expected findings: 3 —
* a read of the donated cache in the statement after the dispatch,
* the same handle re-passed on the next loop iteration without rebind,
* a read of a tree donated to a locally-built donating jit.
"""
import jax


def _step(params, toks, cache):
    return toks, toks, cache


class Engine:
    def __init__(self):
        self._decode = jax.jit(_step, donate_argnums=(2,))

    def read_after_dispatch(self, params, toks, cache):
        nxt, logits, new_cache = self._decode(params, toks, cache)
        return nxt, cache.lengths                     # finding 1

    def stale_loop_operand(self, params, toks, cache):
        out = []
        for _ in range(4):
            # donates `cache` but rebinds `cache2`: iteration t+1
            # passes the freed buffer again
            nxt, logits, cache2 = self._decode(params, toks, cache)
            out.append(nxt)                           # finding 2 (cache)
        return out


def local_jit(tree):
    cast = jax.jit(lambda p: p, donate_argnums=(0,))
    out = cast(tree)
    return out, tree                                  # finding 3
