"""BTF005 negative fixture: the seeded-substream discipline the
workload subsystem actually uses. Expected findings: 0."""
import random
import time

import numpy as np


def seeded_arrivals(seed, n):
    rng = random.Random((seed << 1) ^ 0xA55A)    # seeded constructor
    times = [rng.expovariate(8.0) for _ in range(n)]  # instance draws
    gen = np.random.default_rng(seed)            # seeded numpy
    t0 = time.monotonic()                        # elapsed, not wall
    time.sleep(0.0)
    return times, gen.normal(), time.monotonic() - t0
