"""BTF005 negative fixture: the seeded-substream discipline the
workload subsystem actually uses. Expected findings: 0."""
import random
import time

import numpy as np


def seeded_arrivals(seed, n):
    rng = random.Random((seed << 1) ^ 0xA55A)    # seeded constructor
    times = [rng.expovariate(8.0) for _ in range(n)]  # instance draws
    gen = np.random.default_rng(seed)            # seeded numpy
    t0 = time.monotonic()                        # elapsed, not wall
    time.sleep(0.0)
    return times, gen.normal(), time.monotonic() - t0


def ring_sample(ring, seq, signals, t_wall=0.0):
    # the recorder discipline: ordering from seq + monotonic; the wall
    # stamp is caller-supplied display metadata, never read here
    ring.append({"seq": seq, "t_mono": time.monotonic(),
                 "t_wall": t_wall, "signals": signals})
    return seq + 1
