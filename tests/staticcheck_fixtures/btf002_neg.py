"""BTF002 negative fixture: the blessed donation patterns — rebind in
the same statement, rebind before the next read, factory programs, and
the engine's self.cache = cache idiom. Expected findings: 0."""
import jax


def _step(params, toks, cache):
    return toks, toks, cache


class Engine:
    def __init__(self):
        self._decode = jax.jit(_step, donate_argnums=(2,))
        self._progs = {}

    def _prog(self, k):
        prog = self._progs.get(k)
        if prog is None:
            prog = jax.jit(_step, donate_argnums=(2,))
            self._progs[k] = prog
        return prog

    def same_statement_rebind(self, params, toks, cache):
        nxt, logits, cache = self._decode(params, toks, cache)
        return nxt, cache.lengths       # rebound: reads the NEW buffer

    def attr_rebind(self, params, toks):
        nxt, logits, cache = self._decode(params, toks, self.cache)
        self.cache = cache              # store clears the poison
        return nxt, self.cache.lengths

    def factory_inline(self, params, toks, k):
        nxt, logits, cache = self._prog(k)(params, toks, self.cache)
        self.cache = cache
        return nxt

    def chained_loop(self, params, toks, cache):
        out = []
        for _ in range(4):
            nxt, logits, cache = self._decode(params, toks, cache)
            out.append(nxt)
        return out, cache


def _step_win(params, toks, cache, window, wlen):
    return toks, toks, cache, window, wlen


class WindowEngine:
    """Blessed window-carry pattern (ISSUE 12): every donated carry —
    cache, staged-window buffer, staged count — is rebound from the
    result before any later read (serving.py decode_block_async)."""

    def __init__(self):
        self._win_progs = {}
        self._flush = jax.jit(_step_win, donate_argnums=(2, 4))

    def _win_prog(self, k):
        prog = self._win_progs.get(k)
        if prog is None:
            prog = jax.jit(_step_win, donate_argnums=(2, 3, 4))
            self._win_progs[k] = prog
        return prog

    def windowed_dispatch(self, params, toks, k):
        blk, fin, cache, window, wlen = self._win_prog(k)(
            params, toks, self.cache, self._window, self._wlen)
        self.cache, self._window, self._wlen = cache, window, wlen
        return blk, self._window.width

    def flush(self, params, toks):
        blk, fin, cache, window, wlen = self._flush(
            params, toks, self.cache, self._window, self._wlen)
        self.cache, self._wlen = cache, wlen
        return self._window         # NOT donated by the flush: clean read


def _step_spec(params, hist, cache, dstate):
    return hist, hist, cache, dstate


class DraftEngine:
    """Blessed draft-carry pattern (ISSUE 14): the spec program donates
    the history AND the draft-model KV cache; both rebind from the
    result before any later read (serving.py spec_block_async)."""

    def __init__(self):
        self._spec_progs = {}

    def _spec_prog(self, r):
        prog = self._spec_progs.get(r)
        if prog is None:
            prog = jax.jit(_step_spec, donate_argnums=(1, 3))
            self._spec_progs[r] = prog
        return prog

    def spec_dispatch(self, params, r):
        toks, hist, cache, dstate = self._spec_prog(r)(
            params, self._hist, self.cache, self._draft_state)
        self._hist, self.cache, self._draft_state = hist, cache, dstate
        return toks, self._draft_state.length


def _step_mixed(params, toks, cursor, cache, pbuf):
    return toks, toks, cursor, cache


class MixedEngine:
    """Blessed mixed-dispatch pattern (ISSUE 18): every donated carry —
    the prefill chunk-offset cursor AND the cache — rebinds from the
    result before any later read; the prompt buffer is NOT donated, so
    reading (or host-editing) it after the dispatch is clean
    (serving.py mixed_block_async)."""

    def __init__(self):
        self._mixed_progs = {}

    def _mixed_prog(self, k):
        prog = self._mixed_progs.get(k)
        if prog is None:
            prog = jax.jit(_step_mixed, donate_argnums=(2, 3))
            self._mixed_progs[k] = prog
        return prog

    def mixed_dispatch(self, params, toks, k):
        blk, fin, cursor, cache = self._mixed_prog(k)(
            params, toks, self._cursor, self.cache, self._pbuf)
        self._cursor, self.cache = cursor, cache
        return blk, self._cursor, self._pbuf  # all rebound / non-donated


def _step_tree(params, hist, cache, dstate, window, wlen):
    return hist, hist, cache, dstate, window, wlen


class TreeEngine:
    """Blessed tree-carry pattern (ISSUE 19): history + cache + draft
    KV state + staged tree-KV window + count ALL rebind from the
    result before any later read (serving.py spec_block_async, tree
    windowed path)."""

    def __init__(self):
        self._tree_progs = {}

    def _tree_prog(self, r):
        prog = self._tree_progs.get(r)
        if prog is None:
            prog = jax.jit(_step_tree, donate_argnums=(1, 3, 4, 5))
            self._tree_progs[r] = prog
        return prog

    def tree_dispatch(self, params, r):
        toks, hist, cache, dstate, window, wlen = self._tree_prog(r)(
            params, self._hist, self.cache, self._draft_state,
            self._window, self._wlen)
        self._hist, self.cache = hist, cache
        self._draft_state, self._window, self._wlen = \
            dstate, window, wlen
        return toks, self._window.width  # all rebound: clean reads
