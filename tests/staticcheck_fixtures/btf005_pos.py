"""BTF005 positive fixture: nondeterminism in trace-feeding code.

Expected findings: 6 — a module-global random draw, an unseeded
random.Random(), a wall-clock read, uuid4, os.urandom, and a numpy
global-state draw.
"""
import os
import random
import time
import uuid

import numpy as np


def jittered_arrival(rate):
    dt = random.expovariate(rate)            # 1: global PRNG
    rng = random.Random()                    # 2: unseeded
    t0 = time.time()                         # 3: wall clock
    rid = uuid.uuid4()                       # 4: entropy
    salt = os.urandom(8)                     # 5: entropy
    noise = np.random.normal()               # 6: numpy global state
    return dt, rng, t0, rid, salt, noise
