"""BTF005 positive fixture: nondeterminism in trace-feeding code.

Expected findings: 7 — a module-global random draw, an unseeded
random.Random(), a wall-clock read, uuid4, os.urandom, and a numpy
global-state draw, plus the ISSUE 16 time-series shape: a ring append
that stamps its ordering key from the wall clock.
"""
import os
import random
import time
import uuid

import numpy as np


def jittered_arrival(rate):
    dt = random.expovariate(rate)            # 1: global PRNG
    rng = random.Random()                    # 2: unseeded
    t0 = time.time()                         # 3: wall clock
    rid = uuid.uuid4()                       # 4: entropy
    salt = os.urandom(8)                     # 5: entropy
    noise = np.random.normal()               # 6: numpy global state
    return dt, rng, t0, rid, salt, noise


def ring_sample(ring, signals):
    # a time-series ring ordered by wall stamps is non-replayable: NTP
    # steps reorder it (the recorder orders by seq + monotonic instead)
    ring.append({"t": time.time(), "signals": signals})   # 7: wall clock
