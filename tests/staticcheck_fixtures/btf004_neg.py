"""BTF004 negative fixture: the blessed locking patterns — bounded
acquire, the scheduler thread's own `with self.lock:`, network I/O
outside the critical section, and handler instrument writes under the
metrics lock. Expected findings: 0."""
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler


class State:
    def __init__(self):
        self.lock = threading.Lock()
        self._mlock = threading.Lock()

    def acquire_lock(self, timeout=2.0):
        return self.lock.acquire(timeout=timeout)     # bounded

    def _loop(self):
        # the scheduler thread owns the device: unbounded `with` is its
        # blessed form (State is not a handler class)
        with self.lock:
            self.tick()

    def fetch_then_record(self, url):
        body = urllib.request.urlopen(url, timeout=5.0).read()
        with self._mlock:
            self._c_requests.inc()                    # locked write
        return body


def make_handler(state):
    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if state.acquire_lock():                  # bounded contract
                try:
                    n = len(state.waiting)
                finally:
                    state.lock.release()
            with state._mlock:
                state._c_requests.inc()               # locked write
                state._g_depth.set(1)

    return Handler


class HostTier:
    """The host-KV-tier idiom: the tier lock guards pure in-memory
    dict/array bookkeeping only; any peer fetch happens BEFORE taking
    it, so allocator threads never wait on a remote."""

    def __init__(self):
        self._lock = threading.Lock()
        self._chains = {}

    def pull_from_peer(self, url, chain):
        body = urllib.request.urlopen(url, timeout=5.0).read()
        with self._lock:                              # memory-only span
            self._chains[chain] = body


def make_kv_handler(state):
    class KvHandler(BaseHTTPRequestHandler):
        def do_POST(self):
            with state._mlock:
                state._h_restore.observe(0.01)        # locked observe

    return KvHandler
