"""BTF006 positive fixture: PRNG key indiscipline in sampling code.

Expected findings: 3 — a key consumed by two draws without a split, the
same key consumed once per loop iteration, and a constant PRNGKey.
"""
import jax


def correlated_draws(logits, key):
    a = jax.random.categorical(key, logits)
    b = jax.random.uniform(key, (4,))            # 1: reuse
    return a, b


def loop_reuse(logits, key):
    out = []
    for _ in range(4):
        out.append(jax.random.categorical(key, logits))  # 2: reuse/iter
    return out


def fixed_stream():
    return jax.random.PRNGKey(0)                 # 3: constant key
