"""BTF003 positive fixture: host syncs inside hot functions.

Expected findings: 9 — .item(), .tolist(), np.asarray on a non-literal,
jax.device_get, and int() over a device-carry name inside tick(), plus
the ISSUE 15 timer/ticklog paths: a ticklog record() that .tolist()s a
device value into its entry, and a flight-recorder poll() that float()s
a device carry into a trigger signal, plus the ISSUE 16 time-series
paths: a recorder sample() that .item()s a gauge off the device, and an
evaluate_rules() that float()s a device carry into a predicate, plus
the ISSUE 20 seq-parallel lane: an sp_prefill_chunk() that np.asarray()s
its chunk logits back to the host per dispatch.
"""
import jax
import numpy as np


class Sched:
    def tick(self):
        logits = self.engine.last_logits
        tok = int(logits[0])                      # 1: int over device name
        arr = np.asarray(self.engine.carry)       # 2: non-literal asarray
        val = self._probe_dev.item()              # 3: .item()
        lst = self._next_dev.tolist()             # 4: .tolist()
        jax.device_get(logits)                    # 5: device_get
        return tok, arr, val, lst


class TickLog:
    def record(self, wall_s, phases):
        # a per-tick record must never fetch device state to enrich
        # its entry — that would put a sync in every tick
        entry = {"wall_s": wall_s, "phases": dict(phases),
                 "carry": self._carry_dev.tolist()}   # 6: .tolist()
        self._ring.append(entry)


class FlightRecorder:
    def poll(self, signals):
        burn = float(self._burn_dev)                  # 7: float over _dev
        return burn >= self.threshold


class SignalRecorder:
    def sample(self, gauges, rates=None, t_wall=0.0):
        # the periodic sampler runs in the tick tail: pulling a gauge
        # straight off the device puts a sync in every sample period
        gauges["kv_pages_free"] = self._pages_dev.item()   # 8: .item()
        self._ring.append({"signals": dict(gauges)})


def evaluate_rules(rules, samples):
    for rule in rules:
        if float(rule.threshold_dev) < samples[-1]:        # 9: float/_dev
            return True
    return False


class SpEngine:
    def sp_prefill_chunk(self, slot, tokens, start):
        # the seq-parallel lane lands one chunk per tick: fetching the
        # chunk logits per dispatch serializes the whole long prefill
        # behind the host (the first token samples at the drain)
        logits = self._dispatch(slot, tokens, start)
        return np.asarray(logits)                     # 10: asarray
