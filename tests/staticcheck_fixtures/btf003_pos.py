"""BTF003 positive fixture: host syncs inside hot functions.

Expected findings: 5 — .item(), .tolist(), np.asarray on a non-literal,
jax.device_get, and int() over a device-carry name, all inside tick().
"""
import jax
import numpy as np


class Sched:
    def tick(self):
        logits = self.engine.last_logits
        tok = int(logits[0])                      # 1: int over device name
        arr = np.asarray(self.engine.carry)       # 2: non-literal asarray
        val = self._probe_dev.item()              # 3: .item()
        lst = self._next_dev.tolist()             # 4: .tolist()
        jax.device_get(logits)                    # 5: device_get
        return tok, arr, val, lst
