"""Suppression-mechanics fixture.

* `reasoned` carries a proper `# btf: disable=BTF001 <reason>` —
  its finding is SUPPRESSED.
* `bare` carries a reason-less disable — the BTF001 finding STAYS
  unsuppressed AND a BTF000 bare-suppression finding is added.
* `multiline` shows a standalone comment suppressing the whole next
  (multi-line) statement.
"""
import urllib.request


def reasoned(url):
    return urllib.request.urlopen(url)  # btf: disable=BTF001 fixture: demonstrates a reasoned suppression


def bare(url):
    return urllib.request.urlopen(url)  # btf: disable=BTF001


def multiline(url, host):
    # btf: disable=BTF001 fixture: covers the whole next statement
    return urllib.request.urlopen(
        url,
    )
