"""BTF006 negative fixture: the split/fold_in discipline the engine
uses. Expected findings: 0."""
import jax


def split_per_draw(logits, key):
    key, sub = jax.random.split(key)
    a = jax.random.categorical(sub, logits)
    key, sub = jax.random.split(key)
    b = jax.random.uniform(sub, (4,))
    return a, b


def split_per_iteration(logits, key):
    out = []
    for _ in range(4):
        key, sub = jax.random.split(key)
        out.append(jax.random.categorical(sub, logits))
    return out


def derived_in_scan(logits, key, i):
    # fold_in derives a fresh key per step — not a reuse of `key`
    return jax.random.categorical(jax.random.fold_in(key, i), logits)


def seeded(seed):
    return jax.random.PRNGKey(seed)              # variable seed: fine
