"""BTF001 negative fixture: every call carries a timeout — keyword,
positional (the stdlib signature position), or an opaque **kwargs splat
(accepted: the analyzer cannot see inside). Expected findings: 0."""
import http.client
from urllib.request import urlopen


def probe(url, host, port, kw):
    a = urlopen(url, None, 5.0)                        # positional
    b = urlopen(url, timeout=2.0)                      # keyword
    c = http.client.HTTPConnection(host, port, timeout=1.0)
    d = http.client.HTTPSConnection(host, timeout=1.0)
    e = urlopen(url, **kw)                             # splat: accepted
    with urlopen(url,
                 timeout=30) as resp:                  # multi-line kw
        resp.read()
    f = urlopen(url + "/debug/flightrecorder",
                timeout=10.0)                          # control-loop pull
    return a, b, c, d, e, f
