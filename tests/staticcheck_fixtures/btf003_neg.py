"""BTF003 negative fixture: the same sync primitives OUTSIDE the hot
set (the drain is where synchronization belongs), and the blessed
host->host operand assembly inside a hot function. Expected findings: 0.
"""
from typing import List

import numpy as np


class Sched:
    def tick(self):
        # operand assembly from host lists is host->host, not a sync
        temps = np.asarray([r.temperature for r in self.running])
        active = np.zeros((8,), bool)
        return self._decode_block(4), temps, active

    def _decode_block(self, k: int):
        budgets = np.maximum(self._base - k, 0)   # numpy math, no fetch
        return budgets

    def prefill_batch(self, slots: List[int], chunks: list):
        # annotated host-container params: asarray over them is assembly
        rows = np.asarray(slots, np.int32)
        return rows

    def _drain_blocks(self, blocks):
        # the drain is the one blessed fetch point (not a hot function)
        vals = np.asarray(self._pending)
        return vals.tolist(), int(vals[0])


class TickLog:
    def record(self, wall_s, phases):
        # the blessed tick-anatomy pattern: host floats + dict copies
        # under a tiny lock — no device value anywhere near the ring
        entry = {"wall_s": wall_s, "phases": dict(phases)}
        with self._lock:
            self._ring.append(entry)


class FlightRecorder:
    def note(self, kind, **attrs):
        ev = {"kind": kind}
        ev.update(attrs)
        self._ring.append(ev)

    def poll(self, signals):
        # trigger predicates over a HOST dict snapshot: plain compares
        burn = signals.get("slo_burn_rate", 0.0)
        return burn >= self.threshold


class SignalRecorder:
    def sample(self, gauges, rates=None, t_wall=0.0):
        # the blessed time-series pattern: caller hands in host floats
        # (registry snapshot + len()s), the ring sees no device values
        signals = dict(gauges)
        for name, cum in (rates or {}).items():
            signals[name] = max(0.0, cum - self._prev.get(name, 0.0))
        self._ring.append({"t_wall": t_wall, "signals": signals})


def evaluate_rules(rules, samples):
    # predicates over host sample dicts: plain float compares
    return [r for r in rules
            if samples and samples[-1]["signals"].get(r.signal, 0.0)
            > r.threshold]
