"""Pallas kernel tests (interpret mode on CPU: the exact kernel code path).

flash_attention and paged_attention must match the dense XLA reference
bit-for-nearly-bit; the serving stack with use_kernels=True must produce
token-identical output to the gather path.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from butterfly_tpu.core.config import RuntimeConfig, tiny
from butterfly_tpu.models.common import Model, attend
from butterfly_tpu.ops.flash_attention import flash_attention
from butterfly_tpu.ops.paged_attention import paged_attention


def causal_ref(q, k, v):
    B, T = q.shape[0], q.shape[1]
    pos = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    mask = pos[:, None, :] <= pos[:, :, None]
    return attend(q, k, v, mask, None)


@pytest.mark.parametrize("T,nq,kv,bq,bk", [
    (32, 8, 8, 16, 16),    # MHA, aligned blocks
    (50, 8, 2, 16, 16),    # GQA, ragged tail
    (17, 4, 4, 8, 8),      # tiny blocks, ragged
])
def test_flash_attention_parity(T, nq, kv, bq, bk):
    B, H = 2, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, T, nq, H))
    k = jax.random.normal(ks[1], (B, T, kv, H))
    v = jax.random.normal(ks[2], (B, T, kv, H))
    out = flash_attention(q, k, v, block_q=bq, block_k=bk)
    np.testing.assert_allclose(np.asarray(out), np.asarray(causal_ref(q, k, v)),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_noncausal():
    B, T, N, H = 1, 24, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(ks[i], (B, T, N, H)) for i in range(3))
    out = flash_attention(q, k, v, causal=False, block_q=8, block_k=8)
    ref = attend(q, k, v, jnp.ones((B, T, T), bool), None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_bf16():
    B, T, N, H = 2, 32, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q, k, v = (jax.random.normal(ks[i], (B, T, N, H), jnp.bfloat16)
               for i in range(3))
    out = flash_attention(q, k, v, block_q=16, block_k=16)
    assert out.dtype == jnp.bfloat16
    ref = causal_ref(q.astype(jnp.float32), k.astype(jnp.float32),
                     v.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


def _gather_pool(pages, table):
    """[P,Kv,page,H] pool -> [S, MP*page, Kv, H] dense view."""
    S, MP = table.shape
    P, Kv, page, H = pages.shape
    return pages[table].transpose(0, 1, 3, 2, 4).reshape(S, MP * page, Kv, H)


def test_paged_attention_parity():
    S, Nq, Kv, H, page, P, MP = 3, 8, 2, 16, 4, 10, 4
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (S, Nq, H))
    k_pages = jax.random.normal(ks[1], (P, Kv, page, H))
    v_pages = jax.random.normal(ks[2], (P, Kv, page, H))
    table = jnp.asarray([[0, 2, 9, 9], [3, 1, 4, 9], [5, 6, 7, 8]],
                        jnp.int32)
    lengths = jnp.asarray([6, 3, 15], jnp.int32)
    out = paged_attention(q, k_pages, v_pages, table, lengths)

    kk = _gather_pool(k_pages, table)
    vv = _gather_pool(v_pages, table)
    mask = jnp.arange(MP * page)[None, None, :] < lengths[:, None, None]
    ref = attend(q[:, None], kk, vv, mask, None)[:, 0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_paged_attention_int8_parity():
    """Quantized pools (codes + flat kv-major scale rows) match the dense
    int8 attend over the gathered view."""
    from butterfly_tpu.models.common import quantize_kv

    S, Nq, Kv, H, page, P, MP = 3, 8, 2, 16, 4, 10, 4
    ks = jax.random.split(jax.random.PRNGKey(13), 3)
    q = jax.random.normal(ks[0], (S, Nq, H))
    kf = jax.random.normal(ks[1], (P, Kv, page, H))
    vf = jax.random.normal(ks[2], (P, Kv, page, H))
    kq, ksc = quantize_kv(kf)   # codes [P,Kv,page,H], scales [P,Kv,page]
    vq, vsc = quantize_kv(vf)
    ksp = ksc.reshape(P, Kv * page)
    vsp = vsc.reshape(P, Kv * page)
    table = jnp.asarray([[0, 2, 9, 9], [3, 1, 4, 9], [5, 6, 7, 8]],
                        jnp.int32)
    lengths = jnp.asarray([6, 3, 15], jnp.int32)
    out = paged_attention(q, kq, vq, table, lengths, ksp, vsp)

    # dense reference: dequantize the gathered view, plain attend
    kk = _gather_pool(kq.astype(jnp.float32) * ksc[..., None], table)
    vv = _gather_pool(vq.astype(jnp.float32) * vsc[..., None], table)
    mask = jnp.arange(MP * page)[None, None, :] < lengths[:, None, None]
    ref = attend(q[:, None], kk, vv, mask, None)[:, 0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def _insert_window(view, win, lengths, counts):
    """Dense reference insert: window entry w of slot s lands at
    absolute position lengths[s] + w, entries past counts[s] dropped.
    view [S, MP*page, Kv, H]; win [S, Kv, W, H]."""
    out = np.asarray(view).copy()
    W = win.shape[2]
    for s in range(view.shape[0]):
        for w in range(min(int(counts[s]), W)):
            out[s, int(lengths[s]) + w] = np.asarray(win[s, :, w])
    return jnp.asarray(out)


def test_paged_attention_window_segment_parity():
    """The write-combined window segment (kv_write_combine): staged
    K/V [S, Kv, W, H] at absolute positions lengths..lengths+count-1
    folds into the online softmax exactly like an inserted dense view;
    entries past win_count must be invisible (they are recycled-buffer
    garbage by contract)."""
    S, Nq, Kv, H, page, P, MP, W = 3, 8, 2, 16, 4, 10, 4, 5
    ks = jax.random.split(jax.random.PRNGKey(7), 5)
    q = jax.random.normal(ks[0], (S, Nq, H))
    k_pages = jax.random.normal(ks[1], (P, Kv, page, H))
    v_pages = jax.random.normal(ks[2], (P, Kv, page, H))
    win_k = jax.random.normal(ks[3], (S, Kv, W, H))
    win_v = jax.random.normal(ks[4], (S, Kv, W, H))
    table = jnp.asarray([[0, 2, 9, 9], [3, 1, 4, 9], [5, 6, 7, 8]],
                        jnp.int32)
    lengths = jnp.asarray([6, 3, 9], jnp.int32)   # FLUSHED pool lengths
    counts = jnp.asarray([3, 5, 0], jnp.int32)    # staged entries/slot
    out = paged_attention(q, k_pages, v_pages, table, lengths,
                          win_k=win_k, win_v=win_v, win_count=counts)

    kk = _insert_window(_gather_pool(k_pages, table), win_k, lengths,
                        counts)
    vv = _insert_window(_gather_pool(v_pages, table), win_v, lengths,
                        counts)
    total = (lengths + counts)[:, None, None]
    mask = jnp.arange(MP * page)[None, None, :] < total
    ref = attend(q[:, None], kk, vv, mask, None)[:, 0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    # garbage past win_count must not leak into the output
    poisoned = win_k.at[:, :, 4:].set(1e3)
    out2 = paged_attention(q, k_pages, v_pages, table, lengths,
                           win_k=poisoned, win_v=win_v,
                           win_count=jnp.minimum(counts, 4))
    ref2 = paged_attention(q, k_pages, v_pages, table, lengths,
                           win_k=win_k, win_v=win_v,
                           win_count=jnp.minimum(counts, 4))
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(ref2))


def test_paged_attention_window_segment_int8_parity():
    """Quantized window segment: codes + [S, Kv, W] scales dequantize
    inside the kernel's window step exactly like the pool blocks."""
    from butterfly_tpu.models.common import quantize_kv

    S, Nq, Kv, H, page, P, MP, W = 3, 8, 2, 16, 4, 10, 4, 4
    ks = jax.random.split(jax.random.PRNGKey(17), 5)
    q = jax.random.normal(ks[0], (S, Nq, H))
    kf = jax.random.normal(ks[1], (P, Kv, page, H))
    vf = jax.random.normal(ks[2], (P, Kv, page, H))
    wkf = jax.random.normal(ks[3], (S, Kv, W, H))
    wvf = jax.random.normal(ks[4], (S, Kv, W, H))
    kq, ksc = quantize_kv(kf)
    vq, vsc = quantize_kv(vf)
    wkq, wks = quantize_kv(wkf)   # codes [S,Kv,W,H], scales [S,Kv,W]
    wvq, wvs = quantize_kv(wvf)
    table = jnp.asarray([[0, 2, 9, 9], [3, 1, 4, 9], [5, 6, 7, 8]],
                        jnp.int32)
    lengths = jnp.asarray([6, 3, 9], jnp.int32)
    counts = jnp.asarray([2, 4, 0], jnp.int32)
    out = paged_attention(q, kq, vq, table, lengths,
                          ksc.reshape(P, Kv * page),
                          vsc.reshape(P, Kv * page),
                          win_k=wkq, win_v=wvq, win_count=counts,
                          win_k_scale=wks, win_v_scale=wvs)

    kk = _insert_window(_gather_pool(kq.astype(jnp.float32)
                                     * ksc[..., None], table),
                        wkq.astype(jnp.float32) * wks[..., None],
                        lengths, counts)
    vv = _insert_window(_gather_pool(vq.astype(jnp.float32)
                                     * vsc[..., None], table),
                        wvq.astype(jnp.float32) * wvs[..., None],
                        lengths, counts)
    total = (lengths + counts)[:, None, None]
    mask = jnp.arange(MP * page)[None, None, :] < total
    ref = attend(q[:, None], kk, vv, mask, None)[:, 0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_paged_attention_zero_length_slot():
    """length 0 (inactive slot) visits no pages and returns zeros."""
    S, Nq, Kv, H, page, P = 2, 4, 4, 8, 4, 4
    q = jax.random.normal(jax.random.PRNGKey(4), (S, Nq, H))
    kp = jax.random.normal(jax.random.PRNGKey(5), (P, Kv, page, H))
    table = jnp.zeros((S, 2), jnp.int32)
    out = paged_attention(q, kp, kp, table, jnp.asarray([0, 4], jnp.int32))
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_array_equal(np.asarray(out[0]), 0.0)


@pytest.fixture(scope="module")
def mesh_dt():
    """data=2 x tensor=4 mesh for the sharded kernel wrappers."""
    from butterfly_tpu.core.config import MeshConfig
    from butterfly_tpu.core.mesh import make_mesh
    return make_mesh(MeshConfig(data=2, tensor=4))


def test_shardable_axes_engage(mesh_dt):
    """The eligibility gate must actually fire under a live mesh — the
    fallback is numerically identical, so parity tests alone can't tell
    shard_map engaged (round-3 review finding)."""
    from butterfly_tpu.ops.flash_attention import shardable_axes
    with jax.set_mesh(mesh_dt):
        assert shardable_axes(4, 8, 4) == ("data", "tensor")
        assert shardable_axes(3, 8, 4) == (None, "tensor")   # 3 % data=2
        assert shardable_axes(4, 6, 3) == ("data", None)     # heads % 4
    assert shardable_axes(4, 8, 4) == (None, None)           # no mesh


def test_flash_attention_sharded_parity(mesh_dt):
    """shard_map-wrapped kernel on a data x tensor mesh == plain kernel."""
    from butterfly_tpu.ops.flash_attention import flash_attention_sharded
    B, T, Nq, Kv, H = 4, 32, 8, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(6), 3)
    q = jax.random.normal(ks[0], (B, T, Nq, H))
    k = jax.random.normal(ks[1], (B, T, Kv, H))
    v = jax.random.normal(ks[2], (B, T, Kv, H))
    ref = flash_attention(q, k, v)
    with jax.set_mesh(mesh_dt):
        out = jax.jit(flash_attention_sharded)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_sharded_partial(mesh_dt):
    """Heads that don't divide tensor=4: shard_map engages on data only."""
    from butterfly_tpu.ops.flash_attention import flash_attention_sharded
    B, T, Nq, Kv, H = 2, 16, 3, 3, 8   # B%data=2 ok; heads 3%4 != 0
    ks = jax.random.split(jax.random.PRNGKey(8), 3)
    q = jax.random.normal(ks[0], (B, T, Nq, H))
    k = jax.random.normal(ks[1], (B, T, Kv, H))
    v = jax.random.normal(ks[2], (B, T, Kv, H))
    ref = flash_attention(q, k, v)
    with jax.set_mesh(mesh_dt):
        out = jax.jit(flash_attention_sharded)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_sharded_wrappers_decline_when_nothing_divides(mesh_dt):
    """Live auto mesh + no shardable axis -> None (caller must go dense);
    a bare pallas_call under GSPMD is the failure the old engine guard
    prevented. The engine path must then still be token-correct."""
    from butterfly_tpu.ops.flash_attention import flash_attention_sharded
    B, T, Nq, Kv, H = 3, 16, 3, 3, 8   # 3 divides neither data=2 nor t=4
    ks = jax.random.split(jax.random.PRNGKey(10), 3)
    q = jax.random.normal(ks[0], (B, T, Nq, H))
    k = jax.random.normal(ks[1], (B, T, Kv, H))
    v = jax.random.normal(ks[2], (B, T, Kv, H))
    with jax.set_mesh(mesh_dt):
        assert flash_attention_sharded(q, k, v) is None

    # integration: indivisible-head model, meshed serving w/ kernels on
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.sched.scheduler import Scheduler
    cfg = tiny("llama", dtype="float32", param_dtype="float32",
               num_heads=3, num_kv_heads=3, head_dim=8)
    params = Model(cfg).init(jax.random.PRNGKey(11))
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=64, page_size=8)
    outs = {}
    for mesh in (None, mesh_dt):
        sched = Scheduler(ServingEngine(Model(cfg), params, rt, mesh=mesh,
                                        use_kernels=True))
        r = sched.submit([5, 7, 11], max_new_tokens=6)
        sched.run_until_done()
        outs[mesh is None] = r.output
    assert outs[True] == outs[False]


def test_paged_attention_sharded_parity(mesh_dt):
    from butterfly_tpu.ops.paged_attention import paged_attention_sharded
    S, Nq, Kv, H, page, P = 4, 8, 4, 16, 4, 12
    ks = jax.random.split(jax.random.PRNGKey(9), 3)
    q = jax.random.normal(ks[0], (S, Nq, H))
    kp = jax.random.normal(ks[1], (P, page, Kv, H))
    vp = jax.random.normal(ks[2], (P, page, Kv, H))
    table = jnp.asarray([[0, 2, 11], [3, 1, 11], [5, 6, 7], [8, 9, 10]],
                        jnp.int32)
    lengths = jnp.asarray([6, 3, 12, 9], jnp.int32)
    ref = paged_attention(q, kp, vp, table, lengths)
    with jax.set_mesh(mesh_dt):
        out = jax.jit(paged_attention_sharded)(q, kp, vp, table, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_serving_with_kernels_token_parity():
    """Full scheduler run with Pallas kernels == gather path, token-exact."""
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.sched.scheduler import Scheduler

    cfg = tiny("llama", dtype="float32", param_dtype="float32")
    model = Model(cfg)
    params = model.init(jax.random.PRNGKey(42))
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=64, page_size=8)

    outs = {}
    for use_k in (False, True):
        sched = Scheduler(ServingEngine(model, params, rt,
                                        use_kernels=use_k))
        r1 = sched.submit([5, 7, 11], max_new_tokens=6)
        r2 = sched.submit([3, 1], max_new_tokens=6)
        sched.run_until_done()
        outs[use_k] = (r1.output, r2.output)
    assert outs[False] == outs[True]


def test_engine_flash_prefill_token_parity():
    """InferenceEngine with flash prefill == dense prefill, token-exact."""
    from butterfly_tpu.engine import InferenceEngine, SamplingParams
    cfg = tiny("llama", dtype="float32", param_dtype="float32")
    model = Model(cfg)
    params = model.init(jax.random.PRNGKey(7))
    prompts = [[5, 7, 11, 2], [3]]
    sp = SamplingParams(max_new_tokens=6)
    a = InferenceEngine(model, params,
                        use_flash_prefill=False).generate(prompts, sp)
    b = InferenceEngine(model, params,
                        use_flash_prefill=True).generate(prompts, sp)
    np.testing.assert_array_equal(a.tokens, b.tokens)
