"""Host-RAM KV tier (cache/hosttier.py, ISSUE 17): evict-to-host
instead of drop, revive on prefix hit, export continuation.

Three layers:

* pure tier unit tests — byte-exact save/load, LRU byte budget,
  disk spill + promote;
* allocator hook tests — `on_evict` fires at the deregistration
  moment, `reviver` turns a registry miss into a continued prefix
  walk, and every path holds the full-accounting invariant;
* scheduler integration (CPU) — the full demote/revive round trip is
  byte-exact on the device for BOTH float32 and int8 pools, the
  kv_tier_* metrics move, and export_payload continues a chain from
  the tier after the device registry evicted it.
"""
import numpy as np
import pytest

from butterfly_tpu.cache.hosttier import HostKVTier
from butterfly_tpu.cache.prefix import (
    PrefixCachingAllocator, chain_block_hashes)
from butterfly_tpu.core.config import RuntimeConfig, tiny
from butterfly_tpu.engine.serving import ServingEngine
from butterfly_tpu.fleet.kvtransfer import export_payload, import_payload
from butterfly_tpu.models.common import Model
from butterfly_tpu.sched.scheduler import Scheduler


# ---------------------------------------------------------------------------
# tier unit tests (pure host)
# ---------------------------------------------------------------------------

def page(seed, shape=(2, 1, 4, 3), dtype=np.float32):
    rng = np.random.RandomState(seed)
    if np.dtype(dtype) == np.int8:
        return rng.randint(-128, 128, size=shape).astype(np.int8)
    return rng.standard_normal(shape).astype(dtype)


def test_tier_round_trip_byte_exact():
    tier = HostKVTier(1 << 20)
    k, v = page(1), page(2)
    tier.save(b"h1", k, v)
    got = tier.load(b"h1")
    assert got is not None
    np.testing.assert_array_equal(got[0], k)
    np.testing.assert_array_equal(got[1], v)
    assert got[2] is None and got[3] is None
    # int8 codes + scales survive exactly too
    k8, v8 = page(3, dtype=np.int8), page(4, dtype=np.int8)
    ks, vs = page(5, shape=(2, 4)), page(6, shape=(2, 4))
    tier.save(b"h2", k8, v8, ks, vs)
    g = tier.load(b"h2")
    for a, b in zip(g, (k8, v8, ks, vs)):
        np.testing.assert_array_equal(a, b)
        assert a.dtype == b.dtype
    assert tier.misses == 0 and tier.restores == 2
    assert tier.load(b"nope") is None
    assert tier.misses == 1


def test_tier_save_copies_and_is_idempotent():
    tier = HostKVTier(1 << 20)
    k, v = page(1), page(2)
    tier.save(b"h", k, v)
    k[:] = 0  # caller hands a view; the tier must have copied
    got = tier.load(b"h")
    assert float(np.abs(got[0]).sum()) > 0
    before = tier.bytes_used
    tier.save(b"h", got[0], got[1])  # re-save: refresh, not leak
    assert tier.bytes_used == before
    assert tier.stats()["entries"] == 1


def test_tier_lru_byte_budget_drops_oldest():
    one = _nbytes = page(0).nbytes * 2
    tier = HostKVTier(one * 2 + 1)  # room for two entries
    for i, h in enumerate((b"a", b"b", b"c")):
        tier.save(h, page(i), page(i + 10))
    assert tier.drops == 1 and not tier.contains(b"a")
    assert tier.contains(b"b") and tier.contains(b"c")
    assert tier.bytes_used <= tier.capacity_bytes
    # a load refreshes LRU order: b becomes newest, so d drops c
    assert tier.load(b"b") is not None
    tier.save(b"d", page(7), page(8))
    assert tier.contains(b"b") and not tier.contains(b"c")


def test_tier_disk_spill_and_promote(tmp_path):
    one = page(0).nbytes * 2
    tier = HostKVTier(one * 2 + 1, spill_dir=str(tmp_path))
    pages = {h: (page(i), page(i + 10))
             for i, h in enumerate((b"a", b"b", b"c"))}
    for h, (k, v) in pages.items():
        tier.save(h, k, v)
    # oldest spilled to disk, nothing lost
    assert tier.spills == 1 and tier.drops == 0
    assert tier.stats()["spilled_entries"] == 1
    assert tier.contains(b"a")
    got = tier.load(b"a")  # promote back: byte-exact through the .npz
    np.testing.assert_array_equal(got[0], pages[b"a"][0])
    np.testing.assert_array_equal(got[1], pages[b"a"][1])
    assert tier.stats()["spilled_entries"] == 1  # promotion respilled b
    assert tier.bytes_used <= tier.capacity_bytes


# ---------------------------------------------------------------------------
# allocator hooks (pure host)
# ---------------------------------------------------------------------------

PS = 4


def chain(tokens):
    return chain_block_hashes(tokens, PS)


def register_chain(a, slot, tokens):
    """Admit + register a token chain, then release it so its pages sit
    warm in the evictable list (the demotion candidates)."""
    got = a.admit(slot, tokens, len(tokens))
    assert got is not None
    a.register(slot, tokens)
    a.release(slot)
    a.check_invariants()


def test_on_evict_fires_with_digest_and_page():
    a = PrefixCachingAllocator(4, PS, 8)
    demoted = []
    a.on_evict = lambda h, pid: demoted.append((h, pid))
    toks = list(range(4 * PS))
    register_chain(a, 0, toks)  # 4 pages registered, all evictable
    # a fresh 3-page admission with an empty free list recycles 3
    # registered pages through _take_free -> _evict_one. release()
    # decrefs deepest-first, so the LRU demotes the chain TAIL first —
    # exactly right for prefix reuse (shallow prefixes stay warm
    # longest).
    assert a.admit(1, [99] * (3 * PS), 3 * PS) == 0
    a.check_invariants()
    hashes = chain(toks)
    assert [h for h, _ in demoted] == [hashes[3], hashes[2], hashes[1]]
    # the digests seen by the hook are no longer in the registry
    assert all(a.lookup(h) is None for h, _ in demoted)


def test_on_evict_failure_never_breaks_eviction():
    a = PrefixCachingAllocator(2, PS, 8)

    def boom(h, pid):
        raise RuntimeError("tier unavailable")

    a.on_evict = boom
    register_chain(a, 0, list(range(2 * PS)))
    assert a.admit(1, [5] * (2 * PS), 2 * PS) == 0  # evicts through boom
    a.check_invariants()


def test_reviver_continues_the_prefix_walk():
    a = PrefixCachingAllocator(6, PS, 8)
    toks = list(range(3 * PS + 1))  # 3 matchable pages + 1 spare token
    register_chain(a, 0, toks)
    # evict everything into a fake tier keyed by digest
    tier = {}
    a.on_evict = lambda h, pid: tier.setdefault(h, pid)
    assert a.admit(1, [7] * (6 * PS), 6 * PS) == 0  # recycles all 3
    a.release(1)
    assert all(a.lookup(h) is None for h in chain(toks))

    revived = []

    def reviver(h):
        if h not in tier:
            return None
        pid = a.import_page(h)
        if pid is None:
            return a.lookup(h)
        revived.append(h)
        return pid

    a.reviver = reviver
    got = a.admit(2, toks, len(toks))
    assert got == 3 * PS  # the whole chain came back as a prefix hit
    assert revived == chain(toks)
    a.check_invariants()
    a.release(2)
    a.check_invariants()


def test_reviver_rollback_leaves_revived_pages_warm():
    """A revive followed by a does-not-fit rollback must leave the
    revived pages registered + evictable (warm), with invariants
    intact — the next admission of the chain hits them for free."""
    a = PrefixCachingAllocator(4, PS, 5)
    toks = list(range(2 * PS + 1))  # 2 matchable pages
    register_chain(a, 0, toks)
    tier = {}
    a.on_evict = lambda h, pid: tier.setdefault(h, pid)
    # recycle every page: the registered pair lands in the tier
    assert a.admit(1, [7] * (4 * PS), 4 * PS) == 0
    a.check_invariants()
    a.release(1)

    def reviver(h):
        if h not in tier:
            return None
        try:
            pid = a.import_page(h)
        except MemoryError:
            return None
        return a.lookup(h) if pid is None else pid

    a.reviver = reviver
    # 17 tokens need 5 pages: both tier pages revive (2 imports leave 2
    # free), then want=3 > 2 available -> admit refuses AFTER reviving,
    # exercising the rollback leg over revived pages
    assert a.admit(2, toks, 4 * PS + 1) is None
    a.check_invariants()
    assert all(a.lookup(h) is not None for h in chain(toks))
    # the warm revived pages now serve a fitting admission as plain
    # hits — the reviver is not consulted again
    a.reviver = None
    got = a.admit(3, toks, len(toks))
    assert got == 2 * PS
    a.check_invariants()


# ---------------------------------------------------------------------------
# scheduler integration (CPU)
# ---------------------------------------------------------------------------

def make_sched(**rt_kw):
    cfg = tiny("llama", dtype="float32", param_dtype="float32")
    model = Model(cfg)
    import jax
    params = model.init(jax.random.PRNGKey(0))
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=64, page_size=8,
                       prefix_caching=True, host_kv_tier_mb=8.0,
                       **rt_kw)
    return Scheduler(ServingEngine(model, params, rt, use_kernels=False))


PROMPT_A = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7, 9, 3, 2, 3, 8, 4]
PROMPT_B = [11, 13, 17, 19, 23] * 4


def run_one(sched, prompt, max_new=6):
    req = sched.submit(prompt, max_new_tokens=max_new)
    sched.run_until_done()
    assert req.state == "finished"
    return req.output


def snapshot_chain(sched, tokens):
    """(hashes, per-page host bytes) for the registered leading run of
    `tokens` — the byte-exactness reference."""
    hashes, pids = [], []
    for h in chain_block_hashes(tokens, sched.alloc.page_size):
        pid = sched.alloc.lookup(h)
        if pid is None:
            break
        hashes.append(h)
        pids.append(pid)
    assert pids, "expected a registered chain to snapshot"
    return hashes, sched.engine.read_pages(pids)


@pytest.mark.parametrize("kv_quant", ["none", "int8"])
def test_evict_to_host_round_trip_byte_exact(kv_quant):
    # num_pages=5 -> 4 allocator pages: PROMPT_A (20 tok + 6 new) holds
    # all 4, so PROMPT_B's admission must recycle A's registered pages
    # through the tier
    s = make_sched(num_pages=5, kv_quant=kv_quant)
    out_a = run_one(s, PROMPT_A)
    written = (PROMPT_A + out_a)[:-1]
    hashes, (k0, v0, ks0, vs0) = snapshot_chain(s, written)
    run_one(s, PROMPT_B)  # forces eviction of A's chain tail
    # the LRU demotes deepest-first: the tail pages now live ONLY in
    # the host tier, the chain head may stay registered
    evicted = [h for h in hashes if s.alloc.lookup(h) is None]
    assert len(evicted) >= 2
    assert s.host_tier.saves >= len(evicted)
    # resubmit A: the reviver pulls the chain back from the tier and
    # the request decodes the same tokens it did the first time
    # resubmitting A revives the evicted matchable page(s); the page
    # covering generated tokens is simply recomputed (matchable caps
    # at the prompt, so it can never be asked for at admission)
    assert run_one(s, PROMPT_A) == out_a
    m = s.metrics()
    assert m["kv_tier_pages_restored_total"] >= 1
    assert m["kv_tier_hit_rate"] > 0
    assert "kv_tier_restore_seconds_p50" in m
    assert "kv_tier_restore_seconds_p95" in m
    # byte-exactness on the DEVICE: the revived pages hold exactly the
    # bytes the evicted pages held (codes AND scales for int8)
    pids = [s.alloc.lookup(h) for h in hashes]
    assert all(p is not None for p in pids)
    k1, v1, ks1, vs1 = s.engine.read_pages(pids)
    np.testing.assert_array_equal(k1, k0)
    np.testing.assert_array_equal(v1, v0)
    if kv_quant == "int8":
        np.testing.assert_array_equal(ks1, ks0)
        np.testing.assert_array_equal(vs1, vs0)
    else:
        assert ks0 is None and ks1 is None


def test_export_payload_continues_from_tier():
    """A chain this replica evicted to host stays exportable: the
    /kv/pages surface serves the still-registered head from the device
    pool and CONTINUES the run from the tier where the registry
    misses, and a peer replica imports the whole chain byte-exactly."""
    src = make_sched(num_pages=5)
    out_a = run_one(src, PROMPT_A)
    written = (PROMPT_A + out_a)[:-1]
    hashes, (k0, v0, _, _) = snapshot_chain(src, written)
    run_one(src, PROMPT_B)  # A's chain tail now lives only in the tier
    assert any(src.alloc.lookup(h) is None for h in hashes)
    hexes = [h.hex() for h in hashes]
    payload = export_payload(src, hexes)
    assert [p["hash"] for p in payload["pages"]] == hexes
    assert payload["missing"] == []
    dst = make_sched(num_pages=16)
    res = import_payload(dst, payload)
    assert res["imported"] == len(hashes) and not res["no_space"]
    pids = [dst.alloc.lookup(h) for h in hashes]
    k1, v1, _, _ = dst.engine.read_pages(pids)
    np.testing.assert_array_equal(k1, k0)
    np.testing.assert_array_equal(v1, v0)


def test_tier_off_by_default():
    cfg = tiny("llama", dtype="float32", param_dtype="float32")
    model = Model(cfg)
    import jax
    params = model.init(jax.random.PRNGKey(0))
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=64, page_size=8,
                       prefix_caching=True)
    s = Scheduler(ServingEngine(model, params, rt, use_kernels=False))
    assert s.host_tier is None
    assert s.alloc.on_evict is None and s.alloc.reviver is None
    assert "kv_tier_hit_rate" not in s.metrics()
