"""Worker for the kill-a-host fault-injection test (run as a subprocess).

Runs a Scheduler partway through a batch of requests, writes a serving
snapshot (ckpt.sharded.save_serving_snapshot), then spins so the parent
can SIGKILL it with live, unfinished work — simulating a host crash whose
queued work must be recoverable from the snapshot alone.

Usage: python crash_worker.py <snapshot_path> <ticks_before_spin>
"""
import sys
import time


def main() -> None:
    snap_path, ticks = sys.argv[1], int(sys.argv[2])
    import jax
    jax.config.update("jax_platforms", "cpu")

    from butterfly_tpu.ckpt.sharded import save_serving_snapshot
    from butterfly_tpu.core.config import RuntimeConfig, tiny
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.models.common import Model
    from butterfly_tpu.sched.scheduler import Scheduler

    cfg = tiny("llama", dtype="float32", param_dtype="float32")
    model = Model(cfg)
    params = model.init(jax.random.PRNGKey(42))
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=64, page_size=8,
                       prefill_chunk=2)  # force a mid-prefill request too
    sched = Scheduler(ServingEngine(model, params, rt))
    sched.submit([5, 7, 11], max_new_tokens=12)
    sched.submit([3, 1], max_new_tokens=10)
    sched.submit([2, 4, 6, 8, 10, 12], max_new_tokens=8)  # chunked prefill

    for _ in range(ticks):
        sched.tick()
    assert sched.has_work, "worker drained before the crash point"
    save_serving_snapshot(snap_path + ".tmp", sched)
    import os
    os.replace(snap_path + ".tmp", snap_path)  # atomic publish
    while True:  # parent SIGKILLs us here, mid-flight
        time.sleep(0.1)


if __name__ == "__main__":
    main()
