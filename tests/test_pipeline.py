"""Stage-3 pipeline-parallel tests: GPipe schedule parity vs plain forward.

8 fake CPU devices. The pipeline must produce identical logits and an
identical KV cache to the single-program forward, for prefill and decode,
alone (stage=8... stage=4 x data=2) and composed with TP (stage=2 x
tensor=4).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from butterfly_tpu.core.config import MeshConfig, tiny
from butterfly_tpu.core.mesh import make_mesh
from butterfly_tpu.models.common import KVCache, Model, forward, init_cache
from butterfly_tpu.parallel.partition import shard_cache, shard_params
from butterfly_tpu.parallel.pipeline import pipeline_forward


def pp_cfg(arch="llama", num_layers=4):
    return tiny(arch, num_layers=num_layers, vocab_size=256, hidden_size=64,
                num_heads=8, num_kv_heads=8, head_dim=8,
                intermediate_size=128, dtype="float32",
                param_dtype="float32")


def ref_forward(cfg, params, tokens, max_seq=32):
    cache = init_cache(cfg, batch=tokens.shape[0], max_seq=max_seq)
    return jax.jit(lambda p, t, c: forward(p, cfg, t, c))(
        params, tokens, cache)


@pytest.mark.parametrize("mesh_cfg,mb", [
    (MeshConfig(stage=4, data=2), 2),
    (MeshConfig(stage=2, tensor=4), 4),
    (MeshConfig(stage=4, tensor=2), 1),
])
def test_pipeline_prefill_parity(mesh_cfg, mb):
    cfg = pp_cfg()
    mesh = make_mesh(mesh_cfg)
    params = Model(cfg).init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (4, 10)))
    ref_logits, ref_cache = ref_forward(cfg, params, tokens)

    sparams = shard_params(params, cfg, mesh)
    cache = shard_cache(init_cache(cfg, batch=4, max_seq=32), cfg, mesh)
    with jax.set_mesh(mesh):
        logits, new_cache = jax.jit(
            lambda p, t, c: pipeline_forward(p, cfg, t, c, mesh,
                                             num_microbatches=mb)
        )(sparams, tokens, cache)

    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(new_cache.k),
                               np.asarray(ref_cache.k), rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(new_cache.length),
                                  np.asarray(ref_cache.length))


def test_pipeline_decode_parity():
    """Prefill then single-token decode steps through the pipeline."""
    cfg = pp_cfg()
    mesh = make_mesh(MeshConfig(stage=4, data=2))
    params = Model(cfg).init(jax.random.PRNGKey(1))
    rng = np.random.RandomState(1)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (4, 6)))

    ref_logits, ref_cache = ref_forward(cfg, params, tokens)
    sparams = shard_params(params, cfg, mesh)
    cache = shard_cache(init_cache(cfg, batch=4, max_seq=32), cfg, mesh)

    step = jax.jit(lambda p, t, c: pipeline_forward(p, cfg, t, c, mesh,
                                                    num_microbatches=2))
    with jax.set_mesh(mesh):
        logits, cache = step(sparams, tokens, cache)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=2e-5, atol=2e-5)

    for _ in range(3):
        nxt = jnp.argmax(ref_logits[:, -1, :], axis=-1)[:, None]
        ref_logits, ref_cache = jax.jit(
            lambda p, t, c: forward(p, cfg, t, c))(params, nxt, ref_cache)
        with jax.set_mesh(mesh):
            logits, cache = step(sparams, nxt, cache)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(ref_logits),
                                   rtol=2e-5, atol=2e-5)


def test_pipeline_stage1_fallback():
    """stage=1 mesh routes to the plain forward (no shard_map)."""
    cfg = pp_cfg(num_layers=2)
    mesh = make_mesh(MeshConfig(tensor=8))
    params = Model(cfg).init(jax.random.PRNGKey(2))
    tokens = jnp.asarray(
        np.random.RandomState(2).randint(0, cfg.vocab_size, (2, 5)))
    ref_logits, _ = ref_forward(cfg, params, tokens)
    sparams = shard_params(params, cfg, mesh)
    cache = shard_cache(init_cache(cfg, batch=2, max_seq=32), cfg, mesh)
    with jax.set_mesh(mesh):
        logits, _ = jax.jit(
            lambda p, t, c: pipeline_forward(p, cfg, t, c, mesh))(
                sparams, tokens, cache)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=2e-5, atol=2e-5)


def test_interleaved_pipeline_parity():
    """Virtual-stage (1F1B-style) schedule: S=2 stages x V=2 chunks,
    wrapped ppermute ring + stage-0 holding buffer — logits and cache
    must match the plain forward exactly (prefill then decode steps)."""
    from butterfly_tpu.parallel.pipeline import interleave_layers
    cfg = pp_cfg(num_layers=8)
    mesh = make_mesh(MeshConfig(stage=2, tensor=4))
    S, V, M = 2, 2, 2
    params = Model(cfg).init(jax.random.PRNGKey(4))
    tokens = jnp.asarray(
        np.random.RandomState(4).randint(0, cfg.vocab_size, (4, 10)))
    ref_logits, ref_cache = ref_forward(cfg, params, tokens)

    iparams = dict(params)
    iparams["layers"] = interleave_layers(params["layers"],
                                          cfg.num_layers, S, V)
    sparams = shard_params(iparams, cfg, mesh)
    cache = shard_cache(init_cache(cfg, batch=4, max_seq=32), cfg, mesh)
    step = jax.jit(lambda p, t, c: pipeline_forward(
        p, cfg, t, c, mesh, num_microbatches=M, virtual_stages=V))
    with jax.set_mesh(mesh):
        logits, cache = step(sparams, tokens, cache)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=2e-5, atol=2e-5)
    k_back = interleave_layers(cache.k, cfg.num_layers, S, V, inverse=True)
    np.testing.assert_allclose(np.asarray(k_back), np.asarray(ref_cache.k),
                               rtol=2e-5, atol=2e-5)

    # decode continuation through the interleaved schedule
    for _ in range(2):
        nxt = jnp.argmax(ref_logits[:, -1, :], axis=-1)[:, None]
        ref_logits, ref_cache = jax.jit(
            lambda p, t, c: forward(p, cfg, t, c))(params, nxt, ref_cache)
        with jax.set_mesh(mesh):
            logits, cache = step(sparams, nxt, cache)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(ref_logits),
                                   rtol=2e-5, atol=2e-5)


def test_interleaved_pipeline_validation():
    from butterfly_tpu.parallel.pipeline import interleave_layers
    cfg = pp_cfg(num_layers=8)
    mesh = make_mesh(MeshConfig(stage=2, tensor=4))
    params = shard_params(Model(cfg).init(jax.random.PRNGKey(0)), cfg, mesh)
    cache = shard_cache(init_cache(cfg, batch=4, max_seq=16), cfg, mesh)
    tokens = jnp.zeros((4, 4), jnp.int32)
    with pytest.raises(ValueError, match="microbatches >= stages"):
        pipeline_forward(params, cfg, tokens, cache, mesh,
                         num_microbatches=1, virtual_stages=2)
    cfg6 = pp_cfg(num_layers=6)
    with pytest.raises(ValueError, match="virtual"):
        pipeline_forward(params, cfg6, tokens, cache, mesh,
                         num_microbatches=2, virtual_stages=2)
    # round-trip permutation sanity
    import numpy as _np
    arr = jnp.arange(8)
    back = interleave_layers(
        interleave_layers(arr, 8, 2, 2), 8, 2, 2, inverse=True)
    _np.testing.assert_array_equal(_np.asarray(back), _np.arange(8))


def test_pipeline_no_full_output_allreduce():
    """VERDICT r2 item 5: the pipeline's output must come off the last
    stage as ONE block move (collective-permute / gather of [B,T,D]),
    never as an all-reduce of S zero-padded full-batch copies. Assert no
    all-reduce touches a full [B,T,D]-or-larger operand — TP all-reduces
    are microbatch-sized [mb,T,D] and stay."""
    import re
    from butterfly_tpu.parallel.partition import compiled_hlo
    cfg = pp_cfg()
    mesh = make_mesh(MeshConfig(stage=2, tensor=4))
    params = shard_params(Model(cfg).init(jax.random.PRNGKey(0)), cfg, mesh)
    cache = shard_cache(init_cache(cfg, batch=4, max_seq=32), cfg, mesh)
    tokens = jnp.zeros((4, 8), jnp.int32)
    B, T, D = 4, 8, cfg.hidden_size

    hlo = compiled_hlo(
        lambda p, t, c: pipeline_forward(p, cfg, t, c, mesh,
                                         num_microbatches=4),
        params, tokens, cache, mesh=mesh)
    for line in hlo.splitlines():
        lhs = line.strip().split("=", 1)
        if len(lhs) < 2 or "all-reduce" not in lhs[0]:
            continue
        # replica_groups=[G,Sz]<=[8]: Sz is the per-group device count.
        # tensor-axis reduces (embedding-gather psum, Megatron) have
        # Sz == 4 here and are allowed; anything whose groups span the
        # stage axis (Sz == 2 or 8) must be microbatch-sized or smaller.
        # Unparseable groups fail LOUDLY (a format change must not turn
        # this guard vacuous).
        m = re.search(r"replica_groups=\[\d+,(\d+)\]", lhs[1])
        assert m is not None, \
            f"unparseable replica_groups (update regex): {line.strip()[:160]}"
        if int(m.group(1)) == mesh.shape["tensor"]:
            continue
        shapes = re.findall(r"\[([\d,]+)\]", lhs[1].split("(")[0])
        for sh in shapes:
            elems = int(np.prod([int(d) for d in sh.split(",")]))
            assert elems < B * T * D, \
                f"stage-axis full-size all-reduce: {line.strip()[:160]}"


def test_pipeline_validation_errors():
    cfg = pp_cfg(num_layers=4)
    mesh = make_mesh(MeshConfig(stage=4, data=2))
    params = shard_params(Model(cfg).init(jax.random.PRNGKey(0)), cfg, mesh)
    cache = shard_cache(init_cache(cfg, batch=4, max_seq=16), cfg, mesh)
    tokens = jnp.zeros((4, 4), jnp.int32)
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_forward(params, cfg, tokens, cache, mesh, num_microbatches=3)
    cfg6 = pp_cfg(num_layers=6)
    with pytest.raises(ValueError, match="layers"):
        pipeline_forward(params, cfg6, tokens, cache, mesh,
                         num_microbatches=2)


def test_engine_generate_interleaved_stages():
    """Engine integration: virtual_stages=2 on a stage=2 mesh permutes
    the layer stack once and generates the same tokens as unmeshed."""
    from butterfly_tpu.engine import InferenceEngine, SamplingParams
    cfg = pp_cfg(num_layers=8)
    mesh = make_mesh(MeshConfig(stage=2, tensor=4))
    params = shard_params(Model(cfg).init(jax.random.PRNGKey(5)), cfg, mesh)
    engine = InferenceEngine(Model(cfg), params, mesh=mesh,
                             num_microbatches=2, virtual_stages=2)
    prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9]] + [[2]]
    res = engine.generate(prompts, SamplingParams(max_new_tokens=5))
    ref = InferenceEngine(Model(cfg),
                          Model(cfg).init(jax.random.PRNGKey(5))).generate(
        prompts, SamplingParams(max_new_tokens=5))
    np.testing.assert_array_equal(res.tokens, ref.tokens)


def test_engine_generate_on_pp_mesh_odd_batch():
    """Engine + mesh integration: 3 prompts on a data=2 x stage=2 x tensor=2
    mesh (batch padded internally, dummy rows stripped)."""
    from butterfly_tpu.engine import InferenceEngine, SamplingParams
    cfg = pp_cfg(num_layers=4)
    mesh = make_mesh(MeshConfig(data=2, stage=2, tensor=2))
    params = shard_params(Model(cfg).init(jax.random.PRNGKey(3)), cfg, mesh)
    engine = InferenceEngine(Model(cfg), params, mesh=mesh)
    prompts = [[1, 2, 3], [4, 5], [6]]
    res = engine.generate(prompts, SamplingParams(max_new_tokens=4))
    assert res.tokens.shape == (3, 4)
    assert res.lengths.shape == (3,)

    ref = InferenceEngine(Model(cfg),
                          Model(cfg).init(jax.random.PRNGKey(3))).generate(
        prompts, SamplingParams(max_new_tokens=4))
    np.testing.assert_array_equal(res.tokens, ref.tokens)
