"""Warm-prefix flash prefill (ISSUE 13): kernel + serving-path parity.

The warm multi-token prefill path (chunk continuations, prefix-cache
resumes, warm gang members) dispatches the flash kernel with a cached-
prefix segment instead of the dense O(T*S_max) fallback. Contract:

* kernel level — the prefix segment folds into the online softmax
  exactly like an inserted dense view, per-row count-masked at `start`
  (garbage past it NEVER contributes: recycled buffers are not zeroed);
* serving level — greedy outputs are token-identical to the dense path
  across fresh/warm x chunk sizes x int8/f32 cache x ragged-start gangs
  with padding rows x prefix-hit resume;
* policy level — prefill gangs stop splitting by freshness when the
  warm program is flash-capable (prefill_flash_warm), and
  prefill_flash_warm=False restores the seed behavior exactly.

Interpret mode runs the exact kernel code path on CPU (tier-1).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from butterfly_tpu.core.config import RuntimeConfig, tiny
from butterfly_tpu.engine.serving import ServingEngine
from butterfly_tpu.models.common import (Model, attend, forward, init_cache,
                                         quantize_kv)
from butterfly_tpu.ops.flash_attention import flash_attention
from butterfly_tpu.sched.scheduler import Scheduler

CFG = tiny("llama", dtype="float32", param_dtype="float32")


# ---------------------------------------------------------------------------
# Kernel units (interpret mode = the exact kernel code path)
# ---------------------------------------------------------------------------


def _dense_warm_ref(q, k, v, pk, pv, start):
    """Dense reference: fresh chunk inserted into the prefix view at each
    row's start, causal mask over absolute positions."""
    B, T = q.shape[:2]
    Sp = pk.shape[1]
    rows = []
    for b in range(B):
        S = Sp + T
        kk = jnp.zeros((S,) + pk.shape[2:]).at[:Sp].set(pk[b])
        vv = jnp.zeros((S,) + pv.shape[2:]).at[:Sp].set(pv[b])
        s = int(start[b])
        kk = kk.at[s:s + T].set(k[b])
        vv = vv.at[s:s + T].set(v[b])
        pos = s + jnp.arange(T)
        mask = (jnp.arange(S)[None, :] <= pos[:, None])[None]
        rows.append(attend(q[b:b + 1], kk[None], vv[None], mask, None)[0])
    return jnp.stack(rows)


def test_warm_prefix_kernel_parity_and_garbage():
    """Float prefix segment: parity with the dense insert reference over
    ragged starts (including 0 = a fresh/padding row riding the warm
    program), and garbage past `start` must not change one bit."""
    B, T, Nq, Kv, H, Sp = 3, 12, 4, 2, 16, 40
    ks = jax.random.split(jax.random.PRNGKey(0), 5)
    q = jax.random.normal(ks[0], (B, T, Nq, H))
    k = jax.random.normal(ks[1], (B, T, Kv, H))
    v = jax.random.normal(ks[2], (B, T, Kv, H))
    pk = jax.random.normal(ks[3], (B, Sp, Kv, H))
    pv = jax.random.normal(ks[4], (B, Sp, Kv, H))
    start = jnp.asarray([7, 0, 33], jnp.int32)

    out = flash_attention(q, k, v, block_q=8, block_k=8,
                          prefix_k=pk, prefix_v=pv, prefix_len=start)
    ref = _dense_warm_ref(q, k, v, pk, pv, start)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    # poison the prefix past each row's start: bit-identical output
    poisoned = pk
    for b, s in enumerate([7, 0, 33]):
        poisoned = poisoned.at[b, s:].set(1e3)
    out2 = flash_attention(q, k, v, block_q=8, block_k=8,
                          prefix_k=poisoned, prefix_v=pv, prefix_len=start)
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(out))


def test_warm_prefix_kernel_int8_parity():
    """int8 prefix (codes [B,Kv,Sp,H] + per-vector scales, the pool
    representation): in-kernel dequantization matches the dense attend
    over the dequantized view."""
    B, T, Nq, Kv, H, Sp = 2, 10, 4, 2, 16, 24
    ks = jax.random.split(jax.random.PRNGKey(1), 5)
    q = jax.random.normal(ks[0], (B, T, Nq, H))
    k = jax.random.normal(ks[1], (B, T, Kv, H))
    v = jax.random.normal(ks[2], (B, T, Kv, H))
    pkf = jax.random.normal(ks[3], (B, Sp, Kv, H))
    pvf = jax.random.normal(ks[4], (B, Sp, Kv, H))
    start = jnp.asarray([17, 5], jnp.int32)

    kq, ksc = quantize_kv(pkf)          # [B,Sp,Kv,H] codes, [B,Sp,Kv]
    vq, vsc = quantize_kv(pvf)
    out = flash_attention(
        q, k, v, block_q=8, block_k=8,
        prefix_k=jnp.moveaxis(kq, 2, 1), prefix_v=jnp.moveaxis(vq, 2, 1),
        prefix_len=start,
        prefix_k_scale=jnp.moveaxis(ksc, 2, 1),
        prefix_v_scale=jnp.moveaxis(vsc, 2, 1))
    ref = _dense_warm_ref(q, k, v,
                          kq.astype(jnp.float32) * ksc[..., None],
                          vq.astype(jnp.float32) * vsc[..., None], start)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# Serving-path parity
# ---------------------------------------------------------------------------


def _prompts(seed, lens):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, CFG.vocab_size - 2, (n,)).tolist() for n in lens]


def _run(model, params, prompts, *, use_kernels, warm_flash, kv_quant="none",
         chunk=16, max_new=8, prefix_caching=False, resume=None):
    # mixed_dispatch=False: this file exercises the ALTERNATING path's
    # batched warm/dense prefill programs (under mixed dispatch, the
    # default, prompts ride the fused decode block and prefill_batch
    # never dispatches — test_mixed_dispatch.py covers that path)
    rt = RuntimeConfig(max_batch_size=4, max_seq_len=128, page_size=8,
                       prefill_chunk=chunk, prefill_max_batch=4,
                       prefill_flash_warm=warm_flash, kv_quant=kv_quant,
                       prefix_caching=prefix_caching,
                       mixed_dispatch=False)
    sched = Scheduler(ServingEngine(model, params, rt,
                                    use_kernels=use_kernels))
    reqs = [sched.submit(p, max_new_tokens=max_new) for p in prompts]
    sched.run_until_done()
    outs = [r.output for r in reqs]
    if resume is not None:
        # prefix-hit resume: a later request sharing a registered prefix
        # admits warm (cached_at_admit > 0) and its FIRST chunk runs the
        # warm path at start = cached
        r = sched.submit(resume, max_new_tokens=max_new)
        sched.run_until_done()
        if prefix_caching:
            assert r.cached_at_admit > 0
        outs.append(r.output)
    return outs


def test_serving_warm_flash_vs_dense_parity():
    """Chunked multi-request prefill through the scheduler: the flash
    engine (fresh + warm kernels, merged gangs) must be token-identical
    to the all-dense engine. Prompt lengths straddle chunk boundaries so
    admission rounds mix warm continuations with fresh arrivals (ragged
    starts) and odd gang widths pad (padding rows ride the null page)."""
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(42))
    prompts = _prompts(0, (40, 23, 37))
    dense = _run(model, params, prompts, use_kernels=False, warm_flash=False)
    flash = _run(model, params, prompts, use_kernels=True, warm_flash=True)
    assert dense == flash


def test_serving_warm_flash_prefix_hit_resume_parity():
    """Prefix-cache resume: the second request's first chunk starts warm
    at the cached length; flash and dense engines agree token-for-token
    and the hit actually happened."""
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(43))
    shared = list(range(1, 17))          # two full 8-token pages
    first = [shared + [5, 9]]
    resume = shared + [7, 3, 2]
    dense = _run(model, params, first, use_kernels=False, warm_flash=False,
                 prefix_caching=True, resume=resume)
    flash = _run(model, params, first, use_kernels=True, warm_flash=True,
                 prefix_caching=True, resume=resume)
    assert dense == flash


@pytest.mark.parametrize("kv_quant,chunk", [("none", 8), ("int8", 8),
                                            ("int8", 16)])
def test_warm_flash_parity_grid(kv_quant, chunk):
    """The acceptance grid: warm-flash vs dense byte-parity across cache
    quantization x chunk size, with gangs of ragged lengths + a prefix-
    hit resume leg (slow tier: several engine compiles)."""
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(44))
    shared = list(range(1, 17))
    prompts = [shared + p for p in _prompts(7, (9, 22))] + _prompts(8, (31,))
    resume = shared + [11, 4]
    kw = dict(kv_quant=kv_quant, chunk=chunk, prefix_caching=True,
              resume=resume)
    dense = _run(model, params, prompts, use_kernels=False,
                 warm_flash=False, **kw)
    flash = _run(model, params, prompts, use_kernels=True,
                 warm_flash=True, **kw)
    kernel_dense = _run(model, params, prompts, use_kernels=True,
                        warm_flash=False, **kw)
    assert dense == flash
    assert dense == kernel_dense


def test_engine_prefill_batch_ragged_starts_direct():
    """Engine-level unit: ONE warm prefill_batch dispatch with ragged
    starts (a carried warm member, a shorter warm member, a fresh
    member) and an implicit padding row (B=3 buckets to 4). Last-token
    logits must match the dense engine's bit-for-near-bit."""
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(45))
    rt = RuntimeConfig(max_batch_size=4, max_seq_len=64, page_size=8)
    rng = np.random.RandomState(3)
    toks = [rng.randint(1, 250, (n,)).tolist() for n in (24, 8, 10)]
    outs = {}
    for use_k in (False, True):
        eng = ServingEngine(model, params, rt, use_kernels=use_k)
        # hand each slot a private page run (no allocator needed)
        for slot in range(3):
            eng.set_table_row(slot, list(range(slot * 8, slot * 8 + 8)))
        # seed slots 0/1 with fresh context of different lengths
        eng.prefill_batch([0, 1], [toks[0], toks[1]], [0, 0])
        # ONE warm gang: starts 24 / 8 / 0 — ragged + a fresh row
        logits = eng.prefill_batch([0, 1, 2], [[5, 9, 2], [7, 7], toks[2]],
                                   [24, 8, 0])
        outs[use_k] = np.asarray(logits)
    np.testing.assert_allclose(outs[True], outs[False],
                               rtol=3e-5, atol=3e-5)
    assert (outs[True].argmax(-1) == outs[False].argmax(-1)).all()


def test_contiguous_warm_flash_parity():
    """models.common.forward warm multi-token chunk (the contiguous-
    cache path: engine verify / chunk continuation) takes the kernel
    under attn_impl=flash and matches dense, float and int8 caches."""
    for quant in ("none", "int8"):
        cfg_d = CFG
        cfg_f = CFG.replace(attn_impl="flash")
        model = Model(cfg_d)
        params = model.init(jax.random.PRNGKey(1))
        toks = jax.random.randint(jax.random.PRNGKey(2), (2, 24), 1, 250)
        outs = {}
        for name, cfg in (("dense", cfg_d), ("flash", cfg_f)):
            cache = init_cache(cfg, 2, 64, quant=quant)
            _, cache = forward(params, cfg, toks[:, :10], cache, fresh=True)
            l2, cache = forward(params, cfg, toks[:, 10:], cache)
            outs[name] = np.asarray(l2)
        np.testing.assert_allclose(outs["dense"], outs["flash"],
                                   rtol=3e-5, atol=3e-5)
        assert (outs["dense"].argmax(-1) == outs["flash"].argmax(-1)).all()


# ---------------------------------------------------------------------------
# Dispatch policy
# ---------------------------------------------------------------------------


def test_warm_flash_dispatches_kernel(monkeypatch):
    """The warm program must actually take the kernel: count
    flash_attention_sharded calls carrying a prefix segment from inside
    the paged layer body. Flag off, warm dispatches must make none."""
    import butterfly_tpu.cache.paged as paged

    calls = {"prefix": 0, "fresh": 0}
    real = paged.flash_attention_sharded

    def spy(*args, **kw):
        calls["prefix" if kw.get("prefix_k") is not None else "fresh"] += 1
        return real(*args, **kw)

    monkeypatch.setattr(paged, "flash_attention_sharded", spy)
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(46))
    prompts = _prompts(9, (20,))
    _run(model, params, prompts, use_kernels=True, warm_flash=True, chunk=8)
    assert calls["prefix"] > 0 and calls["fresh"] > 0
    calls.update(prefix=0, fresh=0)
    _run(model, params, prompts, use_kernels=True, warm_flash=False, chunk=8)
    assert calls["prefix"] == 0  # dense warm program never sees a prefix


def test_gang_split_policy_properties():
    """prefill_gang_split_fresh pins the bucketing rule: split ONLY with
    prefill_flash_warm off (the seed behavior); warm_prefill_flash says
    whether the warm program is actually kernelized (kernels AND flag)."""
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(47))
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=64, page_size=8)
    grid = [
        # (use_kernels, flag) -> (warm_prefill_flash, split_fresh)
        ((True, True), (True, False)),
        ((True, False), (False, True)),
        ((False, True), (False, False)),
        ((False, False), (False, True)),
    ]
    for (use_k, flag), (want_flash, want_split) in grid:
        eng = ServingEngine(model, params,
                            rt.replace(prefill_flash_warm=flag),
                            use_kernels=use_k)
        assert eng.warm_prefill_flash == want_flash
        assert eng.prefill_gang_split_fresh == want_split
