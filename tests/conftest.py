"""Test harness: force an 8-fake-device CPU backend (SURVEY.md §4).

Every mesh/collective/partitioner/pipeline test runs on one host by
pretending to have 8 CPU devices. The axon sitecustomize registers the real
TPU backend at interpreter start and pins JAX_PLATFORMS=axon, so a plain
env setdefault is not enough: we must override via jax.config before any
backend is initialized.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

# Sanitizer mode (SURVEY.md §5 race-detection row): BUTTERFLY_DEBUG_NANS=1
# makes every jitted program re-run op-by-op on the first NaN and raise,
# turning silent numeric corruption into a test failure. Off by default
# because it disables donation and slows the suite.
if os.environ.get("BUTTERFLY_DEBUG_NANS") == "1":
    jax.config.update("jax_debug_nans", True)

import pytest  # noqa: E402


def pytest_configure(config):
    assert jax.default_backend() == "cpu", "tests must run on the CPU backend"
    assert len(jax.devices()) == 8, "tests expect 8 fake CPU devices"
    # Best-effort build of the native runtime lib so tests/test_native.py
    # and the scheduler's native-allocator path run in CI; rebuilt when
    # the C++ source is newer than the .so (a stale binary must never be
    # what the parity tests validate). On failure (no g++) those tests
    # skip and everything falls back to Python.
    from pathlib import Path
    from butterfly_tpu.native import _LIB_PATH
    src = Path(__file__).parent.parent / "native" / "allocator.cc"
    stale = (not _LIB_PATH.exists()
             or (src.exists()
                 and src.stat().st_mtime > _LIB_PATH.stat().st_mtime))
    if stale:
        try:
            from butterfly_tpu.native.build import build
            build(verbose=False)
        except FileNotFoundError:
            pass  # no g++ in this environment: tests skip, Python fallback
        # any other failure (real compile error) must fail the session
        # loudly, not silently skip the native parity tests


#: Two-tier suite (SURVEY.md §4 test contract): `-m "not slow"` is the
#: fast core (engine/scheduler/cache/server parity on tiny models, a few
#: minutes single-process); `slow` is everything mesh/pipeline/
#: distributed/HF-parity-heavy (each worker pays the 8-fake-device XLA
#: compile tax repeatedly). Files here are wholly slow; SLOW_TESTS marks
#: the individually expensive cases inside otherwise-fast files.
SLOW_FILES = {
    "test_serving_mesh.py", "test_distributed.py", "test_sequence.py",
    "test_pipeline.py", "test_partition.py", "test_models.py",
    "test_ckpt.py", "test_speculative.py", "test_expert.py",
    "test_kernels.py", "test_kv_quant.py", "test_donation.py",
    "test_quant.py", "test_paged.py",
}
SLOW_TESTS = {
    # engine-backed prefix-caching scenarios (each compiles a scheduler)
    "test_prefix_caching_on_data_tensor_mesh",
    "test_cached_tokens_match_uncached",
    "test_second_request_hits_cache",
    "test_generated_tokens_extend_the_cache",
    "test_concurrent_identical_prompts_share_pages",
    "test_chunked_prefill_with_prefix_caching",
    "test_preempted_request_readmits_via_cache",
    "test_parity_under_preemption_pressure",
    # native twin driven through a full scheduler
    "test_scheduler_runs_on_native_allocator",
    # scheduler scenarios beyond the core parity set
    "test_queue_when_slots_full",
    "test_staggered_admission",
    "test_preemption_under_page_pressure",
    "test_chunked_prefill_parity",
    "test_chunked_prefill_interleaves_decode",
    "test_static_scheduler_drains_batches",
    "test_stop_token_frees_slot",
    "test_request_sized_to_page_cap_completes",
    "test_speculative_scheduler_accepts_drafts",
    "test_speculative_scheduler_stop_token",
    # spec-block scenarios that compile several schedulers (the fast
    # tier still covers the block path: greedy parity, sampling
    # support, and the no-per-round-barrier pipelining property)
    "test_speculative_parity_grid",
    "test_speculative_per_request_opt_out",
    "test_speculative_parity_under_preemption_pressure",
    # draft-model speculation grids (ISSUE 14; each combo compiles a
    # scheduler + the draft programs — the fast tier still covers the
    # path: test_draft_model_spec_greedy_parity anchors one operating
    # point and test_draft_kv_rollback_exact pins the KV invariant)
    "test_draft_model_spec_parity_grid",
    "test_draft_model_spec_int8_parity",
    "test_draft_model_seeded_sampling_reproducible",
    "test_model_drafting_beats_ngram_on_mixed_chat",
    "test_legacy_draft_fn_contract_still_registers",
    # fused-block scenarios that compile a second scheduler / a wide
    # scan (the fast tier still covers the fused path: every core
    # parity test decodes through it, incl. test_decode_steps_per_tick)
    "test_fused_block_greedy_parity",
    "test_fused_block_seeded_sampling_reproducible",
    # batched group-prefill scenarios that compile a second scheduler
    # or several reference engines (the fast tier still covers the gang
    # path: prefill_max_batch defaults to 8, so every core parity test
    # prefills through batched dispatches, and
    # test_gang_admission_single_tick pins the one-dispatch property)
    "test_batched_prefill_parity",
    "test_batched_prefill_budget_and_carry",
    "test_mixed_warm_cold_group_admission",
    "test_preempt_partially_prefilled_group_member",
    "test_prefill_group_member_is_preemption_victim",
    # dispatch-ahead scenarios that compile a second scheduler / run a
    # reference engine (the fast tier still covers the pipeline:
    # inflight_blocks defaults to 2, so every core parity test decodes
    # through it, and the cadence/cancel/barrier tests pin the lazy-
    # drain behavior directly)
    "test_pipelined_greedy_parity_vs_synchronous",
    "test_pipelined_greedy_parity_fused_k8",
    "test_pipelined_parity_under_page_pressure",
    # warm-prefix flash prefill grid (ISSUE 13): 3 engine compiles per
    # param (the fast tier still pins the contract directly: the kernel
    # units, the chunked vs-dense parity, the prefix-hit resume, and
    # the dispatch-policy tests all run fast-tier)
    "test_warm_flash_parity_grid",
    # write-combined KV window grids: 8 (resp. 4) scheduler compiles
    # each (the fast tier still pins the contract directly:
    # kv_write_combine defaults on so EVERY parity test above decodes
    # through the window, test_kv_window_off_matches_on pins on/off
    # byte-equality + the flush instruments, and the flush-before-
    # reclaim / spec-rejection tests pin the drain semantics)
    "test_kv_window_greedy_parity_grid",
    "test_kv_window_seeded_sampling_parity",
    "test_kv_window_spec_parity_grid",
    # fleet scenarios that compile one-or-more extra engines or spin a
    # multi-replica in-process topology (the fast tier keeps the pure-
    # host fleet units: allocator transfer surface, load_score page
    # pressure, role-filtered candidates, topology parsing)
    "test_export_import_roundtrip_and_warm_hit",
    "test_export_reports_missing_tail",
    "test_import_refuses_geometry_mismatch",
    "test_import_idempotent",
    "test_health_carries_fleet_signals",
    "test_kv_endpoint_roundtrip_over_http",
    "test_kv_export_bad_requests",
    "test_kv_import_mismatch_is_409",
    "test_fleet_state_table",
    "test_disaggregated_parity_with_single_replica",
    "test_short_prompt_routes_direct",
    "test_string_prompt_routes_direct",
    "test_handoff_falls_back_when_prefill_tier_dies",
    "test_fleet_soak_rolling_drain_restart",
    # fleet observability scenarios on the same in-process topologies
    # (the fast tier keeps the pure-host pieces: trace merging,
    # exposition parse/sum, trace_report --fleet smoke in test_obs.py)
    "test_fleet_trace_merged_waterfall",
    "test_fleet_trace_direct_request_and_unknown_id",
    "test_fleet_metrics_rollup_sums_match_replicas",
    # overload protection / chaos (ISSUE 8): the multi-engine scenarios
    # (the fast tier keeps the chaos-plan determinism, breaker cycle,
    # scheduler deadline/shed units, and the HTTP 504/429/503 surfaces)
    "test_fleet_deadline_spent_at_arrival_is_504",
    "test_chaos_soak_terminal_outcomes",
    "test_preempt_prefers_batch_victim",
    # elastic fleet (ISSUE 17): live spawn/retire topologies (the fast
    # tier keeps the whole control-loop unit grid on the fake pool —
    # including the hysteresis tests mutcheck leans on — plus topology
    # parsing)
    "test_spawned_replica_joins_and_serves",
    "test_retire_drains_without_dropping_requests",
    "test_autoscaler_closes_the_loop_on_a_live_fleet",
    "test_autoscale_benchmark_beats_static_peak",
    # long-context SP lane (ISSUE 20): interpret-mode Pallas grid + the
    # scheduler scenarios that compile an SP engine AND a dense twin
    # per combo (the fast tier keeps the merge-stats algebra and ONE
    # seq=4 int8 engine-level chunk-prefill parity anchor)
    "test_ring_block_parity_grid",
    "test_sp_sched_long_prefill_parity",
    "test_prefix_hit_after_long_prefill",
    "test_longctx_benchmark_smoke",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if (item.path.name in SLOW_FILES
                or item.name.split("[")[0] in SLOW_TESTS):
            item.add_marker(pytest.mark.slow)


@pytest.fixture(scope="session")
def mesh8():
    from butterfly_tpu.core.config import MeshConfig
    from butterfly_tpu.core.mesh import make_mesh
    return make_mesh(MeshConfig(tensor=8))
