"""Stage-2 partitioner tests (SURVEY.md §7): TP parity + collective placement.

Runs on 8 fake CPU devices (conftest). Parity: sharded TP=8 forward must
match the single-device forward bit-for-bit-ish (f32, highest precision).
HLO: row-parallel wo/w_down must induce all-reduce (or reduce-scatter +
all-gather) in the compiled program.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from butterfly_tpu.core.config import MeshConfig, tiny
from butterfly_tpu.core.mesh import make_mesh
from butterfly_tpu.models.common import Model, forward, init_cache
from butterfly_tpu.parallel.partition import (
    cache_specs, compiled_hlo, count_collectives, param_specs, shard_cache,
    shard_params, to_shardings)


def tp_cfg(arch="llama"):
    """Tiny config whose dims divide a tensor=8 mesh."""
    kw = dict(vocab_size=256, hidden_size=64, num_heads=8, num_kv_heads=8,
              head_dim=8, intermediate_size=128, dtype="float32",
              param_dtype="float32")
    return tiny(arch, **kw)


def run_single(cfg, params, tokens):
    cache = init_cache(cfg, batch=tokens.shape[0], max_seq=32)
    logits, _ = jax.jit(lambda p, t, c: forward(p, cfg, t, c))(
        params, tokens, cache)
    return logits


@pytest.mark.parametrize("arch", ["llama", "gpt2", "mixtral"])
def test_tp8_parity(arch):
    cfg = tp_cfg(arch)
    model = Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 12)))
    ref = run_single(cfg, params, tokens)

    mesh = make_mesh(MeshConfig(tensor=8))
    sparams = shard_params(params, cfg, mesh)
    cache = shard_cache(init_cache(cfg, batch=2, max_seq=32), cfg, mesh)
    tokens_s = jax.device_put(tokens, NamedSharding(mesh, P()))

    with mesh:
        logits, new_cache = jax.jit(
            lambda p, t, c: forward(p, cfg, t, c))(sparams, tokens_s, cache)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    want = NamedSharding(mesh, cache_specs(cfg, mesh).k)
    assert new_cache.k.sharding.is_equivalent_to(want, new_cache.k.ndim)


def test_tp_specs_match_param_tree():
    """Every param leaf has a spec of matching rank; no leaf missed."""
    for arch in ("llama", "gpt2", "mixtral"):
        cfg = tp_cfg(arch)
        mesh = make_mesh(MeshConfig(tensor=8))
        params = Model(cfg).init(jax.random.PRNGKey(0))
        specs = param_specs(cfg, mesh)
        flat_p = jax.tree_util.tree_leaves_with_path(params)
        flat_s = jax.tree_util.tree_leaves_with_path(
            specs, is_leaf=lambda x: isinstance(x, P))
        assert [k for k, _ in flat_p] == [k for k, _ in flat_s]
        for (kp, arr), (_, spec) in zip(flat_p, flat_s):
            assert len(spec) <= arr.ndim, f"{kp}: spec {spec} vs {arr.shape}"
            for dim, ax in zip(arr.shape, spec):
                if ax is not None:
                    assert dim % mesh.shape[ax] == 0, (kp, spec, arr.shape)


def test_tp8_hlo_has_allreduce():
    """Row-parallel wo/w_down must produce cross-device reduction ops."""
    cfg = tp_cfg("llama")
    mesh = make_mesh(MeshConfig(tensor=8))
    params = shard_params(Model(cfg).init(jax.random.PRNGKey(0)), cfg, mesh)
    cache = shard_cache(init_cache(cfg, batch=2, max_seq=32), cfg, mesh)
    tokens = jax.device_put(
        jnp.zeros((2, 8), jnp.int32), NamedSharding(mesh, P()))
    hlo = compiled_hlo(lambda p, t, c: forward(p, cfg, t, c),
                       params, tokens, cache, mesh=mesh)
    counts = count_collectives(hlo)
    reductions = (counts["all-reduce"] + counts["reduce-scatter"]
                  + counts["all-gather"])
    assert reductions > 0, f"no cross-device reduction in HLO: {counts}"


def test_uneven_dims_replicate():
    """A cfg whose heads don't divide the mesh still shards what it can."""
    cfg = tiny("llama", dtype="float32", param_dtype="float32")  # 4 heads
    mesh = make_mesh(MeshConfig(tensor=8))
    specs = param_specs(cfg, mesh)
    assert specs["layers"]["attn"]["wq"] == P(None, None, None, None)
    # intermediate 128 divides 8 -> still sharded
    assert specs["layers"]["mlp"]["w_up"] == P(None, None, "tensor")

    # and the model still runs + matches
    params = Model(cfg).init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.RandomState(1).randint(0, cfg.vocab_size, (2, 6)))
    ref = run_single(cfg, params, tokens)
    sparams = shard_params(params, cfg, mesh)
    cache = shard_cache(init_cache(cfg, batch=2, max_seq=32), cfg, mesh)
    with mesh:
        logits, _ = jax.jit(lambda p, t, c: forward(p, cfg, t, c))(
            sparams, tokens, cache)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_dp_tp_compose():
    """data=2 x tensor=4: batch sharded over data, params over tensor."""
    cfg = tp_cfg("llama")
    mesh = make_mesh(MeshConfig(data=2, tensor=4))
    params = Model(cfg).init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.RandomState(2).randint(0, cfg.vocab_size, (4, 10)))
    ref = run_single(cfg, params, tokens)

    sparams = shard_params(params, cfg, mesh)
    cache = shard_cache(init_cache(cfg, batch=4, max_seq=32), cfg, mesh)
    tokens_s = jax.device_put(tokens, NamedSharding(mesh, P("data", None)))
    with mesh:
        logits, _ = jax.jit(lambda p, t, c: forward(p, cfg, t, c))(
            sparams, tokens_s, cache)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
