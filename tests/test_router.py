"""Multi-replica router tests: prefix affinity, health-aware failover,
streaming passthrough (ISSUE 2).

Fast tier: everything runs in-process — two tiny-model `serve` replicas
behind one router, plus stdlib stub backends for the failure-injection
cases (a replica that dies mid-stream, a port with nothing listening).
"""
import http.client
import json
import socket
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from types import SimpleNamespace

import jax
import pytest

from butterfly_tpu.core.config import RuntimeConfig, tiny
from butterfly_tpu.engine.serving import ServingEngine
from butterfly_tpu.models.common import Model
from butterfly_tpu.obs.registry import MetricsRegistry
from butterfly_tpu.router.policy import (
    HashRing, PrefixAffinityPolicy, affinity_key)
from butterfly_tpu.router.pool import ReplicaPool
from butterfly_tpu.router.proxy import (
    RouterState, extract_route_tokens, make_router_handler)
from butterfly_tpu.sched.scheduler import Scheduler
from butterfly_tpu.serve.server import ServerState, make_handler
from butterfly_tpu.utils.tokenizer import ByteTokenizer

CFG = tiny("llama", dtype="float32", param_dtype="float32")
PAGE = 8
AFF_BLOCKS = 4  # affinity key hashes the leading 4 full pages (32 toks)


def _start_replica():
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(0))
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=64, page_size=PAGE,
                       num_pages=24, prefix_caching=True)
    sched = Scheduler(ServingEngine(model, params, rt))
    state = ServerState(sched, ByteTokenizer())
    state.thread.start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return SimpleNamespace(state=state, httpd=httpd, sched=sched,
                           rid=f"127.0.0.1:{httpd.server_port}",
                           url=f"http://127.0.0.1:{httpd.server_port}")


def _start_router(backends, **kw):
    registry = MetricsRegistry()
    pool = ReplicaPool(backends, probe_interval=0.2, registry=registry,
                       **kw)
    policy = PrefixAffinityPolicy(pool, page_size=PAGE,
                                  affinity_blocks=AFF_BLOCKS)
    state = RouterState(pool, policy, registry=registry,
                        read_timeout=120.0)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                make_router_handler(state))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return SimpleNamespace(pool=pool, policy=policy, state=state,
                           httpd=httpd,
                           url=f"http://127.0.0.1:{httpd.server_port}")


@pytest.fixture(scope="module")
def cluster():
    """Two real tiny-model replicas behind one router. The pool's prober
    runs so health scrapes happen, but replicas start optimistically
    live — tests never wait on a probe cycle."""
    reps = [_start_replica(), _start_replica()]
    router = _start_router([r.rid for r in reps])
    router.pool.start()
    yield SimpleNamespace(router=router, reps=reps,
                          by_rid={r.rid: r for r in reps})
    router.pool.stop()
    router.httpd.shutdown()
    for r in reps:
        r.state.stop.set()
        r.httpd.shutdown()


def post(url, path, obj, raw=False, timeout=120):
    req = urllib.request.Request(
        url + path, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    resp = urllib.request.urlopen(req, timeout=timeout)
    return resp if raw else (json.loads(resp.read()), resp.headers)


def get(url, path):
    return urllib.request.urlopen(url + path, timeout=30).read().decode()


# -- pure-logic units --------------------------------------------------------

def test_hash_ring_stability():
    """Removing one replica only remaps ITS arc: keys whose target
    survives keep their target (the property that preserves every other
    replica's warm cache on failover)."""
    rids = ["10.0.0.1:8000", "10.0.0.2:8000", "10.0.0.3:8000"]
    ring3 = HashRing(rids)
    ring2 = HashRing([rids[0], rids[2]])
    import hashlib
    moved = kept = 0
    for i in range(200):
        key = hashlib.sha256(b"key-%d" % i).digest()
        before = ring3.ordered(key)[0]
        after = ring2.ordered(key)[0]
        if before == rids[1]:
            moved += 1
            assert after in (rids[0], rids[2])
        else:
            kept += 1
            assert after == before, "surviving replica's key moved"
    assert moved > 0 and kept > 0  # both populations exercised


def test_hash_ring_failover_order_is_deterministic():
    ring = HashRing(["a:1", "b:1", "c:1"])
    key = b"\x42" * 32
    assert ring.ordered(key) == ring.ordered(key)
    assert sorted(ring.ordered(key)) == ["a:1", "b:1", "c:1"]


def test_affinity_key_block_granularity():
    """Same leading blocks -> same key regardless of tail; differing
    within the first block -> different key."""
    base = list(range(1, 1 + AFF_BLOCKS * PAGE))
    k1 = affinity_key(base + [7, 8, 9], PAGE, AFF_BLOCKS)
    k2 = affinity_key(base + [200, 201], PAGE, AFF_BLOCKS)
    assert k1 == k2
    changed = [99] + base[1:]
    assert affinity_key(changed, PAGE, AFF_BLOCKS) != k1
    # sub-block prompts still deterministic, and empty -> None
    assert affinity_key([1, 2], PAGE, AFF_BLOCKS) == \
        affinity_key([1, 2], PAGE, AFF_BLOCKS)
    assert affinity_key([], PAGE, AFF_BLOCKS) is None
    assert affinity_key(None, PAGE, AFF_BLOCKS) is None


def test_affinity_key_matches_prefix_cache_blocks():
    """The routing key IS the allocator's chain hash for the same
    blocks — the alignment that makes affinity line up with page
    reuse."""
    from butterfly_tpu.cache.prefix import chain_block_hashes
    toks = list(range(1, 1 + AFF_BLOCKS * PAGE + 5))
    assert affinity_key(toks, PAGE, AFF_BLOCKS) == \
        chain_block_hashes(toks, PAGE, AFF_BLOCKS)[-1]


def test_extract_route_tokens():
    def enc(obj):
        return json.dumps(obj).encode()
    assert extract_route_tokens(enc({"tokens": [1, 2, 3]})) == [1, 2, 3]
    assert extract_route_tokens(enc({"prompt": [4, 5]})) == [4, 5]
    assert extract_route_tokens(enc({"prompt": "hi"})) == [104, 105]
    assert extract_route_tokens(b"not json") is None
    assert extract_route_tokens(enc({"prompt": 7})) is None
    assert extract_route_tokens(b"") is None


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_pool_degrades_then_dead_with_backoff():
    """Consecutive connect failures walk live -> degraded -> dead; dead
    re-probes are scheduled with jittered exponential backoff."""
    pool = ReplicaPool([f"127.0.0.1:{_free_port()}"], dead_after=3,
                       backoff_base=0.5, backoff_max=10.0)
    (r,) = pool.replicas.values()
    assert r.state == "live"  # optimistic until evidence
    pool.probe_one(r)
    assert r.state == "degraded" and r.fails == 1
    pool.probe_one(r)
    assert r.state == "degraded" and r.fails == 2
    t0 = time.monotonic()
    pool.probe_one(r)
    assert r.state == "dead" and r.fails == 3
    delay = r.next_probe_t - t0
    # base * 2^0 = 0.5s, jittered x[0.5, 1.5)
    assert 0.2 <= delay <= 0.8
    pool.probe_one(r)  # deeper backoff grows the delay window
    assert r.next_probe_t - time.monotonic() <= 10.0 * 1.5
    assert pool.candidates() == []  # dead members are never candidates


def test_pool_parses_health_load_signal(cluster):
    pool = cluster.router.pool
    pool.probe_all()
    for snap in pool.snapshot():
        assert snap["state"] == "live"
        assert snap["queue_depth"] >= 0 and snap["active"] >= 0


# -- routing through real replicas ------------------------------------------

def test_proxy_roundtrip_and_replica_header(cluster):
    out, headers = post(cluster.router.url, "/generate",
                        {"tokens": [5, 7, 11], "max_tokens": 4,
                         "stop_token": -1})
    assert len(out["tokens"]) == 4
    assert headers["X-Routed-To"] in cluster.by_rid
    # determinism through the router (both replicas share weights)
    again, _ = post(cluster.router.url, "/generate",
                    {"tokens": [5, 7, 11], "max_tokens": 4,
                     "stop_token": -1})
    assert again["tokens"] == out["tokens"]


def test_request_id_echoes_through_router(cluster):
    req = urllib.request.Request(
        cluster.router.url + "/generate",
        data=json.dumps({"tokens": [9, 9], "max_tokens": 2,
                         "stop_token": -1}).encode(),
        headers={"Content-Type": "application/json",
                 "X-Request-Id": "rte-42"})
    resp = urllib.request.urlopen(req, timeout=120)
    resp.read()
    assert resp.headers["X-Request-Id"] == "rte-42"


def test_same_prefix_lands_on_same_replica_and_hits_cache(cluster):
    """Two same-prefix requests route to one replica and the second is
    served from its prefix cache (hit counter rises THERE)."""
    prefix = [(13 * i) % 250 + 1 for i in range(AFF_BLOCKS * PAGE)]
    before = {r.rid: r.sched.alloc.hit_tokens for r in cluster.reps}
    _, h1 = post(cluster.router.url, "/generate",
                 {"tokens": prefix + [3, 1], "max_tokens": 2,
                  "stop_token": -1})
    _, h2 = post(cluster.router.url, "/generate",
                 {"tokens": prefix + [4, 2], "max_tokens": 2,
                  "stop_token": -1})
    rid = h1["X-Routed-To"]
    assert h2["X-Routed-To"] == rid, "same prefix must share a replica"
    hit = cluster.by_rid[rid].sched.alloc.hit_tokens - before[rid]
    assert hit >= AFF_BLOCKS * PAGE, \
        f"second request should hit the shared prefix pages, got {hit}"
    other = next(r for r in cluster.reps if r.rid != rid)
    assert other.sched.alloc.hit_tokens == before[other.rid]
    # and the router counted the affinity routing
    text = get(cluster.router.url, "/metrics")
    aff = [l for l in text.splitlines()
           if l.startswith("butterfly_router_affinity_hits_total ")]
    assert aff and float(aff[0].split()[-1]) >= 2


def test_affinity_beats_round_robin_under_shared_load(cluster):
    """ISSUE 2 acceptance: 50% shared-prefix workload -> prefix hits
    concentrate on the affinity replica, zero failed requests."""
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))
    try:
        from loadgen import run_load
    finally:
        sys.path.pop(0)
    before = {r.rid: r.sched.alloc.hit_tokens for r in cluster.reps}
    stats = run_load(cluster.router.url, clients=3,
                     requests_per_client=4, prefix_share=0.5,
                     shared_len=AFF_BLOCKS * PAGE, tail_len=4,
                     max_tokens=4, seed=7, vocab=64)
    assert stats["failed"] == 0, stats["errors"]
    assert stats["ok"] == 12
    assert stats["shared_prefix_requests"] >= 2  # workload sanity
    hits = {r.rid: r.sched.alloc.hit_tokens - before[r.rid]
            for r in cluster.reps}
    hot = max(hits.values())
    cold = min(hits.values())
    # every shared-prefix request after the first hits the one affinity
    # replica; round-robin would split them (and halve per-replica hits)
    assert hot >= (stats["shared_prefix_requests"] - 1) * AFF_BLOCKS * PAGE
    assert hot > 2 * cold, f"hits not concentrated: {hits}"
    # every request was routed and tagged (X-Routed-To accounting)
    assert sum(stats["by_replica"].values()) == 12, stats["by_replica"]


def test_sse_stream_through_router_byte_identical(cluster):
    """Router-proxied SSE == direct-to-replica SSE after de-chunking."""
    body = {"tokens": [21, 22, 23], "max_tokens": 3, "stream": True,
            "stop_token": -1}
    via_router = post(cluster.router.url, "/generate", body,
                      raw=True)
    routed_to = via_router.headers["X-Routed-To"]
    router_bytes = via_router.read()
    direct = post(cluster.by_rid[routed_to].url, "/generate", body,
                  raw=True)
    assert direct.read() == router_bytes
    assert via_router.headers["Content-Type"] == "text/event-stream"
    events = [l[6:] for l in router_bytes.split(b"\n")
              if l.startswith(b"data: ")]
    assert events[-1] == b"[DONE]" and len(events) == 4


def test_openai_completions_through_router(cluster):
    out, headers = post(cluster.router.url, "/v1/completions",
                        {"prompt": [5, 7, 11], "max_tokens": 3,
                         "stop_token": -1})
    assert out["object"] == "text_completion"
    assert headers["X-Routed-To"] in cluster.by_rid


def test_backend_4xx_forwarded_not_retried(cluster):
    before = cluster.router.state._c_retry.value
    with pytest.raises(urllib.error.HTTPError) as e:
        post(cluster.router.url, "/generate",
             {"tokens": [999999], "max_tokens": 2})
    assert e.value.code == 400
    assert json.loads(e.value.read())["error"] == "token id out of range"
    assert cluster.router.state._c_retry.value == before


def test_router_replicas_and_drain_workflow(cluster):
    body = json.loads(get(cluster.router.url, "/router/replicas"))
    assert {s["replica"] for s in body["replicas"]} == \
        set(cluster.by_rid)
    target = cluster.reps[0].rid
    out, _ = post(cluster.router.url, "/router/drain",
                  {"replica": target})
    assert out["state"] == "draining"
    try:
        for i in range(4):  # varied prompts: all must avoid the drained
            _, h = post(cluster.router.url, "/generate",
                        {"tokens": [40 + i, 41 + i], "max_tokens": 2,
                         "stop_token": -1})
            assert h["X-Routed-To"] != target
    finally:
        out, _ = post(cluster.router.url, "/router/undrain",
                      {"replica": target})
    assert out["state"] in ("live", "degraded")
    # unknown replica -> 404
    with pytest.raises(urllib.error.HTTPError) as e:
        post(cluster.router.url, "/router/drain", {"replica": "nope:1"})
    assert e.value.code == 404


def test_router_metrics_families(cluster):
    text = get(cluster.router.url, "/metrics")
    assert "# TYPE butterfly_router_requests_total counter" in text
    assert 'butterfly_router_requests_total{replica="' in text
    assert 'outcome="ok"' in text
    assert "butterfly_router_retries_total" in text
    assert "butterfly_router_affinity_hits_total" in text
    assert 'butterfly_router_outstanding_requests{replica="' in text
    # router health rolls up the pool
    health = json.loads(get(cluster.router.url, "/health"))
    assert health["status"] == "ok" and health["replicas_live"] >= 1


# -- failover ---------------------------------------------------------------

def _tokens_targeting(router, rid, length=AFF_BLOCKS * PAGE):
    """Deterministically find a token prompt whose affinity target is
    `rid` (ring lookup is pure, so this is not a race)."""
    for t in range(1, 300):
        cand, _ = router.policy.plan([t % 250 + 1] * length)
        if cand and cand[0].rid == rid:
            return [t % 250 + 1] * length
    raise AssertionError(f"no prompt maps to {rid}")


def test_connect_refused_fails_over_with_zero_failures(cluster):
    """A dead-port backend (replica SIGKILLed and gone) never fails an
    un-started request: the router retries it onto the survivor."""
    dead = f"127.0.0.1:{_free_port()}"
    live = cluster.reps[0]
    router = _start_router([dead, live.rid])  # no prober: optimistic
    try:
        # a prompt whose affinity target is the dead member: first
        # attempt is refused, the retry lands on the survivor
        toks = _tokens_targeting(router, dead)
        out, h = post(router.url, "/generate",
                      {"tokens": toks, "max_tokens": 2,
                       "stop_token": -1})
        assert len(out["tokens"]) == 2
        assert h["X-Routed-To"] == live.rid
        assert router.state._c_retry.value >= 1
        # the connect failure derouted the corpse immediately: varied
        # follow-ups all succeed without touching it
        for i in range(5):
            out, h = post(router.url, "/generate",
                          {"tokens": [60 + i] * 8, "max_tokens": 2,
                           "stop_token": -1})
            assert len(out["tokens"]) == 2
            assert h["X-Routed-To"] == live.rid
        snap = {s["replica"]: s for s in router.pool.snapshot()}
        assert snap[dead]["state"] in ("degraded", "dead")
        assert snap[live.rid]["state"] == "live"
    finally:
        router.httpd.shutdown()


def test_replica_killed_between_requests_fails_over(cluster):
    """Kill one of two stub replicas mid-run: subsequent requests all
    succeed on the survivor (zero failed un-started requests)."""
    a, b = _StubReplica(), _StubReplica()
    router = _start_router([a.rid, b.rid])
    try:
        for i in range(4):
            post(router.url, "/generate",
                 {"tokens": [i + 1, i + 2], "max_tokens": 1})
        a.kill()  # hard stop: connects now refused
        for i in range(6):
            out, h = post(router.url, "/generate",
                          {"tokens": [70 + i] * 8, "max_tokens": 1})
            assert h["X-Routed-To"] == b.rid
        assert a.hits + b.hits == 10
    finally:
        router.httpd.shutdown()
        b.kill()


class _StubReplica:
    """Minimal backend speaking the serve protocol shape: JSON
    /generate, 200 /health. Counts requests; kill() frees the port."""

    def __init__(self):
        outer = self
        self.hits = 0

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _json(self, code, obj):
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._json(200, {"status": "ok", "queue_depth": 0,
                                 "active": 0})

            def do_POST(self):
                outer.hits += 1
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                self._json(200, {"tokens": [1], "text": "x",
                                 "ttft_s": 0.0, "total_s": 0.0})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.rid = f"127.0.0.1:{self.httpd.server_port}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def kill(self):
        self.httpd.shutdown()
        self.httpd.server_close()


class _DyingStreamReplica:
    """Backend that starts an SSE stream then dies mid-flight (socket
    closed without the terminating chunk) — the SIGKILL-mid-stream
    case."""

    def __init__(self, events_before_death=2):
        outer = self
        self.hits = 0

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                data = json.dumps({"status": "ok", "queue_depth": 0,
                                   "active": 0}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_POST(self):
                outer.hits += 1
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                for i in range(events_before_death):
                    payload = (b"data: " + json.dumps(
                        {"token": i, "text": "t"}).encode() + b"\n\n")
                    self.wfile.write(
                        f"{len(payload):X}\r\n".encode() + payload
                        + b"\r\n")
                    self.wfile.flush()
                # die: a real FIN with NO terminating 0-chunk (plain
                # close() would leak the fd via rfile/wfile references
                # and leave the router blocked instead of truncated)
                self.connection.shutdown(socket.SHUT_RDWR)
                self.close_connection = True

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.rid = f"127.0.0.1:{self.httpd.server_port}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()


def test_midstream_death_truncates_and_never_retries():
    """Bytes already sent -> the router must PROPAGATE the truncation,
    not re-run the request on the healthy replica (a retry would
    duplicate tokens the client already consumed)."""
    dying = _DyingStreamReplica()
    healthy = _StubReplica()
    router = _start_router([dying.rid, healthy.rid])
    try:
        # a prompt whose affinity target is the dying replica, so the
        # stream provably starts there (deterministic ring lookup)
        tokens = _tokens_targeting(router, dying.rid)
        host, port = router.url[len("http://"):].split(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=30)
        conn.request("POST", "/generate",
                     body=json.dumps({"tokens": tokens, "max_tokens": 8,
                                      "stream": True}).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("X-Routed-To") == dying.rid
        with pytest.raises((http.client.IncompleteRead,
                            ConnectionError, OSError)):
            # the partial events arrive, then the truncation surfaces as
            # an incomplete chunked body — NOT a clean EOF
            while True:
                if resp.read1(65536) == b"":
                    raise AssertionError(
                        "stream ended cleanly; truncation was masked")
        conn.close()
        assert healthy.hits == 0, \
            "mid-stream failure must never be retried"
        assert dying.hits == 1
        snap = {s["replica"]: s for s in router.pool.snapshot()}
        assert snap[dying.rid]["state"] in ("degraded", "dead")
    finally:
        router.httpd.shutdown()
        dying.httpd.shutdown()
        healthy.kill()


def test_wedged_503_is_retried_before_first_byte():
    """A wedged replica (503s everything) costs a retry, not a failure."""

    class _Wedged:
        def __init__(self):
            outer = self
            self.hits = 0

            class H(BaseHTTPRequestHandler):
                protocol_version = "HTTP/1.1"

                def log_message(self, fmt, *args):
                    pass

                def _json(self, code, obj):
                    data = json.dumps(obj).encode()
                    self.send_response(code)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)

                def do_GET(self):
                    self._json(503, {"status": "error",
                                     "detail": "wedged"})

                def do_POST(self):
                    outer.hits += 1
                    n = int(self.headers.get("Content-Length", 0))
                    self.rfile.read(n)
                    self._json(503, {"error": "server wedged: boom"})

            self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
            self.rid = f"127.0.0.1:{self.httpd.server_port}"
            threading.Thread(target=self.httpd.serve_forever,
                             daemon=True).start()

    wedged = _Wedged()
    healthy = _StubReplica()
    router = _start_router([wedged.rid, healthy.rid])
    try:
        # first request provably targets the wedged member: its 503 is
        # retried (no client bytes yet) onto the healthy one
        toks = _tokens_targeting(router, wedged.rid)
        _, h = post(router.url, "/generate",
                    {"tokens": toks, "max_tokens": 1})
        assert h["X-Routed-To"] == healthy.rid
        assert wedged.hits == 1
        for i in range(5):
            _, h = post(router.url, "/generate",
                        {"tokens": [80 + i] * 8, "max_tokens": 1})
            assert h["X-Routed-To"] == healthy.rid
        snap = {s["replica"]: s for s in router.pool.snapshot()}
        assert snap[wedged.rid]["state"] == "degraded"
        assert wedged.hits == 1, \
            "wedge feedback should deroute after the first 503"
    finally:
        router.httpd.shutdown()
        wedged.httpd.shutdown()
        healthy.kill()


def test_no_routable_replicas_is_503_with_retry_after():
    dead1 = f"127.0.0.1:{_free_port()}"
    dead2 = f"127.0.0.1:{_free_port()}"
    router = _start_router([dead1, dead2], dead_after=1)
    try:
        router.pool.probe_all()  # both marked dead immediately
        with pytest.raises(urllib.error.HTTPError) as e:
            post(router.url, "/generate",
                 {"tokens": [1, 2], "max_tokens": 1})
        assert e.value.code == 503
        assert e.value.headers["Retry-After"] == "1"
    finally:
        router.httpd.shutdown()
