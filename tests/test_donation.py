"""KV-cache buffer donation must actually alias in every decode path.

A "Some donated buffers were not usable" warning means XLA kept a second
full KV pool live (double HBM + a copy per decode step on real configs)
— so these tests turn that warning into a failure (VERDICT.md weak #2).
"""
import warnings

import jax
import numpy as np
import pytest

from butterfly_tpu.cache.allocator import PageAllocator
from butterfly_tpu.core.config import RuntimeConfig, tiny
from butterfly_tpu.engine.engine import InferenceEngine
from butterfly_tpu.engine.sampling import SamplingParams
from butterfly_tpu.engine.serving import ServingEngine
from butterfly_tpu.models.common import Model


DONATION_MSG = "donated buffers were not usable"


class _NoDonationWarnings:
    def __enter__(self):
        self._ctx = warnings.catch_warnings(record=True)
        self._rec = self._ctx.__enter__()
        warnings.simplefilter("always")
        return self

    def __exit__(self, *exc):
        self._ctx.__exit__(*exc)
        if exc[0] is None:
            bad = [str(w.message) for w in self._rec
                   if DONATION_MSG in str(w.message)]
            assert not bad, f"donation failed to alias: {bad}"
        return False


@pytest.fixture(scope="module")
def tiny_model():
    cfg = tiny("llama", dtype="float32", param_dtype="float32")
    model = Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


@pytest.mark.parametrize("fused", [True, False])
def test_generate_paths_alias(tiny_model, fused):
    model, params = tiny_model
    eng = InferenceEngine(model, params,
                          RuntimeConfig(max_seq_len=64))
    with _NoDonationWarnings():
        r = eng.generate([[1, 2, 3, 4], [5, 6, 7]],
                         SamplingParams(max_new_tokens=8, temperature=0.0),
                         fused=fused)
    assert r.tokens.shape == (2, 8)


def test_serving_paths_alias(tiny_model):
    model, params = tiny_model
    rt = RuntimeConfig(max_batch_size=4, max_seq_len=128,
                       page_size=16, num_pages=64)
    eng = ServingEngine(model, params, rt)
    alloc = PageAllocator(64, 16, 8)
    eng.set_table_row(0, alloc.grow(0, 64))
    with _NoDonationWarnings():
        eng.prefill_slot(0, [1, 2, 3, 4, 5])
        toks = np.zeros(4, np.int32)
        active = np.array([1, 0, 0, 0], np.int32)
        temps = np.zeros(4, np.float32)
        for i in range(3):
            toks, _ = eng.decode_active(toks, active, temps,
                                        jax.random.PRNGKey(i))
        # fused decode block: the scan-carried pools must alias too
        # (a non-aliasing carry would keep a second pool live for the
        # whole block — the exact cost the fusion exists to avoid)
        stops = np.full(4, -1, np.int32)
        budgets = np.full(4, 4, np.int32)
        eng.decode_block_async(toks, active, temps, stops, budgets,
                               jax.random.PRNGKey(9), 4)


def test_pipeline_generate_aliases(tiny_model):
    from butterfly_tpu.core.config import MeshConfig
    from butterfly_tpu.core.mesh import make_mesh

    cfg = tiny("llama", dtype="float32", param_dtype="float32",
               num_layers=4)
    model = Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    mesh = make_mesh(MeshConfig(stage=2, tensor=2, data=2))
    from butterfly_tpu.parallel.partition import shard_params
    params = shard_params(params, cfg, mesh)
    eng = InferenceEngine(model, params, RuntimeConfig(max_seq_len=64),
                          mesh=mesh, num_microbatches=2)
    with _NoDonationWarnings():
        r = eng.generate([[1, 2, 3]] * 2,
                         SamplingParams(max_new_tokens=4, temperature=0.0))
    assert r.tokens.shape == (2, 4)
