"""HTTP serving tests: in-process server on an ephemeral port.

Covers /generate (blocking + SSE streaming + token-id path), /metrics
prometheus output, /health, and input validation.
"""
import json
import threading
import urllib.request

import jax
import pytest

from butterfly_tpu.core.config import RuntimeConfig, tiny
from butterfly_tpu.engine.serving import ServingEngine
from butterfly_tpu.models.common import Model
from butterfly_tpu.sched.scheduler import Scheduler
from butterfly_tpu.serve.server import ServerState, make_handler
from butterfly_tpu.utils.tokenizer import ByteTokenizer

CFG = tiny("llama", dtype="float32", param_dtype="float32")


@pytest.fixture(scope="module")
def server():
    from http.server import ThreadingHTTPServer
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(0))
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=64, page_size=8)
    sched = Scheduler(ServingEngine(model, params, rt))
    state = ServerState(sched, ByteTokenizer())
    state.thread.start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_port}"
    state.stop.set()
    httpd.shutdown()


def post(url, path, obj, raw=False):
    req = urllib.request.Request(
        url + path, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    resp = urllib.request.urlopen(req, timeout=120)
    return resp if raw else json.loads(resp.read())


def get(url, path):
    return urllib.request.urlopen(url + path, timeout=30).read().decode()


def test_health(server):
    assert json.loads(get(server, "/health")) == {"status": "ok"}


def test_generate_blocking(server):
    out = post(server, "/generate",
               {"prompt": "hi", "max_tokens": 4, "stop_token": -1})
    assert len(out["tokens"]) == 4
    assert out["ttft_s"] >= 0 and out["total_s"] > 0


def test_generate_token_ids_deterministic(server):
    a = post(server, "/generate",
             {"tokens": [5, 7, 11], "max_tokens": 5, "stop_token": -1})
    b = post(server, "/generate",
             {"tokens": [5, 7, 11], "max_tokens": 5, "stop_token": -1})
    assert a["tokens"] == b["tokens"]


def test_generate_stream(server):
    resp = post(server, "/generate",
                {"prompt": "ab", "max_tokens": 3, "stream": True,
                 "stop_token": -1}, raw=True)
    assert resp.headers["Content-Type"] == "text/event-stream"
    events = []
    for line in resp:
        line = line.strip()
        if line.startswith(b"data: "):
            events.append(line[6:])
    assert events[-1] == b"[DONE]"
    toks = [json.loads(e)["token"] for e in events[:-1]]
    assert len(toks) == 3


def test_concurrent_clients(server):
    results = {}

    def hit(name, prompt):
        results[name] = post(server, "/generate",
                             {"tokens": prompt, "max_tokens": 4,
                              "stop_token": -1})
    threads = [threading.Thread(target=hit, args=(i, [i + 1, i + 2]))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert len(results) == 4
    # determinism: same prompt again matches
    again = post(server, "/generate",
                 {"tokens": [1, 2], "max_tokens": 4, "stop_token": -1})
    assert results[0]["tokens"] == again["tokens"]


def test_metrics_endpoint(server):
    text = get(server, "/metrics")
    assert "butterfly_requests_total" in text
    assert "# TYPE butterfly_tokens_generated_total counter" in text
    assert "butterfly_kv_pages_free" in text


def test_validation_errors(server):
    for body, code in [({"prompt": ""}, 400),
                       ({"tokens": [999999]}, 400),
                       ({"tokens": [1], "max_tokens": 10000}, 400)]:
        try:
            post(server, "/generate", body)
            raised = None
        except urllib.error.HTTPError as e:  # noqa: F821
            raised = e.code
        assert raised == code


import urllib.error  # noqa: E402


def test_scheduler_crash_degrades_health():
    """A tick() exception must not wedge the server: waiters unblock,
    /health goes 503, new submissions are rejected."""
    import queue as _q
    from butterfly_tpu.serve.server import ServerState
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(0))
    rt = RuntimeConfig(max_batch_size=1, max_seq_len=64, page_size=8)
    sched = Scheduler(ServingEngine(model, params, rt))
    state = ServerState(sched, ByteTokenizer())

    calls = {"n": 0}
    def boom():
        calls["n"] += 1
        raise RuntimeError("device on fire")
    sched.tick = boom
    state.thread.start()
    req, q = state.submit([1, 2], 4, 0.0, -1)
    assert q.get(timeout=10) is None        # sentinel: waiter unblocked
    assert req.state == "cancelled"
    assert "device on fire" in state.error
    # wedged: further admissions are rejected loudly (handler -> 503),
    # never queued onto the presumed-dead device
    with pytest.raises(RuntimeError, match="wedged"):
        state.submit([1], 2, 0.0, -1)
    state.stop.set()


def test_preemption_prefers_youngest():
    """Older request keeps its pages; the newcomer preempts itself."""
    from butterfly_tpu.sched.scheduler import Scheduler as S
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(0))
    # pool: 5 usable pages of 4 -> two requests to ~16 tokens can't coexist
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=32, page_size=4,
                       num_pages=5)
    sched = S(ServingEngine(model, params, rt))
    r_old = sched.submit([5, 7, 11], max_new_tokens=12)
    sched.tick()
    r_new = sched.submit([3, 1], max_new_tokens=12)
    sched.run_until_done(max_ticks=400)
    assert r_old.state == "finished" and r_new.state == "finished"
    assert r_old.preemptions == 0          # the older one is never evicted
    assert r_new.preemptions > 0
