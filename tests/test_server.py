"""HTTP serving tests: in-process server on an ephemeral port.

Covers /generate (blocking + SSE streaming + token-id path), /metrics
prometheus output, /health, and input validation.
"""
import json
import threading
import time
import urllib.request

import jax
import pytest

from butterfly_tpu.core.config import RuntimeConfig, tiny
from butterfly_tpu.engine.serving import ServingEngine
from butterfly_tpu.models.common import Model
from butterfly_tpu.sched.scheduler import Scheduler
from butterfly_tpu.serve.server import ServerState, make_handler
from butterfly_tpu.utils.tokenizer import ByteTokenizer

CFG = tiny("llama", dtype="float32", param_dtype="float32")


@pytest.fixture(scope="module")
def server():
    from http.server import ThreadingHTTPServer
    from butterfly_tpu.obs.ticklog import FlightRecorder
    from butterfly_tpu.obs.trace import Tracer
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(0))
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=64, page_size=8)
    sched = Scheduler(ServingEngine(model, params, rt), tracer=Tracer(),
                      flightrec=FlightRecorder())
    state = ServerState(sched, ByteTokenizer())
    state.thread.start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_port}"
    state.stop.set()
    httpd.shutdown()


def post(url, path, obj, raw=False):
    req = urllib.request.Request(
        url + path, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    resp = urllib.request.urlopen(req, timeout=120)
    return resp if raw else json.loads(resp.read())


def get(url, path):
    return urllib.request.urlopen(url + path, timeout=30).read().decode()


def test_health(server):
    body = json.loads(get(server, "/health"))
    assert body["status"] == "ok"
    # load signal for the router's least-loaded policy: one cheap JSON
    # probe instead of a Prometheus text scrape
    assert isinstance(body["queue_depth"], int) and body["queue_depth"] >= 0
    assert isinstance(body["active"], int) and body["active"] >= 0


def test_generate_blocking(server):
    out = post(server, "/generate",
               {"prompt": "hi", "max_tokens": 4, "stop_token": -1})
    assert len(out["tokens"]) == 4
    assert out["ttft_s"] >= 0 and out["total_s"] > 0


def test_speculative_body_knob(server):
    """Per-request speculation opt-out: accepted (and inert) on a
    non-speculating server, rejected when not a boolean."""
    out = post(server, "/generate",
               {"tokens": [5, 7, 11], "max_tokens": 4, "stop_token": -1,
                "speculative": False})
    assert len(out["tokens"]) == 4
    with pytest.raises(urllib.error.HTTPError) as e:
        post(server, "/generate",
             {"tokens": [5, 7], "max_tokens": 2, "speculative": "yes"})
    assert e.value.code == 400


def test_generate_token_ids_deterministic(server):
    a = post(server, "/generate",
             {"tokens": [5, 7, 11], "max_tokens": 5, "stop_token": -1})
    b = post(server, "/generate",
             {"tokens": [5, 7, 11], "max_tokens": 5, "stop_token": -1})
    assert a["tokens"] == b["tokens"]


def test_generate_stream(server):
    resp = post(server, "/generate",
                {"prompt": "ab", "max_tokens": 3, "stream": True,
                 "stop_token": -1}, raw=True)
    assert resp.headers["Content-Type"] == "text/event-stream"
    events = []
    for line in resp:
        line = line.strip()
        if line.startswith(b"data: "):
            events.append(line[6:])
    assert events[-1] == b"[DONE]"
    toks = [json.loads(e)["token"] for e in events[:-1]]
    assert len(toks) == 3


def test_concurrent_clients(server):
    results = {}

    def hit(name, prompt):
        results[name] = post(server, "/generate",
                             {"tokens": prompt, "max_tokens": 4,
                              "stop_token": -1})
    threads = [threading.Thread(target=hit, args=(i, [i + 1, i + 2]))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert len(results) == 4
    # determinism: same prompt again matches
    again = post(server, "/generate",
                 {"tokens": [1, 2], "max_tokens": 4, "stop_token": -1})
    assert results[0]["tokens"] == again["tokens"]


def test_metrics_endpoint(server):
    text = get(server, "/metrics")
    assert "butterfly_requests_total" in text
    assert "# TYPE butterfly_tokens_generated_total counter" in text
    assert "butterfly_kv_pages_free" in text


def test_metrics_histograms_well_formed(server):
    # at least one request must have completed for ttft to be observed
    post(server, "/generate",
         {"tokens": [2, 3], "max_tokens": 3, "stop_token": -1})
    text = get(server, "/metrics")
    assert "# TYPE butterfly_ttft_seconds histogram" in text
    for name in ("ttft_seconds", "queue_wait_seconds", "batch_size",
                 "prefill_tokens"):
        full = f"butterfly_{name}"
        buckets = [l for l in text.splitlines()
                   if l.startswith(full + "_bucket")]
        assert buckets, f"missing {full}_bucket series"
        assert buckets[-1].startswith(full + '_bucket{le="+Inf"}')
    # cumulative monotonicity + _count == +Inf bucket, per histogram
    import re as _re
    for name in ("ttft_seconds", "queue_wait_seconds"):
        full = f"butterfly_{name}"
        vals = [int(m.group(1)) for m in _re.finditer(
            _re.escape(full) + r'_bucket\{le="[^"]+"\} (\d+)', text)]
        assert vals == sorted(vals)
        count = int(_re.search(
            _re.escape(full) + r"_count (\d+)", text).group(1))
        assert vals[-1] == count and count >= 1
        assert _re.search(_re.escape(full) + r"_sum \d", text)
    # a metric name never appears with two TYPE declarations
    types = [l.split()[2] for l in text.splitlines()
             if l.startswith("# TYPE")]
    assert len(types) == len(set(types))


def test_debug_requests_timeline(server):
    # drive a STREAMED request with a client id, then read its timeline
    resp = post(server, "/generate",
                {"tokens": [4, 5, 6], "max_tokens": 4, "stream": True,
                 "stop_token": -1, "request_id": "dbg-stream-1"}, raw=True)
    for _ in resp:  # drain the SSE body to completion
        pass
    body = json.loads(get(server, "/debug/requests"))
    assert body["enabled"] is True
    mine = [r for r in body["requests"]
            if r["request_id"] == "dbg-stream-1"]
    assert len(mine) == 1
    events = mine[0]["events"]
    names = [e["name"] for e in events]
    # acceptance: admit, prefill, first-token, finish present, in order
    for needed in ("submit", "admit", "prefill_done", "first_token",
                   "finish"):
        assert needed in names, f"missing {needed} in {names}"
    assert names.index("admit") < names.index("prefill_done") \
        < names.index("first_token") < names.index("finish")
    ts = [e["t"] for e in events]
    assert ts == sorted(ts), "timestamps must be monotonic"
    fin = events[names.index("finish")]
    assert fin["state"] == "finished" and fin["tokens"] == 4
    # ?n= limits the window
    limited = json.loads(get(server, "/debug/requests?n=1"))
    assert len(limited["requests"]) == 1


def test_debug_requests_header_id_passthrough(server):
    req = urllib.request.Request(
        server + "/generate",
        data=json.dumps({"tokens": [9], "max_tokens": 2,
                         "stop_token": -1}).encode(),
        headers={"Content-Type": "application/json",
                 "X-Request-Id": "hdr-77"})
    json.loads(urllib.request.urlopen(req, timeout=120).read())
    body = json.loads(get(server, "/debug/requests"))
    assert any(r["request_id"] == "hdr-77" for r in body["requests"])


def test_debug_requests_request_id_filter(server):
    """?request_id= narrows the dump to ONE distributed request's
    timelines (the fleet trace-merge fetch), drops the global ring, and
    still carries the wall-clock anchors offline tools align on."""
    for rid in ("filt-a", "filt-b"):
        post(server, "/generate", {"tokens": [3, 5], "max_tokens": 2,
                                   "stop_token": -1, "request_id": rid})
    body = json.loads(get(server, "/debug/requests?request_id=filt-a"))
    assert body["enabled"] is True
    assert [r["request_id"] for r in body["requests"]] == ["filt-a"]
    assert body["global_events"] == []  # one request's view, no ticks
    assert body["t0_wall"] > 0 and body["t0_monotonic"] >= 0
    missing = json.loads(get(server, "/debug/requests?request_id=nope"))
    assert missing["requests"] == []


def test_health_carries_wall_clock(server):
    """/health stamps now_wall — the prober's clock-offset input."""
    import time
    body = json.loads(get(server, "/health"))
    assert abs(body["now_wall"] - time.time()) < 60


def test_validation_errors(server):
    for body, code in [({"prompt": ""}, 400),
                       ({"tokens": [999999]}, 400),
                       ({"tokens": [1], "max_tokens": 10000}, 400)]:
        try:
            post(server, "/generate", body)
            raised = None
        except urllib.error.HTTPError as e:  # noqa: F821
            raised = e.code
        assert raised == code


import urllib.error  # noqa: E402


def test_scheduler_crash_degrades_health():
    """A tick() exception must not wedge the server: waiters unblock,
    /health goes 503, new submissions are rejected."""
    import queue as _q
    from butterfly_tpu.serve.server import ServerState
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(0))
    rt = RuntimeConfig(max_batch_size=1, max_seq_len=64, page_size=8)
    sched = Scheduler(ServingEngine(model, params, rt))
    state = ServerState(sched, ByteTokenizer())

    calls = {"n": 0}
    def boom():
        calls["n"] += 1
        raise RuntimeError("device on fire")
    sched.tick = boom
    state.thread.start()
    req, q = state.submit([1, 2], 4, 0.0, -1)
    assert q.get(timeout=10) is None        # sentinel: waiter unblocked
    assert req.state == "cancelled"
    assert "device on fire" in state.error
    # wedged: further admissions are rejected loudly (handler -> 503),
    # never queued onto the presumed-dead device
    with pytest.raises(RuntimeError, match="wedged"):
        state.submit([1], 2, 0.0, -1)
    state.stop.set()


def test_preemption_prefers_youngest():
    """Older request keeps its pages; the newcomer preempts itself."""
    from butterfly_tpu.sched.scheduler import Scheduler as S
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(0))
    # pool: 5 usable pages of 4 -> two requests to ~16 tokens can't coexist
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=32, page_size=4,
                       num_pages=5)
    sched = S(ServingEngine(model, params, rt))
    r_old = sched.submit([5, 7, 11], max_new_tokens=12)
    sched.tick()
    r_new = sched.submit([3, 1], max_new_tokens=12)
    sched.run_until_done(max_ticks=400)
    assert r_old.state == "finished" and r_new.state == "finished"
    assert r_old.preemptions == 0          # the older one is never evicted
    assert r_new.preemptions > 0


def test_max_new_tokens_alias(server):
    out = post(server, "/generate",
               {"prompt": "hi", "max_new_tokens": 3, "stop_token": -1})
    assert len(out["tokens"]) == 3


def test_openai_completions_blocking(server):
    out = post(server, "/v1/completions",
               {"prompt": "hi", "max_tokens": 4, "stop_token": -1})
    assert out["object"] == "text_completion"
    assert out["id"].startswith("cmpl-")
    assert out["model"] == "butterfly"
    (choice,) = out["choices"]
    assert choice["index"] == 0 and choice["finish_reason"] == "length"
    assert isinstance(choice["text"], str)
    assert out["usage"]["completion_tokens"] == 4
    assert out["usage"]["total_tokens"] == (
        out["usage"]["prompt_tokens"] + 4)


def test_openai_completions_token_prompt_matches_generate(server):
    a = post(server, "/v1/completions",
             {"prompt": [5, 7, 11], "max_tokens": 5, "stop_token": -1})
    b = post(server, "/generate",
             {"tokens": [5, 7, 11], "max_tokens": 5, "stop_token": -1})
    assert a["choices"][0]["text"] == b["text"]


def test_openai_completions_stream(server):
    resp = post(server, "/v1/completions",
                {"prompt": "ab", "max_tokens": 3, "stream": True,
                 "stop_token": -1}, raw=True)
    assert resp.headers["Content-Type"] == "text/event-stream"
    events = []
    for line in resp:
        line = line.strip()
        if line.startswith(b"data: "):
            events.append(line[6:])
    assert events[-1] == b"[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    # 3 token chunks + 1 final finish_reason chunk
    assert len(chunks) == 4
    assert all(c["object"] == "text_completion" for c in chunks)
    assert chunks[-1]["choices"][0]["finish_reason"] == "length"
    assert all(c["choices"][0]["finish_reason"] is None for c in chunks[:-1])


def test_openai_completions_rejects_multi_choice(server):
    import urllib.error
    with pytest.raises(urllib.error.HTTPError) as e:
        post(server, "/v1/completions",
             {"prompt": "hi", "max_tokens": 2, "n": 3})
    assert e.value.code == 400


def test_openai_completions_malformed_n_is_400(server):
    import urllib.error
    with pytest.raises(urllib.error.HTTPError) as e:
        post(server, "/v1/completions",
             {"prompt": "hi", "max_tokens": 2, "n": None})
    assert e.value.code == 400


def test_openai_completions_stop_token_excluded_from_text(server):
    # Discover the greedy continuation, then stop on its first token
    # value that did NOT already occur earlier in the continuation:
    # picking a fixed index broke when the tiny model's greedy chain
    # settled into a repeat (the "3rd token" then also matched token 1
    # and generation legitimately stopped there with empty text).
    ref = post(server, "/generate",
               {"tokens": [5, 7, 11], "max_tokens": 6, "stop_token": -1})
    idx = next((i for i, t in enumerate(ref["tokens"])
                if i > 0 and t not in ref["tokens"][:i]), None)
    if idx is None:
        import pytest
        pytest.skip("greedy continuation is a single repeated token: "
                    "no stop position can leave preceding text")
    stop = ref["tokens"][idx]
    out = post(server, "/v1/completions",
               {"prompt": [5, 7, 11], "max_tokens": 6, "stop_token": stop})
    (choice,) = out["choices"]
    assert choice["finish_reason"] == "stop"
    # stop marker excluded from text; usage still counts it
    from butterfly_tpu.utils.tokenizer import ByteTokenizer
    want_text = ByteTokenizer().decode(ref["tokens"][:idx])
    assert choice["text"] == want_text
    assert out["usage"]["completion_tokens"] == idx + 1

    # streaming path: the stop token's chunk is skipped too
    resp = post(server, "/v1/completions",
                {"prompt": [5, 7, 11], "max_tokens": 6, "stop_token": stop,
                 "stream": True}, raw=True)
    events = [l.strip()[6:] for l in resp if l.strip().startswith(b"data: ")]
    assert events[-1] == b"[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    assert chunks[-1]["choices"][0]["finish_reason"] == "stop"
    texts = [c["choices"][0]["text"] for c in chunks[:-1]]
    assert "".join(texts) == want_text


# -- stop sequences ---------------------------------------------------------

def test_stop_matcher_unit():
    from butterfly_tpu.serve.server import StopSequenceMatcher
    m = StopSequenceMatcher(["END"])
    assert m.feed("hello ") == "hello "
    assert m.feed("E") == ""          # holdback: could grow into END
    assert m.feed("x") == "Ex"        # not a stop after all
    assert m.feed("EN") == ""
    assert m.feed("D ignored") == ""  # hit: nothing past the stop leaks
    assert m.hit
    assert m.text[:m.released] == "hello Ex"

    m2 = StopSequenceMatcher(["ab", "b"])
    assert m2.feed("xa") == "x"       # 'a' held (prefix of 'ab')
    assert m2.feed("b") == ""         # earliest match wins ('ab' at 1)
    assert m2.hit and m2.text[:m2.released] == "x"

    m3 = StopSequenceMatcher(["zz"])
    assert m3.feed("az") == "a"
    assert m3.flush() == "z"          # no hit: holdback released


def _pieces(tokens):
    return [ByteTokenizer().decode([t]) for t in tokens]


def test_openai_completions_stop_sequence_blocking(server):
    ref = post(server, "/generate",
               {"tokens": [5, 7, 11], "max_tokens": 6, "stop_token": -1})
    pieces = _pieces(ref["tokens"])
    full = "".join(pieces)
    stop = pieces[2] + pieces[3]
    out = post(server, "/v1/completions",
               {"prompt": [5, 7, 11], "max_tokens": 6, "stop_token": -1,
                "stop": stop})
    (choice,) = out["choices"]
    assert choice["finish_reason"] == "stop"
    assert choice["text"] == full[:full.find(stop)]


def test_openai_completions_stop_sequence_stream(server):
    ref = post(server, "/generate",
               {"tokens": [5, 7, 11], "max_tokens": 6, "stop_token": -1})
    pieces = _pieces(ref["tokens"])
    full = "".join(pieces)
    stop = pieces[2] + pieces[3]
    resp = post(server, "/v1/completions",
                {"prompt": [5, 7, 11], "max_tokens": 6, "stop_token": -1,
                 "stop": [stop], "stream": True}, raw=True)
    events = [l.strip()[6:] for l in resp if l.strip().startswith(b"data: ")]
    assert events[-1] == b"[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    assert chunks[-1]["choices"][0]["finish_reason"] == "stop"
    streamed = "".join(c["choices"][0]["text"] for c in chunks)
    assert streamed == full[:full.find(stop)]


def test_openai_completions_invalid_stop_is_400(server):
    import urllib.error
    for bad in ({"stop": 7}, {"stop": ["a", "b", "c", "d", "e"]},
                {"stop": [1, 2]}):
        with pytest.raises(urllib.error.HTTPError) as e:
            post(server, "/v1/completions",
                 {"prompt": "hi", "max_tokens": 2, **bad})
        assert e.value.code == 400


def test_openai_error_envelope_from_admit_path(server):
    import urllib.error
    with pytest.raises(urllib.error.HTTPError) as e:
        post(server, "/v1/completions",
             {"prompt": [999999], "max_tokens": 2})
    assert e.value.code == 400
    body = json.loads(e.value.read())
    assert body["error"]["type"] == "invalid_request_error"
    assert "out of range" in body["error"]["message"]
    # native endpoint keeps the flat shape
    with pytest.raises(urllib.error.HTTPError) as e2:
        post(server, "/generate", {"tokens": [999999], "max_tokens": 2})
    assert json.loads(e2.value.read())["error"] == "token id out of range"


# ---------------------------------------------------------------------------
# overload protection (ISSUE 8): deadline 504s, priorities, lock timeouts
# ---------------------------------------------------------------------------

def test_spent_deadline_is_504_at_admission(server):
    """A request arriving with its budget already spent gets a terminal
    504 with where/elapsed detail — it never touches the queue."""
    with pytest.raises(urllib.error.HTTPError) as e:
        post(server, "/generate",
             {"tokens": [1, 2], "max_tokens": 2, "deadline_ms": 0})
    assert e.value.code == 504
    body = json.loads(e.value.read())
    assert body["error"] == "deadline exceeded"
    assert body["where"] == "admission"
    assert "elapsed_ms" in body and body["deadline_ms"] == 0
    # header form (X-Deadline-Ms) wins and takes the same path; the
    # OpenAI endpoint answers in its error envelope
    req = urllib.request.Request(
        server + "/v1/completions",
        data=json.dumps({"prompt": "hi", "max_tokens": 2}).encode(),
        headers={"Content-Type": "application/json",
                 "X-Deadline-Ms": "-5"})
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=30)
    assert e.value.code == 504
    env = json.loads(e.value.read())["error"]
    assert env["type"] == "timeout_error" and env["where"] == "admission"
    # counted (handler-side: the scheduler never saw the request)
    text = get(server, "/metrics")
    assert 'butterfly_deadline_expired_total{where="admission"}' in text


def test_generous_deadline_serves_normally(server):
    out = post(server, "/generate",
               {"tokens": [5, 7], "max_tokens": 3, "stop_token": -1,
                "deadline_ms": 120_000, "priority": "batch"})
    assert len(out["tokens"]) == 3


def test_unknown_priority_is_400(server):
    with pytest.raises(urllib.error.HTTPError) as e:
        post(server, "/generate",
             {"tokens": [1], "max_tokens": 2, "priority": "urgent"})
    assert e.value.code == 400
    assert "priority" in json.loads(e.value.read())["error"]


def test_lock_timeout_answers_503_with_retry_after():
    """A held serving lock (slow/hung tick) must not pin handler
    threads: bounded acquire, 503 + Retry-After, and the timeout is
    counted. Uses a local server whose lock the test holds."""
    from http.server import ThreadingHTTPServer
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(0))
    rt = RuntimeConfig(max_batch_size=1, max_seq_len=64, page_size=8)
    sched = Scheduler(ServingEngine(model, params, rt))
    state = ServerState(sched, ByteTokenizer())
    # scheduler loop deliberately NOT started: the lock stays ours.
    # Admission tolerates compile-length waits in production (30s);
    # shrink it so the test observes the timeout without the wait.
    state.submit_lock_timeout = 0.5
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{httpd.server_port}"
    state.lock.acquire()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(url + "/metrics", timeout=30)
        assert e.value.code == 503
        assert e.value.headers.get("Retry-After") == "1"
        with pytest.raises(urllib.error.HTTPError) as e:
            post(url, "/generate", {"tokens": [1], "max_tokens": 2})
        assert e.value.code == 503
        assert e.value.headers.get("Retry-After") == "1"
        assert sched.registry.get(
            "server_lock_timeouts_total").value == 2
    finally:
        state.lock.release()
        httpd.shutdown()
        httpd.server_close()
    # with the lock free again the same surfaces answer normally
    # (no scheduler thread ran: only the lock-free paths are probed)
    assert "butterfly_server_lock_timeouts_total 2" \
        in state.metrics_text()


# ---------------------------------------------------------------------------
# tick anatomy endpoints: /debug/ticks, /debug/flightrecorder,
# /debug/profile (ISSUE 15)
# ---------------------------------------------------------------------------

def test_debug_ticks_endpoint(server):
    post(server, "/generate",
         {"tokens": [5, 7, 11], "max_tokens": 4, "stop_token": -1})
    body = json.loads(get(server, "/debug/ticks"))
    assert body["enabled"] is True
    assert body["ticks"], "the generate above must have ticked"
    t = body["ticks"][-1]
    for key in ("seq", "wall_s", "phases", "fetch_s", "inflight",
                "barrier_causes", "batch", "waiting", "pages_free"):
        assert key in t, key
    # phase sums reconcile with tick wall (the ring serves exactly what
    # tools/tick_report.py renders)
    assert abs(sum(t["phases"].values()) - t["wall_s"]) \
        <= 0.1 * t["wall_s"] + 1e-6
    # ?n=K limits the window
    limited = json.loads(get(server, "/debug/ticks?n=1"))
    assert len(limited["ticks"]) == 1


def test_debug_flightrecorder_endpoint(server):
    post(server, "/generate",
         {"tokens": [5, 7], "max_tokens": 3, "stop_token": -1})
    body = json.loads(get(server, "/debug/flightrecorder"))
    assert body["enabled"] is True
    kinds = {e["kind"] for e in body["events"]}
    assert "admit" in kinds  # the admissions above were recorded
    assert body["dumps"] == []  # nothing anomalous happened


def test_debug_profile_no_xprof_501(server, monkeypatch):
    """The graceful no-xprof fallback: a capture whose start fails
    (profiler plugin absent) answers 501 with the reason — never a
    crash, never a held serving lock."""
    from butterfly_tpu.serve.server import ServerState

    def boom(logdir):
        raise ImportError("no xprof in this build")

    monkeypatch.setattr(ServerState, "_profiler_start",
                        staticmethod(boom))
    with pytest.raises(urllib.error.HTTPError) as e:
        post(server, "/debug/profile", {"duration_ms": 50})
    assert e.value.code == 501
    body = json.loads(e.value.read())
    assert "no xprof" in body["error"]
    # the server is still fully alive after the failed capture
    out = post(server, "/generate",
               {"tokens": [5, 7], "max_tokens": 2, "stop_token": -1})
    assert len(out["tokens"]) == 2


def test_debug_profile_live_capture_never_blocks_admission(server):
    """POST /debug/profile on a live replica: the capture brackets the
    tick loop WITHOUT the serving lock, so a /generate submitted
    mid-capture is admitted and completes while the capture is still
    open. Returns a capture artifact (or a clean 501 where xprof is
    genuinely absent)."""
    import threading
    result = {}
    # warm the exact serving programs first so the mid-capture latency
    # below measures admission, not a first-shape XLA compile
    post(server, "/generate",
         {"tokens": [5, 7, 11], "max_tokens": 4, "stop_token": -1})

    def capture():
        try:
            result["resp"] = post(server, "/debug/profile",
                                  {"duration_ms": 8000})
            result["code"] = 200
        except urllib.error.HTTPError as e:
            result["code"] = e.code
            result["resp"] = json.loads(e.read())

    t = threading.Thread(target=capture)
    t.start()
    # mid-capture traffic: admitted, decoded, and answered while the
    # capture thread is STILL blocked on its 8s window — the direct
    # proof the capture holds no serving lock (profiling slows the CPU
    # backend, so a wall-clock bound would flake; liveness of the
    # capture thread is the non-racy signal)
    out = post(server, "/generate",
               {"tokens": [5, 7, 11], "max_tokens": 4, "stop_token": -1})
    assert len(out["tokens"]) == 4
    still_capturing = t.is_alive()
    t.join(timeout=60)
    assert not t.is_alive()
    assert result["code"] in (200, 501), result
    if result["code"] == 200:
        assert still_capturing, \
            "the generate should have finished inside the capture window"
        body = result["resp"]
        assert body["files"], "a capture must produce artifact files"
        assert body["duration_ms"] == 8000
    # second capture works too (the guard releases)
    try:
        post(server, "/debug/profile", {"duration_ms": 50})
    except urllib.error.HTTPError as e:
        assert e.code == 501


def test_profile_path_never_touches_serving_lock():
    """The BTF004-shaped pin, direct: the capture code path must not
    reference the serving lock at all — bounded-acquire-to-flip-a-flag
    is the contract, and here the flag needs no serving lock."""
    import inspect
    from butterfly_tpu.serve.server import ServerState
    for fn in (ServerState._maybe_profile, ServerState.request_profile):
        src = inspect.getsource(fn)
        assert "self.lock" not in src
        assert "acquire_lock" not in src


def test_profiler_server_start_guarded():
    """`serve --profiler-port` small fix: start succeeds at most once
    per process and every failure (second start, port in use) is a
    logged False, never a crash."""
    from butterfly_tpu.obs.profile import start_profiler_server
    first = start_profiler_server(49741)
    second = start_profiler_server(49741)
    assert isinstance(first, bool) and isinstance(second, bool)
    # whatever the environment supports, a repeat start must degrade
    assert second is False
