"""Engine: generate loops (fused scan vs python-stepped), sampling, stop tokens."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from butterfly_tpu.core.config import tiny, RuntimeConfig
from butterfly_tpu.engine import InferenceEngine, SamplingParams
from butterfly_tpu.engine.sampling import sample
from butterfly_tpu.models.common import Model


F32 = dict(dtype="float32", param_dtype="float32")


@pytest.fixture(scope="module")
def engine():
    cfg = tiny("llama", **F32)
    m = Model(cfg)
    params = m.init(jax.random.PRNGKey(0))
    return InferenceEngine(m, params, RuntimeConfig(max_seq_len=64))


def test_greedy_fused_equals_stepped(engine):
    prompts = [[1, 2, 3, 4, 5], [7, 8, 9]]
    sp = SamplingParams(max_new_tokens=8)
    fused = engine.generate(prompts, sp, fused=True)
    stepped = engine.generate(prompts, sp, fused=False)
    np.testing.assert_array_equal(fused.tokens, stepped.tokens)
    assert fused.tokens.shape == (2, 8)


def test_greedy_matches_argmax_chain(engine):
    """Fused generation must reproduce manual forward+argmax stepping."""
    prompt = [3, 1, 4, 1, 5]
    sp = SamplingParams(max_new_tokens=6)
    res = engine.generate([prompt], sp)

    m, params = engine.model, engine.params
    cache = m.init_cache(1, 64)
    toks = jnp.asarray([prompt])
    logits, cache = m(params, toks, cache)
    cur = int(jnp.argmax(logits[0, -1]))
    expect = [cur]
    for _ in range(5):
        lg, cache = m(params, jnp.asarray([[cur]]), cache)
        cur = int(jnp.argmax(lg[0, -1]))
        expect.append(cur)
    assert res.tokens[0].tolist() == expect


def test_stop_token(engine):
    sp = SamplingParams(max_new_tokens=10, stop_token=int(
        engine.generate([[1, 2]], SamplingParams(max_new_tokens=3)).tokens[0, 1]))
    res = engine.generate([[1, 2]], sp)
    # token at step 1 is the stop token -> length 2, tail masked to stop id
    assert res.lengths[0] == 2
    assert (res.tokens[0, 2:] == sp.stop_token).all()


def test_sampling_top_k_top_p():
    key = jax.random.PRNGKey(0)
    logits = jnp.asarray([[1.0, 2.0, 3.0, 4.0, -1e9]])
    # top_k=1 == greedy regardless of temperature
    t = sample(logits, key, SamplingParams(temperature=1.0, top_k=1))
    assert t.tolist() == [3]
    # top_p tiny -> only best token survives
    t = sample(logits, key, SamplingParams(temperature=1.0, top_p=0.01))
    assert t.tolist() == [3]
    # temperature sampling never picks a -inf-masked token
    keys = jax.random.split(key, 64)
    for k in keys[:16]:
        t = sample(logits, k, SamplingParams(temperature=2.0, top_k=3))
        assert int(t[0]) in (1, 2, 3)


def test_batch_padding_consistency(engine):
    """A prompt must generate the same greedy tokens alone or in a ragged batch."""
    sp = SamplingParams(max_new_tokens=5)
    alone = engine.generate([[5, 6, 7]], sp)
    batch = engine.generate([[5, 6, 7], [1, 2, 3, 4, 5, 6, 7, 8]], sp)
    np.testing.assert_array_equal(alone.tokens[0], batch.tokens[0])
