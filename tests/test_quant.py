"""Int8 weight-only quantization: error bounds, forward fidelity, TP parity.

The quant path must (a) bound per-weight error by half a quantization
step, (b) keep logits close enough that generation is usable, and
(c) compose with the Megatron TP sharding exactly (quantized TP=8 ==
quantized TP=1 token-for-token).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from butterfly_tpu.core.config import MeshConfig, tiny
from butterfly_tpu.core.mesh import make_mesh
from butterfly_tpu.engine import InferenceEngine, SamplingParams
from butterfly_tpu.models.common import Model, forward, init_cache
from butterfly_tpu.quant import (
    maybe_dequant, quantize_int8, shard_quantized_params)

CFG = tiny("llama", dtype="float32", param_dtype="float32")


@pytest.fixture(scope="module")
def setup():
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(0))
    return model, params, quantize_int8(params, CFG)


def test_dequant_error_bound(setup):
    _, params, qparams = setup
    w = np.asarray(params["layers"]["attn"]["wq"], np.float32)
    leaf = qparams["layers"]["attn"]["wq"]
    deq = np.asarray(maybe_dequant(leaf, jnp.float32))
    step = np.asarray(leaf["s"], np.float32)  # [L,1,N,H] keepdims
    assert np.all(np.abs(deq - w) <= 0.5 * step + 1e-7)


def test_quantized_leaves_are_int8(setup):
    _, _, qparams = setup
    attn = qparams["layers"]["attn"]
    for k in ("wq", "wk", "wv", "wo"):
        assert attn[k]["q8"].dtype == jnp.int8
    # numerically delicate leaves stay full precision
    assert qparams["embed"]["tok"].dtype == jnp.float32
    assert qparams["layers"]["ln1"]["scale"].dtype == jnp.float32


def test_forward_logits_close(setup):
    model, params, qparams = setup
    toks = jnp.asarray([[5, 7, 11, 13, 2, 4, 6, 8]])
    lg, _ = forward(params, CFG, toks, init_cache(CFG, 1, 16))
    lgq, _ = forward(qparams, CFG, toks, init_cache(CFG, 1, 16))
    a, b = np.asarray(lg).ravel(), np.asarray(lgq).ravel()
    corr = np.corrcoef(a, b)[0, 1]
    assert corr > 0.999, f"quantized logits diverged: corr={corr}"


@pytest.mark.parametrize("arch", ["gpt2", "mixtral"])
def test_other_arch_quant_smoke(arch):
    cfg = tiny(arch, dtype="float32", param_dtype="float32")
    params = Model(cfg).init(jax.random.PRNGKey(1))
    qparams = quantize_int8(params, cfg)
    toks = jnp.asarray([[5, 7, 11]])
    lg, _ = forward(params, cfg, toks, init_cache(cfg, 1, 8))
    lgq, _ = forward(qparams, cfg, toks, init_cache(cfg, 1, 8))
    corr = np.corrcoef(np.asarray(lg).ravel(), np.asarray(lgq).ravel())[0, 1]
    assert corr > 0.999


def test_generate_runs_quantized(setup):
    model, _, qparams = setup
    eng = InferenceEngine(model, qparams)
    res = eng.generate([[1, 2, 3]], SamplingParams(max_new_tokens=6,
                                                   temperature=0.0))
    assert res.tokens.shape == (1, 6)
    assert np.all(res.tokens >= 0)


def test_meshed_serving_quantized_token_parity():
    """ServingEngine must route quantized trees through the quant-aware
    specs (float specs would shard a scale's size-1 contraction axis) —
    round-2 ADVICE medium regression test."""
    from butterfly_tpu.core.config import RuntimeConfig
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.sched.scheduler import Scheduler

    cfg = tiny("llama", dtype="float32", param_dtype="float32",
               num_heads=8, num_kv_heads=4, head_dim=8)
    model = Model(cfg)
    qparams = quantize_int8(model.init(jax.random.PRNGKey(3)), cfg)
    rt = RuntimeConfig(max_batch_size=4, max_seq_len=64, page_size=8)
    outs = {}
    for mesh in (None, make_mesh(MeshConfig(data=2, tensor=4))):
        sched = Scheduler(ServingEngine(model, qparams, rt, mesh=mesh))
        reqs = [sched.submit(p, max_new_tokens=6)
                for p in ([5, 7, 11], [3, 1])]
        sched.run_until_done()
        outs[mesh is None] = [r.output for r in reqs]
    assert outs[True] == outs[False]


def test_cli_quant_flag_quantizes():
    """--quant int8 produces a quantized tree through the CLI load path."""
    import argparse
    from butterfly_tpu.quant import tree_is_quantized
    from butterfly_tpu.serve.cli import load_params, resolve_model

    args = argparse.Namespace(model="tiny", ckpt=None, dtype=None,
                              quant="int8", expert_parallel=1)
    model = resolve_model(args)
    params = load_params(model, args)
    assert tree_is_quantized(params)
    assert params["layers"]["attn"]["wq"]["q8"].dtype == jnp.int8


def test_quant_tp8_token_parity(setup):
    """Quantized + TP-sharded must equal quantized single-device exactly."""
    cfg = tiny("llama", dtype="float32", param_dtype="float32",
               num_heads=8, num_kv_heads=8, head_dim=8)
    model = Model(cfg)
    qparams = quantize_int8(model.init(jax.random.PRNGKey(2)), cfg)
    sp = SamplingParams(max_new_tokens=8, temperature=0.0)
    ref = InferenceEngine(model, qparams).generate([[3, 1, 4, 1, 5]], sp)

    mesh = make_mesh(MeshConfig(tensor=8))
    shp = shard_quantized_params(qparams, cfg, mesh)
    got = InferenceEngine(model, shp, mesh=mesh).generate([[3, 1, 4, 1, 5]],
                                                          sp)
    assert got.tokens.tolist() == ref.tokens.tolist()
