"""Statistical distribution parity for speculative sampling.

The rejection-sampling correction (engine.sampling.speculative_accept,
Leviathan et al. 2023) must make speculative output EXACTLY
target-distributed at temperature > 0 — for the one-hot prompt-lookup
proposal: accept draft d_i with probability p_i(d_i), resample the
first rejection from the residual p_i with d_i masked out, bonus-sample
position gamma when everything lands. These tests pin that law
empirically on small vocabularies (chi-square-style max-deviation
bounds at N large enough that a biased kernel fails deterministically),
plus the greedy-row fast path and the per-request opt-out semantics.

Kernel-level deliberately: the serving spec block and
engine.generate_speculative both emit through this one kernel, and
end-to-end empirical distribution tests over a whole model would need
thousands of scheduler runs for the same statistical power.
"""
import jax
import jax.numpy as jnp
import numpy as np

from butterfly_tpu.engine.sampling import (
    _filter_logits, speculative_accept)

V, GAMMA = 8, 3


def _target(logits_row, temp, top_k=0, top_p=1.0):
    """The distribution plain decode samples from at this position."""
    scaled = _filter_logits(jnp.asarray(logits_row) / temp, top_k, top_p)
    return np.asarray(jax.nn.softmax(scaled))


def _draw(logits, drafts, temps, n, top_k=0, top_p=1.0, seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), n)
    f = jax.jit(jax.vmap(lambda k: speculative_accept(
        jnp.asarray(logits), jnp.asarray(drafts, jnp.int32), k,
        jnp.asarray(temps, jnp.float32), top_k, top_p)))
    em, na = f(keys)
    return np.asarray(em), np.asarray(na)


def test_first_token_marginal_matches_target():
    """P(emitted[0] = x) must equal p_0(x) regardless of the draft:
    accepted-draft mass + residual-resample mass reassemble exactly."""
    rng = np.random.RandomState(0)
    logits = rng.randn(1, GAMMA + 1, V).astype(np.float32) * 2.0
    for draft0 in (int(np.argmax(logits[0, 0])),          # likely draft
                   int(np.argmin(logits[0, 0]))):         # unlikely draft
        drafts = np.asarray([[draft0, 1, 5]])
        em, _ = _draw(logits, drafts, [0.7], 20000)
        emp = np.bincount(em[:, 0, 0], minlength=V) / len(em)
        tgt = _target(logits[0, 0], 0.7)
        assert np.abs(emp - tgt).max() < 0.015, (draft0, emp, tgt)


def test_second_token_conditional_matches_target():
    """Given the first draft accepted, emitted[1] must be distributed
    as p_1 — the joint law equals autoregressive sampling."""
    rng = np.random.RandomState(1)
    logits = rng.randn(1, GAMMA + 1, V).astype(np.float32) * 2.0
    d0 = int(np.argmax(logits[0, 0]))  # high-probability first draft
    drafts = np.asarray([[d0, 2, 6]])
    em, na = _draw(logits, drafts, [0.8], 30000)
    sel = na[:, 0] >= 1            # first draft accepted
    assert sel.sum() > 5000        # enough mass to test on
    emp = np.bincount(em[sel, 0, 1], minlength=V) / sel.sum()
    tgt = _target(logits[0, 1], 0.8)
    assert np.abs(emp - tgt).max() < 0.02


def test_acceptance_probability_is_p_of_draft():
    """P(n_acc >= 1) must equal p_0(d_1) — the min(1, p/q) rule with a
    one-hot q."""
    rng = np.random.RandomState(2)
    logits = rng.randn(1, GAMMA + 1, V).astype(np.float32) * 2.0
    d0 = 3
    drafts = np.asarray([[d0, 0, 0]])
    _, na = _draw(logits, drafts, [1.0], 20000)
    p_d = _target(logits[0, 0], 1.0)[d0]
    assert abs((na[:, 0] >= 1).mean() - p_d) < 0.015


def test_filters_respected():
    """top-k filtering applies to acceptance AND resampling: a draft
    outside the top-k nucleus is always rejected, and no emitted token
    ever falls outside the nucleus."""
    rng = np.random.RandomState(3)
    logits = rng.randn(1, GAMMA + 1, V).astype(np.float32) * 2.0
    k = 3
    outside = int(np.argsort(logits[0, 0])[0])  # worst token: not in top-3
    drafts = np.asarray([[outside, 0, 0]])
    em, na = _draw(logits, drafts, [0.9], 4000, top_k=k)
    assert (na[:, 0] == 0).all()  # zero filtered mass -> never accepted
    nucleus = set(np.argsort(logits[0, 0])[-k:].tolist())
    assert set(em[:, 0, 0].tolist()) <= nucleus


def test_greedy_rows_match_accept_drafts():
    """temp-0 rows reproduce the host _accept_drafts semantics (the
    serving byte-parity contract)."""
    from butterfly_tpu.engine.engine import _accept_drafts
    rng = np.random.RandomState(4)
    for trial in range(20):
        logits = rng.randn(1, GAMMA + 1, V).astype(np.float32) * 2.0
        drafts = rng.randint(0, V, (1, GAMMA))
        em, na = speculative_accept(
            jnp.asarray(logits), jnp.asarray(drafts, jnp.int32),
            jax.random.PRNGKey(trial), jnp.asarray([0.0], jnp.float32),
            0, 1.0)
        n = int(np.asarray(na)[0]) + 1
        got = np.asarray(em)[0, :n].tolist()
        greedy = np.argmax(logits[0], axis=-1)
        assert got == _accept_drafts(drafts[0].tolist(), greedy), trial


def _draw_q(logits, q_logits, temps, n, top_k=0, top_p=1.0, seed=0):
    """Real-proposal harness: per trial, the draft is SAMPLED from the
    (scaled, filtered) proposal q — exactly what the on-device draft
    model does (models/draft.py) — then scored by speculative_accept
    with the same q_logits. The output law must still be the target's."""
    scaled_q = _filter_logits(jnp.asarray(q_logits)
                              / jnp.asarray(temps, jnp.float32)[:, None,
                                                                None],
                              top_k, top_p)

    def one(k):
        kd, ka = jax.random.split(k)
        drafts = jax.random.categorical(kd, scaled_q[:, :GAMMA, :],
                                        axis=-1).astype(jnp.int32)
        em, na = speculative_accept(
            jnp.asarray(logits), drafts, ka,
            jnp.asarray(temps, jnp.float32), top_k, top_p,
            q_logits=scaled_q[:, :GAMMA, :])
        return em, na, drafts

    keys = jax.random.split(jax.random.PRNGKey(seed), n)
    em, na, dr = jax.jit(jax.vmap(one))(keys)
    return np.asarray(em), np.asarray(na), np.asarray(dr)


def test_real_q_first_token_marginal_matches_target():
    """ISSUE 14: with a REAL proposal distribution q (draft model),
    accept-w.p.-min(1, p/q) + residual-(p-q)+ resample must leave
    P(emitted[0] = x) exactly p_0(x) — the Leviathan law for arbitrary
    q, not just one-hot."""
    rng = np.random.RandomState(7)
    logits = rng.randn(1, GAMMA + 1, V).astype(np.float32) * 2.0
    q_logits = rng.randn(1, GAMMA + 1, V).astype(np.float32) * 2.0
    em, _, _ = _draw_q(logits, q_logits, [0.7], 20000)
    emp = np.bincount(em[:, 0, 0], minlength=V) / len(em)
    tgt = _target(logits[0, 0], 0.7)
    assert np.abs(emp - tgt).max() < 0.015, (emp, tgt)


def test_real_q_joint_two_position_law():
    """Given the first draft accepted under real q, emitted[1] is still
    distributed as p_1 — the joint law equals autoregressive sampling
    from the target regardless of the proposal."""
    rng = np.random.RandomState(8)
    logits = rng.randn(1, GAMMA + 1, V).astype(np.float32) * 2.0
    # proposal concentrated near the target: plenty of accept mass
    q_logits = logits + rng.randn(1, GAMMA + 1, V).astype(np.float32) * 0.3
    em, na, _ = _draw_q(logits, q_logits, [0.8], 30000, seed=1)
    sel = na[:, 0] >= 1
    assert sel.sum() > 5000
    emp = np.bincount(em[sel, 0, 1], minlength=V) / sel.sum()
    tgt = _target(logits[0, 1], 0.8)
    assert np.abs(emp - tgt).max() < 0.02


def test_real_q_acceptance_rate_is_expected_min_ratio():
    """P(n_acc >= 1) must equal sum_d q(d) min(1, p(d)/q(d)) — the
    textbook acceptance mass of rejection sampling with proposal q."""
    rng = np.random.RandomState(9)
    logits = rng.randn(1, GAMMA + 1, V).astype(np.float32) * 2.0
    q_logits = rng.randn(1, GAMMA + 1, V).astype(np.float32) * 2.0
    _, na, _ = _draw_q(logits, q_logits, [1.0], 20000, seed=2)
    p = _target(logits[0, 0], 1.0)
    q = _target(q_logits[0, 0], 1.0)
    want = float(np.sum(q * np.minimum(1.0, p / np.maximum(q, 1e-30))))
    assert abs((na[:, 0] >= 1).mean() - want) < 0.015


def test_real_q_greedy_rows_ignore_q():
    """temp-0 rows keep the _accept_drafts semantics byte-for-byte no
    matter what q says — the draft-model greedy parity contract."""
    from butterfly_tpu.engine.engine import _accept_drafts
    rng = np.random.RandomState(10)
    for trial in range(10):
        logits = rng.randn(1, GAMMA + 1, V).astype(np.float32) * 2.0
        q_logits = rng.randn(1, GAMMA + 1, V).astype(np.float32) * 2.0
        drafts = rng.randint(0, V, (1, GAMMA))
        em, na = speculative_accept(
            jnp.asarray(logits), jnp.asarray(drafts, jnp.int32),
            jax.random.PRNGKey(trial), jnp.asarray([0.0], jnp.float32),
            0, 1.0, q_logits=jnp.asarray(q_logits[:, :GAMMA, :]))
        n = int(np.asarray(na)[0]) + 1
        got = np.asarray(em)[0, :n].tolist()
        greedy = np.argmax(logits[0], axis=-1)
        assert got == _accept_drafts(drafts[0].tolist(), greedy), trial


def test_real_q_opt_out_rows_sample_full_distribution():
    """spec_mask=False rows under real q: one token from the FULL
    target distribution — no accept test, no residual bias."""
    rng = np.random.RandomState(11)
    logits = rng.randn(1, GAMMA + 1, V).astype(np.float32) * 2.0
    q_logits = rng.randn(1, GAMMA + 1, V).astype(np.float32) * 2.0
    scaled_q = _filter_logits(jnp.asarray(q_logits[:, :GAMMA, :]) / 0.7,
                              0, 1.0)
    drafts = np.asarray([[int(np.argmax(q_logits[0, 0])), 0, 0]])
    keys = jax.random.split(jax.random.PRNGKey(12), 20000)
    f = jax.jit(jax.vmap(lambda k: speculative_accept(
        jnp.asarray(logits), jnp.asarray(drafts, jnp.int32), k,
        jnp.asarray([0.7], jnp.float32), 0, 1.0,
        jnp.asarray([False]), scaled_q)))
    em, na = f(keys)
    em, na = np.asarray(em), np.asarray(na)
    assert (na == 0).all()
    emp = np.bincount(em[:, 0, 0], minlength=V) / len(em)
    tgt = _target(logits[0, 0], 0.7)
    assert np.abs(emp - tgt).max() < 0.015


def test_opt_out_rows_sample_full_distribution():
    """spec_mask=False rows must emit ONE token from the FULL target
    distribution — no draft acceptance, and critically no residual
    exclusion bias against the draft token."""
    rng = np.random.RandomState(5)
    logits = rng.randn(1, GAMMA + 1, V).astype(np.float32) * 2.0
    d0 = int(np.argmax(logits[0, 0]))  # the draft IS the mode: any
    drafts = np.asarray([[d0, 0, 0]])  # exclusion bias would be glaring
    keys = jax.random.split(jax.random.PRNGKey(6), 20000)
    f = jax.jit(jax.vmap(lambda k: speculative_accept(
        jnp.asarray(logits), jnp.asarray(drafts, jnp.int32), k,
        jnp.asarray([0.7], jnp.float32), 0, 1.0,
        jnp.asarray([False]))))
    em, na = f(keys)
    em, na = np.asarray(em), np.asarray(na)
    assert (na == 0).all()
    emp = np.bincount(em[:, 0, 0], minlength=V) / len(em)
    tgt = _target(logits[0, 0], 0.7)
    assert np.abs(emp - tgt).max() < 0.015


# -- token-tree acceptance (ISSUE 19: speculative_tree_accept) --------------
#
# The recursive-residual law for a width-w fan of i.i.d. candidates
# from one proposal q: r_0 = p, accept candidate j w.p.
# min(1, r_j(x)/q(x)), on rejection r_{j+1} = norm((r_j - q)+). Exact
# for ANY q, like the chain law above — these tests pin it on the
# kernel the tree spec scan emits through.

from butterfly_tpu.engine.sampling import (  # noqa: E402
    speculative_tree_accept, tree_node_index)

TREE_W, TREE_N = 2, 5          # width-2, 5 nodes -> depth D = 2
TREE_D = (TREE_N - 1) // TREE_W


def _draw_tree(logits, q_logits, temps, n, top_k=0, top_p=1.0, seed=0,
               spec_mask=None):
    """Tree harness: per trial each depth's fan is w i.i.d. draws from
    the (scaled, filtered) shared q — exactly what tree_draft does on
    stochastic rows — then scored by speculative_tree_accept with the
    same q_logits. logits [S, N, V] plays the tree-verify node batch."""
    S = np.asarray(logits).shape[0]
    V = np.asarray(logits).shape[-1]
    scaled_q = _filter_logits(
        jnp.asarray(q_logits)
        / jnp.asarray(temps, jnp.float32)[:, None, None], top_k, top_p)
    fan_q = jnp.broadcast_to(scaled_q[:, :, None, :],
                             (S, TREE_D, TREE_W, V))

    def one(k):
        kd, ka = jax.random.split(k)
        drafts = jax.random.categorical(kd, fan_q,
                                        axis=-1).astype(jnp.int32)
        em, na, perm = speculative_tree_accept(
            jnp.asarray(logits), drafts, ka,
            jnp.asarray(temps, jnp.float32), top_k, top_p,
            spec_mask if spec_mask is None else jnp.asarray(spec_mask),
            scaled_q, width=TREE_W, nodes=TREE_N)
        return em, na, perm

    keys = jax.random.split(jax.random.PRNGKey(seed), n)
    em, na, perm = jax.jit(jax.vmap(one))(keys)
    return np.asarray(em), np.asarray(na), np.asarray(perm)


def test_tree_first_token_marginal_matches_target():
    """P(emitted[0] = x) = p_0(x) under an ARBITRARY tree proposal:
    accepted-sibling mass + every residual-resample branch reassemble
    the target exactly (the recursive-residual law, depth 1)."""
    rng = np.random.RandomState(20)
    logits = rng.randn(1, TREE_N, V).astype(np.float32) * 2.0
    q_logits = rng.randn(1, TREE_D, V).astype(np.float32) * 2.0
    em, _, _ = _draw_tree(logits, q_logits, [0.7], 20000)
    emp = np.bincount(em[:, 0, 0], minlength=V) / len(em)
    tgt = _target(logits[0, 0], 0.7)
    assert np.abs(emp - tgt).max() < 0.015, (emp, tgt)


def test_tree_acceptance_mass_recursive_residual():
    """P(n_acc >= 1) = 1 - prod_j (1 - beta_j) with beta_j the j-th
    sibling's conditional accept mass sum_x q(x) min(1, r_j(x)/q(x))
    under the recursive residual r_0 = p, r_{j+1} = norm((r_j - q)+).
    beta_0 alone is the ISSUE's 'sum over root children of
    q*min(1, p/q)' — the closed form the product reduces to at w=1."""
    rng = np.random.RandomState(21)
    logits = rng.randn(1, TREE_N, V).astype(np.float32) * 2.0
    q_logits = rng.randn(1, TREE_D, V).astype(np.float32) * 2.0
    _, na, _ = _draw_tree(logits, q_logits, [1.0], 20000, seed=3)
    p = _target(logits[0, 0], 1.0).astype(np.float64)
    q = _target(q_logits[0, 0], 1.0).astype(np.float64)
    r = p.copy()
    miss = 1.0
    for _ in range(TREE_W):
        beta = float(np.sum(q * np.minimum(1.0, r / np.maximum(q, 1e-30))))
        miss *= 1.0 - beta
        r_next = np.maximum(r - q, 0.0)
        if r_next.sum() > 0:
            r = r_next / r_next.sum()
    want = 1.0 - miss
    assert abs((na[:, 0] >= 1).mean() - want) < 0.015, want


def test_tree_depth2_conditional_matches_target():
    """Given the depth-1 PRINCIPAL accepted, emitted[1] must be
    distributed as the target at the principal node — the walk's
    conditional law equals autoregressive sampling along the realized
    path."""
    rng = np.random.RandomState(22)
    logits = rng.randn(1, TREE_N, V).astype(np.float32) * 2.0
    # proposal near the target: plenty of principal-accept mass
    q_logits = np.stack(
        [logits[0, [tree_node_index(d + 1, 0, TREE_W) - 1 if False else 0][0]]
         for d in range(TREE_D)])[None] * 0.0
    q_logits = logits[:, :1, :].repeat(TREE_D, axis=1) \
        + rng.randn(1, TREE_D, V).astype(np.float32) * 0.3
    em, na, perm = _draw_tree(logits, q_logits, [0.8], 30000, seed=4)
    pn1 = tree_node_index(1, 0, TREE_W)  # depth-1 principal chunk index
    sel = (na[:, 0] >= 1) & (perm[:, 0, 1] == pn1)
    assert sel.sum() > 5000
    emp = np.bincount(em[sel, 0, 1], minlength=V) / sel.sum()
    tgt = _target(logits[0, pn1], 0.8)
    assert np.abs(emp - tgt).max() < 0.02


def test_tree_greedy_matches_host_walk():
    """temp-0 rows: the device walk must equal a host reference that
    greedily walks the caterpillar — first sibling matching the
    parent's argmax is accepted, non-principal accepts terminate, and
    the final token is the argmax at the terminal node. This is the
    kernel half of the serving byte-parity contract."""
    from butterfly_tpu.engine.sampling import tree_principal
    rng = np.random.RandomState(23)
    for trial in range(20):
        logits = rng.randn(1, TREE_N, V).astype(np.float32) * 2.0
        drafts = rng.randint(0, V, (1, TREE_D, TREE_W))
        em, na, perm = speculative_tree_accept(
            jnp.asarray(logits), jnp.asarray(drafts, jnp.int32),
            jax.random.PRNGKey(trial), jnp.asarray([0.0], jnp.float32),
            0, 1.0, width=TREE_W, nodes=TREE_N)
        greedy = np.argmax(logits[0], axis=-1)
        want, want_perm = [], [0]
        parent = 0
        for d in range(1, TREE_D + 1):
            hit = None
            for j in range(TREE_W):
                if drafts[0, d - 1, j] == greedy[parent]:
                    hit = j
                    break
            if hit is None:
                want.append(int(greedy[parent]))
                break
            node = tree_node_index(d, hit, TREE_W)
            want.append(int(drafts[0, d - 1, hit]))
            want_perm.append(node)
            if hit != 0 or d == TREE_D:
                want.append(int(greedy[node]))
                break
            parent = node
        n = int(np.asarray(na)[0])
        assert n == len(want) - 1, trial
        assert np.asarray(em)[0, :n + 1].tolist() == want, trial
        assert np.asarray(perm)[0, :n + 1].tolist() == want_perm, trial


def test_tree_opt_out_rows_sample_full_distribution():
    """spec_mask=False rows under the tree kernel: one token from the
    FULL filtered target at node 0 — no accept test, no residual or
    sibling-exclusion bias, n_acc identically 0."""
    rng = np.random.RandomState(24)
    logits = rng.randn(1, TREE_N, V).astype(np.float32) * 2.0
    q_logits = rng.randn(1, TREE_D, V).astype(np.float32) * 2.0
    em, na, _ = _draw_tree(logits, q_logits, [0.7], 20000, seed=5,
                           spec_mask=np.asarray([False]))
    assert (na == 0).all()
    emp = np.bincount(em[:, 0, 0], minlength=V) / len(em)
    tgt = _target(logits[0, 0], 0.7)
    assert np.abs(emp - tgt).max() < 0.015
