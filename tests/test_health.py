"""Heartbeat / failure-detection tests (SURVEY.md §5 failure-detection).

Monitor semantics (miss counting, failure latch, one-shot callback,
no-recovery-after-latch), the real device/all-hosts probes on the fake
CPU backend, and the serving integration: a failing heartbeat wedges
the server (503 /health, queued work drained host-side).
"""
import json
import threading
import urllib.error
import urllib.request

import jax
import pytest

from butterfly_tpu.obs.health import (
    HeartbeatMonitor, all_hosts_probe, device_probe)


def test_probes_pass_on_live_backend():
    assert device_probe()
    import jax
    if not hasattr(jax, "shard_map"):
        # jax < 0.6 exposes shard_map only under jax.experimental;
        # all_hosts_probe (and the whole sharded serving path) targets
        # the top-level API, so on this runtime the collective probe is
        # an environment gap, not a regression
        import pytest
        pytest.skip("jax.shard_map unavailable on this jax "
                    f"({jax.__version__}): all_hosts_probe needs the "
                    "top-level shard_map API")
    assert all_hosts_probe()  # psum over all 8 fake devices


def test_monitor_latches_after_max_misses():
    fired = []
    mon = HeartbeatMonitor(probe=lambda: False, max_misses=3,
                           on_failure=lambda e: fired.append(e))
    assert mon.check_now() is False and mon.healthy      # miss 1
    assert mon.check_now() is False and mon.healthy      # miss 2
    assert mon.check_now() is False and not mon.healthy  # miss 3: latch
    assert len(fired) == 1
    mon.check_now()                                      # miss 4
    assert len(fired) == 1                               # callback fired once


def test_monitor_miss_reset_but_latch_sticks():
    calls = iter([False, False, True, False, False, False])
    mon = HeartbeatMonitor(probe=lambda: next(calls), max_misses=3)
    mon.check_now(), mon.check_now()
    assert mon.misses == 2 and mon.healthy
    assert mon.check_now() is True and mon.misses == 0   # recovery resets
    for _ in range(3):
        mon.check_now()
    assert not mon.healthy                               # latched now
    assert mon.beats == 1


def test_monitor_probe_exception_counts_as_miss():
    def boom():
        raise RuntimeError("chip fell over")
    mon = HeartbeatMonitor(probe=boom, max_misses=1)
    assert mon.check_now() is False
    assert not mon.healthy
    assert "chip fell over" in mon.last_error


def test_watchdog_latches_on_stale_beats():
    """The watchdog thread latches purely on wall-clock staleness — it
    detects a HUNG owner (no beats) without ever running the probe."""
    mon = HeartbeatMonitor(interval=0.02, max_misses=2).start()
    try:
        waiter = threading.Event()
        for _ in range(300):
            if not mon.healthy:
                break
            waiter.wait(0.01)
        assert not mon.healthy
        assert "no heartbeat" in mon.last_error
    finally:
        mon.stop()


def test_watchdog_stays_healthy_while_beating():
    mon = HeartbeatMonitor(interval=0.02, max_misses=2).start()
    try:
        waiter = threading.Event()
        for _ in range(20):
            mon.beat()
            waiter.wait(0.01)
        assert mon.healthy
    finally:
        mon.stop()


def test_maybe_probe_respects_interval():
    calls = []
    mon = HeartbeatMonitor(probe=lambda: calls.append(1) or True,
                           interval=3600)
    mon.maybe_probe()
    mon.maybe_probe()  # within the interval: no second probe
    assert len(calls) == 1 and mon.beats == 1


def test_heartbeat_failure_wedges_server():
    """Injected failing heartbeat: /health goes 503, /generate refuses,
    queued requests are drained via the host-only abort path."""
    from http.server import ThreadingHTTPServer
    from butterfly_tpu.core.config import RuntimeConfig, tiny
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.models.common import Model
    from butterfly_tpu.sched.scheduler import Scheduler
    from butterfly_tpu.serve.server import ServerState, make_handler
    from butterfly_tpu.utils.tokenizer import ByteTokenizer

    cfg = tiny("llama", dtype="float32", param_dtype="float32")
    model = Model(cfg)
    sched = Scheduler(ServingEngine(
        model, model.init(jax.random.PRNGKey(0)),
        RuntimeConfig(max_batch_size=2, max_seq_len=64)))
    hb = HeartbeatMonitor(probe=lambda: False, interval=3600,
                          max_misses=1)  # driven manually below
    state = ServerState(sched, ByteTokenizer(), heartbeat=hb)
    # NB: ServerState.start of the monitor thread uses interval=3600, so
    # the failure is triggered deterministically here:
    hb.check_now()
    assert not hb.healthy and state.error.startswith("heartbeat failed")

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url + "/health", timeout=30)
        assert ei.value.code == 503
        req = urllib.request.Request(
            url + "/generate",
            data=json.dumps({"tokens": [1, 2], "max_tokens": 3}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 503
    finally:
        state.stop.set()
        hb.stop()
        httpd.shutdown()
