"""Workload subsystem tests (ISSUE 10).

Three layers:

* pure-host: seeded determinism (same spec + seed => byte-identical
  trace across generate -> save -> load -> save), arrival-process
  statistics (the mutation catalogue's arrival-rate mutant must die
  here), spec round-trips, page-aligned shared prefixes;
* server-level: the canned mixed_chat workload replayed open-loop at a
  tiny in-process server with an under-provisioned page pool provably
  drives serving_preemptions > 0, and SLO-aware admission sheds at
  least one 429 through the PR-8 path — with the loadgen/replay
  summary folding the server-side counters in (client-observed vs
  server-counted in one artifact);
* bench smoke: a tiny run_mixed_benchmark (seconds) pins the mixed
  bench phase's JSON contract — mixed_* fields, preemptions > 0, the
  >= 2x2 operating-point table + knee — so the subsystem can't
  silently rot, plus `butterfly workload generate|replay` CLI smoke.
"""
import json
import statistics
import threading
import urllib.request

import jax
import pytest

from butterfly_tpu.core.config import RuntimeConfig, tiny
from butterfly_tpu.engine.serving import ServingEngine
from butterfly_tpu.models.common import Model
from butterfly_tpu.sched.scheduler import Scheduler
from butterfly_tpu.serve.server import ServerState, make_handler
from butterfly_tpu.utils.tokenizer import ByteTokenizer
from butterfly_tpu.workload.arrivals import (MarkovOnOff, Poisson, Ramp,
                                             assign_arrivals, parse_arrival)
from butterfly_tpu.workload.models import (RequestSpec, Workload,
                                           get_workload, mixed_chat)
from butterfly_tpu.workload.replay import (load_trace, replay_trace,
                                           save_trace, trace_text)

CFG = tiny("llama", dtype="float32", param_dtype="float32")

#: the CPU-smoke mixed_chat shape (bench.py's CPU sizing, shrunk):
#: decode budgets long enough to keep slots alive across blocks, so a
#: near-instant burst against a tight pool provably contests pages
SMOKE_WL = dict(page_size=8, vocab=258, prompt_lo=8, prompt_hi=48,
                max_new_lo=16, max_new_hi=48)
SMOKE_ARRIVAL = "burst:2000:0.5:0.1"


def smoke_specs(n=12, seed=0):
    wl = mixed_chat(**SMOKE_WL)
    specs = wl.sample(n, seed)
    assign_arrivals(specs, parse_arrival(SMOKE_ARRIVAL), seed)
    return wl, specs


# ---------------------------------------------------------------------------
# determinism
# ---------------------------------------------------------------------------


def test_trace_byte_identical_across_generate_save_load():
    """Same workload spec + seed => byte-identical trace text, and a
    loaded trace re-saves byte-identically (generate -> save -> load ->
    save). This is what makes a saved trace a citable benchmark input:
    replaying it twice fires IDENTICAL request sequences."""
    wl1, s1 = smoke_specs()
    wl2, s2 = smoke_specs()
    t1 = trace_text(s1, workload=wl1, arrival=SMOKE_ARRIVAL, seed=0)
    t2 = trace_text(s2, workload=wl2, arrival=SMOKE_ARRIVAL, seed=0)
    assert t1 == t2
    # and the HTTP payloads the replay driver would fire are identical
    assert [s.payload() for s in s1] == [s.payload() for s in s2]


def test_trace_file_roundtrip(tmp_path):
    wl, specs = smoke_specs()
    p1, p2 = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    save_trace(p1, specs, workload=wl, arrival=SMOKE_ARRIVAL, seed=0)
    header, loaded = load_trace(p1)
    assert header["n"] == len(specs) == len(loaded)
    # the header carries the full generating spec: a trace is
    # self-describing (Workload.from_spec reproduces the population)
    assert Workload.from_spec(header["workload"]) == wl
    save_trace(p2, loaded, workload=wl, arrival=SMOKE_ARRIVAL, seed=0)
    assert p1.read_bytes() == p2.read_bytes()


def test_load_trace_rejects_foreign_file(tmp_path):
    p = tmp_path / "x.jsonl"
    p.write_text('{"some": "json"}\n{"more": 1}\n')
    with pytest.raises(ValueError):
        load_trace(p)


def test_sample_prefix_stable_under_extension():
    """Request i's draw stream is independent of n: sampling 6 then 12
    yields the same first 6 requests (per-index seeded substreams, not
    one shared stream a later request could perturb)."""
    wl = mixed_chat(**SMOKE_WL)
    a = wl.sample(6, seed=7)
    b = wl.sample(12, seed=7)
    assert [s.to_json() for s in a] == [s.to_json() for s in b[:6]]


def test_different_seeds_differ():
    wl = mixed_chat(**SMOKE_WL)
    a = [s.to_json() for s in wl.sample(8, seed=0)]
    b = [s.to_json() for s in wl.sample(8, seed=1)]
    assert a != b


def test_shared_prefix_page_aligned_and_chain_hash_equal():
    """Cohort shared prefixes are whole pages and chain-hash equal
    across requests (the alignment the prefix cache and router
    affinity key on), stable across sample seeds; distinct cohorts get
    distinct prefixes."""
    from butterfly_tpu.cache.prefix import chain_block_hashes
    wl = mixed_chat(**SMOKE_WL)
    by_cohort = {}
    for seed in (0, 1):
        for s in wl.sample(24, seed):
            by_cohort.setdefault(s.cohort, []).append(s)
    chat, alt = by_cohort["chat"], by_cohort["chat_alt"]
    assert len(chat) >= 2 and len(alt) >= 1
    cohorts = {c.name: c for c in wl.cohorts}
    n_prefix = cohorts["chat"].shared_prefix_pages * wl.page_size
    assert n_prefix > 0 and n_prefix % wl.page_size == 0
    heads = {chain_block_hashes(s.tokens, wl.page_size, 1)[0]
             for s in chat}
    assert len(heads) == 1  # one shared first block across seeds
    alt_heads = {chain_block_hashes(s.tokens, wl.page_size, 1)[0]
                 for s in alt}
    assert heads != alt_heads


def test_workload_spec_roundtrip_samples_identically():
    wl = mixed_chat(**SMOKE_WL)
    wl2 = Workload.from_spec(wl.spec())
    assert [s.to_json() for s in wl.sample(8, 3)] == \
        [s.to_json() for s in wl2.sample(8, 3)]


# ---------------------------------------------------------------------------
# arrival processes
# ---------------------------------------------------------------------------


def test_poisson_interarrival_mean():
    """Poisson inter-arrival mean must track 1/rate (10% tolerance at
    n=4000) — this is the test that kills the mutcheck arrival-rate
    mutant (a process that ignores its rate samples mean 1.0s gaps)."""
    rate = 50.0
    ts = Poisson(rate).times(4000, seed=1)
    assert ts == sorted(ts) and ts[0] > 0
    gaps = [b - a for a, b in zip([0.0] + ts[:-1], ts)]
    mean = statistics.mean(gaps)
    assert abs(mean - 1.0 / rate) < 0.1 / rate
    # determinism
    assert ts == Poisson(rate).times(4000, seed=1)
    assert ts != Poisson(rate).times(4000, seed=2)


def test_burst_process_is_bursty():
    """MarkovOnOff gaps are bimodal: dense in-burst gaps at ~1/rate_on
    and off-phase silences near mean_off_s — unlike a Poisson stream of
    the same mean rate."""
    p = MarkovOnOff(rate_on=100.0, mean_on_s=0.5, mean_off_s=2.0)
    ts = p.times(600, seed=0)
    assert ts == sorted(ts)
    gaps = [b - a for a, b in zip([0.0] + ts[:-1], ts)]
    small = sum(1 for g in gaps if g < 5.0 / 100.0)
    assert small / len(gaps) > 0.8        # dense bursts dominate
    assert max(gaps) > 0.5                # but real silences exist
    # spec round-trip
    assert parse_arrival(p.spec()) == p


def test_ramp_accelerates():
    """Ramp arrivals speed up: the mean gap over the first quarter is
    larger than over the last quarter (rate0 < rate1)."""
    ts = Ramp(2.0, 50.0, 5.0).times(400, seed=0)
    gaps = [b - a for a, b in zip([0.0] + ts[:-1], ts)]
    q = len(gaps) // 4
    assert statistics.mean(gaps[:q]) > 2 * statistics.mean(gaps[-q:])


def test_parse_arrival_specs_and_errors():
    assert parse_arrival("poisson:8") == Poisson(8.0)
    assert parse_arrival("burst:20:0.5:2") == \
        MarkovOnOff(20.0, 0.5, 2.0, 0.0)
    assert parse_arrival("burst:20:0.5:2:1") == \
        MarkovOnOff(20.0, 0.5, 2.0, 1.0)
    assert parse_arrival("ramp:2:50:10") == Ramp(2.0, 50.0, 10.0)
    for bad in ("poisson", "poisson:0", "poisson:x", "burst:1:0:1",
                "drizzle:3", "ramp:1:2"):
        with pytest.raises(ValueError):
            parse_arrival(bad)


def test_assign_arrivals_stamps_schedule():
    wl, specs = smoke_specs(n=6)
    assert all(s.arrival_s >= 0 for s in specs)
    assert [s.arrival_s for s in specs] == sorted(s.arrival_s
                                                  for s in specs)


# ---------------------------------------------------------------------------
# server-level: preemption + shed through the real admission path
# ---------------------------------------------------------------------------


def _spin_server(rt: RuntimeConfig, slo_ttft_s=None):
    from http.server import ThreadingHTTPServer
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(0))
    sched = Scheduler(ServingEngine(model, params, rt),
                      slo_ttft_s=slo_ttft_s)
    state = ServerState(sched, ByteTokenizer())
    state.thread.start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return f"http://127.0.0.1:{httpd.server_port}", state, httpd


@pytest.fixture(scope="module")
def pressure_server():
    """Tiny replica with the page pool at ~30% of worst-case demand
    (16 pages vs 4 slots x 14 pages): the mixed_chat burst must
    contest it. No SLO declared — admission never sheds, so the
    preemption pressure is undiluted."""
    rt = RuntimeConfig(max_batch_size=4, max_seq_len=112, page_size=8,
                       num_pages=16, prefix_caching=True,
                       decode_steps_per_tick=4, inflight_blocks=2,
                       prefill_max_batch=4)
    url, state, httpd = _spin_server(rt)
    yield url, state
    state.stop.set()
    httpd.shutdown()


def test_mixed_chat_replay_forces_preemption(pressure_server):
    """THE acceptance property (ROADMAP item 2): the canned mixed_chat
    workload, fired open-loop at a live server, drives
    serving_preemptions > 0 — and every preempted request still
    completes (recompute preemption is invisible to clients). The
    replay summary's ``server`` block (scraped /metrics) is where the
    preemptions show up: client-observed and server-counted outcomes
    in one artifact."""
    url, state = pressure_server
    wl, specs = smoke_specs(n=12, seed=0)
    out = replay_trace(url, specs, timeout=120.0)
    assert out["sent"] == 12
    assert out["outcomes"]["ok"] == 12, out["errors"]
    assert out["open_loop"] is True
    srv = out["server"]
    assert srv["scraped"] is True
    assert srv["serving_preemptions"] > 0
    # server counted every generated token the clients saw
    assert srv["tokens_generated_total"] >= sum(
        1 for _ in range(12))
    # the scheduler's own counter agrees with the scraped artifact
    assert state.sched.metrics()["preemptions_total"] == \
        srv["serving_preemptions"]
    # client-observed: no shed, no deadline — pure page pressure
    assert out["outcomes"]["shed_429"] == 0 == srv["shed_total"]


@pytest.fixture(scope="module")
def shed_server():
    """Replica with a declared (absurdly tight) TTFT objective: once
    latency evidence exists, predicted TTFT always busts 0.01 ms, so
    batch-priority arrivals shed deterministically (PR 8 semantics:
    batch sheds AT the objective; a cold server never sheds blind)."""
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=64, page_size=8)
    url, state, httpd = _spin_server(rt, slo_ttft_s=1e-5)
    yield url, state
    state.stop.set()
    httpd.shutdown()


def test_shed_429_through_admission_path(shed_server):
    """At least one 429 shed through the real PR-8 admission path
    (ServerState.submit -> shed_decision -> HTTP 429 + Retry-After),
    counted on BOTH sides of the wire: the replay summary's shed_429
    outcome and the scraped server shed_total match."""
    url, state = shed_server
    # evidence request: a finished multi-token request populates the
    # rolling ITL window predict_ttft reads (cold server never sheds)
    body = json.dumps({"tokens": [5, 7, 11], "max_tokens": 4,
                       "stop_token": -1}).encode()
    req = urllib.request.Request(url + "/generate", data=body,
                                 headers={"Content-Type":
                                          "application/json"})
    with urllib.request.urlopen(req, timeout=120) as resp:
        assert len(json.loads(resp.read())["tokens"]) == 4
    assert state.sched.predict_ttft(4) is not None  # evidence exists
    specs = [RequestSpec(index=i, cohort="batch", tokens=[3, 1, 4],
                         max_new=4, priority="batch")
             for i in range(3)]
    out = replay_trace(url, specs, timeout=120.0)
    assert out["outcomes"]["shed_429"] >= 1
    srv = out["server"]
    assert srv["scraped"] and srv["shed_total"] >= 1
    assert srv["shed_total"] == out["outcomes"]["shed_429"]
    # sheds are terminal outcomes, not errors (loadgen exit semantics)
    assert out["outcomes"]["error"] == 0
    assert out["terminal"] == out["sent"]


# ---------------------------------------------------------------------------
# bench phase + CLI smoke (tier-1-safe: seconds, not minutes)
# ---------------------------------------------------------------------------


def test_mixed_bench_phase_smoke():
    """The tiny `--mixed` bench phase: run_mixed_benchmark on the
    smallest preemption-forcing shape and pin its JSON contract —
    mixed_* TTFT/ITL/tok/s fields, serving_preemptions > 0, and a
    >= 2x2 decode_steps_per_tick x inflight_blocks operating-point
    table with a knee (the ISSUE 10 acceptance keys)."""
    from butterfly_tpu.obs.benchmark import run_mixed_benchmark
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(0))
    out = run_mixed_benchmark(
        model, params, n_requests=10, max_batch=4,
        prompt_lo=8, prompt_hi=40, max_new_lo=16, max_new_hi=40,
        page_size=8, pool_fraction=0.3, decode_steps_per_tick=2,
        inflight_blocks=2, prefill_max_batch=4, kv_quant="none",
        arrival=SMOKE_ARRIVAL, grid=[(1, 1), (1, 2), (2, 1), (2, 2)])
    assert out["mixed_serving_preemptions"] > 0
    assert out["mixed_serving_tokens_per_sec"] > 0
    for k in ("mixed_ttft_p50", "mixed_ttft_p95",
              "mixed_itl_req_mean_p50", "mixed_shed_total",
              "mixed_deadline_expired_total"):
        assert k in out, k
    pts = out["operating_points"]
    assert len(pts) == 4
    assert {(p["decode_steps_per_tick"], p["inflight_blocks"])
            for p in pts} == {(1, 1), (1, 2), (2, 1), (2, 2)}
    for p in pts:
        assert p["ok"] + p["shed_429"] + p["expired_504"] \
            + p["skipped_too_long"] == 10
        assert p["tokens_per_sec"] > 0 and "ttft_p95" in p
    knee = out["operating_point_knee"]
    assert knee is not None
    assert (knee["decode_steps_per_tick"], knee["inflight_blocks"]) \
        in {(1, 1), (1, 2), (2, 1), (2, 2)}


def test_cli_workload_generate_deterministic(tmp_path):
    """`butterfly workload generate` smoke: writes a loadable trace,
    byte-identical across invocations (CI canary for the whole
    generate -> save chain)."""
    from butterfly_tpu.serve.cli import main
    args = ["workload", "generate", "--workload", "mixed_chat",
            "--n", "6", "--seed", "3", "--arrival", "poisson:50",
            "--page-size", "8", "--prompt-lo", "8", "--prompt-hi", "24",
            "--max-new-lo", "2", "--max-new-hi", "6", "--vocab", "258"]
    p1, p2 = tmp_path / "t1.jsonl", tmp_path / "t2.jsonl"
    assert main(args + ["--out", str(p1)]) == 0
    assert main(args + ["--out", str(p2)]) == 0
    assert p1.read_bytes() == p2.read_bytes()
    header, specs = load_trace(p1)
    assert header["n"] == 6 and len(specs) == 6
    assert header["arrival"] == "poisson:50"


def test_cli_workload_replay_smoke(tmp_path, pressure_server):
    """`butterfly workload replay` smoke against a live replica: the
    saved trace fires and every request reaches a terminal outcome."""
    from butterfly_tpu.serve.cli import main
    url, _ = pressure_server
    p = tmp_path / "t.jsonl"
    assert main(["workload", "generate", "--workload", "mixed_chat",
                 "--n", "4", "--seed", "1", "--arrival", "poisson:50",
                 "--page-size", "8", "--prompt-lo", "8",
                 "--prompt-hi", "24", "--max-new-lo", "2",
                 "--max-new-hi", "6", "--vocab", "258",
                 "--out", str(p)]) == 0
    assert main(["workload", "replay", "--trace", str(p),
                 "--url", url, "--speed", "50"]) == 0


def test_loadgen_open_loop_workload_mode(pressure_server):
    """tools/loadgen.py --workload: the open-loop mode generates,
    schedules, and fires a workload end to end, and its summary folds
    the scraped server counters in (satellite 2)."""
    import importlib
    import sys
    from pathlib import Path
    url, _ = pressure_server
    tools = str(Path(__file__).resolve().parents[1] / "tools")
    sys.path.insert(0, tools)
    try:
        lg = importlib.import_module("loadgen")
    finally:
        sys.path.remove(tools)
    rc = lg.main(["--url", url, "--workload", "mixed_chat", "--n", "4",
                  "--seed", "2", "--arrival", "poisson:50",
                  "--speed", "50", "--page-size", "8",
                  "--prompt-lo", "8", "--prompt-hi", "24",
                  "--max-new-lo", "2", "--max-new-hi", "6",
                  "--vocab", "258", "--json"])
    assert rc == 0
