"""Mesh-aware serving: the north-star distributed-serving path
(BASELINE.json configs[4] x configs[1]) on fake devices.

Token-for-token parity: a Scheduler over a ServingEngine on a
tensor=4 x data=2 mesh must produce exactly what the unmeshed engine
produces, end to end through HTTP. Also: CLI flag wiring (build_mesh)
and donation aliasing under the mesh.
"""
import argparse
import json
import threading
import urllib.request
import warnings

import jax
import pytest

from butterfly_tpu.core.config import MeshConfig, RuntimeConfig, tiny
from butterfly_tpu.core.mesh import make_mesh
from butterfly_tpu.engine.serving import ServingEngine
from butterfly_tpu.models.common import Model
from butterfly_tpu.sched.scheduler import Scheduler

# kv-heads divisible by tensor=4 so the pool actually shards.
CFG = tiny("llama", dtype="float32", param_dtype="float32",
           num_heads=8, num_kv_heads=4, head_dim=8)
PROMPTS = [[5, 7, 11], [3, 1], [2, 4, 6, 8], [9]]


def _make_sched(params, mesh=None, max_batch=4):
    rt = RuntimeConfig(max_batch_size=max_batch, max_seq_len=64, page_size=8)
    return Scheduler(ServingEngine(Model(CFG), params, rt, mesh=mesh))


@pytest.fixture(scope="module")
def params():
    return Model(CFG).init(jax.random.PRNGKey(42))


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(MeshConfig(data=2, tensor=4))


def test_meshed_scheduler_token_parity(params, mesh):
    ref = _make_sched(params)
    ref_reqs = [ref.submit(p, max_new_tokens=6) for p in PROMPTS]
    ref.run_until_done()

    sched = _make_sched(params, mesh=mesh)
    reqs = [sched.submit(p, max_new_tokens=6) for p in PROMPTS]
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        sched.run_until_done()
    assert [r.output for r in reqs] == [r.output for r in ref_reqs]
    bad = [str(w.message) for w in rec
           if "donated buffers were not usable" in str(w.message)]
    assert not bad, f"meshed serving donation failed to alias: {bad}"


def test_meshed_scheduler_kernels_token_parity(params, mesh):
    """Pallas kernels (interpret mode) under the mesh == unmeshed gather
    path, token-exact — the round-2 VERDICT item 1 regression test."""
    ref = _make_sched(params)
    ref_reqs = [ref.submit(p, max_new_tokens=6) for p in PROMPTS]
    ref.run_until_done()

    rt = RuntimeConfig(max_batch_size=4, max_seq_len=64, page_size=8)
    sched = Scheduler(ServingEngine(Model(CFG), params, rt, mesh=mesh,
                                    use_kernels=True))
    reqs = [sched.submit(p, max_new_tokens=6) for p in PROMPTS]
    sched.run_until_done()
    assert [r.output for r in reqs] == [r.output for r in ref_reqs]


def test_meshed_kernels_gqa_kv_smaller_than_tensor(mesh):
    """Kv/page-dim mixup regression (round-4 ADVICE high): with pools
    laid out [P, Kv, page, H], num_kv_heads=2 < tensor=4 while
    page_size=8 IS tensor-divisible. shardable_axes must test Kv (2),
    not page (8) — the kernel falls back to the gather path instead of
    raising in shard_map — and tokens must match the unmeshed engine."""
    cfg = tiny("llama", dtype="float32", param_dtype="float32",
               num_heads=8, num_kv_heads=2, head_dim=8)
    params = Model(cfg).init(jax.random.PRNGKey(7))
    rt = RuntimeConfig(max_batch_size=4, max_seq_len=64, page_size=8)

    ref = Scheduler(ServingEngine(Model(cfg), params, rt))
    ref_reqs = [ref.submit(p, max_new_tokens=6) for p in PROMPTS]
    ref.run_until_done()

    sched = Scheduler(ServingEngine(Model(cfg), params, rt, mesh=mesh,
                                    use_kernels=True))
    reqs = [sched.submit(p, max_new_tokens=6) for p in PROMPTS]
    sched.run_until_done()
    assert [r.output for r in reqs] == [r.output for r in ref_reqs]


def test_meshed_engine_flash_prefill_token_parity(params, mesh):
    """InferenceEngine flash prefill through shard_map on the mesh."""
    import numpy as np
    from butterfly_tpu.engine import InferenceEngine, SamplingParams
    sp = SamplingParams(max_new_tokens=6)
    a = InferenceEngine(Model(CFG), params,
                        use_flash_prefill=False).generate(PROMPTS, sp)
    b = InferenceEngine(Model(CFG), params, mesh=mesh,
                        use_flash_prefill=True).generate(PROMPTS, sp)
    np.testing.assert_array_equal(a.tokens, b.tokens)


def test_meshed_pool_is_sharded(params, mesh):
    eng = ServingEngine(Model(CFG), params,
                        RuntimeConfig(max_batch_size=4, max_seq_len=64,
                                      page_size=8), mesh=mesh)
    spec = eng.cache.k_pages.sharding.spec
    assert spec[2] == "tensor"  # kv-heads split over TP shards
    assert eng.cache.page_table.sharding.spec[0] == "data"


def test_stage_parallel_scheduler_token_parity(params):
    """VERDICT r2 item 4: pipeline-parallel serving — the paged decode
    path runs the GPipe schedule per stage slice; token-exact vs the
    unmeshed scheduler."""
    ref = _make_sched(params)
    ref_reqs = [ref.submit(p, max_new_tokens=6) for p in PROMPTS]
    ref.run_until_done()

    mesh = make_mesh(MeshConfig(stage=2, tensor=4))
    sched = _make_sched(params, mesh=mesh)
    reqs = [sched.submit(p, max_new_tokens=6) for p in PROMPTS]
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        sched.run_until_done()
    assert [r.output for r in reqs] == [r.output for r in ref_reqs]
    bad = [str(w.message) for w in rec
           if "donated buffers were not usable" in str(w.message)]
    assert not bad, f"stage-parallel serving donation failed to alias: {bad}"


def test_stage_data_parallel_scheduler_token_parity(params):
    """PP x DP: slots sharded over data while microbatches of slots flow
    through the stage schedule."""
    ref = _make_sched(params)
    ref_reqs = [ref.submit(p, max_new_tokens=5) for p in PROMPTS]
    ref.run_until_done()

    mesh = make_mesh(MeshConfig(stage=2, data=4))
    sched = _make_sched(params, mesh=mesh)
    reqs = [sched.submit(p, max_new_tokens=5) for p in PROMPTS]
    sched.run_until_done()
    assert [r.output for r in reqs] == [r.output for r in ref_reqs]


def test_stage_pool_is_stage_sharded(params):
    mesh = make_mesh(MeshConfig(stage=2, tensor=4))
    eng = ServingEngine(Model(CFG), params,
                        RuntimeConfig(max_batch_size=4, max_seq_len=64,
                                      page_size=8), mesh=mesh)
    spec = eng.cache.k_pages.sharding.spec
    assert spec[0] == "stage"   # each stage owns its layers' pages
    assert spec[2] == "tensor"


def test_stage_indivisible_layers_rejected(params):
    mesh = make_mesh(MeshConfig(stage=4, data=2))  # 2 layers, 4 stages
    with pytest.raises(ValueError, match="not divisible"):
        ServingEngine(Model(CFG), params, RuntimeConfig(), mesh=mesh)


def test_http_generate_on_mesh(params, mesh):
    from http.server import ThreadingHTTPServer
    from butterfly_tpu.serve.server import ServerState, make_handler
    from butterfly_tpu.utils.tokenizer import ByteTokenizer

    sched = _make_sched(params, mesh=mesh)
    state = ServerState(sched, ByteTokenizer())
    state.thread.start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_port}"
    try:
        req = urllib.request.Request(
            url + "/generate",
            data=json.dumps({"tokens": PROMPTS[0], "max_tokens": 5,
                             "stop_token": -1}).encode(),
            headers={"Content-Type": "application/json"})
        out = json.loads(urllib.request.urlopen(req, timeout=300).read())
        ref = _make_sched(params)
        r = ref.submit(PROMPTS[0], max_new_tokens=5)
        ref.run_until_done()
        assert out["tokens"] == r.output
    finally:
        state.stop.set()
        httpd.shutdown()


def test_cli_build_mesh_flags():
    from butterfly_tpu.serve.cli import build_mesh
    args = argparse.Namespace(tensor_parallel=4, stage_parallel=1,
                              expert_parallel=1, data_parallel=2)
    mesh = build_mesh(args)
    assert mesh.shape["tensor"] == 4 and mesh.shape["data"] == 2

    args1 = argparse.Namespace(tensor_parallel=1, stage_parallel=1,
                               expert_parallel=1, data_parallel=1)
    assert build_mesh(args1) is None

    big = argparse.Namespace(tensor_parallel=64, stage_parallel=1,
                             expert_parallel=1, data_parallel=1)
    with pytest.raises(SystemExit):
        build_mesh(big)
