"""Model correctness: shapes, cache semantics, prefill/decode consistency,
and numerical parity against torch transformers (GPT-2 and Llama)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from butterfly_tpu.core.config import tiny
from butterfly_tpu.models.common import Model, init_cache, forward


F32 = dict(dtype="float32", param_dtype="float32")


@pytest.mark.parametrize("arch", ["gpt2", "llama", "mixtral"])
def test_forward_shapes(arch):
    cfg = tiny(arch, **F32)
    m = Model(cfg)
    params = m.init(jax.random.PRNGKey(0))
    cache = m.init_cache(batch=2, max_seq=32)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 7)))
    logits, cache = m(params, tokens, cache)
    assert logits.shape == (2, 7, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert cache.length.tolist() == [7, 7]
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.parametrize("arch", ["gpt2", "llama"])
def test_prefill_then_decode_matches_full_forward(arch):
    """Logits for token t must be identical whether computed in one forward
    over the whole sequence or via prefill + incremental decode."""
    cfg = tiny(arch, **F32)
    m = Model(cfg)
    params = m.init(jax.random.PRNGKey(1))
    rng = np.random.RandomState(1)
    T = 10
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (1, T)))

    full_logits, _ = m(params, tokens, m.init_cache(1, 32))

    cache = m.init_cache(1, 32)
    split = 6
    logits_a, cache = m(params, tokens[:, :split], cache)
    step_logits = [logits_a]
    for t in range(split, T):
        lg, cache = m(params, tokens[:, t:t + 1], cache)
        step_logits.append(lg)
    inc_logits = jnp.concatenate(step_logits, axis=1)

    np.testing.assert_allclose(np.asarray(full_logits), np.asarray(inc_logits),
                               rtol=2e-4, atol=2e-4)


def test_ragged_batch_isolation():
    """Right-padded prefill must give each sequence the same logits it would
    get alone (padding never leaks through the causal mask)."""
    cfg = tiny("llama", **F32)
    m = Model(cfg)
    params = m.init(jax.random.PRNGKey(2))
    rng = np.random.RandomState(2)
    a = rng.randint(0, cfg.vocab_size, (1, 5))
    b = rng.randint(0, cfg.vocab_size, (1, 9))

    la, _ = m(params, jnp.asarray(a), m.init_cache(1, 32))
    lb, _ = m(params, jnp.asarray(b), m.init_cache(1, 32))

    batch = np.zeros((2, 9), np.int32)
    batch[0, :5] = a[0]
    batch[1] = b[0]
    lbatch, _ = m(params, jnp.asarray(batch), m.init_cache(2, 32))

    np.testing.assert_allclose(np.asarray(lbatch[0, :5]), np.asarray(la[0]),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(lbatch[1]), np.asarray(lb[0]),
                               rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# Golden parity vs torch transformers (random-init, weights copied over)
# ---------------------------------------------------------------------------

def test_gpt2_parity_with_hf():
    torch = pytest.importorskip("torch")
    tr = pytest.importorskip("transformers")
    from butterfly_tpu.models import gpt2 as bf_gpt2

    hf_cfg = tr.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        activation_function="gelu_new", resid_pdrop=0.0, embd_pdrop=0.0,
        attn_pdrop=0.0,
    )
    torch.manual_seed(0)
    hf = tr.GPT2LMHeadModel(hf_cfg).eval()

    cfg = tiny("gpt2", vocab_size=128, hidden_size=32, num_layers=2,
               num_heads=4, num_kv_heads=4, head_dim=8, intermediate_size=128,
               max_seq_len=64, **F32)
    params = bf_gpt2.params_from_hf_state_dict(hf.state_dict(), cfg)

    rng = np.random.RandomState(3)
    tokens = rng.randint(0, 128, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()

    m = Model(cfg)
    ours, _ = m(params, jnp.asarray(tokens), m.init_cache(2, 64))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-4)


def test_llama_parity_with_hf():
    torch = pytest.importorskip("torch")
    tr = pytest.importorskip("transformers")
    from butterfly_tpu.models import llama as bf_llama

    hf_cfg = tr.LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, max_position_embeddings=64, rope_theta=10000.0,
        attention_dropout=0.0, tie_word_embeddings=False, rms_norm_eps=1e-5,
    )
    torch.manual_seed(0)
    hf = tr.LlamaForCausalLM(hf_cfg).eval()

    cfg = tiny("llama", vocab_size=128, hidden_size=32, num_layers=2,
               num_heads=4, num_kv_heads=2, head_dim=8, intermediate_size=64,
               max_seq_len=64, rope_theta=10000.0, **F32)
    params = bf_llama.params_from_hf_state_dict(hf.state_dict(), cfg)

    rng = np.random.RandomState(4)
    tokens = rng.randint(0, 128, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()

    m = Model(cfg)
    ours, _ = m(params, jnp.asarray(tokens), m.init_cache(2, 64))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-4)
