"""Metrics time series (ISSUE 16): the SignalRecorder ring, alert
rules, the fleet rollup timeline, and the stdlib dashboard.

Layers covered:

* rate derivation (``Counter.rate`` clamps at zero across a counter
  reset) and the registry's cheap ``snapshot()``;
* the recorder's ring bounds, ``since=`` pagination across a ring
  wrap, and the ``signals=`` filter (the /debug/timeseries contract);
* alert predicates — including THE mutcheck discriminator: a single
  above-threshold sample must NOT fire a sustained rule — rising-edge
  latching, and the flight-recorder ``alert`` events with series
  context;
* the scheduler soak: a tight page pool under load produces visibly
  MOVING preemption-rate and pages-free series plus a fired alert;
* the fleet merge: >= 3 sources on one clock, stale-gauge drop, the
  per-replica flatline rules;
* tools/dashboard.py + ``butterfly dash`` + tick_report ``--follow``
  subprocess/CLI smoke.
"""
import json
import subprocess
import sys
import threading
import urllib.request
from pathlib import Path

import pytest

from butterfly_tpu.obs.registry import (Counter, MetricsRegistry,
                                        parse_exposition)
from butterfly_tpu.obs.ticklog import FlightRecorder
from butterfly_tpu.obs.timeseries import (FLEET_TIMESERIES_SCHEMA,
                                          TIMESERIES_SCHEMA, AlertRule,
                                          SignalRecorder,
                                          default_fleet_rules,
                                          default_rules, evaluate_rules,
                                          series_summary,
                                          slope_per_sample)

REPO = Path(__file__).parent.parent


def samples_of(values, signal="s"):
    """Ring-entry dicts for one signal's value sequence."""
    return [{"seq": i, "signals": {signal: v}}
            for i, v in enumerate(values)]


# ---------------------------------------------------------------------------
# registry satellites: Counter.rate + snapshot + exposition edge cases
# ---------------------------------------------------------------------------

def test_counter_rate_and_reset_clamp():
    assert Counter.rate(10.0, 30.0, 2.0) == 10.0
    # counter reset (replica restart): clamped, never negative
    assert Counter.rate(100.0, 3.0, 1.0) == 0.0
    # degenerate dt never divides by zero
    assert Counter.rate(0.0, 5.0, 0.0) == 0.0
    assert Counter.rate(0.0, 5.0, -1.0) == 0.0


def test_registry_snapshot_cheap_values():
    reg = MetricsRegistry()
    reg.counter("reqs_total").inc(3)
    reg.gauge("depth").set(7)
    fam = reg.counter_family("by_kind_total", "", ("kind",))
    fam.labels("a").inc(2)
    fam.labels("b").inc(5)
    snap = reg.snapshot()
    assert snap["reqs_total"] == 3.0
    assert snap["depth"] == 7.0
    # labeled families collapse to their sum (a scalar trajectory)
    assert snap["by_kind_total"] == 7.0


def test_zero_observation_histogram_exposition_parses():
    """A histogram with zero observations still renders its full
    ladder, and the parse roundtrip keeps +Inf == _count == 0 (the
    fleet rollup must not choke on a fresh replica)."""
    reg = MetricsRegistry()
    reg.histogram("ttft_seconds", "help", (0.1, 1.0))
    fams = parse_exposition(reg.render())
    h = fams["butterfly_ttft_seconds"]
    inf = h["samples"][("butterfly_ttft_seconds_bucket", (("le", "+Inf"),))]
    assert inf == h["samples"][("butterfly_ttft_seconds_count", ())] == 0.0
    assert h["samples"][("butterfly_ttft_seconds_sum", ())] == 0.0
    # the finite ladder is present even with nothing observed
    assert ("butterfly_ttft_seconds_bucket",
            (("le", "0.1"),)) in h["samples"]


# ---------------------------------------------------------------------------
# the recorder ring
# ---------------------------------------------------------------------------

def test_recorder_rejects_disabled_interval():
    with pytest.raises(ValueError):
        SignalRecorder(interval_s=0.0)


def test_recorder_due_gate():
    rec = SignalRecorder(interval_s=3600.0)
    assert rec.due()  # first sample is owed immediately
    rec.sample({"g": 1.0})
    assert not rec.due()  # next one is an hour away
    rec2 = SignalRecorder(interval_s=1e-9)
    rec2.sample({"g": 1.0})
    assert rec2.due()


def test_recorder_ring_bounded_and_seq_monotonic():
    rec = SignalRecorder(interval_s=1e-9, capacity=4)
    for i in range(7):
        rec.sample({"g": float(i)})
    d = rec.dump()
    assert d["schema"] == TIMESERIES_SCHEMA and d["enabled"] is True
    seqs = [s["seq"] for s in d["samples"]]
    assert seqs == [3, 4, 5, 6]  # oldest evicted, order preserved
    assert d["next_seq"] == 7


def test_recorder_rates_from_cumulative_counters():
    rec = SignalRecorder(interval_s=1e-9)
    rec.sample({}, rates={"tok_ps": 100.0})
    rec.sample({}, rates={"tok_ps": 160.0})
    s1, s2 = rec.dump()["samples"]
    assert s1["signals"]["tok_ps"] == 0.0  # no prior delta yet
    dt = s2["t_mono"] - s1["t_mono"]
    assert s2["signals"]["tok_ps"] == pytest.approx(60.0 / dt)
    # counter reset between samples: the rate clamps flat at zero
    rec.sample({}, rates={"tok_ps": 3.0})
    assert rec.dump()["samples"][-1]["signals"]["tok_ps"] == 0.0


def test_dump_since_pagination_across_ring_wrap():
    rec = SignalRecorder(interval_s=1e-9, capacity=4)
    for i in range(6):
        rec.sample({"g": float(i)})
    # a cursor older than the ring tail returns what survived the wrap
    assert [s["seq"] for s in rec.dump(since=0)["samples"]] == [2, 3, 4, 5]
    assert [s["seq"] for s in rec.dump(since=4)["samples"]] == [4, 5]
    # the incremental-poll contract: since=next_seq is empty, not an error
    nxt = rec.dump()["next_seq"]
    assert rec.dump(since=nxt)["samples"] == []


def test_dump_signals_filter():
    rec = SignalRecorder(interval_s=1e-9)
    rec.sample({"a": 1.0, "b": 2.0, "c": 3.0})
    d = rec.dump(signals=["a", "c"])
    assert d["samples"][0]["signals"] == {"a": 1.0, "c": 3.0}
    # unfiltered dump unaffected
    assert set(rec.dump()["samples"][0]["signals"]) == {"a", "b", "c"}


def test_sample_carries_caller_wall_stamp():
    rec = SignalRecorder(interval_s=1e-9)
    rec.sample({"g": 1.0}, t_wall=1234.5)
    assert rec.dump()["samples"][0]["t_wall"] == 1234.5


# ---------------------------------------------------------------------------
# alert rules
# ---------------------------------------------------------------------------

def test_alert_rule_validation():
    with pytest.raises(ValueError):
        AlertRule("x", "s", 3, "sideways", 1.0)
    with pytest.raises(ValueError):
        AlertRule("x", "s", 0, "sustained_above", 1.0)


def test_slope_per_sample():
    assert slope_per_sample([0.0, 1.0, 2.0, 3.0]) == pytest.approx(1.0)
    assert slope_per_sample([9.0, 7.0, 5.0]) == pytest.approx(-2.0)
    assert slope_per_sample([5.0]) == 0.0
    assert slope_per_sample([]) == 0.0


def test_sustained_single_sample_does_not_fire():
    """THE mutcheck discriminator: one above-threshold sample is a
    blip, not an alert — the window-length guard must hold."""
    rule = AlertRule("burn", "s", 5, "sustained_above", 0.5)
    assert evaluate_rules([rule], samples_of([0.9])) == []
    # even several hot samples short of the window stay silent
    assert evaluate_rules([rule], samples_of([0.9] * 4)) == []


def test_sustained_fires_after_window_and_latches():
    rule = AlertRule("burn", "s", 3, "sustained_above", 0.5,
                     severity="page")
    fired = evaluate_rules([rule], samples_of([0.9, 0.8, 0.7]))
    assert len(fired) == 1
    rec = fired[0]
    assert rec["rule"] == "burn" and rec["severity"] == "page"
    assert rec["value"] == 0.7 and rec["series"] == [0.9, 0.8, 0.7]
    # still hot: same excursion, no repeat alert
    assert evaluate_rules([rule], samples_of([0.9, 0.8, 0.7, 0.6])) == []
    # predicate releases (one cool sample), then a fresh excursion fires
    assert evaluate_rules([rule], samples_of([0.7, 0.6, 0.1])) == []
    assert len(evaluate_rules([rule],
                              samples_of([0.1, 0.9, 0.9, 0.9]))) == 1


def test_drift_above_needs_two_windows():
    rule = AlertRule("drift", "s", 3, "drift_above", 0.5)
    # recent mean 2.0 vs prior mean 1.0: drift 1.0 > 0.5
    vals = [1.0, 1.0, 1.0, 2.0, 2.0, 2.0]
    fired = evaluate_rules([rule], samples_of(vals))
    assert len(fired) == 1 and fired[0]["value"] == pytest.approx(1.0)
    # only one window of history: silent
    rule2 = AlertRule("drift", "s", 3, "drift_above", 0.5)
    assert evaluate_rules([rule2], samples_of([2.0, 2.0, 2.0])) == []


def test_slope_below_fires_on_draining_series():
    rule = AlertRule("drain", "s", 4, "slope_below", -1.0)
    fired = evaluate_rules([rule], samples_of([40.0, 30.0, 20.0, 10.0]))
    assert len(fired) == 1
    assert fired[0]["value"] == pytest.approx(-10.0)
    rule2 = AlertRule("drain", "s", 4, "slope_below", -1.0)
    assert evaluate_rules([rule2],
                          samples_of([10.0, 10.1, 10.0, 10.1])) == []


def test_flatline_counts_missing_not_series():
    rule = AlertRule("flat", "scrape", 3, "flatline", 3)
    assert evaluate_rules([rule], [], missing=2) == []
    fired = evaluate_rules([rule], [], missing=3)
    assert len(fired) == 1 and fired[0]["value"] == 3.0
    # latched while missing, re-arms once the source reappears
    assert evaluate_rules([rule], [], missing=4) == []
    assert evaluate_rules([rule], [], missing=0) == []
    assert len(evaluate_rules([rule], [], missing=3)) == 1


def test_alert_event_lands_in_flightrec_with_series():
    fr = FlightRecorder()
    rule = AlertRule("burn", "s", 2, "sustained_above", 0.5)
    evaluate_rules([rule], samples_of([0.9, 0.9]), flightrec=fr,
                   source="rep1")
    evs = [e for e in fr.dump()["events"] if e["kind"] == "alert"]
    assert len(evs) == 1
    ev = evs[0]
    assert ev["rule"] == "burn" and ev["source"] == "rep1"
    assert ev["series"] == [0.9, 0.9]  # the post-mortem context
    assert "t_wall" in ev


def test_recorder_collects_alerts_in_dump():
    rec = SignalRecorder(
        interval_s=1e-9,
        rules=[AlertRule("burn", "g", 2, "sustained_above", 0.5)])
    rec.sample({"g": 0.9}, t_wall=10.0)
    fired = rec.sample({"g": 0.9}, t_wall=11.0)
    assert len(fired) == 1
    alerts = rec.dump()["alerts"]
    assert len(alerts) == 1
    assert alerts[0]["rule"] == "burn" and alerts[0]["t_wall"] == 11.0
    assert alerts[0]["seq"] == 1


def test_default_rule_tables():
    names = {r.name for r in default_rules()}
    assert names == {"slo_burn_sustained", "host_frac_drift",
                     "pages_free_slope"}
    fleet = {r.name for r in default_fleet_rules()}
    assert "replica_flatline" in fleet
    # described in the dump so a dashboard can render the rule table
    rec = SignalRecorder(interval_s=1e-9, rules=default_rules())
    assert {r["rule"] for r in rec.dump()["rules"]} == names


def test_series_summary_shape_scalars():
    rec = SignalRecorder(interval_s=1e-9)
    for v in (1.0, 3.0, 5.0):
        rec.sample({"g": v, "h": 2.0})
    summ = series_summary(rec.dump())
    assert summ["g"]["peak"] == 5.0
    assert summ["g"]["mean"] == pytest.approx(3.0)
    assert summ["g"]["slope"] == pytest.approx(2.0)
    assert summ["g"]["n"] == 3.0
    assert summ["h"]["slope"] == 0.0
    assert series_summary(rec.dump(), signals=["h"]).keys() == {"h"}


# ---------------------------------------------------------------------------
# scheduler integration: the tight-pool soak
# ---------------------------------------------------------------------------

def _make_sched(**kw):
    import jax
    from butterfly_tpu.core.config import RuntimeConfig, tiny
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.models.common import Model
    from butterfly_tpu.sched.scheduler import Scheduler
    cfg = tiny("llama", dtype="float32", param_dtype="float32")
    model = Model(cfg)
    params = model.init(jax.random.PRNGKey(42))
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=32, page_size=4,
                       num_pages=6)
    return Scheduler(ServingEngine(model, params, rt), **kw)


def test_recorder_off_is_zero_cost_default():
    """No recorder attached: the scheduler's only timeseries state is
    the None attribute (the per-tick cost is one is-None check; the
    phase-reconciliation suite runs entirely in this mode)."""
    sched = _make_sched()
    assert sched.timeseries is None
    sched.submit([5, 7, 11], max_new_tokens=3)
    sched.run_until_done()


def test_scheduler_soak_moving_series_and_alert():
    """The acceptance soak: a tight page pool under competing
    generations yields NON-CONSTANT pages-free and preemption-rate
    series, and an alert fires into the flight recorder with its
    series context attached."""
    fr = FlightRecorder()
    rec = SignalRecorder(
        interval_s=1e-9, capacity=4096, flightrec=fr,
        rules=[
            # fires when the pool drains across a window — the natural
            # trajectory of two growing requests over 6 pages
            AlertRule("pool_draining", "kv_pages_free", 3,
                      "slope_below", -0.01),
            # guaranteed excursion: two consecutive busy samples
            AlertRule("busy", "active_requests", 2,
                      "sustained_above", 0.5),
        ])
    sched = _make_sched(flightrec=fr, timeseries=rec)
    r1 = sched.submit([5, 7, 11], max_new_tokens=10)
    r2 = sched.submit([3, 1], max_new_tokens=10)
    sched.run_until_done(max_ticks=300)
    assert r1.state == "finished" and r2.state == "finished"
    assert sched.metrics()["preemptions_total"] > 0

    d = rec.dump()
    assert len(d["samples"]) >= 10
    pages = [s["signals"]["kv_pages_free"] for s in d["samples"]]
    assert len(set(pages)) > 1  # visibly moving, not a flat line
    pre = [s["signals"]["preemptions_per_sec"] for s in d["samples"]]
    assert max(pre) > 0.0 and len(set(pre)) > 1
    # every sample speaks the full signal vocabulary
    assert {"queue_depth", "active_requests", "inflight_depth",
            "kv_pages_free", "tokens_per_sec",
            "preemptions_per_sec"} <= set(d["samples"][0]["signals"])
    # an alert fired and the flight recorder holds it with context
    assert d["alerts"]
    evs = [e for e in fr.dump()["events"] if e["kind"] == "alert"]
    assert evs and "series" in evs[0]


def test_server_debug_timeseries_endpoint():
    """GET /debug/timeseries end to end: enabled body with samples,
    since/signals query params, and the disabled shape."""
    from http.server import ThreadingHTTPServer
    from butterfly_tpu.serve.server import ServerState, make_handler
    from butterfly_tpu.utils.tokenizer import ByteTokenizer
    rec = SignalRecorder(interval_s=1e-9, rules=default_rules())
    sched = _make_sched(timeseries=rec)
    state = ServerState(sched, ByteTokenizer())
    state.thread.start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_port}"
    try:
        body = json.dumps({"tokens": [5, 6, 7], "max_tokens": 4,
                           "stop_token": -1}).encode()
        req = urllib.request.Request(
            url + "/generate", data=body,
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=120).read()
        d = json.loads(urllib.request.urlopen(
            url + "/debug/timeseries", timeout=30).read())
        assert d["enabled"] and d["schema"] == TIMESERIES_SCHEMA
        assert d["samples"] and d["rules"]
        nxt = d["next_seq"]
        d2 = json.loads(urllib.request.urlopen(
            url + f"/debug/timeseries?since={nxt}&signals=queue_depth",
            timeout=30).read())
        # the scheduler thread may still be ticking, so the incremental
        # poll can legitimately see fresh samples — but never a replay
        # of anything at or before the cursor
        assert all(s["seq"] >= nxt for s in d2["samples"])
        d3 = json.loads(urllib.request.urlopen(
            url + "/debug/timeseries?signals=queue_depth,kv_pages_free",
            timeout=30).read())
        assert set(d3["samples"][0]["signals"]) <= {"queue_depth",
                                                    "kv_pages_free"}
    finally:
        state.stop.set()
        httpd.shutdown()
    # a scheduler without a recorder serves the disabled shape
    from butterfly_tpu.serve.server import ServerState as SS
    state2 = SS(_make_sched(), ByteTokenizer())
    assert state2.debug_timeseries() == {"enabled": False,
                                         "samples": [], "alerts": []}


# ---------------------------------------------------------------------------
# fleet: scrape rings, stale-gauge drop, merged timeline
# ---------------------------------------------------------------------------

def _gauge_text(**gauges):
    lines = []
    for name, v in gauges.items():
        lines.append(f"# TYPE butterfly_{name} gauge")
        lines.append(f"butterfly_{name} {v}")
    lines.append("# TYPE butterfly_reqs_total counter")
    lines.append("butterfly_reqs_total 5")
    return "\n".join(lines) + "\n"


def test_flat_gauges_extracts_unlabeled_gauges():
    from butterfly_tpu.router.pool import _flat_gauges
    text = (_gauge_text(queue_depth=3, kv_pages_free=40)
            + "# TYPE butterfly_out gauge\n"
            + 'butterfly_out{replica="a"} 2\n')
    flat = _flat_gauges(parse_exposition(text))
    # prefix stripped; counters and labeled families skipped
    assert flat == {"queue_depth": 3.0, "kv_pages_free": 40.0}


class _StubReplica:
    """Minimal /health + /metrics HTTP stub for pool-probe tests."""

    def __init__(self):
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)
        import time as _time
        stub = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/health":
                    body = json.dumps(
                        {"status": "ok", "queue_depth": 1, "active": 1,
                         "free_pages": stub.free_pages,
                         "now_wall": _time.time()}).encode()
                    ctype = "application/json"
                else:
                    body = _gauge_text(
                        queue_depth=1,
                        kv_pages_free=stub.free_pages).encode()
                    ctype = "text/plain"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.free_pages = 40
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.rid = f"127.0.0.1:{self.httpd.server_port}"


def test_pool_probe_appends_series_and_tracks_scrape_fails():
    from butterfly_tpu.router.pool import ReplicaPool
    stub = _StubReplica()
    seen = []
    pool = ReplicaPool([stub.rid], scrape_metrics=True,
                       probe_timeout=5.0)
    pool.on_series_sample = lambda rid, tail, missed: seen.append(
        (rid, len(tail), missed))
    r = pool.replicas[stub.rid]
    pool.probe_one(r)
    stub.free_pages = 38
    pool.probe_one(r)
    ring = pool.series_by_replica()[stub.rid]
    assert [s["signals"]["kv_pages_free"] for s in ring] == [40.0, 38.0]
    assert all("t_wall" in s for s in ring)
    assert r.scrape_fails == 0 and pool.stale_scrapes(1) == []
    # observer called outside the lock with the tail + failure count
    assert seen == [(stub.rid, 1, 0), (stub.rid, 2, 0)]
    # kill the replica: probes fail, the stale counter climbs, the
    # last-good series survives for the merge
    stub.httpd.shutdown()
    stub.httpd.server_close()
    for _ in range(3):
        pool.probe_one(r)
    assert r.scrape_fails >= 3
    assert pool.stale_scrapes(3) == [stub.rid]
    assert len(pool.series_by_replica()[stub.rid]) == 2
    assert seen[-1][2] >= 3


def _control_state(backends):
    from butterfly_tpu.fleet.controlplane import ControlPlaneState
    from butterfly_tpu.router.policy import PrefixAffinityPolicy
    from butterfly_tpu.router.pool import ReplicaPool
    pool = ReplicaPool(backends, scrape_metrics=True, probe_timeout=0.5)
    return ControlPlaneState(pool, PrefixAffinityPolicy(pool))


def test_fleet_metrics_text_drops_stale_gauges():
    state = _control_state(["127.0.0.1:1", "127.0.0.1:2"])
    for rid in state.pool.replicas:
        state.pool.replicas[rid].metrics_families = parse_exposition(
            _gauge_text(queue_depth=3, kv_pages_free=40))
    state.pool.replicas["127.0.0.1:2"].scrape_fails = \
        state.SCRAPE_STALE_AFTER
    text = state.fleet_metrics_text()
    # the fresh replica's gauges re-export; the stale one's are dropped
    assert ('butterfly_fleet_replica_queue_depth{replica="127.0.0.1:1"}'
            in text)
    assert 'replica="127.0.0.1:2"' not in text
    # counter sums still include BOTH replicas' last good scrape
    fams = parse_exposition(text)
    assert fams["butterfly_fleet_reqs_total"]["samples"][
        ("butterfly_fleet_reqs_total", ())] == 10.0


def test_fleet_timeseries_merges_three_sources_on_one_clock():
    state = _control_state(
        ["127.0.0.1:1", "127.0.0.1:2", "127.0.0.1:3"])
    for i, rid in enumerate(sorted(state.pool.replicas)):
        r = state.pool.replicas[rid]
        r.clock_offset = float(i)  # learned probe offsets
        for k in range(3):
            r.series.append({"t_wall": 100.0 + 10 * i + k,
                             "signals": {"kv_pages_free": 40.0 - k}})
    # a control-plane alert event rides along in the merged view
    state.flightrec.note("alert", rule="replica_flatline",
                         signal="scrape", source="127.0.0.1:3",
                         severity="page", value=3.0, series=[])
    d = state.fleet_timeseries()
    assert d["schema"] == FLEET_TIMESERIES_SCHEMA
    scrape_srcs = [s for s in d["sources"] if s.startswith("scrape:")]
    assert len(scrape_srcs) == 3  # >= 3 sources merged
    assert all(d["sources"][s]["samples"] == 3 for s in scrape_srcs)
    # unreachable replicas degrade to an error entry, never a 500
    assert all(d["sources"][rid].get("missing")
               for rid in state.pool.replicas)
    # one clock: scrape rings merge at offset zero, ordered by t_fleet
    ts = [s["t_fleet"] for s in d["samples"]]
    assert ts == sorted(ts) and len(ts) == 9
    assert all(s["t_fleet"] == s["t_wall"] for s in d["samples"])
    assert [a["rule"] for a in d["alerts"]] == ["replica_flatline"]
    json.dumps(d)  # the endpoint body must be JSON-clean


def test_control_plane_flatline_rules_per_replica():
    state = _control_state(["127.0.0.1:1", "127.0.0.1:2"])
    # three consecutive missed scrapes: the per-replica rule pages once
    state._on_series_sample("127.0.0.1:1", [], 3)
    state._on_series_sample("127.0.0.1:1", [], 4)  # latched, no repeat
    state._on_series_sample("127.0.0.1:2", [], 3)  # its OWN rule set
    evs = [e for e in state.flightrec.dump()["events"]
           if e["kind"] == "alert"]
    assert [(e["rule"], e["source"]) for e in evs] == \
        [("replica_flatline", "127.0.0.1:1"),
         ("replica_flatline", "127.0.0.1:2")]


# ---------------------------------------------------------------------------
# dashboard + CLI smoke
# ---------------------------------------------------------------------------

def _replica_dump_file(tmp_path):
    rec = SignalRecorder(
        interval_s=1e-9,
        rules=[AlertRule("busy", "queue_depth", 2,
                         "sustained_above", 0.5)])
    for i in range(12):
        rec.sample({"queue_depth": float(i % 5),
                    "kv_pages_free": 40.0 - i}, t_wall=100.0 + i)
    path = tmp_path / "ts.json"
    path.write_text(json.dumps(rec.dump()))
    return path


def _fleet_dump_file(tmp_path):
    samples = [{"seq": i, "t_wall": 100.0 + i, "t_fleet": 100.0 + i,
                "source": src, "signals": {"kv_pages_free": 40.0 - i}}
               for src in ("scrape:a:1", "a:1", "scrape:b:2")
               for i in range(6)]
    dump = {"schema": FLEET_TIMESERIES_SCHEMA,
            "sources": {"scrape:a:1": {"samples": 6}},
            "samples": samples,
            "alerts": [{"rule": "pages_free_slope",
                        "signal": "kv_pages_free", "severity": "warn",
                        "source": "a:1", "value": -1.5, "window": 8,
                        "t_fleet": 103.0}]}
    path = tmp_path / "fleet.json"
    path.write_text(json.dumps(dump))
    return path


def test_dashboard_subprocess_smoke(tmp_path):
    dash = str(REPO / "tools" / "dashboard.py")
    rep = _replica_dump_file(tmp_path)
    out = subprocess.run([sys.executable, dash, str(rep)],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "<svg" in out.stdout and "kv_pages_free" in out.stdout
    assert "replica timeseries" in out.stdout
    assert "alerts" in out.stdout  # the busy rule fired in the window

    txt = subprocess.run([sys.executable, dash, str(rep), "--text"],
                         capture_output=True, text=True, timeout=60)
    assert txt.returncode == 0, txt.stderr
    assert "kv_pages_free" in txt.stdout and "[warn]" in txt.stdout
    assert "window covered" in txt.stdout  # reconciliation footer

    fleet = _fleet_dump_file(tmp_path)
    fout = subprocess.run(
        [sys.executable, dash, str(fleet), "--out",
         str(tmp_path / "fleet.html")],
        capture_output=True, text=True, timeout=60)
    assert fout.returncode == 0, fout.stderr
    html = (tmp_path / "fleet.html").read_text()
    # per-source small multiples + alert annotations
    assert "scrape:a:1" in html and "scrape:b:2" in html
    assert "pages_free_slope" in html and 'class="alert"' in html

    bad = subprocess.run([sys.executable, dash,
                          str(tmp_path / "nope.json")],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 2 and "error:" in bad.stderr


def test_dashboard_scale_annotations_and_tier_panel(tmp_path):
    """--flightrecorder overlays kind=scale events as markers + a
    listing, and kv_tier_* signals render as their own panel with the
    hit rate on top (ISSUE 17)."""
    dash = str(REPO / "tools" / "dashboard.py")
    rec = SignalRecorder(interval_s=1e-9)
    for i in range(10):
        rec.sample({"queue_depth": float(i),
                    "kv_tier_hit_rate": 0.1 * i,
                    "kv_tier_pages_saved_total": float(2 * i)},
                   t_wall=100.0 + i)
    ts = tmp_path / "ts.json"
    ts.write_text(json.dumps(rec.dump()))
    fr = tmp_path / "fr.json"
    fr.write_text(json.dumps({"enabled": True, "events": [
        {"seq": 1, "t_wall": 103.0, "kind": "scale", "tier": "decode",
         "direction": "up", "reason": "signal_high",
         "n_before": 1, "n_after": 2},
        {"seq": 2, "t_wall": 104.0, "kind": "tick"},  # not a scale
        {"seq": 3, "t_wall": 108.0, "kind": "scale", "tier": "decode",
         "direction": "down", "reason": "signal_low",
         "n_before": 2, "n_after": 1},
    ]}))

    out = subprocess.run(
        [sys.executable, dash, str(ts), "--flightrecorder", str(fr)],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert out.stdout.count('class="scale"') >= 2  # both in-window marks
    assert "2 scale event(s)" in out.stdout
    assert "decode up (signal_high) 1 -&gt; 2" in out.stdout
    # the tier panel exists and leads with the hit rate
    assert "<h3 class='panel'>kv tier</h3>" in out.stdout
    assert (out.stdout.index("kv_tier_hit_rate")
            < out.stdout.index("kv_tier_pages_saved_total"))

    txt = subprocess.run(
        [sys.executable, dash, str(ts), "--flightrecorder", str(fr),
         "--text"],
        capture_output=True, text=True, timeout=60)
    assert txt.returncode == 0, txt.stderr
    assert "scale events:" in txt.stdout
    assert "+3.0s decode up (signal_high) 1 -> 2" in txt.stdout
    assert "+8.0s decode down (signal_low) 2 -> 1" in txt.stdout
    assert "-- kv tier --" in txt.stdout

    bad = subprocess.run(
        [sys.executable, dash, str(ts), "--flightrecorder",
         str(tmp_path / "nope.json")],
        capture_output=True, text=True, timeout=60)
    assert bad.returncode == 2 and "error:" in bad.stderr


def test_butterfly_dash_cli(tmp_path, capsys):
    from butterfly_tpu.serve.cli import main
    rep = _replica_dump_file(tmp_path)
    assert main(["dash", str(rep), "--text"]) == 0
    out = capsys.readouterr().out
    assert "kv_pages_free" in out and "timeseries" in out
    html_path = tmp_path / "d.html"
    assert main(["dash", str(rep), "--out", str(html_path)]) == 0
    assert "<svg" in html_path.read_text()


def test_tick_report_follow_polls_since(tmp_path, capsys):
    """--follow against a stub /debug/ticks?since= server: renders
    each tick once, advances the cursor, stops at --max-polls."""
    import importlib.util
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    ticks = [{"seq": i, "wall_s": 0.01,
              "phases": {"dispatch": 0.004, "drain": 0.002},
              "fetch_s": 0.001, "batch": 2, "waiting": 0,
              "inflight": 1, "pages_free": 9, "generated": 2,
              "barrier_causes": []} for i in range(5)]
    cursors = []

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            since = int(self.path.rpartition("=")[2])
            cursors.append(since)
            body = json.dumps(
                {"enabled": True, "next_seq": 5,
                 "ticks": [t for t in ticks
                           if t["seq"] >= since]}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        spec = importlib.util.spec_from_file_location(
            "tick_report", REPO / "tools" / "tick_report.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rc = mod.main([f"http://127.0.0.1:{httpd.server_port}",
                       "--follow", "--interval", "0.01",
                       "--max-polls", "3"])
    finally:
        httpd.shutdown()
    assert rc == 0
    out = capsys.readouterr().out
    # all 5 ticks rendered exactly once, then the cursor caught up
    assert out.count("tick ") == 5
    assert "dom=dispatch" in out
    assert cursors == [0, 5, 5]


# ---------------------------------------------------------------------------
# bench JSON series summaries ride along
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_mixed_benchmark_carries_series_summary():
    import jax
    from butterfly_tpu.core.config import tiny
    from butterfly_tpu.models.common import Model
    from butterfly_tpu.obs.benchmark import run_mixed_benchmark
    cfg = tiny("llama", dtype="float32", param_dtype="float32")
    model = Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    out = run_mixed_benchmark(model, params, n_requests=6,
                              prompt_lo=8, prompt_hi=32,
                              max_new_lo=4, max_new_hi=8,
                              page_size=4, max_seconds=60.0)
    summ = out["mixed_series_summary"]
    assert "kv_pages_free" in summ
    assert {"peak", "mean", "slope", "n"} <= set(summ["kv_pages_free"])
