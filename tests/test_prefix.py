"""Prefix caching (cache/prefix.py): content-hash KV page reuse.

Parity contract: with prefix_caching on, every request's tokens must be
IDENTICAL to the uncached scheduler's — sharing pages changes where K/V
bytes live, never what attention reads. Allocator-level tests drive the
refcount/eviction machinery directly and check the full-accounting
invariant after every mutation.
"""
import numpy as np
import pytest

from butterfly_tpu.cache.prefix import (
    PrefixCachingAllocator, chain_block_hashes)
from butterfly_tpu.core.config import RuntimeConfig, tiny
from butterfly_tpu.engine.serving import ServingEngine
from butterfly_tpu.models.common import Model
from butterfly_tpu.sched.scheduler import Scheduler


# ---------------------------------------------------------------------------
# allocator unit tests (pure host)
# ---------------------------------------------------------------------------

PS = 4  # page size for allocator tests


def toks(*vals):
    return list(vals)


def test_chain_hash_edge_cases():
    """The shapes the cross-replica transfer path feeds the hasher:
    empty prompt, sub-page prompt, partial trailing page — only FULL
    pages ever get a digest (a partial page is never registered, never
    exported, never imported)."""
    assert chain_block_hashes([], PS) == []
    assert chain_block_hashes(list(range(PS - 1)), PS) == []
    # partial trailing page contributes nothing; the full-page digests
    # are unchanged by whatever follows them
    full = chain_block_hashes(list(range(2 * PS)), PS)
    ragged = chain_block_hashes(list(range(2 * PS + 3)), PS)
    assert len(full) == 2 and ragged == full
    # max_pages truncates, never alters, the chain
    assert chain_block_hashes(list(range(3 * PS)), PS, max_pages=2) == full


def test_chain_hash_stability_and_prefix_commitment():
    """Digest i commits to ALL tokens of blocks 0..i: equal digests
    imply equal prefixes, an early divergence changes every later
    digest, and deterministic across calls (the property that lets two
    replicas address each other's pages without comparing tokens)."""
    seq = list(range(4 * PS))
    a = chain_block_hashes(seq, PS)
    assert a == chain_block_hashes(list(seq), PS)  # deterministic
    # chain, not per-block: IDENTICAL blocks at different depths get
    # different digests (position in the chain is part of the key)
    rep = chain_block_hashes([7] * (4 * PS), PS)
    assert len(set(rep)) == len(rep)
    # divergence in block 0 changes EVERY digest downstream
    b = chain_block_hashes([99] + seq[1:], PS)
    assert all(x != y for x, y in zip(a, b))
    # divergence in the last block leaves the shared head intact
    c = chain_block_hashes(seq[:-1] + [99], PS)
    assert c[:-1] == a[:-1] and c[-1] != a[-1]


def test_chain_hash_page_size_is_part_of_the_key():
    """The same tokens at different page sizes must NOT collide: a
    page_size-4 digest can never alias a page_size-8 page in an
    importer's registry (the /kv/import geometry check refuses the
    payload first, but the keys must differ regardless)."""
    seq = list(range(16))
    h4 = chain_block_hashes(seq, 4)
    h8 = chain_block_hashes(seq, 8)
    assert len(h4) == 4 and len(h8) == 2
    assert not set(h4) & set(h8)
    # token-boundary ambiguity: [1, 23] vs [12, 3] style joins must
    # hash differently (the digest separates tokens, not just bytes)
    assert chain_block_hashes([1, 23, 0, 0], 4) \
        != chain_block_hashes([12, 3, 0, 0], 4)


def test_admit_miss_then_hit():
    a = PrefixCachingAllocator(num_pages=16, page_size=PS, max_pages_per_seq=8)
    seq = list(range(10))  # 2 full pages + 2 tokens
    assert a.admit(0, seq, len(seq) + 1) == 0
    a.register(0, seq)
    a.release(0)
    a.check_invariants()
    # identical prompt: both full pages hit; tail tokens still prefill
    assert a.admit(1, seq, len(seq) + 1) == 2 * PS
    a.check_invariants()
    # diverging second page: only the first page hits
    seq2 = seq[:PS] + [99] * 6
    assert a.admit(2, seq2, len(seq2) + 1) == PS
    a.check_invariants()


def test_match_capped_below_full_prompt():
    """A fully-cached prompt must still leave >=1 token to prefill."""
    a = PrefixCachingAllocator(num_pages=16, page_size=PS, max_pages_per_seq=8)
    seq = list(range(8))  # exactly 2 pages
    a.admit(0, seq, len(seq) + 1)
    a.register(0, seq)
    a.release(0)
    # (len-1)//PS = 1: only the first page may hit
    assert a.admit(1, seq, len(seq) + 1) == PS


def test_shared_page_refcount_and_release():
    a = PrefixCachingAllocator(num_pages=8, page_size=PS, max_pages_per_seq=8)
    seq = list(range(9))
    a.admit(0, seq, len(seq) + 1)
    a.register(0, seq)
    assert a.admit(1, seq, len(seq) + 1) == 2 * PS
    a.check_invariants()
    shared = set(a.pages_of(0)[:2])
    assert shared == set(a.pages_of(1)[:2])
    # releasing one holder must NOT free the shared pages
    free_before = len(a._free)
    a.release(0)
    a.check_invariants()
    assert shared & set(a.pages_of(1)) == shared
    # slot 0's private page went back to the free list; shared ones didn't
    assert len(a._free) == free_before + 1
    a.release(1)
    a.check_invariants()
    # now refcount 0: warm (evictable), still not on the raw free list
    assert all(p in a._evictable for p in shared)


def test_eviction_lru_under_pressure():
    a = PrefixCachingAllocator(num_pages=4, page_size=PS, max_pages_per_seq=4)
    for i, base in enumerate((0, 100)):
        seq = [base + t for t in range(PS + 1)]
        assert a.admit(i, seq, len(seq) + 1) == 0
        a.register(i, seq)
        a.release(i)
        a.check_invariants()
    # 2 registered pages warm; a 3-page request must evict the OLDEST
    seq = [200 + t for t in range(2 * PS + 1)]
    assert a.admit(5, seq, len(seq) + 1) == 0
    a.check_invariants()
    a.release(5)
    a.check_invariants()
    # prompt 100.. survived longer than prompt 0..
    assert a.admit(6, [100 + t for t in range(PS + 1)], PS + 2) == PS


def test_admit_rolls_back_when_pool_too_small():
    a = PrefixCachingAllocator(num_pages=4, page_size=PS, max_pages_per_seq=8)
    seq = list(range(PS + 1))
    a.admit(0, seq, len(seq) + 1)
    a.register(0, seq)
    a.release(0)
    # matched 1 warm page, but 5 more pages can never materialize
    assert a.admit(1, seq + list(range(50, 64)), 20) is None
    a.check_invariants()
    # the rollback left the matched page warm and admissible
    assert a.admit(2, seq, len(seq) + 1) == PS


def test_matched_page_in_evictable_not_double_counted():
    """A matched warm page must count as held, not as free headroom."""
    a = PrefixCachingAllocator(num_pages=2, page_size=PS, max_pages_per_seq=4)
    seq = list(range(PS + 1))
    a.admit(0, seq, len(seq) + 1)
    a.register(0, seq)
    a.release(0)  # 1 free + 1 evictable
    got = a.admit(1, seq, len(seq) + 1)  # needs matched + 1 fresh
    assert got == PS
    a.check_invariants()
    assert len(set(a.pages_of(1))) == 2


def test_register_duplicate_content_keeps_one_entry():
    a = PrefixCachingAllocator(num_pages=8, page_size=PS, max_pages_per_seq=8)
    seq = list(range(PS + 1))
    a.admit(0, seq, len(seq) + 1)
    a.admit(1, seq, len(seq) + 1)  # same prompt admitted concurrently
    a.register(0, seq)
    a.register(1, seq)  # duplicate content: second copy stays private
    a.check_invariants()
    a.release(0)
    a.release(1)
    a.check_invariants()
    assert a.admit(2, seq, len(seq) + 1) == PS


def test_grow_evicts_warm_pages():
    a = PrefixCachingAllocator(num_pages=3, page_size=PS, max_pages_per_seq=4)
    seq = list(range(PS + 1))
    a.admit(0, seq, len(seq) + 1)
    a.register(0, seq)
    a.release(0)  # 1 evictable + 1 free
    a.admit(1, [7] * 3, 4)
    assert a.free_pages == 2
    fresh = a.grow(1, 3 * PS)  # needs 2 more: one comes from eviction
    assert fresh is not None and len(fresh) == 2
    a.check_invariants()


def test_import_page_mid_chain_memory_error_recovery():
    """A KV import that exhausts pages mid-chain (import_page raises
    MemoryError) must leave a usable LEADING run: the landed pages stay
    registered + evictable, invariants hold, and the next admission of
    the chain attaches exactly the landed prefix. This is the no_space
    leg of fleet/kvtransfer.import_payload, driven at allocator level."""
    a = PrefixCachingAllocator(num_pages=3, page_size=PS,
                               max_pages_per_seq=8)
    # a LIVE slot owns the chain's leading 2 pages (registered, ref>0)
    # plus one private page: the whole pool is held, nothing evictable
    seq = list(range(2 * PS + 1))
    a.admit(0, seq, len(seq))
    a.register(0, seq)
    a.check_invariants()
    chain = chain_block_hashes(list(range(3 * PS)), PS)
    # the peer's import walks the chain: the live-shared head skips
    # idempotently (None), then the tail exhausts mid-chain
    assert a.import_page(chain[0]) is None
    assert a.import_page(chain[1]) is None
    with pytest.raises(MemoryError):
        a.import_page(chain[2])
    a.check_invariants()
    # what landed (the live head) is still a usable leading run
    assert a.lookup(chain[0]) is not None
    assert a.lookup(chain[1]) is not None
    assert a.lookup(chain[2]) is None
    # the holder releasing unblocks the tail; the re-import completes
    a.release(0)
    a.check_invariants()
    assert a.import_page(chain[2]) is not None
    a.check_invariants()
    # admission attaches the whole chain as a prefix hit (need_len
    # capped at the pool: 3 matched pages, zero fresh)
    got = a.admit(1, list(range(3 * PS)) + [99], 3 * PS)
    assert got == 3 * PS
    a.check_invariants()


def test_import_into_tight_pool_recycles_earlier_imports():
    """An import chain longer than the free headroom never raises while
    its OWN earlier pages are the only evictable ones — it recycles
    them (newest import wins, the leading run is sacrificed). Documents
    the churn shape import_payload tolerates: correctness never depends
    on a transfer landing, and invariants hold throughout."""
    a = PrefixCachingAllocator(num_pages=3, page_size=PS,
                               max_pages_per_seq=8)
    a.admit(0, [9] * (2 * PS), 2 * PS)  # 2 of 3 pages live
    chain = chain_block_hashes(list(range(3 * PS)), PS)
    first = a.import_page(chain[0])
    assert first is not None
    # second import: the only evictable page is chain[0]'s — recycled
    assert a.import_page(chain[1]) == first
    a.check_invariants()
    assert a.lookup(chain[0]) is None
    assert a.lookup(chain[1]) == first
    # the surviving non-leading page is unusable by admit (walks from
    # block 0) but harmless; it recycles like any warm page
    assert a.admit(1, list(range(PS + 1)), PS) == 0
    a.check_invariants()


def test_pin_unpin_refcount_vs_evictable_invariant():
    """The cache/prefix.py audit contract: (refcount == 0) iff the page
    sits in the evictable list. Transfer pins are transient holders —
    a pinned warm page must leave the evictable list (and stop being
    eviction fodder), and an unpin must return it warm. The
    check_invariants audit only balances once pins are released, which
    is exactly the export path's pin/read/unpin-in-finally shape."""
    a = PrefixCachingAllocator(num_pages=3, page_size=PS,
                               max_pages_per_seq=8)
    seq = list(range(2 * PS + 1))
    a.admit(0, seq, len(seq))
    a.register(0, seq)
    a.release(0)
    a.check_invariants()
    pids = [a.lookup(h) for h in chain_block_hashes(seq, PS)]
    assert all(p is not None for p in pids)
    assert all(a._ref[p] == 0 and p in a._evictable for p in pids)
    a.pin(pids)
    # pinned: held, not evictable — and not free headroom either
    assert all(a._ref[p] == 1 and p not in a._evictable for p in pids)
    assert a.free_pages == 1
    # an eviction-forcing admission cannot recycle a pinned page:
    # 2 wanted > 1 free -> refused, nothing allocated
    assert a.admit(1, [7] * (2 * PS), 2 * PS) is None
    # while pinned, the audit must trip: a nonzero refcount with no
    # slot holding the page is exactly what the assert exists to catch
    with pytest.raises(AssertionError):
        a.check_invariants()
    a.unpin(pids)
    # balance restored: warm, evictable, audit passes
    assert all(a._ref[p] == 0 and p in a._evictable for p in pids)
    a.check_invariants()
    assert a.admit(1, [7] * (2 * PS), 2 * PS) == 0  # now they recycle
    a.check_invariants()


def test_fuzz_invariants_random_workload():
    rng = np.random.RandomState(0)
    a = PrefixCachingAllocator(num_pages=24, page_size=PS,
                               max_pages_per_seq=12)
    live = {}
    prompts = [list(rng.randint(0, 5, rng.randint(1, 30))) for _ in range(12)]
    for step in range(400):
        op = rng.randint(3)
        if op == 0 and len(live) < 6:
            slot = next(s for s in range(6) if s not in live)
            seq = prompts[rng.randint(len(prompts))]
            if a.admit(slot, seq, len(seq) + 1) is not None:
                live[slot] = list(seq)
        elif op == 1 and live:
            slot = list(live)[rng.randint(len(live))]
            seq = live[slot]
            if a.can_grow(slot, len(seq) + 2):
                if a.grow(slot, len(seq) + 2) is not None:
                    seq.append(int(rng.randint(5)))
        elif op == 2 and live:
            slot = list(live)[rng.randint(len(live))]
            a.register(slot, live[slot])
            a.release(slot)
            del live[slot]
        a.check_invariants()


# ---------------------------------------------------------------------------
# scheduler integration (8 fake CPU devices via conftest)
# ---------------------------------------------------------------------------

def make_sched(prefix_caching: bool, **rt_kw):
    cfg = tiny("llama", dtype="float32", param_dtype="float32")
    model = Model(cfg)
    import jax
    params = model.init(jax.random.PRNGKey(0))
    rt = RuntimeConfig(max_batch_size=4, max_seq_len=128, page_size=8,
                       prefix_caching=prefix_caching, **rt_kw)
    return Scheduler(ServingEngine(model, params, rt, use_kernels=False))


PROMPT = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7, 9, 3, 2, 3, 8, 4]


def run_one(sched, prompt, max_new=6):
    req = sched.submit(prompt, max_new_tokens=max_new)
    sched.run_until_done()
    assert req.state == "finished"
    return req.output


def test_cached_tokens_match_uncached():
    plain = make_sched(False)
    cached = make_sched(True)
    for prompt in (PROMPT, PROMPT, PROMPT[:9] + [7] * 11, [2], PROMPT):
        assert run_one(cached, prompt) == run_one(plain, prompt), prompt


def test_second_request_hits_cache():
    s = make_sched(True)
    run_one(s, PROMPT)
    assert s.alloc.hit_tokens == 0
    run_one(s, PROMPT)
    # 20-token prompt, page 8: (20-1)//8 = 2 full pages hit
    assert s.alloc.hit_tokens == 16
    m = s.metrics()
    assert m["prefix_cache_hit_tokens"] == 16
    assert m["prefix_cache_lookup_tokens"] == 2 * len(PROMPT)


def test_generated_tokens_extend_the_cache():
    """A follow-up prompt = old prompt + old completion (multi-turn chat
    shape) must hit pages covering the generated tokens too."""
    s = make_sched(True)
    out = run_one(s, PROMPT, max_new=12)
    follow = PROMPT + out + [1, 2, 3]
    before = s.alloc.hit_tokens
    run_one(s, follow)
    # everything written last round is reusable: 20+12-1 = 31 tokens
    # -> 3 full pages (24 tokens) hit
    assert s.alloc.hit_tokens - before == 24


def test_concurrent_identical_prompts_share_pages():
    s = make_sched(True)
    done = []
    reqs = [s.submit(PROMPT, max_new_tokens=4,
                     on_finish=lambda r: done.append(r.id)) for _ in range(3)]
    s.run_until_done()
    assert len(done) == 3
    outs = [r.output for r in reqs]
    assert outs[0] == outs[1] == outs[2]
    s.alloc.check_invariants()


def test_chunked_prefill_with_prefix_caching():
    plain = make_sched(False, prefill_chunk=16)
    cached = make_sched(True, prefill_chunk=16)
    long_prompt = (PROMPT * 5)[:90]
    assert run_one(cached, long_prompt) == run_one(plain, long_prompt)
    before = cached.alloc.hit_tokens
    assert run_one(cached, long_prompt) == run_one(plain, long_prompt)
    # (90-1)//8 = 11 full pages
    assert cached.alloc.hit_tokens - before == 88


def test_preempted_request_readmits_via_cache():
    # pool sized so two long-decoding requests collide mid-flight
    s = make_sched(True, num_pages=12)
    a = s.submit(PROMPT, max_new_tokens=30)
    b = s.submit(PROMPT[:8], max_new_tokens=30)
    s.run_until_done()
    assert a.state == b.state == "finished"
    assert len(a.output) == len(b.output) == 30
    s.alloc.check_invariants()
    if s.metrics()["preemptions_total"]:
        # readmission of (prompt + generated) found warm pages
        assert s.alloc.hit_tokens > 0


def test_parity_under_preemption_pressure():
    plain = make_sched(False, num_pages=12)
    cached = make_sched(True, num_pages=12)
    for s in (plain, cached):
        s._reqs = [s.submit(PROMPT, max_new_tokens=30),
                   s.submit(PROMPT[:8], max_new_tokens=30)]
        s.run_until_done()
    for rp, rc in zip(plain._reqs, cached._reqs):
        assert rp.output == rc.output


def test_prefix_caching_on_data_tensor_mesh():
    """VERDICT r4 item 9: shared prefix pages + data/tensor-sharded pools
    and block tables compose — a cache-hitting admission on the meshed
    engine is token-exact with cold admission on the unmeshed one."""
    import jax
    import pytest
    from butterfly_tpu.core.config import MeshConfig
    from butterfly_tpu.core.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 fake devices")
    cfg = tiny("llama", dtype="float32", param_dtype="float32",
               num_heads=8, num_kv_heads=4, head_dim=8)
    model = Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rt = RuntimeConfig(max_batch_size=4, max_seq_len=128, page_size=8,
                       prefix_caching=True)

    plain = Scheduler(ServingEngine(model, params, rt.replace(
        prefix_caching=False)))
    want = [run_one(plain, p) for p in (PROMPT, PROMPT, PROMPT[:9])]

    mesh = make_mesh(MeshConfig(data=2, tensor=4))
    s = Scheduler(ServingEngine(model, params, rt, mesh=mesh))
    got = [run_one(s, p) for p in (PROMPT, PROMPT, PROMPT[:9])]
    assert got == want
    # the repeat admission (and the shorter shared prefix) actually hit
    assert s.alloc.hit_tokens >= 16
    spec = s.engine.cache.k_pages.sharding.spec
    assert spec[2] == "tensor"  # pools really are sharded under the mesh
    assert s.engine.cache.page_table.sharding.spec[0] == "data"
