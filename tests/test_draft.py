"""On-device draft-model speculation (models/draft.py, ISSUE 14).

Three contracts:

* Derivation — the truncated-layer draft is a strict prefix of the
  target's layer stack with the embedding/final-norm/unembedding SHARED
  BY REFERENCE (same device buffers, zero extra HBM), on float and
  quantized trees alike; an independent draft checkpoint must speak the
  target's vocabulary.
* Exactness — greedy draft-model speculation is byte-identical to plain
  decode (drafts only change how many forwards the tokens take, never
  the tokens), across fused-block width, dispatch-ahead depth, the
  write-combined KV window, and int8 pools; and the draft's own KV
  cache obeys draft_len == hist_len - 1 at every barrier (rollback by
  the ACCEPTED count — the mutcheck draft-rollback mutant must die
  here).
* Quality — on mixed_chat-shaped traffic (where prompt lookup earns
  little) the model source's accept rate beats n-gram's, the ROADMAP
  item 3 evidence.
"""
import json

import jax
import numpy as np
import pytest

from butterfly_tpu.core.config import RuntimeConfig, tiny
from butterfly_tpu.engine.serving import ServingEngine
from butterfly_tpu.models.common import Model
from butterfly_tpu.models.draft import (
    ModelDraftSource, derive_draft_params, resolve_draft_layers)
from butterfly_tpu.sched.scheduler import Scheduler

CFG = tiny("llama", dtype="float32", param_dtype="float32")
MODEL = Model(CFG)
PARAMS = MODEL.init(jax.random.PRNGKey(42))


def make_sched(max_batch=2, max_seq=64, page=8, num_pages=0, seed=0,
               **rt_kw):
    rt = RuntimeConfig(max_batch_size=max_batch, max_seq_len=max_seq,
                       page_size=page, num_pages=num_pages, **rt_kw)
    return Scheduler(ServingEngine(MODEL, PARAMS, rt), seed=seed)


# -- derivation -------------------------------------------------------------


def test_derivation_truncates_and_shares_embed():
    """Round-trip: layer leaves sliced to the first n layers, the
    embed/final-norm/unembed leaves are the SAME objects (no copy)."""
    dcfg, dp = derive_draft_params(PARAMS, CFG, 1)
    assert dcfg.num_layers == 1
    assert dcfg.vocab_size == CFG.vocab_size
    # every layer-stacked leaf keeps its shape except the leading L
    ref_leaves = jax.tree.leaves(PARAMS["layers"])
    got_leaves = jax.tree.leaves(dp["layers"])
    for r, g in zip(ref_leaves, got_leaves):
        assert g.shape == (1,) + r.shape[1:]
        np.testing.assert_array_equal(np.asarray(g), np.asarray(r[:1]))
    # shared by reference — the identity is the zero-extra-HBM claim
    assert dp["embed"] is PARAMS["embed"]
    assert dp["final_norm"] is PARAMS["final_norm"]
    assert dp["lm_head"] is PARAMS["lm_head"]


def test_derivation_quantized_tree():
    """Truncation is dtype-agnostic: int8 {w, scale} leaves slice the
    same way (the bench/serving weight trees are quantized)."""
    from butterfly_tpu.quant.int8 import quantize_int8, tree_is_quantized
    qp = quantize_int8(MODEL.init(jax.random.PRNGKey(1)), CFG)
    assert tree_is_quantized(qp)
    dcfg, dp = derive_draft_params(qp, CFG, 1)
    assert dcfg.num_layers == 1
    for leaf in jax.tree.leaves(dp["layers"]):
        assert leaf.shape[0] == 1
    assert dp["embed"] is qp["embed"]


def test_derivation_depth_validation():
    assert resolve_draft_layers(CFG, 0) == 1  # auto: L/4 floored at 1
    with pytest.raises(ValueError):
        resolve_draft_layers(CFG, CFG.num_layers)  # not a truncation
    with pytest.raises(ValueError):
        resolve_draft_layers(CFG, -3)


def test_draft_ckpt_vocab_must_match(tmp_path):
    """An independent draft checkpoint with a foreign vocabulary is
    rejected before any weights load — q(x) over the wrong ids would
    silently bias every accept test."""
    from butterfly_tpu.ckpt.load import load_draft_checkpoint
    (tmp_path / "config.json").write_text(json.dumps({
        "model_type": "llama", "vocab_size": CFG.vocab_size + 7,
        "hidden_size": 32, "num_hidden_layers": 1,
        "num_attention_heads": 2, "intermediate_size": 64,
    }))
    with pytest.raises(ValueError, match="vocab"):
        load_draft_checkpoint(str(tmp_path), CFG)


def test_unknown_draft_source_fails_at_build():
    with pytest.raises(ValueError, match="draft source"):
        make_sched(speculative_gamma=2, draft_model="nope")


def test_legacy_draft_fn_contract_still_registers():
    """The PR 9 register_draft_source contract — a plain jax callable
    (hist, hist_len, gamma, ngram) -> drafts — still plugs in."""
    from butterfly_tpu.engine.serving import (
        DRAFT_SOURCES, _ngram_drafts, register_draft_source)
    register_draft_source("ngram_twin", _ngram_drafts)
    try:
        ref = make_sched(speculative_gamma=3)
        want = ref.submit([5, 7, 11], max_new_tokens=10)
        ref.run_until_done()
        s = make_sched(speculative_gamma=3, draft_model="ngram_twin")
        got = s.submit([5, 7, 11], max_new_tokens=10)
        s.run_until_done()
        assert got.output == want.output
    finally:
        del DRAFT_SOURCES["ngram_twin"]


# -- exactness --------------------------------------------------------------


def _plain_reference(prompts, max_new):
    ref = make_sched(max_batch=4)
    want = [ref.submit(p, max_new_tokens=max_new) for p in prompts]
    ref.run_until_done()
    return [r.output for r in want]


PROMPTS = [[5, 7, 11], [3, 3, 3, 3, 3], [2], list(range(1, 9))]


def test_draft_model_spec_greedy_parity():
    """Fast-tier anchor: one operating point of the grid below."""
    want = _plain_reference(PROMPTS, 12)
    sched = make_sched(max_batch=4, speculative_gamma=3,
                       draft_model="model", draft_layers=1,
                       decode_steps_per_tick=4)
    got = [sched.submit(p, max_new_tokens=12) for p in PROMPTS]
    sched.run_until_done()
    assert [r.output for r in got] == want
    assert sched.metrics()["spec_forwards_total"] > 0


def test_draft_model_spec_parity_grid():
    """Acceptance criterion: greedy draft-model spec is byte-identical
    to plain decode across k 1/8 x inflight 1/2 x kv_write_combine
    on/off (the draft influences only WHICH tokens verify accepts per
    round, never the emitted sequence)."""
    want = _plain_reference(PROMPTS, 12)
    for k in (1, 8):
        for depth in (1, 2):
            for win in (True, False):
                sched = make_sched(max_batch=4, speculative_gamma=3,
                                   draft_model="model", draft_layers=1,
                                   decode_steps_per_tick=k,
                                   inflight_blocks=depth,
                                   kv_write_combine=win)
                got = [sched.submit(p, max_new_tokens=12)
                       for p in PROMPTS]
                sched.run_until_done()
                assert [r.output for r in got] == want, (k, depth, win)


def test_draft_model_spec_int8_parity():
    """int8 pools: the draft cache allocates in the pool representation
    (int8 codes + scales) and greedy parity still holds vs int8 plain
    decode."""
    ref = make_sched(max_batch=2, kv_quant="int8")
    want = [ref.submit(p, max_new_tokens=10) for p in PROMPTS[:2]]
    ref.run_until_done()
    sched = make_sched(max_batch=2, kv_quant="int8", speculative_gamma=3,
                       draft_model="model", draft_layers=1)
    got = [sched.submit(p, max_new_tokens=10) for p in PROMPTS[:2]]
    sched.run_until_done()
    assert [r.output for r in got] == [r.output for r in want]
    assert sched.engine._draft_state.quantized
    assert sched.engine._draft_state.k.dtype == np.int8


def test_draft_model_seeded_sampling_reproducible():
    """temperature > 0 rides the real-q rejection-sampling correction;
    same scheduler seed -> same draws (distribution exactness is pinned
    kernel-level in tests/test_spec_sampling.py)."""
    outs = []
    for _ in range(2):
        sched = make_sched(speculative_gamma=2, draft_model="model",
                           draft_layers=1, seed=7)
        r1 = sched.submit([5, 7], max_new_tokens=8, temperature=0.8)
        r2 = sched.submit([3, 1, 4], max_new_tokens=6)  # greedy slotmate
        sched.run_until_done()
        assert len(r1.output) == 8 and len(r2.output) == 6
        outs.append((r1.output, r2.output))
    assert outs[0] == outs[1]


def test_draft_kv_rollback_exact():
    """The rollback-by-construction contract: at every drain barrier a
    live slot's draft cache length equals hist_len - 1 — every history
    token's K/V except the newest is in the draft cache, rejected
    drafts' K/V sit past the length (unattendable, overwritten in
    place next round). A rollback that advances by the DRAFTED count
    instead (the mutcheck draft-rollback mutant) breaks the invariant
    on the first rejected draft; the random prompt below guarantees
    rejections (asserted via the accept rate)."""
    rng = np.random.RandomState(0)
    sched = make_sched(max_batch=2, speculative_gamma=3,
                       draft_model="model", draft_layers=1)
    req = sched.submit(rng.randint(1, CFG.vocab_size, (12,)).tolist(),
                       max_new_tokens=30)
    for _ in range(200):
        if req.state == "running":
            break
        sched.tick()
    for _ in range(3):
        sched.tick()
    sched._drain_inflight()
    assert req.state == "running"  # still mid-generation
    hl = int(np.asarray(sched._hist_len_dev)[req.slot])
    dl = int(np.asarray(sched.engine._draft_state.length)[req.slot])
    assert dl > 0  # the admission draft-prefill seeded the cache
    assert dl == hl - 1, (dl, hl)
    # the probe only discriminates if rejections actually happened
    m = sched.metrics()
    assert m["spec_accept_rate"] < 1.0
    sched.run_until_done()


def test_draft_prefill_pads_and_drops():
    """ModelDraftSource.prefill: member rows seed exactly their prompt
    length; padding rows (bucketed gang) scatter nowhere — other
    slots' draft state is untouched."""
    from butterfly_tpu.models.draft import derive_draft_params
    dcfg, dp = derive_draft_params(PARAMS, CFG, 1)
    src = ModelDraftSource(dcfg, dp, num_slots=4, width=32)
    state = src.init_state()
    # pre-poison slot 3's length to detect accidental writes
    state = state._replace(length=state.length.at[3].set(9))
    rows = np.zeros((2, 32), np.int32)
    rows[0, :5] = [5, 7, 11, 2, 4]
    rows[1, :3] = [3, 1, 4]
    state = src.prefill(state, np.asarray([0, 2], np.int32), rows,
                        np.asarray([5, 3], np.int32))
    lens = np.asarray(state.length)
    assert lens.tolist() == [5, 0, 3, 9]


# -- quality ----------------------------------------------------------------


def test_model_drafting_beats_ngram_on_mixed_chat():
    """ROADMAP item 3 evidence, test-tier twin of the bench key: on
    mixed_chat-shaped prompts (template + fresh tails — the realistic
    shape where prompt lookup earns little) the real draft model's
    accept rate beats n-gram's."""
    from butterfly_tpu.workload.models import mixed_chat
    wl = mixed_chat(page_size=8, vocab=CFG.vocab_size,
                    prompt_lo=8, prompt_hi=48,
                    max_new_lo=16, max_new_hi=32)
    prompts = [s.tokens for s in wl.sample(8, seed=0)]
    rates = {}
    for name, extra in (("ngram", {}),
                        ("model", dict(draft_model="model",
                                       draft_layers=1))):
        sched = make_sched(max_batch=4, max_seq=48 + 2 * 32 + 16,
                           speculative_gamma=4,
                           decode_steps_per_tick=4, **extra)
        reqs = [sched.submit(p, max_new_tokens=32) for p in prompts]
        sched.run_until_done(max_ticks=10 ** 6)
        assert all(r.state == "finished" for r in reqs)
        rates[name] = sched.metrics()["spec_accept_rate"]
    assert rates["model"] > rates["ngram"], rates
