"""Stage-6 long-context tests: ring attention / Ulysses / SP forward parity.

8 fake CPU devices. Ring and Ulysses must reproduce dense causal attention
exactly (online softmax is algebraically exact, not approximate), and
sp_forward must match the plain forward's logits and KV cache.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from butterfly_tpu.core import compat
from butterfly_tpu.core.config import MeshConfig, tiny
from butterfly_tpu.core.mesh import make_mesh
from butterfly_tpu.models.common import (
    Model, attend, forward, init_cache, make_mask)
from butterfly_tpu.parallel.sequence import (
    ring_attention, sp_forward, ulysses_attention)


def dense_ref(q, k, v):
    """Plain causal attention over the full sequence."""
    B, T = q.shape[0], q.shape[1]
    pos = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    mask = pos[:, None, :] <= pos[:, :, None]
    return attend(q, k, v, mask, None)


def shard_seq(mesh, x, dim=1):
    spec = [None] * x.ndim
    spec[dim] = "seq"
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))


@pytest.mark.parametrize("nq,kv", [(8, 8), (8, 2)])
def test_ring_attention_matches_dense(nq, kv):
    mesh = make_mesh(MeshConfig(seq=8))
    B, T, H = 2, 32, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, T, nq, H))
    k = jax.random.normal(ks[1], (B, T, kv, H))
    v = jax.random.normal(ks[2], (B, T, kv, H))
    ref = dense_ref(q, k, v)

    pos = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    fn = compat.shard_map(
        lambda q, k, v, qp, kp: ring_attention(q, k, v, qp, kp),
        mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq"),
                  P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"), axis_names={"seq"})
    with compat.mesh_ctx(mesh):
        out = jax.jit(fn)(shard_seq(mesh, q), shard_seq(mesh, k),
                          shard_seq(mesh, v), shard_seq(mesh, pos),
                          shard_seq(mesh, pos))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("Kv", [8, 2])
def test_ulysses_matches_dense(Kv):
    """Kv=8: plain head-scatter. Kv=2 on an 8-way axis: VERDICT r2 weak
    item 7 — GQA head-replication fallback (r = N/Kv copies) must still
    match dense exactly."""
    mesh = make_mesh(MeshConfig(seq=8))
    B, T, Nq, H = 2, 32, 8, 16
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (B, T, Nq, H))
    k = jax.random.normal(ks[1], (B, T, Kv, H))
    v = jax.random.normal(ks[2], (B, T, Kv, H))
    ref = dense_ref(q, k, v)

    pos = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    fn = compat.shard_map(
        lambda q, k, v, qp: ulysses_attention(q, k, v, qp),
        mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq"),
                  P(None, "seq")),
        out_specs=P(None, "seq"), axis_names={"seq"})
    with compat.mesh_ctx(mesh):
        out = jax.jit(fn)(shard_seq(mesh, q), shard_seq(mesh, k),
                          shard_seq(mesh, v), shard_seq(mesh, pos))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_invalid_head_config_rejected():
    mesh = make_mesh(MeshConfig(seq=8))
    B, T, H = 1, 32, 16
    q = jnp.zeros((B, T, 8, H))
    k = v = jnp.zeros((B, T, 3, H))  # Kv=3: neither divides nor divides N
    pos = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    fn = compat.shard_map(
        lambda q, k, v, qp: ulysses_attention(q, k, v, qp), mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq"),
                  P(None, "seq")),
        out_specs=P(None, "seq"), axis_names={"seq"})
    # the body's ValueError surfaces through shard_map's tracing wrapped
    # in its own ValueError — assert the type, not the message
    with compat.mesh_ctx(mesh), pytest.raises(ValueError):
        fn(shard_seq(mesh, q), shard_seq(mesh, k), shard_seq(mesh, v),
           shard_seq(mesh, pos))


@pytest.mark.parametrize("impl,arch,moe_impl", [
    ("ring", "llama", None), ("ulysses", "llama", None),
    ("ring", "mixtral", "dense"), ("ring", "mixtral", "ep"),
])
def test_sp_forward_parity(impl, arch, moe_impl):
    """Whole-model SP prefill matches the plain forward (logits + cache).

    The mixtral/ep case checks the EP dispatch inside the seq-manual
    shard_map (no-drop capacity -> exact parity with dense)."""
    kw = {}
    if moe_impl:
        kw = dict(moe_impl=moe_impl, moe_capacity_factor=4.0)  # C=k*T
    cfg = tiny(arch, vocab_size=256, hidden_size=64, num_heads=8,
               num_kv_heads=8, head_dim=8, intermediate_size=128,
               dtype="float32", param_dtype="float32", **kw)
    mesh = make_mesh(MeshConfig(seq=4, data=2))
    params = Model(cfg).init(jax.random.PRNGKey(0))
    B, T = 2, 24
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (B, T)))

    cache = init_cache(cfg, batch=B, max_seq=T)
    ref_logits, ref_cache = jax.jit(lambda p, t, c: forward(p, cfg, t, c))(
        params, tokens, cache)

    with compat.mesh_ctx(mesh):
        logits, sp_cache = jax.jit(
            lambda p, t: sp_forward(p, cfg, t, mesh, impl=impl))(
                params, tokens)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=3e-5, atol=3e-5)
    np.testing.assert_allclose(np.asarray(sp_cache.k),
                               np.asarray(ref_cache.k), rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(sp_cache.length),
                                  np.asarray(ref_cache.length))


def test_sp_forward_seq_tp_compose():
    """seq=2 x tensor=4: SP composes with TP (auto axes inside shard_map)."""
    cfg = tiny("llama", vocab_size=256, hidden_size=64, num_heads=8,
               num_kv_heads=8, head_dim=8, intermediate_size=128,
               dtype="float32", param_dtype="float32")
    mesh = make_mesh(MeshConfig(seq=2, tensor=4))
    params = Model(cfg).init(jax.random.PRNGKey(1))
    from butterfly_tpu.parallel.partition import shard_params
    sparams = shard_params(params, cfg, mesh)
    tokens = jnp.asarray(
        np.random.RandomState(1).randint(0, cfg.vocab_size, (2, 16)))
    cache = init_cache(cfg, batch=2, max_seq=16)
    ref_logits, _ = jax.jit(lambda p, t, c: forward(p, cfg, t, c))(
        params, tokens, cache)
    with compat.mesh_ctx(mesh):
        logits, _ = jax.jit(
            lambda p, t: sp_forward(p, cfg, t, mesh, impl="ring"))(
                sparams, tokens)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=3e-5, atol=3e-5)


def test_sp_forward_validation():
    cfg = tiny("llama", dtype="float32", param_dtype="float32")
    mesh = make_mesh(MeshConfig(seq=4, data=2))
    params = Model(cfg).init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="not divisible"):
        sp_forward(params, cfg, jnp.zeros((2, 10), jnp.int32), mesh)


@pytest.mark.parametrize("arch,kv", [("llama", 8), ("llama", 2), ("gpt2", 8)])
def test_sp_decode_parity(arch, kv):
    """VERDICT r2 item 6: sp_forward prefill -> sp_decode_step greedy
    decode over the still-seq-sharded prefix cache must produce the exact
    tokens (and near-exact logits) of the single-device forward+decode."""
    from butterfly_tpu.parallel.sequence import sp_decode_step
    cfg = tiny(arch, vocab_size=256, hidden_size=64, num_heads=8,
               num_kv_heads=kv, head_dim=8, intermediate_size=128,
               dtype="float32", param_dtype="float32")
    mesh = make_mesh(MeshConfig(seq=4, data=2))
    params = Model(cfg).init(jax.random.PRNGKey(2))
    B, T, N_NEW = 2, 24, 5
    tokens = jnp.asarray(
        np.random.RandomState(2).randint(0, cfg.vocab_size, (B, T)))

    # single-device reference: contiguous cache all the way
    ref_cache = init_cache(cfg, batch=B, max_seq=T + N_NEW)
    step_ref = jax.jit(lambda p, t, c: forward(p, cfg, t, c))
    ref_logits, ref_cache = step_ref(params, tokens, ref_cache)
    ref_toks = []
    nxt = jnp.argmax(ref_logits[:, -1, :], axis=-1)[:, None]
    for _ in range(N_NEW):
        ref_toks.append(np.asarray(nxt)[:, 0])
        ref_logits, ref_cache = step_ref(params, nxt, ref_cache)
        nxt = jnp.argmax(ref_logits[:, -1, :], axis=-1)[:, None]

    # SP: prefill leaves the prefix sharded over seq; decode merges
    # per-device partials + the replicated suffix cache
    with compat.mesh_ctx(mesh):
        logits, prefix = jax.jit(
            lambda p, t: sp_forward(p, cfg, t, mesh, impl="ring"))(
                params, tokens)
        suffix = init_cache(cfg, batch=B, max_seq=N_NEW)
        step = jax.jit(lambda p, t, pos, pre, suf: sp_decode_step(
            p, cfg, t, pos, pre, suf, mesh))
        nxt = jnp.argmax(logits[:, -1, :], axis=-1)[:, None]
        toks = []
        for i in range(N_NEW):
            toks.append(np.asarray(nxt)[:, 0])
            pos = jnp.full((B, 1), T + i, jnp.int32)
            last, suffix = step(params, nxt, pos, prefix, suffix)
            nxt = jnp.argmax(last, axis=-1)[:, None]

    np.testing.assert_array_equal(np.stack(toks), np.stack(ref_toks))
    np.testing.assert_allclose(np.asarray(last),
                               np.asarray(ref_logits[:, -1, :]),
                               rtol=3e-5, atol=3e-5)
    np.testing.assert_array_equal(np.asarray(suffix.length),
                                  np.full((B,), N_NEW))


def test_generate_long_engine_parity():
    """VERDICT r4 item 4 (product surface): engine.generate_long over a
    seq=4 mesh == the unmeshed engine, with a prompt length NOT divisible
    by the seq axis (exercises the divisibility padding + the decode-time
    pad-K/V masking in sp_decode_step)."""
    from butterfly_tpu.engine import InferenceEngine, SamplingParams
    cfg = tiny("llama", dtype="float32", param_dtype="float32",
               num_heads=8, num_kv_heads=8, head_dim=8)
    params = Model(cfg).init(jax.random.PRNGKey(5))
    prompt = list(range(1, 12))  # 11 tokens: pads to 12 on a seq=4 mesh
    sp = SamplingParams(max_new_tokens=6)
    ref = InferenceEngine(Model(cfg), params).generate([prompt], sp)

    mesh = make_mesh(MeshConfig(seq=4, data=2))
    eng = InferenceEngine(Model(cfg), params, mesh=mesh)
    got = eng.generate_long(prompt, sp)
    np.testing.assert_array_equal(got.tokens[0], ref.tokens[0])

    with pytest.raises(ValueError, match="seq axis"):
        InferenceEngine(Model(cfg), params).generate_long(prompt, sp)


def test_generate_long_cli_parity(capsys):
    """The CLI path (`generate --seq-parallel 4`) end to end: same text
    as the unmeshed engine decoding the same byte prompt — for BOTH
    sequence-parallel attention implementations (--seq-impl)."""
    from butterfly_tpu.engine import InferenceEngine, SamplingParams
    from butterfly_tpu.serve.cli import main
    from butterfly_tpu.utils.tokenizer import ByteTokenizer

    rc = main(["generate", "--model", "tiny", "--seq-parallel", "4",
               "--prompt", "hello", "--max-new", "6"])
    assert rc == 0
    cli_text = capsys.readouterr().out.rstrip("\n")

    rc = main(["generate", "--model", "tiny", "--seq-parallel", "4",
               "--seq-impl", "ulysses", "--prompt", "hello",
               "--max-new", "6"])
    assert rc == 0
    uly_text = capsys.readouterr().out.rstrip("\n")
    assert uly_text == cli_text

    cfg = tiny("llama", dtype="float32", param_dtype="float32")
    tok = ByteTokenizer()
    params = Model(cfg).init(jax.random.PRNGKey(0))  # CLI random-init seed
    eng = InferenceEngine(Model(cfg), params)
    ids = tok.encode("hello")
    stop = tok.eos_id if tok.eos_id is not None else -1
    res = eng.generate([ids], SamplingParams(max_new_tokens=6,
                                             stop_token=stop))
    ref_text = tok.decode(res.tokens[0, :int(res.lengths[0])].tolist())
    assert cli_text == ref_text
