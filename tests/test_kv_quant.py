"""int8 KV cache (models/common.py quant paths).

Contract: the int8 cache is a lossy but tightly-bounded compression of
the bf16/f32 cache. Tests pin (a) the quantizer's error bound, (b)
logit closeness prefill+decode vs the float cache, and (c) the engine
end-to-end path (fused generate, CLI knob) with greedy token parity on
a model where quantization noise doesn't flip the argmax.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from butterfly_tpu.core.config import RuntimeConfig, tiny
from butterfly_tpu.engine import InferenceEngine, SamplingParams
from butterfly_tpu.models.common import (
    Model, forward, init_cache, quantize_kv)

CFG = tiny("llama", dtype="float32", param_dtype="float32")


def test_quantize_roundtrip_error_bound():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 7, 2, 64))
    codes, scale = quantize_kv(x)
    assert codes.dtype == jnp.int8 and scale.shape == (4, 7, 2)
    recon = codes.astype(jnp.float32) * scale[..., None]
    # error per element <= scale/2 (round-to-nearest of x/scale)
    assert float(jnp.max(jnp.abs(recon - x) / scale[..., None])) <= 0.5 + 1e-6


def test_quantize_zero_vector_safe():
    codes, scale = quantize_kv(jnp.zeros((2, 3, 8)))
    assert float(jnp.max(jnp.abs(codes))) == 0
    assert float(jnp.min(scale)) == 1.0  # no div-by-zero sentinels


def _logits_path(quant):
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(1))
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, CFG.vocab_size, (2, 12)))
    cache = init_cache(CFG, batch=2, max_seq=32,
                       quant="int8" if quant else "none")
    logits_p, cache = forward(params, CFG, tokens, cache, fresh=True)
    outs = [logits_p[:, -1]]
    cur = jnp.argmax(logits_p[:, -1], -1).astype(jnp.int32)
    for _ in range(6):
        logits_d, cache = forward(params, CFG, cur[:, None], cache)
        outs.append(logits_d[:, -1])
        cur = jnp.argmax(logits_d[:, -1], -1).astype(jnp.int32)
    return jnp.stack(outs)


def test_prefill_decode_logits_close_to_float_cache():
    lf = _logits_path(False)
    lq = _logits_path(True)
    # int8 per-vector quantization: logits track the float path tightly
    assert float(jnp.max(jnp.abs(lf - lq))) < 0.05 * float(jnp.max(jnp.abs(lf)))
    # and greedy argmax never flipped on this model
    assert jnp.array_equal(jnp.argmax(lf, -1), jnp.argmax(lq, -1))


def test_engine_generate_token_parity():
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(2))
    prompts = [[5, 7, 11, 2], [3, 1]]
    sp = SamplingParams(max_new_tokens=10)
    ref = InferenceEngine(model, params).generate(prompts, sp)
    q = InferenceEngine(model, params,
                        RuntimeConfig(kv_quant="int8")).generate(prompts, sp)
    assert np.array_equal(ref.tokens, q.tokens)


def test_engine_generate_unfused_matches_fused():
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(2))
    eng = InferenceEngine(model, params, RuntimeConfig(kv_quant="int8"))
    sp = SamplingParams(max_new_tokens=8)
    a = eng.generate([[5, 7, 11]], sp, fused=True)
    b = eng.generate([[5, 7, 11]], sp, fused=False)
    assert np.array_equal(a.tokens, b.tokens)


def test_int8_windowed_multi_flush_group_parity():
    """Uniform prompts + int8 cache + a window SMALLER than max_new: the
    second and third flush groups must attend K/V the earlier groups
    flushed — pins the uniform-flush write offset (a one-slot-late
    flush survives any single-group test: nothing ever reads it)."""
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(2))
    # sharpen attention: 0.02-std random weights give near-uniform
    # softmax (q.k ~ 0), which hides key-side cache corruption from
    # greedy argmax entirely — scale wq/wk so scores are O(1) and a
    # misplaced key actually changes what each step attends
    attn = params["layers"]["attn"]
    attn["wq"] = attn["wq"] * 8.0
    attn["wk"] = attn["wk"] * 8.0
    sp = SamplingParams(max_new_tokens=32)
    prompts = [[5, 7, 11], [2, 9, 4]]   # equal lengths -> uniform flush
    ref = InferenceEngine(model, params,
                          RuntimeConfig(kv_quant="int8", decode_window=1)
                          ).generate(prompts, sp)
    win = InferenceEngine(model, params,
                          RuntimeConfig(kv_quant="int8", decode_window=4)
                          ).generate(prompts, sp)
    assert np.array_equal(ref.tokens, win.tokens)


def test_float_cache_windowed_decode_token_parity():
    """decode_window > 1 on the FLOAT cache (the knob, not the int8
    default): windowed fused scan == per-step decode, ragged prompts
    (exercises the vmapped ragged flush) and uniform prompts (the
    single aliasable scalar-offset flush)."""
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(2))
    sp = SamplingParams(max_new_tokens=10)
    for prompts in ([[5, 7, 11, 2], [3, 1]],        # ragged flush path
                    [[5, 7, 11], [2, 9, 4]]):       # uniform flush path
        ref = InferenceEngine(model, params).generate(prompts, sp)
        win = InferenceEngine(model, params,
                              RuntimeConfig(decode_window=4)
                              ).generate(prompts, sp)
        assert np.array_equal(ref.tokens, win.tokens)


def test_quant_cache_under_tp_mesh_matches_single_device():
    """int8 cache + TP/DP mesh: shard_cache handles the scale leaves and
    the sharded program matches the unmeshed int8 engine exactly."""
    from butterfly_tpu.core.config import MeshConfig
    from butterfly_tpu.core.mesh import make_mesh
    from butterfly_tpu.parallel.partition import shard_params

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 fake devices")
    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(3))
    sp = SamplingParams(max_new_tokens=8)
    prompts = [[5, 7, 11, 2], [3, 1, 4, 1]]
    rt = RuntimeConfig(kv_quant="int8")
    ref = InferenceEngine(model, params, rt).generate(prompts, sp)

    mesh = make_mesh(MeshConfig(data=2, tensor=4), jax.devices())
    sharded = shard_params(params, CFG, mesh)
    got = InferenceEngine(model, sharded, rt, mesh=mesh).generate(prompts, sp)
    assert np.array_equal(ref.tokens, got.tokens)


def test_kv_quant_pipeline_mesh_token_parity():
    """VERDICT r4 item 6: the contiguous GPipe pipeline threads the int8
    cache's scale leaves (stage-sharded L like the codes) — stage=2
    token parity vs the unmeshed int8 engine, plus the interleaved
    virtual-stage schedule."""
    from butterfly_tpu.core.config import MeshConfig
    from butterfly_tpu.core.mesh import make_mesh
    from butterfly_tpu.parallel.partition import shard_params

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 fake devices")
    cfg = tiny("llama", dtype="float32", param_dtype="float32",
               num_layers=4)
    model = Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rt = RuntimeConfig(kv_quant="int8")
    prompts = [[5, 7, 11, 2], [3, 1, 4, 1]]
    sp = SamplingParams(max_new_tokens=8)
    ref = InferenceEngine(model, params, rt).generate(prompts, sp)

    mesh = make_mesh(MeshConfig(stage=2, data=2), jax.devices()[:4])
    sharded = shard_params(params, cfg, mesh)
    got = InferenceEngine(model, sharded, rt, mesh=mesh,
                          num_microbatches=2).generate(prompts, sp)
    assert np.array_equal(ref.tokens, got.tokens)

    vgot = InferenceEngine(model, shard_params(params, cfg, mesh), rt,
                           mesh=mesh, num_microbatches=2,
                           virtual_stages=2).generate(prompts, sp)
    assert np.array_equal(ref.tokens, vgot.tokens)


def test_cli_kv_quant_flag():
    from butterfly_tpu.serve.cli import main
    assert main(["generate", "--model", "tiny", "--prompt", "hi",
                 "--max-new", "4", "--kv-quant", "int8"]) == 0


# ---------------------------------------------------------------------------
# Paged serving path (VERDICT r3 item 1: int8 KV on the product path)
# ---------------------------------------------------------------------------

_SERVE_RT = RuntimeConfig(max_batch_size=2, max_seq_len=64, page_size=8,
                          kv_quant="int8")


def _run_sched(params, rt, use_kernels=False, mesh=None, max_new=8):
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.sched.scheduler import Scheduler
    model = Model(CFG)
    sched = Scheduler(ServingEngine(model, params, rt, mesh=mesh,
                                    use_kernels=use_kernels))
    reqs = [sched.submit(p, max_new_tokens=max_new)
            for p in [[5, 7, 11, 2], [3, 1]]]
    sched.run_until_done()
    return [r.output for r in reqs]


def test_serving_int8_kv_pool_allocated():
    from butterfly_tpu.engine.serving import ServingEngine
    eng = ServingEngine(Model(CFG), Model(CFG).init(jax.random.PRNGKey(2)),
                        _SERVE_RT, use_kernels=False)
    assert eng.cache.quantized
    assert eng.cache.k_pages.dtype == jnp.int8
    assert eng.cache.k_scale_pages.shape == (
        CFG.num_layers, eng.cache.num_pages,
        CFG.num_kv_heads * _SERVE_RT.page_size)


def test_scheduler_serving_int8_token_parity_with_engine():
    """Greedy serving with the int8 page pool matches the contiguous
    int8 engine token-for-token (tiny model: quantization noise doesn't
    flip the argmax — same contract as the contiguous tests above)."""
    params = Model(CFG).init(jax.random.PRNGKey(2))
    got = _run_sched(params, _SERVE_RT)
    ref = InferenceEngine(Model(CFG), params,
                          RuntimeConfig(kv_quant="int8")).generate(
        [[5, 7, 11, 2], [3, 1]], SamplingParams(max_new_tokens=8))
    want = [ref.tokens[i, :int(ref.lengths[i])].tolist() for i in range(2)]
    assert got == want


def test_scheduler_serving_int8_kernel_path_parity():
    """The quantized Pallas paged-attention path (interpret mode on CPU)
    matches the quantized dense-gather path exactly."""
    params = Model(CFG).init(jax.random.PRNGKey(5))
    a = _run_sched(params, _SERVE_RT, use_kernels=False)
    b = _run_sched(params, _SERVE_RT, use_kernels=True)
    assert a == b


def test_serving_int8_under_mesh_matches_unmeshed():
    """int8 page pool + DP x TP mesh: scale pools shard with the code
    pools and the meshed scheduler matches the unmeshed one exactly."""
    from butterfly_tpu.core.config import MeshConfig
    from butterfly_tpu.core.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 fake devices")
    params = Model(CFG).init(jax.random.PRNGKey(6))
    ref = _run_sched(params, _SERVE_RT, max_new=6)
    mesh = make_mesh(MeshConfig(data=2, tensor=2), jax.devices()[:4])
    got = _run_sched(params, _SERVE_RT, mesh=mesh, max_new=6)
    assert got == ref


def test_serving_int8_under_stage_mesh_matches_unmeshed():
    """int8 page pool through the GPipe paged pipeline (stage=2): the
    scale pools stage-shard their L dim with the code pools."""
    from butterfly_tpu.core.config import MeshConfig
    from butterfly_tpu.core.mesh import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 fake devices")
    params = Model(CFG).init(jax.random.PRNGKey(7))
    ref = _run_sched(params, _SERVE_RT, max_new=6)
    mesh = make_mesh(MeshConfig(stage=2), jax.devices()[:2])
    got = _run_sched(params, _SERVE_RT, mesh=mesh, max_new=6)
    assert got == ref


def test_int8_quantize_on_flush_parity():
    """Write-combined KV window over the int8 pool (ISSUE 12): the
    window stages the pool's EXACT representation (codes + scales via
    the same quantize_kv the per-token write path uses), so greedy
    serving is byte-identical window on/off — and the flushed pool
    bytes themselves match the per-token path's, codes AND scales, on
    every real page (the null overflow page is scratch in both modes).
    """
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.sched.scheduler import Scheduler

    def run(rt):
        params = Model(CFG).init(jax.random.PRNGKey(2))
        sched = Scheduler(ServingEngine(Model(CFG), params, rt))
        reqs = [sched.submit(p, max_new_tokens=8)
                for p in [[5, 7, 11, 2], [3, 1]]]
        sched.run_until_done()
        return [r.output for r in reqs], sched.engine.cache

    on_toks, on_cache = run(_SERVE_RT)
    off_toks, off_cache = run(_SERVE_RT.replace(kv_write_combine=False))
    assert on_toks == off_toks
    null = on_cache.num_pages - 1  # overflow page: dead-write scratch
    for a, b in ((on_cache.k_pages, off_cache.k_pages),
                 (on_cache.v_pages, off_cache.v_pages),
                 (on_cache.k_scale_pages, off_cache.k_scale_pages),
                 (on_cache.v_scale_pages, off_cache.v_scale_pages)):
        np.testing.assert_array_equal(np.asarray(a[:, :null]),
                                      np.asarray(b[:, :null]))
