"""Checkpoint tests: orbax sharded roundtrip, HF import mapping, serving
snapshot/restore (SURVEY.md §5 checkpoint/resume + §2.2 C10)."""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from butterfly_tpu.core.config import MeshConfig, RuntimeConfig, tiny
from butterfly_tpu.core.mesh import make_mesh
from butterfly_tpu.models.common import Model, forward, init_cache


CFG = tiny("llama", vocab_size=256, hidden_size=64, num_heads=8,
           num_kv_heads=8, head_dim=8, intermediate_size=128,
           dtype="float32", param_dtype="float32")


def test_orbax_roundtrip_resharded(tmp_path):
    """Save unsharded, restore onto a tensor=8 mesh: values + layout."""
    from butterfly_tpu.ckpt.sharded import (
        load_config, load_sharded, save_checkpoint)
    from butterfly_tpu.parallel.partition import param_specs

    params = Model(CFG).init(jax.random.PRNGKey(0))
    save_checkpoint(str(tmp_path / "ck"), params, CFG, step=7)

    cfg2, step = load_config(str(tmp_path / "ck"))
    assert step == 7 and cfg2 == CFG

    mesh = make_mesh(MeshConfig(tensor=8))
    restored = load_sharded(str(tmp_path / "ck"), cfg2, mesh)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), params, restored)
    spec = restored["layers"]["mlp"]["w_up"].sharding.spec
    assert spec == param_specs(CFG, mesh)["layers"]["mlp"]["w_up"]


def test_hf_llama_import_golden():
    """Synthetic HF llama state dict -> our pytree -> forward runs, and
    a known weight lands transposed in the right leaf."""
    from butterfly_tpu.models.llama import params_from_hf_state_dict
    rng = np.random.RandomState(0)
    D, Nq, Kv, H, F, V, L = (CFG.hidden_size, CFG.num_heads,
                             CFG.num_kv_heads, CFG.head_dim,
                             CFG.intermediate_size, CFG.vocab_size,
                             CFG.num_layers)
    sd = {"model.embed_tokens.weight": rng.randn(V, D).astype(np.float32),
          "model.norm.weight": np.ones(D, np.float32),
          "lm_head.weight": rng.randn(V, D).astype(np.float32)}
    for l in range(L):
        p = f"model.layers.{l}."
        sd[p + "input_layernorm.weight"] = np.ones(D, np.float32)
        sd[p + "post_attention_layernorm.weight"] = np.ones(D, np.float32)
        sd[p + "self_attn.q_proj.weight"] = rng.randn(Nq * H, D).astype(np.float32)
        sd[p + "self_attn.k_proj.weight"] = rng.randn(Kv * H, D).astype(np.float32)
        sd[p + "self_attn.v_proj.weight"] = rng.randn(Kv * H, D).astype(np.float32)
        sd[p + "self_attn.o_proj.weight"] = rng.randn(D, Nq * H).astype(np.float32)
        sd[p + "mlp.gate_proj.weight"] = rng.randn(F, D).astype(np.float32)
        sd[p + "mlp.up_proj.weight"] = rng.randn(F, D).astype(np.float32)
        sd[p + "mlp.down_proj.weight"] = rng.randn(D, F).astype(np.float32)
    params = params_from_hf_state_dict(sd, CFG)
    np.testing.assert_allclose(
        np.asarray(params["layers"]["mlp"]["w_gate"][1]),
        sd["model.layers.1.mlp.gate_proj.weight"].T)
    np.testing.assert_allclose(
        np.asarray(params["layers"]["attn"]["wq"][0]),
        sd["model.layers.0.self_attn.q_proj.weight"].T.reshape(D, Nq, H))
    cache = init_cache(CFG, batch=1, max_seq=8)
    logits, _ = forward(params, CFG, jnp.asarray([[1, 2, 3]]), cache)
    assert np.isfinite(np.asarray(logits)).all()


def test_hf_mixtral_import_golden():
    from butterfly_tpu.models.mixtral import params_from_hf_state_dict
    cfg = tiny("mixtral", vocab_size=64, hidden_size=16, num_heads=4,
               num_kv_heads=4, head_dim=4, intermediate_size=32,
               num_layers=2, dtype="float32", param_dtype="float32")
    rng = np.random.RandomState(1)
    D, H, F, V, E = 16, 4, 32, 64, cfg.num_experts
    sd = {"model.embed_tokens.weight": rng.randn(V, D).astype(np.float32),
          "model.norm.weight": np.ones(D, np.float32)}
    for l in range(2):
        p = f"model.layers.{l}."
        sd[p + "input_layernorm.weight"] = np.ones(D, np.float32)
        sd[p + "post_attention_layernorm.weight"] = np.ones(D, np.float32)
        for nm, rows in [("q_proj", 16), ("k_proj", 16), ("v_proj", 16),
                         ("o_proj", D)]:
            cols = D if nm != "o_proj" else 16
            sd[p + f"self_attn.{nm}.weight"] = rng.randn(
                rows, cols).astype(np.float32)
        sd[p + "block_sparse_moe.gate.weight"] = rng.randn(E, D).astype(np.float32)
        for e in range(E):
            q = p + f"block_sparse_moe.experts.{e}."
            sd[q + "w1.weight"] = rng.randn(F, D).astype(np.float32)
            sd[q + "w2.weight"] = rng.randn(D, F).astype(np.float32)
            sd[q + "w3.weight"] = rng.randn(F, D).astype(np.float32)
    params = params_from_hf_state_dict(sd, cfg)
    assert params["layers"]["moe"]["w_gate"].shape == (2, E, D, F)
    np.testing.assert_allclose(
        np.asarray(params["layers"]["moe"]["w_down"][0, 2]),
        sd["model.layers.0.block_sparse_moe.experts.2.w2.weight"].T)
    np.testing.assert_allclose(
        np.asarray(params["layers"]["moe"]["router"][1]),
        sd["model.layers.1.block_sparse_moe.gate.weight"].T)
    cache = init_cache(cfg, batch=1, max_seq=8)
    logits, _ = forward(params, cfg, jnp.asarray([[1, 2]]), cache)
    assert np.isfinite(np.asarray(logits)).all()


def test_serving_snapshot_roundtrip(tmp_path):
    from butterfly_tpu.ckpt.sharded import (
        restore_serving_snapshot, save_serving_snapshot)
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.sched.scheduler import Scheduler

    model = Model(CFG)
    params = model.init(jax.random.PRNGKey(42))
    rt = RuntimeConfig(max_batch_size=2, max_seq_len=64, page_size=8)
    sched = Scheduler(ServingEngine(model, params, rt))
    r1 = sched.submit([5, 7, 11], max_new_tokens=8)
    for _ in range(3):
        sched.tick()
    n_done = len(r1.output)
    assert 0 < n_done < 8
    save_serving_snapshot(str(tmp_path / "snap.json"), sched)

    # "crashed" server: fresh scheduler, same weights
    sched2 = Scheduler(ServingEngine(model, params, rt))
    assert restore_serving_snapshot(str(tmp_path / "snap.json"), sched2) == 1
    req = sched2.waiting[0]
    sched2.run_until_done()
    # continuation tokens equal the uninterrupted run's remainder
    from butterfly_tpu.engine import InferenceEngine, SamplingParams
    full = InferenceEngine(model, params).generate(
        [[5, 7, 11]], SamplingParams(max_new_tokens=8)).tokens[0].tolist()
    assert r1.output + req.output == full
