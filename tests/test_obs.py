"""Observability-layer tests: metrics registry exposition format,
trace ring-buffer semantics, and the tools/trace_report.py smoke run.

All jax-free (registry/trace are stdlib-only) so they run in any
environment the suite does, including JAX_PLATFORMS=cpu CI.
"""
import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

from butterfly_tpu.obs.metrics import render_prometheus
from butterfly_tpu.obs.registry import (
    LATENCY_BUCKETS, Counter, Gauge, Histogram, MetricsRegistry,
    parse_exposition, render_parsed, sanitize_name, sum_expositions)
from butterfly_tpu.obs.trace import (
    Tracer, merge_fleet_trace, summarize_timeline)

REPO = Path(__file__).parent.parent


# -- registry ---------------------------------------------------------------

def test_counter_monotonic():
    c = Counter("reqs", "h")
    c.inc()
    c.inc(3)
    assert c.value == 4
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_set_inc_dec():
    g = Gauge("depth")
    g.set(5)
    g.inc(2)
    g.dec()
    assert g.value == 6


def test_histogram_buckets_cumulative_and_consistent():
    h = Histogram("lat", "h", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    cum, s, c = h.snapshot()
    # cumulative per-le counts: <=0.1 ->1, <=1 ->3, <=10 ->4, +Inf ->5
    assert cum == [1, 3, 4, 5]
    assert cum == sorted(cum), "bucket series must be monotonic"
    assert c == 5 and cum[-1] == c, "+Inf bucket must equal _count"
    assert s == pytest.approx(0.05 + 0.5 + 0.5 + 5.0 + 50.0)


def test_histogram_rejects_bad_buckets():
    for bad in ((), (1.0, 1.0), (2.0, 1.0)):
        with pytest.raises(ValueError):
            Histogram("h", buckets=bad)


def test_histogram_render_format():
    h = Histogram("ttft_seconds", "ttft", buckets=(0.5, 2.0))
    h.observe(0.3)
    h.observe(1.0)
    h.observe(99.0)
    lines = h.render("butterfly")
    assert "# HELP butterfly_ttft_seconds ttft" in lines
    assert "# TYPE butterfly_ttft_seconds histogram" in lines
    assert 'butterfly_ttft_seconds_bucket{le="0.5"} 1' in lines
    assert 'butterfly_ttft_seconds_bucket{le="2"} 2' in lines
    assert 'butterfly_ttft_seconds_bucket{le="+Inf"} 3' in lines
    assert "butterfly_ttft_seconds_sum 100.3" in lines
    assert "butterfly_ttft_seconds_count 3" in lines
    # bucket lines come before _sum/_count, bounds in ascending order
    text = "\n".join(lines)
    assert text.index('le="0.5"') < text.index('le="2"') \
        < text.index('le="+Inf"') < text.index("_sum")


def test_registry_get_or_create_idempotent():
    reg = MetricsRegistry()
    a = reg.counter("x_total", "help")
    b = reg.counter("x_total")
    assert a is b
    with pytest.raises(ValueError):
        reg.gauge("x_total")  # same name, different type


def test_name_sanitization():
    assert sanitize_name("a.b-c d") == "a_b_c_d"
    assert sanitize_name("0abc").startswith("_")
    reg = MetricsRegistry()
    c = reg.counter("bad.name-1")
    c.inc()
    out = reg.render()
    assert "butterfly_bad_name_1 1" in out
    # every exposed sample line is a legal prometheus series
    for line in out.splitlines():
        if line.startswith("#") or not line:
            continue
        assert re.match(r"[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? \S+$", line), \
            line


def test_render_prometheus_registry_wins_name_collisions():
    reg = MetricsRegistry()
    reg.counter("requests_total", "from registry").inc(7)
    reg.histogram("ttft_seconds", "ttft", buckets=LATENCY_BUCKETS)
    text = render_prometheus({"requests_total": 3, "queue_depth": 2},
                             registry=reg)
    # the dict copy of the colliding name is suppressed: exactly one
    # requests_total sample line, carrying the registry's value
    samples = [l for l in text.splitlines()
               if l.startswith("butterfly_requests_total ")]
    assert samples == ["butterfly_requests_total 7"]
    assert "butterfly_queue_depth 2" in text
    assert "butterfly_ttft_seconds_bucket" in text


def test_render_prometheus_plain_dict_unchanged():
    text = render_prometheus({"tokens_generated_total": 5})
    assert "# TYPE butterfly_tokens_generated_total counter" in text
    assert "butterfly_tokens_generated_total 5" in text


# -- tracer -----------------------------------------------------------------

def test_tracer_timeline_roundtrip():
    tr = Tracer()
    tr.begin_request(1, request_id="client-abc", prompt_len=3)
    tr.event(1, "admit", slot=0, queue_wait_s=0.01)
    tr.event(1, "first_token", ttft_s=0.02)
    tr.event(1, "finish", state="finished", tokens=4)
    tl = tr.timeline(1)
    assert tl["request_id"] == "client-abc"
    assert tl["done"] is True
    names = [e["name"] for e in tl["events"]]
    assert names == ["submit", "admit", "first_token", "finish"]
    ts = [e["t"] for e in tl["events"]]
    assert ts == sorted(ts)


def test_tracer_bounds_requests_and_events():
    tr = Tracer(max_requests=2, max_events_per_request=3)
    for rid in range(4):
        tr.begin_request(rid)
    assert [t["id"] for t in tr.timelines()] == [2, 3]
    for _ in range(10):
        tr.event(3, "decode")
    assert len(tr.timeline(3)["events"]) == 3
    # events for evicted/unknown requests are dropped, not resurrected
    tr.event(0, "late")
    assert tr.timeline(0) is None


def test_tracer_global_ring():
    tr = Tracer(max_global_events=4)
    for i in range(10):
        tr.event(None, "decode_tick", batch=i)
    evs = tr.global_events()
    assert len(evs) == 4
    assert [e["batch"] for e in evs] == [6, 7, 8, 9]


def test_summarize_timeline_phases():
    tr = Tracer()
    tr.begin_request(7, request_id="r7")
    tr.event(7, "admit", slot=0)
    tr.event(7, "prefill_chunk", start=0, tokens=8)
    tr.event(7, "prefill_done", tokens=8)
    tr.event(7, "first_token", ttft_s=0.1)
    tr.event(7, "finish", state="finished", tokens=5)
    s = summarize_timeline(tr.timeline(7))
    assert s["id"] == 7 and s["request_id"] == "r7"
    assert s["state"] == "finished" and s["tokens"] == 5
    assert s["prefill_chunks"] == 1 and s["preemptions"] == 0
    for k in ("queue_wait_s", "prefill_s", "ttft_s", "decode_s", "total_s"):
        assert s[k] is not None and s[k] >= 0
    # partial timeline: missing phases are None, not fabricated zeros
    tr.begin_request(8)
    s8 = summarize_timeline(tr.timeline(8))
    assert s8["ttft_s"] is None and s8["total_s"] is None
    assert s8["state"] == "live"


def test_tracer_dump_is_json_serializable():
    tr = Tracer()
    tr.begin_request(0, request_id=None)
    tr.event(0, "finish", state="finished", tokens=1)
    tr.event(None, "decode_tick", batch=1)
    blob = json.dumps(tr.dump())
    back = json.loads(blob)
    assert back["requests"][0]["id"] == 0
    assert back["global_events"][0]["name"] == "decode_tick"


# -- exposition parsing + fleet aggregation ---------------------------------

def _registry_with(n, ladder=(0.1, 1.0)):
    reg = MetricsRegistry()
    reg.counter("requests_total", "Requests").inc(n)
    h = reg.histogram("ttft_seconds", "ttft", buckets=ladder)
    h.observe(0.05)
    h.observe(0.5)
    reg.gauge("queue_depth", "q").set(n)
    reg.counter_family("router_requests_total", "by",
                       ("replica",)).labels(f"r{n}").inc(n)
    return reg


def test_parse_exposition_roundtrip():
    fams = parse_exposition(_registry_with(3).render())
    assert fams["butterfly_requests_total"]["type"] == "counter"
    assert fams["butterfly_requests_total"]["samples"][
        ("butterfly_requests_total", ())] == 3.0
    # histogram series fold under the family name
    h = fams["butterfly_ttft_seconds"]
    assert h["type"] == "histogram"
    assert h["samples"][("butterfly_ttft_seconds_count", ())] == 2.0
    assert h["samples"][
        ("butterfly_ttft_seconds_bucket", (("le", "0.1"),))] == 1.0
    # labeled family samples keep their labels
    assert fams["butterfly_router_requests_total"]["samples"][
        ("butterfly_router_requests_total", (("replica", "r3"),))] == 3.0
    # garbage lines are skipped, not fatal
    assert parse_exposition("not a metric line\n# weird\n") == {}


def test_sum_expositions_counters_and_histograms_exact():
    parsed = [parse_exposition(_registry_with(n).render())
              for n in (3, 5)]
    agg = sum_expositions(parsed)
    assert agg["butterfly_requests_total"]["samples"][
        ("butterfly_requests_total", ())] == 8.0
    h = agg["butterfly_ttft_seconds"]["samples"]
    # cumulative bucket sums stay cumulative and +Inf == _count
    assert h[("butterfly_ttft_seconds_bucket", (("le", "0.1"),))] == 2.0
    assert h[("butterfly_ttft_seconds_bucket", (("le", "+Inf"),))] == 4.0
    assert h[("butterfly_ttft_seconds_count", ())] == 4.0
    # gauges never aggregate by summation
    assert "butterfly_queue_depth" not in agg
    # distinct label children survive as distinct series
    fam = agg["butterfly_router_requests_total"]["samples"]
    assert fam[("butterfly_router_requests_total",
                (("replica", "r3"),))] == 3.0
    assert fam[("butterfly_router_requests_total",
                (("replica", "r5"),))] == 5.0


def test_sum_expositions_drops_mismatched_ladders():
    a = parse_exposition(_registry_with(1, ladder=(0.1, 1.0)).render())
    b = parse_exposition(_registry_with(1, ladder=(0.2, 2.0)).render())
    agg = sum_expositions([a, b])
    # a partial bucket sum would render +Inf != _count: drop the family
    assert "butterfly_ttft_seconds" not in agg
    assert agg["butterfly_requests_total"]["samples"][
        ("butterfly_requests_total", ())] == 2.0


def test_render_parsed_renames_namespaced():
    agg = sum_expositions(
        [parse_exposition(_registry_with(2).render())])
    text = "\n".join(render_parsed(
        agg, rename=lambda n: n.replace("butterfly_",
                                        "butterfly_fleet_", 1)))
    assert "butterfly_fleet_requests_total 2" in text
    assert 'butterfly_fleet_ttft_seconds_bucket{le="+Inf"} 2' in text
    assert 'butterfly_fleet_router_requests_total{replica="r2"} 2' in text
    # every sample line is still a legal prometheus series
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        assert re.match(r"[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? \S+$",
                        line), line


# -- fleet trace merging ------------------------------------------------------

def test_tracer_request_id_filter_and_lookup():
    tr = Tracer()
    tr.begin_request(0, request_id="a")
    tr.begin_request(1, request_id="b")
    tr.begin_request(2, request_id="a")  # retry of the same client id
    assert [t["id"] for t in tr.timelines(request_id="a")] == [0, 2]
    assert tr.find_by_request_id("a")["id"] == 2  # newest wins
    assert tr.find_by_request_id("zzz") is None
    dump = tr.dump(request_id="b", n_global=0)
    assert [t["id"] for t in dump["requests"]] == [1]
    assert dump["global_events"] == []


def _fleet_tracers():
    """A synthetic control plane + one replica tracing the same id.
    Leg events are recorded at leg END carrying dur_s, like the real
    FleetHandler — the sleeps make the ends (and therefore the derived
    start_wall ordering) physically real."""
    import time
    cp = Tracer()
    cp.begin_request(0, request_id="rq", path="/generate")
    time.sleep(0.002)
    cp.event(0, "classify", dur_s=0.001, decision="disagg")
    rep = Tracer()
    rep.begin_request(7, request_id="rq")
    rep.event(7, "first_token", ttft_s=0.002)
    rep.event(7, "finish", state="finished", tokens=1)
    time.sleep(0.012)
    cp.event(0, "prefill_leg", dur_s=0.01, replica="a:1", status="ok")
    time.sleep(0.021)
    cp.event(0, "decode_leg", dur_s=0.02, replica="b:1", status="ok")
    cp.event(0, "finish", state="disaggregated", tokens=8, total_s=0.033,
             ttft_s=0.012, slo_ttft_ok=True)
    return cp, rep


def test_merge_fleet_trace_common_clock_and_offset():
    cp, rep = _fleet_tracers()
    control = {"timeline": cp.timeline(0), "t0_wall": cp.t0_wall,
               "t0_monotonic": cp.t0_monotonic}
    merged = merge_fleet_trace("rq", control, {
        "a:1": {"dump": rep.dump(request_id="rq"), "offset_s": 0.25}})
    # every event lands on one clock, time-sorted
    ts = [ev["t_wall"] for ev in merged["merged"]]
    assert ts == sorted(ts)
    assert {ev["source"] for ev in merged["merged"]} == {"control", "a:1"}
    # the replica's events shifted EARLIER by its +250ms clock offset
    zero = merge_fleet_trace("rq", control, {
        "a:1": {"dump": rep.dump(request_id="rq"), "offset_s": 0.0}})
    t_off = [e["t_wall"] for e in merged["merged"]
             if e["source"] == "a:1"]
    t_zero = [e["t_wall"] for e in zero["merged"]
              if e["source"] == "a:1"]
    assert all(abs((z - o) - 0.25) < 1e-9
               for z, o in zip(t_zero, t_off))
    # legs come from the control-plane dur_s spans, waterfall-ordered
    assert [leg["name"] for leg in merged["legs"]] == \
        ["classify", "prefill_leg", "decode_leg"]
    assert merged["legs_total_s"] == pytest.approx(0.031)
    assert merged["total_s"] == pytest.approx(0.033)
    assert merged["slo"]["slo_ttft_ok"] is True
    json.dumps(merged)  # the /fleet/trace body must be JSON-ready


def test_merge_fleet_trace_missing_replica_degrades():
    cp, _ = _fleet_tracers()
    control = {"timeline": cp.timeline(0), "t0_wall": cp.t0_wall,
               "t0_monotonic": cp.t0_monotonic}
    merged = merge_fleet_trace("rq", control, {
        "a:1": {"dump": None, "offset_s": None, "error": "refused"},
        "b:1": {"dump": {"requests": [], "t0_wall": 0.0,
                         "t0_monotonic": 0.0}, "offset_s": 0.0}})
    # control-plane spans survive alone; both replicas marked missing
    assert {ev["source"] for ev in merged["merged"]} == {"control"}
    assert merged["sources"]["a:1"]["missing"] is True
    assert merged["sources"]["a:1"]["error"] == "refused"
    assert merged["sources"]["b:1"]["missing"] is True
    assert len(merged["legs"]) == 3


# -- tools/trace_report.py smoke --------------------------------------------

def _synthetic_dump(path):
    tr = Tracer()
    for rid in range(3):
        tr.begin_request(rid, request_id=f"client-{rid}", prompt_len=8)
        tr.event(rid, "admit", slot=rid % 2, queue_wait_s=0.001)
        tr.event(rid, "prefill_chunk", start=0, tokens=8)
        tr.event(rid, "prefill_done", tokens=8)
        tr.event(rid, "first_token", ttft_s=0.01)
        if rid == 1:
            tr.event(rid, "preempt", slot=1, preemptions=1)
            tr.event(rid, "admit", slot=0, resumed=True)
        tr.event(rid, "finish", state="finished", tokens=4)
    for i in range(5):
        tr.event(None, "decode_tick", batch=2, generated=2)
    tr.dump_json(str(path))
    return path


def test_trace_report_summary_and_timeline(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "trace_report", REPO / "tools" / "trace_report.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    dump = _synthetic_dump(tmp_path / "trace.json")
    rows = mod.summary_rows(mod.load_dump(str(dump)))
    assert len(rows) == 3
    assert rows[1]["preemptions"] == 1
    text = mod.render_summary(mod.load_dump(str(dump)))
    assert "client-0" in text and "3 request(s)" in text
    assert "5 global event(s), 5 decode tick(s)" in text
    tl = mod.render_timeline(mod.load_dump(str(dump)), 1)
    assert "preempt" in tl and "request_id=client-1" in tl
    with pytest.raises(ValueError):
        mod.render_timeline(mod.load_dump(str(dump)), 99)
    # a non-dump JSON file is a loud error, not a silent empty report
    bad = tmp_path / "bad.json"
    bad.write_text("[1,2,3]")
    with pytest.raises(ValueError):
        mod.load_dump(str(bad))


def test_trace_report_cli_smoke(tmp_path):
    """The CLI entrypoint can't rot: run it as a real subprocess on a
    synthetic dump (stdlib-only import path — no jax startup cost)."""
    dump = _synthetic_dump(tmp_path / "trace.json")
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "trace_report.py"),
         str(dump)],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "3 request(s)" in out.stdout
    out2 = subprocess.run(
        [sys.executable, str(REPO / "tools" / "trace_report.py"),
         str(dump), "--json"],
        capture_output=True, text=True, timeout=60)
    assert out2.returncode == 0, out2.stderr
    assert len(json.loads(out2.stdout)) == 3
    # missing file exits 2 with a diagnostic on stderr
    out3 = subprocess.run(
        [sys.executable, str(REPO / "tools" / "trace_report.py"),
         str(tmp_path / "nope.json")],
        capture_output=True, text=True, timeout=60)
    assert out3.returncode == 2 and "error:" in out3.stderr


def test_trace_report_fleet_cli_smoke(tmp_path):
    """--fleet renders a dumped merged trace (the GET /fleet/trace
    body) as a real subprocess — stdlib-only, no jax import — so
    report-rendering regressions fail tier-1."""
    cp, rep = _fleet_tracers()
    merged = merge_fleet_trace(
        "rq", {"timeline": cp.timeline(0), "t0_wall": cp.t0_wall,
               "t0_monotonic": cp.t0_monotonic},
        {"a:1": {"dump": rep.dump(request_id="rq"), "offset_s": 0.0},
         "b:1": {"dump": None, "offset_s": None, "error": "refused"}})
    path = tmp_path / "fleet.json"
    path.write_text(json.dumps(merged))
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "trace_report.py"),
         "--fleet", str(path)],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    for needle in ("request_id=rq", "prefill_leg", "decode_leg",
                   "legs sum", "MISSING", "slo:"):
        assert needle in out.stdout, (needle, out.stdout)
    # a per-request dump is not a fleet dump: loud error, exit 2
    plain = tmp_path / "plain.json"
    _synthetic_dump(plain)
    out2 = subprocess.run(
        [sys.executable, str(REPO / "tools" / "trace_report.py"),
         "--fleet", str(plain)],
        capture_output=True, text=True, timeout=60)
    assert out2.returncode == 2 and "merged" in out2.stderr
