"""Observability-layer tests: metrics registry exposition format,
trace ring-buffer semantics, and the tools/trace_report.py smoke run.

All jax-free (registry/trace are stdlib-only) so they run in any
environment the suite does, including JAX_PLATFORMS=cpu CI.
"""
import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

from butterfly_tpu.obs.metrics import render_prometheus
from butterfly_tpu.obs.registry import (
    LATENCY_BUCKETS, Counter, Gauge, Histogram, MetricsRegistry,
    parse_exposition, render_parsed, sanitize_name, sum_expositions)
from butterfly_tpu.obs.trace import (
    Tracer, merge_fleet_trace, summarize_timeline)

REPO = Path(__file__).parent.parent


# -- registry ---------------------------------------------------------------

def test_counter_monotonic():
    c = Counter("reqs", "h")
    c.inc()
    c.inc(3)
    assert c.value == 4
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_set_inc_dec():
    g = Gauge("depth")
    g.set(5)
    g.inc(2)
    g.dec()
    assert g.value == 6


def test_histogram_buckets_cumulative_and_consistent():
    h = Histogram("lat", "h", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    cum, s, c = h.snapshot()
    # cumulative per-le counts: <=0.1 ->1, <=1 ->3, <=10 ->4, +Inf ->5
    assert cum == [1, 3, 4, 5]
    assert cum == sorted(cum), "bucket series must be monotonic"
    assert c == 5 and cum[-1] == c, "+Inf bucket must equal _count"
    assert s == pytest.approx(0.05 + 0.5 + 0.5 + 5.0 + 50.0)


def test_histogram_rejects_bad_buckets():
    for bad in ((), (1.0, 1.0), (2.0, 1.0)):
        with pytest.raises(ValueError):
            Histogram("h", buckets=bad)


def test_histogram_render_format():
    h = Histogram("ttft_seconds", "ttft", buckets=(0.5, 2.0))
    h.observe(0.3)
    h.observe(1.0)
    h.observe(99.0)
    lines = h.render("butterfly")
    assert "# HELP butterfly_ttft_seconds ttft" in lines
    assert "# TYPE butterfly_ttft_seconds histogram" in lines
    assert 'butterfly_ttft_seconds_bucket{le="0.5"} 1' in lines
    assert 'butterfly_ttft_seconds_bucket{le="2"} 2' in lines
    assert 'butterfly_ttft_seconds_bucket{le="+Inf"} 3' in lines
    assert "butterfly_ttft_seconds_sum 100.3" in lines
    assert "butterfly_ttft_seconds_count 3" in lines
    # bucket lines come before _sum/_count, bounds in ascending order
    text = "\n".join(lines)
    assert text.index('le="0.5"') < text.index('le="2"') \
        < text.index('le="+Inf"') < text.index("_sum")


def test_registry_get_or_create_idempotent():
    reg = MetricsRegistry()
    a = reg.counter("x_total", "help")
    b = reg.counter("x_total")
    assert a is b
    with pytest.raises(ValueError):
        reg.gauge("x_total")  # same name, different type


def test_name_sanitization():
    assert sanitize_name("a.b-c d") == "a_b_c_d"
    assert sanitize_name("0abc").startswith("_")
    reg = MetricsRegistry()
    c = reg.counter("bad.name-1")
    c.inc()
    out = reg.render()
    assert "butterfly_bad_name_1 1" in out
    # every exposed sample line is a legal prometheus series
    for line in out.splitlines():
        if line.startswith("#") or not line:
            continue
        assert re.match(r"[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? \S+$", line), \
            line


def test_render_prometheus_registry_wins_name_collisions():
    reg = MetricsRegistry()
    reg.counter("requests_total", "from registry").inc(7)
    reg.histogram("ttft_seconds", "ttft", buckets=LATENCY_BUCKETS)
    text = render_prometheus({"requests_total": 3, "queue_depth": 2},
                             registry=reg)
    # the dict copy of the colliding name is suppressed: exactly one
    # requests_total sample line, carrying the registry's value
    samples = [l for l in text.splitlines()
               if l.startswith("butterfly_requests_total ")]
    assert samples == ["butterfly_requests_total 7"]
    assert "butterfly_queue_depth 2" in text
    assert "butterfly_ttft_seconds_bucket" in text


def test_render_prometheus_plain_dict_unchanged():
    text = render_prometheus({"tokens_generated_total": 5})
    assert "# TYPE butterfly_tokens_generated_total counter" in text
    assert "butterfly_tokens_generated_total 5" in text


def test_render_prometheus_string_annotation_becomes_comment():
    """String-valued metrics() entries (spec_mixed_fallback_reason) must
    not crash the exposition renderer — they ride as comment lines the
    text-format parsers (including parse_prometheus) ignore."""
    text = render_prometheus({
        "spec_mixed_fallback_total": 1.0,
        "spec_mixed_fallback_reason": "tree speculation has no "
                                      "fused mixed program",
    })
    assert "butterfly_spec_mixed_fallback_total 1" in text
    assert "# butterfly_spec_mixed_fallback_reason: tree speculation" \
        in text
    for line in text.splitlines():
        if not line.startswith("#"):
            float(line.rsplit(None, 1)[1])  # every sample parses


# -- tracer -----------------------------------------------------------------

def test_tracer_timeline_roundtrip():
    tr = Tracer()
    tr.begin_request(1, request_id="client-abc", prompt_len=3)
    tr.event(1, "admit", slot=0, queue_wait_s=0.01)
    tr.event(1, "first_token", ttft_s=0.02)
    tr.event(1, "finish", state="finished", tokens=4)
    tl = tr.timeline(1)
    assert tl["request_id"] == "client-abc"
    assert tl["done"] is True
    names = [e["name"] for e in tl["events"]]
    assert names == ["submit", "admit", "first_token", "finish"]
    ts = [e["t"] for e in tl["events"]]
    assert ts == sorted(ts)


def test_tracer_bounds_requests_and_events():
    tr = Tracer(max_requests=2, max_events_per_request=3)
    for rid in range(4):
        tr.begin_request(rid)
    assert [t["id"] for t in tr.timelines()] == [2, 3]
    for _ in range(10):
        tr.event(3, "decode")
    assert len(tr.timeline(3)["events"]) == 3
    # events for evicted/unknown requests are dropped, not resurrected
    tr.event(0, "late")
    assert tr.timeline(0) is None


def test_tracer_global_ring():
    tr = Tracer(max_global_events=4)
    for i in range(10):
        tr.event(None, "decode_tick", batch=i)
    evs = tr.global_events()
    assert len(evs) == 4
    assert [e["batch"] for e in evs] == [6, 7, 8, 9]


def test_summarize_timeline_phases():
    tr = Tracer()
    tr.begin_request(7, request_id="r7")
    tr.event(7, "admit", slot=0)
    tr.event(7, "prefill_chunk", start=0, tokens=8)
    tr.event(7, "prefill_done", tokens=8)
    tr.event(7, "first_token", ttft_s=0.1)
    tr.event(7, "finish", state="finished", tokens=5)
    s = summarize_timeline(tr.timeline(7))
    assert s["id"] == 7 and s["request_id"] == "r7"
    assert s["state"] == "finished" and s["tokens"] == 5
    assert s["prefill_chunks"] == 1 and s["preemptions"] == 0
    for k in ("queue_wait_s", "prefill_s", "ttft_s", "decode_s", "total_s"):
        assert s[k] is not None and s[k] >= 0
    # partial timeline: missing phases are None, not fabricated zeros
    tr.begin_request(8)
    s8 = summarize_timeline(tr.timeline(8))
    assert s8["ttft_s"] is None and s8["total_s"] is None
    assert s8["state"] == "live"


def test_tracer_dump_is_json_serializable():
    tr = Tracer()
    tr.begin_request(0, request_id=None)
    tr.event(0, "finish", state="finished", tokens=1)
    tr.event(None, "decode_tick", batch=1)
    blob = json.dumps(tr.dump())
    back = json.loads(blob)
    assert back["requests"][0]["id"] == 0
    assert back["global_events"][0]["name"] == "decode_tick"


# -- exposition parsing + fleet aggregation ---------------------------------

def _registry_with(n, ladder=(0.1, 1.0)):
    reg = MetricsRegistry()
    reg.counter("requests_total", "Requests").inc(n)
    h = reg.histogram("ttft_seconds", "ttft", buckets=ladder)
    h.observe(0.05)
    h.observe(0.5)
    reg.gauge("queue_depth", "q").set(n)
    reg.counter_family("router_requests_total", "by",
                       ("replica",)).labels(f"r{n}").inc(n)
    return reg


def test_parse_exposition_roundtrip():
    fams = parse_exposition(_registry_with(3).render())
    assert fams["butterfly_requests_total"]["type"] == "counter"
    assert fams["butterfly_requests_total"]["samples"][
        ("butterfly_requests_total", ())] == 3.0
    # histogram series fold under the family name
    h = fams["butterfly_ttft_seconds"]
    assert h["type"] == "histogram"
    assert h["samples"][("butterfly_ttft_seconds_count", ())] == 2.0
    assert h["samples"][
        ("butterfly_ttft_seconds_bucket", (("le", "0.1"),))] == 1.0
    # labeled family samples keep their labels
    assert fams["butterfly_router_requests_total"]["samples"][
        ("butterfly_router_requests_total", (("replica", "r3"),))] == 3.0
    # garbage lines are skipped, not fatal
    assert parse_exposition("not a metric line\n# weird\n") == {}


def test_sum_expositions_counters_and_histograms_exact():
    parsed = [parse_exposition(_registry_with(n).render())
              for n in (3, 5)]
    agg = sum_expositions(parsed)
    assert agg["butterfly_requests_total"]["samples"][
        ("butterfly_requests_total", ())] == 8.0
    h = agg["butterfly_ttft_seconds"]["samples"]
    # cumulative bucket sums stay cumulative and +Inf == _count
    assert h[("butterfly_ttft_seconds_bucket", (("le", "0.1"),))] == 2.0
    assert h[("butterfly_ttft_seconds_bucket", (("le", "+Inf"),))] == 4.0
    assert h[("butterfly_ttft_seconds_count", ())] == 4.0
    # gauges never aggregate by summation
    assert "butterfly_queue_depth" not in agg
    # distinct label children survive as distinct series
    fam = agg["butterfly_router_requests_total"]["samples"]
    assert fam[("butterfly_router_requests_total",
                (("replica", "r3"),))] == 3.0
    assert fam[("butterfly_router_requests_total",
                (("replica", "r5"),))] == 5.0


def test_sum_expositions_drops_mismatched_ladders():
    a = parse_exposition(_registry_with(1, ladder=(0.1, 1.0)).render())
    b = parse_exposition(_registry_with(1, ladder=(0.2, 2.0)).render())
    agg = sum_expositions([a, b])
    # a partial bucket sum would render +Inf != _count: drop the family
    assert "butterfly_ttft_seconds" not in agg
    assert agg["butterfly_requests_total"]["samples"][
        ("butterfly_requests_total", ())] == 2.0


def test_render_parsed_renames_namespaced():
    agg = sum_expositions(
        [parse_exposition(_registry_with(2).render())])
    text = "\n".join(render_parsed(
        agg, rename=lambda n: n.replace("butterfly_",
                                        "butterfly_fleet_", 1)))
    assert "butterfly_fleet_requests_total 2" in text
    assert 'butterfly_fleet_ttft_seconds_bucket{le="+Inf"} 2' in text
    assert 'butterfly_fleet_router_requests_total{replica="r2"} 2' in text
    # every sample line is still a legal prometheus series
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        assert re.match(r"[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? \S+$",
                        line), line


# -- fleet trace merging ------------------------------------------------------

def test_tracer_request_id_filter_and_lookup():
    tr = Tracer()
    tr.begin_request(0, request_id="a")
    tr.begin_request(1, request_id="b")
    tr.begin_request(2, request_id="a")  # retry of the same client id
    assert [t["id"] for t in tr.timelines(request_id="a")] == [0, 2]
    assert tr.find_by_request_id("a")["id"] == 2  # newest wins
    assert tr.find_by_request_id("zzz") is None
    dump = tr.dump(request_id="b", n_global=0)
    assert [t["id"] for t in dump["requests"]] == [1]
    assert dump["global_events"] == []


def _fleet_tracers():
    """A synthetic control plane + one replica tracing the same id.
    Leg events are recorded at leg END carrying dur_s, like the real
    FleetHandler — the sleeps make the ends (and therefore the derived
    start_wall ordering) physically real."""
    import time
    cp = Tracer()
    cp.begin_request(0, request_id="rq", path="/generate")
    time.sleep(0.002)
    cp.event(0, "classify", dur_s=0.001, decision="disagg")
    rep = Tracer()
    rep.begin_request(7, request_id="rq")
    rep.event(7, "first_token", ttft_s=0.002)
    rep.event(7, "finish", state="finished", tokens=1)
    time.sleep(0.012)
    cp.event(0, "prefill_leg", dur_s=0.01, replica="a:1", status="ok")
    time.sleep(0.021)
    cp.event(0, "decode_leg", dur_s=0.02, replica="b:1", status="ok")
    cp.event(0, "finish", state="disaggregated", tokens=8, total_s=0.033,
             ttft_s=0.012, slo_ttft_ok=True)
    return cp, rep


def test_merge_fleet_trace_common_clock_and_offset():
    cp, rep = _fleet_tracers()
    control = {"timeline": cp.timeline(0), "t0_wall": cp.t0_wall,
               "t0_monotonic": cp.t0_monotonic}
    merged = merge_fleet_trace("rq", control, {
        "a:1": {"dump": rep.dump(request_id="rq"), "offset_s": 0.25}})
    # every event lands on one clock, time-sorted
    ts = [ev["t_wall"] for ev in merged["merged"]]
    assert ts == sorted(ts)
    assert {ev["source"] for ev in merged["merged"]} == {"control", "a:1"}
    # the replica's events shifted EARLIER by its +250ms clock offset
    zero = merge_fleet_trace("rq", control, {
        "a:1": {"dump": rep.dump(request_id="rq"), "offset_s": 0.0}})
    t_off = [e["t_wall"] for e in merged["merged"]
             if e["source"] == "a:1"]
    t_zero = [e["t_wall"] for e in zero["merged"]
              if e["source"] == "a:1"]
    assert all(abs((z - o) - 0.25) < 1e-9
               for z, o in zip(t_zero, t_off))
    # legs come from the control-plane dur_s spans, waterfall-ordered
    assert [leg["name"] for leg in merged["legs"]] == \
        ["classify", "prefill_leg", "decode_leg"]
    assert merged["legs_total_s"] == pytest.approx(0.031)
    assert merged["total_s"] == pytest.approx(0.033)
    assert merged["slo"]["slo_ttft_ok"] is True
    json.dumps(merged)  # the /fleet/trace body must be JSON-ready


def test_merge_fleet_trace_missing_replica_degrades():
    cp, _ = _fleet_tracers()
    control = {"timeline": cp.timeline(0), "t0_wall": cp.t0_wall,
               "t0_monotonic": cp.t0_monotonic}
    merged = merge_fleet_trace("rq", control, {
        "a:1": {"dump": None, "offset_s": None, "error": "refused"},
        "b:1": {"dump": {"requests": [], "t0_wall": 0.0,
                         "t0_monotonic": 0.0}, "offset_s": 0.0}})
    # control-plane spans survive alone; both replicas marked missing
    assert {ev["source"] for ev in merged["merged"]} == {"control"}
    assert merged["sources"]["a:1"]["missing"] is True
    assert merged["sources"]["a:1"]["error"] == "refused"
    assert merged["sources"]["b:1"]["missing"] is True
    assert len(merged["legs"]) == 3


# -- tools/trace_report.py smoke --------------------------------------------

def _synthetic_dump(path):
    tr = Tracer()
    for rid in range(3):
        tr.begin_request(rid, request_id=f"client-{rid}", prompt_len=8)
        tr.event(rid, "admit", slot=rid % 2, queue_wait_s=0.001)
        tr.event(rid, "prefill_chunk", start=0, tokens=8)
        tr.event(rid, "prefill_done", tokens=8)
        tr.event(rid, "first_token", ttft_s=0.01)
        if rid == 1:
            tr.event(rid, "preempt", slot=1, preemptions=1)
            tr.event(rid, "admit", slot=0, resumed=True)
        tr.event(rid, "finish", state="finished", tokens=4)
    for i in range(5):
        tr.event(None, "decode_tick", batch=2, generated=2)
    tr.dump_json(str(path))
    return path


def test_trace_report_summary_and_timeline(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "trace_report", REPO / "tools" / "trace_report.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    dump = _synthetic_dump(tmp_path / "trace.json")
    rows = mod.summary_rows(mod.load_dump(str(dump)))
    assert len(rows) == 3
    assert rows[1]["preemptions"] == 1
    text = mod.render_summary(mod.load_dump(str(dump)))
    assert "client-0" in text and "3 request(s)" in text
    assert "5 global event(s), 5 decode tick(s)" in text
    tl = mod.render_timeline(mod.load_dump(str(dump)), 1)
    assert "preempt" in tl and "request_id=client-1" in tl
    with pytest.raises(ValueError):
        mod.render_timeline(mod.load_dump(str(dump)), 99)
    # a non-dump JSON file is a loud error, not a silent empty report
    bad = tmp_path / "bad.json"
    bad.write_text("[1,2,3]")
    with pytest.raises(ValueError):
        mod.load_dump(str(bad))


def test_trace_report_cli_smoke(tmp_path):
    """The CLI entrypoint can't rot: run it as a real subprocess on a
    synthetic dump (stdlib-only import path — no jax startup cost)."""
    dump = _synthetic_dump(tmp_path / "trace.json")
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "trace_report.py"),
         str(dump)],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "3 request(s)" in out.stdout
    out2 = subprocess.run(
        [sys.executable, str(REPO / "tools" / "trace_report.py"),
         str(dump), "--json"],
        capture_output=True, text=True, timeout=60)
    assert out2.returncode == 0, out2.stderr
    assert len(json.loads(out2.stdout)) == 3
    # missing file exits 2 with a diagnostic on stderr
    out3 = subprocess.run(
        [sys.executable, str(REPO / "tools" / "trace_report.py"),
         str(tmp_path / "nope.json")],
        capture_output=True, text=True, timeout=60)
    assert out3.returncode == 2 and "error:" in out3.stderr


def test_trace_report_fleet_cli_smoke(tmp_path):
    """--fleet renders a dumped merged trace (the GET /fleet/trace
    body) as a real subprocess — stdlib-only, no jax import — so
    report-rendering regressions fail tier-1."""
    cp, rep = _fleet_tracers()
    merged = merge_fleet_trace(
        "rq", {"timeline": cp.timeline(0), "t0_wall": cp.t0_wall,
               "t0_monotonic": cp.t0_monotonic},
        {"a:1": {"dump": rep.dump(request_id="rq"), "offset_s": 0.0},
         "b:1": {"dump": None, "offset_s": None, "error": "refused"}})
    path = tmp_path / "fleet.json"
    path.write_text(json.dumps(merged))
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "trace_report.py"),
         "--fleet", str(path)],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    for needle in ("request_id=rq", "prefill_leg", "decode_leg",
                   "legs sum", "MISSING", "slo:"):
        assert needle in out.stdout, (needle, out.stdout)
    # a per-request dump is not a fleet dump: loud error, exit 2
    plain = tmp_path / "plain.json"
    _synthetic_dump(plain)
    out2 = subprocess.run(
        [sys.executable, str(REPO / "tools" / "trace_report.py"),
         "--fleet", str(plain)],
        capture_output=True, text=True, timeout=60)
    assert out2.returncode == 2 and "merged" in out2.stderr


# -- tick anatomy: the timeline ring (ISSUE 15) -----------------------------

def _tick_entry(log, wall=0.01, **kw):
    phases = kw.pop("phases", {"admit": 0.002, "dispatch": 0.004,
                               "drain_oldest": 0.003, "other": 0.001})
    log.record(wall, phases, **kw)


def test_ticklog_bounded_and_seq_monotonic():
    from butterfly_tpu.obs.ticklog import TickLog
    log = TickLog(capacity=4)
    for i in range(10):
        _tick_entry(log, wall=0.01 * (i + 1), batch=i)
    d = log.dump()
    assert len(d["ticks"]) == 4 and d["next_seq"] == 10
    seqs = [t["seq"] for t in d["ticks"]]
    assert seqs == sorted(seqs) == [6, 7, 8, 9]
    # ?n=K limit semantics (the /debug/ticks query)
    assert [t["seq"] for t in log.dump(n=2)["ticks"]] == [8, 9]
    assert log.dump(n=0)["ticks"] == []
    json.dumps(d)  # the /debug/ticks body must be JSON-ready


def test_ticklog_record_copies_phases():
    """The ring entry must not alias the scheduler's reusable phase
    accumulator — zeroing it for the next tick would rewrite history."""
    from butterfly_tpu.obs.ticklog import TickLog
    log = TickLog()
    phases = {"admit": 0.5}
    log.record(0.5, phases)
    phases["admit"] = 0.0
    assert log.dump()["ticks"][0]["phases"]["admit"] == 0.5


def test_ticklog_phase_percentiles_and_combined_drain():
    from butterfly_tpu.obs.ticklog import TickLog
    log = TickLog()
    for i in range(20):
        log.record(0.01, {"admit": 0.001 * i, "drain_oldest": 0.002,
                          "drain_barrier": 0.003})
    pp = log.phase_percentiles()
    assert pp["drain"]["p50"] == pytest.approx(0.005)
    assert pp["admit"]["p95"] >= pp["admit"]["p50"]
    assert TickLog().phase_percentiles() == {}


# -- anomaly flight recorder (ISSUE 15) -------------------------------------

def _validate_artifact(art):
    from butterfly_tpu.obs.ticklog import FLIGHTREC_SCHEMA
    assert art["schema"] == FLIGHTREC_SCHEMA
    for key in ("reason", "seed", "t_wall", "next_seq", "signals",
                "event_counts", "events"):
        assert key in art, key
    json.dumps(art)


def test_flight_recorder_ring_bounded():
    from butterfly_tpu.obs.ticklog import FlightRecorder
    fr = FlightRecorder(capacity=3)
    for i in range(7):
        fr.note("admit", id=i)
    d = fr.dump()
    assert d["enabled"] and len(d["events"]) == 3
    assert [e["id"] for e in d["events"]] == [4, 5, 6]
    seqs = [e["seq"] for e in d["events"]]
    assert seqs == sorted(seqs)
    json.dumps(d)


def test_flight_recorder_slo_burn_trigger():
    """The mutcheck discriminator: poll at burn >= threshold MUST dump
    (threshold weakened to inf would silently never fire)."""
    from butterfly_tpu.obs.ticklog import FlightRecorder
    fr = FlightRecorder(slo_burn_threshold=0.5)
    fr.note("admit", id=0)
    assert fr.poll({"slo_burn_rate": 0.4}) is None
    art = fr.poll({"slo_burn_rate": 0.6})
    assert art is not None and art["reason"] == "slo_burn"
    _validate_artifact(art)
    assert art["signals"]["slo_burn_rate"] == 0.6
    assert art["event_counts"] == {"admit": 1}
    assert fr.dump()["triggers_fired"] == {"slo_burn": 1}


def test_flight_recorder_burn_zero_never_fires():
    """threshold 0 + burn 0 (no SLO declared anywhere) must stay
    quiet: the recorder never alarms on an idle default setup."""
    from butterfly_tpu.obs.ticklog import FlightRecorder
    fr = FlightRecorder(slo_burn_threshold=0.0)
    assert fr.poll({"slo_burn_rate": 0.0}) is None


def test_flight_recorder_preempt_storm_and_cooldown():
    from butterfly_tpu.obs.ticklog import FlightRecorder
    fr = FlightRecorder(preempt_storm=3, cooldown_s=3600.0)
    assert fr.poll({"preemptions_total": 0}) is None
    assert fr.poll({"preemptions_total": 2}) is None
    art = fr.poll({"preemptions_total": 3})
    assert art is not None and art["reason"] == "preempt_storm"
    _validate_artifact(art)
    # cooldown: the signal staying bad must not spam artifacts
    assert fr.poll({"preemptions_total": 9}) is None
    assert len(fr.dump()["dumps"]) == 1


def test_flight_recorder_expiry_burst_trigger():
    from butterfly_tpu.obs.ticklog import FlightRecorder
    fr = FlightRecorder(expiry_burst=2)
    assert fr.poll({"deadline_expired_total": 0}) is None
    art = fr.poll({"deadline_expired_total": 2})
    assert art is not None and art["reason"] == "expiry_burst"


def test_flight_recorder_wedge_trigger_and_dump_dir(tmp_path):
    """The wedge latch calls trigger() directly (the tick loop may be
    dead); with dump_dir set the artifact lands on disk as JSON."""
    from butterfly_tpu.obs.ticklog import FlightRecorder
    fr = FlightRecorder(dump_dir=str(tmp_path / "rec"))
    fr.note("wedge", error="heartbeat failed")
    art = fr.trigger("wedge", {"error": "heartbeat failed"})
    _validate_artifact(art)
    assert "path" in art
    on_disk = json.loads(Path(art["path"]).read_text())
    assert on_disk["reason"] == "wedge"
    assert on_disk["events"][0]["kind"] == "wedge"


# -- tools/tick_report.py smoke ---------------------------------------------

def _synthetic_ticks(path, n=12):
    from butterfly_tpu.obs.ticklog import TickLog
    log = TickLog()
    for i in range(n):
        phases = {"expire": 0.0001, "drain_oldest": 0.001,
                  "drain_barrier": 0.002 if i % 3 == 0 else 0.0,
                  "admit": 0.003, "assemble": 0.0005,
                  "dispatch": 0.004, "mixed": 0.005, "spec_emit": 0.0,
                  "flush": 0.0002, "other": 0.0008}
        wall = sum(phases.values())
        log.record(wall, phases, fetch_s=0.0015, inflight=2,
                   barrier_causes=["admission"] if i % 3 == 0 else [],
                   batch=4, waiting=i % 2, pages_free=10, generated=8)
    path.write_text(json.dumps({"enabled": True, **log.dump()}))
    return path


def test_tick_report_stats_and_reconciliation(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "tick_report", REPO / "tools" / "tick_report.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    dump = mod.load_dump(str(_synthetic_ticks(tmp_path / "ticks.json")))
    s = mod.phase_stats(dump)
    assert s["ticks"] == 12
    # THE acceptance property: phase sums reconcile with tick wall
    assert abs(s["reconciliation"] - 1.0) <= 0.10
    assert s["host_frac"] + s["device_frac"] == pytest.approx(1.0)
    # top-terms order: totals descending, dispatch ahead of expire
    totals = [p["total_s"] for p in s["phases"]]
    assert totals == sorted(totals, reverse=True)
    assert s["barrier_causes"] == {"admission": 4}
    text = mod.render(dump)
    assert "dispatch" in text and "barriers by cause" in text
    # the top-terms table speaks the mixed-dispatch vocabulary: the
    # fused phase renders with its glossary note
    assert "mixed" in text and "ONE fused dispatch" in text
    # a non-dump file is a loud error
    bad = tmp_path / "bad.json"
    bad.write_text("[1,2]")
    with pytest.raises(ValueError):
        mod.load_dump(str(bad))


def test_tick_report_cli_smoke(tmp_path):
    """Subprocess smoke (stdlib-only import path, like trace_report)."""
    dump = _synthetic_ticks(tmp_path / "ticks.json")
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "tick_report.py"),
         str(dump)],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "12 tick(s)" in out.stdout
    assert "phase sums account for" in out.stdout
    out2 = subprocess.run(
        [sys.executable, str(REPO / "tools" / "tick_report.py"),
         str(dump), "--json"],
        capture_output=True, text=True, timeout=60)
    assert out2.returncode == 0, out2.stderr
    stats = json.loads(out2.stdout)
    assert abs(stats["reconciliation"] - 1.0) <= 0.10
    out3 = subprocess.run(
        [sys.executable, str(REPO / "tools" / "tick_report.py"),
         str(tmp_path / "nope.json")],
        capture_output=True, text=True, timeout=60)
    assert out3.returncode == 2 and "error:" in out3.stderr
