"""Long-context serving tests (ISSUE 20).

Four layers, bottom up:

* ring block kernel: the Pallas leg (interpret mode — the jnp twin is
  what shard_map bodies run on CPU, so the kernel needs its own direct
  coverage) vs `ring_block_stats_ref` vs dense attention, float x int8,
  aligned x ragged chunk geometry;
* the stats algebra: a seq=4-style four-shard split merged with
  `merge_stats` must reproduce dense exactly (the running-max
  correction `exp(m_a - m)` is load-bearing here — mutcheck target);
* engine surface: `sp_prefill_chunk` (seq=4 mesh, int8 KV) vs the
  dense `prefill_chunk` logits, chunk by chunk;
* scheduler: long prompts admitted through the seq-parallel lane
  (chunked SP prefill -> ordinary paged decode) match the dense-path
  scheduler token for token, and the pages the lane writes are
  prefix-registry-visible on resubmission.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from butterfly_tpu.core.config import MeshConfig, ModelConfig, RuntimeConfig
from butterfly_tpu.core.mesh import make_mesh
from butterfly_tpu.engine.serving import ServingEngine
from butterfly_tpu.models.common import Model, init_params
from butterfly_tpu.ops.ring_attention import (
    finalize_stats, merge_stats, ring_block_stats, ring_block_stats_ref,
    zero_stats)
from butterfly_tpu.sched.scheduler import Scheduler


# ---------------------------------------------------------------------------
# kernel-level parity
# ---------------------------------------------------------------------------

def _dense_ref(q, k, v, q_pos, k_pos):
    """Full masked softmax attention. q [B,T,Nq,H]; k/v [B,S,Kv,H] float.

    GQA head order matches the ring contract: head n reads kv head n // G.
    """
    B, T, Nq, H = q.shape
    G = Nq // k.shape[2]
    kx = jnp.repeat(k, G, axis=2).astype(jnp.float32)
    vx = jnp.repeat(v, G, axis=2).astype(jnp.float32)
    s = jnp.einsum("btnh,bsnh->bnts", q.astype(jnp.float32), kx,
                   preferred_element_type=jnp.float32) / np.sqrt(H)
    mask = k_pos[:, None, None, :] <= q_pos[:, None, :, None]
    s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bnts,bsnh->btnh", p, vx)


def _make_block(T, S, start, seed=0):
    """A chunk of T queries at positions [start, start+T) over S keys."""
    B, Nq, Kv, H = 2, 8, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, T, Nq, H), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, Kv, H), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, Kv, H), jnp.float32)
    q_pos = jnp.broadcast_to(jnp.arange(start, start + T)[None], (B, T))
    k_pos = jnp.broadcast_to(jnp.arange(S)[None], (B, S))
    return q, k, v, q_pos.astype(jnp.int32), k_pos.astype(jnp.int32)


def _quant_kv(x):
    """[B,S,Kv,H] float -> (codes [B,Kv,S,H] int8, scales [B,Kv,S])."""
    xt = jnp.moveaxis(x, 2, 1)                        # [B,Kv,S,H]
    scale = jnp.max(jnp.abs(xt), axis=-1) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    codes = jnp.round(xt / scale[..., None]).astype(jnp.int8)
    return codes, scale.astype(jnp.float32)


@pytest.mark.parametrize("quant", [False, True], ids=["float", "int8"])
@pytest.mark.parametrize("T,S,start", [(8, 32, 24), (5, 19, 11)],
                         ids=["aligned", "ragged"])
def test_ring_block_parity_grid(quant, T, S, start):
    """Pallas kernel (interpret) vs jnp twin vs dense, small blocks so the
    grid's reduction axis actually streams several K/V tiles through the
    scratch state (and the ragged case exercises the INVALID_POS pad)."""
    q, k, v, q_pos, k_pos = _make_block(T, S, start)
    if quant:
        kc, ks = _quant_kv(k)
        vc, vs = _quant_kv(v)
        ref_in = (q, kc, vc, q_pos, k_pos, ks, vs)
        k_dq = jnp.moveaxis(kc.astype(jnp.float32) * ks[..., None], 1, 2)
        v_dq = jnp.moveaxis(vc.astype(jnp.float32) * vs[..., None], 1, 2)
        dense = _dense_ref(q, k_dq, v_dq, q_pos, k_pos)
    else:
        ref_in = (q, k, v, q_pos, k_pos)
        dense = _dense_ref(q, k, v, q_pos, k_pos)

    twin = finalize_stats(ring_block_stats_ref(*ref_in), jnp.float32)
    kern = finalize_stats(
        ring_block_stats(*ref_in, block_q=8, block_k=8, interpret=True),
        jnp.float32)

    np.testing.assert_allclose(np.asarray(twin), np.asarray(dense),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(kern), np.asarray(twin),
                               rtol=2e-5, atol=2e-5)


def test_ring_merge_four_shards_matches_dense():
    """seq=4 ring decomposition, one device: per-shard partial stats
    merged left-to-right (seeded with the zero_stats identity) must equal
    dense. Each shard has a different score max, so the running-max
    rescale `exp(m_a - m)` in merge_stats is what makes this pass."""
    T, S, start = 8, 32, 24
    q, k, v, q_pos, k_pos = _make_block(T, S, start, seed=3)
    B, _, Nq, H = q.shape
    parts = []
    for i in range(4):
        sl = slice(i * 8, (i + 1) * 8)
        parts.append(ring_block_stats_ref(
            q, k[:, sl], v[:, sl], q_pos, k_pos[:, sl]))
    merged = functools.reduce(merge_stats, parts, zero_stats(B, Nq, T, H))
    out = finalize_stats(merged, jnp.float32)
    dense = _dense_ref(q, k, v, q_pos, k_pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# engine + scheduler surfaces (tiny model, seq=4 x data=2 mesh)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_model():
    cfg = ModelConfig(vocab_size=256, hidden_size=64, num_layers=2,
                      num_heads=8, num_kv_heads=2, head_dim=8,
                      intermediate_size=128, max_seq_len=256,
                      dtype="float32")
    return Model(cfg), init_params(cfg, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh(MeshConfig(seq=4, data=2))


LONG = [int(t) for t in (np.arange(100) * 7 + 3) % 256]
SHORT = [int(t) for t in (np.arange(12) * 5 + 1) % 256]


def test_sp_chunk_prefill_int8_logits_parity(tiny_model, sp_mesh):
    """Fast-tier anchor: seq-parallel chunk prefill with int8 KV matches
    the dense chunk path's logits chunk for chunk (dequant happens inside
    the ring blocks — the engine-level guard that used to reject this
    combination is gone)."""
    model, params = tiny_model
    rt = RuntimeConfig(max_batch_size=2, page_size=16, max_seq_len=128,
                       kv_quant="int8")
    dense = ServingEngine(model, params, runtime=rt)
    sp = ServingEngine(model, params, runtime=rt, mesh=sp_mesh)
    assert sp.supports_seq_parallel and sp.sp_degree == 4

    prompt = [int(t) for t in (np.arange(40) * 11 + 5) % 256]
    pages = list(range(-(-len(prompt) // 16)))
    dense.set_table_row(0, pages)
    sp.set_table_row(0, pages)
    for lo, hi in ((0, 24), (24, 40)):
        ld = dense.prefill_chunk(0, prompt[lo:hi], lo)
        ls = sp.sp_prefill_chunk(0, prompt[lo:hi], lo)
        np.testing.assert_allclose(np.asarray(ls), np.asarray(ld),
                                   rtol=3e-4, atol=3e-4)
    assert int(np.asarray(jax.device_get(sp.cache.lengths))[0]) == 40


@pytest.mark.parametrize("mode,kvq", [
    ("alternating", "none"), ("alternating", "int8"), ("mixed", "none"),
], ids=["alt-float", "alt-int8", "mixed-float"])
def test_sp_sched_long_prefill_parity(tiny_model, sp_mesh, mode, kvq):
    """A long prompt (above seq_parallel_threshold) admitted through the
    scheduler's SP lane plus a concurrent short prompt on the normal
    path: both must match the dense-path scheduler token for token, and
    the lane must actually have dispatched SP chunks."""
    model, params = tiny_model
    rt = RuntimeConfig(max_batch_size=2, page_size=16, max_seq_len=160,
                       kv_quant=kvq, prefill_chunk=16,
                       seq_parallel_threshold=64,
                       mixed_dispatch=(mode == "mixed"))
    sp = Scheduler(ServingEngine(model, params, rt, mesh=sp_mesh), seed=0)
    assert sp._sp_enabled
    dn = Scheduler(ServingEngine(
        model, params, rt.replace(seq_parallel_threshold=0)), seed=0)

    r_sp = sp.submit(list(LONG), max_new_tokens=8, temperature=0.0)
    s_sp = sp.submit(list(SHORT), max_new_tokens=8, temperature=0.0)
    sp.run_until_done()
    r_dn = dn.submit(list(LONG), max_new_tokens=8, temperature=0.0)
    s_dn = dn.submit(list(SHORT), max_new_tokens=8, temperature=0.0)
    dn.run_until_done()

    assert r_sp.output == r_dn.output
    assert s_sp.output == s_dn.output
    assert sp._c_sp_tokens.value > 0


def test_prefix_hit_after_long_prefill(tiny_model, sp_mesh):
    """KV written by SP chunk prefill lands in the paged pool like any
    other prefill: resubmitting the long prompt must hit the prefix
    registry (cached pages at admit) and still decode identically."""
    model, params = tiny_model
    rt = RuntimeConfig(max_batch_size=2, page_size=16, max_seq_len=160,
                       kv_quant="none", prefill_chunk=16,
                       seq_parallel_threshold=64, prefix_caching=True)
    s = Scheduler(ServingEngine(model, params, rt, mesh=sp_mesh), seed=0)
    a = s.submit(list(LONG), max_new_tokens=4, temperature=0.0)
    s.run_until_done()
    b = s.submit(list(LONG), max_new_tokens=4, temperature=0.0)
    s.run_until_done()
    assert b.cached_at_admit > 0
    assert a.output == b.output


def test_longctx_benchmark_smoke(tiny_model):
    """The bench row end to end at a tiny shape: the SP lane must be
    exercised (sp tokens > 0), the ring microbench pair must carry the
    CPU honesty key, and the declared ITL budget must be emitted (the
    within-budget bool itself is asserted by the driver's bench run,
    not here — a loaded CI box can blow any wall-clock bound)."""
    from butterfly_tpu.obs.benchmark import run_longctx_benchmark
    model, params = tiny_model
    out = run_longctx_benchmark(model, params, prompt_len=128,
                                prefill_chunk=16, max_new=4,
                                n_decoders=2, decode_new=12, repeats=1)
    assert out["longctx_supported"]
    assert out["longctx_ring_kernelized"] is False
    assert out["longctx_sp_prefill_tokens"] > 0
    assert out["longctx_prefill_tokens_per_sec"] > 0
    assert out["longctx_ring_block_ms_jnp"] > 0
    assert "longctx_itl_budget_s" in out
    assert isinstance(out["longctx_itl_within_budget"], bool)
