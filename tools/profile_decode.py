#!/usr/bin/env python
"""XProf the windowed int8 decode step at the bench operating point.

Captures a trace of ONLY the fused decode program (prefill + first sample
run outside the trace window), converts the xplane with xprof's
`hlo_stats` tool, and prints the top HLO ops by self time — the artifact
VERDICT r4 item 2 asks for (docs/decode_profile_r5.md).

`--serving` traces the SERVING path's fused decode block instead: one
Scheduler tick's k-step jitted scan (engine._decode_scan) over the paged
pool, warmed through real admissions so the trace window holds exactly
one block dispatch.

`--prefill` traces one batched [B, Tbucket] prefill dispatch
(engine.prefill_batch): a gang of waiting requests is admitted inside
the trace window after the program compiled off the clock — the
admission-path twin of --serving.

`--pipeline` traces TWO chained in-flight decode blocks (dispatch-ahead,
ISSUE 5): block 2 is dispatched on block 1's device-resident carry
before block 1 is drained, so the trace shows whether the device runs
the blocks back-to-back (no bubble) while the host sits in between.

`--spec` traces one batched SPECULATIVE block (ISSUE 9): k rounds of
draft + [S, gamma+1] multi-slot verify + on-device accept as one
jitted scan (engine._spec_scan) — the speculative twin of --serving.
Add `--tree-width w [--tree-nodes N]` (ISSUE 19) to trace the token-
TREE variant instead (engine._spec_tree_scan: [S, N] single-dispatch
tree verify under the tree-attention mask) — the TPU tree point is
this flag flip.

Usage: python tools/profile_decode.py [--max-new N] [--out DIR]
       python tools/profile_decode.py --serving [--steps-per-tick K]
       python tools/profile_decode.py --prefill [--prefill-max-batch B]
       python tools/profile_decode.py --pipeline [--steps-per-tick K]
       python tools/profile_decode.py --spec [--gamma G]
       python tools/profile_decode.py --spec --draft-source model \
           --tree-width 2 [--tree-nodes N]
"""
from __future__ import annotations

import argparse
import glob
import json
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-new", type=int, default=128)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--prompt-len", type=int, default=128)
    ap.add_argument("--preset", default="1b", choices=("1b", "8b"),
                    help="'1b' (round-4 proxy) or '8b' (config of record)")
    ap.add_argument("--out", default=None, help="trace dir (default: tmp)")
    ap.add_argument("--top", type=int, default=15)
    ap.add_argument("--serving", action="store_true",
                    help="trace one fused SERVING decode block "
                         "(Scheduler + ServingEngine paged path) instead "
                         "of the offline engine's fused scan")
    ap.add_argument("--steps-per-tick", type=int, default=16,
                    help="fused block width for --serving (matches "
                         "RuntimeConfig.decode_steps_per_tick)")
    ap.add_argument("--prefill", action="store_true",
                    help="trace one batched [B, Tbucket] prefill "
                         "dispatch (group admission, "
                         "engine.prefill_batch) instead of a decode "
                         "program")
    ap.add_argument("--prefill-max-batch", type=int, default=8,
                    help="gang width for --prefill (matches "
                         "RuntimeConfig.prefill_max_batch; clamped to "
                         "--batch)")
    ap.add_argument("--pipeline", action="store_true",
                    help="trace TWO chained in-flight serving decode "
                         "blocks (dispatch-ahead: block 2 dispatched "
                         "on block 1's device carry before block 1 is "
                         "drained) — shows whether the device runs "
                         "them back-to-back with no bubble")
    ap.add_argument("--spec", action="store_true",
                    help="trace ONE batched speculative verify block "
                         "(engine._spec_scan: draft + multi-slot "
                         "verify + on-device accept rounds as one "
                         "jitted scan) — the speculative twin of "
                         "--serving")
    ap.add_argument("--gamma", type=int, default=4,
                    help="draft width for --spec (matches "
                         "RuntimeConfig.speculative_gamma)")
    ap.add_argument("--draft-source", default="ngram",
                    help="draft source for --spec (matches "
                         "RuntimeConfig.draft_model): 'ngram' = prompt "
                         "lookup, 'model' = the on-device draft model "
                         "(its per-round micro-steps land inside the "
                         "traced scan) — the ROADMAP item 3 TPU "
                         "speedup point is this flag flip")
    ap.add_argument("--tree-width", type=int, default=0,
                    help="token-TREE speculation for --spec (matches "
                         "RuntimeConfig.spec_tree_width, ISSUE 19): "
                         "branch top-WIDTH children per draft expansion "
                         "and verify the whole tree in one forward — "
                         "the TPU tree trace is this flag flip. "
                         "Requires --draft-source model; 0 = linear")
    ap.add_argument("--tree-nodes", type=int, default=0,
                    help="tree node budget N for --tree-width (matches "
                         "RuntimeConfig.spec_tree_nodes; 0 = auto "
                         "gamma+1, equal verify FLOPs vs the linear "
                         "chain)")
    ap.add_argument("--draft-layers", type=int, default=0,
                    help="truncation depth for --draft-source model "
                         "(matches RuntimeConfig.draft_layers; 0 = "
                         "num_layers/4, floor 1)")
    ap.add_argument("--long-context", action="store_true",
                    help="trace ONE seq-parallel prefill chunk dispatch "
                         "(engine.sp_prefill_chunk: ring attention over "
                         "the mesh's seq axis, K/V scattered into the "
                         "paged pool) plus one fused decode block "
                         "beside it — the ISSUE 20 scheduler lane. "
                         "Builds a seq=4 mesh; the device count must be "
                         "a multiple of 4 (on CPU, 8 host devices are "
                         "forced like tests/conftest.py)")
    args = ap.parse_args()

    if args.long_context:
        # must land before the first jax import initializes the backend
        import os
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from butterfly_tpu.core.config import ModelConfig, RuntimeConfig, tiny
    from butterfly_tpu.engine import InferenceEngine, SamplingParams
    from butterfly_tpu.engine.engine import pad_prompts
    from butterfly_tpu.engine.sampling import sample
    from butterfly_tpu.models.common import Model
    from butterfly_tpu.quant.int8 import (init_params_quantized,
                                          quantize_int8)

    on_tpu = jax.devices()[0].platform != "cpu"
    if on_tpu and args.preset == "8b":
        from butterfly_tpu.core.config import llama3_8b
        cfg = llama3_8b().replace(max_seq_len=2048)
    elif on_tpu:
        cfg = ModelConfig(arch="llama", vocab_size=32000, hidden_size=2048,
                          num_layers=16, num_heads=16, num_kv_heads=8,
                          head_dim=128, intermediate_size=5632,
                          max_seq_len=2048)
    else:
        if args.preset != "1b":
            print(f"warning: no TPU visible — profiling the tiny CPU "
                  f"config, NOT --preset {args.preset}", file=sys.stderr)
        cfg = tiny("llama", dtype="float32", param_dtype="float32")
        args.batch, args.prompt_len, args.max_new = 4, 32, 16

    model = Model(cfg)
    params = init_params_quantized(cfg, jax.random.PRNGKey(0)) if on_tpu \
        else quantize_int8(model.init(jax.random.PRNGKey(0)), cfg)
    kv_quant = "int8" if on_tpu else "none"
    if args.long_context:
        return _profile_longctx(args, model, params, kv_quant)
    if args.prefill:
        return _profile_prefill_batch(args, model, params, kv_quant)
    if args.pipeline:
        return _profile_pipeline(args, model, params, kv_quant)
    if args.spec:
        return _profile_spec_block(args, model, params, kv_quant)
    if args.serving:
        return _profile_serving_block(args, model, params, kv_quant)
    engine = InferenceEngine(
        model, params,
        RuntimeConfig(max_seq_len=args.prompt_len + args.max_new,
                      kv_quant=kv_quant))

    rng = np.random.RandomState(0)
    prompts = rng.randint(1, cfg.vocab_size,
                          (args.batch, args.prompt_len)).tolist()
    sp = SamplingParams(max_new_tokens=args.max_new)

    # compile both programs, then replicate generate()'s body so the
    # trace window contains ONLY the fused decode scan
    engine.generate(prompts, sp)
    tokens, true_lens = pad_prompts(prompts)
    C = engine._decode_window
    steps = sp.max_new_tokens - 1
    iters = -(-steps // C) if steps else 0
    max_seq = max(engine.runtime.max_seq_len,
                  tokens.shape[1] + max(sp.max_new_tokens, iters * C))
    cache = engine._cache_pool.pop((args.batch, max_seq), None)
    if cache is None:
        cache = engine.new_cache(args.batch, max_seq)
    key, first_key, loop_key = jax.random.split(jax.random.PRNGKey(0), 3)
    logits, cache = engine.prefill(jnp.asarray(tokens),
                                   jnp.asarray(true_lens), cache)
    first = sample(logits, first_key, sp)
    jax.block_until_ready(first)

    logdir = args.out or tempfile.mkdtemp(prefix="decode_trace_")
    fused_args = (engine.params, first, cache, loop_key, sp,
                  sp.max_new_tokens)
    if C > 1:
        fused_args += (bool(np.all(true_lens == true_lens[0])),)
    jax.profiler.start_trace(logdir)
    out, lens, cache = engine._generate_fused(*fused_args)
    jax.block_until_ready(out)
    jax.profiler.stop_trace()
    return _report(logdir, args.top)


def _profile_serving_block(args, model, params, kv_quant: str) -> int:
    """Trace ONE fused serving decode block (ISSUE 3): a Scheduler is
    warmed through real admissions until every slot decodes, then a
    single k-step block is dispatched inside the trace window — the
    program one tick() pays for, including the on-device sampling, RNG
    fold-in, and EOS/budget masking."""
    import jax
    import numpy as np

    from butterfly_tpu.core.config import RuntimeConfig
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.sched.scheduler import Scheduler

    k = args.steps_per_tick
    cfg = model.cfg
    # budget for the warmup blocks PLUS the traced one (a request that
    # finishes during warmup would leave the traced dispatch a no-op —
    # the CPU fallback's max_new=16 is smaller than one k=16 block);
    # prefill_chunk sized to admit the whole batch in one tick: the
    # warmup then costs ~3 ticks, so slots can't finish (and free)
    # before the trace window captures a FULL-batch block
    max_new = max(args.max_new, 3 * k + 8)
    rt = RuntimeConfig(max_batch_size=args.batch,
                       max_seq_len=args.prompt_len + max_new + 16,
                       kv_quant=kv_quant, decode_steps_per_tick=k,
                       prefill_chunk=max(512, args.prompt_len * args.batch))
    engine = ServingEngine(model, params, rt)
    sched = Scheduler(engine)
    rng = np.random.RandomState(0)
    for _ in range(args.batch):
        sched.submit(rng.randint(1, cfg.vocab_size,
                                 (args.prompt_len,)).tolist(),
                     max_new_tokens=max_new)
    # warm until every submission is admitted and decoding (compiles the
    # prefill buckets + the k-step block program off the clock)
    while sched.waiting or sched._prefill_group:
        sched.tick()
    sched.tick()
    sched._drain_inflight()
    # replicate tick()'s page preallocation so the traced block pays no
    # host-side growth, then capture exactly one fused dispatch
    for req in list(sched.running):
        if req in sched.running:
            need = min(len(req.all_tokens) + k + 1,
                       len(req.prompt) + req.max_new_tokens)
            sched._ensure_or_preempt(req, need)
    jax.block_until_ready(engine.cache.lengths)
    logdir = args.out or tempfile.mkdtemp(prefix="serving_block_trace_")
    jax.profiler.start_trace(logdir)
    sched._decode_block(k)
    jax.block_until_ready(sched._inflight[-1][1])
    jax.profiler.stop_trace()
    sched.run_until_done(max_ticks=10 ** 6)
    return _report(logdir, args.top)


def _profile_longctx(args, model, params, kv_quant: str) -> int:
    """Trace the long-context lane (ISSUE 20): one seq-parallel prefill
    chunk dispatch (ring attention over the seq axis, K/V scattered into
    the paged pool) plus one fused decode block beside it — the two
    programs a tick pays while a long prompt streams through the lane.
    Warmed end to end first (a full long prefill + decode) so both
    programs are compiled off the clock."""
    import jax
    import numpy as np

    from butterfly_tpu.core.config import MeshConfig, RuntimeConfig
    from butterfly_tpu.core.mesh import make_mesh
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.sched.scheduler import Scheduler

    n_dev = jax.device_count()
    if n_dev < 4 or n_dev % 4:
        print(f"--long-context needs a device count divisible by 4 for "
              f"the seq=4 mesh (have {n_dev})", file=sys.stderr)
        return 1
    mesh = make_mesh(MeshConfig(seq=4, data=n_dev // 4))
    cfg = model.cfg
    k = args.steps_per_tick
    chunk = args.prompt_len            # per-shard work unit per dispatch
    long_len = 8 * chunk               # the lane's admission regime
    max_new = max(args.max_new, 8 * k + 16)
    rt = RuntimeConfig(max_batch_size=args.batch,
                       max_seq_len=long_len + max_new + 16,
                       kv_quant=kv_quant, decode_steps_per_tick=k,
                       prefill_chunk=chunk,
                       seq_parallel_threshold=long_len // 2)
    engine = ServingEngine(model, params, rt, mesh=mesh)
    if not engine.supports_seq_parallel:
        print("engine cannot seq-parallel on this mesh", file=sys.stderr)
        return 1
    sched = Scheduler(engine)
    rng = np.random.RandomState(0)

    def prompt(n):
        return rng.randint(1, cfg.vocab_size, (n,)).tolist()

    # warm: one long prefill end to end + decoders that keep decoding
    # (compiles the SP chunk program and the k-step block off the clock)
    warm_long = sched.submit(prompt(long_len), max_new_tokens=2)
    for _ in range(args.batch - 1):
        sched.submit(prompt(args.prompt_len), max_new_tokens=max_new)
    while (sched.waiting or sched._prefill_group or sched._sp_group
           or not warm_long.done):
        sched.tick()
    sched._drain_inflight()
    # a fresh long prompt into the (now free) lane slot
    sched.submit(prompt(long_len), max_new_tokens=2)
    sched._sp_admit()
    assert sched._sp_group, "long prompt did not enter the SP lane"
    # replicate tick()'s page preallocation so the traced block pays no
    # host-side growth
    for req in list(sched.running):
        if req in sched.running:
            need = min(len(req.all_tokens) + k + 1,
                       len(req.prompt) + req.max_new_tokens)
            sched._ensure_or_preempt(req, need)
    jax.block_until_ready(engine.cache.lengths)
    logdir = args.out or tempfile.mkdtemp(prefix="longctx_trace_")
    jax.profiler.start_trace(logdir)
    sched._sp_prefill_step()           # ONE seq-parallel chunk dispatch
    sched._decode_block(k)             # one fused block beside the lane
    jax.block_until_ready(sched._inflight[-1][1])
    jax.profiler.stop_trace()
    sched.run_until_done(max_ticks=10 ** 6)
    return _report(logdir, args.top)


def _profile_spec_block(args, model, params, kv_quant: str) -> int:
    """Trace ONE batched speculative block (ISSUE 9): a speculating
    Scheduler is warmed through real admissions until every slot
    decodes — prompts seeded with each request's own greedy
    continuation so prompt-lookup drafts land — then a single
    `--steps-per-tick`-round spec block is dispatched inside the trace
    window: the draft gathers, the [S, gamma+1] verify forwards, and
    the on-device accept/rollback one tick() pays for."""
    import jax
    import numpy as np

    from butterfly_tpu.core.config import RuntimeConfig
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.sched.scheduler import Scheduler

    k = args.steps_per_tick
    gamma = args.gamma
    cfg = model.cfg
    # budget: warmup rounds PLUS the traced block's worst case
    # (k rounds x gamma+1 emissions per slot)
    max_new = max(args.max_new, 3 * k * (gamma + 1) + 8)
    rt = RuntimeConfig(max_batch_size=args.batch,
                       max_seq_len=args.prompt_len + max_new + gamma + 16,
                       kv_quant=kv_quant, decode_steps_per_tick=k,
                       speculative_gamma=gamma,
                       draft_model=args.draft_source,
                       draft_layers=args.draft_layers,
                       spec_tree_width=getattr(args, "tree_width", 0),
                       spec_tree_nodes=getattr(args, "tree_nodes", 0),
                       prefill_chunk=max(512, args.prompt_len * args.batch))
    rng = np.random.RandomState(0)
    # harvest greedy continuations with a plain scheduler so the traced
    # workload is draft-friendly (looping structure for prompt lookup)
    probe = Scheduler(ServingEngine(model, params,
                                    rt.replace(speculative_gamma=0)))
    half = max(1, args.prompt_len // 2)
    bases = [rng.randint(1, cfg.vocab_size, (half,)).tolist()
             for _ in range(args.batch)]
    cont = [probe.submit(b, max_new_tokens=args.prompt_len - half)
            for b in bases]
    probe.run_until_done(max_ticks=10 ** 6)
    prompts = [b + r.output for b, r in zip(bases, cont)]

    engine = ServingEngine(model, params, rt)
    sched = Scheduler(engine)
    for p in prompts:
        sched.submit(p, max_new_tokens=max_new)
    # warm until every submission is admitted and speculating (compiles
    # the prefill buckets + the spec block program off the clock)
    while sched.waiting or sched._prefill_group:
        sched.tick()
    sched.tick()
    sched._drain_inflight()
    # replicate tick()'s page preallocation so the traced block pays no
    # host-side growth, then capture exactly one fused spec dispatch
    # (tree mode: emit width D+1 per round plus the N-(D+1) compaction
    # overhang — same arithmetic as Scheduler.tick)
    step = k * engine.spec_emit_width
    slack = 0
    if engine.spec_tree_mode:
        slack = engine.spec_tree_geometry[1] - engine.spec_emit_width
    for req in list(sched.running):
        if req in sched.running:
            need = min(len(req.all_tokens) + step + slack + 1,
                       len(req.prompt) + req.max_new_tokens + slack)
            sched._ensure_or_preempt(req, need)
    jax.block_until_ready(engine.cache.lengths)
    logdir = args.out or tempfile.mkdtemp(prefix="spec_block_trace_")
    jax.profiler.start_trace(logdir)
    sched._spec_block(k)
    jax.block_until_ready(sched._inflight[-1][2][0])
    jax.profiler.stop_trace()
    sched.run_until_done(max_ticks=10 ** 6)
    return _report(logdir, args.top)


def _profile_pipeline(args, model, params, kv_quant: str) -> int:
    """Trace TWO chained in-flight decode blocks (ISSUE 5 dispatch-
    ahead): after warmup, block 1 is dispatched and block 2 is chained
    on its device-resident carry WITHOUT draining block 1 — both land
    inside the trace window, so the timeline shows whether the device
    runs them back-to-back (the host work between the two dispatches
    hides under block 1's compute) or leaves a bubble."""
    import jax
    import numpy as np

    from butterfly_tpu.core.config import RuntimeConfig
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.sched.scheduler import Scheduler

    k = args.steps_per_tick
    cfg = model.cfg
    # budget for warmup (first token + one drained block) PLUS the two
    # traced in-flight blocks — otherwise the second dispatch is a
    # no-op once the device-side budgets are spent
    max_new = max(args.max_new, 3 * k + 8)
    rt = RuntimeConfig(max_batch_size=args.batch,
                       max_seq_len=args.prompt_len + max_new + 16,
                       kv_quant=kv_quant, decode_steps_per_tick=k,
                       inflight_blocks=2,
                       prefill_chunk=max(512, args.prompt_len * args.batch))
    engine = ServingEngine(model, params, rt)
    sched = Scheduler(engine)
    rng = np.random.RandomState(0)
    for _ in range(args.batch):
        sched.submit(rng.randint(1, cfg.vocab_size,
                                 (args.prompt_len,)).tolist(),
                     max_new_tokens=max_new)
    # warm until every submission decodes (compiles the prefill buckets
    # and the k-step block program off the clock), then reconcile
    while sched.waiting or sched._prefill_group:
        sched.tick()
    sched.tick()
    sched._drain_inflight()
    # preallocate pages for BOTH blocks so neither dispatch pays
    # host-side growth inside the window (tick()'s (m+1)*k+1 horizon)
    for req in list(sched.running):
        if req in sched.running:
            need = min(len(req.all_tokens) + 2 * k + 2,
                       len(req.prompt) + req.max_new_tokens)
            sched._ensure_or_preempt(req, need)
    jax.block_until_ready(engine.cache.lengths)
    logdir = args.out or tempfile.mkdtemp(prefix="pipeline_trace_")
    jax.profiler.start_trace(logdir)
    sched._decode_block(k)   # block 1
    sched._decode_block(k)   # block 2, chained on block 1's carry
    jax.block_until_ready(sched._inflight[-1][1])
    jax.profiler.stop_trace()
    sched.run_until_done(max_ticks=10 ** 6)
    return _report(logdir, args.top)


def _profile_prefill_batch(args, model, params, kv_quant: str) -> int:
    """Trace ONE batched prefill dispatch (ISSUE 4): the [B, Tbucket]
    gang-admission program is compiled off the clock by a warmup batch,
    then a fresh gang of B waiting requests is admitted inside the trace
    window — exactly one engine.prefill_batch dispatch, including the
    pool scatters and the per-row start/length masking."""
    import jax
    import numpy as np

    from butterfly_tpu.core.config import RuntimeConfig
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.sched.scheduler import Scheduler

    cfg = model.cfg
    B = max(1, min(args.prefill_max_batch, args.batch))
    # prefill_chunk sized so the whole gang's prompts fit one round:
    # the traced window then holds ONE [B, Tbucket] dispatch
    rt = RuntimeConfig(max_batch_size=args.batch,
                       max_seq_len=args.prompt_len + args.max_new + 16,
                       kv_quant=kv_quant, prefill_max_batch=B,
                       prefill_chunk=max(512, args.prompt_len * B))
    engine = ServingEngine(model, params, rt)
    sched = Scheduler(engine)
    rng = np.random.RandomState(0)

    def prompt():
        return rng.randint(1, cfg.vocab_size, (args.prompt_len,)).tolist()

    # warmup gang: compiles the (B-bucket, T-bucket) prefill program
    # (and the decode program the post-trace drain uses) off the clock
    for _ in range(B):
        sched.submit(prompt(), max_new_tokens=2)
    sched.run_until_done()
    for _ in range(B):
        sched.submit(prompt(), max_new_tokens=2)
    jax.block_until_ready(engine.cache.lengths)
    logdir = args.out or tempfile.mkdtemp(prefix="prefill_batch_trace_")
    jax.profiler.start_trace(logdir)
    sched._admit()  # ONE gang admission: the batched prefill dispatch
    jax.block_until_ready(engine.cache.k_pages)
    jax.profiler.stop_trace()
    sched.run_until_done(max_ticks=10 ** 6)
    return _report(logdir, args.top)


def _report(logdir: str, top: int) -> int:
    print(f"# trace: {logdir}", file=sys.stderr)
    planes = glob.glob(f"{logdir}/**/*.xplane.pb", recursive=True)
    if not planes:
        print("no xplane captured", file=sys.stderr)
        return 1
    try:
        from xprof.convert import raw_to_tool_data
    except ImportError:
        print("xprof not installed: raw trace kept at the path above, "
              "no hlo_stats table", file=sys.stderr)
        return 1
    data, _ = raw_to_tool_data.xspace_to_tool_data(planes, "hlo_stats", {})
    rows = json.loads(data) if isinstance(data, (str, bytes)) else data
    _print_hlo_stats(rows, top)
    return 0


def _print_hlo_stats(rows, top: int) -> None:
    """hlo_stats arrives as a GViz-style table; print top ops by self time."""
    if isinstance(rows, dict) and "rows" in rows:   # gviz DataTable json
        cols = [c.get("label", c.get("id", "")) for c in rows["table"]["cols"]] \
            if "table" in rows else [c.get("label", c.get("id", ""))
                                     for c in rows["cols"]]
        raw = rows["rows"] if "rows" in rows else rows["table"]["rows"]
        recs = [{cols[i]: (c or {}).get("v") for i, c in enumerate(r["c"])}
                for r in raw]
    elif isinstance(rows, list):
        recs = rows
    else:
        print(json.dumps(rows)[:2000])
        return
    tkey = next((k for k in recs[0] if "self" in k.lower()
                 and "time" in k.lower() and "%" not in k), None)
    if tkey is None:
        tkey = next(k for k in recs[0] if "time" in k.lower())
    recs.sort(key=lambda r: -(r.get(tkey) or 0))
    tot = sum(r.get(tkey) or 0 for r in recs)
    print(f"{'self_time':>12} {'%':>6}  op")
    for r in recs[:top]:
        name = (r.get("HLO Op Name") or r.get("hlo_op_name")
                or r.get("HLO Op Expression") or "?")
        cat = r.get("HLO Op Category") or r.get("hlo_category") or ""
        t = r.get(tkey) or 0
        print(f"{t:12.1f} {100*t/max(tot,1e-9):6.2f}  [{cat}] {str(name)[:110]}")


if __name__ == "__main__":
    sys.exit(main())
