#!/usr/bin/env python
"""Project-native static analysis driver (ISSUE 11): `butterfly lint`.

Walks the repo's Python trees and enforces the serving contracts the
first ten growth PRs hand-audited — donation, host-sync, lock
discipline, HTTP timeouts, workload determinism, PRNG hygiene — as AST
rules (tools/staticrules/). Findings print one per line::

    butterfly_tpu/foo.py:123:4: BTF001 outbound HTTP call urlopen(...) ...

Exit status: 0 = clean (suppressed findings don't count), 1 = at least
one unsuppressed finding, 2 = usage/parse error.

Usage:
    python tools/staticcheck.py                   # default trees
    python tools/staticcheck.py butterfly_tpu tests/test_sched.py
    python tools/staticcheck.py --list-rules      # the rule catalog
    python tools/staticcheck.py --json            # machine-readable

Suppression syntax (reason MANDATORY — a bare disable is itself a
BTF000 finding):
    something_flagged()  # btf: disable=BTF001 one-line reason

The same engine runs as the tier-1 test (tests/test_staticcheck.py),
as `butterfly lint` (serve/cli.py), and as bench.py's preflight — one
registry, so no surface can silently drop a rule.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Iterable, List, Optional

try:  # script mode: tools/ is sys.path[0]
    import staticrules
except ImportError:  # imported from elsewhere (cli, bench preflight)
    sys.path.insert(0, str(Path(__file__).resolve().parent))
    import staticrules
from staticrules import Finding, check_context, make_context

REPO = Path(__file__).resolve().parent.parent

#: the trees `butterfly lint` / the tier-1 test walk by default
DEFAULT_TREES = ("butterfly_tpu", "tools", "tests")

#: never walked by default: the fixture snippets VIOLATE the rules by
#: design (each rule's positive example), and caches aren't source
DEFAULT_EXCLUDES = ("tests/staticcheck_fixtures", "__pycache__",
                    ".git", ".eggs", "build")


def _excluded(rel: str, excludes: Iterable[str]) -> bool:
    parts = rel.split("/")
    for e in excludes:
        if rel == e or rel.startswith(e.rstrip("/") + "/") or e in parts:
            return True
    return False


def iter_py_files(paths: Iterable[Path],
                  excludes: Iterable[str] = DEFAULT_EXCLUDES):
    for p in paths:
        if p.is_file() and p.suffix == ".py":
            yield p
            continue
        if not p.is_dir():
            continue
        for f in sorted(p.rglob("*.py")):
            rel = f.relative_to(REPO).as_posix() if f.is_relative_to(REPO) \
                else f.as_posix()
            if _excluded(rel, excludes):
                continue
            yield f


def run_paths(paths: Iterable[Path],
              excludes: Iterable[str] = DEFAULT_EXCLUDES,
              rules=None, force: bool = False) -> List[Finding]:
    """Lint files/trees; returns ALL findings (suppressed ones marked).
    ``force=True`` runs every rule regardless of its scope (ad-hoc
    sweeps and fixture linting)."""
    findings: List[Finding] = []
    for f in iter_py_files(paths, excludes=excludes):
        rel = f.relative_to(REPO).as_posix() if f.is_relative_to(REPO) \
            else f.as_posix()
        try:
            ctx = make_context(f, rel)
        except SyntaxError as e:
            findings.append(Finding(
                rule="BTF000", path=rel, line=e.lineno or 1, col=0,
                message=f"file does not parse: {e.msg}"))
            continue
        findings.extend(check_context(ctx, rules=rules, force=force))
    return findings


def run_default(root: Optional[Path] = None) -> List[Finding]:
    """The canonical repo walk (tier-1 test + bench preflight):
    butterfly_tpu/, tools/, tests/ minus the fixture snippets.
    Returns only the UNSUPPRESSED findings."""
    base = root or REPO
    found = run_paths([base / t for t in DEFAULT_TREES])
    return [f for f in found if not f.suppressed]


def list_rules() -> str:
    lines = ["BTF000  bare-suppression  (framework) a '# btf: disable=' "
             "comment without a reason"]
    for rid in sorted(staticrules.RULES):
        r = staticrules.RULES[rid]
        lines.append(f"{r.id}  {r.name}  [{', '.join(r.scope)}]\n"
                     f"        {r.invariant}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="staticcheck",
        description="AST lint for the repo's serving contracts "
                    "(donation, locks, host-sync, determinism)")
    ap.add_argument("paths", nargs="*",
                    help=f"files/trees to lint (default: "
                         f"{' '.join(DEFAULT_TREES)})")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--json", action="store_true",
                    help="one JSON object per finding (jsonl)")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed findings (never affect "
                         "the exit status)")
    ap.add_argument("--force", action="store_true",
                    help="run every rule on every given path, ignoring "
                         "per-rule scopes (ad-hoc sweeps)")
    args = ap.parse_args(argv)

    if args.list_rules:
        print(list_rules())
        return 0

    paths = [Path(p) for p in args.paths] if args.paths \
        else [REPO / t for t in DEFAULT_TREES]
    for p in paths:
        if not p.exists():
            print(f"staticcheck: no such path: {p}", file=sys.stderr)
            return 2
    findings = run_paths(paths, force=args.force)
    unsuppressed = [f for f in findings if not f.suppressed]
    shown = findings if args.show_suppressed else unsuppressed
    for f in shown:
        if args.json:
            print(json.dumps(vars(f), sort_keys=True))
        else:
            print(f.render())
    n_sup = sum(1 for f in findings if f.suppressed)
    if not args.json:
        print(f"staticcheck: {len(unsuppressed)} finding(s), "
              f"{n_sup} suppressed", file=sys.stderr)
    return 1 if unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
