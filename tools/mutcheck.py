#!/usr/bin/env python
"""Mutation-testing smoke: prove the suite KILLS planted bugs.

The reference intended mutation testing (cargo-mutants artifacts in its
.gitignore — SURVEY.md §4); this is the framework's analogue, sized for
CI: a curated set of single-line mutations in numerically-load-bearing
code, each of which MUST make its covering test subset fail. A mutant
that survives means the tests have a blind spot — the tool exits 1 and
names it.

Usage:  python tools/mutcheck.py            # run all mutants
        python tools/mutcheck.py --list     # show the catalogue

Each mutation is applied in-place, the covering tests are run in a
subprocess, and the file is restored from git (requires a clean tree
for the mutated files).
"""
from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

#: (file, original, mutated, covering-tests, extra-env) — original must
#: occur exactly once in the file so the mutation is unambiguous.
MUTANTS = [
    # rms_norm: drop the rsqrt normalization direction
    ("butterfly_tpu/models/common.py",
     "x = x * lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)",
     "x = x * lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + 1.0)",
     ["tests/test_models.py"], {}),
    # causal mask off-by-one: attend to the future
    ("butterfly_tpu/models/common.py",
     "return j <= positions[:, :, None]",
     "return j <= positions[:, :, None] + 1",
     ["tests/test_models.py"], {}),
    # decode fast path: self-term dropped from the merged softmax.
    # Killed by the prefill-whole vs incremental-decode invariant
    # (test_models) — NOT by test_engine, whose compared paths share
    # decode_attend (first mutcheck run found that blind spot).
    ("butterfly_tpu/models/common.py",
     "out = out + p[..., -1:].astype(v_new.dtype) * v_new.reshape(B, Kv, 1, H)",
     "out = out + 0 * p[..., -1:].astype(v_new.dtype) * v_new.reshape(B, Kv, 1, H)",
     ["tests/test_models.py"], {}),
    # int8 KV quantizer: wrong scale denominator (codes clip hard)
    ("butterfly_tpu/models/common.py",
     "scale = jnp.where(amax > 0, amax / 127.0, 1.0)",
     "scale = jnp.where(amax > 0, amax / 64.0, 1.0)",
     ["tests/test_kv_quant.py"], {}),
    # decode window: window K-scales dropped from the merged softmax
    # (quantized window scores would be raw code dots)
    ("butterfly_tpu/models/common.py",
     "s_w = s_w * jnp.moveaxis(wk_s, 0, -1)[:, :, None, :]",
     "s_w = s_w * 1.0",
     ["tests/test_kv_quant.py"], {}),
    # decode window flush (uniform fast path): off-by-one write offset —
    # the flush group lands one slot late, orphaning slot `start`
    ("butterfly_tpu/models/common.py",
     "new_k = lax.dynamic_update_slice(cache.k, kq, (0, 0, 0, s0, 0))",
     "new_k = lax.dynamic_update_slice(cache.k, kq, (0, 0, 0, s0 + 1, 0))",
     ["tests/test_kv_quant.py"], {}),
    # prefix cache: chain digest forgets the parent (a page would match
    # regardless of what precedes it)
    ("butterfly_tpu/cache/prefix.py",
     "m = hashlib.sha256(h)",
     "m = hashlib.sha256()",
     ["tests/test_prefix.py"], {}),
    # prefix cache: refcount never increments (shared pages freed while
    # still attached)
    ("butterfly_tpu/cache/prefix.py",
     "self._ref[pid] += 1",
     "self._ref[pid] += 0",
     ["tests/test_prefix.py"], {}),
    # prefix cache: register the last sampled (never-written) token's
    # page as reusable content
    ("butterfly_tpu/sched/scheduler.py",
     "return len(req.all_tokens) - 1",
     "return len(req.all_tokens)",
     ["tests/test_prefix.py"], {}),
    # stop sequences: leak the first byte of the stop text
    ("butterfly_tpu/serve/server.py",
     "out = self.text[self.released:cut]",
     "out = self.text[self.released:cut + 1]",
     ["tests/test_server.py"], {}),
    # speculative decoding: accept mismatched drafts in the engine's
    # host accept loop (generate_speculative greedy fast path)
    ("butterfly_tpu/engine/engine.py",
     "if d != int(greedy[i]):",
     "if False and d != int(greedy[i]):",
     ["tests/test_speculative.py"], {}),
    # speculative serving: accept mismatched drafts in the DEVICE
    # accept kernel's greedy rows (the serving spec block's byte-parity
    # contract — test_sched greedy parity + the kernel unit tests)
    ("butterfly_tpu/engine/sampling.py",
     "drafts == greedy_tok[:, :gamma]",
     "jnp.ones_like(drafts, dtype=bool)",
     ["tests/test_sched.py", "tests/test_spec_sampling.py"], {}),
    # allocator: hand out one page fewer than needed. Must pin the
    # PYTHON backend: with the native lib built, the scheduler uses the
    # C++ twin and a Python-side mutation is invisible (first mutcheck
    # run found that blind spot too).
    ("butterfly_tpu/cache/allocator.py",
     "want = -(-new_length // self.page_size)",
     "want = new_length // self.page_size",
     ["tests/test_sched.py"], {"BUTTERFLY_NATIVE": "0"}),
    # scheduler: chunked prefill skips the final prompt token
    ("butterfly_tpu/sched/scheduler.py",
     "chunk = prefix[req.prefilled:end]",
     "chunk = prefix[req.prefilled:max(req.prefilled + 1, end - 1)]",
     ["tests/test_sched.py"], {}),
    # paged write: scatter every token to page offset 0
    ("butterfly_tpu/cache/paged.py",
     "offset = pos % page",
     "offset = pos * 0",
     ["tests/test_paged.py"], {}),
    # paged decode kernel: attend one not-yet-written slot past each
    # sequence's length
    ("butterfly_tpu/ops/paged_attention.py",
     "mask = group_ok & (pos < length)",
     "mask = group_ok & (pos <= length)",
     ["tests/test_kernels.py"], {}),
    # paged decode kernel: K scales dropped (int8 scores = raw code dots)
    ("butterfly_tpu/ops/paged_attention.py",
     "s = s * ks_ref[0]",
     "s = s * 1.0",
     ["tests/test_kernels.py"], {}),
    # contiguous int8 attend: V scale not folded into the probs
    ("butterfly_tpu/models/common.py",
     "probs = probs * v_scale[:, :, None, None, :]",
     "probs = probs * 1.0",
     ["tests/test_kv_quant.py"], {}),
    # ring attention: one rotation short (each device misses one
    # neighbor's K/V block)
    ("butterfly_tpu/parallel/sequence.py",
     "step, (stats, k, v, k_pos, k_scale, v_scale), None, length=N)",
     "step, (stats, k, v, k_pos, k_scale, v_scale), None, length=N - 1)",
     ["tests/test_sequence.py"], {}),
    # flash-stats merge (ISSUE 20): drop the running-max correction on
    # the a-leg — partials whose local max is below the joint max keep
    # their unrescaled weight, so every ring rotation / SP chunk merge
    # over-counts the smaller-max side. Killed by the four-shard merge
    # algebra test in tests/test_longctx.py (and the ring parity grid).
    ("butterfly_tpu/ops/ring_attention.py",
     "c_a = jnp.exp(m_a - m)",
     "c_a = jnp.exp(m_a - m_a)",
     ["tests/test_longctx.py"], {}),
    # sp_decode partial-softmax merge: global max skipped (per-device
    # exp shifts disagree, denominators mis-merge)
    ("butterfly_tpu/parallel/sequence.py",
     'm_g = lax.pmax(m_i, "seq")',
     "m_g = m_i",
     ["tests/test_sequence.py"], {}),
    # EP a2a dispatch: counting-sort slot ignores the running count
    # (every assignment of an expert lands in slot 0)
    ("butterfly_tpu/parallel/expert.py",
     "pos = (jnp.cumsum(onehot, axis=0) - 1)[jnp.arange(A), g_flat]",
     "pos = 0 * (jnp.cumsum(onehot, axis=0) - 1)[jnp.arange(A), g_flat]",
     ["tests/test_expert.py"], {}),
    # speculative serving scan: length rollback off by one (the first
    # rejected position's stale K/V becomes attendable). The anchor
    # used to live in the scheduler's host accept loop; it moved into
    # the on-device scan when acceptance did.
    ("butterfly_tpu/engine/serving.py",
     "cache = cache._replace(lengths=jnp.where(live, W + m, W))",
     "cache = cache._replace(lengths=jnp.where(live, W + m + 1, W))",
     ["tests/test_sched.py"], {}),
    # tree speculation (ISSUE 19): collapse the tree-attention
    # ancestor mask to all-ones — every node attends EVERY chunk
    # position in range, so sibling branches leak into each other's
    # scores (a depth-2 node sees its parent's rejected sibling). The
    # realized greedy path's logits shift and the tree parity grid
    # (test_sched k x inflight x window, byte-identical vs spec-off)
    # diverges within a few tokens.
    ("butterfly_tpu/engine/serving.py",
     "& jnp.transpose(tree_bits, (1, 0, 2))",
     "& True",
     ["tests/test_sched.py"], {}),
    # write-combined KV window (ISSUE 12): drop the flush's K-pool
    # scatter — staged K bytes never land, so after a drain the pool
    # serves zeros for flushed positions. Killed by the int8
    # quantize-on-flush parity test (token parity AND a byte-level
    # pool compare vs the per-token path — the float smoke model's
    # greedy argmax can shrug off zeroed K, the int8 path cannot).
    ("butterfly_tpu/cache/paged.py",
     "k_pages = cache.k_pages.at[:, flat_pages, :, flat_off].set(kv_vals)",
     "k_pages = cache.k_pages",
     ["tests/test_kv_quant.py", "tests/test_sched.py"], {}),
    # write-combined KV window, spec: flush without rollback truncation
    # — win_len advances by the full gamma+1 verify width instead of
    # the ACCEPTED count, so rejected drafts become attendable/flushable
    # and the window desynchronizes from the token history (killed by
    # the spec parity grid + the rejection-never-flushed pool probe)
    ("butterfly_tpu/engine/serving.py",
     "wlen = jnp.where(live, wlen + m, wlen)",
     "wlen = jnp.where(live, wlen + C, wlen)",
     ["tests/test_sched.py"], {}),
    # draft-model speculation (ISSUE 14): draft KV length advances by
    # the DRAFTED count (the γ+1 micro-step writes stay live) instead
    # of the accepted count — rejected drafts' K/V become attendable,
    # the draft desynchronizes from the history (wrong positions, wrong
    # context), and the draft_len == hist_len - 1 invariant breaks.
    # Killed by the draft spec parity-grid file's rollback-exactness
    # probe (tests/test_draft.py pins the invariant mid-flight on a
    # rejection-heavy prompt).
    ("butterfly_tpu/engine/serving.py",
     "return dstate._replace(length=jnp.where(live, dlen0 + m, dlen0))",
     "return dstate",
     ["tests/test_draft.py"], {}),
    # warm-prefix flash prefill (ISSUE 13): drop the prefix-length mask
    # — every row would attend the FULL cached-prefix block run,
    # including recycled-buffer garbage past its start, zero padding,
    # and (in serving) the chunk's own in-cache copy. Killed by the
    # kernel unit's garbage-past-start bit-compare and the dense-insert
    # parity checks in tests/test_warm_prefill.py.
    ("butterfly_tpu/ops/flash_attention.py",
     "mask = cols < start",
     "mask = cols >= 0",
     ["tests/test_warm_prefill.py"], {}),
    # flight recorder (ISSUE 15): weaken the SLO-burn trigger predicate
    # to threshold=inf — the anomaly post-mortem would silently never
    # fire on a burning error budget. Killed by the trigger tests in
    # tests/test_obs.py (poll at burn >= threshold must dump).
    ("butterfly_tpu/obs/ticklog.py",
     "if burn >= self.slo_burn_threshold and burn > 0.0:",
     'if burn >= float("inf") and burn > 0.0:',
     ["tests/test_obs.py"], {}),
    # alert rules (ISSUE 16): collapse the sustained-window guard so a
    # rule fires on a SINGLE above-threshold sample — every transient
    # blip would page. Killed by the alert-rule unit tests (one hot
    # sample must NOT fire; a full window must).
    ("butterfly_tpu/obs/timeseries.py",
     "if len(tail) < rule.window:",
     "if len(tail) < 1:",
     ["tests/test_timeseries.py"], {}),
    # workload generator: the Poisson arrival process ignores its rate
    # (every open-loop bench/sweep would silently offer ~1 req/s
    # regardless of the requested load) — the arrival-statistics test
    # must pin the mean inter-arrival to 1/rate
    ("butterfly_tpu/workload/arrivals.py",
     "dt = rng.expovariate(self.rate)",
     "dt = rng.expovariate(1.0)",
     ["tests/test_workload.py"], {}),
    # -- static-analyzer mutants (ISSUE 11): weaken one predicate per
    # rule; the fixture suite's EXACT positive counts must fail. The
    # checker is mutation-tested like the kernels — a rule that stops
    # firing must never pass silently.
    # BTF001: accept any keyword list as "has a timeout"
    ("tools/staticrules/http_timeout.py",
     'if any(kw.arg == "timeout" for kw in node.keywords):',
     "if node.keywords or not node.keywords:",
     ["tests/test_staticcheck.py"], {}),
    # BTF002: donating calls stop poisoning their arguments
    ("tools/staticrules/donation.py",
     "poison = poison | self._donated_handles(stmt)",
     "poison = poison | set()",
     ["tests/test_staticcheck.py"], {}),
    # BTF003: .item() dropped from the sync markers
    ("tools/staticrules/host_sync.py",
     'if name in ("item", "tolist", "block_until_ready") and \\',
     'if name in ("tolist", "block_until_ready") and \\',
     ["tests/test_staticcheck.py"], {}),
    # BTF004: every .acquire() counts as bounded
    ("tools/staticrules/locks.py",
     'if any(kw.arg == "timeout" for kw in node.keywords) or \\',
     "if (node.keywords is not None) or \\",
     ["tests/test_staticcheck.py"], {}),
    # BTF005: wall-clock reads allowed
    ("tools/staticrules/determinism.py",
     'if dotted == "time.time":',
     'if dotted == "time.time_never":',
     ["tests/test_staticcheck.py"], {}),
    # BTF006: key reuse never flagged
    ("tools/staticrules/prng.py",
     "if h in consumed or h in new:",
     "if h in consumed and h in new:",
     ["tests/test_staticcheck.py"], {}),
    # mixed dispatch (ISSUE 18): drop the prefill_inline_budget bound —
    # every waiting request would enter prefill phase at once, so one
    # fused scan step chews an unbounded number of prompt tokens while
    # every decode slot waits on that step's forward (exactly the ITL
    # tail the knob exists to cap). Killed by the inline-budget cap
    # test in tests/test_mixed_dispatch.py (concurrent prefill lanes
    # must never exceed prefill_inline_budget // chunk_width).
    ("butterfly_tpu/sched/scheduler.py",
     "self._mixed_max_pf = max(1, rt.prefill_inline_budget // self._mixed_chunk)",
     "self._mixed_max_pf = engine.num_slots",
     ["tests/test_mixed_dispatch.py"], {}),
    # elastic fleet (ISSUE 17): invert the scale-down hysteresis guard —
    # a shrink would be HELD only after the quiet window and allowed
    # inside it, so a grow->shrink->grow flap pays the warmup on every
    # cycle. Killed by the autoscaler unit grid (the hysteresis test
    # pins both branches: held inside the window, allowed after it).
    ("butterfly_tpu/fleet/autoscale.py",
     "if now - last < pol.cooldown_down_s:",
     "if now - last >= pol.cooldown_down_s:",
     ["tests/test_autoscale.py"], {}),
]


def run_tests(tests, extra_env) -> bool:
    """True if the covering tests PASS (i.e. the mutant survived)."""
    import os
    env = dict(os.environ, **extra_env)
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "-x", "-q", *tests],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=1200, env=env)
    return r.returncode == 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--list", action="store_true")
    args = ap.parse_args()
    if args.list:
        for f, orig, mut, tests, env in MUTANTS:
            print(f"{f}: {orig!r} -> {mut!r}  [{' '.join(tests)}] {env}")
        return 0

    dirty = subprocess.run(
        ["git", "diff", "--name-only"], cwd=REPO,
        capture_output=True, text=True).stdout.split()
    mutated_files = {m[0] for m in MUTANTS}
    if mutated_files & set(dirty):
        print(f"refusing to run: uncommitted changes in {mutated_files & set(dirty)}")
        return 2

    survived = []
    for i, (fname, orig, mut, tests, extra_env) in enumerate(MUTANTS):
        path = REPO / fname
        src = path.read_text()
        assert src.count(orig) == 1, f"ambiguous mutation site in {fname}"
        print(f"[{i + 1}/{len(MUTANTS)}] {fname}: {orig[:50]!r}...",
              flush=True)
        path.write_text(src.replace(orig, mut))
        try:
            if run_tests(tests, extra_env):
                survived.append((fname, orig))
                print("  SURVIVED — tests have a blind spot", flush=True)
            else:
                print("  killed", flush=True)
        finally:
            subprocess.run(["git", "checkout", "--", fname], cwd=REPO,
                           check=True)

    if survived:
        print(f"\n{len(survived)} mutant(s) survived:")
        for fname, orig in survived:
            print(f"  {fname}: {orig!r}")
        return 1
    print(f"\nall {len(MUTANTS)} mutants killed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
