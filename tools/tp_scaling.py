#!/usr/bin/env python
"""Tensor-parallel scaling model from compiled HLO (BASELINE.md metric:
"TP scaling efficiency 8 -> 64", VERDICT r4 missing item 3).

Real multi-chip runs are impossible in this environment (one tunneled
v5e chip), so the evidence is built the way the scaling-book recipe
says to reason about it: lower the ACTUAL decode/prefill programs over
fake-device meshes of growing `tensor` size, read the collectives XLA
inserted out of the optimized HLO (op kind + operand shapes -> bytes
moved per step), and combine with the v5e roofline numbers
(HBM 819 GB/s, one-way ICI ~ 45 GB/s/link on the 2D torus) into a
per-chip step-time model:

    t(tp) = max(weight_bytes/tp / HBM_BW, flops/tp / PEAK) + comm(tp)/ICI
    eff(tp) = t(1-chip work split ideally) / (tp * t(tp))

Collective payloads measured at tp in {2,4,8} extrapolate to 16..64:
Megatron TP moves 2 all-reduces of the [B,1,D] activation per layer
per step regardless of tp (ring all-reduce: each chip sends/receives
2*(tp-1)/tp * payload), so per-chip comm bytes are ~constant while
per-chip compute shrinks 1/tp — exactly the regime the table shows.

Usage: python tools/tp_scaling.py [--layers 2] [--batch 8]
Writes docs/tp_scaling_r5.md and prints the table.
"""
from __future__ import annotations

import argparse
import re
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

HBM_BW = 819e9          # v5e usable HBM bytes/s
PEAK_FLOPS = 197e12     # v5e bf16 dense peak
ICI_BW = 45e9           # v5e one-way per-link ICI bytes/s (2D torus)

DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "s8": 1, "u8": 1,
               "s32": 4, "u32": 4, "pred": 1, "f64": 8, "s64": 8}

COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
               "all-to-all", "collective-permute")


def collective_bytes(hlo: str) -> dict:
    """Sum output bytes per collective kind from optimized HLO text."""
    out = {k: 0 for k in COLLECTIVES}
    for line in hlo.splitlines():
        s = line.lstrip()
        if "=" not in s:
            continue
        lhs, rhs = s.split("=", 1)
        if "-done" in lhs:      # async pairs: count the -start only
            continue
        kind = next((k for k in COLLECTIVES if k in lhs), None)
        if kind is None:
            continue
        m = re.match(r"\s*\(?([a-z0-9]+)\[([0-9,]*)\]", rhs)
        if not m:
            continue
        dt, dims = m.groups()
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out[kind] += n * DTYPE_BYTES.get(dt, 4)
    return out


def measure_subprocess(tp: int, layers: int, batch: int, seq: int):
    """Run measure() in a child process: the CPU device count must be
    set before the backend initializes, so each mesh size needs a fresh
    interpreter."""
    import json
    import subprocess
    r = subprocess.run(
        [sys.executable, __file__, "--measure-tp", str(tp),
         "--layers", str(layers), "--batch", str(batch),
         "--seq", str(seq)],
        capture_output=True, text=True, timeout=900)
    if r.returncode != 0:
        raise RuntimeError(f"tp={tp} measurement failed:\n{r.stderr[-2000:]}")
    return json.loads(r.stdout.splitlines()[-1])


def measure(tp: int, layers: int, batch: int, seq: int):
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", max(tp, 1))
    import jax.numpy as jnp
    from butterfly_tpu.core.config import MeshConfig, llama3_8b
    from butterfly_tpu.core.mesh import make_mesh
    from butterfly_tpu.models.common import Model, forward, init_cache
    from butterfly_tpu.parallel.partition import (compiled_hlo, shard_cache,
                                                  shard_params)

    # Llama-3-8B LAYER geometry (the per-layer collectives are what
    # scale); a short stack keeps CPU compiles tractable and per-layer
    # numbers extrapolate exactly (collectives are per-layer identical).
    cfg = llama3_8b().replace(num_layers=layers, max_seq_len=seq,
                              dtype="float32", param_dtype="float32")
    model = Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    mesh = make_mesh(MeshConfig(tensor=tp)) if tp > 1 else None
    if mesh is not None:
        params = shard_params(params, cfg, mesh)
    cache = init_cache(cfg, batch, seq)
    if mesh is not None:
        cache = shard_cache(cache, cfg, mesh)
    tok1 = jnp.zeros((batch, 1), jnp.int32)

    def decode(p, t, c):
        return forward(p, cfg, t, c)

    hlo = compiled_hlo(decode, params, tok1, cache, mesh=mesh)
    return collective_bytes(hlo)


def model_row(tp: int, per_layer_ar_bytes: float, cfg_layers: int = 32,
              batch: int = 8):
    """Per-chip decode-step time model for Llama-3-8B int8 at `tp`."""
    weight_bytes = 8.03e9           # int8 weights (+scales) of record
    flops = 2 * 8.03e9 * batch
    comm = cfg_layers * per_layer_ar_bytes   # bytes each chip moves/step
    t_compute = max(weight_bytes / tp / HBM_BW, flops / tp / PEAK_FLOPS)
    t_comm = comm / ICI_BW
    t = t_compute + t_comm
    t1 = max(weight_bytes / HBM_BW, flops / PEAK_FLOPS)
    eff = t1 / (tp * t)
    return t_compute, t_comm, t, eff


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--out", default="docs/tp_scaling_r5.md")
    ap.add_argument("--measure-tp", type=int, default=0,
                    help="internal: measure one mesh size and print JSON")
    args = ap.parse_args()

    if args.measure_tp:
        import json
        print(json.dumps(measure(args.measure_tp, args.layers, args.batch,
                                 args.seq)))
        return 0

    rows = []
    for tp in (1, 2, 4, 8):
        b = measure_subprocess(tp, args.layers, args.batch, args.seq)
        rows.append((tp, b))
        print(f"tp={tp}: {b}", file=sys.stderr)

    # Megatron decode: 2 all-reduces/layer of the [B,1,D] activation.
    # Ring all-reduce per-chip traffic = 2*(tp-1)/tp * payload; HLO
    # reports the op's logical output bytes — convert per measured tp.
    per_layer = {}
    for tp, b in rows[1:]:
        ar = b["all-reduce"] / args.layers
        per_layer[tp] = ar * 2 * (tp - 1) / tp
    # extrapolate with the asymptote 2*payload (tp -> inf)
    payload = per_layer[8] / (2 * 7 / 8)

    lines = [
        "# TP scaling model — round 5 (HLO-derived, fake-device sweep)",
        "",
        "Built by `tools/tp_scaling.py`: the REAL decode program "
        "(models/common.forward, Llama-3-8B layer geometry, "
        f"{args.layers} layers, batch {args.batch}) is compiled over "
        "fake-device `tensor` meshes and the collectives XLA/GSPMD "
        "inserted are read back out of the optimized HLO.",
        "",
        "## Measured collective volume per decode step",
        "",
        "| tp | all-reduce B (HLO, total) | per layer | per-chip ring bytes/layer |",
        "|---|---|---|---|",
    ]
    for tp, b in rows:
        ar = b["all-reduce"]
        pl = ar / args.layers
        ring = pl * 2 * (tp - 1) / tp if tp > 1 else 0
        lines.append(f"| {tp} | {ar:,} | {pl:,.0f} | {ring:,.0f} |")
    lines += [
        "",
        f"Per-layer all-reduce payload: {payload:,.0f} B "
        f"([B,1,D] activation x 2 sublayers) — INDEPENDENT of tp, as "
        "Megatron row/column sharding predicts: per-chip comm is flat "
        "while per-chip compute shrinks 1/tp.",
        "",
        "## Projected Llama-3-8B int8 decode scaling (v5e roofline)",
        "",
        f"HBM {HBM_BW/1e9:.0f} GB/s, ICI one-way {ICI_BW/1e9:.0f} GB/s, "
        "bf16 peak 197 TF/s; t = max(weights/tp/HBM, flops/tp/peak) + "
        "comm/ICI (no overlap assumed — pessimistic).",
        "",
        "| tp | compute ms | comm ms | step ms | scaling efficiency |",
        "|---|---|---|---|---|",
    ]
    for tp in (1, 2, 4, 8, 16, 32, 64):
        ring = payload * 2 * (tp - 1) / tp if tp > 1 else 0.0
        tc, tm, t, eff = model_row(tp, ring, batch=args.batch)
        lines.append(f"| {tp} | {tc*1e3:.3f} | {tm*1e3:.3f} | "
                     f"{t*1e3:.3f} | {eff*100:.1f}% |")
    lines += [
        "",
        "Reading: 8 -> 64 chips the per-chip comm term is flat "
        "(~2x payload over the ring) while compute shrinks linearly, so "
        "efficiency decays only through the fixed comm floor; XLA's "
        "latency-hiding scheduler overlaps much of it in practice, so "
        "these are LOWER bounds. Validation on real multi-chip hardware "
        "is the remaining step (single tunneled chip here).",
        "",
    ]
    Path(args.out).write_text("\n".join(lines))
    print("\n".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
