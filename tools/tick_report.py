#!/usr/bin/env python
"""Render a dumped GET /debug/ticks body: the tick-anatomy report.

The software answer to "what are the top host terms in a serving tick"
(ROADMAP item 1) — a top-terms table of the structural tick phases
(total seconds, share of tick wall, p50/p95), the host/device wall
split, the per-cause barrier counts, and a reconciliation line proving
the phase sums account for the measured tick wall time.

stdlib-only (no jax, no numpy): runs anywhere, like trace_report.py.

Usage:  curl -s host:8000/debug/ticks > ticks.json
        python tools/tick_report.py ticks.json [--json]
        python tools/tick_report.py http://host:8000 --follow

``--follow`` polls ``GET /debug/ticks?since=<seq>`` incrementally —
each poll fetches only the ticks recorded since the last one (the
seq-paged ring contract) and renders them one line per tick, so a live
TPU sitting watches the tick anatomy without repeated full dumps.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from typing import Dict, List


#: one-line glossary for the structural phase vocabulary — the table's
#: top terms should be self-explaining in a report pasted into an issue
PHASE_NOTES = {
    "expire": "deadline scrub over waiting + running",
    "drain_oldest": "lazy drain of the oldest in-flight block",
    "drain_barrier": "FULL drain (membership change forced it)",
    "admit": "admission: slot grant + prompt staging",
    "assemble": "per-tick operand assembly for the batch",
    "dispatch": "alternating-path prefill/decode dispatch (seeing "
                "this with mixed_dispatch requested = the engine "
                "gated mixed off — stateful draft source or tree "
                "speculation; spec_mixed_fallback_total counts it "
                "and metrics() carries the reason line)",
    "mixed": "ONE fused dispatch: prefill chunks + decode/spec "
             "blocks together (mixed_dispatch, the default)",
    "spec_emit": "host accept/emit walk over drafted tokens",
    "flush": "write-combined KV window flush",
    "other": "unattributed residual of the tick wall",
}


def load_dump(path: str) -> dict:
    with open(path) as f:
        dump = json.load(f)
    if not isinstance(dump, dict) or "ticks" not in dump:
        raise ValueError(
            f"{path} is not a /debug/ticks dump (expected a JSON object "
            f"with a 'ticks' list)")
    return dump


def percentile(values: List[float], q: float) -> float:
    if not values:
        return 0.0
    s = sorted(values)
    idx = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return float(s[idx])


def phase_stats(dump: dict) -> dict:
    """Aggregate the dump: per-phase totals/percentiles (sorted by
    total, descending — the top-terms order), wall/fetch totals, and
    barrier-cause counts."""
    ticks = dump.get("ticks", [])
    series: Dict[str, List[float]] = {}
    wall_total = 0.0
    fetch_total = 0.0
    causes: Dict[str, int] = {}
    for t in ticks:
        wall_total += t.get("wall_s", 0.0)
        fetch_total += t.get("fetch_s", 0.0)
        for name, v in t.get("phases", {}).items():
            series.setdefault(name, []).append(v)
        for c in t.get("barrier_causes", ()):
            causes[c] = causes.get(c, 0) + 1
    phases = [{"phase": name,
               "total_s": sum(vals),
               "share": (sum(vals) / wall_total) if wall_total else 0.0,
               "p50_s": percentile(vals, 50),
               "p95_s": percentile(vals, 95)}
              for name, vals in series.items()]
    phases.sort(key=lambda p: -p["total_s"])
    phase_sum = sum(p["total_s"] for p in phases)
    return {
        "ticks": len(ticks),
        "wall_total_s": wall_total,
        "phase_total_s": phase_sum,
        # phase sums / tick wall: ~1.0 means the attribution accounts
        # for the measured time (the acceptance property, +-10%)
        "reconciliation": (phase_sum / wall_total) if wall_total else 1.0,
        "host_frac": ((wall_total - fetch_total) / wall_total)
        if wall_total else 0.0,
        "device_frac": (fetch_total / wall_total) if wall_total else 0.0,
        "phases": phases,
        "barrier_causes": causes,
    }


def render(dump: dict) -> str:
    s = phase_stats(dump)
    lines = []
    lines.append(f"{s['ticks']} tick(s), {s['wall_total_s']:.4f}s wall "
                 f"(next_seq={dump.get('next_seq', '?')}, "
                 f"ring capacity {dump.get('capacity', '?')})")
    lines.append(f"host {100 * s['host_frac']:.1f}% / device-fetch "
                 f"{100 * s['device_frac']:.1f}% of tick wall")
    lines.append("")
    lines.append(f"{'phase':>14} {'total_s':>10} {'share':>7} "
                 f"{'p50_s':>10} {'p95_s':>10}  note")
    for p in s["phases"]:
        lines.append(f"{p['phase']:>14} {p['total_s']:>10.4f} "
                     f"{100 * p['share']:>6.1f}% "
                     f"{p['p50_s']:>10.5f} {p['p95_s']:>10.5f}  "
                     f"{PHASE_NOTES.get(p['phase'], '')}")
    lines.append("")
    lines.append(f"phase sums account for "
                 f"{100 * s['reconciliation']:.1f}% of tick wall")
    if s["barrier_causes"]:
        lines.append("")
        lines.append("full drain barriers by cause:")
        for cause, n in sorted(s["barrier_causes"].items(),
                               key=lambda kv: -kv[1]):
            lines.append(f"  {cause:>14} {n}")
    else:
        lines.append("no full drain barriers in the window")
    return "\n".join(lines)


def tick_line(t: dict) -> str:
    """One incremental --follow line per tick: seq, wall, the dominant
    phase of THIS tick, pipeline depth, occupancy, page headroom."""
    phases = t.get("phases", {})
    timed = {k: v for k, v in phases.items() if k != "other"}
    dom = max(timed, key=timed.get) if timed else "-"
    causes = ",".join(t.get("barrier_causes", ())) or "-"
    return (f"tick {t.get('seq', '?'):>7} {t.get('wall_s', 0.0):>9.4f}s "
            f"dom={dom}:{timed.get(dom, 0.0):.4f}s "
            f"fetch={t.get('fetch_s', 0.0):.4f}s "
            f"batch={t.get('batch', 0)} wait={t.get('waiting', 0)} "
            f"inflight={t.get('inflight', 0)} "
            f"pages={t.get('pages_free', 0)} "
            f"gen={t.get('generated', 0)} barriers={causes}")


def follow(url: str, interval: float, timeout: float,
           max_polls: int = 0) -> int:
    """Poll GET /debug/ticks?since=<seq> and render new ticks as they
    land. `max_polls` bounds the loop for scripted runs (0 = forever).
    """
    base = url.rstrip("/")
    since = 0
    polls = 0
    while True:
        try:
            with urllib.request.urlopen(
                    f"{base}/debug/ticks?since={since}",
                    timeout=timeout) as resp:
                dump = json.loads(resp.read() or b"{}")
        except Exception as e:  # server restarting: report, keep polling
            print(f"poll error: {type(e).__name__}: {e}",
                  file=sys.stderr)
            dump = {}
        for t in dump.get("ticks", ()):
            print(tick_line(t), flush=True)
        since = max(since, int(dump.get("next_seq", since)))
        polls += 1
        if max_polls and polls >= max_polls:
            return 0
        time.sleep(interval)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render a dumped GET /debug/ticks body")
    ap.add_argument("dump", help="JSON file (the /debug/ticks body), "
                                 "or the server base URL with --follow")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable aggregate instead of the table")
    ap.add_argument("--follow", action="store_true",
                    help="poll /debug/ticks?since=seq incrementally "
                         "(dump is the base URL, e.g. http://host:8000)")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="--follow poll interval in seconds")
    ap.add_argument("--timeout", type=float, default=5.0,
                    help="--follow per-poll HTTP timeout in seconds")
    ap.add_argument("--max-polls", type=int, default=0,
                    help="--follow: stop after N polls (0 = forever)")
    args = ap.parse_args(argv)
    if args.follow:
        return follow(args.dump, args.interval, args.timeout,
                      max_polls=args.max_polls)
    try:
        dump = load_dump(args.dump)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(phase_stats(dump)))
    else:
        print(render(dump))
    return 0


if __name__ == "__main__":
    sys.exit(main())
