#!/usr/bin/env python3
"""Closed-loop multi-client load generator for a serve replica or router.

stdlib-only (urllib + threading — no jax, no backend): each of
``--clients`` worker threads keeps exactly ONE request in flight (issue,
wait for the full response, repeat), the closed-loop shape that exercises
continuous batching without open-loop queue explosion.

``--prefix-share R`` is the affinity workload knob: fraction of requests
whose token prompt begins with a SHARED ``--shared-len``-token prefix
(the "same system prompt" population). Pointed at a router, a high share
should concentrate those requests on one replica and raise its
prefix-cache hit counters; pointed straight at a replica it measures
prefix-caching TTFT wins.

Importable by tests (``run_load``) and runnable standalone:

    python tools/loadgen.py --url http://127.0.0.1:8100 \
        --clients 8 --requests 16 --prefix-share 0.5 --json
"""
from __future__ import annotations

import argparse
import json
import random
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional


def _percentile(xs: List[float], p: float) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    k = min(len(s) - 1, max(0, int(round(p / 100 * (len(s) - 1)))))
    return s[k]


def shared_prefix(shared_len: int, seed: int = 0,
                  vocab: int = 64) -> List[int]:
    """The deterministic shared-prefix token block (page-aligned lengths
    make it land whole pages in the replicas' prefix caches)."""
    rng = random.Random(10_000 + seed)
    return [rng.randrange(1, vocab) for _ in range(shared_len)]


def run_load(url: str, clients: int = 4, requests_per_client: int = 8,
             prefix_share: float = 0.5, shared_len: int = 32,
             tail_len: int = 8, max_tokens: int = 8, seed: int = 0,
             vocab: int = 64, path: str = "/generate",
             timeout: float = 120.0) -> Dict:
    """Drive `url` closed-loop; returns aggregate stats.

    Every request uses token-id prompts (deterministic, tokenizer-free).
    A `prefix_share` fraction starts with the shared prefix plus a
    per-request tail; the rest are fully private prompts of the same
    total length, so the two populations differ only in shareability.
    """
    prefix = shared_prefix(shared_len, seed, vocab)
    lock = threading.Lock()
    latencies: List[float] = []
    shared_latencies: List[float] = []
    by_replica: Dict[str, int] = {}
    errors: List[str] = []
    counts = {"sent": 0, "ok": 0, "shared": 0}

    def one_client(cid: int) -> None:
        rng = random.Random(seed * 1000 + cid)
        for i in range(requests_per_client):
            is_shared = rng.random() < prefix_share
            tail = [rng.randrange(1, vocab) for _ in range(tail_len)]
            tokens = (prefix + tail) if is_shared else \
                [rng.randrange(1, vocab)
                 for _ in range(shared_len + tail_len)]
            body = json.dumps({
                "tokens": tokens, "max_tokens": max_tokens,
                "stop_token": -1,
                "request_id": f"loadgen-{cid}-{i}"}).encode()
            req = urllib.request.Request(
                url + path, data=body,
                headers={"Content-Type": "application/json"})
            t0 = time.monotonic()
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    resp.read()
                    routed = resp.headers.get("X-Routed-To")
                dt = time.monotonic() - t0
                with lock:
                    counts["sent"] += 1
                    counts["ok"] += 1
                    counts["shared"] += int(is_shared)
                    latencies.append(dt)
                    if is_shared:
                        shared_latencies.append(dt)
                    if routed:
                        by_replica[routed] = by_replica.get(routed, 0) + 1
            except (urllib.error.URLError, OSError) as e:
                with lock:
                    counts["sent"] += 1
                    errors.append(f"client{cid}#{i}: {e}")

    t_start = time.monotonic()
    threads = [threading.Thread(target=one_client, args=(c,))
               for c in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t_start
    return {
        "sent": counts["sent"], "ok": counts["ok"],
        "failed": counts["sent"] - counts["ok"],
        "shared_prefix_requests": counts["shared"],
        "wall_s": wall,
        "rps": counts["ok"] / wall if wall > 0 else 0.0,
        "latency_p50_s": _percentile(latencies, 50),
        "latency_p95_s": _percentile(latencies, 95),
        "shared_latency_p50_s": _percentile(shared_latencies, 50),
        "by_replica": by_replica,
        "errors": errors[:20],
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="closed-loop load generator for butterfly serve/route")
    ap.add_argument("--url", required=True,
                    help="base URL, e.g. http://127.0.0.1:8100")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests", type=int, default=8,
                    help="requests per client")
    ap.add_argument("--prefix-share", type=float, default=0.5)
    ap.add_argument("--shared-len", type=int, default=32)
    ap.add_argument("--tail-len", type=int, default=8)
    ap.add_argument("--max-tokens", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--path", default="/generate")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)
    stats = run_load(args.url, clients=args.clients,
                     requests_per_client=args.requests,
                     prefix_share=args.prefix_share,
                     shared_len=args.shared_len, tail_len=args.tail_len,
                     max_tokens=args.max_tokens, seed=args.seed,
                     path=args.path)
    if args.json:
        print(json.dumps(stats, indent=2))
    else:
        print(f"sent={stats['sent']} ok={stats['ok']} "
              f"failed={stats['failed']} rps={stats['rps']:.2f}")
        print(f"latency p50={stats['latency_p50_s'] * 1e3:.1f}ms "
              f"p95={stats['latency_p95_s'] * 1e3:.1f}ms")
        if stats["by_replica"]:
            print("by replica: " + ", ".join(
                f"{rid}={n}" for rid, n in
                sorted(stats["by_replica"].items())))
        for e in stats["errors"]:
            print(f"error: {e}", file=sys.stderr)
    return 0 if stats["failed"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
