#!/usr/bin/env python3
"""Closed-loop multi-client load generator for a serve replica or router.

stdlib-only (urllib + threading — no jax, no backend): each of
``--clients`` worker threads keeps exactly ONE request in flight (issue,
wait for the full response, repeat), the closed-loop shape that exercises
continuous batching without open-loop queue explosion.

``--prefix-share R`` is the affinity workload knob: fraction of requests
whose token prompt begins with a SHARED ``--shared-len``-token prefix
(the "same system prompt" population). Pointed at a router, a high share
should concentrate those requests on one replica and raise its
prefix-cache hit counters; pointed straight at a replica it measures
prefix-caching TTFT wins.

``--soak`` is the fleet mode: while the closed-loop load runs, every
replica behind the router/control plane is rolled through
drain -> (restart) -> undrain in sequence (``run_fleet_soak``); the
pass property is zero dropped un-started requests, and against a
disaggregated control plane the result also carries the
/fleet/state transfer counters (kv_transfer_hit_rate, bytes, the
disagg/direct split) and client-observed TTFT percentiles.

``--slo-ttft-ms`` / ``--slo-itl-ms`` declare latency objectives: every
request is judged client-side (TTFT and per-request mean ITL from the
response body) and the summary reports ``slo_attainment`` — the
fraction of successful requests that met every declared objective,
the client-observed twin of the servers' slo_* counters.

Importable by tests (``run_load`` / ``run_fleet_soak``) and runnable
standalone:

    python tools/loadgen.py --url http://127.0.0.1:8100 \
        --clients 8 --requests 16 --prefix-share 0.5 --json
"""
from __future__ import annotations

import argparse
import json
import random
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional


def _percentile(xs: List[float], p: float) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    k = min(len(s) - 1, max(0, int(round(p / 100 * (len(s) - 1)))))
    return s[k]


def shared_prefix(shared_len: int, seed: int = 0,
                  vocab: int = 64) -> List[int]:
    """The deterministic shared-prefix token block (page-aligned lengths
    make it land whole pages in the replicas' prefix caches)."""
    rng = random.Random(10_000 + seed)
    return [rng.randrange(1, vocab) for _ in range(shared_len)]


def run_load(url: str, clients: int = 4, requests_per_client: int = 8,
             prefix_share: float = 0.5, shared_len: int = 32,
             tail_len: int = 8, max_tokens: int = 8, seed: int = 0,
             vocab: int = 64, path: str = "/generate",
             timeout: float = 120.0,
             slo_ttft_ms: Optional[float] = None,
             slo_itl_ms: Optional[float] = None,
             deadline_ms: Optional[float] = None,
             priority: Optional[str] = None,
             speculative: Optional[bool] = None) -> Dict:
    """Drive `url` closed-loop; returns aggregate stats.

    Every request uses token-id prompts (deterministic, tokenizer-free).
    A `prefix_share` fraction starts with the shared prefix plus a
    per-request tail; the rest are fully private prompts of the same
    total length, so the two populations differ only in shareability.

    With declared objectives (`slo_ttft_ms` / `slo_itl_ms`) every
    request is judged CLIENT-SIDE against them — TTFT from the body's
    `ttft_s`, mean ITL from `(total_s - ttft_s)/(tokens - 1)` — and the
    summary carries `slo_attainment`, the fraction of OK responses that
    met every declared objective (a response missing the fields it
    needs counts as a miss: the client couldn't verify its SLO).

    `deadline_ms` stamps a latency budget on every request (the server
    504s whatever blows it); `priority` tags the admission class
    ('interactive'/'batch'; batch sheds first under load). The summary's
    `outcomes` dict is the TERMINAL-OUTCOME breakdown — ok / shed_429 /
    deadline_504 / error — so a soak shows shedding and expiry instead
    of hiding them inside `failed`; `terminal` counts requests that got
    ANY definitive answer (everything but transport errors/hangs)."""
    prefix = shared_prefix(shared_len, seed, vocab)
    lock = threading.Lock()
    latencies: List[float] = []
    ttfts: List[float] = []
    shared_latencies: List[float] = []
    by_replica: Dict[str, int] = {}
    errors: List[str] = []
    counts = {"sent": 0, "ok": 0, "shared": 0, "disaggregated": 0,
              "slo_ok": 0, "slo_ttft_ok": 0, "slo_itl_ok": 0}
    outcomes = {"ok": 0, "shed_429": 0, "deadline_504": 0, "error": 0}
    slo_declared = slo_ttft_ms is not None or slo_itl_ms is not None

    def one_client(cid: int) -> None:
        rng = random.Random(seed * 1000 + cid)
        for i in range(requests_per_client):
            is_shared = rng.random() < prefix_share
            tail = [rng.randrange(1, vocab) for _ in range(tail_len)]
            tokens = (prefix + tail) if is_shared else \
                [rng.randrange(1, vocab)
                 for _ in range(shared_len + tail_len)]
            payload = {
                "tokens": tokens, "max_tokens": max_tokens,
                "stop_token": -1,
                "request_id": f"loadgen-{cid}-{i}"}
            if deadline_ms is not None:
                payload["deadline_ms"] = deadline_ms
            if priority is not None:
                payload["priority"] = priority
            if speculative is not None:
                payload["speculative"] = speculative
            body = json.dumps(payload).encode()
            req = urllib.request.Request(
                url + path, data=body,
                headers={"Content-Type": "application/json"})
            t0 = time.monotonic()
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    raw = resp.read()
                    routed = resp.headers.get("X-Routed-To")
                dt = time.monotonic() - t0
                try:  # /generate bodies carry ttft_s (replica-measured
                    # direct, control-plane-measured across a
                    # disaggregated handoff) + the handoff marker
                    obj = json.loads(raw or b"{}")
                    ttft = obj.get("ttft_s")
                    disagg = bool(obj.get("disaggregated"))
                    n_toks = len(obj.get("tokens") or ())
                    total = obj.get("total_s")
                except (ValueError, AttributeError):
                    ttft, disagg, n_toks, total = None, False, 0, None
                # client-side SLO verdicts for this request
                ttft_ok = itl_ok = True
                if slo_ttft_ms is not None:
                    ttft_ok = isinstance(ttft, (int, float)) \
                        and ttft * 1e3 <= slo_ttft_ms
                if slo_itl_ms is not None and n_toks > 1 \
                        and isinstance(ttft, (int, float)) \
                        and isinstance(total, (int, float)):
                    itl_ok = ((total - ttft) / (n_toks - 1)
                              * 1e3 <= slo_itl_ms)
                elif slo_itl_ms is not None and (
                        not isinstance(total, (int, float))):
                    itl_ok = False
                with lock:
                    counts["sent"] += 1
                    counts["ok"] += 1
                    outcomes["ok"] += 1
                    counts["shared"] += int(is_shared)
                    counts["disaggregated"] += int(disagg)
                    if slo_declared:
                        counts["slo_ttft_ok"] += int(ttft_ok)
                        counts["slo_itl_ok"] += int(itl_ok)
                        counts["slo_ok"] += int(ttft_ok and itl_ok)
                    latencies.append(dt)
                    if isinstance(ttft, (int, float)):
                        ttfts.append(float(ttft))
                    if is_shared:
                        shared_latencies.append(dt)
                    if routed:
                        by_replica[routed] = by_replica.get(routed, 0) + 1
            except urllib.error.HTTPError as e:
                # an HTTP error IS a terminal outcome: the server
                # answered definitively. 429 = shed/backpressure,
                # 504 = deadline exceeded; anything else is a fault.
                try:
                    e.read()
                except OSError:
                    pass
                e.close()
                with lock:
                    counts["sent"] += 1
                    if e.code == 429:
                        outcomes["shed_429"] += 1
                    elif e.code == 504:
                        outcomes["deadline_504"] += 1
                    else:
                        outcomes["error"] += 1
                        errors.append(f"client{cid}#{i}: http {e.code}")
            except (urllib.error.URLError, OSError) as e:
                with lock:
                    counts["sent"] += 1
                    outcomes["error"] += 1
                    errors.append(f"client{cid}#{i}: {e}")

    t_start = time.monotonic()
    threads = [threading.Thread(target=one_client, args=(c,))
               for c in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t_start
    return {
        "sent": counts["sent"], "ok": counts["ok"],
        "failed": counts["sent"] - counts["ok"],
        # terminal-outcome breakdown: every sent request lands in
        # exactly one bucket; `terminal` excludes only transport
        # errors/hangs — the chaos soak's zero-hang property is
        # terminal == sent with outcomes["error"] == 0
        "outcomes": dict(outcomes),
        "terminal": outcomes["ok"] + outcomes["shed_429"]
                    + outcomes["deadline_504"],
        "shared_prefix_requests": counts["shared"],
        "disaggregated": counts["disaggregated"],
        "wall_s": wall,
        "rps": counts["ok"] / wall if wall > 0 else 0.0,
        "latency_p50_s": _percentile(latencies, 50),
        "latency_p95_s": _percentile(latencies, 95),
        "ttft_p50_s": _percentile(ttfts, 50),
        "ttft_p95_s": _percentile(ttfts, 95),
        "shared_latency_p50_s": _percentile(shared_latencies, 50),
        "by_replica": by_replica,
        "errors": errors[:20],
        "slo_ttft_ms": slo_ttft_ms,
        "slo_itl_ms": slo_itl_ms,
        "slo_attainment": (counts["slo_ok"] / counts["ok"]
                           if slo_declared and counts["ok"] else None),
        "slo_ttft_ok": counts["slo_ttft_ok"] if slo_declared else None,
        "slo_itl_ok": counts["slo_itl_ok"] if slo_declared else None,
    }


def _get_json(url: str, path: str, timeout: float = 10.0) -> Dict:
    with urllib.request.urlopen(url + path, timeout=timeout) as resp:
        return json.loads(resp.read() or b"{}")


def _post_json(url: str, path: str, obj: Dict, timeout: float = 10.0) -> Dict:
    req = urllib.request.Request(
        url + path, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read() or b"{}")


def _wait_drained(url: str, rid: str, timeout: float = 30.0) -> bool:
    """Poll the router snapshot until `rid` has zero outstanding
    proxied requests (its in-flight work finished; only NEW requests
    were being refused by the drain)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        snaps = _get_json(url, "/router/replicas").get("replicas", [])
        me = next((s for s in snaps if s["replica"] == rid), None)
        if me is not None and int(me.get("outstanding", 0)) == 0:
            return True
        time.sleep(0.05)
    return False


def run_fleet_soak(url: str, clients: int = 4,
                   requests_per_client: int = 8,
                   prefix_share: float = 0.5, shared_len: int = 32,
                   tail_len: int = 8, max_tokens: int = 8, seed: int = 0,
                   vocab: int = 64, timeout: float = 120.0,
                   replicas: Optional[List[str]] = None,
                   restart_hook=None, settle_s: float = 0.3,
                   slo_ttft_ms: Optional[float] = None,
                   slo_itl_ms: Optional[float] = None,
                   deadline_ms: Optional[float] = None,
                   priority: Optional[str] = None,
                   speculative: Optional[bool] = None) -> Dict:
    """Fleet soak: closed-loop load against a control plane WHILE every
    replica is rolled through drain -> (restart) -> undrain, one at a
    time. The pass/fail property is the router tier's: zero dropped
    un-started requests — a drained/restarting replica stops receiving
    new work, its in-flight work finishes, and the rest of the fleet
    absorbs the traffic.

    `restart_hook(rid)` (optional) bounces the replica between drain
    and undrain — the in-process harness passes
    ``fleet.by_rid[rid].restart``; against a real deployment the
    operator's supervisor plays that part. Returns the load stats plus
    the control plane's /fleet/state counters (kv_transfer_hit_rate,
    transfer bytes/pages, disagg/direct split) and the rolling-cycle
    log."""
    if replicas is None:
        replicas = [s["replica"] for s in
                    _get_json(url, "/router/replicas").get("replicas", [])]
    result: Dict = {}

    def _load():
        result.update(run_load(
            url, clients=clients, requests_per_client=requests_per_client,
            prefix_share=prefix_share, shared_len=shared_len,
            tail_len=tail_len, max_tokens=max_tokens, seed=seed,
            vocab=vocab, timeout=timeout, slo_ttft_ms=slo_ttft_ms,
            slo_itl_ms=slo_itl_ms, deadline_ms=deadline_ms,
            priority=priority, speculative=speculative))

    t = threading.Thread(target=_load)
    t.start()
    cycles = []
    for rid in replicas:
        cycle = {"replica": rid}
        _post_json(url, "/router/drain", {"replica": rid})
        cycle["drained"] = _wait_drained(url, rid)
        if restart_hook is not None:
            restart_hook(rid)
            cycle["restarted"] = True
        time.sleep(settle_s)
        _post_json(url, "/router/undrain", {"replica": rid})
        cycles.append(cycle)
        if t.is_alive():
            time.sleep(settle_s)
    t.join()
    result["rolling_cycles"] = cycles
    try:  # a plain (non-fleet) router has no /fleet/state — soak still valid
        state = _get_json(url, "/fleet/state")
        result["fleet_metrics"] = state.get("metrics", {})
        result["fleet_tiers"] = state.get("tiers", {})
    except (urllib.error.URLError, OSError, ValueError):
        pass
    return result


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="closed-loop load generator for butterfly serve/route")
    ap.add_argument("--url", required=True,
                    help="base URL, e.g. http://127.0.0.1:8100")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests", type=int, default=8,
                    help="requests per client")
    ap.add_argument("--prefix-share", type=float, default=0.5)
    ap.add_argument("--shared-len", type=int, default=32)
    ap.add_argument("--tail-len", type=int, default=8)
    ap.add_argument("--max-tokens", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--path", default="/generate")
    ap.add_argument("--slo-ttft-ms", type=float, default=None,
                    help="declared TTFT objective: judge every request "
                         "client-side and report slo_attainment")
    ap.add_argument("--slo-itl-ms", type=float, default=None,
                    help="declared mean inter-token-latency objective "
                         "(per request), judged client-side")
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="stamp this latency budget (deadline_ms) on "
                         "every request; the server answers 504 for "
                         "whatever blows it — the summary's outcomes "
                         "dict shows the deadline_504 count")
    ap.add_argument("--priority", choices=["interactive", "batch"],
                    default=None,
                    help="admission class tag: 'batch' is shed first "
                         "when SLO-aware admission is active")
    ap.add_argument("--speculative", choices=["on", "off"], default=None,
                    help="stamp \"speculative\": true/false on every "
                         "request (per-request opt-in/out of draft "
                         "acceptance on a `serve --speculate` replica; "
                         "omit to leave the server default)")
    ap.add_argument("--soak", action="store_true",
                    help="fleet soak mode: roll every replica through "
                         "drain/undrain (discovered via "
                         "/router/replicas) while the load runs; "
                         "requires --url to be a router or fleet "
                         "control plane")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)
    if args.soak:
        stats = run_fleet_soak(args.url, clients=args.clients,
                               requests_per_client=args.requests,
                               prefix_share=args.prefix_share,
                               shared_len=args.shared_len,
                               tail_len=args.tail_len,
                               max_tokens=args.max_tokens, seed=args.seed,
                               slo_ttft_ms=args.slo_ttft_ms,
                               slo_itl_ms=args.slo_itl_ms,
                               deadline_ms=args.deadline_ms,
                               priority=args.priority,
                               speculative=(None if args.speculative is None
                                            else args.speculative == "on"))
    else:
        stats = run_load(args.url, clients=args.clients,
                         requests_per_client=args.requests,
                         prefix_share=args.prefix_share,
                         shared_len=args.shared_len, tail_len=args.tail_len,
                         max_tokens=args.max_tokens, seed=args.seed,
                         path=args.path, slo_ttft_ms=args.slo_ttft_ms,
                         slo_itl_ms=args.slo_itl_ms,
                         deadline_ms=args.deadline_ms,
                         priority=args.priority,
                         speculative=(None if args.speculative is None
                                      else args.speculative == "on"))
    if args.json:
        print(json.dumps(stats, indent=2))
    else:
        print(f"sent={stats['sent']} ok={stats['ok']} "
              f"failed={stats['failed']} rps={stats['rps']:.2f}")
        o = stats["outcomes"]
        print(f"outcomes: ok={o['ok']} shed_429={o['shed_429']} "
              f"deadline_504={o['deadline_504']} error={o['error']} "
              f"(terminal {stats['terminal']}/{stats['sent']})")
        print(f"latency p50={stats['latency_p50_s'] * 1e3:.1f}ms "
              f"p95={stats['latency_p95_s'] * 1e3:.1f}ms")
        if stats.get("slo_attainment") is not None:
            print(f"slo attainment={stats['slo_attainment']:.3f} "
                  f"(ttft_ok={stats['slo_ttft_ok']}/{stats['ok']}, "
                  f"itl_ok={stats['slo_itl_ok']}/{stats['ok']})")
        if stats["by_replica"]:
            print("by replica: " + ", ".join(
                f"{rid}={n}" for rid, n in
                sorted(stats["by_replica"].items())))
        for e in stats["errors"]:
            print(f"error: {e}", file=sys.stderr)
    # sheds and deadline 504s are terminal outcomes the run ASKED for
    # (backpressure working as designed) — only transport errors/hangs
    # and 5xx faults fail the run
    return 0 if stats["outcomes"]["error"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
