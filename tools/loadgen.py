#!/usr/bin/env python3
"""Load generator for a serve replica, router, or fleet control plane.

stdlib-only (urllib + threading — no jax, no backend). Two drive modes:

* **Closed loop** (default): each of ``--clients`` worker threads keeps
  exactly ONE request in flight (issue, wait, repeat) — exercises
  continuous batching without open-loop queue explosion. The offered
  rate is throttled by the server's own latency, so this mode can
  never really force queue growth, shedding, or preemption.
* **Open loop** (``--workload NAME`` or ``--trace FILE``): requests
  fire on an absolute arrival schedule (``--arrival poisson:8``,
  ``burst:...``, ``ramp:...`` — butterfly_tpu/workload/arrivals.py)
  regardless of how earlier requests are faring. This is the
  admission-control regime: load is no longer bounded by client count,
  so the queue, the shed path, and the page pool actually get tested.
  ``--save trace.jsonl`` persists the generated trace for replay.

Request firing and judging live in ``fire_one`` + ``Collector`` and are
shared by both modes AND by the workload replay driver
(butterfly_tpu/workload/replay.py) — one accounting implementation,
every summary the same shape.

Every summary also scrapes the target's ``/metrics`` after the run and
folds the server-side counters (``serving_preemptions``, ``shed_total``,
``deadline_expired_total``) in under ``server``, so client-observed and
server-counted outcomes are checked against each other in one artifact.

``--prefix-share R`` (closed loop) is the affinity workload knob:
fraction of requests whose token prompt begins with a SHARED
``--shared-len``-token prefix. ``--soak`` is the fleet mode: while the
closed-loop load runs, every replica behind the router/control plane is
rolled through drain -> (restart) -> undrain (``run_fleet_soak``).
``--slo-ttft-ms`` / ``--slo-itl-ms`` declare latency objectives judged
client-side per request (``slo_attainment`` in the summary).

Importable by tests (``run_load`` / ``run_fleet_soak`` / ``fire_one`` /
``Collector``) and runnable standalone:

    python tools/loadgen.py --url http://127.0.0.1:8100 \
        --clients 8 --requests 16 --prefix-share 0.5 --json
    python tools/loadgen.py --url http://127.0.0.1:8100 \
        --workload mixed_chat --n 64 --arrival burst:20:0.5:2 --json

The closed-loop path stays jax-free; the open-loop path imports
butterfly_tpu.workload (stdlib itself, but the package import pulls the
usual butterfly_tpu deps).
"""
from __future__ import annotations

import argparse
import json
import random
import re
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional


def _percentile(xs: List[float], p: float) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    k = min(len(s) - 1, max(0, int(round(p / 100 * (len(s) - 1)))))
    return s[k]


def shared_prefix(shared_len: int, seed: int = 0,
                  vocab: int = 64) -> List[int]:
    """The deterministic shared-prefix token block (page-aligned lengths
    make it land whole pages in the replicas' prefix caches)."""
    rng = random.Random(10_000 + seed)
    return [rng.randrange(1, vocab) for _ in range(shared_len)]


class Collector:
    """Thread-safe per-request outcome accounting shared by the
    closed-loop clients, the fleet soak, and the open-loop trace
    replay (workload/replay.py) — TTFT/ITL/SLO verdicts and the
    terminal-outcome breakdown live HERE, once.

    Outcome semantics: an HTTP error IS a terminal outcome (the server
    answered definitively) — 429 = shed/backpressure, 504 = deadline
    exceeded; anything else is a fault. `terminal` counts requests that
    got ANY definitive answer; the zero-hang property of a soak is
    terminal == sent with outcomes["error"] == 0.
    """

    def __init__(self, slo_ttft_ms: Optional[float] = None,
                 slo_itl_ms: Optional[float] = None):
        self.lock = threading.Lock()
        self.slo_ttft_ms = slo_ttft_ms
        self.slo_itl_ms = slo_itl_ms
        self.slo_declared = slo_ttft_ms is not None or slo_itl_ms is not None
        self.latencies: List[float] = []
        self.ttfts: List[float] = []
        self.shared_latencies: List[float] = []
        self.by_replica: Dict[str, int] = {}
        self.errors: List[str] = []
        self.counts = {"sent": 0, "ok": 0, "shared": 0, "disaggregated": 0,
                       "slo_ok": 0, "slo_ttft_ok": 0, "slo_itl_ok": 0}
        self.outcomes = {"ok": 0, "shed_429": 0, "deadline_504": 0,
                         "error": 0}

    def record_ok(self, dt: float, ttft, total, n_toks: int,
                  routed: Optional[str], disagg: bool,
                  shared: bool = False) -> None:
        # client-side SLO verdicts for this request: a response missing
        # the fields its verdict needs counts as a miss — the client
        # couldn't verify its SLO
        ttft_ok = itl_ok = True
        if self.slo_ttft_ms is not None:
            ttft_ok = isinstance(ttft, (int, float)) \
                and ttft * 1e3 <= self.slo_ttft_ms
        if self.slo_itl_ms is not None and n_toks > 1 \
                and isinstance(ttft, (int, float)) \
                and isinstance(total, (int, float)):
            itl_ok = ((total - ttft) / (n_toks - 1)
                      * 1e3 <= self.slo_itl_ms)
        elif self.slo_itl_ms is not None and (
                not isinstance(total, (int, float))):
            itl_ok = False
        with self.lock:
            self.counts["sent"] += 1
            self.counts["ok"] += 1
            self.outcomes["ok"] += 1
            self.counts["shared"] += int(shared)
            self.counts["disaggregated"] += int(disagg)
            if self.slo_declared:
                self.counts["slo_ttft_ok"] += int(ttft_ok)
                self.counts["slo_itl_ok"] += int(itl_ok)
                self.counts["slo_ok"] += int(ttft_ok and itl_ok)
            self.latencies.append(dt)
            if isinstance(ttft, (int, float)):
                self.ttfts.append(float(ttft))
            if shared:
                self.shared_latencies.append(dt)
            if routed:
                self.by_replica[routed] = self.by_replica.get(routed, 0) + 1

    def record_http_error(self, code: int, label: str) -> None:
        with self.lock:
            self.counts["sent"] += 1
            if code == 429:
                self.outcomes["shed_429"] += 1
            elif code == 504:
                self.outcomes["deadline_504"] += 1
            else:
                self.outcomes["error"] += 1
                self.errors.append(f"{label}: http {code}")

    def record_transport_error(self, err, label: str) -> None:
        with self.lock:
            self.counts["sent"] += 1
            self.outcomes["error"] += 1
            self.errors.append(f"{label}: {err}")

    def summary(self, wall: float) -> Dict:
        c, o = self.counts, self.outcomes
        return {
            "sent": c["sent"], "ok": c["ok"],
            "failed": c["sent"] - c["ok"],
            # terminal-outcome breakdown: every sent request lands in
            # exactly one bucket; `terminal` excludes only transport
            # errors/hangs
            "outcomes": dict(o),
            "terminal": o["ok"] + o["shed_429"] + o["deadline_504"],
            "shared_prefix_requests": c["shared"],
            "disaggregated": c["disaggregated"],
            "wall_s": wall,
            "rps": c["ok"] / wall if wall > 0 else 0.0,
            "latency_p50_s": _percentile(self.latencies, 50),
            "latency_p95_s": _percentile(self.latencies, 95),
            "ttft_p50_s": _percentile(self.ttfts, 50),
            "ttft_p95_s": _percentile(self.ttfts, 95),
            "shared_latency_p50_s": _percentile(self.shared_latencies, 50),
            "by_replica": dict(self.by_replica),
            "errors": self.errors[:20],
            "slo_ttft_ms": self.slo_ttft_ms,
            "slo_itl_ms": self.slo_itl_ms,
            "slo_attainment": (c["slo_ok"] / c["ok"]
                               if self.slo_declared and c["ok"] else None),
            "slo_ttft_ok": c["slo_ttft_ok"] if self.slo_declared else None,
            "slo_itl_ok": c["slo_itl_ok"] if self.slo_declared else None,
        }


def fire_one(url: str, path: str, payload: Dict, timeout: float,
             col: Collector, label: str = "req",
             shared: bool = False) -> None:
    """POST one request and record its outcome into `col`."""
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        url + path, data=body,
        headers={"Content-Type": "application/json"})
    t0 = time.monotonic()
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read()
            routed = resp.headers.get("X-Routed-To")
        dt = time.monotonic() - t0
        try:  # /generate bodies carry ttft_s (replica-measured direct,
            # control-plane-measured across a disaggregated handoff)
            # + the handoff marker
            obj = json.loads(raw or b"{}")
            ttft = obj.get("ttft_s")
            disagg = bool(obj.get("disaggregated"))
            n_toks = len(obj.get("tokens") or ())
            total = obj.get("total_s")
        except (ValueError, AttributeError):
            ttft, disagg, n_toks, total = None, False, 0, None
        col.record_ok(dt, ttft, total, n_toks, routed, disagg,
                      shared=shared)
    except urllib.error.HTTPError as e:
        try:
            e.read()
        except OSError:
            pass
        e.close()
        col.record_http_error(e.code, label)
    except (urllib.error.URLError, OSError) as e:
        col.record_transport_error(e, label)


#: prometheus sample line: name{labels} value  (labels optional)
_METRIC_RE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(\{[^}]*\})?\s+(\S+)$")

#: server-side counter families folded into every loadgen/replay
#: summary (labeled families sum over their children), keyed by the
#: summary field name they land under
_SERVER_FAMILIES = {
    "serving_preemptions": "butterfly_preemptions_total",
    "shed_total": "butterfly_shed_total",
    "deadline_expired_total": "butterfly_deadline_expired_total",
    "tokens_generated_total": "butterfly_tokens_generated_total",
}


def scrape_server_counters(url: str, timeout: float = 10.0) -> Dict:
    """GET /metrics and fold the overload-protection counters into a
    small dict, so a load run's JSON carries the SERVER-counted
    outcomes next to the client-observed ones (a shed the client saw
    as 429 should show up in shed_total; a preemption is invisible to
    clients and ONLY shows up here). Families absent at the target
    (e.g. a plain router's registry) read 0.0; an unreachable /metrics
    reads {"scraped": False}."""
    try:
        with urllib.request.urlopen(url + "/metrics",
                                    timeout=timeout) as resp:
            text = resp.read().decode("utf-8", "replace")
    except (urllib.error.URLError, OSError, ValueError) as e:
        return {"scraped": False, "error": str(e)[:200]}
    sums: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _METRIC_RE.match(line)
        if not m:
            continue
        name, _, raw = m.groups()
        try:
            val = float(raw)
        except ValueError:
            continue
        sums[name] = sums.get(name, 0.0) + val
    out: Dict = {"scraped": True}
    for field, family in _SERVER_FAMILIES.items():
        out[field] = sums.get(family, 0.0)
    return out


def run_load(url: str, clients: int = 4, requests_per_client: int = 8,
             prefix_share: float = 0.5, shared_len: int = 32,
             tail_len: int = 8, max_tokens: int = 8, seed: int = 0,
             vocab: int = 64, path: str = "/generate",
             timeout: float = 120.0,
             slo_ttft_ms: Optional[float] = None,
             slo_itl_ms: Optional[float] = None,
             deadline_ms: Optional[float] = None,
             priority: Optional[str] = None,
             speculative: Optional[bool] = None,
             arrival: Optional[str] = None,
             scrape: bool = True) -> Dict:
    """Drive `url` closed-loop; returns aggregate stats.

    Every request uses token-id prompts (deterministic, tokenizer-free).
    A `prefix_share` fraction starts with the shared prefix plus a
    per-request tail; the rest are fully private prompts of the same
    total length, so the two populations differ only in shareability.

    `deadline_ms` stamps a latency budget on every request (the server
    504s whatever blows it); `priority` tags the admission class
    ('interactive'/'batch'; batch sheds first under load). Outcome /
    SLO semantics live in `Collector`; the summary additionally carries
    the post-run server-side counters under ``server``
    (`scrape_server_counters`).

    `arrival` (a workload/arrivals.py spec — ``ramp:2:50:10``,
    ``burst:20:0.5:2``, ``poisson:8``) switches the lanes from
    closed-loop to a SCHEDULED offered load: the clients*requests
    arrival offsets are drawn once from the process and dealt round-
    robin across the lanes, and each lane sleeps until a request's
    offset before firing. Per-lane it is semi-open — a response that
    overruns the gap delays that lane's next shot but nobody else's —
    which is what ramps the pressure an elastic fleet has to absorb."""
    prefix = shared_prefix(shared_len, seed, vocab)
    col = Collector(slo_ttft_ms=slo_ttft_ms, slo_itl_ms=slo_itl_ms)
    offsets = None
    if arrival is not None:
        _, arrivals, _ = _workload_modules()
        offsets = arrivals.parse_arrival(arrival).times(
            clients * requests_per_client, seed)
    t_start = time.monotonic()

    def one_client(cid: int) -> None:
        rng = random.Random(seed * 1000 + cid)
        for i in range(requests_per_client):
            is_shared = rng.random() < prefix_share
            tail = [rng.randrange(1, vocab) for _ in range(tail_len)]
            tokens = (prefix + tail) if is_shared else \
                [rng.randrange(1, vocab)
                 for _ in range(shared_len + tail_len)]
            payload = {
                "tokens": tokens, "max_tokens": max_tokens,
                "stop_token": -1,
                "request_id": f"loadgen-{cid}-{i}"}
            if deadline_ms is not None:
                payload["deadline_ms"] = deadline_ms
            if priority is not None:
                payload["priority"] = priority
            if speculative is not None:
                payload["speculative"] = speculative
            if offsets is not None:
                # round-robin deal keeps each lane's schedule ascending
                # while spreading a ramp's dense tail across all lanes
                wait = t_start + offsets[cid + clients * i] \
                    - time.monotonic()
                if wait > 0:
                    time.sleep(wait)
            fire_one(url, path, payload, timeout, col,
                     label=f"client{cid}#{i}", shared=is_shared)

    threads = [threading.Thread(target=one_client, args=(c,))
               for c in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    out = col.summary(time.monotonic() - t_start)
    if arrival is not None:
        out["arrival"] = arrival
    if scrape:
        out["server"] = scrape_server_counters(url)
    return out


def _get_json(url: str, path: str, timeout: float = 10.0) -> Dict:
    with urllib.request.urlopen(url + path, timeout=timeout) as resp:
        return json.loads(resp.read() or b"{}")


def _post_json(url: str, path: str, obj: Dict, timeout: float = 10.0) -> Dict:
    req = urllib.request.Request(
        url + path, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read() or b"{}")


def _wait_drained(url: str, rid: str, timeout: float = 30.0) -> bool:
    """Poll the router snapshot until `rid` has zero outstanding
    proxied requests (its in-flight work finished; only NEW requests
    were being refused by the drain)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        snaps = _get_json(url, "/router/replicas").get("replicas", [])
        me = next((s for s in snaps if s["replica"] == rid), None)
        if me is not None and int(me.get("outstanding", 0)) == 0:
            return True
        time.sleep(0.05)
    return False


def run_fleet_soak(url: str, clients: int = 4,
                   requests_per_client: int = 8,
                   prefix_share: float = 0.5, shared_len: int = 32,
                   tail_len: int = 8, max_tokens: int = 8, seed: int = 0,
                   vocab: int = 64, timeout: float = 120.0,
                   replicas: Optional[List[str]] = None,
                   restart_hook=None, settle_s: float = 0.3,
                   slo_ttft_ms: Optional[float] = None,
                   slo_itl_ms: Optional[float] = None,
                   deadline_ms: Optional[float] = None,
                   priority: Optional[str] = None,
                   speculative: Optional[bool] = None,
                   arrival: Optional[str] = None) -> Dict:
    """Fleet soak: closed-loop load against a control plane WHILE every
    replica is rolled through drain -> (restart) -> undrain, one at a
    time. The pass/fail property is the router tier's: zero dropped
    un-started requests — a drained/restarting replica stops receiving
    new work, its in-flight work finishes, and the rest of the fleet
    absorbs the traffic.

    `restart_hook(rid)` (optional) bounces the replica between drain
    and undrain — the in-process harness passes
    ``fleet.by_rid[rid].restart``; against a real deployment the
    operator's supervisor plays that part. Returns the load stats plus
    the control plane's /fleet/state counters (kv_transfer_hit_rate,
    transfer bytes/pages, disagg/direct split) and the rolling-cycle
    log."""
    if replicas is None:
        replicas = [s["replica"] for s in
                    _get_json(url, "/router/replicas").get("replicas", [])]
    result: Dict = {}

    def _load():
        result.update(run_load(
            url, clients=clients, requests_per_client=requests_per_client,
            prefix_share=prefix_share, shared_len=shared_len,
            tail_len=tail_len, max_tokens=max_tokens, seed=seed,
            vocab=vocab, timeout=timeout, slo_ttft_ms=slo_ttft_ms,
            slo_itl_ms=slo_itl_ms, deadline_ms=deadline_ms,
            priority=priority, speculative=speculative,
            arrival=arrival))

    t = threading.Thread(target=_load)
    t.start()
    cycles = []
    for rid in replicas:
        cycle = {"replica": rid}
        _post_json(url, "/router/drain", {"replica": rid})
        cycle["drained"] = _wait_drained(url, rid)
        if restart_hook is not None:
            restart_hook(rid)
            cycle["restarted"] = True
        time.sleep(settle_s)
        _post_json(url, "/router/undrain", {"replica": rid})
        cycles.append(cycle)
        if t.is_alive():
            time.sleep(settle_s)
    t.join()
    result["rolling_cycles"] = cycles
    try:  # a plain (non-fleet) router has no /fleet/state — soak still valid
        state = _get_json(url, "/fleet/state")
        result["fleet_metrics"] = state.get("metrics", {})
        result["fleet_tiers"] = state.get("tiers", {})
    except (urllib.error.URLError, OSError, ValueError):
        pass
    return result


def _workload_modules():
    """Lazy import of the workload subsystem (open-loop mode only —
    the closed-loop path stays importable without the package). Running
    the script from outside the repo root still resolves: fall back to
    inserting the repo root on sys.path."""
    try:
        from butterfly_tpu.workload import arrivals, models, replay
    except ImportError:
        from pathlib import Path
        sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
        from butterfly_tpu.workload import arrivals, models, replay
    return models, arrivals, replay


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="load generator for butterfly serve/route "
                    "(closed-loop clients, or open-loop workload/trace "
                    "replay)")
    ap.add_argument("--url", required=True,
                    help="base URL, e.g. http://127.0.0.1:8100")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests", type=int, default=8,
                    help="requests per client (closed loop)")
    ap.add_argument("--prefix-share", type=float, default=0.5)
    ap.add_argument("--shared-len", type=int, default=32)
    ap.add_argument("--tail-len", type=int, default=8)
    ap.add_argument("--max-tokens", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--path", default="/generate")
    ap.add_argument("--timeout", type=float, default=120.0)
    # -- open-loop workload mode ------------------------------------------
    ap.add_argument("--workload", default=None, metavar="NAME",
                    help="OPEN-LOOP mode: generate this canned workload "
                         "(butterfly_tpu/workload: mixed_chat, uniform) "
                         "and fire it on the --arrival schedule instead "
                         "of running closed-loop clients")
    ap.add_argument("--trace", default=None, metavar="FILE",
                    help="OPEN-LOOP mode: replay a saved JSONL trace "
                         "(butterfly workload generate / --save) with "
                         "absolute-time fidelity")
    ap.add_argument("--arrival", default=None,
                    help="arrival process: poisson:<rate>"
                         ", burst:<rate_on>:<mean_on_s>:<mean_off_s>"
                         "[:<rate_off>], or ramp:<r0>:<r1>:<ramp_s>. "
                         "With --workload this paces the open-loop "
                         "replay (default poisson:8); in the default "
                         "and --soak modes it switches the client "
                         "lanes from closed-loop to the scheduled "
                         "offered load (e.g. --arrival ramp:2:50:10 "
                         "to ramp pressure on an elastic fleet)")
    ap.add_argument("--n", type=int, default=32,
                    help="total requests to generate for --workload")
    ap.add_argument("--speed", type=float, default=1.0,
                    help="replay time compression: 2.0 fires a trace's "
                         "schedule twice as fast")
    ap.add_argument("--save", default=None, metavar="FILE",
                    help="with --workload: also save the generated "
                         "trace as JSONL before firing it")
    ap.add_argument("--vocab", type=int, default=258,
                    help="workload token-id vocabulary (match the "
                         "model; 258 = tiny/ByteTokenizer)")
    ap.add_argument("--page-size", type=int, default=16,
                    help="workload prefix alignment unit — match the "
                         "server's --page-size so shared prefixes land "
                         "whole pages")
    ap.add_argument("--prompt-lo", type=int, default=32)
    ap.add_argument("--prompt-hi", type=int, default=1024)
    ap.add_argument("--max-new-lo", type=int, default=8)
    ap.add_argument("--max-new-hi", type=int, default=256)
    # -- shared knobs ------------------------------------------------------
    ap.add_argument("--slo-ttft-ms", type=float, default=None,
                    help="declared TTFT objective: judge every request "
                         "client-side and report slo_attainment")
    ap.add_argument("--slo-itl-ms", type=float, default=None,
                    help="declared mean inter-token-latency objective "
                         "(per request), judged client-side")
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="stamp this latency budget (deadline_ms) on "
                         "every request; the server answers 504 for "
                         "whatever blows it — the summary's outcomes "
                         "dict shows the deadline_504 count")
    ap.add_argument("--priority", choices=["interactive", "batch"],
                    default=None,
                    help="admission class tag: 'batch' is shed first "
                         "when SLO-aware admission is active")
    ap.add_argument("--speculative", choices=["on", "off"], default=None,
                    help="stamp \"speculative\": true/false on every "
                         "request (per-request opt-in/out of draft "
                         "acceptance on a `serve --speculate` replica; "
                         "omit to leave the server default)")
    ap.add_argument("--soak", action="store_true",
                    help="fleet soak mode: roll every replica through "
                         "drain/undrain (discovered via "
                         "/router/replicas) while the load runs; "
                         "requires --url to be a router or fleet "
                         "control plane")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)
    if args.trace and args.workload:
        ap.error("--trace and --workload are mutually exclusive")
    if args.trace or args.workload:
        if args.soak:
            ap.error("--soak is a closed-loop fleet mode; open-loop "
                     "workload replay does its own pacing")
        models, arrivals, replay = _workload_modules()
        if args.trace:
            _, specs = replay.load_trace(args.trace)
        else:
            wl = models.get_workload(
                args.workload, page_size=args.page_size,
                vocab=args.vocab, prompt_lo=args.prompt_lo,
                prompt_hi=args.prompt_hi, max_new_lo=args.max_new_lo,
                max_new_hi=args.max_new_hi,
                deadline_ms=args.deadline_ms)
            specs = wl.sample(args.n, args.seed)
            arrivals.assign_arrivals(
                specs,
                arrivals.parse_arrival(args.arrival or "poisson:8"),
                args.seed)
            if args.priority is not None:
                for s in specs:
                    s.priority = args.priority
            if args.speculative is not None:
                for s in specs:
                    s.speculative = args.speculative == "on"
            if args.save:
                replay.save_trace(args.save, specs, workload=wl,
                                  arrival=args.arrival or "poisson:8",
                                  seed=args.seed)
        stats = replay.replay_trace(
            args.url, specs, path=args.path, timeout=args.timeout,
            speed=args.speed, slo_ttft_ms=args.slo_ttft_ms,
            slo_itl_ms=args.slo_itl_ms)
    elif args.soak:
        stats = run_fleet_soak(args.url, clients=args.clients,
                               requests_per_client=args.requests,
                               prefix_share=args.prefix_share,
                               shared_len=args.shared_len,
                               tail_len=args.tail_len,
                               max_tokens=args.max_tokens, seed=args.seed,
                               timeout=args.timeout,
                               slo_ttft_ms=args.slo_ttft_ms,
                               slo_itl_ms=args.slo_itl_ms,
                               deadline_ms=args.deadline_ms,
                               priority=args.priority,
                               speculative=(None if args.speculative is None
                                            else args.speculative == "on"),
                               arrival=args.arrival)
    else:
        stats = run_load(args.url, clients=args.clients,
                         requests_per_client=args.requests,
                         prefix_share=args.prefix_share,
                         shared_len=args.shared_len, tail_len=args.tail_len,
                         max_tokens=args.max_tokens, seed=args.seed,
                         path=args.path, timeout=args.timeout,
                         slo_ttft_ms=args.slo_ttft_ms,
                         slo_itl_ms=args.slo_itl_ms,
                         deadline_ms=args.deadline_ms,
                         priority=args.priority,
                         speculative=(None if args.speculative is None
                                      else args.speculative == "on"),
                         arrival=args.arrival)
    if args.json:
        print(json.dumps(stats, indent=2))
    else:
        print(f"sent={stats['sent']} ok={stats['ok']} "
              f"failed={stats['failed']} rps={stats['rps']:.2f}")
        o = stats["outcomes"]
        print(f"outcomes: ok={o['ok']} shed_429={o['shed_429']} "
              f"deadline_504={o['deadline_504']} error={o['error']} "
              f"(terminal {stats['terminal']}/{stats['sent']})")
        print(f"latency p50={stats['latency_p50_s'] * 1e3:.1f}ms "
              f"p95={stats['latency_p95_s'] * 1e3:.1f}ms")
        if stats.get("slo_attainment") is not None:
            print(f"slo attainment={stats['slo_attainment']:.3f} "
                  f"(ttft_ok={stats['slo_ttft_ok']}/{stats['ok']}, "
                  f"itl_ok={stats['slo_itl_ok']}/{stats['ok']})")
        srv = stats.get("server") or {}
        if srv.get("scraped"):
            print(f"server counters: preemptions="
                  f"{srv['serving_preemptions']:.0f} "
                  f"shed={srv['shed_total']:.0f} "
                  f"deadline_expired={srv['deadline_expired_total']:.0f}")
        if stats["by_replica"]:
            print("by replica: " + ", ".join(
                f"{rid}={n}" for rid, n in
                sorted(stats["by_replica"].items())))
        for e in stats["errors"]:
            print(f"error: {e}", file=sys.stderr)
    # sheds and deadline 504s are terminal outcomes the run ASKED for
    # (backpressure working as designed) — only transport errors/hangs
    # and 5xx faults fail the run
    return 0 if stats["outcomes"]["error"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
