#!/usr/bin/env python
"""Render a per-request timeline report from a dumped trace.

Input: the JSON a running server returns from ``GET /debug/requests``
(or ``Tracer.dump_json``). Output: a per-request summary table (queue
wait, prefill, TTFT, decode, totals) and, with ``--timeline ID``, the
full event list for one request with inter-event deltas — the "where
did this request's time go" view.

    curl -s localhost:8000/debug/requests > trace.json
    python tools/trace_report.py trace.json
    python tools/trace_report.py trace.json --timeline 17

``--fleet`` renders a MERGED cross-replica trace instead — the JSON a
fleet control plane returns from ``GET /fleet/trace?request_id=``: the
control-plane leg waterfall (classify → prefill_leg → kv transfer →
decode_leg), every involved replica's span events interleaved on the
control plane's clock, per-leg durations, and the SLO verdicts.

    curl -s "localhost:8100/fleet/trace?request_id=abc" > fleet.json
    python tools/trace_report.py --fleet fleet.json

stdlib-only on purpose: runs anywhere the dump lands (laptop, CI), no
jax / no backend required.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional


def _summarize_timeline():
    """Resolve obs.trace.summarize_timeline WITHOUT importing the
    butterfly_tpu package root (which drags in jax): the trace module is
    stdlib-only, so a checkout loads it straight from its file. Falls
    back to the package import for installed layouts."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                        "butterfly_tpu", "obs", "trace.py")
    if os.path.exists(path):
        import importlib.util
        spec = importlib.util.spec_from_file_location("_bt_obs_trace", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.summarize_timeline
    from butterfly_tpu.obs.trace import summarize_timeline
    return summarize_timeline


def _fmt_s(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if v >= 1.0:
        return f"{v:.2f}s"
    return f"{v * 1e3:.1f}ms"


def _fmt(v: Any) -> str:
    return "-" if v is None else str(v)


def load_dump(path: str) -> Dict[str, Any]:
    with open(path) as f:
        dump = json.load(f)
    if not isinstance(dump, dict) or "requests" not in dump:
        raise ValueError(
            f"{path}: not a trace dump (expected a JSON object with a "
            f"'requests' key — the GET /debug/requests body)")
    return dump


def summary_rows(dump: Dict[str, Any]) -> List[Dict[str, Any]]:
    summarize = _summarize_timeline()
    return [summarize(rec) for rec in dump.get("requests", ())]


def render_summary(dump: Dict[str, Any]) -> str:
    rows = summary_rows(dump)
    cols = [("id", 5), ("request_id", 14), ("state", 9), ("queue", 8),
            ("prefill", 8), ("ttft", 8), ("decode", 8), ("total", 8),
            ("toks", 5), ("chunks", 6), ("preempt", 7)]
    out = [" ".join(f"{name:>{w}}" for name, w in cols)]
    for r in rows:
        vals = [_fmt(r["id"]), _fmt(r["request_id"])[:14], _fmt(r["state"]),
                _fmt_s(r["queue_wait_s"]), _fmt_s(r["prefill_s"]),
                _fmt_s(r["ttft_s"]), _fmt_s(r["decode_s"]),
                _fmt_s(r["total_s"]), _fmt(r["tokens"]),
                _fmt(r["prefill_chunks"]), _fmt(r["preemptions"])]
        out.append(" ".join(f"{v:>{w}}" for v, (_, w) in zip(vals, cols)))
    done = [r for r in rows if r["total_s"] is not None]
    out.append("")
    out.append(f"{len(rows)} request(s), {len(done)} with a complete "
               f"submit->finish timeline")
    if done:
        ttfts = sorted(r["ttft_s"] for r in done
                       if r["ttft_s"] is not None)
        if ttfts:
            out.append(
                f"ttft: min {_fmt_s(ttfts[0])}  "
                f"p50 {_fmt_s(ttfts[len(ttfts) // 2])}  "
                f"max {_fmt_s(ttfts[-1])}")
    n_glob = len(dump.get("global_events", ()))
    if n_glob:
        ticks = sum(1 for ev in dump["global_events"]
                    if ev.get("name") == "decode_tick")
        out.append(f"{n_glob} global event(s), {ticks} decode tick(s)")
    return "\n".join(out)


def render_timeline(dump: Dict[str, Any], rid: int) -> str:
    rec = next((r for r in dump.get("requests", ())
                if r.get("id") == rid), None)
    if rec is None:
        raise ValueError(f"no request with id {rid} in the dump "
                         f"(have: {[r.get('id') for r in dump['requests']]})")
    events = rec.get("events", [])
    out = [f"request {rid}"
           + (f" (request_id={rec['request_id']})"
              if rec.get("request_id") else "")]
    t0 = events[0]["t"] if events else 0.0
    prev = t0
    for ev in events:
        t = ev["t"]
        attrs = " ".join(f"{k}={v}" for k, v in ev.items()
                         if k not in ("t", "name"))
        out.append(f"  +{t - t0:9.4f}s (Δ{_fmt_s(t - prev)}) "
                   f"{ev['name']:<14} {attrs}")
        prev = t
    return "\n".join(out)


def load_fleet_dump(path: str) -> Dict[str, Any]:
    with open(path) as f:
        dump = json.load(f)
    if not isinstance(dump, dict) or "merged" not in dump:
        raise ValueError(
            f"{path}: not a merged fleet trace (expected a JSON object "
            f"with a 'merged' key — the GET /fleet/trace?request_id= "
            f"body)")
    return dump


def render_fleet(dump: Dict[str, Any]) -> str:
    """The cross-replica waterfall: control-plane legs with durations,
    then every source's events interleaved on the common clock."""
    out = [f"fleet trace request_id={dump.get('request_id')}"]
    t0 = dump.get("t0_wall") or 0.0
    legs = dump.get("legs", [])
    if legs:
        out.append("legs (control plane):")
        for leg in legs:
            where = leg.get("replica") or "-"
            status = leg.get("status", "")
            out.append(f"  +{leg['start_wall'] - t0:9.4f}s "
                       f"{leg['name']:<12} {_fmt_s(leg['dur_s']):>9}  "
                       f"{where}{('  [' + status + ']') if status and status != 'ok' else ''}")
        total, legsum = dump.get("total_s"), dump.get("legs_total_s")
        if total:
            out.append(f"  legs sum {_fmt_s(legsum)} of "
                       f"{_fmt_s(total)} end-to-end "
                       f"({legsum / total * 100:.1f}% accounted)")
    out.append("merged timeline:")
    width = max((len(ev.get("source", "")) for ev in dump["merged"]),
                default=7)
    prev = t0
    for ev in dump["merged"]:
        t = ev["t_wall"]
        attrs = " ".join(f"{k}={v}" for k, v in ev.items()
                         if k not in ("t", "t_wall", "name", "source",
                                      "replica_req"))
        out.append(f"  +{t - t0:9.4f}s (Δ{_fmt_s(max(0.0, t - prev)):>7}) "
                   f"[{ev.get('source', ''):<{width}}] "
                   f"{ev['name']:<14} {attrs}")
        prev = t
    srcs = dump.get("sources", {})
    if srcs:
        parts = []
        for name, info in srcs.items():
            if info.get("missing"):
                parts.append(f"{name}: MISSING ({info.get('error', '?')})")
            else:
                off = info.get("offset_s")
                parts.append(f"{name}: {info.get('events', 0)} event(s)"
                             + (f", clock offset {off * 1e3:+.1f}ms"
                                if off else ""))
        out.append("sources: " + "; ".join(parts))
    slo = dump.get("slo")
    if slo:
        verdicts = []
        if "slo_ttft_ok" in slo:
            verdicts.append(
                f"ttft {_fmt_s(slo.get('ttft_s'))} -> "
                f"{'OK' if slo['slo_ttft_ok'] else 'VIOLATED'}")
        if "slo_itl_ok" in slo:
            verdicts.append(
                f"itl_mean {_fmt_s(slo.get('itl_mean_s'))} -> "
                f"{'OK' if slo['slo_itl_ok'] else 'VIOLATED'}")
        out.append("slo: " + ("; ".join(verdicts) if verdicts
                              else "no objectives declared"))
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="trace_report",
        description="summarize a /debug/requests trace dump (or, with "
                    "--fleet, a merged /fleet/trace dump)")
    p.add_argument("dump", help="path to the JSON trace dump")
    p.add_argument("--timeline", type=int, default=None, metavar="ID",
                   help="print one request's full event timeline")
    p.add_argument("--fleet", action="store_true",
                   help="render a merged cross-replica fleet trace "
                        "(the GET /fleet/trace?request_id= body)")
    p.add_argument("--json", action="store_true",
                   help="emit the per-request summaries as JSON instead "
                        "of a table")
    args = p.parse_args(argv)
    try:
        if args.fleet:
            print(render_fleet(load_fleet_dump(args.dump)))
            return 0
        dump = load_dump(args.dump)
        if args.timeline is not None:
            print(render_timeline(dump, args.timeline))
        elif args.json:
            print(json.dumps(summary_rows(dump)))
        else:
            print(render_summary(dump))
    except (ValueError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
