"""BTF005 — workload/chaos determinism: no unseeded randomness, no
wall-clock reads.

Past incident class: the workload subsystem's whole contract (PR 10) is
byte-identical traces — ``sample(n, seed)`` / ``times(n, seed)`` are
per-request-substreamed so replay, the mixed bench, and the chaos soak
reproduce exactly. One bare ``random.random()`` (module-global PRNG,
process-seeded) or ``time.time()`` (wall clock) in that path silently
breaks replay while every test still passes on its own machine. The
chaos plan carries the same contract (same plan + seed + call sequence
=> identical injections, PR 8).

Flags, in the trace-feeding scope (workload/, fleet/chaos.py, the
loadgen/replay tooling, and the obs time-series ring, whose ordering
contract is seq + monotonic only — wall stamps are caller-supplied):

* module-global PRNG draws: ``random.<fn>()`` for any fn except the
  ``Random``/``SystemRandom`` constructors; ``np.random.<fn>()`` except
  the seedable constructor forms;
* unseeded constructors: ``random.Random()`` / ``np.random.default_rng()``
  with no arguments;
* wall-clock reads: ``time.time()`` (``time.monotonic`` /
  ``perf_counter`` measure elapsed time and stay legal — open-loop
  pacing needs them);
* entropy sources: ``os.urandom``, ``uuid.uuid4``, ``secrets.*``.
"""
from __future__ import annotations

import ast
from typing import Iterator

from . import FileContext, Finding, Rule, dotted_name, register

_SEEDED_CONSTRUCTORS = {"Random", "SystemRandom"}
_NP_SEEDED = {"default_rng", "RandomState", "Generator", "SeedSequence",
              "PCG64", "Philox"}


@register
class DeterminismRule(Rule):
    id = "BTF005"
    name = "workload-determinism"
    invariant = ("trace-feeding code draws only from seeded generators "
                 "and never reads the wall clock")
    scope = ("butterfly_tpu/workload", "butterfly_tpu/fleet/chaos.py",
             "tools/loadgen.py", "butterfly_tpu/obs/timeseries.py")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)
            if not dotted:
                continue
            yield from self._check_call(ctx, node, dotted)

    def _check_call(self, ctx: FileContext, node: ast.Call,
                    dotted: str) -> Iterator[Finding]:
        parts = dotted.split(".")
        # random.<fn> — the module-global, process-seeded PRNG. The
        # constructors are the blessed path (they take the seed).
        if parts[0] == "random" and len(parts) == 2:
            fn = parts[1]
            if fn in _SEEDED_CONSTRUCTORS:
                if fn == "Random" and not node.args:
                    yield self.finding(
                        ctx, node,
                        "random.Random() without a seed draws from OS "
                        "entropy — pass the workload/plan seed so the "
                        "trace replays byte-identically")
            else:
                yield self.finding(
                    ctx, node,
                    f"module-global random.{fn}() breaks trace "
                    f"determinism — draw from a seeded random.Random "
                    f"substream instead")
            return
        # np.random.* — same contract for the numpy global state
        if len(parts) >= 3 and parts[-3] in ("np", "numpy") and \
                parts[-2] == "random":
            fn = parts[-1]
            if fn in _NP_SEEDED:
                if fn == "default_rng" and not node.args:
                    yield self.finding(
                        ctx, node,
                        "np.random.default_rng() without a seed draws "
                        "from OS entropy — pass the workload seed")
            else:
                yield self.finding(
                    ctx, node,
                    f"np.random.{fn}() uses numpy's global PRNG state — "
                    f"use a seeded default_rng(seed)")
            return
        if dotted == "time.time":
            yield self.finding(
                ctx, node,
                "time.time() is a wall-clock read: traces recorded "
                "against it never replay identically — use "
                "time.monotonic() for pacing/elapsed measurement")
            return
        if dotted == "os.urandom" or dotted == "uuid.uuid4" or \
                parts[0] == "secrets":
            yield self.finding(
                ctx, node,
                f"{dotted}() is an OS entropy source — trace-feeding "
                f"code must derive everything from the recorded seed")
