"""BTF002 — no reads of a donated buffer after the dispatch that donated it.

Past incident class: every decode/prefill/spec dispatch donates the KV
pools (and the spec block donates the device token-history carry; the
write-combined windowed blocks additionally donate the staged-window
buffer + per-slot staged count — ISSUE 12's window carry — under a
model draft source the spec block also donates the draft model's own
KV cache, ISSUE 14's draft-cache carry, and the mixed-dispatch blocks
donate the per-slot prefill CURSOR carry — ISSUE 18's chunk-offset
vector, rebound from every mixed_block_async /
mixed_spec_block_async result; all the same factory pattern) so XLA
updates them in place. A host-side read of the donated reference
after the dispatch call observes freed/aliased memory — under paged
serving this aliases garbage K/V under a valid page id, silently
(PR 5's "in-flight writes must never land on reclaimed pages" is the
scheduler-level twin of the same hazard; PR 6's geometry-mismatch 409
is the cross-replica one).

Mechanics (per function, linear flow with loop bodies walked twice so a
next-iteration read is seen):

* donating callables are discovered from ``self.X = jax.jit(...,
  donate_argnums=...)`` assignments, from factory methods that build and
  return such a jit (``self._decode_block_prog(k)(...)`` and
  ``verify = self._verify_program(...)``), from ``A if c else B``
  aliases of two same-signature donators, and from the
  ``KNOWN_DONATING_METHODS`` table for cross-module engine APIs whose
  docstring-contract donates a caller argument.
* at a donating call, every donated positional arg that is a plain
  reference (``cache``, ``self.cache``, ``self._hist_dev``) is poisoned
  — unless the same statement rebinds it (the blessed
  ``logits, cache = prog(..., cache, ...)`` pattern).
* any later read of a poisoned reference is a finding; any store to it
  clears the poison.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from . import (FileContext, Finding, Rule, assigned_handles, handle_of,
               register)

#: Cross-module donating APIs: method name -> donated positional indices
#: OF THE CALLER'S argument list. ServingEngine.spec_block_async donates
#: its ``hist`` argument (engine/serving.py jit donate_argnums=(1,)
#: shifted past the bound params); cast_params donates the source tree.
#: The mixed-dispatch blocks (ISSUE 18) donate the per-slot prefill
#: cursor carry — mixed_block_async its ``cursor`` (caller index 1),
#: mixed_spec_block_async its ``hist`` and ``cursor`` (0 and 2); the
#: prompt buffer is deliberately NOT donated (the scheduler edits it
#: host-side between dispatches at admission).
#: decode_block_async / decode_active_async donate only the engine's own
#: self.cache, never a caller argument, so they are absent by design.
KNOWN_DONATING_METHODS: Dict[str, Tuple[int, ...]] = {
    "spec_block_async": (0,),
    "mixed_block_async": (1,),
    "mixed_spec_block_async": (0, 2),
    "cast_params": (0,),
}


def _donate_argnums(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """(indices,) iff `call` is jax.jit(..., donate_argnums=...)."""
    func = call.func
    is_jit = (isinstance(func, ast.Attribute) and func.attr == "jit") or \
             (isinstance(func, ast.Name) and func.id == "jit")
    if not is_jit:
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.append(e.value)
            return tuple(out)
        return ()  # dynamic indices: can't track, treat as non-donating
    return None


class _ClassTable:
    """Donating callables reachable through ``self`` in one class."""

    def __init__(self):
        self.attrs: Dict[str, Tuple[int, ...]] = {}      # self.X(...)
        self.factories: Dict[str, Tuple[int, ...]] = {}  # self.F(...)(...)


def _collect_class_tables(tree: ast.AST) -> Dict[ast.ClassDef, _ClassTable]:
    tables: Dict[ast.ClassDef, _ClassTable] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        table = _ClassTable()
        for meth in node.body:
            if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            jit_indices: Optional[Tuple[int, ...]] = None
            has_return = False
            for sub in ast.walk(meth):
                if isinstance(sub, ast.Call):
                    idx = _donate_argnums(sub)
                    if idx:
                        jit_indices = idx
                if isinstance(sub, ast.Return) and sub.value is not None:
                    has_return = True
                # self.X = jax.jit(..., donate_argnums=...)
                if isinstance(sub, ast.Assign) and \
                        isinstance(sub.value, ast.Call):
                    idx = _donate_argnums(sub.value)
                    if idx:
                        for t in sub.targets:
                            if isinstance(t, ast.Attribute) and \
                                    isinstance(t.value, ast.Name) and \
                                    t.value.id == "self":
                                table.attrs[t.attr] = idx
            # a method that builds a donating jit and returns something
            # is a program factory (the _decode_block_prog /
            # _verify_program caching pattern)
            if jit_indices and has_return:
                table.factories[meth.name] = jit_indices
        tables[node] = table
    return tables


class _FunctionFlow:
    """Linear poison-propagation over one function body."""

    def __init__(self, rule: "UseAfterDonationRule", ctx: FileContext,
                 table: _ClassTable):
        self.rule = rule
        self.ctx = ctx
        self.table = table
        self.findings: List[Finding] = []
        self._seen: Set[Tuple[int, int, str]] = set()
        #: locals bound to a donating callable: V = self._verify_program(...)
        self.local_donators: Dict[str, Tuple[int, ...]] = {}

    # -- donating-call discovery ------------------------------------------

    def _call_donates(self, call: ast.Call) -> Optional[Tuple[int, ...]]:
        func = call.func
        # self.X(...) where X is a recorded donating jit attribute
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name) and func.value.id == "self":
            if func.attr in self.table.attrs:
                return self.table.attrs[func.attr]
        # V(...) where V was bound to a factory's product
        if isinstance(func, ast.Name) and func.id in self.local_donators:
            return self.local_donators[func.id]
        # self.F(...)(...) — factory called inline
        if isinstance(func, ast.Call) and \
                isinstance(func.func, ast.Attribute) and \
                isinstance(func.func.value, ast.Name) and \
                func.func.value.id == "self":
            if func.func.attr in self.table.factories:
                return self.table.factories[func.func.attr]
        # cross-module engine APIs donating a caller argument
        if isinstance(func, ast.Attribute) and \
                func.attr in KNOWN_DONATING_METHODS:
            return KNOWN_DONATING_METHODS[func.attr]
        if isinstance(func, ast.Name) and \
                func.id in KNOWN_DONATING_METHODS:
            return KNOWN_DONATING_METHODS[func.id]
        return None

    def _donated_handles(self, stmt: ast.stmt) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            indices = self._call_donates(node)
            if not indices:
                continue
            for i in indices:
                if i < len(node.args):
                    h = handle_of(node.args[i])
                    if h and h != "self":
                        out.add(h)
        return out

    def _note_donator_aliases(self, stmt: ast.stmt) -> None:
        """Track V = self._verify_program(...) / V = self._a if c else
        self._b (both donators) so later V(...) calls are donating."""
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            return
        t = stmt.targets[0]
        if not isinstance(t, ast.Name):
            return
        v = stmt.value
        if isinstance(v, ast.Call):
            idx = _donate_argnums(v)
            if idx:  # V = jax.jit(..., donate_argnums=...) in-function
                self.local_donators[t.id] = idx
                return
        if isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute) \
                and isinstance(v.func.value, ast.Name) \
                and v.func.value.id == "self" \
                and v.func.attr in self.table.factories:
            self.local_donators[t.id] = self.table.factories[v.func.attr]
            return
        if isinstance(v, ast.IfExp):
            def attr_of(e):
                if isinstance(e, ast.Attribute) and \
                        isinstance(e.value, ast.Name) and \
                        e.value.id == "self":
                    return self.table.attrs.get(e.attr)
                return None
            a, b = attr_of(v.body), attr_of(v.orelse)
            if a is not None and a == b:
                self.local_donators[t.id] = a

    # -- reads --------------------------------------------------------------

    def _flag_reads(self, node: ast.AST, poison: Set[str]) -> None:
        if not poison:
            return
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Name, ast.Attribute)) and \
                    isinstance(getattr(sub, "ctx", None), ast.Load):
                h = handle_of(sub)
                if h in poison:
                    key = (sub.lineno, sub.col_offset, h)
                    if key in self._seen:
                        continue
                    self._seen.add(key)
                    self.findings.append(self.rule.finding(
                        self.ctx, sub,
                        f"read of {h!r} after it was donated to a jit "
                        f"dispatch — the buffer may already be freed or "
                        f"aliased in place; rebind it from the call's "
                        f"result instead"))

    # -- flow ---------------------------------------------------------------

    def run(self, body: List[ast.stmt]) -> None:
        self._block(body, set())

    def _block(self, stmts: List[ast.stmt], poison: Set[str]) -> Set[str]:
        for stmt in stmts:
            poison = self._stmt(stmt, poison)
        return poison

    def _stmt(self, stmt: ast.stmt, poison: Set[str]) -> Set[str]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return poison  # nested scopes analyzed separately
        if isinstance(stmt, ast.If):
            self._flag_reads(stmt.test, poison)
            p1 = self._block(stmt.body, set(poison))
            p2 = self._block(stmt.orelse, set(poison))
            return p1 | p2
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            header = stmt.iter if isinstance(stmt, (ast.For, ast.AsyncFor)) \
                else stmt.test
            self._flag_reads(header, poison)
            poison = poison - assigned_handles(stmt)
            # twice: a handle donated in iteration t is read at the top
            # of iteration t+1 — the single-pass walk would miss it
            for _ in range(2):
                poison = self._block(stmt.body, poison)
            return self._block(stmt.orelse, poison)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._flag_reads(item.context_expr, poison)
            return self._block(stmt.body, poison)
        if isinstance(stmt, ast.Try):
            poison = self._block(stmt.body, poison)
            merged = set(poison)
            for h in stmt.handlers:
                merged |= self._block(h.body, set(poison))
            merged = self._block(stmt.orelse, merged)
            return self._block(stmt.finalbody, merged)
        # simple statement: reads against the CURRENT poison set, then
        # new donations, then same-statement rebinds clear
        self._flag_reads(stmt, poison)
        self._note_donator_aliases(stmt)
        poison = poison | self._donated_handles(stmt)
        return poison - assigned_handles(stmt)


@register
class UseAfterDonationRule(Rule):
    id = "BTF002"
    name = "use-after-donation"
    invariant = ("a reference passed at a donate_argnums position is "
                 "never read after the dispatch unless rebound from the "
                 "call's result")
    scope = ("butterfly_tpu/engine/serving.py",
             "butterfly_tpu/engine/engine.py",
             "butterfly_tpu/sched/scheduler.py")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        tables = _collect_class_tables(ctx.tree)
        # map each function to its enclosing class's table (module-level
        # functions get an empty table: KNOWN methods still apply)
        empty = _ClassTable()
        owner: Dict[ast.AST, _ClassTable] = {}
        for cls, table in tables.items():
            for node in ast.walk(cls):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    owner.setdefault(node, table)
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                flow = _FunctionFlow(self, ctx, owner.get(node, empty))
                flow.run(node.body)
                yield from flow.findings
