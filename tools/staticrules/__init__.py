"""Project-native static analysis: the rule framework (ISSUE 11).

Every growth PR before this one re-audited the same invariants by hand:
donated buffers must not be read after dispatch, every outbound HTTP
call needs an explicit timeout, serving-lock holders must never block on
network work, workload/chaos code must stay seeded and wall-clock-free.
This package turns those review checklists into AST rules so the checks
run as a tier-1 test (`tests/test_staticcheck.py`) and a CLI
(`butterfly lint`), not reviewer vigilance.

A rule is a class with:

* ``id``        — "BTF0xx" (stable, referenced by suppressions)
* ``name``      — kebab-case slug
* ``invariant`` — the one-line contract the rule enforces
* ``scope``     — repo-relative path prefixes (or exact files) the rule
  applies to. Scoping is deliberate: host-sync is a hot-path contract,
  determinism a workload/chaos contract — flagging them tree-wide would
  drown the real signal in intentional uses.
* ``check(ctx)`` — yield ``Finding``s for one parsed file.

Suppressions are inline comments::

    urlopen(url)  # btf: disable=BTF001 <one-line reason>
    # btf: disable=BTF002,BTF003 <one-line reason>   (covers next line)

A reason is MANDATORY: a reason-less disable is itself reported as
BTF000 (and BTF000 cannot be suppressed) — the repo-wide test asserts
no bare suppressions exist, so every exception stays explained.

The checker itself is mutation-tested (tools/mutcheck.py grows one
weakened-predicate mutant per rule; the fixture suite in
tests/staticcheck_fixtures/ must kill each one), the same contract the
numeric kernels live under.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

#: ``# btf: disable=BTF001[,BTF002] reason...`` — the reason group is
#: everything after the id list; empty means a bare (illegal) suppression.
_SUPPRESS_RE = re.compile(
    r"#\s*btf:\s*disable=(?P<ids>BTF\d{3}(?:\s*,\s*BTF\d{3})*)"
    r"[ \t]*(?P<reason>[^\n]*)")

#: The framework's own rule id: a suppression without a reason. Not
#: registered as a Rule (it has no check method) and never suppressible.
BARE_SUPPRESSION_ID = "BTF000"


@dataclass
class Finding:
    rule: str          # "BTF001"
    path: str          # repo-relative posix path
    line: int          # 1-based
    col: int           # 0-based
    message: str
    suppressed: bool = False
    reason: str = ""   # the suppression's reason when suppressed

    def render(self) -> str:
        tag = " (suppressed: %s)" % self.reason if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: " \
               f"{self.rule} {self.message}{tag}"


@dataclass
class Suppression:
    line: int          # line the comment sits on
    ids: Tuple[str, ...]
    reason: str
    standalone: bool   # comment-only line: also covers the next line
    used: bool = False


@dataclass
class FileContext:
    """One parsed file, shared by every rule that applies to it."""
    path: Path
    relpath: str       # repo-relative posix
    source: str
    tree: ast.AST
    suppressions: List[Suppression] = field(default_factory=list)


def parse_suppressions(source: str) -> List[Suppression]:
    out = []
    for i, raw in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(raw)
        if not m:
            continue
        ids = tuple(s.strip() for s in m.group("ids").split(","))
        out.append(Suppression(
            line=i, ids=ids, reason=m.group("reason").strip(),
            standalone=raw.lstrip().startswith("#")))
    return out


def make_context(path: Path, relpath: str) -> FileContext:
    source = path.read_text(encoding="utf-8")
    tree = ast.parse(source, filename=str(path))
    return FileContext(path=path, relpath=relpath, source=source,
                       tree=tree, suppressions=parse_suppressions(source))


class Rule:
    id: str = "BTF0xx"
    name: str = "unnamed"
    invariant: str = ""
    #: repo-relative path prefixes / exact files this rule walks
    scope: Tuple[str, ...] = ()

    def applies(self, relpath: str) -> bool:
        return any(relpath == p or relpath.startswith(p.rstrip("/") + "/")
                   for p in self.scope)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: FileContext, node: ast.AST,
                message: str) -> Finding:
        return Finding(rule=self.id, path=ctx.relpath,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0),
                       message=message)


#: id -> rule instance. Populated by @register at import time; the
#: driver, the tier-1 test, and the mutcheck mutants all read this one
#: registry, so a rule cannot be silently dropped from one surface.
RULES: Dict[str, Rule] = {}


def register(cls):
    rule = cls()
    if rule.id in RULES:
        raise ValueError(f"duplicate rule id {rule.id}")
    RULES[rule.id] = rule
    return cls


def _statement_spans(tree: ast.AST) -> List[Tuple[int, int]]:
    return [(n.lineno, n.end_lineno or n.lineno)
            for n in ast.walk(tree)
            if isinstance(n, ast.stmt) and hasattr(n, "lineno")]


def suppression_lines(ctx: FileContext, s: Suppression) -> range:
    """The line range a suppression covers: the innermost statement
    containing its line (a trailing comment anywhere in a multi-line
    call covers the whole call), or — for a standalone comment line —
    the whole next statement (skipping further comment/blank lines)."""
    lines = ctx.source.splitlines()
    target = s.line
    if s.standalone:
        target = s.line + 1
        while target <= len(lines) and (
                not lines[target - 1].strip()
                or lines[target - 1].lstrip().startswith("#")):
            target += 1
    best: Optional[Tuple[int, int]] = None
    for lo, hi in _statement_spans(ctx.tree):
        if lo <= target <= hi:
            if best is None or (hi - lo) < (best[1] - best[0]):
                best = (lo, hi)
    if best is None:
        return range(target, target + 1)
    return range(best[0], best[1] + 1)


def apply_suppressions(ctx: FileContext,
                       findings: List[Finding]) -> List[Finding]:
    """Mark findings covered by a same-statement (or preceding
    standalone-comment) suppression; append a BTF000 finding per
    reason-less suppression. Returns the full (marked) finding list."""
    by_line: Dict[int, List[Suppression]] = {}
    for s in ctx.suppressions:
        for line in suppression_lines(ctx, s):
            by_line.setdefault(line, []).append(s)
    for f in findings:
        for s in by_line.get(f.line, ()):
            if f.rule in s.ids and s.reason:
                f.suppressed, f.reason = True, s.reason
                s.used = True
    out = list(findings)
    for s in ctx.suppressions:
        if not s.reason:
            out.append(Finding(
                rule=BARE_SUPPRESSION_ID, path=ctx.relpath, line=s.line,
                col=0,
                message="bare suppression: '# btf: disable=' needs a "
                        "one-line reason after the rule id(s)"))
    return out


def check_context(ctx: FileContext, rules: Optional[Iterable[Rule]] = None,
                  force: bool = False) -> List[Finding]:
    """Run rules over one parsed file. ``force=True`` skips scope
    filtering (fixture tests drive rules at out-of-scope paths)."""
    active = list(rules) if rules is not None else list(RULES.values())
    findings: List[Finding] = []
    for rule in active:
        if force or rule.applies(ctx.relpath):
            findings.extend(rule.check(ctx))
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return apply_suppressions(ctx, findings)


def check_file(path: Path, relpath: Optional[str] = None,
               rules: Optional[Iterable[Rule]] = None,
               force: bool = False) -> List[Finding]:
    rel = relpath if relpath is not None else path.as_posix()
    return check_context(make_context(path, rel), rules=rules, force=force)


def check_source(source: str, relpath: str = "<string>",
                 rules: Optional[Iterable[Rule]] = None,
                 force: bool = True) -> List[Finding]:
    """Lint a source string (fixture/unit tests)."""
    ctx = FileContext(path=Path(relpath), relpath=relpath, source=source,
                      tree=ast.parse(source),
                      suppressions=parse_suppressions(source))
    return check_context(ctx, rules=rules, force=force)


# -- shared AST helpers -------------------------------------------------------

def call_name(func: ast.AST) -> str:
    """Last segment of a call target: urlopen, HTTPConnection, ..."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def dotted_name(node: ast.AST) -> str:
    """'urllib.request.urlopen' for a Name/Attribute chain, '' if the
    chain bottoms out in anything else (a call, a subscript, ...)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def handle_of(node: ast.AST) -> str:
    """A stable string handle for a Name or a self/attr chain ('cache',
    'self.cache', 'self._hist_dev'); '' when the expression is not a
    plain reference (calls, subscripts, literals donate a temporary —
    nothing to read later)."""
    return dotted_name(node)


def walk_functions(tree: ast.AST):
    """Yield (funcdef, enclosing_classdef_or_None) for every function."""
    def visit(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, child)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, cls
                yield from visit(child, cls)
            else:
                yield from visit(child, cls)
    yield from visit(tree, None)


def assigned_handles(stmt: ast.stmt) -> set:
    """Handles (re)bound by this statement (tuple targets flattened)."""
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        targets = [i.optional_vars for i in stmt.items if i.optional_vars]
    out = set()
    stack = list(targets)
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        elif isinstance(t, ast.Starred):
            stack.append(t.value)
        else:
            h = handle_of(t)
            if h:
                out.add(h)
    return out


# -- rule modules (import order = catalog order) -----------------------------
# Imported for the @register side effect; the names also give callers a
# stable module path per rule (mutcheck mutates these files).
from . import http_timeout   # noqa: E402,F401  BTF001
from . import donation       # noqa: E402,F401  BTF002
from . import host_sync      # noqa: E402,F401  BTF003
from . import locks          # noqa: E402,F401  BTF004
from . import determinism    # noqa: E402,F401  BTF005
from . import prng           # noqa: E402,F401  BTF006
