"""BTF004 — serving-lock discipline.

Past incident class: PR 8 found HTTP handler paths pinning their thread
on ``state.lock`` while a slow/hung tick held it (fixed with the bounded
``ServerState.acquire_lock`` / ``_locked`` contract + LockTimeout 503s),
and the fleet rollout repeatedly re-audited that no lock holder blocks
on network work. The scheduler thread itself may hold the lock
unboundedly (it OWNS the device); the contract binds the *other*
threads.

Three checks, scoped to the serving/router/fleet HTTP tier:

* **unbounded acquire** — ``<lockish>.acquire()`` without a ``timeout=``
  anywhere in scope. A hung tick holds the serving lock forever; an
  unbounded acquire on any thread but the scheduler loop pins that
  thread with it. (The one blessed unbounded form is the ``with lock:``
  statement on the scheduler thread — handler classes are denied even
  that, next check.)
* **raw lock in a handler class** — ``with <x>.lock:`` or
  ``<x>.lock.acquire(...)`` inside a ``*Handler`` class:
  handler threads must go through the bounded
  ``ServerState.acquire_lock``/``_locked`` contract so they 503 instead
  of hanging.
* **network I/O under a lock** — an outbound HTTP call (urlopen /
  HTTPConnection) lexically inside any ``with <lock-ish>:`` block: a
  lock holder waiting on a peer couples every local waiter to that
  peer's latency.
* **unlocked shared-counter write in a handler class** — handler
  threads are multi-writer, so instrument updates
  (``<x>._c_*/._g_*/._h_* .inc()/.set()/.observe()`` or ``+=`` on such
  an attribute) must sit inside a ``with <lock-ish>:`` block (the
  single-writer scheduler-thread registry contract does not apply to
  handlers).
"""
from __future__ import annotations

import ast
from typing import Iterator, List

from . import FileContext, Finding, Rule, call_name, dotted_name, register

_HTTP_CALLS = {"urlopen", "HTTPConnection", "HTTPSConnection",
               "create_connection"}

#: instrument naming convention (scheduler/router/fleet registries):
#: counters _c_*, gauges _g_*, histograms _h_*
_INSTRUMENT_PREFIXES = ("_c_", "_g_", "_h_")

_INSTRUMENT_METHODS = {"inc", "set", "observe", "dec"}


def _is_lockish(expr: ast.AST) -> bool:
    """Does this with-context expression look like a lock acquisition?
    `with self.lock:`, `with state._mlock:`, `with self._locked():`."""
    if isinstance(expr, ast.Call):
        name = call_name(expr.func)
        return "lock" in name.lower()
    name = dotted_name(expr)
    return "lock" in name.rsplit(".", 1)[-1].lower() if name else False


def _is_handler_class(cls: ast.ClassDef) -> bool:
    if cls.name.endswith("Handler"):
        return True
    for base in cls.bases:
        base_name = dotted_name(base)
        if "Handler" in base_name or "handler" in base_name:
            return True
    return False


def _mentions_instrument(expr: ast.AST) -> bool:
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Attribute) and \
                sub.attr.startswith(_INSTRUMENT_PREFIXES):
            return True
    return False


@register
class LockDisciplineRule(Rule):
    id = "BTF004"
    name = "lock-discipline"
    invariant = ("handler threads use the bounded acquire contract, no "
                 "lock holder does network I/O, and handler-thread "
                 "instrument writes are locked")
    scope = ("butterfly_tpu/serve", "butterfly_tpu/router",
             "butterfly_tpu/fleet", "butterfly_tpu/sched",
             "butterfly_tpu/obs", "butterfly_tpu/cache")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        yield from self._check_acquires(ctx)
        yield from self._check_under_locks(ctx)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef) and _is_handler_class(node):
                yield from self._check_handler_class(ctx, node)

    # -- unbounded .acquire() ------------------------------------------------

    def _check_acquires(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not (isinstance(func, ast.Attribute)
                    and func.attr == "acquire"):
                continue
            owner = dotted_name(func.value)
            if "lock" not in owner.rsplit(".", 1)[-1].lower():
                continue
            if any(kw.arg == "timeout" for kw in node.keywords) or \
                    any(kw.arg is None for kw in node.keywords) or \
                    node.args:
                continue
            yield self.finding(
                ctx, node,
                f"unbounded {owner}.acquire(): a hung tick holds the "
                f"serving lock forever — pass timeout= (or use "
                f"ServerState.acquire_lock / _locked)")

    # -- blocking work while holding a lock ----------------------------------

    def _check_under_locks(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            if not any(_is_lockish(i.context_expr) for i in node.items):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        call_name(sub.func) in _HTTP_CALLS:
                    yield self.finding(
                        ctx, sub,
                        f"network I/O ({call_name(sub.func)}) while "
                        f"holding a lock: every waiter on this lock now "
                        f"shares the peer's latency/timeout — move the "
                        f"call outside the critical section")

    # -- handler-class checks ------------------------------------------------

    def _check_handler_class(self, ctx: FileContext,
                             cls: ast.ClassDef) -> Iterator[Finding]:
        # raw lock use: with <x>.lock / <x>.lock.acquire
        for node in ast.walk(cls):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    name = dotted_name(item.context_expr)
                    if name.endswith(".lock"):
                        yield self.finding(
                            ctx, item.context_expr,
                            f"raw 'with {name}:' in handler class "
                            f"{cls.name}: handler threads must use the "
                            f"bounded ServerState.acquire_lock/_locked "
                            f"contract (503 + Retry-After, never a hang)")
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "acquire":
                owner = dotted_name(node.func.value)
                if owner.endswith(".lock"):
                    yield self.finding(
                        ctx, node,
                        f"raw {owner}.acquire(...) in handler class "
                        f"{cls.name}: use the bounded "
                        f"ServerState.acquire_lock/_locked contract")
        # unlocked instrument writes
        locked_spans: List[ast.AST] = [
            n for n in ast.walk(cls)
            if isinstance(n, (ast.With, ast.AsyncWith))
            and any(_is_lockish(i.context_expr) for i in n.items)]

        def under_lock(node: ast.AST) -> bool:
            return any(node in set(ast.walk(w)) for w in locked_spans)

        for node in ast.walk(cls):
            hit = None
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _INSTRUMENT_METHODS and \
                    _mentions_instrument(node.func.value):
                hit = node
            elif isinstance(node, ast.AugAssign) and \
                    _mentions_instrument(node.target):
                hit = node
            if hit is not None and not under_lock(hit):
                yield self.finding(
                    ctx, hit,
                    f"unlocked shared-instrument write in handler class "
                    f"{cls.name}: handler threads are multi-writer — "
                    f"take the metrics lock (the state.inc/state.count "
                    f"pattern) or lose increments under concurrency")
