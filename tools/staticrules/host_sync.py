"""BTF003 — no host synchronization inside the dispatch hot path.

Past incident class: the BENCH_r05 serving-vs-engine gap (502 vs 6,988
tok/s on the same chip) was host-bound — every per-token host<->device
round trip (``int(np.asarray(tok))`` and friends) serialized the device
behind the host section (ROADMAP item 1). PRs 3/5/9 rebuilt the tick
around dispatch-ahead precisely so the HOT functions (tick, operand
assembly, block dispatch) never materialize a device value; draining is
where synchronization is *intended* and the drain functions are
deliberately outside this rule's hot set.

The rule flags, inside the configured hot functions only:

* ``.item()`` / ``.tolist()`` / ``.block_until_ready()`` calls — the
  unambiguous sync markers;
* ``jax.device_get(...)``;
* ``np.asarray(x)`` / ``np.array(x)`` where ``x`` is not host-side by
  construction (a list/tuple/comprehension/constant, or a parameter
  annotated as a host container like ``slots: list[int]``, is
  host->host and fine — the operand-assembly pattern);
* ``int()`` / ``float()`` / ``bool()`` whose argument mentions a
  device-carry name (``*_dev``, or one of the conventional
  device-resident names below) — the exact ``int(logits[...])`` shape
  the old per-token readback used.
"""
from __future__ import annotations

import ast
from typing import Iterator, Set

from . import FileContext, Finding, Rule, call_name, dotted_name, register

#: functions whose bodies must stay sync-free. Drain/emit functions are
#: intentionally absent: the stacked drain is the one blessed fetch.
#: The ISSUE 15 tick-anatomy paths (phase timers, the ticklog ring
#: append, the flight-recorder note/poll) run once per tick inside the
#: hot section, so they are IN the set: a timer that materialized a
#: device value would reintroduce exactly the sync it exists to find.
HOT_FUNCTIONS: Set[str] = {
    "tick", "_decode_block", "_spec_block", "_assemble", "_admit",
    "_admit_round", "_finish_prefill", "_note_bubble",
    "decode_block_async", "spec_block_async", "decode_active_async",
    "prefill_batch", "_sync_table",
    # ISSUE 20: the seq-parallel long-prompt lane — one chunk dispatch
    # per tick; a per-chunk readback would serialize the whole prefill
    "_sp_prefill_step", "sp_prefill_chunk",
    "_phase_add", "_drain_accrued", "_record_tick",
    "record", "note", "poll",
    # ISSUE 16: the signal recorder samples inside _record_tick (the
    # tail of the hot section) — it must consume host floats only
    "sample", "evaluate_rules",
}

#: conventional device-resident value names in the hot path (plus any
#: name suffixed _dev): int()/float()/bool() over these is a readback
DEVICE_NAMES: Set[str] = {"logits", "final", "firsts", "block", "carry",
                          "toks3", "valid3"}

_LITERALS = (ast.List, ast.Tuple, ast.ListComp, ast.GeneratorExp,
             ast.Constant, ast.Dict, ast.Set, ast.SetComp, ast.DictComp)

#: annotation heads marking a parameter as a host-side container —
#: np.asarray over one is host->host operand assembly, not a device sync
_HOST_CONTAINER_ANNOTATIONS = {"list", "List", "tuple", "Tuple",
                               "Sequence", "Iterable", "dict", "Dict"}


def _host_container_params(fn: ast.FunctionDef):
    """Parameter names whose annotation is a host container type."""
    out = set()
    for arg in (list(fn.args.posonlyargs) + list(fn.args.args)
                + list(fn.args.kwonlyargs)):
        ann = arg.annotation
        if ann is None:
            continue
        head = ann.value if isinstance(ann, ast.Subscript) else ann
        if isinstance(head, ast.Name) and \
                head.id in _HOST_CONTAINER_ANNOTATIONS:
            out.add(arg.arg)
    return out


def _mentions_device_name(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            if sub.id in DEVICE_NAMES or sub.id.endswith("_dev"):
                return True
        if isinstance(sub, ast.Attribute):
            if sub.attr in DEVICE_NAMES or sub.attr.endswith("_dev"):
                return True
    return False


@register
class HostSyncRule(Rule):
    id = "BTF003"
    name = "host-sync-in-hot-path"
    invariant = ("tick/dispatch hot functions never materialize a "
                 "device value on the host (sync belongs to the "
                 "stacked drain)")
    scope = ("butterfly_tpu/engine/serving.py",
             "butterfly_tpu/sched/scheduler.py",
             "butterfly_tpu/obs/ticklog.py",
             "butterfly_tpu/obs/timeseries.py")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in HOT_FUNCTIONS:
                yield from self._check_hot(ctx, node)

    def _check_hot(self, ctx: FileContext,
                   fn: ast.FunctionDef) -> Iterator[Finding]:
        host_params = _host_container_params(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node.func)
            where = f"in hot function {fn.name}()"
            if name in ("item", "tolist", "block_until_ready") and \
                    isinstance(node.func, ast.Attribute):
                yield self.finding(
                    ctx, node,
                    f".{name}() {where} synchronously materializes a "
                    f"device value — move it to the stacked drain")
                continue
            dotted = dotted_name(node.func)
            if dotted in ("jax.device_get",):
                yield self.finding(
                    ctx, node,
                    f"jax.device_get {where} blocks on the device — "
                    f"move it to the stacked drain")
                continue
            if dotted in ("np.asarray", "np.array", "numpy.asarray",
                          "numpy.array"):
                arg0 = node.args[0] if node.args else None
                is_host_param = (isinstance(arg0, ast.Name)
                                 and arg0.id in host_params)
                if arg0 is not None and not is_host_param and \
                        not isinstance(arg0, _LITERALS):
                    yield self.finding(
                        ctx, node,
                        f"{dotted}(...) on a non-literal {where} may "
                        f"fetch a device array to the host — convert at "
                        f"the drain, or build from host lists")
                continue
            if name in ("int", "float", "bool") and \
                    isinstance(node.func, ast.Name) and node.args and \
                    _mentions_device_name(node.args[0]):
                yield self.finding(
                    ctx, node,
                    f"{name}() over a device-carry value {where} is a "
                    f"per-token host readback (the BENCH_r05 serving-"
                    f"gap shape) — keep the value device-resident")
