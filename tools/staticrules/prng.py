"""BTF006 — JAX PRNG key discipline in the sampling paths.

Past incident class: the serving sampler's correctness contract
(tests/test_spec_sampling.py distribution-parity suite, PR 9) only
holds if every draw consumes a FRESH key: the engine splits per step
(``key, sub = jax.random.split(key)``) or derives per scan iteration
(``fold_in(key, i)``). Passing the same key to two draws makes them
perfectly correlated (two "independent" samples that always agree);
building a key from a constant literal inside the serving path makes
every call draw the identical stream (e.g. a request-independent
"random" sample).

Two checks, per function, over the engine/sched/serve sampling tier:

* **key reuse** — a key reference consumed by more than one drawing
  call (``jax.random.uniform/categorical/...`` and the project's own
  ``sample``/``sample_batched``/``speculative_accept`` wrappers)
  without being rebound (``split``/``fold_in`` reassignment) between;
* **constant key** — ``jax.random.PRNGKey(<literal>)`` in serving-path
  code: a constant key is only legitimate for deliberately-
  deterministic demo/smoke weight init, which carries an inline
  suppression explaining exactly that.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from . import (FileContext, Finding, Rule, assigned_handles, call_name,
               dotted_name, handle_of, register)

#: jax.random drawing functions (consume a key; split/fold_in derive)
_JAX_CONSUMERS = {
    "uniform", "normal", "categorical", "gumbel", "bernoulli",
    "exponential", "randint", "truncated_normal", "choice",
    "permutation", "laplace", "poisson", "gamma", "beta", "dirichlet",
}

#: project sampling wrappers: callable name -> key argument position
PROJECT_CONSUMERS: Dict[str, int] = {
    "sample": 1,             # sample(logits, key, sp)
    "sample_batched": 1,     # sample_batched(logits, key, temps, ...)
    "speculative_accept": 2,  # speculative_accept(logits, drafts, key, ...)
}


def _key_arg(node: ast.Call) -> str:
    """Handle of the key argument if this call consumes a PRNG key."""
    func = node.func
    name = call_name(func)
    dotted = dotted_name(func)
    if name in _JAX_CONSUMERS and ("random" in dotted or
                                   dotted.startswith("jr.")):
        if node.args:
            return handle_of(node.args[0])
        return ""
    if isinstance(func, ast.Name) and name in PROJECT_CONSUMERS:
        pos = PROJECT_CONSUMERS[name]
        if pos < len(node.args):
            return handle_of(node.args[pos])
        for kw in node.keywords:
            if kw.arg == "key":
                return handle_of(kw.value)
    return ""


@register
class PrngDisciplineRule(Rule):
    id = "BTF006"
    name = "prng-key-discipline"
    invariant = ("every sampling draw consumes a fresh key; no constant "
                 "PRNGKey in the serving path")
    scope = ("butterfly_tpu/engine", "butterfly_tpu/sched",
             "butterfly_tpu/serve", "butterfly_tpu/fleet/harness.py",
             "butterfly_tpu/ckpt")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        yield from self._check_constant_keys(ctx)
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_reuse(ctx, node)

    def _check_constant_keys(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    call_name(node.func) == "PRNGKey" and node.args and \
                    isinstance(node.args[0], ast.Constant):
                yield self.finding(
                    ctx, node,
                    f"constant jax.random.PRNGKey({node.args[0].value!r}) "
                    f"in the serving path: every call draws the "
                    f"identical stream — derive the key from the "
                    f"request/scheduler seed")

    def _check_reuse(self, ctx: FileContext,
                     fn: ast.FunctionDef) -> Iterator[Finding]:
        findings: List[Finding] = []
        seen: Set[Tuple[int, int, str]] = set()

        def block(stmts, consumed: Set[str]) -> Set[str]:
            for stmt in stmts:
                consumed = visit_stmt(stmt, consumed)
            return consumed

        def visit_stmt(stmt, consumed: Set[str]) -> Set[str]:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return consumed
            if isinstance(stmt, ast.If):
                c1 = block(stmt.body, set(consumed) | scan(stmt.test,
                                                           consumed))
                c2 = block(stmt.orelse, set(consumed))
                return c1 | c2
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                header = stmt.iter \
                    if isinstance(stmt, (ast.For, ast.AsyncFor)) \
                    else stmt.test
                consumed = consumed | scan(header, consumed)
                consumed -= assigned_handles(stmt)
                # twice: the same key consumed once per iteration IS
                # reuse — the second pass sees the first pass's set
                for _ in range(2):
                    consumed = block(stmt.body, consumed)
                return block(stmt.orelse, consumed)
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    consumed = consumed | scan(item.context_expr, consumed)
                return block(stmt.body, consumed)
            if isinstance(stmt, ast.Try):
                consumed = block(stmt.body, consumed)
                merged = set(consumed)
                for h in stmt.handlers:
                    merged |= block(h.body, set(consumed))
                merged = block(stmt.orelse, merged)
                return block(stmt.finalbody, merged)
            consumed = consumed | scan(stmt, consumed)
            return consumed - assigned_handles(stmt)

        def scan(node, consumed: Set[str]) -> Set[str]:
            """Flag re-consumed keys in this expression/statement;
            return the keys it newly consumes."""
            new: Set[str] = set()
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                h = _key_arg(sub)
                if not h:
                    continue
                if h in consumed or h in new:
                    key = (sub.lineno, sub.col_offset, h)
                    if key not in seen:
                        seen.add(key)
                        findings.append(self.finding(
                            ctx, sub,
                            f"PRNG key {h!r} consumed more than once "
                            f"without split/fold_in between: the draws "
                            f"are perfectly correlated — rebind with "
                            f"key, sub = jax.random.split({h})"))
                new.add(h)
            return new

        block(fn.body, set())
        yield from findings
