"""BTF001 — every outbound HTTP call carries an explicit timeout.

Past incident: PR 8 found a stray ``urlopen(...)`` riding the OS default
socket timeout (minutes to forever) in the fleet trace assembler — one
wedged peer would have pinned a control-plane thread invisibly — and
left a string-span grep behind in tests/test_chaos.py. This rule is the
AST replacement: it sees through multi-line calls, aliased imports and
keyword order, and accepts the timeout positionally where the stdlib
signature defines one.
"""
from __future__ import annotations

import ast
from typing import Iterator

from . import FileContext, Finding, Rule, call_name, register

#: call-name -> index of the positional ``timeout`` parameter in the
#: stdlib signature (urlopen(url, data=None, timeout=...),
#: HTTPConnection(host, port=None, timeout=...)).
TIMEOUT_ARG_INDEX = {
    "urlopen": 2,
    "HTTPConnection": 2,
    "HTTPSConnection": 2,
}


@register
class HttpTimeoutRule(Rule):
    id = "BTF001"
    name = "outbound-http-timeout"
    invariant = ("every urlopen/HTTPConnection/HTTPSConnection call "
                 "passes an explicit timeout")
    scope = ("butterfly_tpu", "tools")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node.func)
            if name not in TIMEOUT_ARG_INDEX:
                continue
            if any(kw.arg == "timeout" for kw in node.keywords):
                continue
            if any(kw.arg is None for kw in node.keywords):
                continue  # **kwargs splat: cannot see inside, accept
            if len(node.args) > TIMEOUT_ARG_INDEX[name]:
                continue  # timeout passed positionally
            yield self.finding(
                ctx, node,
                f"outbound HTTP call {name}(...) without an explicit "
                f"timeout= waits on the OS default (minutes to forever); "
                f"one wedged peer then pins this thread invisibly")
