#!/usr/bin/env python
"""Render a dumped timeseries body as a static dashboard.

Consumes either shape:

* ``GET /debug/timeseries`` — one replica's SignalRecorder ring
  (samples carry ``seq``/``t_wall``/``signals``);
* ``GET /fleet/timeseries`` — the control plane's clock-offset merge
  (samples additionally carry ``source``/``t_fleet``; rendered as
  per-source small multiples).

Default output is a self-contained static HTML page — inline SVG
sparkline per signal, min/mean/max/last stat row, alert annotations
(vertical markers where an alert rule fired inside the window), and a
reconciliation footer (observed samples vs the span/interval
expectation — the honesty line saying how much of the window the ring
actually covers). ``--text`` renders the same series as unicode
sparklines for terminals.

``--flightrecorder fr.json`` (a saved ``/debug/flightrecorder`` body)
overlays the autoscaler's scale decisions as dashed vertical markers on
every sparkline and lists them in their own section — so a queue-depth
spike can be read against the grow that answered it. KV-tier signals
(``kv_tier_*``, led by the hit rate) render as their own panel per
source instead of alphabetically interleaved with the core signals.

stdlib-only (no jax, no numpy): runs anywhere, like tick_report.py.

Usage:  curl -s host:8000/debug/timeseries > ts.json
        curl -s host:9100/debug/flightrecorder > fr.json
        python tools/dashboard.py ts.json --flightrecorder fr.json --out dash.html
        python tools/dashboard.py ts.json --text
        butterfly dash ts.json --text
"""
from __future__ import annotations

import argparse
import html
import json
import sys
from typing import Dict, List, Optional, Tuple

SPARK_BLOCKS = "▁▂▃▄▅▆▇█"
SVG_W, SVG_H, SVG_PAD = 600, 64, 4


def load_dump(path: str) -> dict:
    with open(path) as f:
        dump = json.load(f)
    if not isinstance(dump, dict) or "samples" not in dump:
        raise ValueError(
            f"{path} is not a timeseries dump (expected a JSON object "
            f"with a 'samples' list — /debug/timeseries or "
            f"/fleet/timeseries)")
    return dump


def load_scale_events(path: str) -> List[dict]:
    """kind == "scale" events out of a saved flight-recorder body
    (either the ring's ``dump()`` object or a bare event list)."""
    with open(path) as f:
        body = json.load(f)
    events = body.get("events", body) if isinstance(body, dict) else body
    if not isinstance(events, list):
        raise ValueError(
            f"{path} is not a flight-recorder dump (expected an "
            f"'events' list — /debug/flightrecorder)")
    return [e for e in events
            if isinstance(e, dict) and e.get("kind") == "scale"]


#: signals that belong to the host-KV-tier panel, hit rate first
_TIER_PREFIX = "kv_tier_"


def split_tier_signals(names: List[str]) -> Tuple[List[str], List[str]]:
    """(core, tier) partition of a source's signal names; the tier
    list leads with kv_tier_hit_rate so the headline ratio sits on
    top of its own panel."""
    core = sorted(n for n in names if not n.startswith(_TIER_PREFIX))
    tier = sorted(n for n in names if n.startswith(_TIER_PREFIX))
    lead = _TIER_PREFIX + "hit_rate"
    if lead in tier:
        tier.remove(lead)
        tier.insert(0, lead)
    return core, tier


def is_fleet(dump: dict) -> bool:
    if str(dump.get("schema", "")).startswith("butterfly-fleet"):
        return True
    return any("source" in s for s in dump.get("samples", ()))


def sample_time(s: dict) -> float:
    """Sample timestamp on the dump's merge clock (fleet dumps carry
    t_fleet; replica dumps t_wall)."""
    return float(s.get("t_fleet", s.get("t_wall", 0.0)))


def collect(dump: dict) -> Dict[str, Dict[str, List[Tuple[float, float]]]]:
    """{source: {signal: [(t, v), ...]}}; a replica dump collapses to
    the single source ''."""
    out: Dict[str, Dict[str, List[Tuple[float, float]]]] = {}
    for s in dump.get("samples", ()):
        src = str(s.get("source", ""))
        t = sample_time(s)
        for k, v in s.get("signals", {}).items():
            out.setdefault(src, {}).setdefault(k, []).append(
                (t, float(v)))
    for signals in out.values():
        for series in signals.values():
            series.sort(key=lambda p: p[0])
    return out


def stats(series: List[Tuple[float, float]]) -> Dict[str, float]:
    vals = [v for _, v in series]
    return {"min": min(vals), "max": max(vals),
            "mean": sum(vals) / len(vals), "last": vals[-1],
            "n": len(vals)}


def reconciliation(dump: dict) -> Optional[Dict[str, float]]:
    """Observed sample count vs the span/interval expectation (replica
    dumps only: the fleet merge mixes cadences)."""
    samples = dump.get("samples", ())
    interval = float(dump.get("interval_s") or 0.0)
    if len(samples) < 2 or interval <= 0:
        return None
    span = sample_time(samples[-1]) - sample_time(samples[0])
    expected = span / interval + 1 if span > 0 else len(samples)
    return {"samples": len(samples), "span_s": span,
            "expected": expected,
            "coverage": len(samples) / expected if expected else 1.0}


# -- text rendering -----------------------------------------------------------

def sparkline(vals: List[float], width: int = 48) -> str:
    if not vals:
        return ""
    if len(vals) > width:  # downsample: last value per bucket
        step = len(vals) / width
        vals = [vals[min(len(vals) - 1, int((i + 1) * step) - 1)]
                for i in range(width)]
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return SPARK_BLOCKS[0] * len(vals)
    return "".join(
        SPARK_BLOCKS[min(len(SPARK_BLOCKS) - 1,
                         int((v - lo) / span * len(SPARK_BLOCKS)))]
        for v in vals)


def _scale_line(e: dict, t0: float) -> str:
    return (f"+{float(e.get('t_wall', 0.0)) - t0:.1f}s "
            f"{e.get('tier', '?')} {e.get('direction', '?')} "
            f"({e.get('reason', '?')}) "
            f"{e.get('n_before', '?')} -> {e.get('n_after', '?')}")


def render_text(dump: dict, scales: Optional[List[dict]] = None) -> str:
    grouped = collect(dump)
    alerts = list(dump.get("alerts", ()))
    scales = scales or []
    lines = []
    kind = "fleet" if is_fleet(dump) else "replica"
    lines.append(f"{kind} timeseries: "
                 f"{len(dump.get('samples', ()))} sample(s), "
                 f"{sum(len(sig) for sig in grouped.values())} series, "
                 f"{len(alerts)} alert(s), "
                 f"{len(scales)} scale event(s)")
    for src in sorted(grouped):
        if src:
            lines.append("")
            lines.append(f"== {src} ==")
        core, tier = split_tier_signals(list(grouped[src]))
        for group, names in (("", core), ("kv tier", tier)):
            if group and names:
                lines.append(f"{'-- ' + group + ' --':>28}")
            for name in names:
                series = grouped[src][name]
                st = stats(series)
                lines.append(
                    f"{name:>28} {sparkline([v for _, v in series])} "
                    f"min {st['min']:g}  mean {st['mean']:g}  "
                    f"max {st['max']:g}  last {st['last']:g}")
    if scales:
        samples = dump.get("samples", ())
        t0 = sample_time(samples[0]) if samples else 0.0
        lines.append("")
        lines.append("scale events:")
        for e in scales:
            lines.append(f"  {_scale_line(e, t0)}")
    if alerts:
        lines.append("")
        lines.append("alerts:")
        for a in alerts:
            src = a.get("source", "")
            lines.append(f"  [{a.get('severity', '?'):>4}] "
                         f"{a.get('rule', '?')} on "
                         f"{a.get('signal', '?')}"
                         + (f" @ {src}" if src else "")
                         + f" (value {a.get('value', 0):g})")
    rec = reconciliation(dump)
    lines.append("")
    if rec is not None:
        lines.append(f"{rec['samples']} samples over "
                     f"{rec['span_s']:.1f}s at interval "
                     f"{dump.get('interval_s')}s: "
                     f"{100 * rec['coverage']:.1f}% of the expected "
                     f"window covered")
    else:
        lines.append("no single-cadence reconciliation "
                     "(merged or short dump)")
    return "\n".join(lines)


# -- HTML rendering -----------------------------------------------------------

def _svg_sparkline(series: List[Tuple[float, float]],
                   alert_ts: List[float],
                   scale_ts: Optional[List[float]] = None) -> str:
    ts = [t for t, _ in series]
    vals = [v for _, v in series]
    t0, t1 = min(ts), max(ts)
    lo, hi = min(vals), max(vals)
    tspan = (t1 - t0) or 1.0
    vspan = (hi - lo) or 1.0
    w, h, pad = SVG_W, SVG_H, SVG_PAD

    def x(t: float) -> float:
        return pad + (t - t0) / tspan * (w - 2 * pad)

    def y(v: float) -> float:
        return h - pad - (v - lo) / vspan * (h - 2 * pad)

    pts = " ".join(f"{x(t):.1f},{y(v):.1f}" for t, v in series)
    marks = "".join(
        f'<line x1="{x(t):.1f}" y1="0" x2="{x(t):.1f}" y2="{h}" '
        f'class="alert"/>' for t in alert_ts if t0 <= t <= t1)
    marks += "".join(
        f'<line x1="{x(t):.1f}" y1="0" x2="{x(t):.1f}" y2="{h}" '
        f'class="scale"/>' for t in (scale_ts or ()) if t0 <= t <= t1)
    return (f'<svg width="{w}" height="{h}" viewBox="0 0 {w} {h}">'
            f'{marks}<polyline points="{pts}" fill="none" '
            f'class="line"/></svg>')


_CSS = """
body { font: 13px/1.5 system-ui, sans-serif; margin: 2em auto;
       max-width: 72em; color: #222; }
h1 { font-size: 1.3em; } h2 { font-size: 1.1em; margin-top: 1.6em; }
table.signals td { padding: 2px 10px; vertical-align: middle; }
td.name { font-family: ui-monospace, monospace; text-align: right; }
td.stat { font-family: ui-monospace, monospace; color: #555;
          white-space: nowrap; }
svg .line { stroke: #2061c4; stroke-width: 1.5; }
svg .alert { stroke: #d43a2f; stroke-width: 1; }
svg .scale { stroke: #1e9e63; stroke-width: 1; stroke-dasharray: 3 2; }
ul.alerts li, ul.scales li { font-family: ui-monospace, monospace; }
h3.panel { font-size: 0.95em; margin: 0.6em 0 0; color: #555; }
.sev-page { color: #d43a2f; font-weight: bold; }
.sev-warn { color: #b07a00; font-weight: bold; }
footer { margin-top: 2em; color: #777; }
"""


def render_html(dump: dict, scales: Optional[List[dict]] = None) -> str:
    grouped = collect(dump)
    alerts = list(dump.get("alerts", ()))
    scales = scales or []
    scale_ts = [float(e.get("t_wall", 0.0)) for e in scales]
    kind = "fleet" if is_fleet(dump) else "replica"
    out = ["<!doctype html><html><head><meta charset='utf-8'>",
           f"<title>butterfly {kind} timeseries</title>",
           f"<style>{_CSS}</style></head><body>",
           f"<h1>butterfly {kind} timeseries</h1>",
           f"<p>{len(dump.get('samples', ()))} sample(s) &middot; "
           f"{len(alerts)} alert(s) &middot; "
           f"{len(scales)} scale event(s) &middot; schema "
           f"{html.escape(str(dump.get('schema', '?')))}</p>"]
    for src in sorted(grouped):
        if src:
            out.append(f"<h2>{html.escape(src)}</h2>")
        core, tier = split_tier_signals(list(grouped[src]))
        for group, names in (("", core), ("kv tier", tier)):
            if not names:
                continue
            if group:
                out.append(f"<h3 class='panel'>{group}</h3>")
            out.append("<table class='signals'>")
            for name in names:
                series = grouped[src][name]
                st = stats(series)
                alert_ts = [float(a.get("t_fleet", a.get("t_wall", 0.0)))
                            for a in alerts
                            if a.get("signal") == name
                            and (not src
                                 or str(a.get("source", "")) in
                                 (src, src.replace("scrape:", "")))]
                out.append(
                    "<tr>"
                    f"<td class='name'>{html.escape(name)}</td>"
                    f"<td>{_svg_sparkline(series, alert_ts, scale_ts)}</td>"
                    f"<td class='stat'>min {st['min']:g}<br>"
                    f"mean {st['mean']:g}</td>"
                    f"<td class='stat'>max {st['max']:g}<br>"
                    f"last {st['last']:g}</td></tr>")
            out.append("</table>")
    if scales:
        samples = dump.get("samples", ())
        t0 = sample_time(samples[0]) if samples else 0.0
        out.append("<h2>scale events</h2><ul class='scales'>")
        for e in scales:
            out.append(f"<li>{html.escape(_scale_line(e, t0))}</li>")
        out.append("</ul>")
    if alerts:
        out.append("<h2>alerts</h2><ul class='alerts'>")
        for a in alerts:
            sev = html.escape(str(a.get("severity", "?")))
            src = html.escape(str(a.get("source", "")))
            out.append(
                f"<li><span class='sev-{sev}'>[{sev}]</span> "
                f"{html.escape(str(a.get('rule', '?')))} on "
                f"{html.escape(str(a.get('signal', '?')))}"
                + (f" @ {src}" if src else "")
                + f" &mdash; value {a.get('value', 0):g}, "
                f"window {a.get('window', '?')}</li>")
        out.append("</ul>")
    rec = reconciliation(dump)
    if rec is not None:
        out.append(f"<footer>{rec['samples']} samples over "
                   f"{rec['span_s']:.1f}s at interval "
                   f"{dump.get('interval_s')}s &mdash; "
                   f"{100 * rec['coverage']:.1f}% of the expected "
                   f"window covered</footer>")
    else:
        out.append("<footer>no single-cadence reconciliation "
                   "(merged or short dump)</footer>")
    out.append("</body></html>")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render a dumped /debug/timeseries or "
                    "/fleet/timeseries body as a dashboard")
    ap.add_argument("dump", help="JSON file (the timeseries body)")
    ap.add_argument("--out", help="write HTML here (default: stdout)")
    ap.add_argument("--text", action="store_true",
                    help="unicode sparklines for terminals instead "
                         "of HTML")
    ap.add_argument("--flightrecorder", metavar="FR_JSON",
                    help="a saved /debug/flightrecorder body: its "
                         "kind=scale events become dashed vertical "
                         "annotations on every sparkline plus a "
                         "'scale events' listing")
    args = ap.parse_args(argv)
    try:
        dump = load_dump(args.dump)
        scales = (load_scale_events(args.flightrecorder)
                  if args.flightrecorder else [])
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    body = (render_text(dump, scales) if args.text
            else render_html(dump, scales))
    if args.out and not args.text:
        with open(args.out, "w") as f:
            f.write(body)
        print(f"wrote {args.out}")
    else:
        print(body)
    return 0


if __name__ == "__main__":
    sys.exit(main())
