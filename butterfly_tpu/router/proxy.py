"""Streaming-safe HTTP proxy tier over the replica pool.

Forwards ``POST /generate`` and ``POST /v1/completions`` verbatim to a
replica chosen by the routing policy, with exactly one failover rule:

    A request may be retried on the next-best replica IFF no response
    byte has been sent to the client.

Concretely (the failure matrix, see docs/serving.md):

* connection refused / reset at connect, or a malformed status line
  (replica SIGKILLed between accept and response)  -> retry next-best,
  and tell the pool so subsequent requests skip the corpse immediately.
* wedged-503 (the replica's heartbeat latch answers every request 503)
  -> retry next-best; the pool degrades the member until /health
  recovers.
* dead-pool member -> never attempted at all (the policy's candidate
  list excludes it); its arc of the hash ring fails over deterministically.
* backend died MID-STREAM (SSE bytes already forwarded) -> NO retry: a
  re-run would duplicate tokens the client already consumed. The
  truncation is propagated by closing the chunked response WITHOUT the
  terminating 0-chunk, so the client's HTTP layer reports an incomplete
  body instead of silently ending the stream.
* non-stream responses are fully buffered from the replica BEFORE the
  first client byte, so even a mid-body replica death stays retryable.
* 429 queue-full and 4xx are forwarded verbatim (Retry-After included):
  saturation is the client's backpressure signal, not a router fault.

SSE streaming passes through with incremental flush (`read1` +
re-chunk), so router-fronted streams deliver tokens with the same
cadence as direct ones; after de-chunking the bytes are identical.

Admin surface: ``GET /router/replicas`` (pool snapshot),
``POST /router/drain`` / ``/router/undrain`` with ``{"replica":
"host:port"}``, and the router's own ``GET /metrics`` — a second
obs/registry.py instance, so a fleet dashboard reads
``butterfly_router_*`` families without touching any replica.

stdlib-only (ThreadingHTTPServer + http.client), like serve/server.py.
"""
from __future__ import annotations

import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from butterfly_tpu.obs.registry import MetricsRegistry
from butterfly_tpu.router.policy import PrefixAffinityPolicy
from butterfly_tpu.router.pool import Replica, ReplicaPool

_RETRY = "retry"   # attempt failed before any client byte: try next
_SENT = "sent"     # a response (possibly truncated) reached the client

PROXIED_PATHS = ("/generate", "/v1/completions")


def extract_route_tokens(raw: bytes) -> Optional[List[int]]:
    """Best-effort token view of a request body for affinity hashing.

    Token-id requests (`tokens` / OpenAI list-form `prompt`) hash the
    ids themselves — bit-identical to what the replica's
    PrefixCachingAllocator will hash, so affinity lines up exactly with
    page reuse. String prompts hash their UTF-8 bytes: not the
    replica's exact token blocks (tokenizers may add BOS etc.), but
    self-consistent — same string -> same key -> same replica, which is
    all page reuse needs, since that replica hashes its own tokens
    consistently. Unparseable bodies return None — the replica will 400
    them; routing by load is fine."""
    try:
        obj = json.loads(raw or b"{}")
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(obj, dict):
        return None
    toks = obj.get("tokens")
    if toks is None:
        p = obj.get("prompt")
        if isinstance(p, str):
            return list(p.encode("utf-8"))
        toks = p
    if not isinstance(toks, list):
        return None
    try:
        return [int(t) for t in toks]
    except (ValueError, TypeError):
        return None


class RouterState:
    """Shared state for router handler threads: pool + policy + the
    router's own metrics registry (instruments are multi-writer here —
    handler threads — so updates go through one small lock, unlike the
    scheduler registry's single-writer contract)."""

    def __init__(self, pool: ReplicaPool, policy: PrefixAffinityPolicy,
                 registry: Optional[MetricsRegistry] = None,
                 read_timeout: float = 300.0):
        self.pool = pool
        self.policy = policy
        self.read_timeout = read_timeout
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.t_start = time.monotonic()
        self._mlock = threading.Lock()
        reg = self.registry
        self._c_req = reg.counter_family(
            "router_requests_total",
            "Proxy attempts by replica and outcome (ok/deadline/"
            "upstream_error/refused/wedged/truncated/client_gone)",
            ("replica", "outcome"))
        self._c_retry = reg.counter(
            "router_retries_total",
            "Requests re-dispatched to another replica before any "
            "response byte was sent")
        self._c_aff = reg.counter(
            "router_affinity_hits_total",
            "Requests dispatched to their prefix-affinity ring target")
        self._c_unroutable = reg.counter(
            "router_unroutable_total",
            "Requests refused outright: no routable replica")
        self._g_uptime = reg.gauge("router_uptime_seconds",
                                   "Router uptime")

    def count(self, replica: str, outcome: str) -> None:
        with self._mlock:
            self._c_req.labels(replica, outcome).inc()

    def inc(self, counter) -> None:
        with self._mlock:
            counter.inc()

    def metrics_text(self) -> str:
        self._g_uptime.set(time.monotonic() - self.t_start)
        return self.registry.render()


def make_router_handler(state: RouterState):
    class RouterHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet
            pass

        def _json(self, code: int, obj, headers=None) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        # -- read-only surface ----------------------------------------------

        def do_GET(self):
            path = self.path.split("?")[0]
            if path == "/router/replicas":
                self._json(200, {"replicas": state.pool.snapshot()})
            elif path == "/metrics":
                body = state.metrics_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/health":
                snaps = state.pool.snapshot()
                live = sum(1 for s in snaps if s["state"] == "live")
                code = 200 if live else 503
                self._json(code, {"status": "ok" if live else "error",
                                  "replicas_live": live,
                                  "replicas_total": len(snaps)})
            else:
                self._json(404, {"error": "not found"})

        # -- admin + proxy dispatch ------------------------------------------

        def do_POST(self):
            if self.path in PROXIED_PATHS:
                self._proxy(self.path)
            elif self.path in ("/router/drain", "/router/undrain"):
                self._admin(draining=self.path.endswith("/drain"))
            else:
                self._json(404, {"error": "not found"})

        def _admin(self, draining: bool) -> None:
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                rid = body.get("replica")
            except (ValueError, TypeError):
                rid = None
            if not rid:
                self._json(400, {"error": 'body must be {"replica": '
                                          '"host:port"}'})
                return
            snap = state.pool.set_drain(str(rid), draining)
            if snap is None:
                self._json(404, {"error": f"unknown replica {rid}"})
            else:
                self._json(200, snap)

        # -- the proxy path ---------------------------------------------------

        def _proxy(self, path: str) -> None:
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
            except (ValueError, OSError):
                self._json(400, {"error": "unreadable body"})
                return
            self._dispatch(path, body)

        def _dispatch(self, path: str, body: bytes,
                      candidates=None, affinity_rid=None) -> Optional[str]:
            """Plan (unless the caller — e.g. the fleet control plane's
            classifier — already planned) and walk the candidate list
            with the single failover rule. Returns the rid of the
            replica that produced the client's response (the fleet
            control plane's trace records it), or None when nothing
            could serve (an error response was sent instead)."""
            if candidates is None:
                candidates, affinity_rid = state.policy.plan(
                    extract_route_tokens(body))
            if not candidates:
                state.inc(state._c_unroutable)
                self._json(503, {"error": "no live replicas"},
                           headers={"Retry-After": "1"})
                return None
            last = ""
            for i, rep in enumerate(candidates):
                if i > 0:
                    state.inc(state._c_retry)
                elif rep.rid == affinity_rid:
                    state.inc(state._c_aff)
                state.pool.note_dispatch(rep.rid)
                try:
                    result = self._attempt(rep, path, body)
                finally:
                    state.pool.note_done(rep.rid)
                if result == _SENT:
                    return rep.rid
                last = rep.rid
            self._json(502, {"error": "all replicas failed "
                                      f"(last tried: {last})"},
                       headers={"Retry-After": "1"})
            return None

        def _attempt(self, rep: Replica, path: str, body: bytes) -> str:
            """One forwarding attempt. Returns _SENT once ANY response
            byte has reached the client (success, forwarded error, or
            propagated truncation) — _RETRY strictly before that."""
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=state.read_timeout)
            try:
                headers = {"Content-Type": self.headers.get(
                    "Content-Type", "application/json")}
                # X-Deadline-Ms rides through: the replica re-anchors
                # the remaining budget at ITS arrival (forwarding is
                # fast relative to any real deadline)
                for k in ("X-Request-Id", "X-Deadline-Ms", "X-Priority"):
                    v = self.headers.get(k)
                    if v:
                        headers[k] = v
                try:
                    conn.request("POST", path, body=body, headers=headers)
                    resp = conn.getresponse()
                except (OSError, http.client.HTTPException) as e:
                    # refused/reset/garbled before a status line: the
                    # replica is gone — fail it fast so the NEXT request
                    # skips it without waiting for the prober
                    state.pool.note_connect_failure(rep.rid, str(e))
                    state.pool.note_leg_failure(rep.rid, str(e))
                    state.count(rep.rid, "refused")
                    return _RETRY
                if resp.status == 503:
                    # wedged replica: every response is 503 until its
                    # operator intervenes; degraded (not dead — the
                    # process answers) and retryable (no client bytes)
                    try:
                        resp.read()
                    except OSError:
                        pass
                    state.pool.note_wedged(rep.rid, "wedged-503")
                    state.pool.note_leg_failure(rep.rid, "wedged-503")
                    state.count(rep.rid, "wedged")
                    return _RETRY
                if resp.status >= 500 and resp.status != 504:
                    # replica-side fault (500/502/...): no client byte
                    # has been sent, so the single failover rule says
                    # retry next-best rather than forward the fault.
                    # 504 is EXEMPT — it is the request's own deadline
                    # verdict (terminal), not replica health, and a
                    # retry would burn compute for a blown budget.
                    try:
                        resp.read()
                    except OSError:
                        pass
                    state.pool.note_leg_failure(rep.rid,
                                                f"http {resp.status}")
                    state.count(rep.rid, "upstream_error")
                    return _RETRY
                ctype = resp.getheader("Content-Type", "")
                if resp.status == 200 and \
                        ctype.startswith("text/event-stream"):
                    return self._stream_through(rep, resp)
                # non-stream: buffer the WHOLE body before the first
                # client byte, so a mid-body replica death is retryable
                try:
                    data = resp.read()
                except (OSError, http.client.HTTPException) as e:
                    state.pool.note_connect_failure(rep.rid, str(e))
                    state.pool.note_leg_failure(rep.rid, str(e))
                    state.count(rep.rid, "refused")
                    return _RETRY
                state.pool.note_leg_ok(rep.rid)
                fwd = {"X-Routed-To": rep.rid}
                for k in ("X-Request-Id", "Retry-After"):
                    v = resp.getheader(k)
                    if v:
                        fwd[k] = v
                self.send_response(resp.status)
                self.send_header("Content-Type",
                                 ctype or "application/json")
                self.send_header("Content-Length", str(len(data)))
                for k, v in fwd.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)
                # >= 500 was retried above; the only 5xx that lands
                # here is 504 — the request's own deadline verdict
                state.count(rep.rid, "deadline" if resp.status == 504
                            else "ok")
                return _SENT
            finally:
                conn.close()

        def _stream_through(self, rep: Replica, resp) -> str:
            """SSE passthrough with incremental flush: re-chunk whatever
            the replica has ready (`read1` returns per-chunk data
            without waiting to fill the buffer), so tokens reach the
            client at the replica's cadence."""
            self.send_response(200)
            self.send_header("Content-Type",
                             resp.getheader("Content-Type"))
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Transfer-Encoding", "chunked")
            rid = resp.getheader("X-Request-Id")
            if rid:
                self.send_header("X-Request-Id", rid)
            self.send_header("X-Routed-To", rep.rid)
            self.end_headers()
            while True:
                try:
                    data = resp.read1(65536)
                except (OSError, http.client.HTTPException) as e:
                    # replica died mid-stream: bytes are already with
                    # the client, so a retry would duplicate tokens.
                    # Propagate the truncation: close WITHOUT the
                    # terminating 0-chunk so the client's HTTP layer
                    # sees an incomplete body.
                    state.pool.note_connect_failure(rep.rid,
                                                    f"mid-stream: {e}")
                    state.pool.note_leg_failure(rep.rid,
                                                f"mid-stream: {e}")
                    state.count(rep.rid, "truncated")
                    self.close_connection = True
                    return _SENT
                if not data:
                    break
                try:
                    self.wfile.write(f"{len(data):X}\r\n".encode()
                                     + data + b"\r\n")
                    self.wfile.flush()
                except OSError:
                    # CLIENT went away — the replica is fine; just stop
                    # forwarding (the replica notices its own dead
                    # socket via the handler's disconnect cancel)
                    state.count(rep.rid, "client_gone")
                    self.close_connection = True
                    return _SENT
            try:
                self.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass
            state.pool.note_leg_ok(rep.rid)
            state.count(rep.rid, "ok")
            return _SENT

    return RouterHandler


def route_forever(backends: List[str], host: str = "0.0.0.0",
                  port: int = 8100, page_size: int = 16,
                  affinity_blocks: int = 4, saturate_after: int = 8,
                  probe_interval: float = 0.5, probe_timeout: float = 2.0,
                  dead_after: int = 3, read_timeout: float = 300.0,
                  ready_event: Optional[threading.Event] = None):
    """Blocking router loop (the `butterfly route` entrypoint).

    `page_size` and `affinity_blocks` should match the replicas'
    --page-size so affinity keys align with their prefix-cache blocks.
    """
    registry = MetricsRegistry()
    pool = ReplicaPool(backends, probe_interval=probe_interval,
                       probe_timeout=probe_timeout, dead_after=dead_after,
                       registry=registry)
    policy = PrefixAffinityPolicy(pool, page_size=page_size,
                                  affinity_blocks=affinity_blocks,
                                  saturate_after=saturate_after)
    state = RouterState(pool, policy, registry=registry,
                        read_timeout=read_timeout)
    pool.probe_all()   # one synchronous round: accurate states at bind
    pool.start()

    class _Server(ThreadingHTTPServer):
        request_queue_size = 128  # match serve/server.py's burst sizing

    httpd = _Server((host, port), make_router_handler(state))
    state.httpd = httpd
    if ready_event is not None:
        ready_event.set()
    n_live = len(pool.routable())
    print(f"[butterfly] routing on {host}:{port} across "
          f"{len(pool.replicas)} replicas ({n_live} live)", flush=True)
    try:
        httpd.serve_forever()
    finally:
        pool.stop()
        httpd.server_close()
    return 0
