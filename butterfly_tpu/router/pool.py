"""Replica pool: health-aware membership for the multi-replica router.

Each backend `butterfly serve` replica is tracked as a `Replica` with a
liveness state plus an orthogonal admin `drain` flag:

* ``live``      last probe returned 200 — routable.
* ``degraded``  reachable-but-unhealthy (a wedged replica's 503) or a
                fresh connection failure below the dead threshold —
                excluded from routing, re-probed at the normal cadence.
* ``dead``      >= `dead_after` consecutive connection failures — re-
                probed with jittered exponential backoff so a downed
                host isn't hammered, and a restarted one is found within
                `backoff_max`.
* ``draining``  admin-requested (POST /router/drain): no NEW requests
                route to it, in-flight ones finish; probing continues so
                an undrain returns it at its true liveness.

The prober is one daemon thread issuing `GET /health` per due replica
(serve/server.py answers it without taking the scheduler lock, so a
busy replica still probes fast). The 200 body carries `queue_depth` and
`active` — the load signal the least-loaded policy reads — so the
router never scrapes full Prometheus text on the request path.

Proxy feedback short-circuits the prober: a connection-refused or
wedged-503 observed while forwarding marks the replica immediately, so
the very next request skips it instead of waiting out a probe cycle.

stdlib-only; thread-safe (one lock around membership state — probe I/O
happens outside it).
"""
from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from typing import Dict, List, Optional

LIVE = "live"
DEGRADED = "degraded"
DEAD = "dead"
DRAINING = "draining"


class Replica:
    """One backend's membership record. Mutated only under the pool lock."""

    __slots__ = ("rid", "host", "port", "liveness", "drain", "outstanding",
                 "queue_depth", "active", "fails", "probes", "last_probe_t",
                 "next_probe_t", "last_error", "role", "free_pages",
                 "inflight", "clock_offset", "metrics_families",
                 "metrics_t", "breaker", "breaker_fails",
                 "breaker_next_probe_t", "breaker_opens", "series",
                 "scrape_fails")

    def __init__(self, rid: str, host: str, port: int):
        self.rid = rid
        self.host = host
        self.port = port
        # optimistic start: routable until a probe (or proxy feedback)
        # says otherwise — the router must not 503 a healthy fleet just
        # because the first probe round hasn't completed yet
        self.liveness = LIVE
        self.drain = False
        self.outstanding = 0     # router-tracked in-flight proxied requests
        self.queue_depth = 0     # from the last /health scrape
        self.active = 0          # from the last /health scrape
        self.role = "both"       # fleet tier (prefill|decode|both), scraped
        self.free_pages: Optional[int] = None  # KV page headroom, scraped
        self.inflight = 0        # decode blocks in flight, scraped
        # estimated replica_wall - router_wall clock offset (seconds),
        # from the /health probe RTT midpoint: the replica stamps
        # `now_wall` into its response, and offset = now_wall - the
        # midpoint of our send/receive wall times. Accurate to ~RTT/2 —
        # what the fleet trace merge needs to place a replica's span
        # events on the control plane's clock. None until a probe with
        # a now_wall-carrying replica lands.
        self.clock_offset: Optional[float] = None
        # last parsed /metrics exposition (obs.registry.parse_exposition
        # output) when the pool scrapes metrics; feeds /fleet/metrics
        self.metrics_families: Optional[dict] = None
        self.metrics_t: Optional[float] = None
        # per-replica gauge history ring (ISSUE 16): each successful
        # scrape appends one {"t_wall", "signals"} entry — this
        # replica's signal trajectory as seen from the scraping
        # process's clock — the /fleet/timeseries merge input and the
        # window the control plane's per-replica alert rules read
        self.series: deque = deque(maxlen=240)
        # consecutive FAILED scrapes (scrape_metrics mode only; probe
        # failures count too — a down replica reports nothing): drives
        # the stale-gauge drop in /fleet/metrics and the
        # replica-flatline alert rule
        self.scrape_fails = 0
        self.fails = 0           # consecutive probe/connect failures
        self.probes = 0
        self.last_probe_t: Optional[float] = None
        self.next_probe_t = 0.0  # due immediately
        self.last_error = ""
        # Serving-path circuit breaker, ORTHOGONAL to probe liveness: a
        # replica whose /health answers fine can still fail every
        # request leg (wedged scheduler, chaos-injected faults,
        # timeouts). `breaker_threshold` consecutive leg failures OPEN
        # the breaker — candidates() skips it entirely — and after
        # `breaker_cooldown` it goes HALF-OPEN: exactly one probe
        # request is let through; success closes it, failure re-opens.
        self.breaker = "closed"          # closed | open | half_open
        self.breaker_fails = 0           # consecutive leg failures
        self.breaker_next_probe_t = 0.0  # when open -> half_open
        self.breaker_opens = 0           # lifetime open transitions

    @property
    def state(self) -> str:
        """Reported state: the admin drain flag masks liveness."""
        return DRAINING if self.drain else self.liveness

    @property
    def routable(self) -> bool:
        return self.liveness == LIVE and not self.drain

    def serves(self, role: Optional[str]) -> bool:
        """Does this replica belong to the given fleet tier? role=None
        means any; 'both' replicas belong to every tier."""
        return role is None or self.role == role or self.role == "both"

    def load_score(self):
        """Ordering key for least-loaded fallback: router-tracked
        outstanding first (always fresh), then the replica's own scraped
        backlog, then KV page PRESSURE (negated free-page headroom: a
        replica one admission from page exhaustion — and therefore from
        preempting its own runners — must stop winning least-outstanding
        ties; unknown headroom scores as zero pages, the conservative
        read for a member that has never answered a probe), then rid for
        determinism."""
        return (self.outstanding, self.queue_depth + self.active,
                -(self.free_pages or 0), self.rid)

    def snapshot(self) -> dict:
        return {"replica": self.rid, "state": self.state, "role": self.role,
                "outstanding": self.outstanding,
                "queue_depth": self.queue_depth, "active": self.active,
                "free_pages": self.free_pages, "inflight": self.inflight,
                "clock_offset_s": self.clock_offset,
                "consecutive_failures": self.fails,
                "scrape_fails": self.scrape_fails,
                "breaker": self.breaker,
                "breaker_fails": self.breaker_fails,
                "breaker_opens": self.breaker_opens,
                "probes": self.probes, "last_error": self.last_error}


def _flat_gauges(families: dict) -> Dict[str, float]:
    """Unlabeled gauge samples from a parsed /metrics exposition, keyed
    by the short signal name (the `butterfly_` prefix stripped so the
    fleet timeline and a replica's own /debug/timeseries speak the same
    signal vocabulary). Labeled gauge families are skipped — a history
    ring wants scalar trajectories."""
    out: Dict[str, float] = {}
    for name, fam in families.items():
        if fam.get("type") != "gauge":
            continue
        v = fam["samples"].get((name, ()))
        if v is None:
            continue
        short = name[len("butterfly_"):] \
            if name.startswith("butterfly_") else name
        out[short] = float(v)
    return out


def parse_backend(spec: str) -> tuple:
    """'host:port' -> (host, port); bare ':port'/'port' default host."""
    spec = spec.strip()
    if ":" in spec:
        host, _, port = spec.rpartition(":")
        host = host or "127.0.0.1"
    else:
        host, port = "127.0.0.1", spec
    return host, int(port)


class ReplicaPool:
    def __init__(self, backends: List[str], probe_interval: float = 0.5,
                 probe_timeout: float = 2.0, dead_after: int = 3,
                 backoff_base: float = 0.5, backoff_max: float = 10.0,
                 registry=None, scrape_metrics: bool = False,
                 breaker_threshold: int = 3,
                 breaker_cooldown: float = 2.0):
        if not backends:
            raise ValueError("router needs at least one backend")
        self.probe_interval = probe_interval
        self.probe_timeout = probe_timeout
        self.dead_after = dead_after
        # serving-path circuit breaker (see Replica.breaker): leg
        # failures to open, seconds open before a half-open probe
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown = breaker_cooldown
        # fleet mode: each successful /health probe is followed by a
        # GET /metrics scrape, parsed and cached on the Replica — the
        # control plane's /fleet/metrics rollup reads the cache instead
        # of fanning out N HTTP calls per dashboard scrape. Off for the
        # plain router (no aggregation surface there).
        self.scrape_metrics = scrape_metrics
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.replicas: Dict[str, Replica] = {}
        for spec in backends:
            host, port = parse_backend(spec)
            rid = f"{host}:{port}"
            if rid in self.replicas:
                raise ValueError(f"duplicate backend {rid}")
            self.replicas[rid] = Replica(rid, host, port)
        # optional observer for breaker OPEN transitions (the fleet
        # control plane's flight recorder hooks this): called with the
        # replica id, under the pool lock — must be quick and must
        # never call back into the pool
        self.on_breaker_open = None
        # optional per-probe series observer (scrape_metrics mode): the
        # control plane hooks this to run its per-replica alert rules
        # (replica-flatline, pages-free-slope) against the gauge
        # history. Called OUTSIDE the pool lock after each probe with
        # (rid, recent series tail, consecutive scrape failures); must
        # never call back into the pool
        self.on_series_sample = None
        # per-replica outstanding gauge on the router's own registry
        self._g_out = None
        self._c_breaker_open = None
        if registry is not None:
            self._g_out = registry.gauge_family(
                "router_outstanding_requests",
                "Requests currently proxied to each replica", ("replica",))
            for rid in self.replicas:
                self._g_out.labels(rid).set(0)
            self._c_breaker_open = registry.counter_family(
                "router_breaker_open_total",
                "Circuit-breaker open transitions per replica "
                "(breaker_threshold consecutive request-leg failures; "
                "half-open probes after breaker_cooldown)", ("replica",))

    # -- membership queries --------------------------------------------------

    def get(self, rid: str) -> Optional[Replica]:
        return self.replicas.get(rid)

    def routable(self) -> List[Replica]:
        with self._lock:
            return [r for r in self.replicas.values() if r.routable]

    def candidates(self, role: Optional[str] = None) -> List[Replica]:
        """Replicas worth attempting, best liveness first: routable ones,
        else (all degraded — e.g. one connect blip marked the only
        replica before its re-probe) the degraded ones as a last resort.
        Dead and draining members are never returned — dead is the
        pool's signal the proxy must not waste a connect on it — and
        neither are members whose circuit breaker is OPEN (a half-open
        member is returned only while it has no in-flight probe, so one
        request at a time tests the recovery).
        `role` restricts to one fleet tier ('prefill'/'decode'; 'both'
        replicas belong to every tier) — the control plane's
        disaggregated planner asks per tier, the plain router asks for
        all; while a whole tier's breakers are open the planner gets an
        empty list and the disagg path degrades to direct dispatch."""
        now = time.monotonic()
        with self._lock:
            live = [r for r in self.replicas.values()
                    if r.routable and r.serves(role)
                    and self._breaker_admits(r, now)]
            if live:
                return live
            return [r for r in self.replicas.values()
                    if r.liveness == DEGRADED and not r.drain
                    and r.serves(role) and self._breaker_admits(r, now)]

    def _breaker_admits(self, r: Replica, now: float) -> bool:
        """Lock held. Open breakers flip to half-open once the cooldown
        passes; a half-open member admits exactly one probe request at
        a time (outstanding == 0)."""
        if r.breaker == "closed":
            return True
        if r.breaker == "open":
            if now < r.breaker_next_probe_t:
                return False
            r.breaker = "half_open"
        return r.outstanding == 0

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [r.snapshot() for r in self.replicas.values()]

    # -- proxy feedback ------------------------------------------------------

    def note_dispatch(self, rid: str) -> None:
        with self._lock:
            r = self.replicas[rid]
            r.outstanding += 1
            if self._g_out is not None:
                self._g_out.labels(rid).set(r.outstanding)

    def note_done(self, rid: str) -> None:
        with self._lock:
            r = self.replicas[rid]
            r.outstanding = max(0, r.outstanding - 1)
            if self._g_out is not None:
                self._g_out.labels(rid).set(r.outstanding)

    def note_connect_failure(self, rid: str, err: str = "") -> None:
        """Proxy saw a refused/reset connect: count it toward dead and
        stop routing there now — don't wait for the next probe cycle."""
        with self._lock:
            self._fail(self.replicas[rid], err or "connect failed",
                       time.monotonic())

    def note_wedged(self, rid: str, err: str = "") -> None:
        """Proxy saw a wedged-503: reachable but unhealthy. Degrade
        without advancing toward dead (the process is up; its prober
        probe will flip it back the moment /health recovers)."""
        with self._lock:
            r = self.replicas[rid]
            if r.liveness == LIVE:
                r.liveness = DEGRADED
            r.last_error = err or "503 from replica"

    # -- circuit breaker (request-leg feedback) -----------------------------

    def note_leg_ok(self, rid: str) -> None:
        """A request leg to `rid` produced a usable response: reset the
        consecutive-failure count; a half-open breaker CLOSES (the
        probe succeeded — full restore)."""
        with self._lock:
            r = self.replicas.get(rid)
            if r is None:
                return
            r.breaker_fails = 0
            r.breaker = "closed"

    def note_leg_failure(self, rid: str, err: str = "") -> None:
        """A request leg to `rid` failed (refused, wedged-503, timeout,
        truncated, bad body). `breaker_threshold` consecutive failures
        open the breaker; any failure during half-open re-opens it
        immediately — one bad probe is enough evidence."""
        with self._lock:
            r = self.replicas.get(rid)
            if r is None:
                return
            r.breaker_fails += 1
            if r.breaker == "half_open" \
                    or r.breaker_fails >= self.breaker_threshold:
                self._open_breaker(r, err)

    def _open_breaker(self, r: Replica, err: str) -> None:
        """Lock held."""
        if r.breaker != "open":
            r.breaker_opens += 1
            if self._c_breaker_open is not None:
                self._c_breaker_open.labels(r.rid).inc()
            if self.on_breaker_open is not None:
                try:
                    self.on_breaker_open(r.rid)
                except Exception:
                    pass  # an observer must never break routing
        r.breaker = "open"
        r.breaker_next_probe_t = time.monotonic() + self.breaker_cooldown
        if err:
            r.last_error = err

    def breaker_opens_total(self) -> int:
        with self._lock:
            return sum(r.breaker_opens for r in self.replicas.values())

    # -- membership changes (fleet elasticity) -------------------------------

    def add(self, spec: str) -> str:
        """Register a new backend at runtime (the autoscaler's
        spawn-attach). The member starts optimistically LIVE with
        next_probe_t due immediately — the very next probe cycle (or an
        explicit probe_one) learns its role and load signals. Returns
        the canonical rid; raises on a duplicate."""
        host, port = parse_backend(spec)
        rid = f"{host}:{port}"
        with self._lock:
            if rid in self.replicas:
                raise ValueError(f"duplicate backend {rid}")
            self.replicas[rid] = Replica(rid, host, port)
            if self._g_out is not None:
                self._g_out.labels(rid).set(0)
        return rid

    def remove(self, rid: str) -> bool:
        """Forget a backend at runtime (the autoscaler's retire, called
        AFTER drain + stop — the pool does no draining itself). False
        if the rid is unknown. The last member cannot be removed: an
        empty pool can route nothing and __init__ forbids starting
        that way."""
        with self._lock:
            if rid not in self.replicas:
                return False
            if len(self.replicas) == 1:
                raise ValueError("cannot remove the last replica")
            del self.replicas[rid]
            return True

    # -- admin ---------------------------------------------------------------

    def set_drain(self, rid: str, draining: bool) -> Optional[dict]:
        with self._lock:
            r = self.replicas.get(rid)
            if r is None:
                return None
            r.drain = draining
            return r.snapshot()

    # -- probing -------------------------------------------------------------

    def probe_one(self, r: Replica) -> None:
        """Synchronous probe of one replica; state applied under the
        lock, network I/O outside it."""
        url = f"http://{r.host}:{r.port}/health"
        now = time.monotonic()
        w0 = time.time()
        try:
            with urllib.request.urlopen(url,
                                        timeout=self.probe_timeout) as resp:
                body = json.loads(resp.read() or b"{}")
            ok, detail = True, body
        except urllib.error.HTTPError as e:  # reachable, unhealthy (503)
            ok, detail = False, f"http {e.code}"
            e.close()
        except Exception as e:  # refused / timeout / reset / bad JSON
            ok, detail = None, f"{type(e).__name__}: {e}"
        w1 = time.time()
        scraped = self._scrape(r) if ok and self.scrape_metrics else None
        series_tail = None
        scrape_fails = 0
        with self._lock:
            r.probes += 1
            r.last_probe_t = now
            if ok:
                r.liveness = LIVE
                r.fails = 0
                r.last_error = ""
                r.queue_depth = int(detail.get("queue_depth", 0) or 0)
                r.active = int(detail.get("active", 0) or 0)
                # fleet signals (serve/server.py /health): absent on a
                # pre-fleet replica — keep the conservative defaults
                r.role = str(detail.get("role") or "both")
                fp = detail.get("free_pages")
                r.free_pages = int(fp) if fp is not None else None
                r.inflight = int(detail.get("inflight_depth", 0) or 0)
                # clock offset from the probe RTT midpoint: the replica
                # stamped now_wall somewhere inside [w0, w1]; the
                # midpoint is the minimum-error estimate without a
                # second exchange (NTP's trick). Error bound ~RTT/2.
                nw = detail.get("now_wall")
                if nw is not None:
                    r.clock_offset = float(nw) - (w0 + w1) / 2.0
                if scraped is not None:
                    r.metrics_families = scraped
                    r.metrics_t = now
                    r.scrape_fails = 0
                    # gauge history append (ISSUE 16): stamped with the
                    # probe RTT midpoint on THIS process's wall clock,
                    # so the fleet merge needs no offset shift for
                    # scrape-derived samples
                    r.series.append({
                        "t_wall": (w0 + w1) / 2.0,
                        "signals": _flat_gauges(scraped)})
                elif self.scrape_metrics:
                    r.scrape_fails += 1
                r.next_probe_t = now + self.probe_interval
            elif ok is False:  # wedged: degraded, normal re-probe cadence
                r.liveness = DEGRADED
                r.last_error = detail
                if self.scrape_metrics:
                    r.scrape_fails += 1
                r.next_probe_t = now + self.probe_interval
            else:
                if self.scrape_metrics:
                    r.scrape_fails += 1
                self._fail(r, detail, now)
            if self.scrape_metrics and self.on_series_sample is not None:
                series_tail = list(r.series)[-16:]
                scrape_fails = r.scrape_fails
        if series_tail is not None:
            try:  # an observer must never break probing
                self.on_series_sample(r.rid, series_tail, scrape_fails)
            except Exception:
                pass

    def _scrape(self, r: Replica):
        """Fetch + parse one replica's /metrics (network + parse OUTSIDE
        the pool lock). Returns parsed families or None on any failure —
        a replica whose /metrics hiccups keeps its last good scrape."""
        from butterfly_tpu.obs.registry import parse_exposition
        try:
            url = f"http://{r.host}:{r.port}/metrics"
            with urllib.request.urlopen(url,
                                        timeout=self.probe_timeout) as resp:
                return parse_exposition(resp.read().decode(
                    "utf-8", "replace"))
        except Exception:
            return None

    def metrics_by_replica(self) -> Dict[str, dict]:
        """Last parsed /metrics scrape per replica (fleet rollup input);
        replicas never scraped (down, or scrape_metrics off) are absent."""
        with self._lock:
            return {rid: r.metrics_families
                    for rid, r in self.replicas.items()
                    if r.metrics_families is not None}

    def series_by_replica(self) -> Dict[str, List[dict]]:
        """Each replica's scrape-derived gauge history ring (the
        /fleet/timeseries merge input); empty rings are absent. Entries
        are stamped on THIS process's wall clock (probe RTT midpoint),
        so they merge at offset zero."""
        with self._lock:
            return {rid: list(r.series)
                    for rid, r in self.replicas.items() if r.series}

    def stale_scrapes(self, after: int) -> List[str]:
        """Replica ids whose last `after`+ scrape attempts all failed:
        their re-exported gauges are STALE (frozen at the last good
        scrape) and /fleet/metrics drops them rather than serving a
        flat line as live data."""
        with self._lock:
            return [rid for rid, r in self.replicas.items()
                    if r.scrape_fails >= after]

    def _fail(self, r: Replica, err: str, now: float) -> None:
        """Shared connect-failure accounting (lock held): escalate
        degraded -> dead and schedule the jittered-backoff re-probe."""
        r.fails += 1
        r.last_error = err
        if r.fails >= self.dead_after:
            r.liveness = DEAD
            # jittered exponential backoff: doubling from the threshold,
            # capped, x[0.5, 1.5) jitter so a fleet of routers doesn't
            # re-probe a recovering host in lockstep
            delay = min(self.backoff_max,
                        self.backoff_base
                        * 2 ** min(r.fails - self.dead_after, 20))
            r.next_probe_t = now + delay * (0.5 + random.random())
        else:
            r.liveness = DEGRADED
            r.next_probe_t = now + self.probe_interval

    def probe_due(self) -> int:
        """Probe every replica whose next_probe_t has passed. Returns how
        many were probed (tests drive this synchronously)."""
        now = time.monotonic()
        with self._lock:
            due = [r for r in self.replicas.values()
                   if r.next_probe_t <= now]
        for r in due:
            self.probe_one(r)
        return len(due)

    def probe_all(self) -> None:
        for r in list(self.replicas.values()):
            self.probe_one(r)

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.probe_due()
            self._stop.wait(self.probe_interval / 2)

    def start(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
