"""Multi-replica routing tier: a stdlib-only front end over N backend
`butterfly serve` replicas (ISSUE 2).

Layer map:
  pool.py    replica membership + health: polls each backend's
             GET /health, tracks live/degraded/draining/dead with
             jittered exponential backoff on dead-replica re-probe
  policy.py  routing decisions: prefix-affinity consistent-hash ring
             (same page-block hashing as cache/prefix.py) with
             least-outstanding-requests fallback
  proxy.py   the HTTP tier: streaming-safe passthrough of /generate and
             /v1/completions, retry-before-first-byte failover, admin
             drain/undrain, and the router's own /metrics

The router multiplies effective KV-cache capacity: sending same-prefix
requests to the same replica means its PrefixCachingAllocator serves
their prompts from pages already in HBM (SGLang-style cache-aware
routing), while health-aware failover turns single-node continuous
batching into a fleet (vLLM-style deployments).
"""
from butterfly_tpu.router.policy import PrefixAffinityPolicy  # noqa: F401
from butterfly_tpu.router.pool import Replica, ReplicaPool  # noqa: F401
from butterfly_tpu.router.proxy import (  # noqa: F401
    RouterState, make_router_handler, route_forever)
