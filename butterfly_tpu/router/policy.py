"""Routing policy: prefix-affinity consistent hashing with a
least-outstanding-requests fallback.

Why affinity beats round-robin here: each replica runs a
PrefixCachingAllocator (cache/prefix.py) whose page registry is keyed by
SHA-256 chain hashes over page-sized token blocks. Two requests sharing
a prompt prefix only reuse K/V pages if they land on the SAME replica —
spread them round-robin and every replica pays the full prefill;
concentrate them and one replica serves the shared blocks from HBM
(SGLang-style cache-aware routing). The affinity key is therefore
computed with the very same block hashing (`chain_block_hashes`) the
allocator uses, over the prompt's leading `affinity_blocks` full blocks:
requests agreeing on that many leading blocks — the shared-system-prompt
case — get the same key, regardless of how their tails differ.

The key lands on a consistent-hash ring (vnode-replicated so removal of
one replica only remaps its own arc, keeping every OTHER replica's warm
cache intact). Ring order also provides the deterministic failover
sequence: when the affinity target is saturated, draining, or down, the
request falls back to least-outstanding-requests among the remaining
candidates — cache misses spread by load instead of piling onto one
secondary.

stdlib-only.
"""
from __future__ import annotations

import hashlib
from typing import List, Optional, Tuple

from butterfly_tpu.cache.prefix import chain_block_hashes
from butterfly_tpu.router.pool import Replica, ReplicaPool


def affinity_key(tokens: Optional[List[int]], page_size: int,
                 affinity_blocks: int) -> Optional[bytes]:
    """Routing key for a prompt: the chain digest of its leading full
    page-blocks (capped at `affinity_blocks`), or a digest of the raw
    tokens for sub-block prompts. None when there is nothing to hash —
    the caller then routes purely by load."""
    if not tokens:
        return None
    hashes = chain_block_hashes(tokens, page_size, affinity_blocks)
    if hashes:
        return hashes[-1]
    # shorter than one block: still deterministic so identical tiny
    # prompts share a replica (their sub-page K/V can't be shared, but
    # sampler/compile warmth and dedup still benefit)
    return hashlib.sha256(
        b"," .join(b"%d" % t for t in tokens)).digest()


def _point(data: bytes) -> int:
    return int.from_bytes(hashlib.sha256(data).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring over replica ids with virtual nodes."""

    def __init__(self, rids: List[str], vnodes: int = 64):
        points: List[Tuple[int, str]] = []
        for rid in rids:
            for i in range(vnodes):
                points.append((_point(f"{rid}#{i}".encode()), rid))
        points.sort()
        self._points = points

    def ordered(self, key: bytes) -> List[str]:
        """Distinct replica ids in ring order starting at `key`'s
        successor point: element 0 is the affinity target, the rest the
        deterministic failover sequence."""
        if not self._points:
            return []
        import bisect
        start = bisect.bisect_right(self._points,
                                    (int.from_bytes(key[:8], "big"), ""))
        seen, order = set(), []
        n = len(self._points)
        for i in range(n):
            rid = self._points[(start + i) % n][1]
            if rid not in seen:
                seen.add(rid)
                order.append(rid)
        return order


class PrefixAffinityPolicy:
    """Pick an ordered candidate list for one request.

    `plan(tokens)` returns ``(candidates, affinity_rid)``:

    * ``candidates`` — replicas to try in order (the proxy walks this on
      retryable failures); empty means nothing is routable.
    * ``affinity_rid`` — the ring target's id when the FIRST candidate is
      it (i.e. the request is being routed for cache affinity), else
      None. The proxy counts router_affinity_hits_total from this.

    The affinity target leads unless it is saturated (its outstanding
    count reaches `saturate_after`) or not routable; remaining
    candidates follow by least-outstanding.
    """

    def __init__(self, pool: ReplicaPool, page_size: int = 16,
                 affinity_blocks: int = 4, saturate_after: int = 8,
                 vnodes: int = 64):
        self.pool = pool
        self.page_size = page_size
        self.affinity_blocks = affinity_blocks
        self.saturate_after = saturate_after
        self._vnodes = vnodes
        self.ring = HashRing(list(pool.replicas), vnodes=vnodes)

    def rebuild_ring(self) -> None:
        """Re-derive the ring from current pool membership — the fleet
        elasticity hook (autoscaler spawn/retire). Vnode placement is
        deterministic per rid, so surviving replicas keep their arcs
        (consistent hashing's point): only the joined/removed member's
        arcs remap. Atomic swap: plan() readers see old or new ring,
        never a half-built one."""
        self.ring = HashRing(list(self.pool.replicas), vnodes=self._vnodes)

    def plan(self, tokens: Optional[List[int]], role: Optional[str] = None
             ) -> Tuple[List[Replica], Optional[str]]:
        """`role` restricts the candidate pool to one fleet tier
        (pool.candidates(role)); the ring is still walked over ALL
        replica ids, so a tier's affinity arcs stay stable when the
        other tier's membership changes."""
        cands = self.pool.candidates(role)
        if not cands:
            return [], None
        by_load = sorted(cands, key=Replica.load_score)
        key = affinity_key(tokens, self.page_size, self.affinity_blocks)
        if key is None:
            return by_load, None
        by_rid = {r.rid: r for r in cands}
        target = None
        for rid in self.ring.ordered(key):
            r = by_rid.get(rid)
            if r is not None:
                target = r
                break
        if target is None or target.outstanding >= self.saturate_after:
            return by_load, None
        rest = [r for r in by_load if r is not target]
        return [target] + rest, target.rid
