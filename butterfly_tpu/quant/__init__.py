from butterfly_tpu.quant.int8 import (  # noqa: F401
    maybe_dequant, quant_specs_like, quantize_int8, shard_quantized_params,
    tree_is_quantized)
