"""Int8 weight-only quantization for the bandwidth-bound decode path.

Decode throughput on TPU is HBM-bound: every step streams the full weight
tree (SURVEY.md §6; VERDICT.md round-1 roofline ~29% of v5e bandwidth).
Symmetric per-output-channel int8 halves the streamed bytes vs bfloat16.

Scheme: for each matmul weight W with contraction axes C,
    scale = absmax(W, over C) / 127        (keepdims, float32)
    q8    = round(W / scale)               (int8)
    W ~= q8 * scale

The forward NEVER computes `q8 * s` as a matmul operand: XLA fuses a
bare int8->bf16 convert into the dot's operand read, but an operand
*multiply* does not fold — it materializes the full dequantized tree in
HBM every step (measured on v5e: the 1B bench decode step streamed
~5.3GB instead of ~1.5GB, 26% roofline). Per-output-channel scales
commute with the contraction, so `qeinsum` computes
`einsum(x, q8.astype(bf16)) * s_out` — scale applied to the (tiny)
matmul OUTPUT — and only the int8 bytes ever cross HBM.

Quantized leaves are `{"q8": int8, "s": float32}` sub-dicts replacing the
original array; everything numerically delicate (embeddings, norms,
biases, MoE router) stays in the master dtype.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


def is_quantized_leaf(x: Any) -> bool:
    return isinstance(x, dict) and "q8" in x and "s" in x


def tree_is_quantized(params: Params) -> bool:
    """True if any leaf of the pytree is a `{"q8","s"}` quantized dict."""
    found = []
    jax.tree.map(lambda x: found.append(True) if is_quantized_leaf(x)
                 else None, params, is_leaf=is_quantized_leaf)
    return bool(found)


def maybe_dequant(w: Any, dtype) -> jax.Array:
    """Dequantize a `{"q8","s"}` leaf to `dtype`; pass arrays through.

    NB: using this as a matmul operand materializes the dequantized
    array (the scale multiply doesn't fold into the dot) — matmul call
    sites must use `qeinsum` instead; this exists for non-matmul uses
    and debugging.
    """
    if is_quantized_leaf(w):
        return w["q8"].astype(dtype) * w["s"].astype(dtype)
    return w


def qeinsum(spec: str, x: jax.Array, w: Any,
            dtype: Optional[Any] = None) -> jax.Array:
    """einsum(spec, x, W) for a possibly-quantized right operand W.

    Quantized: contracts x against the raw int8 codes (the int8->dtype
    convert fuses into the dot's operand read — only int8 bytes stream
    from HBM) and applies the per-output-channel scale to the OUTPUT.
    Valid because the scale has size-1 contraction dims (keepdims), so
    it commutes with the contraction: x @ (q8*s) == (x @ q8) * s. The
    output-shaped scale is derived by running the same einsum spec over
    an all-ones x surrogate (every dim 1) and the scale — shape algebra
    only; it broadcasts over the batch dims of the real output.
    """
    dtype = dtype or x.dtype
    if not is_quantized_leaf(w):
        if jnp.issubdtype(w.dtype, jnp.floating) and w.dtype != dtype:
            w = w.astype(dtype)  # master-dtype leaves compute in `dtype`
        return jnp.einsum(spec, x, w)
    y = jnp.einsum(spec, x, w["q8"].astype(dtype))
    ones = jnp.ones((1,) * x.ndim, dtype)
    s_out = jnp.einsum(spec, ones, w["s"].astype(dtype))
    return y * s_out


def _quant(w: jax.Array, axes: Tuple[int, ...], dtype) -> Dict[str, jax.Array]:
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q8 = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    # Scale lives in the compute dtype so engine cast_params is a no-op
    # on a quantized tree (no donating cast; the tree stays reusable).
    return {"q8": q8, "s": scale.astype(dtype)}


def quantize_int8(params: Params, cfg) -> Params:
    """Quantize every matmul weight of an init_params-shaped tree.

    Contraction axes per leaf (leading L = stacked layers):
      wq/wk/wv [L,D,N,H] -> D;  wo [L,N,H,D] -> (N,H)
      mlp w_gate/w_up [L,D,F] -> D;  w_down [L,F,D] -> F
      moe w_* [L,E,D,F] / [L,E,F,D] -> the D/F contraction axis
      lm_head [D,V] -> D
    Runs as one jit so a large tree quantizes device-side in one program.
    """

    dt = jnp.dtype(cfg.dtype)

    @jax.jit
    def go(params):
        layers = dict(params["layers"])
        attn = dict(layers["attn"])
        for k in ("wq", "wk", "wv"):
            attn[k] = _quant(attn[k], (1,), dt)
        attn["wo"] = _quant(attn["wo"], (1, 2), dt)
        layers["attn"] = attn
        if "mlp" in layers:
            mlp = dict(layers["mlp"])
            for k in ("w_gate", "w_up"):
                if k in mlp:
                    mlp[k] = _quant(mlp[k], (1,), dt)
            mlp["w_down"] = _quant(mlp["w_down"], (1,), dt)
            layers["mlp"] = mlp
        if "moe" in layers:
            moe = dict(layers["moe"])
            for k in ("w_gate", "w_up", "w_down"):
                moe[k] = _quant(moe[k], (2,), dt)
            layers["moe"] = moe
        out = dict(params)
        out["layers"] = layers
        if "lm_head" in params:
            out["lm_head"] = _quant(params["lm_head"], (0,), dt)
        return out

    return go(params)


from functools import partial as _partial


@_partial(jax.jit, static_argnames=("shape", "axes", "dt"))
def _init_quant_leaf(k, shape, axes, dt):
    w = jax.random.normal(k, shape, jnp.float32) * 0.02
    return _quant(w, axes, dt)


@_partial(jax.jit, static_argnames=("shape", "pdt", "kind"))
def _init_plain_leaf(k, shape, pdt, kind):
    if kind == "ones":
        return jnp.ones(shape, pdt)
    if kind == "zeros":
        return jnp.zeros(shape, pdt)
    return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(pdt)


def init_params_quantized(cfg, key: jax.Array) -> Params:
    """Random-init an already-int8-quantized tree without ever holding
    the float tree in HBM.

    `init_params` + `quantize_int8` as two device programs peaks at the
    full master-dtype tree (8B f32 = 32 GB — double a v5e chip's HBM);
    fusing them into one jit does NOT help — XLA schedules the cheap
    RNG ops ahead of the quantizations and materializes the float tree
    anyway (measured: the fused program ResourceExhausted a v5e).
    So each leaf is its own tiny program: init one float leaf,
    quantize, free — peak = int8 tree + one float leaf. Leaf roles
    (matmul -> quantize with quantize_int8's contraction axes;
    norm-scales -> ones; biases -> zeros; everything else -> N(0, .02))
    are resolved by path over init_params' eval_shape tree, so the
    structure can't drift from the real initializer. Benchmark/smoke
    use (real deployments load checkpoints via ckpt/)."""
    from butterfly_tpu.models.common import init_params

    dt = jnp.dtype(cfg.dtype)
    shapes = jax.eval_shape(_partial(init_params, cfg),
                            jax.ShapeDtypeStruct(key.shape, key.dtype))
    leaves, treedef = jax.tree_util.tree_flatten_with_path(shapes)
    keys = jax.random.split(key, len(leaves))
    out = []
    for (path, sd), k in zip(leaves, keys):
        names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        name, parent = names[-1], names[-2] if len(names) > 1 else ""
        if name in ("wq", "wk", "wv"):
            axes = (1,)
        elif name == "wo":
            axes = (1, 2)
        elif parent == "moe" and name in ("w_gate", "w_up", "w_down"):
            axes = (2,)
        elif parent == "mlp" and name in ("w_gate", "w_up", "w_down"):
            axes = (1,)
        elif name == "lm_head":
            axes = (0,)
        else:
            axes = None
        if axes is not None:
            out.append(_init_quant_chunked(k, sd.shape, axes, dt))
        else:
            kind = "ones" if name == "scale" else \
                "zeros" if name.startswith("b") else "normal"
            out.append(_init_plain_chunked(k, sd.shape, sd.dtype, kind))
    return jax.tree_util.tree_unflatten(treedef, out)


#: Per-program element budget for random init: the RNG's bit buffers and
#: the f32 intermediate are ~3x the leaf, so one 525M-element vocab leaf
#: (8B lm_head/embed) spikes ~6 GB — chunking bounds the transient.
_INIT_CHUNK_ELEMS = 128 * 2**20


def _chunks(k, shape, ax):
    n = shape[ax]
    size = 1
    for s in shape:
        size *= s
    nchunks = min(n, -(-size // _INIT_CHUNK_ELEMS))
    if nchunks <= 1:
        return None
    csize = -(-n // nchunks)
    keys = jax.random.split(k, nchunks)
    spans = []
    lo = 0
    while lo < n:
        spans.append((keys[len(spans)], min(csize, n - lo)))
        lo += csize
    return spans

def _init_quant_chunked(k, shape, axes, dt):
    # chunk along the largest non-contracted axis: per-output-channel
    # scales make chunks exactly independent
    ax = max((d for d in range(len(shape)) if d not in axes),
             key=lambda d: shape[d])
    spans = _chunks(k, shape, ax)
    if spans is None:
        return _init_quant_leaf(k, shape, axes, dt)
    parts = []
    for ck, clen in spans:
        cshape = tuple(clen if d == ax else s for d, s in enumerate(shape))
        parts.append(_init_quant_leaf(ck, cshape, axes, dt))
    return {"q8": jnp.concatenate([p["q8"] for p in parts], axis=ax),
            "s": jnp.concatenate([p["s"] for p in parts], axis=ax)}


def _init_plain_chunked(k, shape, pdt, kind):
    spans = _chunks(k, shape, 0) if kind == "normal" and shape else None
    if spans is None:
        return _init_plain_leaf(k, shape, pdt, kind)
    parts = [_init_plain_leaf(ck, (clen,) + tuple(shape[1:]), pdt, kind)
             for ck, clen in spans]
    return jnp.concatenate(parts, axis=0)


def quant_specs_like(qparams: Params, specs: Params) -> Params:
    """Mirror a param_specs tree onto a quantized tree.

    The weight's PartitionSpec applies to q8 unchanged; the scale keeps
    the spec only on dims that are still >1 (contraction dims collapsed
    to 1 by keepdims must not be sharded).
    """
    from jax.sharding import PartitionSpec as P

    def rec(qp, sp):
        if is_quantized_leaf(qp):
            s_spec = P(*[sp[i] if qp["s"].shape[i] > 1 else None
                         for i in range(len(qp["s"].shape))])
            return {"q8": sp, "s": s_spec}
        if isinstance(qp, dict):
            return {k: rec(qp[k], sp[k]) for k in qp}
        return sp

    return rec(qparams, specs)


def shard_quantized_params(qparams: Params, cfg, mesh) -> Params:
    """device_put a quantized tree to its partitioned layout (TP etc.)."""
    from butterfly_tpu.parallel.partition import param_specs, to_shardings
    specs = quant_specs_like(qparams, param_specs(cfg, mesh))
    return jax.device_put(qparams, to_shardings(specs, mesh))
