"""Slot-based serving engine over the paged KV cache.

The continuous-batching scheduler (sched/scheduler.py) drives two jitted
device programs, both static-shape so batch composition changes never
recompile (SURVEY.md §7 "hard parts"):

* `prefill_batch`: B requests' padded prompt chunks [B, Tbucket] against
  the shared page pool as ONE dispatch, each row targeting only that
  request's block-table row (per-row start/length masking — the same
  write/mask machinery paged_forward uses for a single slot). Chunk
  lengths bucket to the next power of two and B buckets to the next
  power of two clamped at runtime.prefill_max_batch, so at most
  (#B-buckets x #T-buckets) prefill programs ever compile per
  fresh/warm flavor; the single-request path is simply B=1 (same jit
  cache, same [1, Tbucket] programs as before).
* `decode_active`: one token for ALL slots [S,1]; inactive slots are
  masked via `active` (their lengths don't advance, their writes land on
  the null page). Sampling is vectorized with per-slot temperature so
  requests with different sampling settings batch together.
* `decode_block`: k chained decode iterations inside ONE jitted
  `lax.scan` (`_decode_scan`) — one host dispatch and one stacked fetch
  per scheduler tick instead of k. Per-step RNG keys are derived on
  device (`fold_in`), and per-slot stop ids + remaining-token budgets
  ride the carry so a slot that finishes mid-block goes dead on device
  (no further writes, no length growth, frozen tokens). The returned
  final-token carry is the dispatch-ahead contract: the scheduler
  chains block t+1 on it BEFORE draining block t (up to
  RuntimeConfig.inflight_blocks undrained), so the device runs blocks
  back-to-back while the host schedules; a dead slot's carry stays
  frozen at its stop id, which starts it dead in every later block.

* `mixed_block` (ISSUE 18): the decode/spec block generalized to carry
  BOTH phases — each scan step, decode-phase slots advance one token
  (or one speculative round) while prefill-phase slots chew a C-token
  chunk of their prompt through the warm multi-token path, with the
  first token sampled on device at the step a slot's prefill completes.
  Phase is a pure function of the per-slot chunk cursor riding the
  carry (`cursor < plen`), so admission becomes a host-side cursor/
  buffer edit between dispatches instead of a drain barrier + separate
  prefill dispatch (the admission-cause barrier class this retires).

Parity contract: tests/test_sched.py and tests/test_serving_mesh.py check
token-for-token equality with InferenceEngine.generate on the contiguous
cache (single-device and meshed respectively); tests/test_mixed_dispatch.py
pins the mixed block token-for-token against the alternating path.
"""
from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from butterfly_tpu.cache.paged import (
    KVWindow, PagedKVCache, flush_paged_window, init_kv_window,
    init_paged_cache, paged_forward, paged_forward_window,
    permute_paged_tail, permute_window_tail)
from butterfly_tpu.core.config import ModelConfig, RuntimeConfig
from butterfly_tpu.engine.sampling import (
    _filter_logits, speculative_accept, speculative_tree_accept,
    tree_ancestor_matrix, tree_depth, tree_node_index)
from butterfly_tpu.models.common import Model


def bucket_len(n: int, lo: int = 16, hi: Optional[int] = None) -> int:
    """Next power-of-two bucket >= n (floor lo), clamped to hi.

    The clamp keeps an over-long chunk from requesting a prefill
    program wider than the cache supports (positions past the table
    row would silently pad to the null page while the mask/gather view
    stays cache-wide); n > hi is a caller bug and raises."""
    if hi is not None and n > hi:
        raise ValueError(f"{n} tokens exceed the cache's {hi}-token "
                         f"capacity")
    b = lo
    while b < n:
        b *= 2
    if hi is not None and b > hi:
        b = hi
    return b


def bucket_batch(n: int, hi: int) -> int:
    """Next power-of-two batch bucket >= n, clamped to hi.

    n > hi returns n exactly (still a static shape — the caller asked
    for a wider gang than the configured cap, so pay one extra program
    rather than refuse)."""
    if n >= hi:
        return n
    b = 1
    while b < n:
        b *= 2
    return min(b, hi)


def sample_batched(logits: jax.Array, key: jax.Array, temps: jax.Array,
                   top_k: int, top_p: float) -> jax.Array:
    """Per-slot-temperature sampling: temp 0 rows are greedy. [S,V]->[S]."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    safe_t = jnp.where(temps > 0, temps, 1.0)[:, None]
    scaled = _filter_logits(logits / safe_t, top_k, top_p)
    drawn = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temps > 0, drawn, greedy)


def _ngram_drafts(hist, hist_len, gamma: int, ngram: int) -> jax.Array:
    """Prompt-lookup drafts for every slot, ON DEVICE — the batched twin
    of engine._ngram_draft (their match rules must not drift): find the
    most recent STRICTLY-EARLIER occurrence of each slot's trailing
    `ngram` tokens in its history and propose the `gamma` tokens that
    followed it, zero-padded where the continuation runs out or no
    match exists (padding just gets rejected by the verify — no special
    casing). hist [S, H] is the per-slot token history (prompt +
    generated so far), hist_len [S] its live length. O(H * ngram)
    compares per slot — noise next to the verify forward it feeds."""
    S, H = hist.shape
    pos = jnp.arange(H)
    tail_idx = jnp.clip(hist_len[:, None] - ngram + jnp.arange(ngram)[None, :],
                        0, H - 1)
    tail = jnp.take_along_axis(hist, tail_idx, axis=1)          # [S, n]
    win_idx = jnp.clip(pos[:, None] + jnp.arange(ngram)[None, :], 0, H - 1)
    wins = hist[:, win_idx]                                     # [S, H, n]
    ok = (wins == tail[:, None, :]).all(-1)                     # [S, H]
    # window must END before the tail itself starts repeating it
    # (host rule: i ranges over len-ngram-1 .. 0), and a history no
    # longer than the ngram has nothing to look up
    ok &= (pos[None, :] + ngram) <= (hist_len[:, None] - 1)
    ok &= (hist_len > ngram)[:, None]
    i_star = jnp.max(jnp.where(ok, pos[None, :], -1), axis=1)   # [S]
    src = i_star[:, None] + ngram + jnp.arange(gamma)[None, :]  # [S, gamma]
    valid = (i_star >= 0)[:, None] & (src < hist_len[:, None])
    cont = jnp.take_along_axis(hist, jnp.clip(src, 0, H - 1), axis=1)
    return jnp.where(valid, cont, 0).astype(jnp.int32)


class _FnDraftSource:
    """Adapter giving a plain draft FUNCTION (the PR 9 contract:
    (hist [S, H], hist_len [S], gamma, ngram) -> drafts [S, gamma]
    int32, pure jax) the full draft-source interface. Stateless: no KV,
    no proposal distribution (q one-hot at the draft — the accept test
    reduces to u < p(d))."""

    stateful = False

    def __init__(self, fn):
        self.fn = fn

    def init_state(self):
        return None

    def draft(self, hist, hlen, gamma, ngram, live, state, key, temps,
              top_k, top_p):
        return self.fn(hist, hlen, gamma, ngram), None, None


def _build_model_draft_source(engine: "ServingEngine"):
    """DRAFT_SOURCES["model"] factory: a real on-device draft model
    (models/draft.py) — an independent narrow checkpoint when
    RuntimeConfig.draft_ckpt is set, else the truncated-layer
    derivation of the engine's own params (first draft_layers layers,
    shared embed/unembed — already cast/quantized/sharded exactly like
    the target, since the leaves ARE the target's)."""
    from butterfly_tpu.models.draft import (
        ModelDraftSource, derive_draft_params)
    rt = engine.runtime
    if rt.draft_ckpt:
        from butterfly_tpu.ckpt.load import load_draft_checkpoint
        from butterfly_tpu.engine.engine import cast_params
        dcfg, dparams = load_draft_checkpoint(rt.draft_ckpt, engine.cfg)
        dparams = cast_params(dparams, dcfg)
    else:
        dcfg, dparams = derive_draft_params(engine.params, engine.cfg,
                                            rt.draft_layers)
    # width = serving max_seq + γ+1 slack: micro-step writes at the
    # sequence cap must clamp into slack, never onto a live entry
    return ModelDraftSource(
        dcfg, dparams, num_slots=engine.num_slots,
        width=engine.cache.max_seq + rt.speculative_gamma + 1,
        kv_quant=rt.kv_quant)


_build_model_draft_source.draft_source_factory = True


#: Draft-source registry for the serving spec block
#: (RuntimeConfig.draft_model selects by name). An entry is either
#: * a pure jax callable (hist [S, H], hist_len [S], gamma, ngram) ->
#:   drafts [S, gamma] int32, traced INSIDE the jitted spec scan (the
#:   PR 9 contract — "ngram" is the model-free prompt-lookup default);
#: * or a FACTORY (attribute draft_source_factory=True) called with
#:   the engine at build time, returning a source object with
#:   `.stateful`, `.init_state()`, `.draft(hist, hlen, gamma, ngram,
#:   live, state, key, temps, top_k, top_p) -> (drafts, q_logits,
#:   state)` (pure jax, traced in-scan) and — when stateful —
#:   `.prefill(state, slots, rows, lens)` (the host-side admission
#:   reseed hook). "model" is the on-device draft model
#:   (models/draft.py): its per-round γ-step forward fuses into the
#:   verify program, its KV cache rides the block carry with exact
#:   rollback, and its real proposal distribution q(x) feeds the full
#:   Leviathan accept rule.
DRAFT_SOURCES: Dict[str, object] = {
    "ngram": _ngram_drafts,
    "model": _build_model_draft_source,
}


def register_draft_source(name: str, fn) -> None:
    """Register a custom draft source (see DRAFT_SOURCES contract:
    plain draft fn, or factory marked draft_source_factory=True)."""
    DRAFT_SOURCES[name] = fn


def _draft_rollback(dstate, dlen0, live, m):
    """Roll the draft-model KV length back to the ACCEPTED count: the
    γ+1 micro-steps advanced a live slot's draft cache to dlen0 + γ+1;
    only the m accepted emissions stay live — rejected drafts' K/V sit
    past the rolled-back length, unattendable (the draft attends
    strictly below its length), and the next round's micro-steps
    overwrite them in place starting exactly at dlen0 + m. This is the
    draft-side twin of _spec_scan's cache-length rollback and the
    windowed path's win_len advance — exact by construction, no stale
    draft state ever influences a later proposal. No-op (None) for
    stateless sources."""
    if dstate is None:
        return None
    return dstate._replace(length=jnp.where(live, dlen0 + m, dlen0))


class ServingEngine:
    """Device-side half of the serving stack (host half: sched/)."""

    def __init__(self, model: Model, params,
                 runtime: Optional[RuntimeConfig] = None, mesh=None,
                 use_kernels: Optional[bool] = None):
        from butterfly_tpu.engine.engine import cast_params
        self.model = model
        self.cfg = model.cfg
        self.runtime = runtime or RuntimeConfig()
        # Optional obs.trace.Tracer (the scheduler shares its own when
        # tracing is on): emits engine-level dispatch events — prefill
        # bucket shapes and block-table syncs — into the global ring.
        # None (the default) keeps every dispatch a single None check.
        self.tracer = None
        self.params = cast_params(params, self.cfg)
        self.mesh = mesh
        stage = mesh.shape.get("stage", 1) if mesh is not None else 1
        if stage > 1 and self.cfg.num_layers % stage != 0:
            raise ValueError(
                f"{self.cfg.num_layers} layers not divisible by "
                f"{stage} pipeline stages")
        if use_kernels is None:
            # Pallas kernels are TPU-only; under a mesh the call sites go
            # through ops/*_sharded (shard_map over data/tensor), so a
            # mesh no longer disables them.
            use_kernels = jax.default_backend() == "tpu"
        self.cache = init_paged_cache(self.cfg, self.runtime)
        if mesh is not None:
            # Megatron param layout + paged pool sharded to match (kv
            # heads over `tensor`, slots over `data`): prefill/decode
            # below then compile to one SPMD program over the mesh.
            # Quantized trees route through the quant-aware specs (the
            # float specs would shard a scale's size-1 contraction dim).
            from butterfly_tpu.parallel.partition import (
                shard_paged_cache, shard_params)
            from butterfly_tpu.quant.int8 import (
                shard_quantized_params, tree_is_quantized)
            if tree_is_quantized(self.params):
                self.params = shard_quantized_params(self.params, self.cfg,
                                                     mesh)
            else:
                self.params = shard_params(self.params, self.cfg, mesh)
            self.cache = shard_paged_cache(self.cache, self.cfg, mesh)
        # Host-side block-table mirror (see set_table_row). Built from
        # the known init value (all rows -> null page) rather than
        # fetching the device array: a multi-process data-sharded table
        # is not addressable from one controller, and doesn't need to be
        # — the host is the only writer.
        self._host_table = np.full(self.cache.page_table.shape,
                                   self.cache.null_page, np.int32)
        self._table_sharding = self.cache.page_table.sharding
        self._table_dirty = False
        # stage>1 routes every paged program through the GPipe schedule
        # (microbatches of slots; pool L dim stage-sharded to match).
        if stage > 1:
            from butterfly_tpu.parallel.pipeline import paged_pipeline_forward
            fwd = partial(paged_pipeline_forward, mesh=mesh)
        else:
            fwd = paged_forward
        prefill_cfg = self.cfg.replace(attn_impl="flash") \
            if use_kernels else self.cfg
        # Two prefill programs: fresh (start==0, flash over the chunk
        # alone) and warm (chunk continuation / prefix-hit resume).
        # With runtime.prefill_flash_warm (default) the warm program
        # compiles with the flash cfg too — the kernel attends cached
        # prefix + fresh chunk (ISSUE 13) — else it keeps the dense
        # gather fallback (the parity reference).
        warm_cfg = prefill_cfg if self.runtime.prefill_flash_warm \
            else self.cfg
        self._prefill = jax.jit(
            partial(_prefill_slot, prefill_cfg, True, fwd),
            donate_argnums=(2,))
        self._prefill_warm = jax.jit(
            partial(_prefill_slot, warm_cfg, False, fwd),
            donate_argnums=(2,))
        self._decode = jax.jit(
            partial(_decode_all, self.cfg, fwd, use_kernel=use_kernels),
            static_argnums=(5, 6), donate_argnums=(2,))
        # Fused decode blocks: one jitted program per block width k
        # (_decode_scan — k is a static scan length). Built lazily; a
        # deployment runs ONE decode_steps_per_tick, so this compiles
        # once in practice.
        self._fwd = fwd
        self._use_kernels = use_kernels
        self._decode_blocks: Dict[int, object] = {}
        # Write-combined KV decode window (RuntimeConfig.kv_write_combine,
        # default on): fused decode/spec blocks stage fresh K/V into an
        # engine-held KVWindow riding the scan carry — the page pool is
        # READ-ONLY inside the block — and the pool takes ONE scatter
        # per flush (scheduler drain) instead of one per token per
        # layer. The window buffer + its per-slot staged count are
        # DONATED to every windowed dispatch and rebound from its
        # results, exactly like the cache (BTF002 contract). The
        # pipeline serving path (stage > 1) threads pools through its
        # stage-local scans, so it keeps per-token writes.
        self._window_mode = bool(self.runtime.kv_write_combine) \
            and stage == 1
        self._kv_window: Optional[KVWindow] = None
        self._win_len = None       # [S] staged count; None = seed zeros
        self._win_dirty = False    # staged entries not yet flushed
        self._win_hwm = 0          # host upper bound on staged entries
        self._decode_win_blocks: Dict[int, object] = {}
        self._spec_win_blocks: Dict[int, object] = {}
        # Mixed blocks (ISSUE 18): the decode scan generalized with
        # prefill lanes, keyed (k, C) — the chunk width is a static
        # shape, and the scheduler collapses C to 1 whenever no slot is
        # in prefill phase, so the steady-state program is exactly the
        # decode block's shape. Spec-mixed programs key on rounds alone
        # (their C is pinned to gamma + 1).
        self._mixed_blocks: Dict[Tuple[int, int], object] = {}
        self._mixed_win_blocks: Dict[Tuple[int, int], object] = {}
        self._mixed_spec_blocks: Dict[int, object] = {}
        self._mixed_spec_win_blocks: Dict[int, object] = {}
        # Seq-parallel chunk-prefill programs (ISSUE 20 move 3), one per
        # bucketed chunk width C — the long-prompt admission lane
        # (sched RuntimeConfig.seq_parallel_threshold) dispatches these.
        self._sp_chunk_progs: Dict[int, object] = {}
        self._flush = jax.jit(flush_paged_window, donate_argnums=(0, 2))
        # Fused speculative blocks (scheduler speculative mode): one
        # jitted program per round count, like _decode_blocks. The
        # draft source resolves from runtime.draft_model NOW so a typo
        # fails at engine build, not at the first spec dispatch; the
        # "model" source also builds its draft weights (truncation or
        # --draft-ckpt) and allocates its KV carry here.
        self._spec_blocks: Dict[int, object] = {}
        # Tree speculation (ISSUE 19): SpecInfer-style token-tree
        # programs, one per round count like the linear pair above.
        self._spec_tree_blocks: Dict[int, object] = {}
        self._spec_tree_win_blocks: Dict[int, object] = {}
        self._tree_width = 0
        self._tree_nodes = 0
        self._draft_stateful = False
        self._draft_state = None
        if self.runtime.speculative_gamma > 0:
            name = self.runtime.draft_model
            if name not in DRAFT_SOURCES:
                raise ValueError(
                    f"unknown draft source {name!r}: expected one of "
                    f"{sorted(DRAFT_SOURCES)} (register_draft_source)")
            entry = DRAFT_SOURCES[name]
            if getattr(entry, "draft_source_factory", False):
                self._draft_src = entry(self)
            elif hasattr(entry, "draft"):
                self._draft_src = entry        # pre-built source object
            else:
                self._draft_src = _FnDraftSource(entry)
            self._draft_stateful = bool(
                getattr(self._draft_src, "stateful", False))
            if self._draft_stateful:
                with self._mesh_ctx():
                    self._draft_state = self._draft_src.init_state()
            if self.runtime.spec_tree_width >= 2:
                w = self.runtime.spec_tree_width
                # default node budget γ+1: tree-vs-linear comparisons
                # at the same gamma hold verify FLOPs equal
                n = self.runtime.spec_tree_nodes \
                    or (self.runtime.speculative_gamma + 1)
                if n < w + 1 or (n - 1) % w != 0:
                    raise ValueError(
                        f"spec_tree_nodes={n} invalid for width {w}: "
                        f"need n >= width+1 and (n-1) divisible by "
                        f"width (full sibling fans only)")
                if not hasattr(self._draft_src, "tree_draft"):
                    raise ValueError(
                        f"spec_tree_width requires a draft source with "
                        f"tree_draft (the 'model' source); "
                        f"{self.runtime.draft_model!r} has none")
                if stage > 1:
                    raise ValueError(
                        "tree speculation does not compose with "
                        "pipeline (stage > 1) serving: the tree-mask "
                        "verify rides paged_forward's attn_mask, which "
                        "the stage-local pipeline scan has no slot for")
                self._tree_width, self._tree_nodes = w, n

    def _mesh_ctx(self):
        from butterfly_tpu.core import compat
        return compat.mesh_ctx(self.mesh)

    @property
    def num_slots(self) -> int:
        return self.runtime.max_batch_size

    @property
    def warm_prefill_flash(self) -> bool:
        """True when the warm prefill program attends through the flash
        kernel (cached prefix + fresh chunk) rather than the dense
        gather fallback — kernels on AND runtime.prefill_flash_warm."""
        return self._use_kernels and bool(self.runtime.prefill_flash_warm)

    @property
    def prefill_gang_split_fresh(self) -> bool:
        """Must the scheduler split prefill gangs by freshness? Only
        with prefill_flash_warm OFF — the seed behavior, where the warm
        program was dense and mixing would drag cold members off the
        flash path (or, kernels off, where splitting was merely
        harmless). With warm-prefix flash on, a mixed gang rides ONE
        dispatch and loses nothing: wherever kernels run the warm
        program is flash too (fresh members ride with prefix_len 0),
        and where they don't, both flavors compile the same dense
        attention. The all-or-nothing freshness downgrade — a warm
        member forcing the whole dispatch dense — is gone (ISSUE 13)."""
        return not bool(self.runtime.prefill_flash_warm)

    @property
    def supports_seq_parallel(self) -> bool:
        """Can long prompts route through the chunked seq-parallel
        prefill lane? Needs a live mesh with a seq axis > 1 and no
        pipeline stages (the ring body runs the WHOLE layer stack on
        every seq shard — it has no stage-local slice to ride)."""
        if self.mesh is None:
            return False
        return (self.mesh.shape.get("seq", 1) > 1
                and self.mesh.shape.get("stage", 1) == 1)

    @property
    def sp_degree(self) -> int:
        """Size of the seq mesh axis (1 when meshless)."""
        return self.mesh.shape.get("seq", 1) if self.mesh is not None else 1

    def set_table_row(self, slot: int, pages) -> None:
        """Host allocator -> block table. The device never writes the
        table, so updates accumulate in a host-side numpy mirror and the
        whole (tiny, int32) table transfers ONCE per device call
        (_sync_table) instead of one .at[].set round-trip per admission
        / page-growth (VERDICT r2 weak item 8)."""
        row = np.full((self.cache.page_table.shape[1],),
                      self.cache.null_page, np.int32)
        row[:len(pages)] = pages
        self._host_table[slot] = row
        self._table_dirty = True

    def reset_slot(self, slot: int) -> None:
        self._host_table[slot] = self.cache.null_page
        self._table_dirty = True
        with self._mesh_ctx():
            self.cache = self.cache._replace(
                lengths=self.cache.lengths.at[slot].set(0))

    def _sync_table(self) -> None:
        """Push pending host-side block-table edits to the device."""
        if not self._table_dirty:
            return
        # numpy straight to the sharded layout: one transfer, no
        # default-device staging copy
        tbl = jax.device_put(self._host_table, self._table_sharding)
        self.cache = self.cache._replace(page_table=tbl)
        self._table_dirty = False
        if self.tracer is not None:
            # table syncs are a measured share of the full-batch serving
            # gap (docs/decode_profile_r5.md) — count them in the trace
            self.tracer.event(None, "engine.table_sync")

    # -- write-combined KV window (kv_write_combine) ------------------------

    def _ensure_window(self, need: int) -> None:
        """Make the window able to accept `need` more staged tokens per
        slot: flush when the worst-case staged count would overflow the
        capacity, (re)allocate when the capacity itself is short. Sized
        to inflight_blocks x need so the scheduler's steady-state lazy
        drain flushes once per tick while `inflight_blocks` dispatched
        blocks keep staging."""
        width = self._kv_window.width if self._kv_window is not None else 0
        if self._win_hwm + need > width:
            if self._win_dirty:
                self.flush_kv_window()
            if width < need:
                width = max(1, self.runtime.inflight_blocks) * need
                with self._mesh_ctx():
                    win = init_kv_window(self.cache, width)
                if self.mesh is not None:
                    from butterfly_tpu.parallel.partition import \
                        shard_kv_window
                    win = shard_kv_window(win, self.cfg, self.mesh)
                self._kv_window = win
                self._win_len = None
        if self._win_len is None:
            self._win_len = jax.device_put(
                np.zeros((self.num_slots,), np.int32),
                self.cache.lengths.sharding)

    def flush_kv_window(self):
        """Flush every staged window entry into the page pool: ONE
        scatter per pool tensor (cache/paged.py flush_paged_window).
        Dispatched like any block — device order puts it after every
        staging dispatch and before anything chained later — so the
        scheduler calls it at its drain points, before page
        registration/reclaim ever reads pool state. Returns the
        device-resident flushed-token count (rides the scheduler's next
        stacked drain fetch), or None if nothing was staged."""
        if not self._win_dirty:
            return None
        with self._mesh_ctx():
            cache, wlen, flushed = self._flush(self.cache, self._kv_window,
                                               self._win_len)
        self.cache, self._win_len = cache, wlen
        self._win_dirty = False
        self._win_hwm = 0
        return flushed

    def drop_kv_window(self) -> None:
        """Discard staged-but-unflushed window state WITHOUT touching
        the device (scheduler.abort_all's wedge path: the device may be
        the thing that is broken). The staged tokens are simply lost —
        their requests are being cancelled host-side anyway — and the
        next windowed dispatch reseeds the staged count from zeros, so
        a later flush can never scatter stale entries into pages that
        have been reclaimed and re-admitted."""
        self._win_dirty = False
        self._win_hwm = 0
        self._win_len = None

    def prefill_slot(self, slot: int, prompt: list[int]) -> jax.Array:
        """Run one request's whole prompt; returns last-token logits [V]."""
        return self.prefill_chunk(slot, prompt, 0)

    def prefill_chunk(self, slot: int, tokens: list[int],
                      start: int) -> jax.Array:
        """Run one chunk of one request's prompt; returns the chunk's
        last-token logits [V]. The B=1 case of prefill_batch — same jit
        cache, same [1, Tbucket] programs."""
        return self.prefill_batch([slot], [tokens], [start])[0]

    def prefill_batch(self, slots: list[int], chunks: list[list[int]],
                      starts: list[int]) -> jax.Array:
        """Run one prompt chunk for EACH of B requests as ONE jitted
        [B, Tbucket] dispatch; returns last-position logits [B, V]
        (device-resident — row i is member i's next-token distribution,
        so every gang member's first token can sample from the same
        dispatch).

        Member i's chunk occupies absolute positions
        starts[i]..starts[i]+len(chunks[i])-1 of its slot's pages; rows
        are individually length-masked (paged_forward's per-slot
        start/length machinery), so members with different chunk lengths
        share a dispatch. B pads to the next power-of-two bucket
        (clamped at runtime.prefill_max_batch); padding rows carry a
        null-page table row, so their writes land on the null page and
        their logits are discarded. An all-fresh gang (every start==0)
        dispatches the fresh program (flash over the chunks alone); any
        warm member routes the gang through the warm program — with
        prefill_flash_warm that program is flash too (cached prefix +
        fresh chunk, per-row start masking, so fresh members simply ride
        with prefix_len 0) and gangs may mix freely; only when the warm
        program is dense while kernels are on does the scheduler still
        split gangs by freshness (prefill_gang_split_fresh).
        """
        B = len(slots)
        T = bucket_len(max(len(c) for c in chunks), hi=self.cache.max_seq)
        Bb = bucket_batch(B, max(1, min(self.runtime.prefill_max_batch,
                                        self.num_slots)))
        buf = np.zeros((Bb, T), np.int32)
        # padding rows: 1 token (a real last_index), null table row
        lens = np.ones((Bb,), np.int32)
        sts = np.zeros((Bb,), np.int32)
        rows = np.full((Bb, self.cache.page_table.shape[1]),
                       self.cache.null_page, np.int32)
        for i, (slot, toks, start) in enumerate(zip(slots, chunks, starts)):
            buf[i, :len(toks)] = toks
            lens[i] = len(toks)
            sts[i] = start
            # host mirror is authoritative (host is the only writer):
            # no device gather of the slot's table row needed
            rows[i] = self._host_table[slot]
        # a prefill writes the pool at each slot's FLUSHED length, so
        # staged window entries must land first (the scheduler barriers
        # before admission anyway — this is the engine-level backstop)
        if self._win_dirty:
            self.flush_kv_window()
        fresh = all(s == 0 for s in starts)
        prog = self._prefill if fresh else self._prefill_warm
        if self.tracer is not None:
            self.tracer.event(None, "engine.prefill_dispatch",
                              slots=list(slots), batch=B, batch_bucket=Bb,
                              tokens=int(sum(len(c) for c in chunks)),
                              bucket=T, fresh=fresh)
        self._sync_table()
        with self._mesh_ctx():
            # pools are donated (scatters land in place); the table rows
            # ride separately so the donation set has no unaliasable
            # leaves (the rows have no matching output)
            pools = (self.cache.k_pages, self.cache.v_pages,
                     self.cache.k_scale_pages, self.cache.v_scale_pages)
            logits, pools = prog(
                self.params, jnp.asarray(buf), pools, jnp.asarray(rows),
                jnp.asarray(lens), jnp.asarray(sts))
            new_lens = jnp.asarray(sts[:B] + lens[:B])
            self.cache = self.cache._replace(
                k_pages=pools[0], v_pages=pools[1],
                k_scale_pages=pools[2], v_scale_pages=pools[3],
                lengths=self.cache.lengths.at[
                    np.asarray(slots, np.int32)].set(new_lens))
        return logits[:B]

    # -- seq-parallel long-prompt prefill (ISSUE 20 move 3) -----------------

    def _sp_chunk_prog(self, C: int):
        """Jitted seq-parallel chunk-prefill program for bucket width C.

        One program per chunk bucket (like _decode_blocks per k): gather
        the slot's flushed pool prefix for ALL layers, run the chunk
        seq-sharded through sp_chunk_body (ring over the fresh chunk,
        flash-stats merge with the replicated prefix), then scatter the
        chunk's K/V into the page pool with ONE all-layer scatter per
        pool tensor (flush_paged_window's idiom) — so the prompt lands
        paged, prefix-registry-visible and evictable, and decode
        proceeds as an ordinary paged slot.
        """
        prog = self._sp_chunk_progs.get(C)
        if prog is not None:
            return prog
        from jax.sharding import PartitionSpec as P

        from butterfly_tpu.core import compat
        from butterfly_tpu.core.mesh import replicated
        from butterfly_tpu.parallel.sequence import sp_chunk_body

        cfg, mesh = self.cfg, self.mesh
        quant = self.cache.quantized
        body = partial(sp_chunk_body, cfg=cfg, quant=quant)

        def run(params, tokens, pools, row, start, clen):
            kp, vp, ksp, vsp = pools
            L, Pp, Kv, pg, H = kp.shape
            mp = row.shape[0]
            S = mp * pg
            # one gather per pool tensor covers every layer's prefix
            if quant:
                pk = kp[:, row].transpose(0, 2, 1, 3, 4) \
                    .reshape(L, 1, Kv, S, H)             # codes [L,1,Kv,S,H]
                pv = vp[:, row].transpose(0, 2, 1, 3, 4) \
                    .reshape(L, 1, Kv, S, H)
                pks = ksp[:, row].reshape(L, mp, Kv, pg) \
                    .transpose(0, 2, 1, 3).reshape(L, 1, Kv, S)
                pvs = vsp[:, row].reshape(L, mp, Kv, pg) \
                    .transpose(0, 2, 1, 3).reshape(L, 1, Kv, S)
                pre_args = (pk, pv, pks, pvs)
                kv_out = (P(None, None, None, "seq", None),
                          P(None, None, None, "seq", None),
                          P(None, None, None, "seq"),
                          P(None, None, None, "seq"))
            else:
                pk = kp[:, row].transpose(0, 1, 3, 2, 4) \
                    .reshape(L, 1, S, Kv, H)             # [L,1,S,Kv,H]
                pv = vp[:, row].transpose(0, 1, 3, 2, 4) \
                    .reshape(L, 1, S, Kv, H)
                pre_args = (pk, pv)
                kv_out = (P(None, None, "seq"), P(None, None, "seq"))
            layers = params["layers"]
            head = {k: v for k, v in params.items() if k != "layers"}
            fn = compat.shard_map(
                body, mesh,
                in_specs=(jax.tree.map(lambda _: P(), layers),
                          jax.tree.map(lambda _: P(), head),
                          P(None, "seq"), P()) + tuple(
                              P() for _ in pre_args),
                out_specs=(P(None, "seq"), kv_out),
                axis_names={"seq"})
            logits, kv = fn(layers, head, tokens, start, *pre_args)
            # flush-style all-layer scatter of the fresh chunk into the
            # pool; pad rows (>= clen) route to the null page
            pos = start + jnp.arange(C)                   # [C] absolute
            valid = jnp.arange(C) < clen
            page_idx = row[jnp.clip(pos // pg, 0, mp - 1)]
            page_idx = jnp.where(valid & (pos < S), page_idx, Pp - 1)
            off = pos % pg
            if quant:
                ck, cv, cks, cvs = kv       # [L,1,Kv,C,H] / [L,1,Kv,C]
                kp = kp.at[:, page_idx, :, off].set(
                    ck[:, 0].transpose(2, 0, 1, 3))       # [C,L,Kv,H]
                vp = vp.at[:, page_idx, :, off].set(
                    cv[:, 0].transpose(2, 0, 1, 3))
                # flat scale dim is kv-major: col = kv*page + offset
                cols = jnp.arange(Kv)[None, :] * pg + off[:, None]
                ksp = ksp.at[:, page_idx[:, None], cols].set(
                    cks[:, 0].transpose(0, 2, 1))         # [L,C,Kv]
                vsp = vsp.at[:, page_idx[:, None], cols].set(
                    cvs[:, 0].transpose(0, 2, 1))
            else:
                ck, cv = kv                 # [L,1,C,Kv,H]
                kp = kp.at[:, page_idx, :, off].set(
                    ck[:, 0].transpose(1, 0, 2, 3).astype(kp.dtype))
                vp = vp.at[:, page_idx, :, off].set(
                    cv[:, 0].transpose(1, 0, 2, 3).astype(vp.dtype))
            last = lax.dynamic_index_in_dim(logits[0], clen - 1, 0,
                                            keepdims=False)
            return last, (kp, vp, ksp, vsp)

        # pin every output fully replicated EXPLICITLY (not via
        # with_sharding_constraint inside the trace — that left the
        # shard_map-manual layout metadata on the results): a program
        # containing a full-manual shard_map otherwise hands back
        # arrays whose seq-sharded provenance poisons later stacked
        # fetches on jax 0.4.x — a drain's multi-part concatenate
        # recompiles under the mesh and sums the seq shards, so every
        # drained token comes back multiplied by the seq degree.
        rep = replicated(mesh)
        prog = jax.jit(run, donate_argnums=(2,),
                       out_shardings=(rep, (rep, rep, rep, rep)))
        self._sp_chunk_progs[C] = prog
        return prog

    def sp_prefill_chunk(self, slot: int, tokens: list[int],
                         start: int) -> jax.Array:
        """Run one seq-parallel chunk of one LONG prompt; returns the
        chunk's last-token logits [V] (device-resident).

        The scheduler's long-prompt lane (seq_parallel_threshold)
        calls this instead of prefill_chunk when the prompt outgrows
        what a single-device chunk program should chew: the chunk is
        sharded over the seq axis (each shard computes C/N tokens of
        qkv + ring attention), the already-flushed pool prefix is
        attended via the same flash-stats merge, and the chunk's K/V
        lands in the slot's pages — identical pool state to the dense
        path, so prefix registry/export/eviction all apply.
        """
        N = self.sp_degree
        C = bucket_len(len(tokens), hi=self.cache.max_seq)
        C = -(-C // N) * N                  # seq axis must divide C
        buf = np.zeros((1, C), np.int32)
        buf[0, :len(tokens)] = tokens
        if self._win_dirty:
            self.flush_kv_window()
        self._sync_table()
        if self.tracer is not None:
            self.tracer.event(None, "engine.sp_prefill_dispatch",
                              slot=slot, tokens=len(tokens), bucket=C,
                              start=start, degree=N)
        prog = self._sp_chunk_prog(C)
        with self._mesh_ctx():
            pools = (self.cache.k_pages, self.cache.v_pages,
                     self.cache.k_scale_pages, self.cache.v_scale_pages)
            logits, pools = prog(
                self.params, jnp.asarray(buf), pools,
                jnp.asarray(self._host_table[slot]),
                jnp.int32(start), jnp.int32(len(tokens)))
            self.cache = self.cache._replace(
                k_pages=pools[0], v_pages=pools[1],
                k_scale_pages=pools[2], v_scale_pages=pools[3],
                lengths=self.cache.lengths.at[slot].set(
                    start + len(tokens)))
        return logits

    def decode_active(self, tokens: np.ndarray, active: np.ndarray,
                      temps: np.ndarray, key: jax.Array
                      ) -> Tuple[np.ndarray, jax.Array]:
        """One decode step for every slot; returns (next tokens [S], logits)."""
        nxt, logits = self.decode_active_async(tokens, active, temps, key)
        return np.asarray(nxt), logits

    def decode_active_async(self, tokens, active: np.ndarray,
                            temps: np.ndarray, key: jax.Array
                            ) -> Tuple[jax.Array, jax.Array]:
        """Dispatch one decode step WITHOUT host synchronization.

        Returns the device-resident next-token vector [S]; feeding it
        back as `tokens` of the next call chains steps entirely on the
        device, so the host can dispatch step N+1 before reading step
        N's tokens (sched/scheduler.py overlap — VERDICT r4 item 5:
        the synchronous per-token readback made ITL host-bound at small
        batch). `tokens` may be a host array or a previous call's
        device vector.
        """
        # the single-step path writes the pool per token; flush any
        # staged window first so lengths/pool state line up
        if self._win_dirty:
            self.flush_kv_window()
        self._sync_table()
        with self._mesh_ctx():
            nxt, logits, cache = self._decode(
                self.params, jnp.asarray(tokens), self.cache,
                jnp.asarray(active), jnp.asarray(temps),
                self.runtime_top_k, self.runtime_top_p, key)
        self.cache = cache
        return nxt, logits

    def _decode_block_prog(self, k: int):
        prog = self._decode_blocks.get(k)
        if prog is None:
            prog = jax.jit(
                partial(_decode_scan, self.cfg, self._fwd, k,
                        use_kernel=self._use_kernels),
                static_argnums=(7, 8), donate_argnums=(2,))
            self._decode_blocks[k] = prog
        return prog

    def _decode_block_win_prog(self, k: int):
        """Windowed twin of _decode_block_prog: the cache, the window
        buffer, and the staged-count vector are all donated — the pool
        passes through unmodified (aliased), the window carries the
        staged K/V to the next dispatch or flush."""
        prog = self._decode_win_blocks.get(k)
        if prog is None:
            prog = jax.jit(
                partial(_decode_scan_win, self.cfg, k,
                        use_kernel=self._use_kernels),
                static_argnums=(9, 10), donate_argnums=(2, 3, 4))
            self._decode_win_blocks[k] = prog
        return prog

    def decode_block_async(self, tokens, active: np.ndarray,
                           temps: np.ndarray, stops: np.ndarray,
                           budgets: np.ndarray, key: jax.Array,
                           k: int) -> Tuple[jax.Array, jax.Array]:
        """Dispatch ONE fused k-step decode block, no host sync.

        k chained decode iterations run inside a single jitted lax.scan
        (_decode_scan): one dispatch, per-step keys derived on device,
        donated KV pools riding the carry. `stops` [S] holds each
        slot's EOS id (-1 = none) and `budgets` [S] its remaining-token
        allowance; a slot that emits its stop token or spends its
        budget mid-block goes dead ON DEVICE — lengths stop advancing,
        writes land on the null page — instead of generating garbage
        the host must discard. Returns (block [k, S], final [S]), both
        device-resident: the stacked per-step tokens for the
        scheduler's stacked drain, and the final token vector for
        chaining the next dispatch (the same contract
        decode_active_async's return value carries).

        kv_write_combine: the block stages its K/V into the engine-held
        window (pool read-only inside the scan) and the scheduler's
        next drain flushes it — one pool scatter per drain instead of
        k x L per block. Token outputs are byte-identical either way.
        """
        self._sync_table()
        if self._window_mode:
            self._ensure_window(k)
            with self._mesh_ctx():
                block, final, cache, window, wlen = \
                    self._decode_block_win_prog(k)(
                        self.params, jnp.asarray(tokens), self.cache,
                        self._kv_window, self._win_len,
                        jnp.asarray(active, bool), jnp.asarray(temps),
                        jnp.asarray(stops, jnp.int32),
                        jnp.asarray(budgets, jnp.int32),
                        self.runtime_top_k, self.runtime_top_p, key)
            self.cache, self._kv_window, self._win_len = cache, window, wlen
            self._win_dirty = True
            self._win_hwm += k
            return block, final
        with self._mesh_ctx():
            block, final, cache = self._decode_block_prog(k)(
                self.params, jnp.asarray(tokens), self.cache,
                jnp.asarray(active, bool), jnp.asarray(temps),
                jnp.asarray(stops, jnp.int32),
                jnp.asarray(budgets, jnp.int32),
                self.runtime_top_k, self.runtime_top_p, key)
        self.cache = cache
        return block, final

    @property
    def spec_tree_mode(self) -> bool:
        """Token-tree speculation on: spec rounds draft a width-w node
        tree and verify it in one tree-masked forward (ISSUE 19)."""
        return self._tree_nodes > 0

    @property
    def spec_tree_geometry(self) -> Tuple[int, int]:
        """(width, nodes) of the validated tree — (0, 0) off."""
        return self._tree_width, self._tree_nodes

    @property
    def spec_emit_width(self) -> int:
        """Max tokens a spec round can emit per slot — the C dimension
        of spec_block_async's (toks, valid) stack and the scheduler's
        budget/reshape unit. Linear: gamma drafts + 1 correction.
        Tree: the max-depth accepted path (D nodes) + 1 correction —
        the node budget N is a VERIFY width, not an emission width."""
        if self.spec_tree_mode:
            return tree_depth(self._tree_width, self._tree_nodes) + 1
        return self.runtime.speculative_gamma + 1

    @property
    def mixed_dispatch_ready(self) -> bool:
        """Can the scheduler route this engine through mixed blocks?
        RuntimeConfig.mixed_dispatch on AND a stateless draft source —
        a stateful ("model") source's admission reseed hook
        (draft_prefill) is a host-side call that needs the drain
        barrier mixed dispatch deletes, so it keeps the alternating
        path. Tree speculation also keeps the alternating path (no
        fused mixed tree program — and its only in-tree source today
        is the stateful "model" one anyway)."""
        return bool(self.runtime.mixed_dispatch) \
            and not self._draft_stateful and not self.spec_tree_mode

    @property
    def mixed_fallback_reason(self) -> Optional[str]:
        """Why mixed_dispatch_ready is False DESPITE the config asking
        for mixed dispatch — the scheduler surfaces this in metrics()
        and counts the silent fallback (spec_mixed_fallback_total);
        None when mixed is off by config or actually on."""
        if not self.runtime.mixed_dispatch or self.mixed_dispatch_ready:
            return None
        if self._draft_stateful:
            return ("stateful draft source "
                    f"({self.runtime.draft_model!r}) needs the "
                    "admission drain barrier for draft_prefill")
        return "tree speculation has no fused mixed program"

    def _mixed_block_prog(self, k: int, C: int):
        prog = self._mixed_blocks.get((k, C))
        if prog is None:
            prog = jax.jit(
                partial(_mixed_scan, self.cfg, self._fwd, k, C,
                        use_kernel=self._use_kernels),
                static_argnums=(10, 11), donate_argnums=(2, 3))
            self._mixed_blocks[(k, C)] = prog
        return prog

    def _mixed_block_win_prog(self, k: int, C: int):
        """Windowed twin of _mixed_block_prog: cursor, cache, window
        buffer, and staged count are all donated — the pool passes
        through unmodified (aliased); the cursor is the NEW carry the
        scheduler must rebind every dispatch (BTF002 contract)."""
        prog = self._mixed_win_blocks.get((k, C))
        if prog is None:
            prog = jax.jit(
                partial(_mixed_scan_win, self.cfg, k, C,
                        use_kernel=self._use_kernels),
                static_argnums=(12, 13), donate_argnums=(2, 3, 4, 5))
            self._mixed_win_blocks[(k, C)] = prog
        return prog

    def mixed_block_async(self, tokens, cursor, pbuf, plen,
                          active: np.ndarray, temps: np.ndarray,
                          stops: np.ndarray, budgets, key: jax.Array,
                          k: int, C: int):
        """Dispatch ONE fused k-step MIXED block, no host sync: decode
        slots advance a token per step while prefill-phase slots chew a
        C-token chunk of their `pbuf` row per step (_mixed_scan), in a
        single jitted scan covering both phases — admission no longer
        costs a drain barrier, just the host-side cursor/pbuf/table
        edits the scheduler does between dispatches.

        `cursor` [S] is the device-resident chunk-cursor carry
        (DONATED — rebind from the result, exactly like the cache);
        `pbuf` [S, H] the prompt rows (read-only, host-rebound on
        admission); `plen` [S] each slot's prompt length (a slot is in
        prefill phase while cursor < plen). Returns (block [k, S],
        valid [k, S], final [S], cursor): stacked step tokens plus the
        validity mask the drain walks (a prefill step emits only at
        completion), and the chain/cursor carries for the next
        dispatch.

        kv_write_combine: stages through the engine window like
        decode_block_async — worst case k * C staged entries (prefill
        lanes advance win_len by their real chunk length; filler past
        it is never flushed)."""
        self._sync_table()
        if self._window_mode:
            self._ensure_window(k * C)
            with self._mesh_ctx():
                block, valid, final, cursor, cache, window, wlen = \
                    self._mixed_block_win_prog(k, C)(
                        self.params, jnp.asarray(tokens), cursor,
                        self.cache, self._kv_window, self._win_len,
                        pbuf, jnp.asarray(plen, jnp.int32),
                        jnp.asarray(active, bool), jnp.asarray(temps),
                        jnp.asarray(stops, jnp.int32),
                        jnp.asarray(budgets, jnp.int32),
                        self.runtime_top_k, self.runtime_top_p, key)
            self.cache, self._kv_window, self._win_len = cache, window, wlen
            self._win_dirty = True
            self._win_hwm += k * C
            return block, valid, final, cursor
        with self._mesh_ctx():
            block, valid, final, cursor, cache = \
                self._mixed_block_prog(k, C)(
                    self.params, jnp.asarray(tokens), cursor, self.cache,
                    pbuf, jnp.asarray(plen, jnp.int32),
                    jnp.asarray(active, bool), jnp.asarray(temps),
                    jnp.asarray(stops, jnp.int32),
                    jnp.asarray(budgets, jnp.int32),
                    self.runtime_top_k, self.runtime_top_p, key)
        self.cache = cache
        return block, valid, final, cursor

    def read_pages(self, pids: list[int]) -> Tuple[np.ndarray, np.ndarray,
                                                   Optional[np.ndarray],
                                                   Optional[np.ndarray]]:
        """Fetch page contents to the host for cross-replica KV export
        (fleet/kvtransfer.py): returns (k [L, n, Kv, page, H],
        v [L, n, Kv, page, H], k_scales, v_scales) — scales [L, n,
        Kv*page] iff the pool is int8, else None. Synchronous device
        read; callers hold the serving lock so the scheduler thread
        cannot donate the pools out from under the gather, and only
        REGISTERED pages (content-immutable — a shared full page is
        never rewritten) may be exported, so in-flight decode blocks
        writing other pages cannot race the bytes."""
        if self._win_dirty:
            self.flush_kv_window()
        idx = jnp.asarray(pids, jnp.int32)
        with self._mesh_ctx():
            k = np.asarray(self.cache.k_pages[:, idx])
            v = np.asarray(self.cache.v_pages[:, idx])
            ks = vs = None
            if self.cache.quantized:
                ks = np.asarray(self.cache.k_scale_pages[:, idx])
                vs = np.asarray(self.cache.v_scale_pages[:, idx])
        return k, v, ks, vs

    def write_pages(self, pids: list[int], k: np.ndarray, v: np.ndarray,
                    k_scales: Optional[np.ndarray] = None,
                    v_scales: Optional[np.ndarray] = None) -> None:
        """Land imported page contents (the read_pages layout) into the
        local pool at freshly claimed page ids (allocator.import_page).
        The pages are not in any slot's table row yet — a later
        admission attaches them read-only via the prefix registry — so
        no in-flight dispatch can be reading them while this scatter
        runs."""
        idx = jnp.asarray(pids, jnp.int32)
        with self._mesh_ctx():
            kp = self.cache.k_pages.at[:, idx].set(
                jnp.asarray(k, self.cache.k_pages.dtype))
            vp = self.cache.v_pages.at[:, idx].set(
                jnp.asarray(v, self.cache.v_pages.dtype))
            ksp, vsp = self.cache.k_scale_pages, self.cache.v_scale_pages
            if self.cache.quantized:
                ksp = ksp.at[:, idx].set(jnp.asarray(k_scales, jnp.float32))
                vsp = vsp.at[:, idx].set(jnp.asarray(v_scales, jnp.float32))
            self.cache = self.cache._replace(
                k_pages=kp, v_pages=vp,
                k_scale_pages=ksp, v_scale_pages=vsp)

    def draft_prefill(self, slots, rows, lens) -> None:
        """Reseed newly admitted slots' draft-model KV cache from host
        truth (the scheduler calls this from _finish_prefill with the
        same prompt rows it seeds the token-history carry with — the
        first sampled token excluded, which is exactly the
        draft_len == hist_len - 1 invariant the in-scan micro-steps
        maintain). Runs only under a stateful ("model") draft source;
        admission happens behind a full drain barrier, so no spec
        block is in flight against the donated draft state."""
        if not self._draft_stateful:
            return
        with self._mesh_ctx():
            self._draft_state = self._draft_src.prefill(
                self._draft_state, slots, rows, lens)

    def _spec_block_prog(self, rounds: int):
        prog = self._spec_blocks.get(rounds)
        if prog is None:
            rt = self.runtime
            # the draft state (arg 4) joins the donation set only when
            # the source carries one (the "model" draft KV cache)
            dn = (1, 3, 4) if self._draft_stateful else (1, 3)
            prog = jax.jit(
                partial(_spec_scan, self.cfg, self._fwd, rounds,
                        rt.speculative_gamma, rt.speculative_ngram,
                        self._draft_src, use_kernel=self._use_kernels),
                static_argnums=(9, 10), donate_argnums=dn)
            self._spec_blocks[rounds] = prog
        return prog

    def _spec_block_win_prog(self, rounds: int):
        """Windowed twin of _spec_block_prog: donates the history carry
        (like the plain spec block) plus the cache / window / staged
        count triple (like the windowed decode block), plus the draft
        state under a stateful source."""
        prog = self._spec_win_blocks.get(rounds)
        if prog is None:
            rt = self.runtime
            dn = (1, 3, 4, 5, 6) if self._draft_stateful else (1, 3, 5, 6)
            prog = jax.jit(
                partial(_spec_scan_win, self.cfg, rounds,
                        rt.speculative_gamma, rt.speculative_ngram,
                        self._draft_src, use_kernel=self._use_kernels),
                static_argnums=(11, 12), donate_argnums=dn)
            self._spec_win_blocks[rounds] = prog
        return prog

    def _spec_tree_prog(self, rounds: int):
        """Tree twin of _spec_block_prog: same operand layout (tree
        geometry replaces gamma/ngram in the closure), so the donation
        set and static sampling filters line up column-for-column."""
        prog = self._spec_tree_blocks.get(rounds)
        if prog is None:
            dn = (1, 3, 4) if self._draft_stateful else (1, 3)
            prog = jax.jit(
                partial(_spec_tree_scan, self.cfg, self._fwd, rounds,
                        self._tree_width, self._tree_nodes,
                        self._draft_src, use_kernel=self._use_kernels),
                static_argnums=(9, 10), donate_argnums=dn)
            self._spec_tree_blocks[rounds] = prog
        return prog

    def _spec_tree_win_prog(self, rounds: int):
        """Tree twin of _spec_block_win_prog."""
        prog = self._spec_tree_win_blocks.get(rounds)
        if prog is None:
            dn = (1, 3, 4, 5, 6) if self._draft_stateful else (1, 3, 5, 6)
            prog = jax.jit(
                partial(_spec_tree_scan_win, self.cfg, rounds,
                        self._tree_width, self._tree_nodes,
                        self._draft_src, use_kernel=self._use_kernels),
                static_argnums=(11, 12), donate_argnums=dn)
            self._spec_tree_win_blocks[rounds] = prog
        return prog

    def spec_block_async(self, hist, hist_len, active: np.ndarray,
                         temps: np.ndarray, stops: np.ndarray,
                         budgets, spec_mask: np.ndarray, key: jax.Array,
                         rounds: int):
        """Dispatch ONE fused speculative block — `rounds` chained
        draft → batched-verify → on-device-accept rounds for every
        active slot in a single jitted lax.scan (_spec_scan) — with no
        host sync. The speculative twin of decode_block_async: drafts
        come from the device-resident token history (`hist`/`hist_len`,
        the carry the scheduler chains block t+1 on before block t is
        drained), acceptance/rollback masks are computed inside the
        scan (rejection-sampling correction at temperature > 0, the
        `_accept_drafts` greedy semantics at 0), and per-slot stop ids
        + remaining budgets kill finished slots on device exactly like
        the decode block. `budgets` may be a host array (first dispatch
        after a barrier) or the previous block's device-resident
        remainder. Returns (toks [rounds, S, C], valid [rounds, S, C],
        hist, hist_len, rem), all device-resident — the stacked
        emissions + validity masks for the scheduler's stacked drain,
        and the carry for chaining the next dispatch.

        kv_write_combine: verify writes stage into the engine-held
        window and only win_len advances by the ACCEPTED count per
        round — rejected drafts' K/V sit past win_len, unattendable,
        and are never flushed into the pool (exact rollback by
        construction).

        Under a stateful draft source ("model") the draft KV cache
        rides the same carry: donated in, advanced per round by the
        accepted count only (_draft_rollback), rebound here.

        spec_tree_mode routes the same operands through the TREE
        programs (_spec_tree_scan[_win]): each round verifies an
        N-node token tree in one tree-masked forward, the emission
        width C becomes spec_emit_width (tree max-depth + 1), and the
        window stages N entries per round of which only the accepted
        path survives the in-window compaction."""
        self._sync_table()
        tree = self.spec_tree_mode
        if self._window_mode:
            # per-round window demand is the VERIFY width: N staged
            # tree nodes (rejected branches die unflushed), or the
            # linear chunk gamma+1
            C = self._tree_nodes if tree \
                else self.runtime.speculative_gamma + 1
            self._ensure_window(rounds * C)
            prog = self._spec_tree_win_prog(rounds) if tree \
                else self._spec_block_win_prog(rounds)
            with self._mesh_ctx():
                (toks, valid, hist, hist_len, rem, cache, window, wlen,
                 dstate) = prog(
                        self.params, hist,
                        jnp.asarray(hist_len, jnp.int32), self.cache,
                        self._draft_state,
                        self._kv_window, self._win_len,
                        jnp.asarray(active, bool), jnp.asarray(temps),
                        jnp.asarray(stops, jnp.int32),
                        jnp.asarray(budgets, jnp.int32),
                        self.runtime_top_k, self.runtime_top_p, key,
                        jnp.asarray(spec_mask, bool))
            self.cache, self._kv_window, self._win_len = cache, window, wlen
            self._draft_state = dstate
            self._win_dirty = True
            self._win_hwm += rounds * C
            return toks, valid, hist, hist_len, rem
        prog = self._spec_tree_prog(rounds) if tree \
            else self._spec_block_prog(rounds)
        with self._mesh_ctx():
            toks, valid, hist, hist_len, rem, cache, dstate = prog(
                    self.params, hist, jnp.asarray(hist_len, jnp.int32),
                    self.cache, self._draft_state,
                    jnp.asarray(active, bool),
                    jnp.asarray(temps), jnp.asarray(stops, jnp.int32),
                    jnp.asarray(budgets, jnp.int32),
                    self.runtime_top_k, self.runtime_top_p, key,
                    jnp.asarray(spec_mask, bool))
        self.cache, self._draft_state = cache, dstate
        return toks, valid, hist, hist_len, rem

    def _mixed_spec_prog(self, rounds: int):
        prog = self._mixed_spec_blocks.get(rounds)
        if prog is None:
            rt = self.runtime
            prog = jax.jit(
                partial(_mixed_spec_scan, self.cfg, self._fwd, rounds,
                        rt.speculative_gamma, rt.speculative_ngram,
                        self._draft_src, use_kernel=self._use_kernels),
                static_argnums=(10, 11), donate_argnums=(1, 3, 5))
            self._mixed_spec_blocks[rounds] = prog
        return prog

    def _mixed_spec_win_prog(self, rounds: int):
        """Windowed twin of _mixed_spec_prog: donates the history and
        cursor carries plus the cache / window / staged-count triple.
        No draft-state slot — mixed dispatch is gated to stateless
        sources (mixed_dispatch_ready)."""
        prog = self._mixed_spec_win_blocks.get(rounds)
        if prog is None:
            rt = self.runtime
            prog = jax.jit(
                partial(_mixed_spec_scan_win, self.cfg, rounds,
                        rt.speculative_gamma, rt.speculative_ngram,
                        self._draft_src, use_kernel=self._use_kernels),
                static_argnums=(12, 13), donate_argnums=(1, 3, 5, 6, 7))
            self._mixed_spec_win_blocks[rounds] = prog
        return prog

    def mixed_spec_block_async(self, hist, hist_len, cursor, plen,
                               active: np.ndarray, temps: np.ndarray,
                               stops: np.ndarray, budgets,
                               spec_mask: np.ndarray, key: jax.Array,
                               rounds: int):
        """Dispatch ONE fused speculative MIXED block — spec_block_async
        with prefill lanes (_mixed_spec_scan). The history carry
        doubles as the prompt buffer (a freshly admitted slot's hist
        row holds its full prompt, hist_len == prompt length), so the
        only new operands are the donated chunk-cursor carry and the
        per-slot prompt lengths. Returns (toks [rounds, S, C], valid
        [rounds, S, C], hist, hist_len, rem, cursor) — a completing
        prefill slot's first token arrives as a single valid entry at
        column 0 of its completion round, so the drain needs no new
        unpacking. Stateless draft sources only (mixed_dispatch_ready).
        """
        self._sync_table()
        if self._window_mode:
            C = self.runtime.speculative_gamma + 1
            self._ensure_window(rounds * C)
            with self._mesh_ctx():
                (toks, valid, hist, hist_len, rem, cursor, cache,
                 window, wlen) = self._mixed_spec_win_prog(rounds)(
                        self.params, hist,
                        jnp.asarray(hist_len, jnp.int32), cursor,
                        jnp.asarray(plen, jnp.int32), self.cache,
                        self._kv_window, self._win_len,
                        jnp.asarray(active, bool), jnp.asarray(temps),
                        jnp.asarray(stops, jnp.int32),
                        jnp.asarray(budgets, jnp.int32),
                        self.runtime_top_k, self.runtime_top_p, key,
                        jnp.asarray(spec_mask, bool))
            self.cache, self._kv_window, self._win_len = cache, window, wlen
            self._win_dirty = True
            self._win_hwm += rounds * C
            return toks, valid, hist, hist_len, rem, cursor
        with self._mesh_ctx():
            toks, valid, hist, hist_len, rem, cursor, cache = \
                self._mixed_spec_prog(rounds)(
                    self.params, hist, jnp.asarray(hist_len, jnp.int32),
                    cursor, jnp.asarray(plen, jnp.int32), self.cache,
                    jnp.asarray(active, bool),
                    jnp.asarray(temps), jnp.asarray(stops, jnp.int32),
                    jnp.asarray(budgets, jnp.int32),
                    self.runtime_top_k, self.runtime_top_p, key,
                    jnp.asarray(spec_mask, bool))
        self.cache = cache
        return toks, valid, hist, hist_len, rem, cursor

    # static sampling knobs (per-slot temps are dynamic)
    @property
    def runtime_top_k(self) -> int:
        return self.runtime.top_k

    @property
    def runtime_top_p(self) -> float:
        return self.runtime.top_p


def _prefill_slot(cfg: ModelConfig, fresh: bool, fwd, params, tokens,
                  pools, table_rows, true_len, start):
    """[B,T] prompt chunks against B slots' table rows; pool-wide scatter.

    `pools` is the (k, v[, k_scale, v_scale]) pool tuple (donated —
    scatters land in place), paired with the B member slots' table rows
    [B, max_pages]; `start` [B] is each chunk's first absolute position;
    `fresh` (static) means every start==0 and the members' pages are
    empty (flash-path eligible). `fwd` is paged_forward or its
    stage-pipelined twin. B=1 is the classic single-slot prefill; the
    batched gang prefill (ServingEngine.prefill_batch) is the same
    program at B>1.
    """
    cache1 = PagedKVCache(pools[0], pools[1], table_rows,
                          jnp.zeros((tokens.shape[0],), jnp.int32),
                          pools[2], pools[3])
    B, T = tokens.shape
    positions = start[:, None] + jnp.broadcast_to(jnp.arange(T)[None, :],
                                                  (B, T))
    # last chunk token's logits only (paged_forward last_index docs);
    # the pipeline path ignores the hint — gather its full-T logits.
    logits, cache1 = fwd(params, cfg, tokens, cache1, positions, fresh=fresh,
                         last_index=true_len - 1)
    if logits.shape[1] != 1:
        logits = jnp.take_along_axis(logits, (true_len - 1)[:, None, None],
                                     axis=1)
    return logits[:, 0, :], (cache1.k_pages, cache1.v_pages,
                             cache1.k_scale_pages, cache1.v_scale_pages)


def _decode_all(cfg: ModelConfig, fwd, params, tokens, cache: PagedKVCache,
                active, temps, top_k: int, top_p: float, key,
                use_kernel: bool = False):
    logits, cache = fwd(params, cfg, tokens[:, None], cache,
                        active=active, use_kernel=use_kernel)
    last = logits[:, -1, :]
    nxt = sample_batched(last, key, temps, top_k, top_p)
    return nxt, last, cache


def _decode_scan(cfg: ModelConfig, fwd, k: int, params, tokens,
                 cache: PagedKVCache, active, temps, stops, budgets,
                 top_k: int, top_p: float, key, use_kernel: bool = False):
    """k chained decode iterations in ONE lax.scan; [S] slots each step.

    Carry: (cur tokens [S], cache, live [S] bool, remaining budgets
    [S]). Step i consumes cur — writing its K/V where live, advancing
    live lengths — and samples the next token with the device-derived
    key fold_in(key, i), so the host pays one dispatch, one operand
    conversion, and one RNG split per BLOCK instead of per token.

    Liveness is the device twin of the host's stop/max_new truncation:
    a slot starts dead if it is inactive, its budget is already spent,
    or its incoming chain token is its stop id (an undrained
    admission-time first token can be EOS); it goes dead the moment a
    sampled token hits the stop id or spends the budget. Dead steps
    freeze the slot's token (the drain discards them anyway), write to
    the null page, and leave lengths at the written-token count — so a
    mid-block finish can never grow pages or attend past the EOS.

    Returns (block [k, S] stacked step tokens, final [S] chain vector,
    cache).
    """
    has_stop = stops >= 0
    live = active & (budgets > 0) \
        & jnp.where(has_stop, tokens != stops, True)

    def body(carry, i):
        cur, cache, live, rem = carry
        logits, cache = fwd(params, cfg, cur[:, None], cache,
                            active=live, use_kernel=use_kernel)
        nxt = sample_batched(logits[:, -1, :], jax.random.fold_in(key, i),
                             temps, top_k, top_p)
        nxt = jnp.where(live, nxt, cur)
        rem = jnp.where(live, rem - 1, rem)
        live = live & (rem > 0) & jnp.where(has_stop, nxt != stops, True)
        return (nxt, cache, live, rem), nxt

    (final, cache, _, _), block = lax.scan(
        body, (tokens, cache, live, budgets),
        jnp.arange(k, dtype=jnp.int32))
    return block, final, cache


def _decode_scan_win(cfg: ModelConfig, k: int, params, tokens,
                     cache: PagedKVCache, window: KVWindow, win_len,
                     active, temps, stops, budgets, top_k: int,
                     top_p: float, key, use_kernel: bool = False):
    """Write-combined twin of _decode_scan — the liveness/budget/RNG
    semantics are IDENTICAL (the parity grid pins byte-equality); only
    the K/V write target differs. The pool is READ-ONLY (closed over by
    paged_forward_window, returned unmodified for donation aliasing):
    each step stages its fresh K/V into the window carry at per-slot
    offset win_len, which advances with the slot's liveness exactly as
    cache.lengths does window-off. The pool scatter this scan no longer
    pays per step — and the pool COPY the scatter forced, because XLA
    cannot alias a scatter into a scan carry — happens once per
    scheduler drain (engine.flush_kv_window).

    Returns (block [k, S], final [S], cache, window, win_len).
    """
    has_stop = stops >= 0
    live = active & (budgets > 0) \
        & jnp.where(has_stop, tokens != stops, True)

    def body(carry, i):
        cur, win, wlen, live, rem = carry
        logits, win = paged_forward_window(params, cfg, cur[:, None],
                                           cache, win, wlen, active=live,
                                           use_kernel=use_kernel)
        nxt = sample_batched(logits[:, -1, :], jax.random.fold_in(key, i),
                             temps, top_k, top_p)
        nxt = jnp.where(live, nxt, cur)
        wlen = jnp.where(live, wlen + 1, wlen)
        rem = jnp.where(live, rem - 1, rem)
        live = live & (rem > 0) & jnp.where(has_stop, nxt != stops, True)
        return (nxt, win, wlen, live, rem), nxt

    (final, window, win_len, _, _), block = lax.scan(
        body, (tokens, window, win_len, live, budgets),
        jnp.arange(k, dtype=jnp.int32))
    return block, final, cache, window, win_len


def _spec_scan(cfg: ModelConfig, fwd, rounds: int, gamma: int, ngram: int,
               draft_src, params, hist, hist_len, cache: PagedKVCache,
               dstate, active, temps, stops, budgets, top_k: int,
               top_p: float, key, spec_mask, use_kernel: bool = False):
    """`rounds` chained speculative rounds in ONE lax.scan — the
    speculative twin of _decode_scan, emitting 1..gamma+1 tokens per
    live slot per round instead of exactly one.

    Each round, for every live slot at once: (1) draft gamma tokens —
    prompt lookup over the device-resident history, or a real
    on-device draft model (`draft_src.draft`, models/draft.py) whose γ
    micro-steps run over its own KV carry `dstate` and return the
    proposal distribution q alongside the tokens; (2) run ONE batched
    (gamma+1)-token verify forward over [S, C] chunks (the dense warm
    multi-token path — the same program shape as a chunked warm
    prefill), writing ALL positions' K/V; (3) accept/correct ON DEVICE
    (sampling.speculative_accept: rejection-sampling correction at
    temperature > 0 — the full min(1, p/q) rule under a real q —
    `_accept_drafts` greedy semantics at 0);
    (4) truncate the emitted run at the slot's stop id / remaining
    budget, roll the slot's cache length back to its written-token
    count, roll the DRAFT cache length back to the accepted count
    (_draft_rollback — rejected drafts' K/V become unattendable and
    are overwritten in place next round), and append the survivors to
    the history carry. No host round-trip decides acceptance — the
    host drains stacked (tokens, validity) blocks after the fact,
    exactly like decode.

    KV correctness under rejection is the write-then-attend argument
    (engine.generate_speculative docs): rejected positions hold stale
    K/V past the rolled-back length, and the next round's chunk —
    which starts at that length and spans gamma+1 >= the stale run —
    rewrites them before any query can attend that far. Writes past a
    slot's allocated pages (the last verify's slack) land on the null
    page via the block-table default, same as dead-slot decode writes.

    Liveness is the decode block's contract: a slot starts dead if
    inactive, out of budget, or its last history token is its stop id;
    it goes dead the round a valid emission hits the stop id or spends
    the budget (lengths freeze, later writes null out via `active`
    masking), so a chained block dispatched before this one drains
    starts it dead too.

    Returns (toks [rounds, S, C], valid [rounds, S, C], hist,
    hist_len, rem, cache, dstate) — valid[r, s, c] marks toks[r, s, c]
    as a real emission of round r (in (round, position) order).
    """
    S, H = hist.shape
    C = gamma + 1
    has_stop = stops >= 0
    col = jnp.arange(C)[None, :]
    rows = jnp.arange(S)[:, None]
    last0 = jnp.take_along_axis(
        hist, jnp.clip(hist_len - 1, 0, H - 1)[:, None], axis=1)[:, 0]
    live0 = active & (budgets > 0) \
        & jnp.where(has_stop, last0 != stops, True)

    def body(carry, i):
        hist, hlen, cache, dst, live, rem = carry
        dlen0 = dst.length if dst is not None else None
        # per-round draft key (stochastic draft-model proposals): the
        # fold_in index offsets past the accept keys' 0..rounds-1 range
        # so the two streams never collide within a block
        drafts, qlog, dst = draft_src.draft(
            hist, hlen, gamma, ngram, live, dst,
            jax.random.fold_in(key, rounds + i), temps, top_k, top_p)
        last = jnp.take_along_axis(
            hist, jnp.clip(hlen - 1, 0, H - 1)[:, None], axis=1)[:, 0]
        toks = jnp.concatenate([last[:, None], drafts], axis=1)  # [S, C]
        W = cache.lengths
        # use_kernel rides through for the decode-kernel plumbing, but
        # a verify is a T=C>1 warm step: paged_layer_body routes it to
        # the dense gather path regardless (kernels are T==1 / fresh)
        logits, cache = fwd(params, cfg, toks, cache, active=live,
                            use_kernel=use_kernel)
        emitted, n_acc = speculative_accept(
            logits, drafts, jax.random.fold_in(key, i), temps,
            top_k, top_p, spec_mask, qlog)
        # emitted prefix n_acc+1, clipped at the remaining budget, cut
        # at the first stop id INCLUSIVE (the stop token itself emits,
        # like _emit's host truncation)
        cand = (col <= n_acc[:, None]) & (col < rem[:, None])
        stop_at = cand & has_stop[:, None] & (emitted == stops[:, None])
        prior = jnp.cumsum(stop_at.astype(jnp.int32), axis=1) \
            - stop_at.astype(jnp.int32)
        valid = cand & (prior == 0) & live[:, None]
        m = valid.sum(axis=1).astype(jnp.int32)
        # written tokens are the old chain token + the accepted drafts:
        # roll the verify's +C advance back to W + m (the last emitted
        # token — correction/bonus — is never written, decode-style);
        # the draft cache rolls back by the same rule
        cache = cache._replace(lengths=jnp.where(live, W + m, W))
        dst = _draft_rollback(dst, dlen0, live, m)
        wpos = jnp.clip(hlen[:, None] + col, 0, H - 1)
        cur = jnp.take_along_axis(hist, wpos, axis=1)
        hist = hist.at[rows, wpos].set(jnp.where(valid, emitted, cur))
        hlen = jnp.where(live, hlen + m, hlen)
        rem = jnp.where(live, rem - m, rem)
        died = (valid & has_stop[:, None]
                & (emitted == stops[:, None])).any(axis=1)
        live = live & ~died & (rem > 0)
        return (hist, hlen, cache, dst, live, rem), (emitted, valid)

    (hist, hist_len, cache, dstate, _, rem), (toks_blk, valid_blk) = \
        lax.scan(body, (hist, hist_len, cache, dstate, live0, budgets),
                 jnp.arange(rounds, dtype=jnp.int32))
    return toks_blk, valid_blk, hist, hist_len, rem, cache, dstate


def _spec_scan_win(cfg: ModelConfig, rounds: int, gamma: int, ngram: int,
                   draft_src, params, hist, hist_len, cache: PagedKVCache,
                   dstate, window: KVWindow, win_len, active, temps,
                   stops, budgets, top_k: int, top_p: float, key,
                   spec_mask, use_kernel: bool = False):
    """Write-combined twin of _spec_scan — draft/verify/accept semantics
    are IDENTICAL (the spec parity grid pins byte-equality); only the
    K/V write target differs. Each round's verify stages ALL C = gamma+1
    positions into the window at offset win_len, then win_len advances
    by only the ACCEPTED count m — the window-side analogue of
    _spec_scan's cache-length rollback, but stronger: a rejected
    draft's K/V sits past win_len, no query can ever attend it (insert
    positions start at the flushed base + win_len >= every valid
    query's horizon), and the flush never writes it, so the POOL never
    holds stale speculative state (window-off relies on the
    write-then-attend rewrite argument for those positions). The next
    round's C-wide write at the new win_len overwrites the stale run
    inside the window buffer itself. The draft-model KV carry `dstate`
    follows the exact same per-round accepted-count rollback as the
    plain scan (_draft_rollback).

    Returns (toks [rounds, S, C], valid [rounds, S, C], hist, hist_len,
    rem, cache, window, win_len, dstate).
    """
    S, H = hist.shape
    C = gamma + 1
    has_stop = stops >= 0
    col = jnp.arange(C)[None, :]
    rows = jnp.arange(S)[:, None]
    last0 = jnp.take_along_axis(
        hist, jnp.clip(hist_len - 1, 0, H - 1)[:, None], axis=1)[:, 0]
    live0 = active & (budgets > 0) \
        & jnp.where(has_stop, last0 != stops, True)

    def body(carry, i):
        hist, hlen, win, wlen, dst, live, rem = carry
        dlen0 = dst.length if dst is not None else None
        drafts, qlog, dst = draft_src.draft(
            hist, hlen, gamma, ngram, live, dst,
            jax.random.fold_in(key, rounds + i), temps, top_k, top_p)
        last = jnp.take_along_axis(
            hist, jnp.clip(hlen - 1, 0, H - 1)[:, None], axis=1)[:, 0]
        toks = jnp.concatenate([last[:, None], drafts], axis=1)  # [S, C]
        logits, win = paged_forward_window(params, cfg, toks, cache, win,
                                           wlen, active=live,
                                           use_kernel=use_kernel)
        emitted, n_acc = speculative_accept(
            logits, drafts, jax.random.fold_in(key, i), temps,
            top_k, top_p, spec_mask, qlog)
        # emitted prefix n_acc+1, clipped at the remaining budget, cut
        # at the first stop id INCLUSIVE — byte-for-byte _spec_scan's
        # truncation
        cand = (col <= n_acc[:, None]) & (col < rem[:, None])
        stop_at = cand & has_stop[:, None] & (emitted == stops[:, None])
        prior = jnp.cumsum(stop_at.astype(jnp.int32), axis=1) \
            - stop_at.astype(jnp.int32)
        valid = cand & (prior == 0) & live[:, None]
        m = valid.sum(axis=1).astype(jnp.int32)
        # keep the old chain token + the accepted drafts staged; the
        # last emitted token (correction/bonus) is never staged,
        # decode-style — win_len is the rollback; the draft cache
        # rolls back by the same accepted count
        wlen = jnp.where(live, wlen + m, wlen)
        dst = _draft_rollback(dst, dlen0, live, m)
        wpos = jnp.clip(hlen[:, None] + col, 0, H - 1)
        cur = jnp.take_along_axis(hist, wpos, axis=1)
        hist = hist.at[rows, wpos].set(jnp.where(valid, emitted, cur))
        hlen = jnp.where(live, hlen + m, hlen)
        rem = jnp.where(live, rem - m, rem)
        died = (valid & has_stop[:, None]
                & (emitted == stops[:, None])).any(axis=1)
        live = live & ~died & (rem > 0)
        return (hist, hlen, win, wlen, dst, live, rem), (emitted, valid)

    (hist, hist_len, window, win_len, dstate, _, rem), \
        (toks_blk, valid_blk) = lax.scan(
            body, (hist, hist_len, window, win_len, dstate, live0,
                   budgets),
            jnp.arange(rounds, dtype=jnp.int32))
    return (toks_blk, valid_blk, hist, hist_len, rem, cache, window,
            win_len, dstate)


def _tree_chunk_operands(width: int, nodes: int, base, s_max: int):
    """RoPE positions + tree-attention mask for one [S, N] tree-verify
    chunk whose node 0 sits at absolute position `base` [S].

    positions[s, n] = base[s] + depth(n): RoPE encodes TREE DEPTH while
    the K/V write location stays base + chunk index (write_paged_layer
    / stage_window_layer use arange(T)) — siblings share a RoPE
    position but occupy distinct storage, and after the accepted-path
    compaction the kept entries' storage positions equal their RoPE
    positions again, indistinguishable from a linear decode.

    mask[s, n, j]: node n attends absolute position j iff j is
    committed history (j < base[s] — includes previously staged window
    entries in the windowed path, whose base is flushed+staged) or j is
    a chunk position on n's own root->n ancestor path
    (tree_ancestor_matrix; self included). Everything else — sibling
    branches above all — is invisible: collapsing this to all-ones is
    the cross-branch attention leak the parity grid kills."""
    depth = np.zeros((nodes,), np.int32)
    for d in range(1, tree_depth(width, nodes) + 1):
        for j in range(width):
            depth[tree_node_index(d, j, width)] = d
    positions = base[:, None] + jnp.asarray(depth)[None, :]   # [S, N]
    anc = jnp.asarray(tree_ancestor_matrix(width, nodes))     # [N, N]
    j_abs = jnp.arange(s_max)[None, :]                        # [1, Smax]
    rel = j_abs - base[:, None]                               # [S, Smax]
    tree_bits = anc[:, jnp.clip(rel, 0, nodes - 1)]           # [N,S,Smax]
    mask = (j_abs < base[:, None])[:, None, :] \
        | (((rel >= 0) & (rel < nodes))[:, None, :]
           & jnp.transpose(tree_bits, (1, 0, 2)))
    return positions, mask


def _spec_tree_scan(cfg: ModelConfig, fwd, rounds: int, width: int,
                    nodes: int, draft_src, params, hist, hist_len,
                    cache: PagedKVCache, dstate, active, temps, stops,
                    budgets, top_k: int, top_p: float, key, spec_mask,
                    use_kernel: bool = False):
    """Token-TREE twin of _spec_scan (ISSUE 19): each round drafts a
    width-w, N-node candidate tree (draft_src.tree_draft — D = (N-1)/w
    principal micro-steps, w i.i.d. samples per fan), verifies ALL N
    nodes in ONE forward via the tree-attention mask
    (_tree_chunk_operands: each node attends committed history + its
    ancestor path only), and walks the recursive-residual accept on
    device (sampling.speculative_tree_accept — the output law stays
    exactly the target's). The per-round emission width is D+1 (the
    max-depth path + correction/bonus), narrower than the verify width
    N — that asymmetry is the whole bet: sibling branches hedge the
    draft's uncertainty at equal verify FLOPs.

    KV: the verify writes all N nodes' K/V at base + chunk index, then
    permute_paged_tail gathers the accepted path to the contiguous
    committed positions base..base+m-1 and the length rolls back to
    base + m — rejected branches sit past the length and the next
    round's N-wide chunk (N >= the stale run) rewrites them before any
    query can attend that far, the same write-then-attend argument as
    the linear scan. The draft cache rolls back to base + m too
    (_draft_rollback); when the deepest accepted node is a
    non-principal sibling its draft-KV entry holds the principal's K/V
    instead (tree_draft docs) — bounded one-token context staleness,
    never an exactness issue.

    Liveness, truncation, history append, and the return contract are
    _spec_scan's verbatim with C = D+1.
    """
    S, H = hist.shape
    D = tree_depth(width, nodes)
    C = D + 1
    has_stop = stops >= 0
    col = jnp.arange(C)[None, :]
    rows = jnp.arange(S)[:, None]
    last0 = jnp.take_along_axis(
        hist, jnp.clip(hist_len - 1, 0, H - 1)[:, None], axis=1)[:, 0]
    live0 = active & (budgets > 0) \
        & jnp.where(has_stop, last0 != stops, True)

    def body(carry, i):
        hist, hlen, cache, dst, live, rem = carry
        dlen0 = dst.length if dst is not None else None
        drafts, qlog, dst = draft_src.tree_draft(
            hist, hlen, width, D, live, dst,
            jax.random.fold_in(key, rounds + i), temps, top_k, top_p)
        last = jnp.take_along_axis(
            hist, jnp.clip(hlen - 1, 0, H - 1)[:, None], axis=1)[:, 0]
        toks = jnp.concatenate(
            [last[:, None], drafts.reshape(S, D * width)], axis=1)
        W = cache.lengths
        positions, mask = _tree_chunk_operands(width, nodes, W,
                                               cache.max_seq)
        logits, cache = fwd(params, cfg, toks, cache, active=live,
                            use_kernel=use_kernel, positions=positions,
                            attn_mask=mask)
        emitted, n_acc, perm = speculative_tree_accept(
            logits, drafts, jax.random.fold_in(key, i), temps,
            top_k, top_p, spec_mask, qlog, width=width, nodes=nodes)
        cand = (col <= n_acc[:, None]) & (col < rem[:, None])
        stop_at = cand & has_stop[:, None] & (emitted == stops[:, None])
        prior = jnp.cumsum(stop_at.astype(jnp.int32), axis=1) \
            - stop_at.astype(jnp.int32)
        valid = cand & (prior == 0) & live[:, None]
        m = valid.sum(axis=1).astype(jnp.int32)
        # compact the accepted path's K/V to base..base+m-1 (the
        # verify wrote chunk-index order), then roll the +N advance
        # back to W + m — the last emitted token is never written,
        # decode-style
        cache = cache._replace(lengths=W)
        cache = permute_paged_tail(cache, perm, active=live)
        cache = cache._replace(
            lengths=jnp.where(live, W + m, W))
        dst = _draft_rollback(dst, dlen0, live, m)
        wpos = jnp.clip(hlen[:, None] + col, 0, H - 1)
        cur = jnp.take_along_axis(hist, wpos, axis=1)
        hist = hist.at[rows, wpos].set(jnp.where(valid, emitted, cur))
        hlen = jnp.where(live, hlen + m, hlen)
        rem = jnp.where(live, rem - m, rem)
        died = (valid & has_stop[:, None]
                & (emitted == stops[:, None])).any(axis=1)
        live = live & ~died & (rem > 0)
        return (hist, hlen, cache, dst, live, rem), (emitted, valid)

    (hist, hist_len, cache, dstate, _, rem), (toks_blk, valid_blk) = \
        lax.scan(body, (hist, hist_len, cache, dstate, live0, budgets),
                 jnp.arange(rounds, dtype=jnp.int32))
    return toks_blk, valid_blk, hist, hist_len, rem, cache, dstate


def _spec_tree_scan_win(cfg: ModelConfig, rounds: int, width: int,
                        nodes: int, draft_src, params, hist, hist_len,
                        cache: PagedKVCache, dstate, window: KVWindow,
                        win_len, active, temps, stops, budgets,
                        top_k: int, top_p: float, key, spec_mask,
                        use_kernel: bool = False):
    """Write-combined twin of _spec_tree_scan — the verify stages all N
    tree nodes into the window at offset win_len (chunk-index order),
    permute_window_tail compacts the accepted path to win_len..
    win_len+m-1, and win_len advances by m only: rejected BRANCHES sit
    past win_len exactly like the linear path's rejected drafts —
    unattendable, never flushed into the pool, overwritten by the next
    round's staging. This is the stronger rollback story of the two
    (the pool never holds a rejected node), which is why tree K/V is
    staged past the committed length in the write-combined window by
    default. Absolute geometry: node 0 sits at flushed + staged length
    (cache.lengths + win_len), so `j < base` in the tree mask covers
    committed AND previously staged entries.
    """
    S, H = hist.shape
    D = tree_depth(width, nodes)
    C = D + 1
    has_stop = stops >= 0
    col = jnp.arange(C)[None, :]
    rows = jnp.arange(S)[:, None]
    last0 = jnp.take_along_axis(
        hist, jnp.clip(hist_len - 1, 0, H - 1)[:, None], axis=1)[:, 0]
    live0 = active & (budgets > 0) \
        & jnp.where(has_stop, last0 != stops, True)

    def body(carry, i):
        hist, hlen, win, wlen, dst, live, rem = carry
        dlen0 = dst.length if dst is not None else None
        drafts, qlog, dst = draft_src.tree_draft(
            hist, hlen, width, D, live, dst,
            jax.random.fold_in(key, rounds + i), temps, top_k, top_p)
        last = jnp.take_along_axis(
            hist, jnp.clip(hlen - 1, 0, H - 1)[:, None], axis=1)[:, 0]
        toks = jnp.concatenate(
            [last[:, None], drafts.reshape(S, D * width)], axis=1)
        positions, mask = _tree_chunk_operands(
            width, nodes, cache.lengths + wlen, cache.max_seq)
        logits, win = paged_forward_window(params, cfg, toks, cache, win,
                                           wlen, active=live,
                                           use_kernel=use_kernel,
                                           positions=positions,
                                           attn_mask=mask)
        emitted, n_acc, perm = speculative_tree_accept(
            logits, drafts, jax.random.fold_in(key, i), temps,
            top_k, top_p, spec_mask, qlog, width=width, nodes=nodes)
        cand = (col <= n_acc[:, None]) & (col < rem[:, None])
        stop_at = cand & has_stop[:, None] & (emitted == stops[:, None])
        prior = jnp.cumsum(stop_at.astype(jnp.int32), axis=1) \
            - stop_at.astype(jnp.int32)
        valid = cand & (prior == 0) & live[:, None]
        m = valid.sum(axis=1).astype(jnp.int32)
        # compact the accepted path inside the window, then advance
        # win_len by the kept count — the rollback
        win = permute_window_tail(win, wlen, perm)
        wlen = jnp.where(live, wlen + m, wlen)
        dst = _draft_rollback(dst, dlen0, live, m)
        wpos = jnp.clip(hlen[:, None] + col, 0, H - 1)
        cur = jnp.take_along_axis(hist, wpos, axis=1)
        hist = hist.at[rows, wpos].set(jnp.where(valid, emitted, cur))
        hlen = jnp.where(live, hlen + m, hlen)
        rem = jnp.where(live, rem - m, rem)
        died = (valid & has_stop[:, None]
                & (emitted == stops[:, None])).any(axis=1)
        live = live & ~died & (rem > 0)
        return (hist, hlen, win, wlen, dst, live, rem), (emitted, valid)

    (hist, hist_len, window, win_len, dstate, _, rem), \
        (toks_blk, valid_blk) = lax.scan(
            body, (hist, hist_len, window, win_len, dstate, live0,
                   budgets),
            jnp.arange(rounds, dtype=jnp.int32))
    return (toks_blk, valid_blk, hist, hist_len, rem, cache, window,
            win_len, dstate)


def _mixed_scan(cfg: ModelConfig, fwd, k: int, C: int, params, tokens,
                cursor, cache: PagedKVCache, pbuf, plen, active, temps,
                stops, budgets, top_k: int, top_p: float, key,
                use_kernel: bool = False):
    """k chained MIXED iterations in ONE lax.scan (ISSUE 18): each
    step, every slot is in exactly one phase — decode slots advance one
    token (_decode_scan's semantics, token-for-token) while prefill
    slots chew a C-token chunk of their prompt-buffer row through the
    warm multi-token path, the same [S, C] program shape the spec
    verify runs. Phase is a pure function of the carry: a slot is in
    prefill phase while cursor < plen. The scheduler seeds cursor at
    the cached-prefix length on admission and keeps the invariant
    cursor == the slot's written-token count (cache.lengths), so the
    forward's per-row position base is exact for both phases.

    A prefill step consumes count = min(C, plen - cursor) real
    positions; columns past count — and every column past the first of
    a decode slot, whose chain token rides broadcast across the chunk
    width — carry filler whose K/V lands past the slot's advanced
    length. The advance is rolled back to the real count via the
    lengths-replace pattern (_spec_scan's rollback), and the stale run
    is rewritten before any query can attend it (write-then-attend):
    the next step's C-wide write starts exactly at the rolled-back
    length.

    Emissions: a decode step emits its sampled token; a prefill step
    emits ONLY at the step its prefill completes — the slot's first
    token, sampled on device from the chunk's last real column (the
    same last-position logits the alternating path's gang prefill
    hands _finish_prefill). valid[i, s] marks block[i, s] as a real
    emission; the drain walks it like the spec block's validity mask.
    With no prefill-phase slot in the batch and C == 1 the program
    degenerates to _decode_scan exactly (same RNG stream fold_in(key,
    i), same liveness algebra) — the parity grid pins this.

    Returns (block [k, S], valid [k, S], final [S], cursor, cache).
    """
    S = tokens.shape[0]
    H = pbuf.shape[1]
    ccol = jnp.arange(C)[None, :]
    has_stop = stops >= 0
    is_pf0 = cursor < plen
    # prefill-phase slots skip the chain-token stop check: their
    # incoming token is prompt filler, not an emission
    live = active & (budgets > 0) \
        & jnp.where(has_stop & ~is_pf0, tokens != stops, True)

    def body(carry, i):
        cur, cursor, cache, live, rem = carry
        is_pf = cursor < plen
        count = jnp.where(is_pf, jnp.clip(plen - cursor, 0, C), 0)
        pchunk = jnp.take_along_axis(
            pbuf, jnp.clip(cursor[:, None] + ccol, 0, H - 1), axis=1)
        toks = jnp.where(is_pf[:, None], pchunk,
                         jnp.broadcast_to(cur[:, None], (S, C)))
        W = cache.lengths
        logits, cache = fwd(params, cfg, toks, cache, active=live,
                            use_kernel=use_kernel)
        completing = is_pf & (cursor + count >= plen)
        sidx = jnp.where(is_pf, jnp.clip(count - 1, 0, C - 1), 0)
        lg = jnp.take_along_axis(logits, sidx[:, None, None],
                                 axis=1)[:, 0, :]
        nxt = sample_batched(lg, jax.random.fold_in(key, i), temps,
                             top_k, top_p)
        emit = live & (completing | ~is_pf)
        nxt = jnp.where(emit, nxt, cur)
        adv = jnp.where(live, jnp.where(is_pf, count, 1), 0)
        cache = cache._replace(lengths=W + adv)
        cursor = jnp.where(live & is_pf, cursor + count, cursor)
        rem = jnp.where(emit, rem - 1, rem)
        live = live & jnp.where(
            emit, (rem > 0) & jnp.where(has_stop, nxt != stops, True),
            True)
        return (nxt, cursor, cache, live, rem), (nxt, emit)

    (final, cursor, cache, _, _), (block, valid) = lax.scan(
        body, (tokens, cursor, cache, live, budgets),
        jnp.arange(k, dtype=jnp.int32))
    return block, valid, final, cursor, cache


def _mixed_scan_win(cfg: ModelConfig, k: int, C: int, params, tokens,
                    cursor, cache: PagedKVCache, window: KVWindow,
                    win_len, pbuf, plen, active, temps, stops, budgets,
                    top_k: int, top_p: float, key,
                    use_kernel: bool = False):
    """Write-combined twin of _mixed_scan — phase/emission/RNG
    semantics are IDENTICAL (the parity grid pins token equality);
    only the K/V write target differs. A step stages its full C-wide
    chunk at the slot's win_len and win_len advances by the REAL count
    only (chunk length for a prefill step, 1 for a decode step, 0
    dead): filler and dead-step repeats sit past win_len, unattendable
    and never flushed, and the next step's C-wide stage rewrites them
    inside the window buffer — the spec window's rollback argument
    applied to chunk raggedness. The pool stays READ-ONLY; a freshly
    admitted slot's registered-prefix pages are flushed state by
    construction (registration happens at drain points, after the
    flush), so its chunk attends prefix from the pool and its own
    staged run from the window with no ordering hazard.

    Returns (block [k, S], valid [k, S], final [S], cursor, cache,
    window, win_len).
    """
    S = tokens.shape[0]
    H = pbuf.shape[1]
    ccol = jnp.arange(C)[None, :]
    has_stop = stops >= 0
    is_pf0 = cursor < plen
    live = active & (budgets > 0) \
        & jnp.where(has_stop & ~is_pf0, tokens != stops, True)

    def body(carry, i):
        cur, cursor, win, wlen, live, rem = carry
        is_pf = cursor < plen
        count = jnp.where(is_pf, jnp.clip(plen - cursor, 0, C), 0)
        pchunk = jnp.take_along_axis(
            pbuf, jnp.clip(cursor[:, None] + ccol, 0, H - 1), axis=1)
        toks = jnp.where(is_pf[:, None], pchunk,
                         jnp.broadcast_to(cur[:, None], (S, C)))
        logits, win = paged_forward_window(params, cfg, toks, cache,
                                           win, wlen, active=live,
                                           use_kernel=use_kernel)
        completing = is_pf & (cursor + count >= plen)
        sidx = jnp.where(is_pf, jnp.clip(count - 1, 0, C - 1), 0)
        lg = jnp.take_along_axis(logits, sidx[:, None, None],
                                 axis=1)[:, 0, :]
        nxt = sample_batched(lg, jax.random.fold_in(key, i), temps,
                             top_k, top_p)
        emit = live & (completing | ~is_pf)
        nxt = jnp.where(emit, nxt, cur)
        adv = jnp.where(live, jnp.where(is_pf, count, 1), 0)
        wlen = wlen + adv
        cursor = jnp.where(live & is_pf, cursor + count, cursor)
        rem = jnp.where(emit, rem - 1, rem)
        live = live & jnp.where(
            emit, (rem > 0) & jnp.where(has_stop, nxt != stops, True),
            True)
        return (nxt, cursor, win, wlen, live, rem), (nxt, emit)

    (final, cursor, window, win_len, _, _), (block, valid) = lax.scan(
        body, (tokens, cursor, window, win_len, live, budgets),
        jnp.arange(k, dtype=jnp.int32))
    return block, valid, final, cursor, cache, window, win_len


def _mixed_spec_scan(cfg: ModelConfig, fwd, rounds: int, gamma: int,
                     ngram: int, draft_src, params, hist, hist_len,
                     cursor, plen, cache: PagedKVCache, active, temps,
                     stops, budgets, top_k: int, top_p: float, key,
                     spec_mask, use_kernel: bool = False):
    """Speculative mixed block: _spec_scan generalized with prefill
    lanes (ISSUE 18). Decode-phase slots run the full draft ->
    batched-verify -> on-device-accept round, token-for-token
    _spec_scan (same accept keys fold_in(key, i), same draft keys
    fold_in(key, rounds + i)); prefill-phase slots (cursor < plen)
    spend the round's [S, C = gamma+1] forward on a C-token chunk of
    their HISTORY row instead — under spec the history carry already
    holds the full prompt at admission (hist_len == prompt length), so
    it doubles as the prompt buffer and no separate chunk operand
    exists. A completing slot samples its first token from the chunk's
    last real column under fold_in(key, 2 * rounds + i) — a third key
    stream that cannot collide with the accept (0..rounds-1) or draft
    (rounds..2*rounds-1) index ranges — and emits it as ONE valid
    entry at column 0 of its completion round; the unified
    history-append then lands it at position hist_len exactly like an
    accepted token, so the next round's ngram lookup already sees it.

    Stateless draft sources only: a stateful source's admission reseed
    hook (engine.draft_prefill) is a host-side call that requires the
    drain barrier mixed dispatch deletes, so the scheduler gates those
    to the alternating path (mixed_dispatch_ready).

    Returns (toks [rounds, S, C], valid [rounds, S, C], hist,
    hist_len, rem, cursor, cache).
    """
    S, H = hist.shape
    C = gamma + 1
    has_stop = stops >= 0
    col = jnp.arange(C)[None, :]
    rows = jnp.arange(S)[:, None]
    is_pf0 = cursor < plen
    last0 = jnp.take_along_axis(
        hist, jnp.clip(hist_len - 1, 0, H - 1)[:, None], axis=1)[:, 0]
    # prefill-phase slots skip the last-token stop check: their history
    # tail is prompt, not an emission (a prompt MAY end with the stop id)
    live0 = active & (budgets > 0) \
        & jnp.where(has_stop & ~is_pf0, last0 != stops, True)

    def body(carry, i):
        hist, hlen, cursor, cache, live, rem = carry
        is_pf = cursor < plen
        count = jnp.where(is_pf, jnp.clip(plen - cursor, 0, C), 0)
        drafts, qlog, _ = draft_src.draft(
            hist, hlen, gamma, ngram, live & ~is_pf, None,
            jax.random.fold_in(key, rounds + i), temps, top_k, top_p)
        last = jnp.take_along_axis(
            hist, jnp.clip(hlen - 1, 0, H - 1)[:, None], axis=1)[:, 0]
        pchunk = jnp.take_along_axis(
            hist, jnp.clip(cursor[:, None] + col, 0, H - 1), axis=1)
        toks = jnp.where(
            is_pf[:, None], pchunk,
            jnp.concatenate([last[:, None], drafts], axis=1))
        W = cache.lengths
        logits, cache = fwd(params, cfg, toks, cache, active=live,
                            use_kernel=use_kernel)
        emitted, n_acc = speculative_accept(
            logits, drafts, jax.random.fold_in(key, i), temps,
            top_k, top_p, spec_mask, qlog)
        # decode lanes: _spec_scan's budget/stop truncation, restricted
        # to decode phase
        cand = (col <= n_acc[:, None]) & (col < rem[:, None]) \
            & ~is_pf[:, None]
        stop_at = cand & has_stop[:, None] & (emitted == stops[:, None])
        prior = jnp.cumsum(stop_at.astype(jnp.int32), axis=1) \
            - stop_at.astype(jnp.int32)
        valid = cand & (prior == 0) & live[:, None]
        # prefill lanes: completion emits the slot's FIRST token at
        # column 0, sampled from the chunk's last real column
        completing = is_pf & (cursor + count >= plen)
        sidx = jnp.clip(count - 1, 0, C - 1)
        lg = jnp.take_along_axis(logits, sidx[:, None, None],
                                 axis=1)[:, 0, :]
        first = sample_batched(
            lg, jax.random.fold_in(key, 2 * rounds + i), temps, top_k,
            top_p)
        emitted = jnp.where(is_pf[:, None] & (col == 0),
                            first[:, None], emitted)
        valid = valid | ((completing & live)[:, None] & (col == 0))
        m = valid.sum(axis=1).astype(jnp.int32)
        # per-slot advance: a prefill step keeps its real chunk length,
        # a decode round its accepted count — the verify's +C rolls
        # back to exactly the written tokens either way
        adv = jnp.where(is_pf, count, m)
        cache = cache._replace(lengths=jnp.where(live, W + adv, W))
        wpos = jnp.clip(hlen[:, None] + col, 0, H - 1)
        cur = jnp.take_along_axis(hist, wpos, axis=1)
        hist = hist.at[rows, wpos].set(jnp.where(valid, emitted, cur))
        hlen = jnp.where(live, hlen + m, hlen)
        cursor = jnp.where(live & is_pf, cursor + count, cursor)
        rem = jnp.where(live, rem - m, rem)
        died = (valid & has_stop[:, None]
                & (emitted == stops[:, None])).any(axis=1)
        live = live & ~died & (rem > 0)
        return (hist, hlen, cursor, cache, live, rem), (emitted, valid)

    (hist, hist_len, cursor, cache, _, rem), (toks_blk, valid_blk) = \
        lax.scan(body, (hist, hist_len, cursor, cache, live0, budgets),
                 jnp.arange(rounds, dtype=jnp.int32))
    return toks_blk, valid_blk, hist, hist_len, rem, cursor, cache


def _mixed_spec_scan_win(cfg: ModelConfig, rounds: int, gamma: int,
                         ngram: int, draft_src, params, hist, hist_len,
                         cursor, plen, cache: PagedKVCache,
                         window: KVWindow, win_len, active, temps,
                         stops, budgets, top_k: int, top_p: float, key,
                         spec_mask, use_kernel: bool = False):
    """Write-combined twin of _mixed_spec_scan — lane semantics are
    IDENTICAL; each round's [S, C] forward stages into the window and
    win_len advances by the per-slot real count (chunk length for a
    prefill lane, accepted count for a decode lane): filler, rejected
    drafts, and dead-round repeats sit past win_len, unattendable and
    never flushed (_spec_scan_win's rollback-by-construction, extended
    to chunk raggedness).

    Returns (toks [rounds, S, C], valid [rounds, S, C], hist,
    hist_len, rem, cursor, cache, window, win_len).
    """
    S, H = hist.shape
    C = gamma + 1
    has_stop = stops >= 0
    col = jnp.arange(C)[None, :]
    rows = jnp.arange(S)[:, None]
    is_pf0 = cursor < plen
    last0 = jnp.take_along_axis(
        hist, jnp.clip(hist_len - 1, 0, H - 1)[:, None], axis=1)[:, 0]
    live0 = active & (budgets > 0) \
        & jnp.where(has_stop & ~is_pf0, last0 != stops, True)

    def body(carry, i):
        hist, hlen, cursor, win, wlen, live, rem = carry
        is_pf = cursor < plen
        count = jnp.where(is_pf, jnp.clip(plen - cursor, 0, C), 0)
        drafts, qlog, _ = draft_src.draft(
            hist, hlen, gamma, ngram, live & ~is_pf, None,
            jax.random.fold_in(key, rounds + i), temps, top_k, top_p)
        last = jnp.take_along_axis(
            hist, jnp.clip(hlen - 1, 0, H - 1)[:, None], axis=1)[:, 0]
        pchunk = jnp.take_along_axis(
            hist, jnp.clip(cursor[:, None] + col, 0, H - 1), axis=1)
        toks = jnp.where(
            is_pf[:, None], pchunk,
            jnp.concatenate([last[:, None], drafts], axis=1))
        logits, win = paged_forward_window(params, cfg, toks, cache,
                                           win, wlen, active=live,
                                           use_kernel=use_kernel)
        emitted, n_acc = speculative_accept(
            logits, drafts, jax.random.fold_in(key, i), temps,
            top_k, top_p, spec_mask, qlog)
        cand = (col <= n_acc[:, None]) & (col < rem[:, None]) \
            & ~is_pf[:, None]
        stop_at = cand & has_stop[:, None] & (emitted == stops[:, None])
        prior = jnp.cumsum(stop_at.astype(jnp.int32), axis=1) \
            - stop_at.astype(jnp.int32)
        valid = cand & (prior == 0) & live[:, None]
        completing = is_pf & (cursor + count >= plen)
        sidx = jnp.clip(count - 1, 0, C - 1)
        lg = jnp.take_along_axis(logits, sidx[:, None, None],
                                 axis=1)[:, 0, :]
        first = sample_batched(
            lg, jax.random.fold_in(key, 2 * rounds + i), temps, top_k,
            top_p)
        emitted = jnp.where(is_pf[:, None] & (col == 0),
                            first[:, None], emitted)
        valid = valid | ((completing & live)[:, None] & (col == 0))
        m = valid.sum(axis=1).astype(jnp.int32)
        adv = jnp.where(is_pf, count, m)
        wlen = jnp.where(live, wlen + adv, wlen)
        wpos = jnp.clip(hlen[:, None] + col, 0, H - 1)
        cur = jnp.take_along_axis(hist, wpos, axis=1)
        hist = hist.at[rows, wpos].set(jnp.where(valid, emitted, cur))
        hlen = jnp.where(live, hlen + m, hlen)
        cursor = jnp.where(live & is_pf, cursor + count, cursor)
        rem = jnp.where(live, rem - m, rem)
        died = (valid & has_stop[:, None]
                & (emitted == stops[:, None])).any(axis=1)
        live = live & ~died & (rem > 0)
        return (hist, hlen, cursor, win, wlen, live, rem), \
            (emitted, valid)

    (hist, hist_len, cursor, window, win_len, _, rem), \
        (toks_blk, valid_blk) = lax.scan(
            body, (hist, hist_len, cursor, window, win_len, live0,
                   budgets),
            jnp.arange(rounds, dtype=jnp.int32))
    return (toks_blk, valid_blk, hist, hist_len, rem, cursor, cache,
            window, win_len)
