from butterfly_tpu.engine.engine import (  # noqa: F401
    GenerateResult, InferenceEngine, SpeculativeResult)
from butterfly_tpu.engine.sampling import SamplingParams, sample  # noqa: F401
