from butterfly_tpu.engine.engine import InferenceEngine, GenerateResult  # noqa: F401
from butterfly_tpu.engine.sampling import SamplingParams, sample  # noqa: F401
